"""Checker ``dataplane``: explicit-state model checking of the byte plane.

Explores EVERY interleaving of the data-plane machines in
``dataplane_spec.py`` — frame duplication, reorder across stripes, conn
death mid-window, the watchdog ladder re-issuing on a fresh conn and
relaying around a CONFIRMED edge, relay windows racing direct copies on
the same byte ranges, zombie frames landing after a tag retired, chunk
serves with a seeder SIGKILL mid-range — against these invariants:

  * **conservation**: rx_bytes + rx_relay_bytes - dup_bytes equals the
    unique payload ground truth at every reachable state (the identity
    ``sockets.cpp``'s deliver_window documents as exact);
  * **no-double-publish**: no placement publishes into a byte range a
    concurrent writer has claimed and not yet committed;
  * **ack-retire soundness**: a stalled direct copy cancelled early via
    relay acks has its whole span acked, and every acked byte really is
    accounted for at the receiver;
  * **no-stuck**: every reachable state has a path to quiescence — ops
    complete or abort under any fault schedule (reverse-reachability, so
    livelocks with no escape path are caught too).

A conformance pass diffs the spec's frame vocabulary and handler arms
against the REAL dispatch surface (``sockets.hpp``'s Kind enum, the
rx_loop if-chain and tx_loop switch in ``sockets.cpp``, the router hooks
client.cpp installs, reduce.cpp's EdgeHealth ladder, ss_chunk.hpp's
PlanStats fields), exactly as the control-plane ``conformance`` checker
pins master.cpp — so the model cannot drift from the code.

Run as a checker (CI: ``python -m tools.pcclt_verify --checker dataplane``)
or directly (``python -m tools.pcclt_verify.dataplane_check [--deep]``).
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path
from typing import Any

from . import Finding, Skip
from . import dataplane_spec as spec
from .dataplane_spec import AckModel, DataViolation, TableModel

CHECKER = "dataplane"
SRC = "pccl_tpu/native/src"
SPEC_REL = "tools/pcclt_verify/dataplane_spec.py"

Action = tuple[Any, ...]


class Violation(Exception):
    def __init__(self, message: str, trace: "list[Action] | None" = None):
        super().__init__(message)
        self.message = message
        self.trace = trace or []

    def __str__(self) -> str:
        tail = self.trace[-14:]
        steps = " ; ".join("/".join(str(p) for p in a) for a in tail)
        more = "" if len(self.trace) <= 14 else f" (last 14 of {len(self.trace)} steps) "
        return f"{self.message}{more and ' '}[trace{more}: {steps}]"


@dataclasses.dataclass
class Scenario:
    """One adversarial data-plane workload, explored exhaustively.

    A *copy* is one wire incarnation of a stripe window (the original
    send, a duplicated frame, or a watchdog re-issue on a fresh conn);
    every copy's begin/commit interleaves freely with everything else.
    """

    name: str
    cap: int                                  # sink bytes per round
    stripes: "tuple[tuple[int, int], ...]"    # direct windows [off, end)
    rounds: int = 1                           # tag reuse across incarnations
    dup: "tuple[int, ...]" = ()               # stripes the env may duplicate
    wd: bool = False                          # watchdog ladder enabled
    relay: "tuple[tuple[int, int], ...]" = ()  # CONFIRMED windows [off, end)
    relay_dup: "tuple[int, ...]" = ()         # relay windows env may dup
    deaths: int = 0                           # conn/seeder death budget
    chunk: bool = False                       # chunk-plane round trip
    max_states: int = 400_000


# copy states: inflight -> begun -> done | lost | cancelled
_LIVE = ("inflight", "begun")
_TERMINAL = ("done", "lost", "cancelled")


@dataclasses.dataclass
class World:
    table: TableModel
    acks: AckModel
    scenario: Scenario
    round: int = 0                 # op incarnation / fetch attempt index
    fetch_done: bool = False       # chunk: some attempt completed the range
    registered: bool = False
    req_sent: bool = False         # chunk: request reached the seeder
    hdr: str = "none"              # chunk: none|inflight|queued|consumed
    seeder_dead: bool = False      # chunk: current round's seeder
    health: int = 0                # LADDER rung, reset per round
    deaths_left: int = 0
    # (round, stripe, copy) -> state; copy 0 = original, 1 = duplicated
    # frame, 2 = watchdog re-issue (re-armed while the stripe is missing)
    copies: "dict[tuple[int, int, int], str]" = dataclasses.field(
        default_factory=dict)
    # (round, relay_idx, copy) -> "inflight" | "delivered"
    relays: "dict[tuple[int, int, int], str]" = dataclasses.field(
        default_factory=dict)
    # (round, tag, off, len) — acks carry their op incarnation: the real
    # client scopes relay acks to the op (tag ranges embed the seq and
    # purge_relay_acks runs at op end), so an ack can never cross into the
    # next incarnation. The soundness check below PROVES that purge is
    # load-bearing: without it, a stale ack from a finished incarnation
    # cancels a retried op's direct copy whose bytes never arrived.
    acks_inflight: "tuple[tuple[int, int, int, int], ...]" = ()

    def copy_world(self) -> "World":
        return World(self.table.copy(), self.acks.copy(), self.scenario,
                     self.round, self.fetch_done, self.registered,
                     self.req_sent, self.hdr, self.seeder_dead, self.health,
                     self.deaths_left, dict(self.copies), dict(self.relays),
                     self.acks_inflight)

    def freeze(self):
        return (self.table.freeze(), self.acks.freeze(), self.round,
                self.fetch_done, self.registered, self.req_sent, self.hdr,
                self.seeder_dead, self.health, self.deaths_left,
                tuple(sorted(self.copies.items())),
                tuple(sorted(self.relays.items())),
                tuple(sorted(self.acks_inflight)))

    def done_all(self) -> bool:
        if self.scenario.chunk:
            return self.fetch_done
        return self.round >= self.scenario.rounds

    # ---- tags: collectives reuse one tag across incarnations (op retry
    # after an abort replays the same coordinates); chunk fetches burn a
    # fresh tag per attempt (client.cpp's chunk_tag_seq_) ----

    def tag_of(self, rnd: int) -> int:
        return (rnd + 1) if self.scenario.chunk else 1

    def cur_tag(self) -> int:
        return self.tag_of(self.round)

    def stripe_done(self, s: int) -> bool:
        off, end = self.scenario.stripes[s]
        sink = self.table.sinks.get(self.cur_tag())
        return sink is not None and sink.fully_covered(off, end)


def initial_world(sc: Scenario) -> World:
    w = World(TableModel(), AckModel(), sc, deaths_left=sc.deaths)
    if not sc.chunk:
        # the sender's stripes are on the wire from the start; sink
        # registration races them (the queued-frame path)
        for s in range(len(sc.stripes)):
            w.copies[(0, s, 0)] = "inflight"
    return w


def _spawn_round(w: World) -> None:
    for s in range(len(w.scenario.stripes)):
        w.copies[(w.round, s, 0)] = "inflight"


# --------------------------------------------------------------------------
# enabled actions
# --------------------------------------------------------------------------


def enabled_actions(w: World) -> "list[Action]":
    acts: "list[Action]" = []
    sc = w.scenario
    done_all = w.done_all()
    tag = w.cur_tag() if not done_all else None

    if not done_all:
        if not w.registered:
            acts.append(("register",))
        if sc.chunk:
            if w.registered and not w.req_sent:
                acts.append(("chunk_req",))
            if w.req_sent and not w.seeder_dead and w.hdr == "none":
                acts.append(("serve_hdr",))
            if w.hdr == "inflight":
                acts.append(("hdr_arrive",))
            if (w.hdr == "queued"
                    and w.table.take_hdr_peek(tag)):
                acts.append(("hdr_consume",))
            if w.deaths_left > 0 and not w.seeder_dead and w.req_sent:
                acts.append(("seeder_die",))
            # each seeder death buys one re-source attempt (fresh tag)
            if w.seeder_dead and w.round < sc.deaths:
                sink = w.table.sinks.get(tag)
                if w.registered and (sink is None or sink.busy == 0):
                    acts.append(("resource",))
        # watchdog ladder (monotone per op incarnation)
        if sc.wd and not sc.chunk:
            incomplete = [s for s in range(len(sc.stripes))
                          if not w.stripe_done(s)]
            if w.health == 0 and incomplete and w.registered:
                acts.append(("suspect",))
            if w.health >= 1:
                for s in incomplete:
                    live = any(st in _LIVE for (r, si, c), st
                               in w.copies.items()
                               if r == w.round and si == s)
                    if not live:
                        acts.append(("reissue", s))
            if w.health == 1 and sc.relay:
                acts.append(("confirm",))
        # round completion: the consumer saw every byte (and, chunk-side,
        # the response header); stragglers may still be on the wire — that
        # is exactly the retire machinery's job
        sink = w.table.sinks.get(tag) if w.registered else None
        if (sink is not None and sink.complete() and sink.busy == 0
                and (not sc.chunk or w.hdr == "consumed")):
            acts.append(("complete",))

    # frame-level actions stay enabled for every round's stragglers
    for (r, s, c), st in sorted(w.copies.items()):
        if st == "inflight":
            acts.append(("begin", r, s, c))
            if w.deaths_left > 0 and not sc.chunk:
                acts.append(("lose", r, s, c))
            off, end = sc.stripes[s]
            if w.acks.ack_covered(w.tag_of(r), off, end - off):
                acts.append(("cancel", r, s, c))
        elif st == "begun":
            acts.append(("commit", r, s, c))
            if w.deaths_left > 0 and not sc.chunk:
                acts.append(("die", r, s, c))
        if (st in ("inflight", "begun", "done") and c == 0
                and s in sc.dup and (r, s, 1) not in w.copies):
            acts.append(("dup_frame", r, s))
    for (r, i, c), st in sorted(w.relays.items()):
        if st == "inflight":
            acts.append(("relay_arrive", r, i, c))
        if (st in ("inflight", "delivered") and c == 0
                and i in sc.relay_dup and (r, i, 1) not in w.relays):
            acts.append(("relay_dup", r, i))
    for k in range(len(w.acks_inflight)):
        acts.append(("ack_arrive", k))
    return acts


# --------------------------------------------------------------------------
# action application (returns the successor world)
# --------------------------------------------------------------------------


def apply_action(w0: World, act: Action) -> World:
    w = w0.copy_world()
    sc = w.scenario
    kind = act[0]

    if kind == "register":
        w.table.register_sink(w.cur_tag(), sc.cap)
        w.registered = True
    elif kind == "chunk_req":
        w.req_sent = True
    elif kind == "serve_hdr":
        # the seeder's reply: header + striped payload start racing back
        w.hdr = "inflight"
        for s in range(len(sc.stripes)):
            w.copies[(w.round, s, 0)] = "inflight"
    elif kind == "hdr_arrive":
        w.table.chunk_hdr(w.cur_tag(), 0)
        w.hdr = "queued"
    elif kind == "hdr_consume":
        if w.table.take_hdr(w.cur_tag()) is None:
            raise Violation("hdr_consume enabled with no queued header")
        w.hdr = "consumed"
    elif kind == "seeder_die":
        # SIGKILL: every in-flight frame from this seeder dies with it;
        # a mid-write copy releases its claim (rx_loop's failure path)
        w.seeder_dead = True
        w.deaths_left -= 1
        for (r, s, c), st in list(w.copies.items()):
            if r != w.round:
                continue
            if st == "begun":
                off, end = sc.stripes[s]
                w.table.data_die(w.cur_tag(), off, end - off)
            if st in _LIVE:
                w.copies[(r, s, c)] = "lost"
        if w.hdr == "inflight":
            w.hdr = "none"
    elif kind == "resource":
        # fetch worker's drop_sink: unregister + purge (retire), then
        # re-request the range from the next seeder on a FRESH tag
        w.table.purge(w.cur_tag())
        w.round += 1
        w.registered = False
        w.req_sent = False
        w.hdr = "none"
        w.seeder_dead = False
        w.health = 0
    elif kind == "suspect":
        w.health = 1
    elif kind == "reissue":
        # the watchdog re-issues the missed window on a fresh pool conn,
        # re-armed for as long as the stripe stays missing (deadline loop)
        w.copies[(w.round, act[1], 2)] = "inflight"
    elif kind == "confirm":
        w.health = 2
        for i in range(len(sc.relay)):
            w.relays[(w.round, i, 0)] = "inflight"
    elif kind == "relay_dup":
        _, r, i = act
        w.relays[(r, i, 1)] = "inflight"
    elif kind == "relay_arrive":
        _, r, i, c = act
        off, end = sc.relay[i]
        length = end - off
        tag = w.tag_of(r)
        settled = w.table.deliver_window(tag, off, length)
        w.relays[(r, i, c)] = "delivered"
        # the final receiver acks the RANGE end-to-end, fire-and-forget —
        # but ONLY when deliver_window reports it durably accounted for.
        # A range partially dropped against a mid-write claim must not be
        # acked: the claim-holder can die and tear those bytes, and the
        # ack would cancel the origin's last copy on lying coverage
        # (model-checker finding, relay_vs_direct_deaths)
        if settled:
            w.acks_inflight = w.acks_inflight + ((r, tag, off, length),)
    elif kind == "ack_arrive":
        k = act[1]
        r, tag, off, length = w.acks_inflight[k]
        w.acks_inflight = w.acks_inflight[:k] + w.acks_inflight[k + 1:]
        if r >= w.round or sc.chunk:
            w.acks.note_ack(tag, off, length)
        # else: the incarnation that launched this relay already finished;
        # its wire-tag range is dead (seq-scoped, purged at op end), so
        # the ack merges into nothing — modeling note_relay_ack on a
        # purged, never-reused tag range
    elif kind == "dup_frame":
        _, r, s = act
        w.copies[(r, s, 1)] = "inflight"
    elif kind in ("begin", "lose", "cancel"):
        _, r, s, c = act
        off, end = sc.stripes[s]
        length = end - off
        tag = w.tag_of(r)
        if kind == "begin":
            verdict = w.table.data_begin(tag, off, length)
            if verdict == "claimed":
                w.copies[(r, s, c)] = "begun"
            else:  # dup / queued: the frame is fully drained on arrival
                w.copies[(r, s, c)] = "done"
        elif kind == "lose":
            w.deaths_left -= 1
            w.copies[(r, s, c)] = "lost"
        else:  # cancel: early zombie retirement via relay-ack coverage
            for b in range(off, end):
                if not w.table.byte_present(tag, b):
                    raise Violation(
                        f"ack-retire unsound: zombie copy {(r, s, c)} "
                        f"cancelled on relay-ack coverage of [{off},{end}) "
                        f"but byte {b} is not accounted for at the "
                        "receiver — acked coverage lied")
            w.copies[(r, s, c)] = "cancelled"
    elif kind in ("commit", "die"):
        _, r, s, c = act
        off, end = sc.stripes[s]
        length = end - off
        if kind == "commit":
            w.table.data_commit(w.tag_of(r), off, length)
            w.copies[(r, s, c)] = "done"
        else:
            w.deaths_left -= 1
            w.table.data_die(w.tag_of(r), off, length)
            w.copies[(r, s, c)] = "lost"
    elif kind == "complete":
        # op end: unregister (retire) the sink and purge the op's relay
        # acks (client.cpp's purge_relay_acks — op-scoped ack validity)
        w.table.unregister_sink(w.cur_tag())
        w.acks.acks.pop(w.cur_tag(), None)
        w.registered = False
        w.health = 0
        w.req_sent = False
        w.hdr = "none"
        w.seeder_dead = False
        if sc.chunk:
            w.fetch_done = True
        else:
            w.round += 1
            if w.round < sc.rounds:
                _spawn_round(w)
    else:  # pragma: no cover - enumerator/apply drift
        raise AssertionError(f"unknown action {act}")

    w.table.check_conservation()
    return w


# --------------------------------------------------------------------------
# exploration
# --------------------------------------------------------------------------


def _quiescent(w: World) -> bool:
    if not w.done_all():
        return False
    if any(st not in _TERMINAL for st in w.copies.values()):
        return False
    if any(st != "delivered" for st in w.relays.values()):
        return False
    return not w.acks_inflight


@dataclasses.dataclass
class Result:
    scenario: str
    states: int
    quiescent: int


def explore(sc: Scenario, table_cls: type = TableModel,
            ack_cls: type = AckModel) -> Result:
    """DFS every interleaving; raises Violation on the first broken
    invariant (with the action trace that reaches it)."""
    w0 = initial_world(sc)
    w0.table = table_cls()
    w0.acks = ack_cls()
    f0 = w0.freeze()
    worlds: "dict[Any, World]" = {f0: w0}
    parent: "dict[Any, tuple[Any, Action] | None]" = {f0: None}
    succs: "dict[Any, list[Any]]" = {}
    stack = [f0]
    quiescent: "set[Any]" = set()

    def trace_to(f: Any) -> "list[Action]":
        acts: "list[Action]" = []
        while True:
            pa = parent[f]
            if pa is None:
                break
            f, a = pa
            acts.append(a)
        acts.reverse()
        return acts

    while stack:
        f = stack.pop()
        if f in succs:
            continue
        w = worlds[f]
        acts = enabled_actions(w)
        nxt: "list[Any]" = []
        if not acts and not _quiescent(w):
            raise Violation(
                f"stuck world in scenario '{sc.name}': no action enabled "
                f"but round {w.round}/{sc.rounds} is incomplete "
                f"(copies={dict(w.copies)})", trace_to(f))
        for a in acts:
            try:
                w2 = apply_action(w, a)
            except (Violation, DataViolation) as v:
                msg = getattr(v, "message", str(v))
                raise Violation(f"scenario '{sc.name}': {msg}",
                                trace_to(f) + [a]) from None
            f2 = w2.freeze()
            nxt.append(f2)
            if f2 not in worlds:
                worlds[f2] = w2
                parent[f2] = (f, a)
                stack.append(f2)
                if len(worlds) > sc.max_states:
                    raise Violation(
                        f"scenario '{sc.name}' exceeded {sc.max_states} "
                        "states — shrink the scenario (this cap is a guard "
                        "against model regressions, not an invariant)")
        succs[f] = nxt
        if _quiescent(w):
            quiescent.add(f)

    # liveness: every reachable state must have a PATH to quiescence
    rev: "dict[Any, list[Any]]" = {}
    for f, ns in succs.items():
        for n in ns:
            rev.setdefault(n, []).append(f)
    ok = set(quiescent)
    frontier = list(quiescent)
    while frontier:
        f = frontier.pop()
        for p in rev.get(f, ()):
            if p not in ok:
                ok.add(p)
                frontier.append(p)
    bad = [f for f in succs if f not in ok]
    if bad:
        f = bad[0]
        w = worlds[f]
        raise Violation(
            f"livelock in scenario '{sc.name}': {len(bad)} reachable "
            f"state(s) have NO path to quiescence; e.g. round {w.round} "
            f"with copies={dict(w.copies)} relays={dict(w.relays)}",
            trace_to(f))
    return Result(sc.name, len(worlds), len(quiescent))


# --------------------------------------------------------------------------
# scenario suite
# --------------------------------------------------------------------------


def default_scenarios() -> "list[Scenario]":
    """The per-PR suite: every data-plane fault class from ISSUE/PR 10-19,
    sized to finish on a 1-core CI box."""
    return [
        # striped sends racing sink registration, one frame duplicated:
        # the queued-frame path, queue dedupe, and first-arrival-wins
        Scenario("stripe_reorder_dup", cap=4, stripes=((0, 2), (2, 4)),
                 dup=(0,)),
        # the full failover ladder: a stalled direct window re-issued on a
        # fresh conn, then CONFIRMED-relayed as two misaligned windows
        # racing the direct copies on the same byte ranges, with
        # end-to-end acks retiring the zombie early (a duplicated relay
        # window double-acks one sub-range, and a duplicated direct frame
        # races the relay windows on partially-overlapping ranges)
        Scenario("relay_vs_direct", cap=4, stripes=((0, 4),), wd=True,
                 dup=(0,), relay=((0, 2), (2, 4)), relay_dup=(0,)),
        # conn death at every point of a striped window (frame lost in
        # flight, or mid-write with a claim held); watchdog re-issue is
        # the recovery path
        Scenario("conn_death_mid_window", cap=4, stripes=((0, 2), (2, 4)),
                 wd=True, deaths=1),
        # two incarnations of one op on the SAME tag (abort/retry replays
        # identical coordinates): round 1 retires the tag, round 2 must
        # un-retire it on re-registration; round-2 frames racing the
        # re-registration are dropped as retired stragglers and the
        # ladder re-issues them — with relay windows and zombie
        # cancellation in the mix
        # NOTE wd=True is load-bearing for liveness, not just scenario
        # spice: a round-2 frame that arrives BEFORE the re-registration
        # is (correctly) dropped against the round-1 retire marker, and
        # only the watchdog re-issue rung recovers the stripe. The model
        # proves the no-watchdog variant of this interleaving deadlocks —
        # which is why reduce.cpp always arms the ladder for striped ops.
        Scenario("retire_tag_reuse", cap=2, stripes=((0, 2),), rounds=2,
                 wd=True, relay=((0, 2),)),
        # chunk plane: request/header/striped-payload round trip with the
        # seeder SIGKILLed mid-range; the fetch worker drops+purges the
        # tag and re-sources from a second seeder on a fresh tag
        Scenario("chunk_serve_sigkill", cap=4, stripes=((0, 2), (2, 4)),
                 chunk=True, deaths=1),
    ]


def deep_scenarios() -> "list[Scenario]":
    return [
        Scenario("stripe3_dup2", cap=6, stripes=((0, 2), (2, 4), (4, 6)),
                 dup=(0, 1), max_states=2_000_000),
        Scenario("relay_vs_direct_deaths", cap=4, stripes=((0, 4),),
                 wd=True, relay=((0, 2), (2, 4)), relay_dup=(0, 1),
                 deaths=1, max_states=2_000_000),
        Scenario("reuse3_relay", cap=2, stripes=((0, 2),), rounds=3,
                 wd=True, relay=((0, 2),), max_states=2_000_000),
        Scenario("chunk_double_sigkill", cap=4, stripes=((0, 2), (2, 4)),
                 chunk=True, deaths=2, max_states=2_000_000),
    ]


def run_suite(scenarios: "list[Scenario]",
              table_cls: type = TableModel,
              ack_cls: type = AckModel,
              verbose: bool = False) -> "list[Result]":
    out = []
    for sc in scenarios:
        r = explore(sc, table_cls, ack_cls)
        out.append(r)
        if verbose:
            print(f"  {r.scenario}: {r.states} states, "
                  f"{r.quiescent} quiescent — ok")
    return out


# --------------------------------------------------------------------------
# conformance: the model cannot drift from the dispatch surface
# --------------------------------------------------------------------------


def parse_kind_enum(sockets_hpp: str) -> "dict[str, int]":
    """Kind enumerator -> value from sockets.hpp's MultiplexConn::Kind."""
    m = re.search(r"enum\s+Kind\s*:\s*uint8_t\s*\{(.*?)\};", sockets_hpp,
                  re.S)
    if not m:
        return {}
    return {name: int(val) for name, val in
            re.findall(r"(k\w+)\s*=\s*(\d+)", m.group(1))}


def parse_rx_arms(rx_body: str) -> "list[frozenset[str]]":
    """The rx_loop's top-level `if (kind == kX || kind == kY)` dispatch
    conditions, one frozenset of kinds per arm (nested re-checks inside an
    arm are deeper-indented and skipped)."""
    out = []
    for cond in re.findall(r"(?m)^ {8}if \((kind == k\w+"
                           r"(?: \|\| kind == k\w+)*)\)", rx_body):
        out.append(frozenset(re.findall(r"kind == (k\w+)", cond)))
    return out


def _body_of(text: str, marker: str) -> str:
    """Source text from `marker` to the next top-level function def."""
    start = text.find(marker)
    if start < 0:
        return ""
    end = re.search(r"\n\}\n\n", text[start:])
    return text[start:start + end.end()] if end else text[start:]


def conformance_findings(root: Path) -> "list[Finding]":
    src = Path(root) / SRC

    def text_of(name: str) -> str:
        p = src / name
        return p.read_text() if p.is_file() else ""

    sockets_hpp = text_of("sockets.hpp")
    sockets_cpp = text_of("sockets.cpp")
    client = text_of("client.cpp")
    reduce_cpp = text_of("reduce.cpp")
    telemetry_hpp = text_of("telemetry.hpp")
    ss_chunk_hpp = text_of("ss_chunk.hpp")
    out: "list[Finding]" = []
    if not sockets_hpp or not sockets_cpp or not client:
        return [Finding(CHECKER, SRC, 0,
                        "sockets.hpp/sockets.cpp/client.cpp missing — "
                        "cannot diff the spec against the frame surface")]

    # --- Kind enum <-> FRAME_KINDS (names, values, uniqueness) --------
    real = parse_kind_enum(sockets_hpp)
    if not real:
        out.append(Finding(
            CHECKER, f"{SRC}/sockets.hpp", 0,
            "could not parse `enum Kind : uint8_t { ... }` — the frame "
            "vocabulary moved; realign parse_kind_enum"))
    vals: "dict[int, list[str]]" = {}
    for name, v in real.items():
        vals.setdefault(v, []).append(name)
    for v, names in sorted(vals.items()):
        if len(names) > 1:
            out.append(Finding(
                CHECKER, f"{SRC}/sockets.hpp", 0,
                f"frame kinds {sorted(names)} share wire value {v} — "
                "kinds must be unique on the wire"))
    for name in sorted(set(real) - set(spec.FRAME_KINDS)):
        out.append(Finding(
            CHECKER, f"{SRC}/sockets.hpp", 0,
            f"frame kind {name} = {real[name]} has no entry in the "
            f"data-plane spec — teach {SPEC_REL} the kind (FRAME_KINDS "
            "and its RX_DISPATCH arm)"))
    for name in sorted(set(spec.FRAME_KINDS) - set(real)):
        out.append(Finding(
            CHECKER, SPEC_REL, 0,
            f"spec kind {name} no longer exists in sockets.hpp's Kind "
            "enum — stale spec entry"))
    for name in sorted(set(real) & set(spec.FRAME_KINDS)):
        if real[name] != spec.FRAME_KINDS[name]:
            out.append(Finding(
                CHECKER, SPEC_REL, 0,
                f"spec pins {name} = {spec.FRAME_KINDS[name]} but "
                f"sockets.hpp says {real[name]} — realign the spec"))

    # --- rx_loop if-chain <-> RX_DISPATCH arm partition ---------------
    rx = _body_of(sockets_cpp, "void MultiplexConn::rx_loop()")
    if not rx:
        out.append(Finding(
            CHECKER, f"{SRC}/sockets.cpp", 0,
            "MultiplexConn::rx_loop not found — realign the dataplane "
            "conformance parser"))
    else:
        arms = parse_rx_arms(rx)
        spec_arms: "dict[str, set[str]]" = {}
        for k, arm in spec.RX_DISPATCH.items():
            spec_arms.setdefault(arm, set()).add(k)
        fallthrough = spec_arms.pop("sink_fastpath", set())
        want = {frozenset(g) for g in spec_arms.values()}
        got = set(arms)
        for g in sorted(got - want, key=sorted):
            out.append(Finding(
                CHECKER, f"{SRC}/sockets.cpp", 0,
                f"rx_loop dispatch arm for {sorted(g)} has no matching "
                f"arm grouping in the spec's RX_DISPATCH — teach "
                f"{SPEC_REL} the arm"))
        for g in sorted(want - got, key=sorted):
            out.append(Finding(
                CHECKER, SPEC_REL, 0,
                f"spec groups {sorted(g)} under one rx arm but rx_loop "
                "has no such dispatch condition — stale spec arm"))
        if fallthrough != {"kData"}:
            out.append(Finding(
                CHECKER, SPEC_REL, 0,
                "spec's sink_fastpath fall-through arm must be exactly "
                f"{{kData}}, got {sorted(fallthrough)}"))
        elif "// kData — sink fast path" not in rx:
            out.append(Finding(
                CHECKER, f"{SRC}/sockets.cpp", 0,
                "rx_loop's kData fall-through lost its '// kData — sink "
                "fast path' marker — the spec pins kData as the final "
                "arm; restore the marker where the fast path begins"))

    # --- tx_loop switch <-> TX_ARMS -----------------------------------
    tx = _body_of(sockets_cpp, "void MultiplexConn::tx_loop()")
    tx_cases = set(re.findall(r"case (k\w+):", tx))
    for k in sorted(tx_cases - spec.TX_ARMS):
        out.append(Finding(
            CHECKER, f"{SRC}/sockets.cpp", 0,
            f"tx_loop sends {k} but the spec's TX_ARMS does not list it"))
    for k in sorted(spec.TX_ARMS - tx_cases):
        out.append(Finding(
            CHECKER, SPEC_REL, 0,
            f"spec lists tx arm {k} but tx_loop's switch has no such "
            "case — stale spec arm"))

    # --- routed kinds: rx arm invokes the hook, client installs it ----
    for k, hook in sorted(spec.ROUTED_KINDS.items()):
        if hook + "(" not in rx:
            out.append(Finding(
                CHECKER, f"{SRC}/sockets.cpp", 0,
                f"spec routes {k} through hook {hook} but rx_loop never "
                f"invokes {hook}(...) — rewire the arm or the spec"))
        if not re.search(rf"\b{hook}\b", sockets_hpp):
            out.append(Finding(
                CHECKER, f"{SRC}/sockets.hpp", 0,
                f"hook member {hook} (route for {k}) missing from "
                "MultiplexConn"))
    for installer in ("set_relay_handlers", "set_chunk_req_handler"):
        if installer not in client:
            out.append(Finding(
                CHECKER, f"{SRC}/client.cpp", 0,
                f"client.cpp never calls {installer} — the routed frame "
                "kinds would hit the no-router fallback on every conn"))

    # --- client-originated kinds --------------------------------------
    client_kinds = set(re.findall(r"MultiplexConn::(k\w+)", client))
    client_kinds &= set(spec.FRAME_KINDS)
    # kData payloads ride the striped Link helpers, not a Kind literal
    if re.search(r"\bsend_at\(|\bsend_async\(|\bsend_bytes\(", client):
        client_kinds.add("kData")
    for k in sorted(client_kinds - spec.CLIENT_SENDS):
        out.append(Finding(
            CHECKER, f"{SRC}/client.cpp", 0,
            f"client.cpp originates {k} frames but the spec's "
            f"CLIENT_SENDS does not include it — teach {SPEC_REL}"))
    for k in sorted(spec.CLIENT_SENDS - client_kinds):
        out.append(Finding(
            CHECKER, SPEC_REL, 0,
            f"spec claims the client originates {k} but client.cpp never "
            "does — stale spec entry"))

    # --- the watchdog ladder <-> EdgeHealth ---------------------------
    m = re.search(r"enum class EdgeHealth[^{]*\{(.*?)\};", telemetry_hpp,
                  re.S)
    ladder = {name: int(v) for name, v in
              re.findall(r"(k\w+)\s*=\s*(\d+)", m.group(1))} if m else {}
    if ladder != spec.LADDER:
        out.append(Finding(
            CHECKER, SPEC_REL, 0,
            f"spec LADDER {spec.LADDER} != telemetry.hpp EdgeHealth "
            f"{ladder} — the failover ladder drifted"))
    for rung in ("kSuspect", "kConfirmed"):
        if not re.search(rf"EdgeHealth::{rung}\b", reduce_cpp):
            out.append(Finding(
                CHECKER, f"{SRC}/reduce.cpp", 0,
                f"reduce.cpp never climbs to EdgeHealth::{rung} — the "
                "modeled ladder rung is unreachable in the watchdog"))

    # --- chunk-plane stats fields -------------------------------------
    ps = re.search(r"struct PlanStats\s*\{(.*?)\};", ss_chunk_hpp, re.S)
    fields = set(re.findall(r"(\w+)\s*=", ps.group(1))) if ps else set()
    for f in sorted(spec.PLAN_STATS_FIELDS - fields):
        out.append(Finding(
            CHECKER, f"{SRC}/ss_chunk.hpp", 0,
            f"PlanStats field {f} (named in the spec's conservation "
            "identity) no longer exists — realign the spec or the struct"))
    return out


# --------------------------------------------------------------------------
# checker entry points
# --------------------------------------------------------------------------


def check(root: Path) -> "list[Finding] | Skip":
    out = conformance_findings(Path(root))
    try:
        run_suite(default_scenarios())
    except Violation as v:
        out.append(Finding(CHECKER, SPEC_REL, 0, str(v)))
    return out


def main(argv: "list[str] | None" = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="pcclt_verify.dataplane_check",
        description="explicit-state model checker for the byte plane")
    ap.add_argument("--deep", action="store_true",
                    help="also run the larger worlds (minutes, not seconds)")
    ap.add_argument("--root", default=".",
                    help="repo root for the conformance diff")
    args = ap.parse_args(argv)
    rc = 0
    for f in conformance_findings(Path(args.root)):
        print(f"CONFORMANCE: {f}")
        rc = 1
    try:
        print("default suite:")
        run_suite(default_scenarios(), verbose=True)
        if args.deep:
            print("deep suite:")
            run_suite(deep_scenarios(), verbose=True)
    except Violation as v:
        print(f"VIOLATION: {v}")
        return 1
    if rc == 0:
        print("dataplane check: all invariants hold")
    return rc


if __name__ == "__main__":
    import sys
    sys.exit(main())
