"""Checker ``conformance``: the FSM spec cannot drift from the code.

A hand-written model is only worth trusting while it matches the thing it
models. This checker diffs the spec's packet-transition tables
(``fsm_spec.MASTER_DISPATCH`` / ``MASTER_EMITS`` / ``CLIENT_SENDS`` /
``CLIENT_CONSUMES``) against the REAL protocol surface, extending the
PR-4 ``protocol`` checker's parsing:

  * every ``case PacketType::kC2M...`` dispatch arm in ``master.cpp`` must
    appear in the spec, and must route to the same ``on_*`` handler the
    spec transition names (and the handler must exist on the spec class);
  * every spec transition must still have its dispatch arm (a removed or
    renamed arm orphans the model);
  * every ``kM2C*`` id master_state.cpp emits must be one the spec can
    emit, and vice versa;
  * every ``kC2M*``/``kM2C*`` id the client sends/consumes in client.cpp
    must match the client-FSM tables.

So: adding a packet without teaching the model fails CI, and simplifying
the model below the code's real surface fails CI too.
"""

from __future__ import annotations

import re
from pathlib import Path

from . import Finding, Skip

CHECKER = "conformance"
SRC = "pccl_tpu/native/src"


def parse_dispatch_arms(master_text: str) -> "dict[str, str]":
    """kC2M id -> the state_.on_*() handler its case arm calls."""
    out: "dict[str, str]" = {}
    # split the switch body at case labels; each chunk belongs to the id
    # that opened it (fallthrough-free switch, enforced by the handler
    # check below failing on an empty chunk)
    parts = re.split(r"case\s+PacketType::(k\w+):", master_text)
    for i in range(1, len(parts) - 1, 2):
        pid, body = parts[i], parts[i + 1]
        if not pid.startswith("kC2M"):
            continue
        m = re.search(r"state_\.(on_\w+)\s*\(", body)
        out[pid] = m.group(1) if m else ""
    return out


def check(root: Path) -> "list[Finding] | Skip":
    from . import fsm_spec
    from .fsm_spec import MasterModel

    rootp = Path(root)
    src = rootp / SRC
    out: "list[Finding]" = []

    def text_of(name: str) -> str:
        p = src / name
        return p.read_text() if p.is_file() else ""

    master = text_of("master.cpp")
    master_state = text_of("master_state.cpp")
    client = text_of("client.cpp")
    if not master or not master_state or not client:
        return [Finding(CHECKER, SRC, 0,
                        "master.cpp/master_state.cpp/client.cpp missing — "
                        "cannot diff the spec against the dispatch surface")]
    spec_rel = "tools/pcclt_verify/fsm_spec.py"

    # --- master dispatch arms <-> spec transitions --------------------
    arms = parse_dispatch_arms(master)
    for pid, handler in sorted(arms.items()):
        spec_handler = fsm_spec.MASTER_DISPATCH.get(pid)
        if spec_handler is None:
            out.append(Finding(
                CHECKER, f"{SRC}/master.cpp", 0,
                f"dispatch arm {pid} -> {handler or '?'} has no transition "
                f"in the FSM spec — teach {spec_rel} the packet (or the "
                "model no longer covers the control plane)"))
        elif handler != spec_handler:
            out.append(Finding(
                CHECKER, f"{SRC}/master.cpp", 0,
                f"dispatch arm {pid} calls state_.{handler or '<nothing>'} "
                f"but the spec transition names {spec_handler} — realign "
                f"the arm or {spec_rel}"))
    for pid, spec_handler in sorted(fsm_spec.MASTER_DISPATCH.items()):
        if pid not in arms:
            out.append(Finding(
                CHECKER, spec_rel, 0,
                f"spec transition {pid} -> {spec_handler} has no dispatch "
                "arm in master.cpp's packet switch — the modeled packet "
                "no longer exists (remove it from the spec or restore the "
                "arm)"))
        if not hasattr(MasterModel, spec_handler):
            out.append(Finding(
                CHECKER, spec_rel, 0,
                f"spec names handler {spec_handler} for {pid} but "
                "MasterModel defines no such method — the model would "
                "drop the packet"))

    # --- master emissions <-> spec emissions --------------------------
    emitted = set(re.findall(r"PacketType::(kM2C\w+)", master_state))
    # kM2CWelcome's wire-rev-mismatch rejection also writes from on_hello;
    # both sites are in master_state.cpp, so the harvest is complete.
    for pid in sorted(emitted - fsm_spec.MASTER_EMITS):
        out.append(Finding(
            CHECKER, f"{SRC}/master_state.cpp", 0,
            f"master_state.cpp emits {pid} but the spec's MASTER_EMITS "
            f"does not include it — teach {spec_rel} the emission"))
    for pid in sorted(fsm_spec.MASTER_EMITS - emitted):
        out.append(Finding(
            CHECKER, spec_rel, 0,
            f"spec claims the master emits {pid} but master_state.cpp "
            "never does — stale spec emission"))

    # --- client surface <-> client-FSM tables -------------------------
    sends = set(re.findall(r"PacketType::(kC2M\w+)", client))
    consumes = set(re.findall(r"PacketType::(kM2C\w+)", client))
    for pid in sorted(sends - fsm_spec.CLIENT_SENDS):
        out.append(Finding(
            CHECKER, f"{SRC}/client.cpp", 0,
            f"client.cpp sends {pid} but the spec's CLIENT_SENDS does not "
            f"include it — teach {spec_rel} the client transition"))
    for pid in sorted(fsm_spec.CLIENT_SENDS - sends):
        out.append(Finding(
            CHECKER, spec_rel, 0,
            f"spec claims the client sends {pid} but client.cpp never "
            "does — stale client transition"))
    for pid in sorted(consumes - fsm_spec.CLIENT_CONSUMES):
        out.append(Finding(
            CHECKER, f"{SRC}/client.cpp", 0,
            f"client.cpp consumes {pid} but the spec's CLIENT_CONSUMES "
            f"does not include it — teach {spec_rel} the reaction"))
    for pid in sorted(fsm_spec.CLIENT_CONSUMES - consumes):
        out.append(Finding(
            CHECKER, spec_rel, 0,
            f"spec claims the client consumes {pid} but client.cpp never "
            "matches it — stale spec consumption"))
    return out
