"""Whole-program lock-fact harvest over the native TUs, via libclang.

One parse of every TU in ``pccl_tpu/native/src`` produces the facts both
lock checkers consume:

  * every ``pcclt::Mutex`` declaration (class member, global, or
    function-local) with its ``// lock-rank: N [io]`` annotation;
  * per function: the lock-acquisition events (``MutexLock`` RAII,
    explicit ``lock()``/``unlock()``, drop-and-reacquire windows) with the
    set of locks already held at each event;
  * per function: every call site with the held-set at the call, plus the
    resolution of ``Mutex &`` arguments (so ``send_frame(sock, write_mu,
    ...)`` attributes its internal acquisition to the caller's mutex);
  * per function: direct calls to blocking primitives (socket syscalls,
    fsync, sleeps, futex parks) and CondVar waits.

Identity model: one node per *declaration* — ``net::SinkTable::mu_`` is a
single node even though many SinkTable instances exist at runtime. This is
the classic lock-RANK abstraction: it cannot distinguish two instances of
the same class, so acquiring one SinkTable's mu_ under another's shows up
as a self-edge, which ``lockorder`` reports as its own finding class.

Lambda bodies are analyzed as separate anonymous functions with an EMPTY
initial held-set (a lambda usually runs on another thread; a lambda
invoked inline under a lock is already banned by the PR-4 discipline, see
docs/11_static_analysis.md).
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

SRC = "pccl_tpu/native/src"

# TUs whose locks live inside test fixtures; their acquisitions still feed
# cycle detection, but their (function-local) locks need no declared rank.
TEST_TUS = {"selftest.cpp", "socktest.cpp"}

# Direct blocking primitives: anything that can park the calling thread on
# the network, the disk, another process, or the clock. Plain stderr
# logging (fprintf/fputs) is deliberately NOT here — it cannot stall on a
# remote peer and listing it would bury the real findings in log noise.
BLOCKING_FUNCTIONS = {
    # sockets
    "send", "recv", "sendto", "recvfrom", "sendmsg", "recvmsg",
    "connect", "accept", "accept4", "poll", "ppoll", "select",
    "epoll_wait", "writev", "readv", "getaddrinfo",
    # file IO (journal appends, trace dumps)
    "fsync", "fdatasync", "fwrite", "fflush", "fopen", "fread",
    # cross-process memory (CMA pulls)
    "process_vm_readv",
    # the clock
    "nanosleep", "usleep", "sleep",
}
# method-style blocking primitives, matched as Class::method
BLOCKING_METHODS = {
    ("Event", "wait"),          # park::Event futex park
    ("Event", "wait_for"),
    ("thread", "join"),         # joining a thread that may itself block
}
# namespace-qualified free functions
BLOCKING_QUALIFIED = {"sleep_for", "sleep_until", "call_once"}

RANK_RE = re.compile(r"lock-rank:\s*(?:(\d+)\s*)?(io\b)?\s*(blocking-ok\b)?")


@dataclasses.dataclass(frozen=True)
class LockDecl:
    identity: str
    file: str            # repo-relative
    line: int
    rank: "int | None"   # None = no annotation found
    io: bool             # serializes one fd/file: blocking ok, must be leaf
    blocking_ok: bool    # long-span serialization lock: blocking sanctioned,
                         # but ordering rules still apply (not a leaf)
    local: bool          # function-local (or test-fixture) declaration


@dataclasses.dataclass(frozen=True)
class Acquire:
    lock: str                  # identity, or "param:<index>"
    held: "tuple[str, ...]"
    file: str
    line: int


@dataclasses.dataclass(frozen=True)
class CallSite:
    callee: str                # USR of the referenced declaration
    callee_name: str           # display name for messages
    held: "tuple[str, ...]"
    file: str
    line: int
    # Mutex& arguments: callee param index -> resolved identity
    mutex_args: "tuple[tuple[int, str], ...]" = ()


@dataclasses.dataclass(frozen=True)
class BlockingCall:
    what: str                  # primitive name
    held: "tuple[str, ...]"
    file: str
    line: int


@dataclasses.dataclass(frozen=True)
class CvWait:
    mutex: str                 # the mutex the wait releases
    held: "tuple[str, ...]"    # full held-set at the wait (includes mutex)
    file: str
    line: int


@dataclasses.dataclass
class FuncFacts:
    usr: str
    name: str                  # qualified display name
    file: str
    line: int
    requires: "tuple[str, ...]" = ()
    acquires: "list[Acquire]" = dataclasses.field(default_factory=list)
    calls: "list[CallSite]" = dataclasses.field(default_factory=list)
    blocking: "list[BlockingCall]" = dataclasses.field(default_factory=list)
    cv_waits: "list[CvWait]" = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Program:
    locks: "dict[str, LockDecl]"
    funcs: "dict[str, FuncFacts]"      # by USR
    errors: "list[str]"                # parse-level problems


_memo: "dict[str, Program]" = {}


def harvest(root: Path) -> "Program | str":
    """Parse every native TU once; returns Program or an error string when
    libclang is unavailable."""
    key = str(Path(root).resolve())
    if key in _memo:
        return _memo[key]
    try:
        from clang import cindex
        index = cindex.Index.create()
    except Exception as e:  # no wheel, or libclang.so failed to load
        return f"libclang unavailable ({e})"
    from tools.pcclt_check import thread_safety

    rootp = Path(root).resolve()
    src = rootp / SRC
    args = thread_safety.parse_args(rootp)
    prog = Program(locks={}, funcs={}, errors=[])
    h = _Harvester(cindex, rootp, prog)
    for tu_path in sorted(src.glob("*.cpp")):
        tu = index.parse(str(tu_path), args=args)
        fatal = [d for d in tu.diagnostics if d.severity >= 4]
        if fatal:
            prog.errors.append(f"{tu_path.name}: {fatal[0].spelling}")
            continue
        h.visit_tu(tu, tu_path)
    _memo[key] = prog
    return prog


def display_rel(root: Path, f: "str | None") -> str:
    if not f:
        return SRC
    try:
        return str(Path(f).resolve().relative_to(Path(root).resolve()))
    except ValueError:
        return str(f)


class _Harvester:
    def __init__(self, cindex, root: Path, prog: Program):
        self.ci = cindex
        self.K = cindex.CursorKind
        self.root = root
        self.prog = prog
        self._file_cache: "dict[str, list[str]]" = {}

    # ---------------- source access ----------------

    def _lines(self, path: str) -> "list[str]":
        if path not in self._file_cache:
            try:
                self._file_cache[path] = Path(path).read_text(
                    errors="replace").splitlines()
            except OSError:
                self._file_cache[path] = []
        return self._file_cache[path]

    def _line_text(self, path: str, line: int) -> str:
        lines = self._lines(path)
        return lines[line - 1] if 0 < line <= len(lines) else ""

    # ---------------- identity ----------------

    def qualified(self, cursor) -> str:
        """Display-qualified name: namespace/class chain, 'pcclt::' elided."""
        parts: "list[str]" = []
        c = cursor
        while c is not None and c.kind != self.K.TRANSLATION_UNIT:
            if c.spelling:
                parts.append(c.spelling)
            c = c.semantic_parent
        parts.reverse()
        if parts and parts[0] == "pcclt":
            parts = parts[1:]
        return "::".join(parts) or cursor.spelling

    def _is_mutex_type(self, t) -> bool:
        s = t.get_canonical().spelling
        # strip reference/cv qualifiers: params arrive as `pcclt::Mutex &`
        s = s.removesuffix("&").strip().removeprefix("const").strip()
        return s.endswith("pcclt::Mutex") or s == "pcclt::Mutex"

    def _is_mutex_ref(self, t) -> bool:
        return "&" in t.get_canonical().spelling

    def _is_mutexlock_type(self, t) -> bool:
        s = t.get_canonical().spelling
        return s.endswith("pcclt::MutexLock") or s == "pcclt::MutexLock"

    def _in_function(self, cursor) -> bool:
        c = cursor.semantic_parent
        while c is not None and c.kind != self.K.TRANSLATION_UNIT:
            if c.kind in (self.K.CXX_METHOD, self.K.FUNCTION_DECL,
                          self.K.CONSTRUCTOR, self.K.DESTRUCTOR,
                          self.K.LAMBDA_EXPR, self.K.FUNCTION_TEMPLATE):
                return True
            c = c.semantic_parent
        return False

    def lock_identity(self, decl) -> str:
        """Identity for a Mutex declaration cursor (field/var/param)."""
        if decl.kind == self.K.PARM_DECL:
            return f"param:{decl.spelling}"
        if (decl.kind == self.K.VAR_DECL and self._in_function(decl)
                and decl.storage_class == self.ci.StorageClass.STATIC):
            # function-local static: global lifetime, shared across
            # threads — a real graph node, not a per-frame throwaway
            return self.qualified(decl)
        if decl.kind == self.K.VAR_DECL and self._in_function(decl):
            loc = decl.location
            rel = display_rel(self.root, str(loc.file) if loc.file else "")
            return f"local:{rel}:{loc.line}:{decl.spelling}"
        if decl.kind == self.K.FIELD_DECL and self._in_function(decl):
            # member of a function-local struct (test fixtures)
            loc = decl.location
            rel = display_rel(self.root, str(loc.file) if loc.file else "")
            return f"local:{rel}:{loc.line}:{decl.spelling}"
        return self.qualified(decl)

    def note_lock_decl(self, decl) -> str:
        ident = self.lock_identity(decl)
        if ident in self.prog.locks or ident.startswith("param:"):
            return ident
        loc = decl.location
        path = str(loc.file) if loc.file else ""
        rel = display_rel(self.root, path)
        local = ident.startswith("local:") or Path(rel).name in TEST_TUS
        rank, io, bok = self._rank_annotation(path, loc.line)
        self.prog.locks[ident] = LockDecl(ident, rel, loc.line, rank, io,
                                          bok, local)
        return ident

    def _rank_annotation(self, path: str, line: int
                         ) -> "tuple[int | None, bool, bool]":
        """``// lock-rank: N [io|blocking-ok]`` on the declaration line or
        anywhere in the contiguous comment block directly above it (rank
        tags often lead a prose paragraph explaining the lock)."""
        candidates = [line]
        ln = line - 1
        while ln > 0 and len(candidates) < 12:
            stripped = self._line_text(path, ln).strip()
            if not stripped.startswith("//"):
                break
            candidates.append(ln)
            ln -= 1
        for ln in candidates:
            text = self._line_text(path, ln)
            if "lock-rank:" not in text:
                continue
            m = RANK_RE.search(text)
            if m:
                rank = int(m.group(1)) if m.group(1) else None
                return rank, bool(m.group(2)), bool(m.group(3))
        return None, False, False

    # ---------------- expression resolution ----------------

    def resolve_mutex_expr(self, expr) -> "str | None":
        """Resolve an expression naming a pcclt::Mutex to its identity."""
        if expr is None:
            return None
        K = self.K
        if expr.kind in (K.MEMBER_REF_EXPR, K.DECL_REF_EXPR):
            ref = expr.referenced
            if ref is not None and self._is_mutex_type(ref.type):
                return self.note_lock_decl(ref)
            return None
        # unwrap casts/parens/unexposed wrappers
        for ch in expr.get_children():
            got = self.resolve_mutex_expr(ch)
            if got is not None:
                return got
        return None

    # ---------------- TU walk ----------------

    def visit_tu(self, tu, tu_path: Path) -> None:
        src_dir = str((self.root / SRC).resolve())
        inc_dir = str((self.root / "pccl_tpu/native/include").resolve())

        def in_repo(c) -> bool:
            f = c.location.file
            if f is None:
                return False
            s = str(f)
            if s.endswith("annotations.hpp"):
                # the annotated primitives themselves are the TRUSTED layer
                # (their internals intentionally touch raw std::mutex and a
                # Mutex& alias member); analyzing them only manufactures
                # phantom lock nodes
                return False
            return s.startswith(src_dir) or s.startswith(inc_dir)

        def walk(c):
            if not in_repo(c):
                return
            K = self.K
            if c.kind in (K.CXX_METHOD, K.FUNCTION_DECL, K.CONSTRUCTOR,
                          K.DESTRUCTOR) and c.is_definition():
                self.visit_function(c)
                return  # visit_function walks the body (incl. lambdas)
            if (c.kind == K.FIELD_DECL and self._is_mutex_type(c.type)
                    and not self._is_mutex_ref(c.type)):
                # reference members (MutexLock::mu_) alias a lock declared
                # elsewhere; they are not graph nodes themselves
                self.note_lock_decl(c)
            if (c.kind == K.VAR_DECL and self._is_mutex_type(c.type)
                    and not self._in_function(c)):
                self.note_lock_decl(c)  # global / file-static mutex
            for ch in c.get_children():
                walk(ch)

        for c in tu.cursor.get_children():
            walk(c)

    # ---------------- function analysis ----------------

    def _attr_locks(self, func, macro_names: "tuple[str, ...]"
                    ) -> "list[str]":
        """Harvest PCCLT_<macro>(args) annotations textually: libclang
        exposes the attribute kind but macro expansion swallows the
        argument, so the source line at the attribute's extent is read
        back and parsed."""
        out: "list[str]" = []
        for ch in func.get_children():
            if not ch.kind.is_attribute():
                continue
            loc = ch.extent.start
            path = str(loc.file) if loc.file else ""
            text = (self._line_text(path, loc.line) + " " +
                    self._line_text(path, loc.line + 1))
            for m in re.finditer(r"PCCLT_(\w+)\s*\(([^()]*)\)", text):
                if m.group(1) not in macro_names:
                    continue
                for arg in m.group(2).split(","):
                    arg = arg.strip()
                    if arg:
                        out.append(arg)
        return out

    def _resolve_annotation_arg(self, func, arg: str) -> "str | None":
        """Map an annotation argument name (e.g. ``mu_``) to an identity,
        by looking it up among the owning class's fields, then params."""
        arg = arg.strip().removesuffix(")").strip()
        K = self.K
        for i, p in enumerate(self._params(func)):
            if p.spelling == arg:
                return f"param:{i}"
        cls = func.semantic_parent
        if cls is not None and cls.kind in (K.CLASS_DECL, K.STRUCT_DECL,
                                            K.CLASS_TEMPLATE):
            for ch in cls.get_children():
                if ch.kind == K.FIELD_DECL and ch.spelling == arg:
                    if self._is_mutex_type(ch.type):
                        return self.note_lock_decl(ch)
        return None

    def _params(self, func) -> list:
        return [ch for ch in func.get_children()
                if ch.kind == self.K.PARM_DECL]

    def visit_function(self, func) -> None:
        usr = func.get_usr()
        if usr in self.prog.funcs:
            return
        loc = func.location
        facts = FuncFacts(
            usr=usr, name=self.qualified(func),
            file=display_rel(self.root, str(loc.file) if loc.file else ""),
            line=loc.line)
        req: "list[str]" = []
        for arg in self._attr_locks(func, ("REQUIRES", "REQUIRES_SHARED")):
            ident = self._resolve_annotation_arg(func, arg)
            if ident is not None and not ident.startswith("param:"):
                req.append(ident)
            elif ident is not None:
                # REQUIRES(param): held identity is the param placeholder
                req.append(ident)
        facts.requires = tuple(req)
        self.prog.funcs[usr] = facts

        body = None
        for ch in func.get_children():
            if ch.kind == self.K.COMPOUND_STMT:
                body = ch
        if body is None:
            return
        mutex_params = {p.get_usr(): f"param:{i}"
                        for i, p in enumerate(self._params(func))
                        if self._is_mutex_type(p.type)}
        held: "dict[str, int]" = {r: 1 for r in facts.requires}
        self._walk_stmt(body, facts, held, {}, mutex_params)

    # -- statement walk with a held-set --------------------------------

    def _held_tuple(self, held: "dict[str, int]") -> "tuple[str, ...]":
        return tuple(sorted(k for k, v in held.items() if v > 0))

    def _acquire(self, facts, held, lock: str, cursor) -> None:
        loc = cursor.location
        facts.acquires.append(Acquire(
            lock, self._held_tuple(held),
            display_rel(self.root, str(loc.file) if loc.file else ""),
            loc.line))
        held[lock] = held.get(lock, 0) + 1

    def _release(self, held, lock: str) -> None:
        if held.get(lock, 0) > 0:
            held[lock] -= 1

    def _walk_stmt(self, c, facts, held, lockvars, mutex_params,
                   scope_locks: "list[str] | None" = None) -> None:
        """Recursive walk. `held` maps identity -> count; `lockvars` maps
        MutexLock var USR -> identity; compound statements release their
        RAII acquisitions on exit."""
        K = self.K

        if c.kind == K.COMPOUND_STMT:
            my_scope: "list[str]" = []
            for ch in c.get_children():
                self._walk_stmt(ch, facts, held, lockvars, mutex_params,
                                my_scope)
            for lock in my_scope:
                self._release(held, lock)
            return

        if c.kind == K.LAMBDA_EXPR:
            # separate "function": empty held-set (runs on another thread)
            sub = FuncFacts(
                usr=f"{facts.usr}:lambda:{c.location.line}",
                name=f"{facts.name}::<lambda@{c.location.line}>",
                file=facts.file, line=c.location.line)
            self.prog.funcs[sub.usr] = sub
            body = None
            for ch in c.get_children():
                if ch.kind == K.COMPOUND_STMT:
                    body = ch
            if body is not None:
                self._walk_stmt(body, sub, {}, {}, {})
            # No call edge from the enclosing function: nearly every lambda
            # here is a deferred thread body (or an atexit hook) that does
            # NOT run under the definition point's locks, and an edge would
            # manufacture false self-edges (the reader-thread gate in
            # Master::launch) and false may-block taints (the Recorder's
            # atexit dump). The lambda body is still analyzed standalone —
            # its own critical sections are checked. The cost is missing
            # immediately-invoked lambdas under a lock — a pattern the
            # PR-4 discipline already bans (docs/11_static_analysis.md).
            return

        if c.kind == K.VAR_DECL and self._is_mutexlock_type(c.type):
            mu = None
            for ch in c.get_children():
                mu = self.resolve_mutex_expr(ch) or mu
            if mu is None:
                mu = f"<unresolved@{facts.file}:{c.location.line}>"
            if mu.startswith("param:"):
                # normalize the name form to the index form (functions
                # taking one Mutex& param, i.e. send_frame's write_mu)
                for ident in mutex_params.values():
                    mu = ident
            self._acquire(facts, held, mu, c)
            lockvars[c.get_usr()] = mu
            if scope_locks is not None:
                scope_locks.append(mu)
            return

        if c.kind == K.VAR_DECL and self._is_mutex_type(c.type):
            self.note_lock_decl(c)  # function-local mutex

        if c.kind == K.CALL_EXPR:
            self._visit_call(c, facts, held, lockvars, mutex_params)
            # still walk children: nested calls appear as children
            for ch in c.get_children():
                self._walk_stmt(ch, facts, held, lockvars, mutex_params,
                                scope_locks)
            return

        for ch in c.get_children():
            self._walk_stmt(ch, facts, held, lockvars, mutex_params,
                            scope_locks)

    # -- call handling --------------------------------------------------

    def _call_object_lock(self, call, lockvars, mutex_params
                          ) -> "str | None":
        """For obj.method() calls, resolve `obj` when it is a MutexLock
        variable or a Mutex; returns its identity."""
        children = list(call.get_children())
        if not children:
            return None
        base = children[0]
        # member call: first child is MEMBER_REF_EXPR whose first child is
        # the object expression
        if base.kind == self.K.MEMBER_REF_EXPR:
            sub = list(base.get_children())
            obj = sub[0] if sub else None
        else:
            obj = base
        if obj is None:
            return None
        K = self.K
        e = obj
        while e is not None and e.kind not in (K.DECL_REF_EXPR,
                                               K.MEMBER_REF_EXPR):
            nxt = list(e.get_children())
            e = nxt[0] if nxt else None
        if e is None:
            return None
        ref = e.referenced
        if ref is None:
            return None
        if ref.get_usr() in lockvars:
            return lockvars[ref.get_usr()]
        if self._is_mutex_type(ref.type):
            if ref.kind == self.K.PARM_DECL:
                # map to param index
                return mutex_params.get(ref.get_usr(),
                                        f"param:{ref.spelling}")
            return self.note_lock_decl(ref)
        return None

    def _visit_call(self, call, facts, held, lockvars, mutex_params) -> None:
        ref = call.referenced
        name = ref.spelling if ref is not None else call.spelling
        parent = (self.qualified(ref.semantic_parent)
                  if ref is not None and ref.semantic_parent is not None
                  else "")
        loc = call.location
        rel = display_rel(self.root, str(loc.file) if loc.file else "")

        # Mutex/MutexLock state transitions
        if name in ("lock", "unlock", "try_lock"):
            tgt = self._call_object_lock(call, lockvars, mutex_params)
            if tgt is not None:
                if name in ("lock", "try_lock"):
                    self._acquire(facts, held, tgt, call)
                else:
                    self._release(held, tgt)
                return

        # CondVar waits: first arg is the mutex released during the wait
        if (ref is not None and parent.endswith("CondVar")
                and name in ("wait", "wait_for", "wait_until")):
            args = [ch for ch in call.get_children()][1:]
            mu = None
            for a in args:
                mu = self.resolve_mutex_expr(a)
                if mu is not None:
                    break
            if mu is not None and mu.startswith("param:"):
                pass
            facts.cv_waits.append(CvWait(
                mu or "<unknown>", self._held_tuple(held), rel, loc.line))
            return

        # blocking primitives
        if ref is not None:
            is_method = ref.kind == self.K.CXX_METHOD
            ns = parent.rsplit("::", 1)[-1] if parent else ""
            if ((not is_method and name in BLOCKING_FUNCTIONS
                 and "std::" not in parent)
                    or (is_method and (ns, name) in BLOCKING_METHODS)
                    or name in BLOCKING_QUALIFIED):
                facts.blocking.append(BlockingCall(
                    (f"{ns}::{name}" if is_method else name),
                    self._held_tuple(held), rel, loc.line))
                return

        if ref is None:
            return
        # ordinary call: record with resolved Mutex& arguments
        callee_usr = ref.get_usr()
        if not callee_usr:
            return
        margs: "list[tuple[int, str]]" = []
        params = self._params(ref)
        if any(self._is_mutex_type(p.type) for p in params):
            args = list(call.get_children())
            # member calls: children[0] is the callee expr; free functions:
            # children[0] is an unexposed ref — align from the tail
            argexprs = args[-len(params):] if params else []
            for i, (p, a) in enumerate(zip(params, argexprs)):
                if not self._is_mutex_type(p.type):
                    continue
                ident = self.resolve_mutex_expr(a)
                if ident is not None and ident.startswith("param:"):
                    # caller's own param forwarded: map name->index form
                    ident = next(iter(mutex_params.values()), ident)
                if ident is not None:
                    margs.append((i, ident))
        facts.calls.append(CallSite(
            callee_usr, (f"{parent}::{name}" if parent else name),
            self._held_tuple(held), rel, loc.line, tuple(margs)))
