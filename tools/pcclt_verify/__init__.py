"""pcclt-verify: whole-program concurrency verification for the native core.

PR 4's toolchain (tools/pcclt_check) proves per-TU lock *discipline*: every
guarded field is accessed under its declared mutex. This layer proves the
two properties discipline alone cannot:

  * the whole-program lock acquisition graph is DEADLOCK-FREE — every
    ``pcclt::Mutex`` carries a declared rank (``// lock-rank: N``), every
    observed acquisition order respects the ranks, and the harvested graph
    has no cycle                           (checkers: ``lockorder``)
  * no critical section blocks — no socket send/recv/connect/poll, no
    journal fsync, no sleep while holding a non-IO lock, and no CondVar
    wait while a *different* mutex is held (checker:  ``blocking``)
  * the master's membership/consensus machine and the client session FSM
    have no stuck-world interleavings — an explicit-state model checker
    DFS-explores join/leave/kick/disconnect-mid-vote/master-restart/
    resume/limbo-expiry at world <= 4      (checker:  ``fsm``)
  * the model cannot drift from the code — the spec's packet-triggered
    transitions are diffed against the real kC2M*/kM2C* dispatch arms in
    master.cpp / client.cpp               (checker:  ``conformance``)

Run everything: ``python -m tools.pcclt_verify``.  See
``docs/11_static_analysis.md`` for the lock-rank discipline and the FSM
spec format.
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, Iterable

# One Finding/Skip vocabulary across the whole static-analysis toolchain:
# pcclt_verify findings print and exit exactly like pcclt_check's.
from tools.pcclt_check import Finding, Skip

__all__ = ["Finding", "Skip", "checker_names", "run"]

CheckFn = Callable[[Path], "list[Finding] | Skip"]


def _registry() -> "dict[str, CheckFn]":
    # imported lazily so `--checker fsm` does not pay for libclang
    from . import (blocking, conformance, dataplane_check, lock_graph,
                   model_check)

    return {
        "lockorder": lock_graph.check,
        "blocking": blocking.check,
        "fsm": model_check.check,
        "conformance": conformance.check,
        "dataplane": dataplane_check.check,
    }


def checker_names() -> "list[str]":
    return list(_registry())


def run(root: Path, names: "Iterable[str] | None" = None
        ) -> "tuple[list[Finding], list[Skip]]":
    """Run the named checkers (default: all) against the tree at `root`."""
    registry = _registry()
    findings: "list[Finding]" = []
    skips: "list[Skip]" = []
    for name in names if names is not None else registry:
        if name not in registry:
            raise KeyError(f"unknown checker {name!r}; have {sorted(registry)}")
        out = registry[name](Path(root))
        if isinstance(out, Skip):
            skips.append(out)
        else:
            findings.extend(out)
    return findings, skips
