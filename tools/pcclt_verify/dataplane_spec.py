"""Executable spec of the pooled data plane: frames, sinks, the ladder.

The control-plane spec (``fsm_spec.py``) deliberately declares the byte
plane out of model.  This module is that missing layer: a Python mirror of
the machinery that makes faults bit-identical —

  * the **frame vocabulary** of ``MultiplexConn`` (``sockets.hpp``'s
    ``Kind`` enum) and the RX/TX dispatch arms in ``sockets.cpp`` that
    route each kind (tables below, pinned by the dataplane conformance
    pass in ``dataplane_check.py``);
  * the **SinkTable** claim/publish/dedup/retire machine
    (``sockets.cpp``): byte-range coverage via ``prefix``/``extents``/
    ``claims``, first-verified-arrival-wins dedupe, queued frames and
    parked relay windows that raced sink registration, and the
    ``retired_`` tag ranges that turn post-completion stragglers into
    counted duplicates;
  * the **watchdog ladder** (``reduce.cpp``): OK -> SUSPECT (re-issue the
    window on a fresh pool conn; first success wins, the loser dedupes)
    -> CONFIRMED (acked relay detour via a healthy third peer), with
    end-to-end ``kRelayAck`` coverage merged origin-side
    (``Client::note_relay_ack``) so a stalled direct copy — a *zombie* —
    retires early only once its whole span is acked
    (``Client::relay_ack_covered``);
  * the **chunk plane** round trip (``kChunkReq``/``kChunkHdr`` + striped
    ``kData`` payloads) including serve-side seeder death and the
    retire/un-retire rule that makes tag reuse across op incarnations
    legal (``SinkTable::register_sink``'s single-tag un-retire).

Deliberate abstractions, in the control-plane spec's style:

  * bytes carry no content — the plane is bit-identical by construction
    (content-addressed chunks, deterministic reductions), so coverage
    arithmetic over ``[off, end)`` ranges IS the payload model;
  * conns are reduced to "the transfer a frame rides": conn death maps to
    in-flight frame loss plus claim release (``rx_loop``'s mid-write
    failure path);
  * CMA/shm same-host kinds keep their dispatch-table entries (the
    conformance pass pins them) but are not explored — they bypass the
    byte-conservation machinery (descriptor acks complete sender handles
    without touching sink coverage);
  * rx accounting is counted at frame *commit*: the real ``rx_loop``
    counts ``rx_bytes`` at header parse, so a conn dying mid-frame leaves
    telemetry slop on a dying edge.  The conservation identity is
    therefore specified — and checked — over cleanly delivered traffic;
  * ``purge_range`` in the model counts purged queued frames as
    duplicates so the identity stays exact across aborts; the
    implementation drops them unattributed (aborted ops sit outside its
    exactness claim, which covers completed traffic only).

The invariants the explorer (``dataplane_check.py``) holds this model to:

  ===================  ====================================================
  conservation         rx_bytes + rx_relay_bytes - dup_bytes equals the
                       unique payload ground truth (published coverage +
                       retained coverage of retired sinks + queued bytes)
                       at every reachable state
  no-double-publish    no placement ever publishes into a byte range
                       another writer has claimed and not yet published
  ack-retire           a zombie cancelled via relay acks has its whole
                       span acked, and every acked byte is accounted for
                       at the receiver (placed, parked, queued, or
                       dropped-as-duplicate)
  no-stuck             every reachable state has a path to quiescence
                       (ops complete or abort under any fault schedule)
  ===================  ====================================================
"""

from __future__ import annotations

import dataclasses

# --------------------------------------------------------------------------
# frame vocabulary (sockets.hpp MultiplexConn::Kind) — conformance-pinned
# --------------------------------------------------------------------------

FRAME_KINDS: "dict[str, int]" = {
    "kData": 0,
    "kCmaDesc": 1,
    "kCmaAck": 2,
    "kCmaNack": 3,
    "kCmaHello": 4,
    "kShmAnnounce": 5,
    "kShmRetire": 6,
    "kCmaAckDrop": 7,
    "kRelayFwd": 8,
    "kRelayDeliver": 9,
    "kRelayAck": 10,
    "kChunkReq": 11,
    "kChunkHdr": 12,
}

# rx_loop dispatch: kind -> the arm that consumes it. Kinds sharing one
# `if (kind == a || kind == b)` condition share an arm label. kData is the
# fall-through arm (the sink fast path) — there is no `if` for it; the
# conformance pass checks the arm's marker comment instead.
RX_DISPATCH: "dict[str, str]" = {
    "kCmaAck": "cma_completion",
    "kCmaAckDrop": "cma_completion",
    "kCmaNack": "cma_completion",
    "kCmaHello": "cma_hello",
    "kShmAnnounce": "shm_announce",
    "kShmRetire": "shm_retire",
    "kCmaDesc": "cma_desc",
    "kRelayFwd": "relay_window",
    "kRelayDeliver": "relay_window",
    "kRelayAck": "relay_ack",
    "kChunkReq": "chunk_req",
    "kChunkHdr": "chunk_hdr",
    "kData": "sink_fastpath",  # fall-through, not an if-arm
}

# tx_loop dispatch: every kind must have a `case` in the send switch
# (kCmaDesc/kShmAnnounce/kShmRetire are never enqueued — shm_sync_tx
# writes them inline — but their arms must exist and say so).
TX_ARMS: "set[str]" = set(FRAME_KINDS)

# kinds the conn routes to installed client hooks instead of handling
# internally: kind -> the MultiplexConn hook member its rx arm invokes.
ROUTED_KINDS: "dict[str, str]" = {
    "kRelayFwd": "relay_fwd_",
    "kRelayDeliver": "relay_deliver_",
    "kRelayAck": "relay_ack_",
    "kChunkReq": "chunk_req_",
}

# kinds client.cpp originates over the pool (send_owned/send_async sites).
CLIENT_SENDS: "set[str]" = {
    "kData", "kRelayFwd", "kRelayDeliver", "kRelayAck",
    "kChunkReq", "kChunkHdr",
}

# the reduce.cpp failover ladder (enum EdgeHealth) the watchdog climbs —
# monotone within an op: OK -> SUSPECT -> CONFIRMED.
LADDER: "dict[str, int]" = {"kOk": 0, "kSuspect": 1, "kConfirmed": 2}

# ss_chunk.hpp PlanStats counters whose documented conservation identities
# the chunk plane rests on (fetched + resourced - dup == unique;
# unique + delta_skipped == total): pinned so a counter rename in the
# real tree orphans the spec'd identity.
PLAN_STATS_FIELDS: "set[str]" = {
    "bytes_fetched", "bytes_resourced", "bytes_dup", "unique_bytes",
    "bytes_delta_skipped",
}


class DataViolation(Exception):
    """An invariant of the data-plane spec broken by a model step."""


def _merge_into(m: "dict[int, int]", lo: int, hi: int) -> None:
    """Interval-merge [lo, hi) into a map off->end (note_relay_ack)."""
    drop = []
    for o, e in m.items():
        if e >= lo and o <= hi:  # touching or overlapping
            lo = min(lo, o)
            hi = max(hi, e)
            drop.append(o)
    for o in drop:
        del m[o]
    m[lo] = hi


# --------------------------------------------------------------------------
# SinkTable model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class SinkModel:
    """One registered sink: mirrors SinkTable::Sink's coverage machine."""

    cap: int
    prefix: int = 0
    extents: "dict[int, int]" = dataclasses.field(default_factory=dict)
    claims: "dict[int, int]" = dataclasses.field(default_factory=dict)
    busy: int = 0
    cancel: bool = False

    def copy(self) -> "SinkModel":
        return SinkModel(self.cap, self.prefix, dict(self.extents),
                         dict(self.claims), self.busy, self.cancel)

    def freeze(self):
        return (self.cap, self.prefix, tuple(sorted(self.extents.items())),
                tuple(sorted(self.claims.items())), self.busy, self.cancel)

    # -- coverage arithmetic (Sink::fully_covered / published_overlap) --

    def _byte_in(self, b: int, with_claims: bool) -> bool:
        if b < self.prefix:
            return True
        maps = (self.extents, self.claims) if with_claims else (self.extents,)
        return any(o <= b < e for m in maps for o, e in m.items())

    def covered_bytes(self, off: int, end: int) -> int:
        """Bytes of [off, end) covered by prefix/extents/claims."""
        return sum(1 for b in range(off, end) if self._byte_in(b, True))

    def published_bytes(self, off: int, end: int) -> int:
        """Bytes of [off, end) actually published (prefix/extents only)."""
        return sum(1 for b in range(off, end) if self._byte_in(b, False))

    def fully_covered(self, off: int, end: int) -> bool:
        return self.covered_bytes(off, end) == end - off

    def add_extent(self, off: int, end: int) -> None:
        if off <= self.prefix:
            self.prefix = max(self.prefix, end)
            while True:
                nxt = [o for o in self.extents if o <= self.prefix]
                if not nxt:
                    break
                for o in nxt:
                    self.prefix = max(self.prefix, self.extents.pop(o))
        else:
            self.extents[off] = max(self.extents.get(off, 0), end)

    def published_total(self) -> int:
        return self.published_bytes(0, self.cap)

    def complete(self) -> bool:
        return self.cap > 0 and self.prefix >= self.cap


@dataclasses.dataclass
class Counters:
    """The per-edge conservation counters, folded to one aggregate."""

    rx_bytes: int = 0
    rx_relay_bytes: int = 0
    dup_bytes: int = 0

    def copy(self) -> "Counters":
        return dataclasses.replace(self)

    def freeze(self):
        return (self.rx_bytes, self.rx_relay_bytes, self.dup_bytes)


class TableModel:
    """Mirror of SinkTable: sinks, queued frames, parked relay windows,
    retired tag ranges, and the conservation counters.

    Overridable RULE methods (the mutation-test surface, mirroring
    MasterModel's style):

      * ``dedup_direct``   — the fully-covered first-arrival-wins verdict
                             the kData fast path runs before claiming;
      * ``dup_on_commit``  — the duplicate-byte accounting of a committed
                             direct write (bytes that did not grow
                             coverage count as duplicates);
      * ``unretire_on_register`` — register_sink's single-tag un-retire
                             that makes tag reuse across op incarnations
                             legal.
    """

    def __init__(self) -> None:
        self.sinks: "dict[int, SinkModel]" = {}
        self.queues: "dict[int, tuple]" = {}      # tag -> ((off, len), ...)
        self.relay_pending: "dict[int, tuple]" = {}
        self.retired: "tuple[tuple[int, int], ...]" = ()
        self.counters = Counters()
        self.retained = 0  # published coverage of retired/unregistered sinks

    # ---- copy/freeze ----

    def copy(self) -> "TableModel":
        t = type(self)()
        t.sinks = {k: s.copy() for k, s in self.sinks.items()}
        t.queues = dict(self.queues)
        t.relay_pending = dict(self.relay_pending)
        t.retired = self.retired
        t.counters = self.counters.copy()
        t.retained = self.retained
        return t

    def freeze(self):
        return (tuple((k, s.freeze()) for k, s in sorted(self.sinks.items())),
                tuple(sorted(self.queues.items())),
                tuple(sorted(self.relay_pending.items())),
                self.retired, self.counters.freeze(), self.retained)

    # ---- retire machinery ----

    def is_retired(self, tag: int) -> bool:
        return any(lo <= tag < hi for lo, hi in self.retired)

    def unretire_on_register(self, tag: int) -> None:
        # RULE: register_sink removes a completed-tag marker (single-tag
        # entries from unregister_sink) — re-registration means the tag is
        # live again, so tag reuse across op incarnations stays legal.
        self.retired = tuple((lo, hi) for lo, hi in self.retired
                             if not (lo == tag and hi == tag + 1))

    # ---- dedup rules ----

    def dedup_direct(self, s: SinkModel, off: int, end: int) -> bool:
        # RULE: the kData fast path drops (and counts) a frame whose whole
        # range is already covered by prefix/extents/claims — first
        # verified arrival wins; published bytes are never rewritten under
        # a consumer.
        return s.fully_covered(off, end)

    def dup_on_commit(self, length: int, fresh: int) -> int:
        # RULE: a committed direct write whose range partially overlapped
        # already-published bytes grew coverage by `fresh` only — the
        # remainder is a duplicate and must be counted, or the identity
        # rx + relay - dup == unique drifts on every relay-vs-direct race
        # whose window boundaries misalign (model-checker finding; see the
        # published_overlap accounting in sockets.cpp's rx_loop).
        return length - fresh

    # ---- sink lifecycle ----

    def register_sink(self, tag: int, cap: int) -> None:
        self.unretire_on_register(tag)
        s = SinkModel(cap)
        # frames that raced ahead of registration were queued with offsets
        for off, length in self.queues.pop(tag, ()):
            if off + length <= cap:
                s.add_extent(off, off + length)
        self.sinks[tag] = s
        # parked failover windows: place with the same dedupe + accounting
        # as a live delivery
        for off, length in self.relay_pending.pop(tag, ()):
            delivered = 0
            if not s.cancel and off + length <= cap:
                delivered, _ = self._place_deduped(s, off, length)
            self.counters.rx_relay_bytes += length
            self.counters.dup_bytes += length - delivered

    def unregister_sink(self, tag: int) -> None:
        s = self.sinks.get(tag)
        if s is None:
            return
        if s.busy:
            raise DataViolation(
                "unregister_sink while a writer is busy — the real table "
                "waits out wait_not_busy_range first (model ordering bug)")
        complete = s.complete()
        self.retained += s.published_total()
        del self.sinks[tag]
        if complete:
            self.retired = self.retired + ((tag, tag + 1),)

    def purge(self, tag: int) -> None:
        """purge_range([tag, tag+1)): cancel, drop, retire. The model
        counts dropped queued bytes as duplicates (see module docstring)."""
        s = self.sinks.get(tag)
        if s is not None:
            if s.busy:
                raise DataViolation("purge finishing with a busy writer — "
                                    "wait_not_busy_range ordering bug")
            self.retained += s.published_total()
            del self.sinks[tag]
        for off, length in self.queues.pop(tag, ()):
            if off != "hdr":
                self.counters.dup_bytes += length
        for off, length in self.relay_pending.pop(tag, ()):
            self.counters.rx_relay_bytes += length
            self.counters.dup_bytes += length
        self.retired = self.retired + ((tag, tag + 1),)

    # ---- frame arrival (the rx_loop arms) ----

    def data_begin(self, tag: int, off: int, length: int) -> str:
        """kData header parsed: dedupe verdict + claim. Returns 'claimed',
        'dup' (drained + counted), or 'queued'."""
        end = off + length
        s = self.sinks.get(tag)
        if s is not None and not s.cancel and end <= s.cap:
            if self.dedup_direct(s, off, end):
                self.counters.rx_bytes += length
                self.counters.dup_bytes += length
                return "dup"
            s.busy += 1
            # claim before writing: a concurrent failover delivery must
            # skip (not republish) the range we are filling
            s.claims[off] = max(s.claims.get(off, 0), end)
            return "claimed"
        if self.is_retired(tag) or s is not None:
            # post-completion straggler, or cancelled/overflow: drain+count
            self.counters.rx_bytes += length
            self.counters.dup_bytes += length
            return "dup"
        # no sink yet: queue for registration. Exact-range duplicates are
        # dropped and counted here — a re-issued window racing sink
        # registration must not queue twice (both copies would later
        # publish as extents with no dup accounting; model-checker
        # finding, mirrored by the queue dedupe in sockets.cpp).
        if (off, length) in self.queues.get(tag, ()):
            self.counters.rx_bytes += length
            self.counters.dup_bytes += length
            return "dup"
        self.queues[tag] = self.queues.get(tag, ()) + ((off, length),)
        self.counters.rx_bytes += length
        return "queued"

    def data_commit(self, tag: int, off: int, length: int) -> None:
        """The claimed write finished cleanly: publish + account."""
        end = off + length
        s = self.sinks.get(tag)
        if s is None:
            raise DataViolation("commit for an unregistered sink — busy "
                                "must pin the sink (wait_not_busy_range)")
        s.busy -= 1
        fresh = length - s.published_bytes(off, end)
        s.claims.pop(off, None)
        s.add_extent(off, end)
        self.counters.rx_bytes += length
        self.counters.dup_bytes += self.dup_on_commit(length, fresh)

    def data_die(self, tag: int, off: int, length: int) -> None:
        """Conn died mid-write: claim released, nothing published, and no
        rx is counted for the torn frame (see the module docstring)."""
        s = self.sinks.get(tag)
        if s is None:
            raise DataViolation("mid-write death for an unregistered sink")
        s.busy -= 1
        s.claims.pop(off, None)

    def _place_deduped(self, s: SinkModel, off: int,
                       length: int) -> "tuple[int, tuple[int, ...]]":
        """Byte-granular gap filling (SinkTable::place_deduped): fill only
        what prefix/extents/claims leave open; never touch a claim.
        Returns (delivered, placed byte positions)."""
        placed = []
        for b in range(off, off + length):
            if s._byte_in(b, True):
                continue
            placed.append(b)
            s.add_extent(b, b + 1)
        return len(placed), tuple(placed)

    def deliver_window(self, tag: int, off: int, length: int) -> bool:
        """kRelayDeliver handled (SinkTable::deliver_window). Placed bytes
        publish; the remainder counts duplicate. Returns whether the range
        is DURABLY accounted for afterwards — the kRelayAck gate: bytes
        skipped against a mid-write CLAIM are not durable (the claim
        holder can die and tear them), so such a window must not be acked
        (model-checker finding, relay_vs_direct_deaths)."""
        if self.is_retired(tag):
            self.counters.rx_relay_bytes += length
            self.counters.dup_bytes += length
            return True  # finished op: its bytes are settled
        s = self.sinks.get(tag)
        if s is None:
            # raced ahead of the stage's registration: park it — held
            # verbatim until the sink appears, so the range is durable
            self.relay_pending[tag] = (self.relay_pending.get(tag, ())
                                       + ((off, length),))
            return True
        delivered = 0
        ack_ok = False
        if not s.cancel and off + length <= s.cap:
            claims_before = dict(s.claims)
            delivered, placed = self._place_deduped(s, off, length)
            for b in placed:
                if any(o <= b < e for o, e in claims_before.items()):
                    raise DataViolation(
                        f"relay placement published byte {b} inside a "
                        "claimed range another writer is filling — "
                        "double-publish into a claimed range")
            ack_ok = s.published_bytes(off, off + length) == length
        else:
            # cancelled: the consumer is tossing the op, acking cannot
            # lose wanted bytes; overflow: malformed, never acked
            ack_ok = s.cancel
        self.counters.rx_relay_bytes += length
        self.counters.dup_bytes += length - delivered
        return ack_ok

    def chunk_hdr(self, tag: int, status: int) -> None:
        """kChunkHdr queued for the fetch worker — dropped if retired."""
        if self.is_retired(tag):
            return
        self.queues[tag] = self.queues.get(tag, ()) + (("hdr", status),)

    def take_hdr_peek(self, tag: int) -> bool:
        return any(item[0] == "hdr" for item in self.queues.get(tag, ()))

    def take_hdr(self, tag: int) -> "int | None":
        q = self.queues.get(tag, ())
        for i, item in enumerate(q):
            if item[0] == "hdr":
                self.queues[tag] = q[:i] + q[i + 1:]
                if not self.queues[tag]:
                    del self.queues[tag]
                return item[1]
        return None

    # ---- the conservation identity ----

    def unique_truth(self) -> int:
        """Ground-truth unique payload: published coverage of live sinks,
        retained coverage of finished ones, and data bytes held queued."""
        live = sum(s.published_total() for s in self.sinks.values())
        queued = sum(length for q in self.queues.values()
                     for off, length in q if off != "hdr")
        return self.retained + live + queued

    def byte_present(self, tag: int, b: int) -> bool:
        """Is byte `b` of `tag` accounted for receiver-side? (placed,
        queued, parked, or legitimately dropped on a finished/cancelled
        sink) — the ack-retire soundness witness.

        A LIVE sink takes precedence over a retired marker: in the correct
        model the two never coexist (register_sink un-retires), and a
        mutant that breaks the unretire rule must not get its wrongly-kept
        marker accepted as evidence that the live op's bytes arrived."""
        s = self.sinks.get(tag)
        if s is not None:
            if s.cancel or s._byte_in(b, True):
                return True
        elif self.is_retired(tag):
            return True
        for off, length in self.queues.get(tag, ()):
            if off != "hdr" and off <= b < off + length:
                return True
        for off, length in self.relay_pending.get(tag, ()):
            if off <= b < off + length:
                return True
        return False

    def check_conservation(self) -> None:
        c = self.counters
        truth = self.unique_truth()
        if c.rx_bytes + c.rx_relay_bytes - c.dup_bytes != truth:
            raise DataViolation(
                f"byte conservation violated: rx {c.rx_bytes} + relay "
                f"{c.rx_relay_bytes} - dup {c.dup_bytes} != unique {truth} "
                "— a copy was double-published or a duplicate went "
                "uncounted")


# --------------------------------------------------------------------------
# origin-side ack machine (Client::note_relay_ack / relay_ack_covered)
# --------------------------------------------------------------------------


class AckModel:
    """The origin's merged relay-ack coverage, per tag.

    Overridable RULE methods:

      * ``note_ack``    — interval-MERGE the acked range (adjacent and
                          overlapping acks coalesce into one interval);
      * ``ack_covered`` — containment: one merged interval must span the
                          whole queried range before a zombie may retire.
    """

    def __init__(self) -> None:
        self.acks: "dict[int, dict[int, int]]" = {}

    def copy(self) -> "AckModel":
        a = type(self)()
        a.acks = {t: dict(m) for t, m in self.acks.items()}
        return a

    def freeze(self):
        return tuple((t, tuple(sorted(m.items())))
                     for t, m in sorted(self.acks.items()))

    def note_ack(self, tag: int, off: int, length: int) -> None:
        if length == 0:
            return
        _merge_into(self.acks.setdefault(tag, {}), off, off + length)

    def ack_covered(self, tag: int, off: int, length: int) -> bool:
        m = self.acks.get(tag)
        if not m:
            return False
        return any(o <= off and e >= off + length for o, e in m.items())
