"""CLI: ``python -m tools.pcclt_verify [--root DIR] [--checker NAME ...]``.

Exit codes: 0 = clean, 1 = violation found, 2 = usage error. (Same
contract as ``tools.pcclt_check``; the lint lane runs both.)
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from . import checker_names, run


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pcclt_verify",
        description="lock-order/blocking analysis + control-plane model "
                    "checking for the native core",
    )
    ap.add_argument("--root", type=Path,
                    default=Path(__file__).resolve().parents[2],
                    help="repo root (default: inferred from this file)")
    ap.add_argument("--checker", action="append", choices=checker_names(),
                    help="run only this checker (repeatable; default: all)")
    ap.add_argument("--list", action="store_true", help="list checkers and exit")
    args = ap.parse_args(argv)

    if args.list:
        for n in checker_names():
            print(n)
        return 0
    root = args.root.resolve()
    if not (root / "pccl_tpu").is_dir():
        print(f"pcclt_verify: {root} does not look like a pcclt repo "
              "(no pccl_tpu/)", file=sys.stderr)
        return 2

    t0 = time.monotonic()
    try:
        findings, skips = run(root, args.checker)
    except KeyError as e:
        print(f"pcclt_verify: {e}", file=sys.stderr)
        return 2
    for s in skips:
        print(s, file=sys.stderr)
    for f in findings:
        print(f)
    names = args.checker or checker_names()
    status = "FAILED" if findings else "ok"
    print(f"pcclt_verify: {len(findings)} finding(s) from "
          f"{len(names) - len(skips)} checker(s) "
          f"({time.monotonic() - t0:.1f}s) -- {status}", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
