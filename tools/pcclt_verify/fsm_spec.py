"""Executable spec of the CCoIP control plane: master consensus machine +
client session FSM.

This is a hand-written Python mirror of ``master_state.cpp`` (one method
per ``MasterState::on_*`` handler, same names) and of the client protocol
loop in ``client.cpp`` (connect/establish, topology vote with the
deferred tie-break, collective init->commence->complete->exactly-one-abort
->done, shared-state sync, optimize, master-restart resume with the
session-generation retry rule). The model checker (``model_check.py``)
DFS-explores every interleaving of these machines; the ``conformance``
checker diffs the packet tables below against the real dispatch arms so
the spec cannot silently drift from the code.

Abstractions (deliberate, documented):
  * payload *contents* are reduced to what the control flow branches on
    (revisions, tags, ok flags); tensor data, hashes and endpoint info are
    out of scope;
  * shared-state entries always agree in key-set and content, and all
    clients use enforce-popular — the mask-election/kick ladder for
    mismatched offers is data-plane validation, not interleaving logic;
  * p2p establishment succeeds unless the scenario injects a failure;
  * bandwidth matrices collapse to one "measured" bit per client;
  * client<->master delivery is instant into per-client FIFO inboxes
    (TCP per-connection ordering + the single-dispatcher master make the
    *order of client sends* the only real nondeterminism), and clients
    consume replies by type-matched scan, mirroring ControlClient's
    matched receive.
"""

from __future__ import annotations

import dataclasses
from typing import Any

# --------------------------------------------------------------------------
# Packet-transition tables (diffed against master.cpp/client.cpp by the
# `conformance` checker — extend BOTH the code and these when adding ids).
# --------------------------------------------------------------------------

# kC2M packet -> MasterState handler its dispatch arm must call
MASTER_DISPATCH = {
    "kC2MHello": "on_hello",
    "kC2MSessionResume": "on_session_resume",
    "kC2MTopologyUpdate": "on_topology_update",
    "kC2MPeersPendingQuery": "on_peers_pending_query",
    "kC2MP2PEstablished": "on_p2p_established",
    "kC2MCollectiveInit": "on_collective_init",
    "kC2MCollectiveComplete": "on_collective_complete",
    "kC2MSharedStateSync": "on_shared_state_sync",
    "kC2MSharedStateDistDone": "on_dist_done",
    "kC2MOptimizeTopology": "on_optimize",
    "kC2MBandwidthReport": "on_bandwidth_report",
    "kC2MOptimizeWorkDone": "on_optimize_work_done",
    "kC2MTelemetryDigest": "on_telemetry_digest",
    "kC2MSyncKeyDone": "on_sync_key_done",
}

# kM2C ids the master machine can emit (master_state.cpp).
# kM2CIncidentDump is fire-and-forget and env-gated (PCCLT_INCIDENT_DIR):
# it never participates in consensus — no vote, no reply, no state the
# client FSM observes — so the model checker keeps it OUT of the explored
# state space (like the data-plane watchdog, docs/11): MasterModel never
# emits it and the client model never consumes it. Conformance still pins
# the id to its emission site and the client's set_notify consumption.
# kC2MSyncKeyDone / kM2CSeederUpdate (chunk plane, docs/04) are the same
# class of out-of-model traffic: a promotion is data-plane routing advice
# inside one sync round — no vote, no reply, no consensus state change
# (on_sync_key_done mutates only the round's promotion dedupe set). The
# model's on_sync_key_done is a no-op and the client model never consumes
# the update; conformance pins both ids to their real sites.
# kM2CScheduleUpdate (schedule synthesizer, docs/12) is the same
# fire-and-forget class: per-op algorithm binding rides the commence stamp,
# so a late or lost update can never split the group — the broadcast is
# version-gated introspection/telemetry only. The model never emits it and
# the client model never consumes it; conformance pins the id to its
# emission site (check_optimize) and the client's set_notify consumption.
MASTER_EMITS = {
    "kM2CWelcome", "kM2CSessionResumeAck", "kM2CPeersPendingReply",
    "kM2CP2PConnInfo", "kM2CP2PEstablishedResp", "kM2CTopologyDeferred",
    "kM2CCollectiveCommence", "kM2CCollectiveAbort", "kM2CCollectiveDone",
    "kM2CSharedStateSyncResp", "kM2CSharedStateDone",
    "kM2COptimizeResponse", "kM2COptimizeComplete", "kM2CKicked",
    "kM2CIncidentDump", "kM2CSeederUpdate", "kM2CScheduleUpdate",
}

# kM2C ids the client session FSM consumes (client.cpp recv_match sites)
CLIENT_CONSUMES = set(MASTER_EMITS)

# kC2M ids the client session FSM sends
CLIENT_SENDS = set(MASTER_DISPATCH)


# --------------------------------------------------------------------------
# Master model (mirrors master_state.cpp; uuid == client name — the model
# never reuses a name across different logical peers)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class MClient:
    uuid: str
    group: int = 0
    accepted: bool = False
    vote_topology: bool = False
    admission_vote: bool = False  # granted at admission; never declined moot
    reported_establish: bool = False
    establish_ok: bool = False
    establish_failed: "tuple[str, ...]" = ()
    vote_optimize: bool = False
    optimize_work_done: bool = False
    bw_measured: bool = False          # stands in for the bandwidth matrix
    sync_req: "int | None" = None      # offered revision
    dist_done: bool = False

    def copy(self) -> "MClient":
        return dataclasses.replace(self)


@dataclasses.dataclass
class MOp:
    commenced: bool = False
    seq: int = 0
    abort_broadcast: bool = False
    any_aborted: bool = False
    members: "frozenset[str]" = frozenset()
    initiated: "frozenset[str]" = frozenset()
    completed: "frozenset[str]" = frozenset()

    def copy(self) -> "MOp":
        return dataclasses.replace(self)


@dataclasses.dataclass
class MGroup:
    revision_initialized: bool = False
    last_revision: int = 0
    sync_in_flight: bool = False
    sync_revision: int = 0
    # highest tag that ever commenced: the model's stand-in for the
    # app-level step coordination (training loops derive the op tag from
    # the shared-state step a joiner adopts at sync) — a freshly admitted
    # member starts at the group's progress, not at tag 1
    tag_hwm: int = 0
    ops: "dict[int, MOp]" = dataclasses.field(default_factory=dict)

    def copy(self) -> "MGroup":
        g = dataclasses.replace(self)
        g.ops = {t: op.copy() for t, op in self.ops.items()}
        return g


@dataclasses.dataclass
class Journal:
    """Durable subset, appended at the same points as journal.cpp. Records
    per key are kept as histories so the `lag` restart variant can replay
    all but the final group append (the crash-between-Done-and-append
    window the resume ack's trust-the-client rule exists for)."""
    clients: "dict[str, tuple[int, bool]]" = dataclasses.field(
        default_factory=dict)  # uuid -> (group, accepted)
    group_hist: "dict[int, list[tuple[int, bool]]]" = dataclasses.field(
        default_factory=dict)  # gid -> [(last_revision, initialized)...]
    # app-level step progress (rides the shared-state revision in reality;
    # colocated with the seq-bound journaling in the model)
    tag_hwm: "dict[int, int]" = dataclasses.field(default_factory=dict)
    # write-ahead completed-collective verdicts (journal.cpp kOpDone):
    # (gid, tag) -> (seq, any_aborted, members still owed the replay)
    op_done: "dict[tuple[int, int], tuple[int, bool, frozenset[str]]]" = \
        dataclasses.field(default_factory=dict)
    topology_revision: int = 0
    seq_bound: int = 0
    epoch: int = 1

    def copy(self) -> "Journal":
        j = dataclasses.replace(self)
        j.clients = dict(self.clients)
        j.group_hist = {g: list(h) for g, h in self.group_hist.items()}
        j.tag_hwm = dict(self.tag_hwm)
        j.op_done = dict(self.op_done)
        return j

    def record_group(self, gid: int, rev: int, init: bool) -> None:
        self.group_hist.setdefault(gid, []).append((rev, init))

    def restored_group(self, gid: int, lag: bool) -> "tuple[int, bool]":
        h = self.group_hist.get(gid, [])
        if lag and h:
            h = h[:-1]
        return h[-1] if h else (0, False)


Packet = tuple[str, str, dict]  # (dst client, ptype, payload)


class MasterModel:
    """The consensus machine. Mutates self; returns packets to deliver.
    Tests subclass this and break one rule to prove the checker can fail
    (drift-injection, PR-4 style)."""

    def __init__(self, journal: "Journal | None"):
        self.epoch = 1
        self.topology_revision = 0
        self.next_seq = 1
        self.seq_bound = 0
        self.clients: "dict[str, MClient]" = {}
        self.groups: "dict[int, MGroup]" = {}
        self.limbo: "dict[str, tuple[int, bool]]" = {}  # uuid->(group,accepted)
        self.establish_in_flight = False
        self.optimize_in_flight = False
        self.round_members: "frozenset[str]" = frozenset()
        self.journal = journal
        self.pending_closes: "set[str]" = set()
        # verdicts owed from the previous incarnation (journal op_done)
        self.replay_ops: "dict[tuple[int, int], tuple[int, bool, frozenset[str]]]" = {}

    def copy(self) -> "MasterModel":
        m = self.__class__.__new__(self.__class__)
        m.epoch = self.epoch
        m.topology_revision = self.topology_revision
        m.next_seq = self.next_seq
        m.seq_bound = self.seq_bound
        m.clients = {k: v.copy() for k, v in self.clients.items()}
        m.groups = {k: v.copy() for k, v in self.groups.items()}
        m.limbo = dict(self.limbo)
        m.establish_in_flight = self.establish_in_flight
        m.optimize_in_flight = self.optimize_in_flight
        m.round_members = self.round_members
        m.journal = self.journal.copy() if self.journal else None
        m.pending_closes = set(self.pending_closes)
        m.replay_ops = dict(self.replay_ops)
        return m

    def freeze(self) -> "tuple[Any, ...]":
        return (
            self.epoch, self.topology_revision, self.next_seq,
            self.seq_bound,
            tuple(sorted((k, dataclasses.astuple(v))
                         for k, v in self.clients.items())),
            tuple(sorted(
                (g, (v.revision_initialized, v.last_revision,
                     v.sync_in_flight, v.sync_revision, v.tag_hwm,
                     tuple(sorted((t, dataclasses.astuple(op))
                                  for t, op in v.ops.items()))))
                for g, v in self.groups.items())),
            tuple(sorted(self.limbo.items())),
            self.establish_in_flight, self.optimize_in_flight,
            self.round_members,
            tuple(sorted(self.pending_closes)),
            tuple(sorted(self.replay_ops.items())),
            (tuple(sorted(self.journal.clients.items())),
             tuple(sorted((g, tuple(h))
                          for g, h in self.journal.group_hist.items())),
             tuple(sorted(self.journal.tag_hwm.items())),
             tuple(sorted(self.journal.op_done.items())),
             self.journal.topology_revision, self.journal.seq_bound,
             self.journal.epoch) if self.journal else None,
        )

    # ---- helpers mirrored from master_state.cpp ----

    def group_members(self, gid: int) -> "list[MClient]":
        return [c for c in self.clients.values()
                if c.accepted and c.group == gid]

    def accepted_clients(self) -> "list[MClient]":
        return [c for c in self.clients.values() if c.accepted]

    def group_frozen(self, gid: int) -> bool:
        return any(g == gid for (g, _a) in self.limbo.values())

    def journal_client(self, c: MClient) -> None:
        if self.journal is not None:
            self.journal.clients[c.uuid] = (c.group, c.accepted)

    def kick(self, out: "list[Packet]", c: MClient, reason: str) -> None:
        out.append((c.uuid, "kM2CKicked", {"reason": reason}))
        self.pending_closes.add(c.uuid)

    # ---- event handlers (names == MasterState methods) ----

    def on_hello(self, uuid: str, group: int) -> "list[Packet]":
        out: "list[Packet]" = []
        self.clients[uuid] = MClient(uuid=uuid, group=group)
        out.append((uuid, "kM2CWelcome",
                    {"ok": 1, "uuid": uuid, "epoch": self.epoch}))
        self.check_topology(out)
        return out

    def on_session_resume(self, uuid: str, last_revision: int
                          ) -> "list[Packet]":
        out: "list[Packet]" = []
        if uuid not in self.limbo:
            out.append((uuid, "kM2CSessionResumeAck",
                        {"ok": 0, "epoch": self.epoch}))
            return out
        group, accepted = self.limbo.pop(uuid)
        c = MClient(uuid=uuid, group=group, accepted=accepted)
        g = self.groups.setdefault(group, MGroup())
        if last_revision > g.last_revision:
            # the client witnessed a Done the journal missed: trust it
            g.last_revision = last_revision
            g.revision_initialized = True
            if self.journal is not None:
                self.journal.record_group(group, g.last_revision, True)
        self.clients[uuid] = c
        self.journal_client(c)
        out.append((uuid, "kM2CSessionResumeAck",
                    {"ok": 1, "epoch": self.epoch,
                     "last_revision": g.last_revision}))
        if not self.limbo:
            self.recheck_all(out)
        return out

    def on_limbo_expiry(self, uuid: str) -> "list[Packet]":
        out: "list[Packet]" = []
        group, _accepted = self.limbo.pop(uuid)
        if self.journal is not None:
            self.journal.clients.pop(uuid, None)
        self.remove_client(out, uuid, group)
        return out

    def on_topology_update(self, uuid: str) -> "list[Packet]":
        out: "list[Packet]" = []
        c = self.clients.get(uuid)
        if c is None:
            return out
        if c.accepted and self.group_mid_round(c):
            out.append((uuid, "kM2CTopologyDeferred", {}))
            return out
        c.vote_topology = True
        self.check_topology(out)
        return out

    def group_mid_round(self, c: MClient) -> bool:
        g = self.groups.get(c.group)
        if g is None:
            return False
        for op in g.ops.values():
            if not op.commenced and op.initiated and c.uuid not in op.initiated:
                return True
        if not g.sync_in_flight and c.sync_req is None:
            for m in self.group_members(c.group):
                if m.uuid != c.uuid and m.sync_req is not None:
                    return True
        return False

    def defer_topology_voters(self, out: "list[Packet]", gid: int) -> None:
        for m in self.group_members(gid):
            if m.vote_topology:
                m.vote_topology = False
                out.append((m.uuid, "kM2CTopologyDeferred", {}))

    def on_peers_pending_query(self, uuid: str) -> "list[Packet]":
        pending = any(not c.accepted for c in self.clients.values())
        return [(uuid, "kM2CPeersPendingReply", {"pending": int(pending)})]

    def check_topology(self, out: "list[Packet]") -> None:
        if self.establish_in_flight or self.optimize_in_flight:
            return
        if self.limbo:
            return  # HA freeze
        acc = self.accepted_clients()
        any_pending = len(self.clients) > len(acc)
        if not acc and not any_pending:
            return
        if any(not a.vote_topology for a in acc):
            return
        for c in self.clients.values():
            if not c.accepted:
                c.accepted = True
                # an admitted joiner is by definition parked in its
                # establish loop: grant it a STANDING vote so a round that
                # fails (member crash, unreachable kick) re-opens for it
                # instead of stranding it admitted-but-unconfirmed with no
                # voter left (model-checker finding, scenario
                # collective_crash; fixed in master_state.cpp in the same
                # PR that added this spec)
                c.vote_topology = True
                c.admission_vote = True
                self.journal_client(c)
        self.topology_revision += 1
        if self.journal is not None:
            self.journal.topology_revision = self.topology_revision
        self.establish_in_flight = True
        self.round_members = frozenset(self.clients)
        for c in self.clients.values():
            c.reported_establish = False
            c.establish_ok = False
            c.establish_failed = ()
        for c in self.clients.values():
            out.append((c.uuid, "kM2CP2PConnInfo",
                        {"revision": self.topology_revision}))

    def on_p2p_established(self, uuid: str, revision: int, ok: bool,
                           failed: "tuple[str, ...]" = ()) -> "list[Packet]":
        out: "list[Packet]" = []
        c = self.clients.get(uuid)
        if c is None:
            return out
        if revision != self.topology_revision:
            return out  # stale-round report
        c.reported_establish = True
        c.establish_ok = ok
        c.establish_failed = failed
        self.check_establish(out)
        return out

    def check_establish(self, out: "list[Packet]") -> None:
        if not self.establish_in_flight:
            return
        if any(c.accepted and not c.reported_establish
               for c in self.clients.values()):
            return
        present = sum(1 for c in self.clients.values()
                      if c.uuid in self.round_members)
        membership_stable = present == len(self.round_members)
        unreachable: "set[str]" = set()
        all_ok = True
        for c in self.clients.values():
            if not c.accepted:
                continue
            if not c.establish_ok:
                all_ok = False
            unreachable.update(c.establish_failed)
        self.establish_in_flight = False
        if all_ok and membership_stable and not unreachable:
            for c in self.clients.values():
                if not c.accepted:
                    continue
                c.vote_topology = False
                c.admission_vote = False
                c.reported_establish = False
                out.append((c.uuid, "kM2CP2PEstablishedResp",
                            {"revision": self.topology_revision, "ok": 1}))
        else:
            to_kick = [c for c in self.clients.values()
                       if c.uuid in unreachable]
            for c in to_kick:
                self.kick(out, c, "unreachable by peers")
            for c in self.clients.values():
                if not c.accepted or c.uuid in unreachable:
                    continue
                c.reported_establish = False
                out.append((c.uuid, "kM2CP2PEstablishedResp",
                            {"revision": self.topology_revision, "ok": 0}))
            self.check_topology(out)  # votes still standing

    def on_collective_init(self, uuid: str, tag: int,
                           retry: bool = False, retry_seq: int = 0
                           ) -> "list[Packet]":
        out: "list[Packet]" = []
        c = self.clients.get(uuid)
        if c is None or not c.accepted:
            return out
        # Verdict replay: the op completed under the previous incarnation
        # and this member's Done was lost in the crash (see the journaled
        # OpDoneRec in journal.cpp / master_state.cpp). Gated on the
        # client's retry flag AND the seq its dead attempt observed at
        # commence: tags are app-reused across steps, so neither the tag
        # nor the bare flag identifies the op incarnation. Any OTHER init
        # from an owed member proves it is past the recorded op — consume
        # its owed entry so the stale-verdict window closes.
        rec = self.replay_ops.get((c.group, tag))
        if rec is not None and uuid in rec[2] and \
                not (retry and retry_seq == rec[0]):
            members = rec[2] - {uuid}
            if members:
                self.replay_ops[(c.group, tag)] = (rec[0], rec[1], members)
            else:
                del self.replay_ops[(c.group, tag)]
            if self.journal is not None:
                jrec = self.journal.op_done.get((c.group, tag))
                if jrec is not None:
                    jm = jrec[2] - {uuid}
                    if jm:
                        self.journal.op_done[(c.group, tag)] = \
                            (jrec[0], jrec[1], jm)
                    else:
                        del self.journal.op_done[(c.group, tag)]
            rec = None
        if retry and rec is not None and uuid in rec[2] and retry_seq == rec[0]:
            # deliberately NOT consumed here (mirrors master_state.cpp):
            # consuming before the packets land would strand the member on
            # a crash in between; replaying twice is harmless, and the
            # member's next NON-matching init consumes the entry above
            out.append((uuid, "kM2CCollectiveAbort",
                        {"tag": tag, "aborted": int(rec[1]),
                         "world": len(rec[2])}))
            out.append((uuid, "kM2CCollectiveDone", {"tag": tag}))
            return out
        g = self.groups.setdefault(c.group, MGroup())
        op = g.ops.setdefault(tag, MOp())
        op.initiated = op.initiated | {uuid}
        self.check_collective(out, c.group, tag)
        op = g.ops.get(tag)
        if op is not None and not op.commenced:
            self.defer_topology_voters(out, c.group)
        return out

    def check_collective(self, out: "list[Packet]", gid: int, tag: int
                         ) -> None:
        g = self.groups.get(gid)
        if g is None or tag not in g.ops:
            return
        op = g.ops[tag]
        members = self.group_members(gid)
        if not op.commenced:
            if self.group_frozen(gid):
                return  # HA freeze
            if any(m.uuid not in op.initiated for m in members):
                return
            op.commenced = True
            g.tag_hwm = max(g.tag_hwm, tag)
            if self.journal is not None:
                self.journal.tag_hwm[gid] = g.tag_hwm
            op.seq = self.next_seq
            self.next_seq += 1
            if self.journal is not None and self.next_seq > self.seq_bound:
                self.seq_bound = self.next_seq + 1024
                self.journal.seq_bound = self.seq_bound
            op.members = frozenset(m.uuid for m in members)
            for m in members:
                # `world` is not on the wire — the client derives it from
                # its adopted ring; the model ships it here for convenience
                out.append((m.uuid, "kM2CCollectiveCommence",
                            {"tag": tag, "seq": op.seq,
                             "world": len(op.members)}))
            return
        for u in op.members:
            if u in self.clients and u not in op.completed:
                return
        # write-ahead completion record BEFORE the verdict/Done packets
        # (journal.cpp kOpDone): a straggler's lost Done is replayable
        if self.journal is not None:
            self.journal.op_done[(gid, tag)] = (op.seq, op.any_aborted,
                                                op.members)
        for u in op.members:
            if u not in self.clients:
                continue
            if not op.abort_broadcast:
                out.append((u, "kM2CCollectiveAbort",
                            {"tag": tag, "aborted": int(op.any_aborted)}))
            out.append((u, "kM2CCollectiveDone", {"tag": tag}))
        del g.ops[tag]

    def on_collective_complete(self, uuid: str, tag: int, aborted: bool
                               ) -> "list[Packet]":
        out: "list[Packet]" = []
        c = self.clients.get(uuid)
        if c is None:
            return out
        g = self.groups.setdefault(c.group, MGroup())
        op = g.ops.get(tag)
        if op is None:
            return out
        op.completed = op.completed | {uuid}
        if aborted:
            op.any_aborted = True
            if op.commenced and not op.abort_broadcast:
                op.abort_broadcast = True
                for u in op.members:
                    if u in self.clients:
                        out.append((u, "kM2CCollectiveAbort",
                                    {"tag": tag, "aborted": 1}))
        self.check_collective(out, c.group, tag)
        return out

    def abort_group_collectives(self, out: "list[Packet]", gid: int) -> None:
        g = self.groups.get(gid)
        if g is None:
            return
        for tag, op in g.ops.items():
            if not op.commenced or op.abort_broadcast:
                continue
            op.abort_broadcast = True
            op.any_aborted = True
            for u in op.members:
                if u in self.clients:
                    out.append((u, "kM2CCollectiveAbort",
                                {"tag": tag, "aborted": 1}))

    def on_shared_state_sync(self, uuid: str, revision: int
                             ) -> "list[Packet]":
        out: "list[Packet]" = []
        c = self.clients.get(uuid)
        if c is None or not c.accepted:
            return out
        g = self.groups.setdefault(c.group, MGroup())
        if g.revision_initialized and revision > g.last_revision + 1:
            self.kick(out, c, "shared-state revision increment violation")
            return out
        c.sync_req = revision
        c.dist_done = False
        self.check_shared_state(out, c.group)
        if not self.groups[c.group].sync_in_flight:
            self.defer_topology_voters(out, c.group)
        return out

    def check_shared_state(self, out: "list[Packet]", gid: int) -> None:
        g = self.groups.setdefault(gid, MGroup())
        if g.sync_in_flight:
            return
        if self.group_frozen(gid):
            return  # HA freeze
        members = self.group_members(gid)
        if not members:
            return
        if any(m.sync_req is None for m in members):
            return
        # all modeled clients are tx-capable enforce-popular with identical
        # content: election reduces to the expected-revision rule
        expected = (g.last_revision + 1 if g.revision_initialized
                    else max(m.sync_req for m in members
                             if m.sync_req is not None))
        matched = [m for m in members if m.sync_req == expected]
        if not matched:
            for m in members:
                out.append((m.uuid, "kM2CSharedStateSyncResp",
                            {"failed": 1, "revision": expected}))
                m.sync_req = None
                m.dist_done = False
            return
        for m in members:
            out.append((m.uuid, "kM2CSharedStateSyncResp",
                        {"failed": 0, "revision": expected}))
        g.sync_in_flight = True
        g.sync_revision = expected

    def on_dist_done(self, uuid: str) -> "list[Packet]":
        out: "list[Packet]" = []
        c = self.clients.get(uuid)
        if c is None:
            return out
        c.dist_done = True
        members = self.group_members(c.group)
        if any(m.sync_req is not None and not m.dist_done for m in members):
            return out
        g = self.groups.setdefault(c.group, MGroup())
        for m in members:
            out.append((m.uuid, "kM2CSharedStateDone",
                        {"revision": g.sync_revision}))
            m.sync_req = None
            m.dist_done = False
        g.last_revision = g.sync_revision
        g.revision_initialized = True
        g.sync_in_flight = False
        if self.journal is not None:
            self.journal.record_group(c.group, g.last_revision, True)
        return out

    def on_optimize(self, uuid: str) -> "list[Packet]":
        out: "list[Packet]" = []
        c = self.clients.get(uuid)
        if c is None or not c.accepted:
            return out
        c.vote_optimize = True
        self.check_optimize(out)
        return out

    def check_optimize(self, out: "list[Packet]") -> None:
        if self.limbo:
            return  # HA freeze (optimize rounds are global)
        acc = self.accepted_clients()
        if not acc:
            # world emptied mid-round: clear the latch, or check_topology
            # stays blocked forever and no client can ever join again —
            # and re-open the admission round for joiners turned away
            # while the latch held (model-checker finding, scenario
            # optimize_crash; fixed in master_state.cpp in the same PR)
            self.optimize_in_flight = False
            self.check_topology(out)
            return
        if not self.optimize_in_flight:
            if any(not a.vote_optimize for a in acc):
                return
            self.optimize_in_flight = True
        else:
            if any(not a.optimize_work_done for a in acc):
                return
        if any(not a.bw_measured for a in acc):
            for a in acc:
                a.optimize_work_done = False
                out.append((a.uuid, "kM2COptimizeResponse", {"complete": 0}))
            return
        for a in acc:
            a.vote_optimize = False
            a.optimize_work_done = False
            out.append((a.uuid, "kM2COptimizeComplete", {"ok": 1}))
        self.optimize_in_flight = False

    def on_bandwidth_report(self, uuid: str) -> "list[Packet]":
        c = self.clients.get(uuid)
        if c is not None:
            c.bw_measured = True
        return []

    def on_optimize_work_done(self, uuid: str) -> "list[Packet]":
        out: "list[Packet]" = []
        c = self.clients.get(uuid)
        if c is None:
            return out
        c.optimize_work_done = True
        self.check_optimize(out)
        return out

    def on_telemetry_digest(self, uuid: str) -> "list[Packet]":
        # fire-and-forget observability input: folds into the fleet health
        # model (soft state, no replies, no consensus interaction) — by
        # construction it cannot change any control-flow the checker
        # explores, so the model consumes it as a no-op.
        #
        # Straggler-immune data plane (docs/05): the digest now also
        # carries per-edge watchdog verdicts (wd_state), and a CONFIRMED
        # edge may fire the PCCLT_STRAGGLER_REOPT background moonshot.
        # That stays OUT of the model on purpose: the re-opt only spawns
        # an async ATSP improvement whose adoption rides the ALREADY
        # MODELED optimize round (check_optimize); it emits no packets,
        # holds no votes, and cannot park a client — the watchdog/relay
        # ladder itself lives entirely in the data plane (reduce.cpp /
        # sockets.cpp), below the control-plane state machine this spec
        # mirrors. on_disconnect/remove_client invariants are unaffected:
        # relay frames ride existing p2p conns and die with them.
        return []

    def on_sync_key_done(self, uuid: str) -> "list[Packet]":
        # chunk-plane seeder promotion (docs/04): fire-and-forget routing
        # advice WITHIN one sync round. The real handler only inserts into
        # the round's promotion dedupe set and broadcasts the (equally
        # fire-and-forget) kM2CSeederUpdate; no vote, no reply, no
        # revision/ring/membership state changes, and the dist-done
        # barrier the model DOES explore is untouched — so the model
        # consumes it as a no-op, like the telemetry digest above. A
        # promoted seeder dying mid-round is also out of scope here: the
        # fetch engine re-sources from remaining seeders in the data
        # plane, and the member's disconnect rides the already-modeled
        # on_disconnect path (dist-done barrier completion included).
        return []

    def on_disconnect(self, uuid: str) -> "list[Packet]":
        out: "list[Packet]" = []
        c = self.clients.pop(uuid, None)
        self.pending_closes.discard(uuid)
        if c is None:
            return out
        if self.journal is not None:
            self.journal.clients.pop(uuid, None)
        self.remove_client(out, uuid, c.group)
        return out

    def remove_client(self, out: "list[Packet]", uuid: str, gid: int
                      ) -> None:
        self.abort_group_collectives(out, gid)
        g = self.groups.get(gid)
        if g is not None:
            for op in g.ops.values():
                op.initiated = op.initiated - {uuid}
                op.completed = op.completed - {uuid}
            # an op whose every initiator departed before commence has no
            # observable state (no packets went out): drop the record
            # instead of leaking it in the op table until the group empties
            for tag in [t for t, op in g.ops.items()
                        if not op.commenced and not op.initiated]:
                del g.ops[tag]
            if not self.group_members(gid) and not self.group_frozen(gid):
                self.groups[gid] = MGroup()
                if self.journal is not None:
                    self.journal.record_group(gid, 0, False)
        self.recheck_all(out)
        # Moot-vote decline: if the departed client leaves NO pending
        # joiner and no round started, every standing topology vote now
        # waits for a round that can never form (the app only votes while
        # peers are pending, so the non-voters never will). Decline the
        # votes like the mid-round tie-break does — the parked voters
        # return no-op and re-vote when peers are pending again.
        # (Model-checker finding, scenario collective_crash: the pending
        # joiner crashes and the lone voter parks forever.)
        if not self.establish_in_flight and \
                all(c.accepted for c in self.clients.values()):
            for c in self.clients.values():
                if c.accepted and c.vote_topology and not c.admission_vote:
                    c.vote_topology = False
                    out.append((c.uuid, "kM2CTopologyDeferred", {}))

    def recheck_all(self, out: "list[Packet]") -> None:
        self.check_establish(out)
        self.check_topology(out)
        for gid, g in list(self.groups.items()):
            for tag in list(g.ops):
                self.check_collective(out, gid, tag)
        for gid in list(self.groups):
            self.check_shared_state(out, gid)
            members = self.group_members(gid)
            if members and self.groups[gid].sync_in_flight:
                if all(m.sync_req is None or m.dist_done for m in members):
                    out.extend(self.on_dist_done(members[0].uuid))
        self.check_optimize(out)

    # ---- restart (SIGKILL + rehydrate; the env action) ----

    @classmethod
    def restart(cls, journal: Journal, lag: bool = False) -> "MasterModel":
        """A new incarnation rehydrated from the journal. `lag` drops the
        final group append (crash between emitting Done and the append
        reaching disk)."""
        j = journal.copy()
        j.epoch += 1
        m = cls(j)
        m.epoch = j.epoch
        m.topology_revision = j.topology_revision
        m.next_seq = max(1, j.seq_bound)
        m.seq_bound = m.next_seq
        for uuid, (group, accepted) in j.clients.items():
            m.limbo[uuid] = (group, accepted)
        for gid in j.group_hist:
            rev, init = j.restored_group(gid, lag)
            g = m.groups.setdefault(gid, MGroup())
            g.last_revision = rev
            g.revision_initialized = init
        for gid, hwm in j.tag_hwm.items():
            m.groups.setdefault(gid, MGroup()).tag_hwm = hwm
        # verdicts owed to journaled members (journal replay prunes
        # departed members; the real journal also caps records per group,
        # sound because per-connection Dones are delivered in order)
        m.replay_ops = {
            key: (seq, aborted,
                  frozenset(u for u in members if u in j.clients))
            for key, (seq, aborted, members) in j.op_done.items()
            if any(u in j.clients for u in members)}
        return m


# --------------------------------------------------------------------------
# Client session FSM (mirrors client.cpp's protocol loop)
# --------------------------------------------------------------------------

# phases a terminal (quiescent) state may legitimately contain
QUIESCENT_PHASES = {"active", "done", "left", "kicked", "dead"}


@dataclasses.dataclass
class ClientModel:
    name: str
    group: int = 0
    # steps: collective | sync | optimize | leave. Admission votes are NOT
    # script steps: the app contract (train_ddp's admit-pending loop) is
    # "any active client votes whenever peers are pending", modeled as an
    # always-enabled action so a joiner can never be starved by a script.
    script: "tuple[str, ...]" = ()
    phase: str = "init"                # see step() for the FSM
    inbox: "tuple[tuple[str, tuple], ...]" = ()
    # op state
    cur_tag: int = 0
    cur_world: int = 0                 # world at commence (from the ring)
    abort_seen: int = 0                # abort packets since (re-)init
    last_seq: int = 0                  # monotonicity witness
    last_sync_revision: int = 0
    sync_offered: int = 0              # revision of the in-flight sync round
    epoch: int = 0
    estab_revision: int = 0            # round currently being established
    # mirrors establish_loop's vote_deferrable: only the FIRST wait after
    # a vote may consume kM2CTopologyDeferred; a Deferred landing on any
    # other wait sits unmatched (and the model would report the stall)
    deferrable: bool = False
    local_abort: bool = False          # scenario: this client fails its op
    estab_fail_used: bool = False      # scenario: one-shot establish failure
    # resume bookkeeping: the request to re-issue after a session resume
    resume_phase: str = ""

    def copy(self) -> "ClientModel":
        return dataclasses.replace(self)

    def freeze(self) -> "tuple[Any, ...]":
        return dataclasses.astuple(self)

    # -- inbox helpers (ControlClient matched-receive semantics) --

    def take(self, ptype: str, **match: Any) -> "dict | None":
        """Consume the first queued frame of `ptype` whose payload matches
        the given keys (recv_match with a predicate)."""
        for i, (t, payload) in enumerate(self.inbox):
            p = dict(payload)
            if t == ptype and all(p.get(k) == v for k, v in match.items()):
                self.inbox = self.inbox[:i] + self.inbox[i + 1:]
                return p
        return None

    def first_of(self, ptypes: "tuple[str, ...]", **match: Any
                 ) -> "str | None":
        """Type of the FIRST queued frame among `ptypes` matching the
        payload keys — recv_match_any's FIFO semantics, which is what
        makes an abort-before-commence distinguishable from an abort
        racing in after the commence."""
        for t, payload in self.inbox:
            if t not in ptypes:
                continue
            p = dict(payload)
            if all(p.get(k) == v for k, v in match.items()):
                return t
        return None

    def peek(self, ptype: str, **match: Any) -> bool:
        for t, payload in self.inbox:
            if t != ptype:
                continue
            p = dict(payload)
            if all(p.get(k) == v for k, v in match.items()):
                return True
        return False

    def deliver(self, ptype: str, payload: dict) -> None:
        self.inbox = self.inbox + ((ptype, tuple(sorted(payload.items()))),)
