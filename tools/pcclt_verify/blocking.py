"""Checker ``blocking``: no blocking calls inside critical sections.

Three lint classes over the harvested facts (one libclang parse shared
with ``lockorder``):

  * **blocking-under-lock** — a call that can park the thread on the
    network, the disk, another process, or the clock (``send``/``recv``/
    ``connect``/``poll``, ``fsync``/``fwrite``, ``process_vm_readv``,
    sleeps, futex parks, ``std::call_once``) while holding a lock that is
    not ``io``-tagged. An io lock exists to serialize exactly one fd, so
    blocking under it is its job; blocking under a *state* lock turns one
    slow peer into a process-wide stall (the "few network failures slow
    the entire AllReduce" failure mode, at the lock granularity).
    Transitive: calling a may-block function under a lock counts.
  * **condvar-foreign-wait** — ``CondVar::wait(mu)`` releases only ``mu``;
    any *other* lock stays held for the whole park. That is a stall at
    best and half a deadlock at worst.
  * **fsync-under-hot-lock** — the journal's fsync/fwrite appends are
    singled out with a dedicated message when reached under any master
    hot-path lock, because that is the exact regression the HA subsystem
    must never grow (a world-freezing disk stall).

A deliberate, reviewed exception is annotated at the call site with
``// pcclt-verify: allow-blocking(reason)`` and must carry the reason.
"""

from __future__ import annotations

from pathlib import Path

from . import Finding, Skip
from .harvest import Program, harvest

CHECKER = "blocking"

ALLOW_MARK = "pcclt-verify: allow-blocking("

# journal append path: flagged with a dedicated message under these
HOT_LOCKS = {"master::Master::ev_mu_", "master::Master::conns_mu_",
             "master::MasterState::moon_mu_"}
JOURNAL_PRIMS = {"fsync", "fdatasync", "fwrite", "fflush"}


def _io_ok(prog: Program, ident: str) -> bool:
    """True when blocking under `ident` is sanctioned: io-tagged or
    blocking-ok-tagged by its declaration, or a function-local mutex (it
    serializes at most the enclosing frame's own IO — the throwaway
    send_frame mutex pattern)."""
    d = prog.locks.get(ident)
    if d is None:
        return ident.startswith(("local:", "param:"))
    return d.io or d.blocking_ok or d.local


def may_block(prog: Program) -> "dict[str, tuple[str, str, int] | None]":
    """USR -> witness (primitive, file, line) when the function may block,
    directly or transitively; None otherwise."""
    blk: "dict[str, tuple[str, str, int] | None]" = {}
    for usr, f in prog.funcs.items():
        blk[usr] = ((f.blocking[0].what, f.blocking[0].file,
                     f.blocking[0].line) if f.blocking else None)
    changed = True
    while changed:
        changed = False
        for usr, f in prog.funcs.items():
            if blk[usr] is not None:
                continue
            for cs in f.calls:
                w = blk.get(cs.callee)
                if w is not None:
                    blk[usr] = (f"{cs.callee_name} -> {w[0]}", cs.file,
                                cs.line)
                    changed = True
                    break
    return blk


def parks_holding(prog: Program,
                  blk: "dict[str, tuple[str, str, int] | None]"
                  ) -> "dict[str, frozenset[str]]":
    """USR -> locks actually HELD at some park reachable from the
    function. A callee that REQUIRES a lock but drops it before every
    park (the SinkTable::wait_not_busy_range window) does not hold it at
    the park, so a caller whose only held lock is that REQUIRES'd one is
    not stalled-under-lock: the callee releases it while parked."""
    ph: "dict[str, set[str]]" = {
        usr: set().union(*(set(b.held) for b in f.blocking))
        if f.blocking else set()
        for usr, f in prog.funcs.items()}
    changed = True
    while changed:
        changed = False
        for usr, f in prog.funcs.items():
            cur = ph[usr]
            for cs in f.calls:
                if blk.get(cs.callee) is None:
                    continue  # callee never blocks
                callee = prog.funcs.get(cs.callee)
                sub = ph.get(cs.callee, set())
                # locks the callee REQUIRES and never holds at a park are
                # dropped by the callee before parking
                dropped = (set(callee.requires) - sub) if callee else set()
                add = (set(cs.held) - dropped) | sub
                if not add <= cur:
                    cur |= add
                    changed = True
    return {u: frozenset(s) for u, s in ph.items()}


def _allowed(root: Path, file: str, line: int,
             cache: "dict[str, list[str]]") -> bool:
    if file not in cache:
        try:
            cache[file] = (root / file).read_text(
                errors="replace").splitlines()
        except OSError:
            cache[file] = []
    lines = cache[file]
    for ln in (line, line - 1):
        if 0 < ln <= len(lines) and ALLOW_MARK in lines[ln - 1]:
            return True
    return False


def check(root: Path) -> "list[Finding] | Skip":
    prog = harvest(root)
    if isinstance(prog, str):
        return Skip(CHECKER, f"{prog}; install the libclang wheel to run "
                    "the blocking-under-lock analysis")
    rootp = Path(root).resolve()
    out: "list[Finding]" = []
    src_cache: "dict[str, list[str]]" = {}
    blk = may_block(prog)
    ph = parks_holding(prog, blk)

    def offenders(held: "tuple[str, ...]") -> "list[str]":
        return [h for h in held if not _io_ok(prog, h)]

    for f in prog.funcs.values():
        # direct primitives under a lock
        for b in f.blocking:
            bad = offenders(b.held)
            if not bad or _allowed(rootp, b.file, b.line, src_cache):
                continue
            prim = b.what.rsplit("::", 1)[-1].split(" ")[0]
            if prim in JOURNAL_PRIMS and any(h in HOT_LOCKS for h in bad):
                out.append(Finding(
                    CHECKER, b.file, b.line,
                    f"journal-class disk write ({b.what}) while holding "
                    f"hot-path lock(s) {', '.join(bad)} — a disk stall "
                    "here freezes the whole world; append outside the "
                    "lock or hand off to the journal thread"))
            else:
                out.append(Finding(
                    CHECKER, b.file, b.line,
                    f"{f.name} calls blocking {b.what} while holding "
                    f"{', '.join(bad)} — move the call outside the "
                    "critical section (copy what you need under the lock, "
                    "then block), tag the lock `io` if its whole purpose "
                    "is serializing this fd, or annotate "
                    "`// pcclt-verify: allow-blocking(reason)`"))
        # transitive: call to a may-block function under a lock
        for cs in f.calls:
            bad = offenders(cs.held)
            if not bad:
                continue
            w = blk.get(cs.callee)
            if w is None:
                continue
            callee = prog.funcs.get(cs.callee)
            if callee is not None:
                # drop-window excuse: the callee REQUIRES the lock and
                # releases it before every park it can reach
                dropped = set(callee.requires) - ph.get(cs.callee,
                                                        frozenset())
                bad = [h for h in bad if h not in dropped]
            if not bad:
                continue
            if _allowed(rootp, cs.file, cs.line, src_cache):
                continue
            out.append(Finding(
                CHECKER, cs.file, cs.line,
                f"{f.name} calls {cs.callee_name} while holding "
                f"{', '.join(bad)}, and {cs.callee_name} may block "
                f"({w[0]} at {w[1]}:{w[2]}) — release the lock before the "
                "call or annotate `// pcclt-verify: allow-blocking(reason)`"))
        # CondVar waits holding a second lock
        for cv in f.cv_waits:
            others = [h for h in cv.held if h != cv.mutex]
            if not others or _allowed(rootp, cv.file, cv.line, src_cache):
                continue
            out.append(Finding(
                CHECKER, cv.file, cv.line,
                f"{f.name} waits on a CondVar with {cv.mutex} while ALSO "
                f"holding {', '.join(others)} — the wait releases only its "
                "own mutex; every other lock stays held for the whole "
                "park (stall at best, half a deadlock at worst)"))
    return out
