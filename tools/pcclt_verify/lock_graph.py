"""Checker ``lockorder``: whole-program lock-acquisition graph analysis.

Builds the directed graph "lock A is held while lock B is acquired" from
the harvested facts (direct acquisitions + one level of ``Mutex &``
parameter substitution + transitive acquisitions through the call graph)
and enforces three properties:

  * **no cycles** — a cycle is a potential deadlock: two threads entering
    the cycle from different locks can each hold what the other wants;
  * **declared ranks are respected** — every non-local ``pcclt::Mutex``
    carries a ``// lock-rank: N [io]`` comment on (or directly above) its
    declaration, and every edge goes from a LOWER rank to a HIGHER one, so
    the global order is documented where the lock lives instead of only in
    the heads of people who read the whole call graph;
  * **io locks are leaves** — an ``io``-tagged lock exists to serialize a
    single fd/file; acquiring anything else while holding one turns an IO
    stall into a lock-graph stall.

Same-identity self-edges (two *instances* of one class's mutex held at
once) are reported as their own finding class: ranks cannot order them.
"""

from __future__ import annotations

from pathlib import Path

from . import Finding, Skip
from .harvest import SRC, Program, harvest

CHECKER = "lockorder"


def _is_local(prog: Program, ident: str) -> bool:
    d = prog.locks.get(ident)
    return d.local if d is not None else ident.startswith(
        ("local:", "param:", "<unresolved"))


def transitive_acquires(prog: Program) -> "dict[str, set[str]]":
    """USR -> set of lock identities the function may acquire, directly or
    through calls, with Mutex& parameters substituted per call edge."""
    # A lock the function REQUIRES is excluded from its own acquisition
    # summary: re-acquiring it inside (a drop-and-reacquire window, e.g.
    # SinkTable::wait_not_busy_range) is the caller's already-held lock,
    # not a new acquisition the caller nests under its held-set.
    tacq: "dict[str, set[str]]" = {
        usr: {a.lock for a in f.acquires if a.lock not in f.requires}
        for usr, f in prog.funcs.items()}
    changed = True
    while changed:
        changed = False
        for usr, f in prog.funcs.items():
            cur = tacq[usr]
            for cs in f.calls:
                sub = dict(cs.mutex_args)
                for lock in tacq.get(cs.callee, ()):
                    if lock.startswith("param:"):
                        try:
                            idx = int(lock.split(":", 1)[1])
                        except ValueError:
                            idx = -1
                        lock = sub.get(idx, lock)
                    if lock not in cur:
                        cur.add(lock)
                        changed = True
    return tacq


class Edge:
    __slots__ = ("src", "dst", "file", "line", "via")

    def __init__(self, src: str, dst: str, file: str, line: int, via: str):
        self.src, self.dst = src, dst
        self.file, self.line, self.via = file, line, via


def build_edges(prog: Program) -> "list[Edge]":
    tacq = transitive_acquires(prog)
    edges: "list[Edge]" = []
    seen: "set[tuple[str, str]]" = set()

    def add(src: str, dst: str, file: str, line: int, via: str) -> None:
        if (src, dst) in seen:
            return
        seen.add((src, dst))
        edges.append(Edge(src, dst, file, line, via))

    for f in prog.funcs.values():
        for a in f.acquires:
            for h in a.held:
                add(h, a.lock, a.file, a.line, "direct acquisition")
        for cs in f.calls:
            if not cs.held:
                continue
            sub = dict(cs.mutex_args)
            for lock in tacq.get(cs.callee, ()):
                if lock.startswith("param:"):
                    try:
                        idx = int(lock.split(":", 1)[1])
                    except ValueError:
                        idx = -1
                    lock = sub.get(idx, lock)
                if lock.startswith("param:"):
                    continue  # unresolved caller-of-caller param
                for h in cs.held:
                    add(h, lock, cs.file, cs.line,
                        f"call to {cs.callee_name}")
    return edges


def find_cycles(edges: "list[Edge]") -> "list[list[Edge]]":
    """Minimal cycle witnesses, one per strongly-connected component."""
    adj: "dict[str, list[Edge]]" = {}
    for e in edges:
        adj.setdefault(e.src, []).append(e)

    cycles: "list[list[Edge]]" = []
    claimed: "set[str]" = set()
    for start in sorted(adj):
        if start in claimed:
            continue
        # BFS back to start
        prev: "dict[str, Edge]" = {}
        frontier = [start]
        found = None
        while frontier and found is None:
            nxt = []
            for node in frontier:
                for e in adj.get(node, ()):
                    if e.dst == start:
                        prev[start] = e
                        found = e
                        break
                    if e.dst not in prev:
                        prev[e.dst] = e
                        nxt.append(e.dst)
                if found:
                    break
            frontier = nxt
        if found is None:
            continue
        # reconstruct start -> ... -> start
        path = [prev[start]]
        node = prev[start].src
        while node != start:
            path.append(prev[node])
            node = prev[node].src
        path.reverse()
        cycles.append(path)
        for e in path:
            claimed.add(e.src)
    return cycles


def check(root: Path) -> "list[Finding] | Skip":
    prog = harvest(root)
    if isinstance(prog, str):
        return Skip(CHECKER, f"{prog}; install the libclang wheel to run "
                    "the lock-order analysis")
    out: "list[Finding]" = []
    for err in prog.errors:
        out.append(Finding(CHECKER, SRC, 0, f"TU failed to parse: {err}"))

    # --- every non-local lock declares a rank -------------------------
    for ident, d in sorted(prog.locks.items()):
        if d.local:
            continue
        if d.rank is None and not d.io:
            out.append(Finding(
                CHECKER, d.file, d.line,
                f"{ident} has no `// lock-rank: N [io]` annotation — every "
                "pcclt::Mutex declares its place in the global acquisition "
                "order (docs/11_static_analysis.md)"))

    edges = build_edges(prog)

    # --- self-edges: instance-order hazards ---------------------------
    for e in edges:
        if e.src == e.dst:
            out.append(Finding(
                CHECKER, e.file, e.line,
                f"{e.src} acquired while an instance of the same lock is "
                f"already held ({e.via}) — ranks cannot order two instances "
                "of one lock; impose an instance order (address order) or "
                "restructure"))

    # --- rank monotonicity + io leaves --------------------------------
    def rank_of(ident: str) -> "int | None":
        d = prog.locks.get(ident)
        if d is None:
            return None
        if d.local:
            return None  # locals are unordered leaves
        return d.rank

    for e in edges:
        if e.src == e.dst:
            continue
        src_d = prog.locks.get(e.src)
        if src_d is not None and src_d.io:
            out.append(Finding(
                CHECKER, e.file, e.line,
                f"{e.dst} acquired while holding io-tagged {e.src} "
                f"({e.via}) — io locks serialize one fd and must be leaves "
                "of the lock graph"))
            continue
        if _is_local(prog, e.src) and not _is_local(prog, e.dst):
            # a function-local lock is private to one call frame; ordering
            # a shared lock under it cannot deadlock against another thread
            # (no other thread can hold the local), so locals stay leaves
            # unless they wrap a shared acquisition — which we do flag:
            out.append(Finding(
                CHECKER, e.file, e.line,
                f"{e.dst} acquired while holding function-local {e.src} "
                f"({e.via}) — widen the shared lock's scope instead of "
                "nesting it inside a throwaway mutex"))
            continue
        rs, rd = rank_of(e.src), rank_of(e.dst)
        if rs is None or rd is None:
            continue  # missing-rank finding already emitted above
        if rs >= rd:
            out.append(Finding(
                CHECKER, e.file, e.line,
                f"lock-order inversion: {e.dst} (rank {rd}) acquired while "
                f"holding {e.src} (rank {rs}) via {e.via} — edges must go "
                "strictly rank-upward; re-rank or restructure the critical "
                "section"))

    # --- cycles (independent of ranks: catches unranked cycles too) ---
    for cyc in find_cycles([e for e in edges if e.src != e.dst]):
        desc = " -> ".join(f"{e.src} ({e.file}:{e.line})" for e in cyc)
        first = cyc[0]
        out.append(Finding(
            CHECKER, first.file, first.line,
            f"lock-acquisition cycle (potential deadlock): {desc} -> "
            f"{cyc[-1].dst} — break the cycle by restructuring one of the "
            "critical sections"))
    return out
