"""Checker ``fsm``: explicit-state model checking of the control plane.

Explores EVERY interleaving of the spec machines in ``fsm_spec.py`` —
client protocol steps, disconnects, kicks, master SIGKILL+restart,
session resume, limbo expiry — at world <= 4, against these invariants:

  * **no stuck world**: from every reachable state there is a path to a
    quiescent state (all clients active/done/left/kicked/dead, no round
    in flight). This is strictly stronger than "no deadlocked terminal
    state": it also catches livelocks with no escape path.
  * **exactly-one-abort**: every member of a commenced collective receives
    exactly ONE abort-verdict packet per op incarnation (early broadcast
    or completion verdict — never zero, never two).
  * **seq monotone**: collective seqs observed by a client strictly
    increase, across master restarts included (the journaled seq bound).
  * **revision monotone**: a client's observed shared-state revision never
    decreases, across epochs included (the resume-ack max() rule).
  * **epoch monotone**: the epoch a client observes never decreases.
  * scenario-scoped: no client is kicked in scenarios where every client
    follows the protocol (a kick there means the master punished a
    correct peer — the restart_lag scenario exists exactly for this).

Run as a checker (CI: ``python -m tools.pcclt_verify --checker fsm``) or
directly (``python -m tools.pcclt_verify.model_check [--deep]``) for the
larger worlds.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

from . import Finding, Skip
from .fsm_spec import (QUIESCENT_PHASES, ClientModel, Journal, MasterModel,
                       Packet)

CHECKER = "fsm"

Action = tuple[Any, ...]


class Violation(Exception):
    def __init__(self, message: str, trace: "list[Action] | None" = None):
        super().__init__(message)
        self.message = message
        self.trace = trace or []

    def __str__(self) -> str:
        tail = self.trace[-14:]
        steps = " ; ".join("/".join(str(p) for p in a) for a in tail)
        more = "" if len(self.trace) <= 14 else f" (last 14 of {len(self.trace)} steps) "
        return f"{self.message}{more and ' '}[trace{more}: {steps}]"


@dataclasses.dataclass
class Scenario:
    name: str
    clients: "tuple[tuple[str, int, tuple[str, ...]], ...]"
    journal: bool = False
    max_restarts: int = 0
    lag: bool = False                       # drop the final journal append
    disconnects: "tuple[str, ...]" = ()     # clients that may crash (once)
    local_abort: "tuple[str, ...]" = ()     # clients whose op fails locally
    establish_fail: "tuple[tuple[str, str], ...]" = ()  # (reporter, victim)
    expect_no_kicks: bool = True
    # staged: run a canonical join+establish prologue before exploring, so
    # faults hit a FORMED world. Use for mixed-op scripts: a member that
    # solo-runs part of its script before a peer joins ends up at a
    # different program position, and two parked members cross-waiting on
    # different op TYPES is an app-divergence artifact, not a protocol
    # state (join interleavings stay fully explored in the join scenarios)
    staged: bool = False
    max_states: int = 400_000


@dataclasses.dataclass
class World:
    master: MasterModel
    clients: "dict[str, ClientModel]"
    pending_disconnects: "frozenset[str]"
    restarts_left: int
    scenario: Scenario

    def copy(self) -> "World":
        return World(self.master.copy(),
                     {k: v.copy() for k, v in self.clients.items()},
                     self.pending_disconnects, self.restarts_left,
                     self.scenario)

    def freeze(self) -> "tuple[Any, ...]":
        return (self.master.freeze(),
                tuple(c.freeze() for _, c in sorted(self.clients.items())),
                self.pending_disconnects, self.restarts_left)

    def deliver(self, packets: "list[Packet]") -> None:
        for dst, ptype, payload in packets:
            c = self.clients.get(dst)
            if c is not None and c.phase not in ("left", "dead"):
                if ptype == "kM2CKicked" and self.scenario.expect_no_kicks:
                    raise Violation(
                        f"{dst} kicked ({payload.get('reason')}) in scenario "
                        f"'{self.scenario.name}' where every client follows "
                        "the protocol — the master punished a correct peer")
                c.deliver(ptype, payload)


def initial_world(sc: Scenario, master_cls: type = MasterModel) -> World:
    master = master_cls(Journal() if sc.journal else None)
    clients = {}
    for name, group, script in sc.clients:
        c = ClientModel(name=name, group=group, script=tuple(script))
        c.local_abort = name in sc.local_abort
        clients[name] = c
    w = World(master, clients, frozenset(), sc.max_restarts, sc)
    if sc.staged:
        for name in sorted(w.clients):
            w = apply_action(w, ("client", name, "join"))
        for _ in range(10_000):
            acts = [a for a in enabled_actions(w)
                    if a[0] == "client" and a[2] in
                    ("consume_conninfo", "consume_estab_resp", "vote",
                     "consume_deferred")]
            if not acts:
                break
            w = apply_action(w, acts[0])
        for name, c in w.clients.items():
            if c.phase != "active":
                raise Violation(
                    f"scenario '{sc.name}': staged prologue left {name} in "
                    f"{c.phase} — the canonical join drain did not converge")
    return w


# --------------------------------------------------------------------------
# enabled actions
# --------------------------------------------------------------------------


def enabled_actions(w: World) -> "list[Action]":
    acts: "list[Action]" = []
    sc = w.scenario
    m = w.master
    pending_exists = any(not c.accepted for c in m.clients.values())
    for name, c in sorted(w.clients.items()):
        ph = c.phase
        if ph in ("left", "dead", "kicked"):
            continue
        if c.peek("kM2CKicked"):
            acts.append(("client", name, "consume_kicked"))
            continue  # a queued kick is authoritative (classify_master_loss)
        if ph == "init":
            acts.append(("client", name, "join"))
        elif ph == "wait_conninfo":
            if c.peek("kM2CP2PConnInfo"):
                acts.append(("client", name, "consume_conninfo"))
            if c.deferrable and c.peek("kM2CTopologyDeferred"):
                acts.append(("client", name, "consume_deferred"))
        elif ph == "wait_estab_resp":
            if c.peek("kM2CP2PEstablishedResp", revision=c.estab_revision):
                acts.append(("client", name, "consume_estab_resp"))
        elif ph == "active":
            # the app contract: any active member votes while peers are
            # pending (train_ddp's admit-pending loop) — implicit action,
            # not a script step, so a joiner can never be script-starved
            if pending_exists:
                acts.append(("client", name, "vote"))
            if c.script:
                step = c.script[0]
                if step == "collective":
                    acts.append(("client", name, "start_collective"))
                elif step == "sync":
                    acts.append(("client", name, "start_sync"))
                elif step == "optimize":
                    acts.append(("client", name, "start_optimize"))
                elif step == "leave":
                    acts.append(("client", name, "leave"))
            # app contract: members of a group all run the same step
            # sequence, so a member whose script is ahead/exhausted still
            # answers a group op/sync round its peers have opened
            mc = m.clients.get(name)
            g = m.groups.get(c.group)
            if mc is not None and mc.accepted and g is not None:
                if not c.script or c.script[0] != "collective":
                    # guarded to not-yet-commenced ops: a late joiner only
                    # participates in future ops (step adoption via sync)
                    for tag, op in sorted(g.ops.items()):
                        if (not op.commenced and op.initiated
                                and name not in op.initiated):
                            acts.append(("client", name, "follow", tag))
                if ((not c.script or c.script[0] != "sync")
                        and not g.sync_in_flight and mc.sync_req is None
                        and any(o.sync_req is not None
                                for o in m.group_members(c.group))):
                    acts.append(("client", name, "follow_sync"))
            if (mc is not None and mc.accepted and not mc.vote_optimize
                    and (not c.script or c.script[0] != "optimize")
                    and not m.optimize_in_flight
                    and any(o.vote_optimize
                            for o in m.accepted_clients())):
                # optimize votes are GLOBAL: every accepted client must
                # join the round, whatever group it is in
                acts.append(("client", name, "follow_optimize"))
        elif ph == "wait_commence":
            first = c.first_of(("kM2CCollectiveCommence",
                                "kM2CCollectiveAbort"), tag=c.cur_tag)
            if first == "kM2CCollectiveCommence":
                acts.append(("client", name, "consume_commence"))
            elif first == "kM2CCollectiveAbort":
                # abort BEFORE any commence: a restarted master replaying
                # the completed op's verdict (client.cpp's any-match wait)
                acts.append(("client", name, "consume_replay"))
        elif ph == "in_ring":
            acts.append(("client", name, "finish_ring"))
        elif ph == "wait_coll_done":
            if c.peek("kM2CCollectiveDone"):
                acts.append(("client", name, "consume_coll_done"))
        elif ph == "wait_sync_resp":
            if c.peek("kM2CSharedStateSyncResp"):
                acts.append(("client", name, "consume_sync_resp"))
        elif ph == "wait_sync_done":
            if c.peek("kM2CSharedStateDone"):
                acts.append(("client", name, "consume_sync_done"))
        elif ph == "wait_opt":
            if c.peek("kM2COptimizeResponse"):
                acts.append(("client", name, "consume_opt_resp"))
            if c.peek("kM2COptimizeComplete"):
                acts.append(("client", name, "consume_opt_complete"))
        elif ph == "resuming":
            acts.append(("client", name, "resume"))
        # scenario fault: crash at any point while connected
        if name in sc.disconnects and ph not in ("init",):
            acts.append(("env", "crash", name))
    for name in sorted(w.pending_disconnects | w.master.pending_closes):
        acts.append(("env", "deliver_disconnect", name))
    if w.restarts_left > 0 and w.master.journal is not None:
        acts.append(("env", "restart"))
    for uuid in sorted(w.master.limbo):
        acts.append(("env", "limbo_expiry", uuid))
    return acts


# --------------------------------------------------------------------------
# action application (returns the successor world)
# --------------------------------------------------------------------------


def apply_action(w0: World, act: Action) -> World:
    w = w0.copy()
    sc = w.scenario
    kind = act[0]
    if kind == "env":
        if act[1] == "crash":
            name = act[2]
            c = w.clients[name]
            c.phase = "left"
            c.inbox = ()
            # a crashed client never comes back; drop its fault budget
            w.scenario = sc  # budgets are encoded by phase, nothing to do
            w.pending_disconnects = w.pending_disconnects | {name}
        elif act[1] == "deliver_disconnect":
            name = act[2]
            w.pending_disconnects = w.pending_disconnects - {name}
            w.deliver(w.master.on_disconnect(name))
        elif act[1] == "restart":
            w.restarts_left -= 1
            assert w.master.journal is not None
            old_epoch = w.master.epoch
            w.master = type(w.master).restart(w.master.journal, lag=sc.lag)
            if w.master.epoch <= old_epoch:
                raise Violation("epoch did not advance across restart")
            w.pending_disconnects = frozenset()
            for c in w.clients.values():
                if c.phase in ("init", "left", "dead", "kicked"):
                    continue
                c.inbox = ()  # in-flight packets died with the master
                c.resume_phase = c.phase if c.phase != "resuming" else c.resume_phase
                c.phase = "resuming"
        elif act[1] == "limbo_expiry":
            w.deliver(w.master.on_limbo_expiry(act[2]))
        return w

    name, step = act[1], act[2]
    c = w.clients[name]
    m = w.master

    def est_report(revision: int) -> None:
        failed: "tuple[str, ...]" = ()
        for reporter, victim in sc.establish_fail:
            if reporter == name and victim in m.clients \
                    and not c.estab_fail_used:
                failed = (victim,)
                c.estab_fail_used = True
        c.estab_revision = revision
        c.phase = "wait_estab_resp"
        w.deliver(m.on_p2p_established(name, revision, not failed, failed))

    if step == "join":
        w.deliver(m.on_hello(name, c.group))
        welcome = c.take("kM2CWelcome")
        if welcome is None or not welcome.get("ok"):
            c.phase = "dead"
            return w
        if welcome["epoch"] < c.epoch:
            raise Violation(f"{name} observed epoch moving backwards")
        c.epoch = welcome["epoch"]
        c.phase = "wait_conninfo"
    elif step == "consume_kicked":
        c.take("kM2CKicked")
        c.phase = "kicked"
        c.inbox = ()
    elif step == "consume_conninfo":
        info = c.take("kM2CP2PConnInfo")
        assert info is not None
        while True:  # stale rounds queue older conn infos; use the newest
            newer = c.take("kM2CP2PConnInfo")
            if newer is None:
                break
            info = newer
        c.deferrable = False  # only the first wait honors a Deferred
        est_report(info["revision"])
    elif step == "consume_deferred":
        c.take("kM2CTopologyDeferred")
        c.deferrable = False
        c.phase = "active"  # vote declined: no-op success, app re-votes later
    elif step == "consume_estab_resp":
        resp = c.take("kM2CP2PEstablishedResp", revision=c.estab_revision)
        assert resp is not None
        if resp["ok"]:
            c.phase = "active"
            # step adoption: a member entering the group starts at the
            # group's op progress, not at tag 1 (in reality the joiner's
            # first shared-state sync adopts the cohort's step, and the
            # training loop derives op tags from it)
            g = m.groups.get(c.group)
            if g is not None:
                c.cur_tag = max(c.cur_tag, g.tag_hwm)
        else:
            c.phase = "wait_conninfo"  # failed round: wait for the retry
    elif step == "vote":
        w.deliver(m.on_topology_update(name))
        if c.take("kM2CTopologyDeferred") is not None:
            pass  # declined mid-round: no-op, app re-votes later
        else:
            c.phase = "wait_conninfo"
            c.deferrable = True
    elif step == "start_collective":
        c.cur_tag += 1
        c.script = c.script[1:]
        c.abort_seen = 0
        c.phase = "wait_commence"
        w.deliver(m.on_collective_init(name, c.cur_tag))
    elif step == "follow":
        c.cur_tag = act[3]
        c.abort_seen = 0
        c.phase = "wait_commence"
        w.deliver(m.on_collective_init(name, c.cur_tag))
    elif step == "consume_replay":
        ab = c.take("kM2CCollectiveAbort", tag=c.cur_tag)
        assert ab is not None
        done = c.take("kM2CCollectiveDone", tag=c.cur_tag)
        if done is None:
            raise Violation(
                f"{name} got a pre-commence abort for tag {c.cur_tag} with "
                "no Done following it — replay must deliver verdict+done "
                "atomically")
        c.phase = "active"  # kOk or kAborted: either way the app moves on
    elif step == "consume_commence":
        fr = c.take("kM2CCollectiveCommence", tag=c.cur_tag)
        assert fr is not None
        if fr["seq"] <= c.last_seq:
            raise Violation(
                f"{name} observed collective seq {fr['seq']} after "
                f"{c.last_seq} — seqs must be strictly monotone (journaled "
                "seq bound across restarts)")
        c.last_seq = fr["seq"]
        c.cur_world = fr["world"]
        c.phase = "in_ring"
    elif step == "finish_ring":
        aborted = False
        ab = c.take("kM2CCollectiveAbort", tag=c.cur_tag)
        if ab is not None:
            _count_abort(c, name)
            aborted = True  # the worker unwound on the abort poll
        elif c.cur_world < 2:
            # a ring needs two nodes: the worker fails the op through the
            # NORMAL completion handshake (local_failure=true), so the
            # master's op table is closed out instead of leaking the op
            # until this client disconnects (found by this checker; see
            # run_reduce_worker's world<2 bail in client.cpp)
            aborted = True
        elif c.local_abort:
            aborted = True
            c.local_abort = False
        c.phase = "wait_coll_done"
        w.deliver(m.on_collective_complete(name, c.cur_tag, aborted))
    elif step == "consume_coll_done":
        while True:  # consume the verdict(s) queued before Done
            ab = c.take("kM2CCollectiveAbort", tag=c.cur_tag)
            if ab is None:
                break
            _count_abort(c, name)
        if c.abort_seen != 1:
            raise Violation(
                f"{name} reached CollectiveDone for tag {c.cur_tag} with "
                f"{c.abort_seen} abort-verdict packets — the contract is "
                "exactly one (early broadcast or completion verdict)")
        c.take("kM2CCollectiveDone", tag=c.cur_tag)
        c.phase = "active"
    elif step == "start_sync":
        c.script = c.script[1:]
        c.phase = "wait_sync_resp"
        c.sync_offered = c.last_sync_revision + 1
        w.deliver(m.on_shared_state_sync(name, c.sync_offered))
    elif step == "follow_sync":
        c.phase = "wait_sync_resp"
        c.sync_offered = c.last_sync_revision + 1
        w.deliver(m.on_shared_state_sync(name, c.sync_offered))
    elif step == "consume_sync_resp":
        resp = c.take("kM2CSharedStateSyncResp")
        assert resp is not None
        if resp["failed"]:
            c.phase = "active"  # round failed loudly; app decides what next
        else:
            c.phase = "wait_sync_done"
            w.deliver(m.on_dist_done(name))
    elif step == "consume_sync_done":
        fr = c.take("kM2CSharedStateDone")
        assert fr is not None
        if fr["revision"] < c.last_sync_revision:
            raise Violation(
                f"{name} observed shared-state revision {fr['revision']} "
                f"after {c.last_sync_revision} — revisions must be monotone "
                "across epochs (resume-ack max() rule)")
        c.last_sync_revision = fr["revision"]
        c.phase = "active"
    elif step == "start_optimize":
        c.script = c.script[1:]
        c.phase = "wait_opt"
        w.deliver(m.on_optimize(name))
    elif step == "follow_optimize":
        c.phase = "wait_opt"
        w.deliver(m.on_optimize(name))
    elif step == "consume_opt_resp":
        c.take("kM2COptimizeResponse")
        w.deliver(m.on_bandwidth_report(name))
        w.deliver(m.on_optimize_work_done(name))
    elif step == "consume_opt_complete":
        c.take("kM2COptimizeComplete")
        c.phase = "active"
    elif step == "leave":
        c.script = c.script[1:]
        c.phase = "left"
        c.inbox = ()
        w.pending_disconnects = w.pending_disconnects | {name}
    elif step == "resume":
        w.deliver(m.on_session_resume(name, c.last_sync_revision))
        ack = c.take("kM2CSessionResumeAck")
        assert ack is not None
        if not ack["ok"]:
            c.phase = "dead"  # kMasterUnreachable: app re-registers from scratch
            return w
        if ack["epoch"] < c.epoch:
            raise Violation(f"{name} observed epoch moving backwards on resume")
        c.epoch = ack["epoch"]
        c.last_sync_revision = max(c.last_sync_revision,
                                   ack.get("last_revision", 0))
        rp, c.resume_phase = c.resume_phase, ""
        # session-generation rule: the in-flight op died with the old
        # session; re-issue it on the resumed one (client.cpp retry paths)
        if rp in ("wait_commence", "in_ring", "wait_coll_done"):
            c.abort_seen = 0
            # the previous attempt died with the session: a RETRY carrying
            # the seq it observed at commence (0 = it never saw one)
            seen = c.last_seq if rp in ("in_ring", "wait_coll_done") else 0
            c.phase = "wait_commence"
            w.deliver(m.on_collective_init(name, c.cur_tag, retry=True,
                                           retry_seq=seen))
        elif rp in ("wait_sync_resp", "wait_sync_done"):
            if c.last_sync_revision >= c.sync_offered:
                # the resume ack's revision adoption PROVED the in-flight
                # round completed group-wide just before the crash: skip
                # the retry instead of wedging the group on a revision
                # disagreement (docs/10, the tests/ha_peer.py pattern)
                c.phase = "active"
            else:
                c.phase = "wait_sync_resp"
                c.sync_offered = c.last_sync_revision + 1
                w.deliver(m.on_shared_state_sync(name, c.sync_offered))
        elif rp in ("wait_conninfo", "wait_estab_resp"):
            # the vote died with the old session; the implicit vote action
            # re-votes if anyone is still pending
            c.phase = "active"
        elif rp == "wait_opt":
            c.phase = "wait_opt"
            w.deliver(m.on_optimize(name))
        else:
            c.phase = "active"
    else:  # pragma: no cover - enumerator/apply drift
        raise AssertionError(f"unknown action {act}")
    return w


def _count_abort(c: ClientModel, name: str) -> None:
    c.abort_seen += 1
    if c.abort_seen > 1:
        raise Violation(
            f"{name} received {c.abort_seen} abort packets for tag "
            f"{c.cur_tag} — exactly-one-abort violated (double broadcast)")


# --------------------------------------------------------------------------
# exploration
# --------------------------------------------------------------------------


def _quiescent(w: World) -> bool:
    if w.master.limbo or w.pending_disconnects or w.master.pending_closes:
        return False
    for c in w.clients.values():
        if c.phase not in QUIESCENT_PHASES:
            return False
        if c.phase == "active" and c.script:
            return False
    # master-side leftovers are latent wedges: a dangling op wedges its tag
    # for every future joiner, an in-flight round means someone never
    # answered (their phase would be non-quiescent — this is a backstop)
    if w.master.establish_in_flight:
        return False
    for g in w.master.groups.values():
        if g.ops or g.sync_in_flight:
            return False
    return True


@dataclasses.dataclass
class Result:
    scenario: str
    states: int
    quiescent: int


def explore(sc: Scenario, master_cls: type = MasterModel) -> Result:
    """DFS every interleaving; raises Violation on the first broken
    invariant (with the action trace that reaches it)."""
    w0 = initial_world(sc, master_cls)
    f0 = w0.freeze()
    worlds: "dict[Any, World]" = {f0: w0}
    parent: "dict[Any, tuple[Any, Action] | None]" = {f0: None}
    succs: "dict[Any, list[Any]]" = {}
    stack = [f0]
    quiescent: "set[Any]" = set()

    def trace_to(f: Any) -> "list[Action]":
        acts: "list[Action]" = []
        while True:
            pa = parent[f]
            if pa is None:
                break
            f, a = pa
            acts.append(a)
        acts.reverse()
        return acts

    while stack:
        f = stack.pop()
        if f in succs:
            continue
        w = worlds[f]
        acts = enabled_actions(w)
        nxt: "list[Any]" = []
        if not acts:
            if not _quiescent(w):
                waiting = {n: c.phase for n, c in w.clients.items()
                           if c.phase not in QUIESCENT_PHASES}
                raise Violation(
                    f"stuck world in scenario '{sc.name}': no action enabled "
                    f"but clients are still waiting: {waiting}",
                    trace_to(f))
            quiescent.add(f)
        for a in acts:
            try:
                w2 = apply_action(w, a)
            except Violation as v:
                raise Violation(f"scenario '{sc.name}': {v.message}",
                                trace_to(f) + [a]) from None
            f2 = w2.freeze()
            nxt.append(f2)
            if f2 not in worlds:
                worlds[f2] = w2
                parent[f2] = (f, a)
                stack.append(f2)
                if len(worlds) > sc.max_states:
                    raise Violation(
                        f"scenario '{sc.name}' exceeded {sc.max_states} "
                        "states — shrink the scenario (this cap is a guard "
                        "against model regressions, not an invariant)")
        succs[f] = nxt
        if _quiescent(w):
            quiescent.add(f)

    # liveness: every reachable state must have a PATH to quiescence
    rev: "dict[Any, list[Any]]" = {}
    for f, ns in succs.items():
        for n in ns:
            rev.setdefault(n, []).append(f)
    ok = set(quiescent)
    frontier = list(quiescent)
    while frontier:
        f = frontier.pop()
        for p in rev.get(f, ()):
            if p not in ok:
                ok.add(p)
                frontier.append(p)
    bad = [f for f in succs if f not in ok]
    if bad:
        f = bad[0]
        w = worlds[f]
        waiting = {n: c.phase for n, c in w.clients.items()
                   if c.phase not in QUIESCENT_PHASES}
        raise Violation(
            f"livelock in scenario '{sc.name}': {len(bad)} reachable "
            f"state(s) have NO path to quiescence; e.g. clients stuck in "
            f"{waiting}", trace_to(f))
    return Result(sc.name, len(worlds), len(quiescent))


# --------------------------------------------------------------------------
# scenario suite
# --------------------------------------------------------------------------


def default_scenarios() -> "list[Scenario]":
    """The per-PR suite: every fault class, worlds sized to finish on a
    1-core CI box. --deep widens the worlds."""
    return [
        # all interleavings of a 4-way join + establish (world <= 4 gate)
        Scenario("join4_establish",
                 (("a", 0, ()), ("b", 0, ()), ("c", 0, ()), ("d", 0, ()))),
        # the hand-reasoned vote-vs-commence deadlock tie-break: two active
        # peers run a collective while a third joins mid-round (admission
        # votes are implicit actions, enabled whenever `j` is pending).
        # `j` joins another peer group: collectives are group-scoped, so a
        # same-group joiner would additionally have to participate in the
        # op — the admission/vote interleaving is identical either way.
        Scenario("join_during_collective",
                 (("a", 0, ("collective",)), ("b", 0, ("collective",)),
                  ("j", 1, ()))),
        # one collective, one member aborts locally -> exactly-one-abort
        Scenario("collective_local_abort",
                 (("a", 0, ("collective",)), ("b", 0, ("collective",)),
                  ("c", 0, ("collective",))),
                 local_abort=("b",)),
        # disconnect at every possible point around a collective (scripts
        # are coordination-closed: every group member participates in
        # every group op unless it crashed — the app contract)
        Scenario("collective_crash",
                 (("a", 0, ("collective", "collective")),
                  ("b", 0, ("collective", "collective")),
                  ("c", 0, ("collective", "collective"))),
                 disconnects=("c",), expect_no_kicks=True),
        # shared-state sync with a mid-round crash
        Scenario("sync_crash",
                 (("a", 0, ("sync", "sync")), ("b", 0, ("sync", "sync")),
                  ("c", 0, ("sync", "sync"))),
                 disconnects=("c",)),
        # establish failure -> the unreachable peer is kicked
        Scenario("establish_kick",
                 (("a", 0, ()), ("b", 0, ()), ("v", 0, ())),
                 establish_fail=(("a", "v"),), expect_no_kicks=False),
        # optimize vote round with a crash
        Scenario("optimize_crash",
                 (("a", 0, ("optimize",)), ("b", 0, ("optimize",)),
                  ("c", 0, ("optimize",))),
                 disconnects=("c",)),
        # master SIGKILL+restart at every point of a collective+sync run;
        # resume or limbo-expiry at every point after
        Scenario("restart_resume",
                 (("a", 0, ("collective", "sync")),
                  ("b", 0, ("collective", "sync"))),
                 journal=True, max_restarts=1, staged=True),
        # crash window between Done and the journal append: the resume
        # ack's trust-the-client rule must absorb it without kicks
        Scenario("restart_lag",
                 (("a", 0, ("sync", "sync")), ("b", 0, ("sync", "sync"))),
                 journal=True, max_restarts=1, lag=True),
        # a client joins while another leaves, with a restart in the mix
        Scenario("churn_restart",
                 (("a", 0, ("collective",)),
                  ("b", 0, ("collective", "leave")),
                  ("j", 1, ())),
                 journal=True, max_restarts=1),
    ]


def deep_scenarios() -> "list[Scenario]":
    return [
        Scenario("join4_sync",
                 (("a", 0, ("sync",)), ("b", 0, ("sync",)),
                  ("c", 0, ("sync",)), ("d", 0, ("sync",))),
                 max_states=2_000_000),
        Scenario("collective4_abort",
                 (("a", 0, ("collective",)), ("b", 0, ("collective",)),
                  ("c", 0, ("collective",)), ("d", 0, ("collective",))),
                 local_abort=("d",), max_states=2_000_000),
        Scenario("restart_resume_w3",
                 (("a", 0, ("collective", "sync")),
                  ("b", 0, ("collective", "sync")),
                  ("c", 0, ("collective", "sync"))),
                 journal=True, max_restarts=1, staged=True,
                 max_states=4_000_000),
        Scenario("double_restart",
                 (("a", 0, ("sync", "collective")),
                  ("b", 0, ("sync", "collective"))),
                 journal=True, max_restarts=2, staged=True,
                 max_states=4_000_000),
    ]


def run_suite(scenarios: "list[Scenario]",
              master_cls: type = MasterModel,
              verbose: bool = False) -> "list[Result]":
    out = []
    for sc in scenarios:
        r = explore(sc, master_cls)
        out.append(r)
        if verbose:
            print(f"  {r.scenario}: {r.states} states, "
                  f"{r.quiescent} quiescent — ok")
    return out


def check(root: Path) -> "list[Finding] | Skip":
    del root  # the model is self-contained
    try:
        run_suite(default_scenarios())
    except Violation as v:
        return [Finding(CHECKER, "tools/pcclt_verify/fsm_spec.py", 0, str(v))]
    return []


def main(argv: "list[str] | None" = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="pcclt_verify.model_check",
        description="explicit-state model checker for the CCoIP control plane")
    ap.add_argument("--deep", action="store_true",
                    help="also run the larger worlds (minutes, not seconds)")
    args = ap.parse_args(argv)
    try:
        print("default suite:")
        run_suite(default_scenarios(), verbose=True)
        if args.deep:
            print("deep suite:")
            run_suite(deep_scenarios(), verbose=True)
    except Violation as v:
        print(f"VIOLATION: {v}")
        return 1
    print("model check: all invariants hold")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
