"""Cross-peer trace correlation: merge N per-peer Chrome traces into one
fleet timeline (``python -m tools.trace_merge peer*.json -o fleet.json``).

Every native collective span carries the master-issued ``seq`` (and, since
the observability plane, the master ``epoch``) in its args, and a
collective COMPLETES at nearly the same instant on every member — the ring
finishes when the last chunk lands, and the members' final stages are one
chunk apart. That makes (epoch, seq) a shared event in every peer's local
CLOCK_MONOTONIC timeline: for each non-reference peer we take the median
over shared (epoch, seq) keys of (reference op end - peer op end) as the
peer's clock offset and shift its whole trace by it. Median, not mean — a
straggling op on one peer must not skew the alignment.

The result loads in chrome://tracing / ui.perfetto.dev with one process
track per (peer, original pid), process names prefixed ``peer<i>:`` so a
merged python+native trace keeps both tracks attributable.
"""

from __future__ import annotations

import json
import statistics
from pathlib import Path
from typing import Any, Dict, List, Sequence, Tuple

# spans whose end time anchors the alignment (native collective op spans;
# they carry args.seq and complete near-simultaneously fleet-wide)
ANCHOR_NAMES = ("allreduce", "allgather")


def _events_of(doc: Any) -> List[dict]:
    if isinstance(doc, dict):
        evs = doc.get("traceEvents", [])
    else:  # bare event-array form is also legal Chrome trace JSON
        evs = doc
    return [e for e in evs if isinstance(e, dict)]


def _anchor_ends(events: Sequence[dict]) -> Dict[Tuple[int, int], float]:
    """(epoch, seq) -> µs end time of that collective's op span."""
    out: Dict[Tuple[int, int], float] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("name") not in ANCHOR_NAMES:
            continue
        args = e.get("args") or {}
        if "seq" not in args:
            continue
        key = (int(args.get("epoch", 0)), int(args["seq"]))
        end = float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
        out[key] = max(out.get(key, 0.0), end)
    return out


def merge_traces(docs: Sequence[Any],
                 labels: "Sequence[str] | None" = None) -> dict:
    """Merge parsed per-peer trace documents into one fleet trace dict.

    docs[0] is the reference timeline; every other doc is shifted by the
    median (epoch, seq)-anchored offset against it. Peers sharing no
    anchor with the reference merge unshifted (offset 0) — visible in the
    returned metadata, never a silent misalignment.
    """
    if not docs:
        return {"traceEvents": [], "metadata": {"peers": 0}}
    labels = list(labels) if labels else [f"peer{i}" for i in range(len(docs))]
    per_peer_events = [_events_of(d) for d in docs]
    ref_ends = _anchor_ends(per_peer_events[0])

    merged: List[dict] = []
    offsets_us: Dict[str, float] = {}
    anchors: Dict[str, int] = {}
    pid_map: Dict[Tuple[int, int], int] = {}

    def new_pid(peer: int, old: int) -> int:
        key = (peer, old)
        if key not in pid_map:
            pid_map[key] = len(pid_map) + 1
        return pid_map[key]

    for i, events in enumerate(per_peer_events):
        if i == 0:
            offset = 0.0
            shared = len(ref_ends)
        else:
            ends = _anchor_ends(events)
            deltas = [ref_ends[k] - v for k, v in ends.items()
                      if k in ref_ends]
            shared = len(deltas)
            offset = statistics.median(deltas) if deltas else 0.0
        offsets_us[labels[i]] = offset
        anchors[labels[i]] = shared
        for e in events:
            e = dict(e)  # never mutate the caller's events
            if "pid" in e:
                e["pid"] = new_pid(i, int(e["pid"]))
            if "ts" in e:
                e["ts"] = float(e["ts"]) + offset
            if e.get("ph") == "M" and e.get("name") == "process_name":
                args = dict(e.get("args") or {})
                args["name"] = f"{labels[i]}: {args.get('name', '')}"
                e["args"] = args
            merged.append(e)
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": merged,
        "metadata": {
            "peers": len(docs),
            "labels": labels,
            "offsets_us": offsets_us,
            "shared_anchors": anchors,
        },
    }


def _unique_labels(names: Sequence[str]) -> List[str]:
    """Disambiguate duplicate stems (peer dirs often share a filename) —
    colliding labels would overwrite each other's offset/anchor metadata
    and let an unanchored peer slip past the CLI's exit-1 check."""
    out: List[str] = []
    seen: Dict[str, int] = {}
    for n in names:
        k = seen.get(n, 0)
        seen[n] = k + 1
        out.append(n if k == 0 else f"{n}#{k}")
    return out


def merge_files(paths: Sequence[Path],
                labels: "Sequence[str] | None" = None) -> dict:
    docs = [json.loads(Path(p).read_text()) for p in paths]
    return merge_traces(docs,
                        _unique_labels(list(labels) if labels
                                       else [Path(p).stem for p in paths]))
