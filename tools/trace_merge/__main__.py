"""CLI: ``python -m tools.trace_merge peer0.json peer1.json -o fleet.json``.

Exit 1 when any non-reference peer shares no (epoch, seq) anchor with the
reference (its track would merge unaligned) unless --allow-unanchored.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import merge_files


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.trace_merge",
        description="merge per-peer PCCLT_TRACE dumps into one fleet "
                    "timeline aligned on (epoch, seq)")
    ap.add_argument("traces", nargs="+", type=Path,
                    help="per-peer Chrome trace JSON files (first = "
                         "reference timeline)")
    ap.add_argument("-o", "--out", type=Path, default=Path("fleet_trace.json"))
    ap.add_argument("--allow-unanchored", action="store_true",
                    help="merge peers sharing no collective anchor with the "
                         "reference at offset 0 instead of failing")
    args = ap.parse_args()

    doc = merge_files(args.traces)
    meta = doc["metadata"]
    bad = [lbl for lbl, n in meta["shared_anchors"].items()
           if n == 0 and lbl != meta["labels"][0]]
    for lbl in meta["labels"]:
        print(f"  {lbl}: offset {meta['offsets_us'][lbl]:+.1f} us over "
              f"{meta['shared_anchors'][lbl]} shared (epoch, seq) anchors")
    if bad and not args.allow_unanchored:
        print(f"error: no shared collective anchors for {', '.join(bad)} — "
              "were these traces captured in the same run with the flight "
              "recorder on? (--allow-unanchored to merge anyway)",
              file=sys.stderr)
        return 1
    args.out.write_text(json.dumps(doc))
    print(f"wrote {args.out} ({len(doc['traceEvents'])} events from "
          f"{meta['peers']} peers)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
