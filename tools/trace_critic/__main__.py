"""CLI: ``python -m tools.trace_critic peer*.json [-o report.json]``.

Walks per-peer PCCLT_TRACE dumps (or an incident bundle's ``peer-*.trace
.json`` files), reconstructs each collective's critical path, prints the
per-op attribution table, and optionally writes the full JSON report.
Exit 2 when ``--min-coverage`` is given and the mean attribution coverage
falls below it (the decomposition failed to explain the timeline — stage
spans missing or traces from mismatched runs).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import analyze_files, format_report


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.trace_critic",
        description="attribute each collective's wall time to concrete "
                    "(peer, stage, edge, phase) segments and name the "
                    "binding chain")
    ap.add_argument("traces", nargs="+", type=Path,
                    help="per-peer Chrome trace JSON files (PCCLT_TRACE "
                         "dumps or incident-bundle peer-*.trace.json)")
    ap.add_argument("-o", "--out", type=Path, default=None,
                    help="write the full JSON report here")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the printed per-op table")
    ap.add_argument("--min-coverage", type=float, default=None,
                    help="exit 2 when mean attribution coverage is below "
                         "this fraction (e.g. 0.95)")
    args = ap.parse_args()

    report = analyze_files(args.traces)
    print(format_report(report, top=args.top))
    if args.out:
        args.out.write_text(json.dumps(report, indent=1))
        print(f"wrote {args.out}")
    if not report["aggregate"]["ops"]:
        print("error: no collectives found — were these traces captured "
              "with the flight recorder on?", file=sys.stderr)
        return 1
    if (args.min_coverage is not None
            and report["aggregate"]["mean_coverage"] < args.min_coverage):
        print(f"error: attribution coverage "
              f"{report['aggregate']['mean_coverage']:.1%} < "
              f"{args.min_coverage:.1%}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
