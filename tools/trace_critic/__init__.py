"""Cross-peer critical-path attribution over PCCLT flight-recorder traces.

``tools/trace_merge`` answers *when* — one fleet timeline aligned on
(epoch, seq). This package answers *why*: for every collective it walks
each peer's spans (``commence_wait`` → ``op_setup`` → per-stage
``rs_stage``/``ag_stage``/``gather_stage``, each carrying its ``stall_ns``
and the inbound edge endpoint in ``detail``), reconstructs the binding
chain, and classifies the op:

* **setup-dominated** — master consensus + link setup bound the op (the
  ROADMAP ``commence_wait``/``op_setup`` residual);
* **codec-limited** — quantize/dequantize kernels bound it;
* **stall-straggler** — ONE edge's wire-stall bound it (the edge is
  named: the actionable verdict per arXiv 2606.01680);
* **wire-limited** — stall spread across edges (the pipe itself, not a
  specific hop);
* **balanced** — compute/overlap bound; nothing pathological.

Attribution is duration-based, so no cross-peer clock alignment is
needed; the per-op *binding peer* is simply the one whose op span is
longest. Coverage = attributed segment time / per-peer wall time — the
acceptance gate asserts >= 0.95, i.e. the timeline decomposition explains
the op, it doesn't sample it.
"""

from __future__ import annotations

import json
from collections import Counter, defaultdict
from pathlib import Path
from typing import Any, Dict, List, Sequence

OP_NAMES = ("allreduce", "allgather")
STAGE_NAMES = ("rs_stage", "ag_stage", "gather_stage")

# verdict thresholds (fractions of the binding peer's wall time)
SETUP_FRAC = 0.35
CODEC_FRAC = 0.30
STALL_FRAC = 0.35
# a single edge owning this share of the binding peer's stall names it
STRAGGLER_EDGE_SHARE = 0.60


def _events_of(doc: Any) -> List[dict]:
    evs = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    return [e for e in evs if isinstance(e, dict)]


def _collect_peer(events: Sequence[dict]) -> Dict[tuple, dict]:
    """(epoch, seq) -> this peer's per-collective record (times in µs)."""
    out: Dict[tuple, dict] = {}

    def rec(args) -> dict:
        key = (int(args.get("epoch", 0)), int(args["seq"]))
        return out.setdefault(key, {
            "op_start": None, "op_end": None, "op_us": 0.0,
            "cw_start": None, "cw_us": 0.0, "setup_us": 0.0,
            "stages": [], "quant_us": 0.0, "dequant_us": 0.0,
            "drain_us": 0.0, "drain_edge": "",
            "wd_confirm": set(), "wd_suspect": set(),
        })

    for e in events:
        args = e.get("args") or {}
        if "seq" not in args:
            continue
        name = e.get("name")
        ts = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0))
        if e.get("ph") == "X" and name in OP_NAMES:
            r = rec(args)
            # keep the longest op span per key (retries overwrite shorter)
            if dur >= r["op_us"]:
                r.update(op_start=ts, op_end=ts + dur, op_us=dur)
        elif e.get("ph") == "X" and name == "commence_wait":
            r = rec(args)
            r["cw_start"] = ts if r["cw_start"] is None else min(r["cw_start"], ts)
            r["cw_us"] += dur
        elif e.get("ph") == "X" and name == "op_setup":
            rec(args)["setup_us"] += dur
        elif e.get("ph") == "X" and name == "zombie_drain":
            # post-failover wait for stalled direct copies to crawl out at
            # the degraded rate — charged to the OUTBOUND edge
            r = rec(args)
            r["drain_us"] += dur
            r["drain_edge"] = args.get("detail") or r["drain_edge"]
        elif e.get("ph") == "X" and name in STAGE_NAMES:
            rec(args)["stages"].append({
                "stage": int(args.get("stage", -1)),
                "kind": name,
                "us": dur,
                "stall_us": float(args.get("stall_ns", 0)) / 1e3,
                "edge": args.get("detail") or "",
            })
        elif name == "quantize":
            rec(args)["quant_us"] += float(args.get("ns", 0)) / 1e3
        elif name == "dequantize":
            rec(args)["dequant_us"] += float(args.get("ns", 0)) / 1e3
        elif name in ("edge_confirm", "edge_suspect"):
            # the data plane's own watchdog verdict, carrying the OUTBOUND
            # edge endpoint in detail — the strongest attribution signal
            # (in a coupled ring every peer stalls; only the watchdog
            # names the hop that caused it)
            edge = args.get("detail") or ""
            if edge:
                kind = "wd_confirm" if name == "edge_confirm" else "wd_suspect"
                rec(args)[kind].add(edge)
    return {k: v for k, v in out.items() if v["op_start"] is not None}


def _peer_breakdown(r: dict) -> dict:
    """Attribute one peer's collective: wall, segments, coverage."""
    start = r["cw_start"] if r["cw_start"] is not None else r["op_start"]
    wall = max(r["op_end"] - start, 1e-9)
    stage_us = sum(s["us"] for s in r["stages"])
    stall_us = sum(s["stall_us"] for s in r["stages"])
    per_edge_stall: Dict[str, float] = defaultdict(float)
    per_edge_stage: Dict[str, float] = defaultdict(float)
    for s in r["stages"]:
        per_edge_stall[s["edge"]] += s["stall_us"]
        per_edge_stage[s["edge"]] += s["us"]
    if r["drain_us"]:
        # the drain is a stall on the outbound hop in all but name
        per_edge_stall[r["drain_edge"]] += r["drain_us"]
        stall_us += r["drain_us"]
    attributed = r["cw_us"] + r["setup_us"] + stage_us + r["drain_us"]
    return {
        "wall_us": wall,
        "coverage": min(attributed / wall, 1.0),
        "cw_us": r["cw_us"],
        "setup_us": r["setup_us"],
        "stage_us": stage_us,
        "stall_us": stall_us,
        "drain_us": r["drain_us"],
        "codec_us": r["quant_us"] + r["dequant_us"],
        "per_edge_stall": dict(per_edge_stall),
        "per_edge_stage": dict(per_edge_stage),
        "n_stages": len(r["stages"]),
        "wd_confirm": sorted(r["wd_confirm"]),
        "wd_suspect": sorted(r["wd_suspect"]),
    }


def _classify(b: dict, members: "Dict[str, dict]") -> tuple:
    """(verdict, named_edge) for a binding peer's breakdown.

    The straggler test is FLEET-relative: on a healthy wire-paced ring
    every peer stalls comparably on its own inbound hop (the wire is the
    bound — that's wire-limited, not a straggler); only when one directed
    hop owns most of the op's stall fleet-wide is it named."""
    wall = b["wall_us"]
    setup_frac = (b["cw_us"] + b["setup_us"]) / wall
    codec_frac = b["codec_us"] / wall
    stall_frac = b["stall_us"] / wall
    if setup_frac > SETUP_FRAC:
        return "setup-dominated", ""
    if codec_frac >= CODEC_FRAC:
        return "codec-limited", ""
    if stall_frac >= STALL_FRAC:
        fleet_total = sum(m["stall_us"] for m in members.values())
        fleet_edges: Dict[tuple, float] = defaultdict(float)
        for lbl, m in members.items():
            for edge, us in m["per_edge_stall"].items():
                fleet_edges[(lbl, edge)] += us
        if fleet_total > 0 and fleet_edges:
            (_, edge), top = max(fleet_edges.items(), key=lambda kv: kv[1])
            if top >= STRAGGLER_EDGE_SHARE * fleet_total:
                return "stall-straggler", edge
        return "wire-limited", ""
    return "balanced", ""


def analyze_docs(docs: Sequence[Any],
                 labels: "Sequence[str] | None" = None) -> dict:
    labels = list(labels) if labels else [f"peer{i}" for i in range(len(docs))]
    per_peer = {labels[i]: _collect_peer(_events_of(d))
                for i, d in enumerate(docs)}
    keys = sorted({k for recs in per_peer.values() for k in recs})

    collectives: List[dict] = []
    verdicts: Counter = Counter()
    edge_stall: Dict[tuple, float] = defaultdict(float)  # (witness, edge)
    edge_stage: Dict[tuple, float] = defaultdict(float)
    phase_totals: Dict[str, float] = defaultdict(float)
    coverages: List[float] = []
    wd_named: Counter = Counter()  # watchdog-confirmed edges across the run

    for key in keys:
        members = {lbl: _peer_breakdown(recs[key])
                   for lbl, recs in per_peer.items() if key in recs}
        if not members:
            continue
        binding = max(members, key=lambda lbl: members[lbl]["wall_us"])
        bb = members[binding]
        verdict, named_edge = _classify(bb, members)
        # watchdog override: the data plane CONFIRMed a specific edge
        # during this collective — in a coupled ring every peer's stall is
        # comparable, so the in-band verdict outranks the stall ranking
        wd_edges = sorted({e for m in members.values()
                           for e in m["wd_confirm"]})
        if wd_edges and verdict in ("wire-limited", "stall-straggler",
                                    "balanced"):
            verdict, named_edge = "stall-straggler", wd_edges[0]
        for e in wd_edges:
            wd_named[e] += 1
        verdicts[verdict] += 1
        coverages.append(min(m["coverage"] for m in members.values()))
        # run-level edge ranking: every peer's witness counts, not just
        # the binding one — a hop binding HALF the ops still dominates
        crit_peer, crit_edge, crit_stall = binding, named_edge, 0.0
        if named_edge:  # a watchdog-named edge is final for this op
            crit_stall = float("inf")
        for lbl, m in members.items():
            in_stage_stall = m["stall_us"] - m["drain_us"]
            phase_totals["commence_wait"] += m["cw_us"]
            phase_totals["op_setup"] += m["setup_us"]
            phase_totals["stage"] += m["stage_us"] - in_stage_stall
            phase_totals["stall"] += in_stage_stall
            phase_totals["drain"] += m["drain_us"]
            # NOTE: codec OVERLAPS the stage bucket (kernels run inside
            # the stage windows) — sum the other five for a disjoint wall
            # decomposition; codec is a cross-cutting view
            phase_totals["codec"] += m["codec_us"]
            for edge, us in m["per_edge_stall"].items():
                edge_stall[(lbl, edge)] += us
                if us > crit_stall:
                    crit_peer, crit_edge, crit_stall = lbl, edge, us
            for edge, us in m["per_edge_stage"].items():
                edge_stage[(lbl, edge)] += us
        collectives.append({
            "epoch": key[0], "seq": key[1],
            "peers": len(members),
            "binding_peer": binding,
            "wall_us": bb["wall_us"],
            "coverage": min(m["coverage"] for m in members.values()),
            "verdict": verdict,
            "critical_edge": crit_edge,
            "critical_witness": crit_peer,
            "fracs": {
                "setup": (bb["cw_us"] + bb["setup_us"]) / bb["wall_us"],
                "codec": bb["codec_us"] / bb["wall_us"],
                "stall": bb["stall_us"] / bb["wall_us"],
            },
            "members": members,
        })

    edges = [{"witness": w, "edge": e, "stall_us": us,
              "stage_us": edge_stage.get((w, e), 0.0)}
             for (w, e), us in sorted(edge_stall.items(),
                                      key=lambda kv: -kv[1])]
    # run-level critical edge: a watchdog-confirmed edge wins outright
    # (the data plane proved the hop); otherwise the top stall witness
    if wd_named:
        crit_edge = wd_named.most_common(1)[0][0]
        crit_wit = "watchdog"
    elif edges and edges[0]["stall_us"] > 0:
        crit_edge, crit_wit = edges[0]["edge"], edges[0]["witness"]
    else:
        crit_edge = crit_wit = ""
    agg = {
        "ops": len(collectives),
        "peers": len(docs),
        "mean_coverage": (sum(coverages) / len(coverages)) if coverages else 0.0,
        "min_coverage": min(coverages) if coverages else 0.0,
        "verdicts": dict(verdicts),
        "edges": edges,
        "wd_confirmed_edges": dict(wd_named),
        "critical_edge": crit_edge,
        "critical_witness": crit_wit,
        "phase_totals_us": dict(phase_totals),
    }
    return {"collectives": collectives, "aggregate": agg}


def analyze_files(paths: Sequence[Path],
                  labels: "Sequence[str] | None" = None) -> dict:
    docs = [json.loads(Path(p).read_text()) for p in paths]
    return analyze_docs(
        docs, list(labels) if labels else [Path(p).stem for p in paths])


def format_report(report: dict, top: int = 10) -> str:
    """Human-readable per-op table + aggregate summary."""
    lines: List[str] = []
    agg = report["aggregate"]
    lines.append(f"trace_critic: {agg['ops']} collectives across "
                 f"{agg['peers']} peer traces "
                 f"(coverage mean {agg['mean_coverage']:.1%}, "
                 f"min {agg['min_coverage']:.1%})")
    lines.append("")
    lines.append(f"{'seq':>6} {'wall ms':>9} {'bind':>8} {'stall':>6} "
                 f"{'codec':>6} {'setup':>6}  verdict / critical edge")
    for c in report["collectives"][:top]:
        f = c["fracs"]
        edge = f" via {c['critical_edge']}" if c["critical_edge"] else ""
        lines.append(
            f"{c['seq']:>6} {c['wall_us'] / 1e3:>9.2f} "
            f"{c['binding_peer']:>8} {f['stall']:>6.1%} {f['codec']:>6.1%} "
            f"{f['setup']:>6.1%}  {c['verdict']}{edge}")
    if agg["ops"] > top:
        lines.append(f"  ... {agg['ops'] - top} more")
    lines.append("")
    lines.append("verdicts: " + (", ".join(
        f"{k}={v}" for k, v in sorted(agg["verdicts"].items())) or "none"))
    if agg["edges"]:
        lines.append("edges by total witnessed stall:")
        for e in agg["edges"][:top]:
            lines.append(f"  {e['edge'] or '(unknown)':>22} <- {e['witness']}: "
                         f"stall {e['stall_us'] / 1e3:.1f} ms over "
                         f"{e['stage_us'] / 1e3:.1f} ms of stages")
    if agg["critical_edge"]:
        lines.append(f"critical path: edge {agg['critical_edge']} "
                     f"(witnessed by {agg['critical_witness']})")
    return "\n".join(lines)
