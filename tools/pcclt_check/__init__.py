"""pcclt-check: cross-layer drift linters + static lock-discipline analysis.

The native core and its Python binding carry several hand-maintained
mirrors that TSan and the test suite cannot see drifting:

  * ``include/pcclt.h`` structs/enums/prototypes  <->  the ctypes mirrors
    in ``pccl_tpu/comm/_native.py``            (checker: ``abi``)
  * protocol ids in ``protocol.hpp``           <->  their encode/decode
    sites and dispatch arms                     (checker: ``protocol``)
  * ``getenv("PCCLT_*")`` reads                 <->  the env-var table in
    ``docs/03_api_overview.md``                 (checker: ``env``)
  * "single-threaded by design" markers         <->  runtime
    ``PCCLT_THREAD_GUARD`` enforcement          (checker: ``guards``)
  * ``PCCLT_GUARDED_BY``/``PCCLT_REQUIRES`` lock contracts
    (annotations.hpp)                           (checker: ``tsa``,
    clang -Wthread-safety via libclang; the CMake ``-DPCCLT_ANALYZE=ON``
    config runs the same analysis with a real clang++ driver)

Run everything: ``python -m tools.pcclt_check``.  See
``docs/11_static_analysis.md`` for the discipline and how to extend it.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Callable, Iterable


@dataclasses.dataclass
class Finding:
    """One actionable drift report: where it is and how to fix it."""

    checker: str
    path: str  # repo-relative
    line: int  # 0 = whole-file
    message: str

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"[{self.checker}] {loc}: {self.message}"


@dataclasses.dataclass
class Skip:
    """A checker that could not run here (missing optional dependency)."""

    checker: str
    reason: str

    def __str__(self) -> str:
        return f"[{self.checker}] SKIPPED: {self.reason}"


CheckFn = Callable[[Path], "list[Finding] | Skip"]


def _registry() -> "dict[str, CheckFn]":
    # imported lazily so `--checker abi` does not pay for libclang etc.
    from . import abi, env_registry, guards, protocol_ids, thread_safety

    return {
        "abi": abi.check,
        "protocol": protocol_ids.check,
        "env": env_registry.check,
        "guards": guards.check,
        "tsa": thread_safety.check,
    }


def checker_names() -> "list[str]":
    return list(_registry())


def run(root: Path, names: "Iterable[str] | None" = None
        ) -> "tuple[list[Finding], list[Skip]]":
    """Run the named checkers (default: all) against the tree at `root`."""
    registry = _registry()
    findings: "list[Finding]" = []
    skips: "list[Skip]" = []
    for name in names if names is not None else registry:
        if name not in registry:
            raise KeyError(f"unknown checker {name!r}; have {sorted(registry)}")
        out = registry[name](Path(root))
        if isinstance(out, Skip):
            skips.append(out)
        else:
            findings.extend(out)
    return findings, skips
