"""Checker ``tsa``: clang -Wthread-safety over every native TU, via libclang.

This is the same analysis the CMake ``-DPCCLT_ANALYZE=ON`` config runs with
a real ``clang++`` driver (CI's lint lane), made available on hosts that
only have the ``libclang`` Python wheel: each translation unit in
``pccl_tpu/native/src`` is parsed with ``-Wthread-safety`` and ANY
diagnostic of warning severity or above fails the check — the tree is
kept warning-clean under the analysis, so a single new warning is always
a regression in the change that introduced it.

Two host quirks are absorbed here:

  * the libclang wheel ships no resource headers, so clang's builtin
    includes (stddef.h & friends) come from the host GCC's builtin dir;
  * GCC's SIMD intrinsic headers call GCC-only builtins clang cannot
    parse, so ``intrin_shim/`` shadows them with parse-only signatures
    (see pcclt_shim_common.h — never used for code generation).

No libclang on the host -> the checker reports a Skip (the CI lint lane
still enforces the analysis with real clang++).
"""

from __future__ import annotations

import glob
from pathlib import Path

from . import Finding, Skip

SRC = "pccl_tpu/native/src"
INCLUDE = "pccl_tpu/native/include"
# severity 2 = warning, 3 = error, 4 = fatal (clang.cindex.Diagnostic)
_FAIL_AT = 2


def _gcc_builtin_include() -> "str | None":
    hits = sorted(glob.glob("/usr/lib/gcc/*/*/include"))
    return hits[-1] if hits else None


def parse_args(root: Path) -> "list[str]":
    args = [
        "-std=c++20", "-x", "c++", "-pthread",
        f"-I{root / INCLUDE}", f"-I{root / SRC}",
        f"-I{Path(__file__).resolve().parent / 'intrin_shim'}",
        "-Wthread-safety", "-Wthread-safety-beta",
    ]
    gcc_inc = _gcc_builtin_include()
    if gcc_inc:
        args.append(f"-I{gcc_inc}")
    return args


def check(root: Path) -> "list[Finding] | Skip":
    try:
        from clang import cindex
        index = cindex.Index.create()
    except Exception as e:  # no wheel, or libclang.so failed to load
        return Skip("tsa", f"libclang unavailable ({e}); run the analysis via "
                    "CXX=clang++ cmake -DPCCLT_ANALYZE=ON instead")

    src = root / SRC
    tus = sorted(src.glob("*.cpp"))
    if not tus:
        return [Finding("tsa", SRC, 0, "no native TUs found")]

    args = parse_args(root)
    out: "list[Finding]" = []
    for tu_path in tus:
        tu = index.parse(str(tu_path), args=args)
        for d in tu.diagnostics:
            if d.severity < _FAIL_AT:
                continue
            loc = d.location
            fpath = str(loc.file) if loc.file else str(tu_path)
            try:
                rel = str(Path(fpath).resolve().relative_to(root.resolve()))
            except ValueError:
                rel = fpath  # a system header: report as-is
            out.append(Finding(
                "tsa", rel, loc.line,
                f"{d.spelling} [clang -Wthread-safety sweep of "
                f"{tu_path.name}]"))
    return out
