"""Checker ``abi``: pcclt.h <-> _native.py ctypes mirror parity.

Parses ``pccl_tpu/native/include/pcclt.h`` (structs, enums, prototypes)
with a small parser for the header's controlled C99 style, and
``pccl_tpu/comm/_native.py`` with :mod:`ast` (never importing it — the
checker must run without a built ``libpcclt.so``).  Diffs, field by field
and argument by argument:

  * every header struct has a ``ctypes.Structure`` mirror whose fields
    match in NAME, ORDER and WIDTH (e.g. ``uint32_t`` must be mirrored as
    ``c_uint32``, ``char x[64]`` as ``c_char * 64``);
  * every function declared in ``_declare()`` exists in the header with
    the same arity and compatible argument/return ctypes, and every
    exported header function is declared (a C-side signature change that
    the binding misses corrupts arguments silently at call time).

Mirror names match after normalization (strip the ``pcclt`` prefix and
``_t`` suffix, a trailing ``C`` disambiguator, underscores, case), so
``pccltTensorInfo_t`` <-> ``TensorInfoC``.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from . import Finding

HEADER = "pccl_tpu/native/include/pcclt.h"
NATIVE = "pccl_tpu/comm/_native.py"

# C scalar type -> the one acceptable ctypes token (width parity)
_SCALAR = {
    "uint8_t": "c_uint8",
    "int8_t": "c_int8",
    "uint16_t": "c_uint16",
    "int16_t": "c_int16",
    "uint32_t": "c_uint32",
    "int32_t": "c_int32",
    "uint64_t": "c_uint64",
    "int64_t": "c_int64",
    "int": "c_int",
    "unsigned": "c_uint",
    "char": "c_char",
    "float": "c_float",
    "double": "c_double",
    "size_t": "c_size_t",
}


def _strip_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", lambda m: "\n" * m.group(0).count("\n"), text,
                  flags=re.S)
    return re.sub(r"//[^\n]*", "", text)


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


class HeaderModel:
    def __init__(self) -> None:
        self.enums: dict[str, dict[str, int]] = {}
        self.structs: dict[str, list[tuple[str, str, int]]] = {}  # name -> [(field, ctype, line)]
        self.struct_lines: dict[str, int] = {}
        self.funcs: dict[str, tuple[str, list[str], int]] = {}  # name -> (ret, args, line)


def _canon_c_type(decl: str, enums: set[str], structs: set[str]) -> str:
    """Map one C declarator type to the expected ctypes token."""
    t = decl.strip()
    t = re.sub(r"\bconst\b", "", t).strip()
    t = re.sub(r"\s+", " ", t)
    stars = t.count("*")
    base = t.replace("*", "").strip()
    if stars == 0:
        if base in _SCALAR:
            return _SCALAR[base]
        if base in enums:
            return "c_int"  # ctypes convention for C enums (int-sized)
        return f"?{base}"
    if base == "char" and stars == 1:
        return "c_char_p"
    if base == "void" and stars >= 1:
        # void* / void** / void *const *: one indirection is the handle
        return "c_void_p" if stars == 1 else "POINTER(c_void_p)"
    if stars == 1:
        if base in _SCALAR:
            return f"POINTER({_SCALAR[base]})"
        if base in enums:
            return "POINTER(c_int)"
        if base in structs:
            return f"POINTER({base})"
        # opaque handle (pccltComm_t / pccltMaster_t)
        return "c_void_p"
    if stars == 2:
        # out-params for handles/structs: POINTER(<single-star form>)
        inner = _canon_c_type(base + " *", enums, structs)
        return f"POINTER({inner})"
    return f"?{t}"


def parse_header(text: str) -> HeaderModel:
    m = HeaderModel()
    clean = _strip_comments(text)

    for em in re.finditer(r"typedef enum (\w+)\s*\{(.*?)\}\s*\1\s*;", clean, re.S):
        name, body = em.group(1), em.group(2)
        vals: dict[str, int] = {}
        nxt = 0
        for ent in body.split(","):
            ent = ent.strip()
            if not ent:
                continue
            if "=" in ent:
                k, v = ent.split("=")
                nxt = int(v.strip(), 0)
                vals[k.strip()] = nxt
            else:
                vals[ent] = nxt
            nxt += 1
        m.enums[name] = vals

    enum_names = set(m.enums)
    # two passes so a struct field may reference a struct declared later
    struct_bodies = list(
        re.finditer(r"typedef struct (\w+)\s*\{(.*?)\}\s*\1\s*;", clean, re.S))
    struct_names = {sm.group(1) for sm in struct_bodies}
    for sm in struct_bodies:
        name, body = sm.group(1), sm.group(2)
        m.struct_lines[name] = _line_of(clean, sm.start())
        fields: list[tuple[str, str, int]] = []
        for decl in body.split(";"):
            line = _line_of(clean, sm.start(2) + body.find(decl))
            decl = decl.strip()
            if not decl:
                continue
            fp = re.match(r"[\w\s]+\**\s*\(\s*\*\s*(\w+)\s*\)\s*\(.*\)$", decl, re.S)
            if fp:
                fields.append((fp.group(1), "CFUNCTYPE", line))
                continue
            arr = re.match(r"(.+?)\s+(\w+)\s*\[\s*(\d+)\s*\]$", decl)
            if arr:
                base = _canon_c_type(arr.group(1), enum_names, struct_names)
                fields.append((arr.group(2), f"{base}*{arr.group(3)}", line))
                continue
            pm = re.match(r"(.+?)\s*(\w+)$", decl, re.S)
            if pm:
                typ, fname = pm.group(1), pm.group(2)
                # '*' may lean on the name: "const char *master_ip"
                fields.append(
                    (fname, _canon_c_type(typ, enum_names, struct_names), line))
        m.structs[name] = fields

    for fm in re.finditer(
            r"PCCLT_EXPORT\s+([\w\s]+?\**)\s*(pcclt\w+)\s*\((.*?)\)\s*;",
            clean, re.S):
        ret, name, argstr = fm.group(1), fm.group(2), fm.group(3)
        args: list[str] = []
        argstr = re.sub(r"\s+", " ", argstr).strip()
        if argstr not in ("", "void"):
            for a in argstr.split(","):
                a = a.strip()
                # drop the parameter name (last identifier not part of type)
                am = re.match(r"(.+?)\s*(\w+)$", a)
                typ = am.group(1) if am else a
                # "const uint64_t *counts" keeps stars with the type above;
                # "void *const *recvbufs" needs the trailing qualifier fold
                if am and am.group(2) not in _SCALAR and not am.group(2).startswith("pcclt"):
                    typ = a[: a.rfind(am.group(2))]
                args.append(_canon_c_type(typ, enum_names, struct_names))
        m.funcs[name] = (_canon_c_type(ret, enum_names, struct_names), args,
                         _line_of(clean, fm.start()))
    return m


# ---------------------------------------------------------------- python side


def _canon_py(expr: ast.expr) -> str:
    """Canonicalize a ctypes expression from _native.py to a token."""
    if isinstance(expr, ast.Attribute):  # ctypes.c_uint64 / c.c_uint64
        return expr.attr
    if isinstance(expr, ast.Name):  # P / CommStats / MaterializeFn
        return expr.id
    if isinstance(expr, ast.Call):  # ctypes.POINTER(X) / P(X) / CFUNCTYPE(...)
        fn = _canon_py(expr.func)
        if fn in ("POINTER", "P"):
            return f"POINTER({_canon_py(expr.args[0])})"
        if fn == "CFUNCTYPE":
            return "CFUNCTYPE"
        return fn
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mult):  # c_char * 64
        right = expr.right
        if isinstance(right, ast.Constant):
            return f"{_canon_py(expr.left)}*{right.value}"
    return f"?{ast.dump(expr)}"


class PyModel:
    def __init__(self) -> None:
        self.structs: dict[str, list[tuple[str, str, int]]] = {}
        self.struct_lines: dict[str, int] = {}
        self.funcs: dict[str, dict] = {}  # name -> {restype, argtypes, line}
        self.cfunc_aliases: set[str] = set()


def parse_native(text: str) -> PyModel:
    tree = ast.parse(text)
    m = PyModel()

    for node in tree.body:
        if (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)
                and _canon_py(node.value.func) == "CFUNCTYPE"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    m.cfunc_aliases.add(t.id)
        if not isinstance(node, ast.ClassDef):
            continue
        bases = [_canon_py(b) for b in node.bases]
        if "Structure" not in bases:
            continue
        for stmt in node.body:
            if (isinstance(stmt, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "_fields_"
                            for t in stmt.targets)
                    and isinstance(stmt.value, ast.List)):
                fields = []
                for elt in stmt.value.elts:
                    if isinstance(elt, ast.Tuple) and len(elt.elts) == 2:
                        fname = elt.elts[0].value  # type: ignore[attr-defined]
                        ftype = _canon_py(elt.elts[1])
                        if ftype in m.cfunc_aliases:
                            ftype = "CFUNCTYPE"
                        fields.append((fname, ftype, elt.lineno))
                m.structs[node.name] = fields
                m.struct_lines[node.name] = node.lineno

    decl = next((n for n in tree.body
                 if isinstance(n, ast.FunctionDef) and n.name == "_declare"), None)
    if decl is None:
        return m

    def record(fname: str, attr: str, value: ast.expr, line: int) -> None:
        e = m.funcs.setdefault(fname, {"line": line})
        if attr == "restype":
            e["restype"] = _canon_py(value)
        elif attr == "argtypes":
            if isinstance(value, ast.List):
                e["argtypes"] = [_canon_py(x) for x in value.elts]

    def walk(stmts: list[ast.stmt], loop_names: list[str] | None = None) -> None:
        for st in stmts:
            if isinstance(st, ast.Try):
                walk(st.body, loop_names)
                continue
            if isinstance(st, ast.For):
                # for fn in ("A", "B", ...): f = getattr(lib, fn); f.X = ...
                names: list[str] = []
                if isinstance(st.iter, (ast.Tuple, ast.List)):
                    names = [e.value for e in st.iter.elts
                             if isinstance(e, ast.Constant)]
                walk(st.body, names)
                continue
            if not isinstance(st, ast.Assign) or len(st.targets) != 1:
                continue
            t = st.targets[0]
            if not isinstance(t, ast.Attribute):
                continue
            attr = t.attr  # restype / argtypes
            holder = t.value
            # lib.NAME.restype = ...
            if (isinstance(holder, ast.Attribute)
                    and isinstance(holder.value, ast.Name)
                    and holder.value.id == "lib"):
                record(holder.attr, attr, st.value, st.lineno)
            # f.restype = ... inside a for-getattr loop
            elif isinstance(holder, ast.Name) and loop_names:
                for n in loop_names:
                    record(n, attr, st.value, st.lineno)

    walk(decl.body)
    return m


# ------------------------------------------------------------------ compare


def _norm(name: str) -> str:
    n = name
    if n.startswith("pcclt"):
        n = n[len("pcclt"):]
    if n.endswith("_t"):
        n = n[:-2]
    if n.endswith("C") and len(n) > 1:
        n = n[:-1]
    return n.replace("_", "").lower()


def _compatible(expected: str, actual: str, py_structs: set[str]) -> bool:
    if expected == actual:
        return True
    # POINTER(pccltX_t) vs POINTER(PyMirror): struct names match normalized
    em = re.match(r"POINTER\((\w+)\)", expected)
    am = re.match(r"POINTER\((\w+)\)", actual)
    if em and am:
        return _norm(em.group(1)) == _norm(am.group(1))
    # a struct pointer may legitimately be declared opaque on the py side
    if em and actual == "c_void_p":
        return True
    return False


def check(root: Path) -> "list[Finding]":
    out: list[Finding] = []
    hpath, npath = root / HEADER, root / NATIVE
    for p in (hpath, npath):
        if not p.is_file():
            return [Finding("abi", str(p.relative_to(root)) if p.is_relative_to(root)
                            else str(p), 0, "file missing — cannot diff the ABI")]
    hm = parse_header(hpath.read_text())
    pm = parse_native(npath.read_text())
    py_structs = set(pm.structs)
    py_by_norm = {_norm(k): k for k in pm.structs}

    # --- structs, field by field ---
    for cname, cfields in hm.structs.items():
        pyname = py_by_norm.get(_norm(cname))
        if pyname is None:
            out.append(Finding("abi", NATIVE, 0,
                               f"header struct {cname} has no ctypes.Structure "
                               f"mirror (add one with {len(cfields)} fields)"))
            continue
        pfields = pm.structs[pyname]
        for i, (cf, pf) in enumerate(zip(cfields, pfields)):
            if cf[0] != pf[0]:
                out.append(Finding(
                    "abi", NATIVE, pf[2],
                    f"{pyname}._fields_[{i}] is {pf[0]!r} but {cname} field "
                    f"#{i} in pcclt.h is {cf[0]!r} (name/order drift)"))
                break  # order is shifted; further pairs are noise
            if not _compatible(cf[1], pf[1], py_structs):
                out.append(Finding(
                    "abi", NATIVE, pf[2],
                    f"{pyname}.{pf[0]} is {pf[1]} but pcclt.h declares "
                    f"{cname}.{cf[0]} as {cf[1]} (width drift)"))
        if len(cfields) != len(pfields):
            out.append(Finding(
                "abi", NATIVE, pm.struct_lines[pyname],
                f"{pyname} has {len(pfields)} fields but {cname} in pcclt.h "
                f"has {len(cfields)}"))

    # --- functions, argument by argument ---
    for fname, entry in pm.funcs.items():
        if fname not in hm.funcs:
            out.append(Finding("abi", NATIVE, entry["line"],
                               f"_declare() declares lib.{fname} but pcclt.h "
                               "exports no such function"))
            continue
        ret, cargs, _hline = hm.funcs[fname]
        if "restype" in entry and not _compatible(ret, entry["restype"], py_structs):
            out.append(Finding(
                "abi", NATIVE, entry["line"],
                f"lib.{fname}.restype is {entry['restype']} but pcclt.h "
                f"returns {ret}"))
        if "argtypes" in entry:
            pargs = entry["argtypes"]
            if len(pargs) != len(cargs):
                out.append(Finding(
                    "abi", NATIVE, entry["line"],
                    f"lib.{fname}.argtypes has {len(pargs)} entries but "
                    f"pcclt.h declares {len(cargs)} parameters"))
            else:
                for i, (ca, pa) in enumerate(zip(cargs, pargs)):
                    if not _compatible(ca, pa, py_structs):
                        out.append(Finding(
                            "abi", NATIVE, entry["line"],
                            f"lib.{fname}.argtypes[{i}] is {pa} but pcclt.h "
                            f"parameter #{i} is {ca}"))
        elif cargs:
            out.append(Finding(
                "abi", NATIVE, entry["line"],
                f"lib.{fname} sets no argtypes but pcclt.h declares "
                f"{len(cargs)} parameters (ctypes would guess widths)"))

    for fname, (_ret, _args, hline) in hm.funcs.items():
        if fname not in pm.funcs:
            out.append(Finding(
                "abi", HEADER, hline,
                f"pcclt.h exports {fname} but _declare() never declares it "
                "(Python callers would get unchecked int-width defaults)"))
    return out
