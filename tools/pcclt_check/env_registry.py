"""Checker ``env``: every ``PCCLT_*`` env var read by the code is documented,
and every env var the docs promise actually exists in the code.

Code side (reads only — tests/orchestrators SETTING vars is not an API):

  * native: string literals passed to ``getenv(...)`` or the ``env_*``
    helpers in ``pccl_tpu/native/{src,include}``;
  * Python: ``os.environ.get("PCCLT_X")`` / ``os.getenv("PCCLT_X")`` /
    ``os.environ["PCCLT_X"]`` reads (subscript writes excluded) under
    ``pccl_tpu/``, ``examples/``, ``tests/`` and ``bench.py``;
  * Python, helper-routed: an AST pass finds *env-reader helpers* —
    functions that forward a parameter into ``environ.get``/``getenv``
    (e.g. native_bench's ``_port(env, dflt)``), transitively — then
    harvests every ``PCCLT_*`` literal passed to (or defaulted into)
    that parameter, so knobs routed through wrappers stay visible.

A documented row also covers its suffixed per-leg variants: a read of
``PCCLT_BENCH_MASTER_PORT_WAN`` is satisfied by the
``PCCLT_BENCH_MASTER_PORT`` row when the suffix starts with a digit or
``_`` (the row documents the family; 18 near-identical rows would drown
the table).

Docs side: the env-var table in ``docs/03_api_overview.md`` (rows of the
form ``| `PCCLT_X` | default | meaning |``) is the registry of record.
Additionally, every ``PCCLT_*`` token mentioned anywhere in ``docs/`` or
``README.md`` must be either a known env var, a ``#define``d macro, a
CMake option (both harvested from the sources, so new macros never need a
checker edit), or a ``PCCLT_ATTR_*`` enum constant — anything else is a
stale or misspelled reference.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from . import Finding

DOC_TABLE = "docs/03_api_overview.md"

_NATIVE_READ = re.compile(
    r"(?:getenv|env_f|env_int|env_size|env_bool|env_double)"
    r"\s*\(\s*\"(PCCLT_[A-Z0-9_]+)\"")
_PY_READ = re.compile(
    r"(?:environ\.get|getenv)\s*\(\s*\"(PCCLT_[A-Z0-9_]+)\"")
_PY_SUBSCRIPT = re.compile(r"environ\[\s*\"(PCCLT_[A-Z0-9_]+)\"\s*\]\s*([=\w]?)")
_TOKEN = re.compile(r"\bPCCLT_[A-Z0-9_]+\b")


def _native_files(root: Path):
    native = root / "pccl_tpu" / "native"
    yield from sorted((native / "src").glob("*.[ch]pp"))
    yield from sorted((native / "include").glob("*.h"))


def _python_files(root: Path):
    for base in ("pccl_tpu", "examples", "tests"):
        d = root / base
        if d.is_dir():
            yield from sorted(p for p in d.rglob("*.py") if "native" not in p.parts)
    if (root / "bench.py").is_file():
        yield root / "bench.py"


def _is_env_read_call(node: ast.Call) -> bool:
    """environ.get(...) / os.getenv(...) / getenv(...)"""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "get" and isinstance(f.value, ast.Attribute) \
                and f.value.attr == "environ":
            return True
        if f.attr == "getenv":
            return True
    return isinstance(f, ast.Name) and f.id == "getenv"


def _helper_reads(tree: ast.Module) -> "list[tuple[str, int]]":
    """PCCLT_* names routed through env-reader helper functions.

    Fixpoint over this module: a function is an env reader at param `p`
    when its body passes `p` as the env-name argument of environ.get /
    getenv / another known reader.  Then every call site's literal for
    that argument, and the param's own default, count as reads.
    """
    funcs = {n.name: n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    readers: dict[str, str] = {}  # func name -> env-name param

    def reader_arg(call: ast.Call) -> "ast.expr | None":
        """The expression a call passes as the env-var name, if known."""
        if _is_env_read_call(call):
            return call.args[0] if call.args else None
        name = call.func.attr if isinstance(call.func, ast.Attribute) else (
            call.func.id if isinstance(call.func, ast.Name) else None)
        if name not in readers:
            return None
        param = readers[name]
        params = [a.arg for a in funcs[name].args.args] if name in funcs else []
        if param in params and len(call.args) > params.index(param):
            return call.args[params.index(param)]
        for kw in call.keywords:
            if kw.arg == param:
                return kw.value
        return None

    changed = True
    while changed:
        changed = False
        for fname, fn in funcs.items():
            if fname in readers:
                continue
            params = {a.arg for a in fn.args.args}
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                arg = reader_arg(call)
                if isinstance(arg, ast.Name) and arg.id in params:
                    readers[fname] = arg.id
                    changed = True
                    break

    out: list[tuple[str, int]] = []

    def note_literal(expr: "ast.expr | None") -> None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
                and re.fullmatch(r"PCCLT_[A-Z0-9_]+", expr.value):
            out.append((expr.value, expr.lineno))

    for call in ast.walk(tree):
        if isinstance(call, ast.Call):
            note_literal(reader_arg(call))
    for fname, param in readers.items():
        fn = funcs[fname]
        args, defaults = fn.args.args, fn.args.defaults
        for a, d in zip(args[len(args) - len(defaults):], defaults):
            if a.arg == param:
                note_literal(d)
    return out


def code_env_reads(root: Path) -> "dict[str, tuple[str, int]]":
    """env var -> first (repo-relative file, line) that reads it."""
    reads: dict[str, tuple[str, int]] = {}

    def note(var: str, path: Path, line: int) -> None:
        reads.setdefault(var, (str(path.relative_to(root)), line))

    for p in _native_files(root):
        for i, ln in enumerate(p.read_text().splitlines(), 1):
            for m in _NATIVE_READ.finditer(ln):
                note(m.group(1), p, i)
    for p in _python_files(root):
        text = p.read_text()
        for i, ln in enumerate(text.splitlines(), 1):
            for m in _PY_READ.finditer(ln):
                note(m.group(1), p, i)
            for m in _PY_SUBSCRIPT.finditer(ln):
                if m.group(2) != "=":  # subscript assignment is a write
                    note(m.group(1), p, i)
        try:
            for var, line in _helper_reads(ast.parse(text)):
                note(var, p, line)
        except SyntaxError:
            pass  # unparsable file: the regex pass above still applies
    return reads


def documented_vars(root: Path) -> "dict[str, int]":
    """Vars with a row in the docs/03 env table -> line number."""
    path = root / DOC_TABLE
    if not path.is_file():
        return {}
    out: dict[str, int] = {}
    for i, ln in enumerate(path.read_text().splitlines(), 1):
        m = re.match(r"\|\s*`(PCCLT_[A-Z0-9_]+)`\s*\|", ln)
        if m:
            out[m.group(1)] = i
    return out


def _non_env_tokens(root: Path) -> "set[str]":
    """PCCLT_* identifiers that are legitimately not env vars."""
    ok: set[str] = set()
    for p in _native_files(root):
        ok.update(re.findall(r"#define\s+(PCCLT_[A-Z0-9_]+)", p.read_text()))
    cml = root / "pccl_tpu" / "native" / "CMakeLists.txt"
    if cml.is_file():
        ok.update(re.findall(r"option\(\s*(PCCLT_[A-Z0-9_]+)", cml.read_text()))
    return ok


def check(root: Path) -> "list[Finding]":
    out: list[Finding] = []
    reads = code_env_reads(root)
    table = documented_vars(root)
    if not table:
        return [Finding("env", DOC_TABLE, 0,
                        "env-var table not found (rows like '| `PCCLT_X` | ...')")]

    def covered(var: str) -> bool:
        if var in table:
            return True
        # family rule: a row covers its suffixed per-leg variants
        # (PCCLT_BENCH_MASTER_PORT row covers ..._WAN, ...2, ...)
        return any(var.startswith(row) and var[len(row)] in "0123456789_"
                   for row in table if len(var) > len(row))

    for var, (path, line) in sorted(reads.items()):
        if not covered(var):
            out.append(Finding(
                "env", path, line,
                f"{var} is read here but has no row in the {DOC_TABLE} "
                "env-var table — document it (name | default | meaning)"))

    for var, line in sorted(table.items()):
        if var not in reads:
            out.append(Finding(
                "env", DOC_TABLE, line,
                f"{var} is documented but nothing in the tree reads it — "
                "stale row (or the reader was renamed/removed)"))

    # any other doc mention must be a known identifier class
    known = set(reads) | set(table) | _non_env_tokens(root)
    doc_files = sorted((root / "docs").glob("*.md")) if (root / "docs").is_dir() else []
    if (root / "README.md").is_file():
        doc_files.append(root / "README.md")
    for p in doc_files:
        rel = str(p.relative_to(root))
        for i, ln in enumerate(p.read_text().splitlines(), 1):
            for tok in _TOKEN.findall(ln):
                if tok in known or tok.startswith("PCCLT_ATTR_"):
                    continue
                # prefix mentions like "the PCCLT_WIRE_ maps" read as prose
                if tok.endswith("_"):
                    continue
                known.add(tok)  # report each unknown token once
                out.append(Finding(
                    "env", rel, i,
                    f"{tok} is mentioned here but is neither a code-read env "
                    "var, a #define, a CMake option, nor a PCCLT_ATTR_ "
                    "constant — stale or misspelled reference"))
    return out
