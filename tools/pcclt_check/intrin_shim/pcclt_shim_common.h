/* Parse-only x86 intrinsics shim for the pcclt-check thread-safety driver.
 *
 * The libclang wheel ships libclang.so but NOT clang's resource headers, so
 * the tsa checker (tools/pcclt_check/thread_safety.py) parses against GCC's
 * builtin include dir — whose <xmmintrin.h> family calls GCC-only
 * __builtin_ia32_* builtins clang does not implement. This shim shadows
 * those headers with just the declarations the pcclt tree uses, typed with
 * portable vector extensions, so the SIMD TUs (kernels.cpp,
 * kernels_avx2.cpp, hash_clmul.cpp) stay inside the analysis sweep.
 *
 * NEVER used for code generation: real builds (gcc via build_gcc.sh, clang
 * via -DPCCLT_ANALYZE=ON) use their toolchain's own intrinsic headers. The
 * semantics below are deliberately wrong (identity bodies) — only the
 * signatures matter to the parse. Extend it when a new intrinsic appears;
 * the tsa checker's parse error will point here.
 */
#ifndef PCCLT_CHECK_INTRIN_SHIM_H
#define PCCLT_CHECK_INTRIN_SHIM_H

typedef float __m128 __attribute__((__vector_size__(16), __aligned__(16)));
typedef long long __m128i __attribute__((__vector_size__(16), __aligned__(16)));
typedef double __m128d __attribute__((__vector_size__(16), __aligned__(16)));
typedef float __m256 __attribute__((__vector_size__(32), __aligned__(32)));
typedef long long __m256i __attribute__((__vector_size__(32), __aligned__(32)));

static inline __m128 _mm_loadu_ps(const float *p) { return *(const __m128 *)p; }
static inline void _mm_stream_ps(float *p, __m128 a) { *(__m128 *)p = a; }
static inline __m128 _mm_add_ps(__m128 a, __m128 b) { return a + b; }
/* clang predeclares _mm_sfence as a (non-static) library builtin, so a
 * static inline shim would clash; a macro sidesteps the declaration. */
#define _mm_sfence() ((void)0)
#define _MM_SHUFFLE(a, b, c, d) ((((a) << 6) | ((b) << 4) | ((c) << 2) | (d)))

static inline __m128i _mm_loadu_si128(const __m128i *p) { return *p; }
static inline void _mm_storeu_si128(__m128i *p, __m128i a) { *p = a; }
static inline void _mm_stream_si128(__m128i *p, __m128i a) { *p = a; }
static inline __m128i _mm_and_si128(__m128i a, __m128i b) { return a & b; }
static inline __m128i _mm_xor_si128(__m128i a, __m128i b) { return a ^ b; }
static inline __m128i _mm_set_epi32(int a, int b, int c, int d) {
    return (__m128i){(long long)a, (long long)d};
}
static inline __m128i _mm_cvtsi32_si128(int a) { return (__m128i){a, 0}; }
static inline int _mm_extract_epi32(__m128i a, int i) { return (int)a[0] + i; }
static inline __m128i _mm_srli_si128(__m128i a, int i) { return a; }
static inline __m128i _mm_clmulepi64_si128(__m128i a, __m128i b, int i) {
    return a ^ b;
}

static inline __m256 _mm256_add_ps(__m256 a, __m256 b) { return a + b; }
static inline __m256i _mm256_add_epi32(__m256i a, __m256i b) { return a + b; }
static inline __m256i _mm256_and_si256(__m256i a, __m256i b) { return a & b; }
static inline __m256i _mm256_castps_si256(__m256 a) { return (__m256i){0, 0, 0, 0}; }
static inline __m256 _mm256_castsi256_ps(__m256i a) { return (__m256){0, 0, 0, 0, 0, 0, 0, 0}; }
static inline __m128i _mm256_castsi256_si128(__m256i a) { return (__m128i){a[0], a[1]}; }
static inline __m256i _mm256_cvtepu16_epi32(__m128i a) { return (__m256i){a[0], a[1], 0, 0}; }
static inline __m256i _mm256_packus_epi32(__m256i a, __m256i b) { return a; }
static inline __m256i _mm256_permute4x64_epi64(__m256i a, int i) { return a; }
static inline __m256i _mm256_set1_epi32(int a) { return (__m256i){a, a, a, a}; }
static inline __m256i _mm256_setzero_si256(void) { return (__m256i){0, 0, 0, 0}; }
static inline __m256i _mm256_slli_epi32(__m256i a, int i) { return a; }
static inline __m256i _mm256_srli_epi32(__m256i a, int i) { return a; }
static inline __m256i _mm256_loadu_si256(const __m256i *p) { return *p; }
static inline void _mm256_storeu_si256(__m256i *p, __m256i a) { *p = a; }
static inline __m256 _mm256_loadu_ps(const float *p) { return *(const __m256 *)p; }
static inline void _mm256_storeu_ps(float *p, __m256 a) { *(__m256 *)p = a; }

#endif /* PCCLT_CHECK_INTRIN_SHIM_H */
