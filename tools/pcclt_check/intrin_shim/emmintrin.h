/* parse-only shim: see pcclt_shim_common.h */
#include "pcclt_shim_common.h"
