"""Checker ``guards``: single-threaded-by-design classes really enforce it.

Lock annotations (annotations.hpp) cover state that IS shared; the other
concurrency contract in the tree is the opposite claim — "only one thread
ever enters this state machine" — which is not expressible as a capability
and is enforced at runtime by ``pcclt::ThreadGuard`` (thread_guard.hpp).
This checker keeps the three pieces of that contract from drifting apart:

  * a class whose comment carries the canonical marker
    ``single-threaded by design`` must declare a ``ThreadGuard`` member
    (the claim without the tripwire is wishful thinking);
  * every declared ``ThreadGuard`` member must be checked — at least one
    ``PCCLT_THREAD_GUARD(<member>)`` call site in the sources (a guard
    nobody calls catches nothing);
  * every ``PCCLT_THREAD_GUARD(x)`` call must name a declared guard
    (catches a renamed member leaving a stale call).

The marker comment must sit within 8 lines above (or inside) the class it
describes.  See docs/11_static_analysis.md for the convention.
"""

from __future__ import annotations

import re
from pathlib import Path

from . import Finding

SRC = "pccl_tpu/native/src"
MARKER = re.compile(r"single-threaded by design", re.I)
GUARD_DECL = re.compile(r"\bThreadGuard\s+(\w+)\s*;")
GUARD_CALL = re.compile(r"PCCLT_THREAD_GUARD\(\s*(\w+)\s*\)")
CLASS_DECL = re.compile(r"^\s*(?:class|struct)\s+(\w+)")


def _enclosing_class(lines: "list[str]", idx: int) -> str:
    for j in range(idx, -1, -1):
        m = CLASS_DECL.match(lines[j])
        if m:
            return m.group(1)
    return "?"


def check(root: Path) -> "list[Finding]":
    out: list[Finding] = []
    src = root / SRC
    files = sorted(src.glob("*.[ch]pp"))
    if not files:
        return [Finding("guards", SRC, 0, "no native sources found")]

    all_text = {p: p.read_text() for p in files}
    # member -> every (file, line, class) declaring it: calls are matched by
    # bare member name (the macro call site carries no class), so a name
    # declared by TWO classes would let one class's call mask the other's
    # missing check — flagged below as ambiguity rather than guessed at
    decls: dict[str, list[tuple[str, int, str]]] = {}
    calls: dict[str, tuple[str, int]] = {}

    for p, text in all_text.items():
        if p.name == "thread_guard.hpp":
            continue  # the definition itself
        rel = str(p.relative_to(root))
        lines = text.splitlines()
        for i, ln in enumerate(lines):
            dm = GUARD_DECL.search(ln)
            if dm:
                decls.setdefault(dm.group(1), []).append(
                    (rel, i + 1, _enclosing_class(lines, i)))
            if "#define" not in ln:
                for cm in GUARD_CALL.finditer(ln):
                    calls.setdefault(cm.group(1), (rel, i + 1))

        # marker comment -> a class with a guard member must follow
        for i, ln in enumerate(lines):
            if "//" not in ln or not MARKER.search(ln):
                continue
            for j in range(i, min(i + 9, len(lines))):
                m = CLASS_DECL.match(lines[j])
                if m:
                    # the class body must declare a ThreadGuard member
                    depth, body = 0, []
                    for k in range(j, len(lines)):
                        body.append(lines[k])
                        depth += lines[k].count("{") - lines[k].count("}")
                        if depth == 0 and "{" in "".join(body):
                            break
                    if not GUARD_DECL.search("\n".join(body)):
                        out.append(Finding(
                            "guards", rel, j + 1,
                            f"class {m.group(1)} is marked 'single-threaded "
                            "by design' but declares no pcclt::ThreadGuard "
                            "member — the invariant is unenforced"))
                    break
            else:
                out.append(Finding(
                    "guards", rel, i + 1,
                    "'single-threaded by design' marker is attached to no "
                    "class declaration within 8 lines — move it onto the "
                    "class that owns the ThreadGuard"))

    for member, sites in sorted(decls.items()):
        if len(sites) > 1:
            where = ", ".join(f"{c} ({r}:{ln})" for r, ln, c in sites)
            out.append(Finding(
                "guards", sites[0][0], sites[0][1],
                f"ThreadGuard member {member!r} is declared by multiple "
                f"classes — {where}; calls are matched by bare name, so one "
                "class's check would mask the others' missing one. Give each "
                "guard a unique name."))
            continue
        rel, line, cls = sites[0]
        if member not in calls:
            out.append(Finding(
                "guards", rel, line,
                f"{cls}::{member} is a ThreadGuard nobody checks — add "
                f"PCCLT_THREAD_GUARD({member}) at the guarded entry point(s) "
                "or remove the member"))

    for member, (rel, line) in sorted(calls.items()):
        if member not in decls:
            out.append(Finding(
                "guards", rel, line,
                f"PCCLT_THREAD_GUARD({member}) names no declared ThreadGuard "
                "member — stale call after a rename?"))
    return out
