"""Checker ``protocol``: PCCP packet-id exhaustiveness across layers.

Parses the ``PacketType`` enum in ``protocol.hpp`` and verifies, for every
id, the invariants a new packet type must satisfy before it can work
end-to-end (each one has been violated by real drift at least once in
comparable codebases — an orphaned id compiles fine and fails at runtime):

  * id values are unique (a collision silently routes packets to the
    wrong handler);
  * every ``kC2M*`` id is sent somewhere in ``client.cpp`` AND has a
    ``case PacketType::kC2M...`` dispatch arm in ``master.cpp`` (the
    dispatcher that feeds MasterState);
  * every ``kM2C*`` id is emitted by ``master_state.cpp`` AND matched
    somewhere in ``client.cpp``;
  * every other id (``kP2P*``, ``kC2S*``/``kS2C*``, ``kBench*``) is
    referenced by at least one data-plane implementation file;
  * every payload struct declared with ``encode()`` in ``protocol.hpp``
    defines BOTH ``X::encode`` and ``X::decode`` in ``protocol.cpp``
    (serialize/deserialize parity).

The same exhaustiveness discipline covers the DATA-plane frame vocabulary
(``MultiplexConn::Kind`` in ``sockets.hpp`` — kData, the relay trio, the
chunk pair, CMA/shm control frames):

  * kind wire values are unique;
  * every kind has a real rx handler arm in ``sockets.cpp``'s rx_loop
    (kData is the pinned fall-through, marked ``// kData — sink fast
    path``) — an unhandled kind is dropped as garbage at the demux;
  * every kind has a ``case kX:`` arm in tx_loop's frame writer — a kind
    nobody can send is an orphan.

The deeper semantic diff (arm grouping, hook routing, ladder pinning)
lives in ``tools/pcclt_verify/dataplane_check.py``; this layer is the
cheap per-kind existence audit that runs with the other id checks.
"""

from __future__ import annotations

import re
from pathlib import Path

from . import Finding

SRC = "pccl_tpu/native/src"


def parse_packet_enum(text: str) -> "dict[str, tuple[int, int]]":
    """PacketType enumerators -> (value, line)."""
    m = re.search(r"enum PacketType[^{]*\{(.*?)\};", text, re.S)
    if not m:
        return {}
    body, start = m.group(1), m.start(1)
    out: dict[str, tuple[int, int]] = {}
    for em in re.finditer(r"(k\w+)\s*=\s*(0[xX][0-9a-fA-F]+|\d+)", body):
        line = text.count("\n", 0, start + em.start()) + 1
        out[em.group(1)] = (int(em.group(2), 0), line)
    return out


def parse_frame_kinds(text: str) -> "dict[str, tuple[int, int]]":
    """MultiplexConn::Kind enumerators -> (value, line) from sockets.hpp."""
    m = re.search(r"enum\s+Kind\s*:\s*uint8_t\s*\{(.*?)\};", text, re.S)
    if not m:
        return {}
    body, start = m.group(1), m.start(1)
    out: dict[str, tuple[int, int]] = {}
    for em in re.finditer(r"(k\w+)\s*=\s*(\d+)", body):
        line = text.count("\n", 0, start + em.start()) + 1
        out[em.group(1)] = (int(em.group(2)), line)
    return out


def check(root: Path) -> "list[Finding]":
    out: list[Finding] = []
    src = root / SRC
    hpp = src / "protocol.hpp"
    if not hpp.is_file():
        return [Finding("protocol", f"{SRC}/protocol.hpp", 0, "file missing")]
    htext = hpp.read_text()
    ids = parse_packet_enum(htext)
    if not ids:
        return [Finding("protocol", f"{SRC}/protocol.hpp", 0,
                        "could not parse the PacketType enum")]

    # --- unique values ---
    by_val: dict[int, str] = {}
    for name, (val, line) in ids.items():
        if val in by_val:
            out.append(Finding(
                "protocol", f"{SRC}/protocol.hpp", line,
                f"{name} reuses packet id 0x{val:04X} already taken by "
                f"{by_val[val]} — collisions dispatch to the wrong handler"))
        else:
            by_val[val] = name

    def text_of(name: str) -> str:
        p = src / name
        return p.read_text() if p.is_file() else ""

    client = text_of("client.cpp")
    master = text_of("master.cpp")
    master_state = text_of("master_state.cpp")
    dataplane = "\n".join(
        text_of(n) for n in ("client.cpp", "sockets.cpp", "benchmark.cpp"))

    def used(text: str, ident: str) -> bool:
        return re.search(rf"\b{ident}\b", text) is not None

    for name, (_val, line) in ids.items():
        if name.startswith("kC2M"):
            if not used(client, name):
                out.append(Finding(
                    "protocol", f"{SRC}/protocol.hpp", line,
                    f"{name} is never sent by client.cpp — orphaned "
                    "client->master id (remove it or wire the sender)"))
            if not re.search(rf"case\s+PacketType::{name}\b", master):
                out.append(Finding(
                    "protocol", f"{SRC}/protocol.hpp", line,
                    f"{name} has no dispatch arm in master.cpp's packet "
                    "switch — the master would drop it as unknown"))
        elif name.startswith("kM2C"):
            if not used(master_state, name):
                out.append(Finding(
                    "protocol", f"{SRC}/protocol.hpp", line,
                    f"{name} is never emitted by master_state.cpp — "
                    "orphaned master->client id"))
            if not used(client, name):
                out.append(Finding(
                    "protocol", f"{SRC}/protocol.hpp", line,
                    f"{name} is never matched in client.cpp — the client "
                    "would never consume it"))
        else:
            if not used(dataplane, name):
                out.append(Finding(
                    "protocol", f"{SRC}/protocol.hpp", line,
                    f"{name} is referenced by no data-plane file "
                    "(client/sockets/benchmark) — orphaned id"))

    # --- data-plane frame kinds (MultiplexConn::Kind) ---
    sockets_hpp = text_of("sockets.hpp")
    sockets_cpp = text_of("sockets.cpp")
    kinds = parse_frame_kinds(sockets_hpp)
    if not kinds:
        out.append(Finding(
            "protocol", f"{SRC}/sockets.hpp", 0,
            "could not parse `enum Kind : uint8_t` — the data-plane frame "
            "vocabulary moved; realign parse_frame_kinds"))
    kind_vals: dict[int, str] = {}
    for name, (val, line) in sorted(kinds.items(), key=lambda kv: kv[1]):
        if val in kind_vals:
            out.append(Finding(
                "protocol", f"{SRC}/sockets.hpp", line,
                f"frame kind {name} reuses wire value {val} already taken "
                f"by {kind_vals[val]} — the demux would misroute frames"))
        else:
            kind_vals[val] = name
        # rx: a dispatch condition per kind; kData is the pinned
        # fall-through after every `kind ==` test fails
        if name == "kData":
            if "// kData — sink fast path" not in sockets_cpp:
                out.append(Finding(
                    "protocol", f"{SRC}/sockets.cpp", 0,
                    "rx_loop's kData fall-through lost its '// kData — "
                    "sink fast path' marker — restore it where the sink "
                    "fast path begins"))
        elif not re.search(rf"kind == {name}\b", sockets_cpp):
            out.append(Finding(
                "protocol", f"{SRC}/sockets.hpp", line,
                f"frame kind {name} has no `kind == {name}` rx handler arm "
                "in sockets.cpp — inbound frames of this kind are dropped "
                "as garbage"))
        # tx: every kind must be sendable through tx_loop's frame writer
        if not re.search(rf"case {name}:", sockets_cpp):
            out.append(Finding(
                "protocol", f"{SRC}/sockets.hpp", line,
                f"frame kind {name} has no `case {name}:` arm in "
                "sockets.cpp's tx_loop — an orphaned kind nobody can send"))

    # --- encode/decode parity for typed payloads ---
    proto_cpp = text_of("protocol.cpp")
    declared = set(re.findall(
        r"struct (\w+)\s*\{[^{}]*?encode\(\) const;", htext, re.S))
    for struct in sorted(declared):
        has_enc = re.search(rf"\b{struct}::encode\b", proto_cpp)
        has_dec = re.search(rf"\b{struct}::decode\b", proto_cpp)
        if not has_enc or not has_dec:
            missing = "encode" if not has_enc else "decode"
            out.append(Finding(
                "protocol", f"{SRC}/protocol.cpp", 0,
                f"{struct} declares encode()/decode() in protocol.hpp but "
                f"protocol.cpp defines no {struct}::{missing} — "
                "serialize/deserialize drift"))
    return out
