"""Standalone master runner: ``python -m pccl_tpu.comm.master --port 48500``.

Reference parity: the reference ships both a ccoip_master binary
(/root/reference/ccoip_master/src/main.cpp) and a python master wrapper
(/root/reference/python/framework/pccl/master.py). The native equivalent
binary here is pccl_tpu/native/build/pcclt_master; this module is the
python-side runner for environments that only have the shared library.

``--journal PATH`` enables master HA: state is write-ahead-logged to PATH
and a restarted master pointed at the same journal resumes the same world
view under a bumped epoch — clients session-resume instead of
re-registering (docs/10_high_availability.md).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

from .api import MasterNode


def main() -> int:
    ap = argparse.ArgumentParser(description="pccl_tpu master node")
    ap.add_argument("--listen", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=48500)
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="HA journal path (restart on the same journal = "
                         "resume the world, not reset it); default: the "
                         "PCCLT_MASTER_JOURNAL env var, else disabled")
    ap.add_argument("--metrics-port", default=None, metavar="PORT",
                    help="serve plain-HTTP /metrics (Prometheus) + /health "
                         "(JSON) on this port (0 = kernel-assigned); "
                         "default: the PCCLT_MASTER_METRICS_PORT env var, "
                         "else disabled (docs/09_observability.md)")
    args = ap.parse_args()

    if args.metrics_port is not None:
        # the native core reads the env at pccltRunMaster
        os.environ["PCCLT_MASTER_METRICS_PORT"] = str(args.metrics_port)
    m = MasterNode(args.listen, args.port, journal_path=args.journal)
    m.run()
    extra = f", metrics on :{m.metrics_port}" if m.metrics_port else ""
    print(f"master listening on {args.listen}:{m.port} (epoch {m.epoch}"
          f"{extra})", flush=True)

    # sigwait instead of a signal handler: a handler would never run while
    # the main thread is blocked inside the foreign await_termination call
    # (ctypes pthread join), so Ctrl-C would hang the process. The signals
    # must be blocked first or their default disposition still terminates.
    signal.pthread_sigmask(signal.SIG_BLOCK, {signal.SIGINT, signal.SIGTERM})
    signal.sigwait({signal.SIGINT, signal.SIGTERM})
    m.interrupt()
    m.await_termination()
    m.destroy()
    return 0


if __name__ == "__main__":
    sys.exit(main())
