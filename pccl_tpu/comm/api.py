"""High-level Python API over the native core.

Reference parity: python/framework/pccl/_pccl.py of the reference —
Communicator, MasterNode, TensorInfo (from_numpy/from_torch, plus from_jax
here), SharedState, AsyncReduceHandle, ReduceOperandDescriptor — with the
same fault-tolerance contract: collective ops raise PcclError subclasses on
peer churn and the caller retries after update_topology() (reference
README.md:90-130 loop).

TPU note: jax.Array buffers are immutable and may live in HBM; TensorInfo
.from_jax stages to a pinned host copy, and jax_value() returns the synced
content as a fresh device array. The hierarchical ICI+WAN path lives in
pccl_tpu.parallel.hierarchical.
"""

from __future__ import annotations

import ctypes
import enum
import threading
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

from . import _native


class Result(enum.IntEnum):
    SUCCESS = 0
    INVALID_ARGUMENT = 1
    NOT_CONNECTED = 2
    CONNECTION_LOST = 3
    OPERATION_ABORTED = 4
    TOO_FEW_PEERS = 5
    DUPLICATE_TAG = 6
    KICKED = 7
    MASTER_UNREACHABLE = 8
    INTERNAL_ERROR = 9
    CONTENT_MISMATCH = 10
    PENDING_ASYNC_OPS = 11
    INVALID_USAGE = 12


class DataType(enum.IntEnum):
    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    UINT32 = 4
    INT32 = 5
    UINT64 = 6
    INT64 = 7
    FLOAT16 = 8
    BFLOAT16 = 9
    FLOAT32 = 10
    FLOAT64 = 11


class DeviceType(enum.IntEnum):
    HOST = 0
    TPU = 1


class ReduceOp(enum.IntEnum):
    SUM = 0
    AVG = 1
    PROD = 2
    MAX = 3
    MIN = 4


class QuantizationAlgorithm(enum.IntEnum):
    NONE = 0
    MIN_MAX = 1
    ZERO_POINT_SCALE = 2


class SharedStateSyncStrategy(enum.IntEnum):
    ENFORCE_POPULAR = 0
    RECEIVE_ONLY = 1
    SEND_ONLY = 2


class Attribute(enum.IntEnum):
    GLOBAL_WORLD_SIZE = 0
    PEER_GROUP_WORLD_SIZE = 1
    NUM_DISTINCT_PEER_GROUPS = 2
    LARGEST_PEER_GROUP_WORLD_SIZE = 3
    # master HA (docs/10_high_availability.md)
    MASTER_EPOCH = 4
    RECONNECT_COUNT = 5
    SHARED_STATE_REVISION = 6


_NP_TO_DTYPE = {
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.uint16): DataType.UINT16,
    np.dtype(np.int16): DataType.INT16,
    np.dtype(np.uint32): DataType.UINT32,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.uint64): DataType.UINT64,
    np.dtype(np.int64): DataType.INT64,
    np.dtype(np.float16): DataType.FLOAT16,
    np.dtype(np.float32): DataType.FLOAT32,
    np.dtype(np.float64): DataType.FLOAT64,
}


def _np_dtype_of(arr: np.ndarray) -> DataType:
    # ml_dtypes.bfloat16 arrays (jax host staging) are not in the static map
    if arr.dtype.name == "bfloat16":
        return DataType.BFLOAT16
    try:
        return _NP_TO_DTYPE[arr.dtype]
    except KeyError:
        raise ValueError(f"unsupported dtype {arr.dtype}") from None


_DTYPE_ITEMSIZE = {
    DataType.UINT8: 1, DataType.INT8: 1,
    DataType.UINT16: 2, DataType.INT16: 2,
    DataType.UINT32: 4, DataType.INT32: 4,
    DataType.UINT64: 8, DataType.INT64: 8,
    DataType.FLOAT16: 2, DataType.BFLOAT16: 2,
    DataType.FLOAT32: 4, DataType.FLOAT64: 8,
}


# ---------------------------------------------------------------- exceptions

class PcclError(RuntimeError):
    """Base error; .result carries the native status code."""

    def __init__(self, result: Result, what: str = ""):
        self.result = Result(result)
        super().__init__(f"{self.result.name}{': ' + what if what else ''}")


class ConnectionLostError(PcclError):
    """A peer died mid-op; re-establish with update_topology() and retry."""


class OperationAbortedError(PcclError):
    """The op was aborted group-wide; retry after update_topology()."""


class TooFewPeersError(PcclError):
    """world < 2 — wait for peers to join, then retry."""


class KickedError(PcclError):
    """The master kicked this peer (protocol violation or state mismatch)."""


class MasterUnreachableError(PcclError):
    pass


def _check(code: int, what: str = "") -> None:
    if code == Result.SUCCESS:
        return
    r = Result(code)
    cls = {
        Result.CONNECTION_LOST: ConnectionLostError,
        Result.OPERATION_ABORTED: OperationAbortedError,
        Result.TOO_FEW_PEERS: TooFewPeersError,
        Result.KICKED: KickedError,
        Result.MASTER_UNREACHABLE: MasterUnreachableError,
    }.get(r, PcclError)
    raise cls(r, what)


# ------------------------------------------------- registered shm buffers

def shm_ndarray(shape, dtype=np.float32) -> np.ndarray:
    """Allocate a numpy array in a REGISTERED shared-memory region
    (pccltShmAlloc). Collectives whose payload lives in a registered region
    take the same-host zero-copy path: local peers map the region and reduce
    straight out of it, skipping even the one-copy CMA pull. Use for
    communication-heavy staging tensors (DiLoCo outer-step flats, bench
    buffers); ordinary arrays work with every op regardless.

    The region is freed when the returned array (and all its views) are
    garbage collected. pcclt extension — the reference (jundi69/pccl) always
    streams payloads over TCP and has no registered-buffer concept.
    """
    import weakref

    lib = _native.load()
    shape = tuple(np.atleast_1d(np.asarray(shape, dtype=np.int64)).tolist()) \
        if not isinstance(shape, (tuple, list)) else tuple(int(s) for s in shape)
    nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    ptr = ctypes.c_void_p()
    _check(lib.pccltShmAlloc(max(1, nbytes), ctypes.byref(ptr)), "shm alloc")
    buf = (ctypes.c_uint8 * max(1, nbytes)).from_address(ptr.value)
    weakref.finalize(buf, lib.pccltShmFree, ctypes.c_void_p(ptr.value))
    return np.ndarray(shape, dtype=dtype, buffer=buf)

# ---------------------------------------------------- flight-recorder trace

def trace_enable(on: bool = True) -> None:
    """Toggle the native flight recorder's event capture at runtime
    (process-global; see docs/09_observability.md). Counters —
    ``Communicator.stats()`` — are always on; this gates only the event
    ring feeding ``trace_events()`` / ``trace_dump()``. ``PCCLT_TRACE=path``
    in the environment enables capture at load and dumps at process exit."""
    lib = _native.load()
    _check(lib.pccltTraceEnable(1 if on else 0), "trace enable")


def trace_clear() -> None:
    """Drop every captured event (isolates multi-phase runs sharing one
    process, e.g. consecutive bench legs)."""
    lib = _native.load()
    _check(lib.pccltTraceClear(), "trace clear")


def trace_dump(path: str) -> None:
    """Write the recorder's event ring as Chrome trace-event JSON (load in
    chrome://tracing or ui.perfetto.dev). Timestamps are CLOCK_MONOTONIC
    microseconds — merge with Python profiler sections via
    Profiler.export_chrome_trace(..., native_events=...)."""
    lib = _native.load()
    _check(lib.pccltTraceDump(path.encode()), "trace dump")


def netem_inject(endpoint: str, spec: str) -> None:
    """Arm a time-scripted chaos fault schedule on the wire-emulation edge
    toward ``endpoint`` ("ip:port"), offsets relative to NOW — e.g.
    ``"degrade@t=0s:40mbit/8s"``, ``"flap@t=1s:200msx5"``,
    ``"blackhole@t=0s:2s"`` (';'-separate multiple faults). Mirrors
    ``pccltNetemInject``; see docs/05_fault_tolerance.md for the grammar
    and the live-connection caveat. An empty spec disarms the edge."""
    lib = _native.load()
    _check(lib.pccltNetemInject(endpoint.encode(), spec.encode()),
           "netem inject")


def trace_events() -> list:
    """The native recorder's current events as a list of Chrome trace-event
    dicts (the parsed form of trace_dump's output)."""
    import json
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        tmp = f.name
    try:
        trace_dump(tmp)
        with open(tmp) as f:
            return json.load(f)["traceEvents"]
    finally:
        import os

        try:
            os.unlink(tmp)
        except OSError:
            pass


class MasterNode:
    """Standalone orchestration master (reference: pccl.MasterNode /
    the ccoip_master binary). Control plane only — bulk data never flows
    through it.

    ``journal_path`` enables master HA: authoritative state (registrations,
    membership, ring order, shared-state revision, bandwidth matrix) is
    write-ahead-logged there, and a later ``MasterNode`` pointed at the same
    journal resumes the same world view under a bumped :attr:`epoch` —
    clients re-attach via session resume instead of re-registering
    (docs/10_high_availability.md). ``None`` falls back to the
    ``PCCLT_MASTER_JOURNAL`` env var; pass ``""`` to force-disable."""

    def __init__(self, listen_address: str = "0.0.0.0", port: int = 48501,
                 journal_path: Optional[str] = None):
        self._lib = _native.load()
        handle = ctypes.c_void_p()
        if journal_path is not None and not hasattr(self._lib,
                                                    "pccltCreateMasterEx"):
            raise PcclError(Result.INVALID_USAGE,
                            "this libpcclt.so predates master HA "
                            "(pccltCreateMasterEx); rebuild the native core")
        if hasattr(self._lib, "pccltCreateMasterEx"):
            _check(self._lib.pccltCreateMasterEx(
                listen_address.encode(), port,
                journal_path.encode() if journal_path is not None else None,
                ctypes.byref(handle)), "create master")
        else:
            _check(self._lib.pccltCreateMaster(listen_address.encode(), port,
                                               ctypes.byref(handle)),
                   "create master")
        self._h = handle
        self._ran = False

    def run(self) -> None:
        _check(self._lib.pccltRunMaster(self._h), "run master")
        self._ran = True

    @property
    def port(self) -> int:
        return int(self._lib.pccltMasterPort(self._h))

    @property
    def epoch(self) -> int:
        """This incarnation's epoch: 1 fresh (or journal-less), +1 on every
        journaled restart. Valid after run()."""
        if not hasattr(self._lib, "pccltMasterEpoch"):
            return 0
        return int(self._lib.pccltMasterEpoch(self._h))

    @property
    def metrics_port(self) -> int:
        """Bound port of the plain-HTTP ``/metrics`` (Prometheus text) +
        ``/health`` (JSON) endpoint — enabled by the
        ``PCCLT_MASTER_METRICS_PORT`` env var (``"0"`` = kernel-assigned,
        read the real port here). 0 while disabled or before run()."""
        if not hasattr(self._lib, "pccltMasterMetricsPort"):
            return 0
        return int(self._lib.pccltMasterMetricsPort(self._h))

    def health(self) -> dict:
        """The master's fleet health model as a dict (the ``/health`` JSON:
        epoch, world/client/limbo counts, per-peer digest freshness and
        per-edge EWMA throughput/stall with straggler flags). Works with
        the HTTP endpoint disabled — this reads the native state directly.
        Peers appear once they push telemetry digests
        (``PCCLT_TELEMETRY_PUSH_MS``); see docs/09_observability.md."""
        import json

        if not hasattr(self._lib, "pccltMasterGetHealth"):
            raise PcclError(Result.INVALID_USAGE,
                            "this libpcclt.so predates the observability "
                            "plane (pccltMasterGetHealth); rebuild")
        need = ctypes.c_uint64()
        _check(self._lib.pccltMasterGetHealth(self._h, None, 0,
                                              ctypes.byref(need)), "health")
        # size-then-fetch can race live digests growing the document: the
        # copy call re-reports the true length, so retry until it fits
        for _ in range(8):
            cap = need.value + 256  # slack absorbs small growth in one trip
            buf = ctypes.create_string_buffer(cap)
            _check(self._lib.pccltMasterGetHealth(self._h, buf, cap,
                                                  ctypes.byref(need)),
                   "health")
            if need.value < cap:
                return json.loads(buf.value.decode())
        raise PcclError(Result.INTERNAL_ERROR,
                        "health document kept outgrowing its buffer")

    def interrupt(self) -> None:
        _check(self._lib.pccltInterruptMaster(self._h))

    def await_termination(self) -> None:
        _check(self._lib.pccltMasterAwaitTermination(self._h))

    def destroy(self) -> None:
        if self._h:
            self._lib.pccltDestroyMaster(self._h)
            self._h = None

    def __enter__(self) -> "MasterNode":
        self.run()
        return self

    def __exit__(self, *exc) -> None:
        self.interrupt()
        self.destroy()

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass


# ---------------------------------------------------------------- tensors

@dataclass
class TensorInfo:
    """One named shared-state entry (reference: pccl.TensorInfo,
    _pccl.py:350-372). Keeps the backing buffer alive."""

    name: str
    data: np.ndarray                  # host buffer the native core reads/writes
    dtype: DataType
    device: DeviceType = DeviceType.HOST
    allow_content_inequality: bool = False
    _source: Any = field(default=None, repr=False)  # torch tensor / jax array
    # device-hash path (from_jax_device): hash computed on the accelerator,
    # host staging deferred until the native core actually serves the bytes
    _precomputed_hash: Any = field(default=None, repr=False)
    _materialize_cb: Any = field(default=None, repr=False)  # keepalive
    _updated: bool = field(default=False, repr=False)

    @staticmethod
    def from_numpy(name: str, arr: np.ndarray,
                   allow_content_inequality: bool = False) -> "TensorInfo":
        if not arr.flags["C_CONTIGUOUS"]:
            raise ValueError("array must be C-contiguous")
        if not arr.flags["WRITEABLE"]:
            raise ValueError("array must be writable (sync writes into it)")
        return TensorInfo(name, arr, _np_dtype_of(arr), DeviceType.HOST,
                          allow_content_inequality)

    @staticmethod
    def from_torch(name: str, tensor,
                   allow_content_inequality: bool = False) -> "TensorInfo":
        if tensor.device.type != "cpu":
            raise ValueError("torch tensor must be on CPU (stage accelerator "
                             "state via .cpu() or use from_jax for TPU arrays)")
        arr = tensor.detach().numpy()
        ti = TensorInfo.from_numpy(name, arr, allow_content_inequality)
        ti._source = tensor  # in-place: numpy view shares storage
        return ti

    @staticmethod
    def from_jax(name: str, arr,
                 allow_content_inequality: bool = False) -> "TensorInfo":
        """Stage a jax.Array to a host copy. After sync_shared_state, read the
        (possibly updated) content back with .jax_value()."""
        host = np.asarray(arr)
        if not host.flags["WRITEABLE"]:
            host = host.copy()
        ti = TensorInfo(name, host, _np_dtype_of(host), DeviceType.TPU,
                        allow_content_inequality)
        ti._source = arr
        return ti

    @staticmethod
    def from_jax_device(name: str, arr,
                        allow_content_inequality: bool = False
                        ) -> "TensorInfo":
        """TPU-resident entry whose content hash is computed ON DEVICE
        (ops.hashing.jax_simplehash_device — 8 bytes cross to the host);
        the array is staged to the host ONLY if the sync actually needs
        the bytes (this peer is elected distributor, via the native
        materialize callback, or the entry arrives outdated). A clean
        sync of N gigabytes therefore moves 8 bytes instead of N — the
        invariant the reference preserves by hashing CUDA buffers on-GPU
        (/root/reference/ccoip/src/cuda/simplehash_cuda.cu).

        Requires PCCLT_SS_HASH=simple-tpu group-wide (the one hash type a
        TPU can compute over resident bytes); raises otherwise so a
        mismatched configuration fails loudly instead of looping forever
        on phantom hash drift. After sync, read the authoritative value
        with .jax_value() (device content unless the sync updated it)."""
        import os

        from ..ops.hashing import jax_simplehash_device

        if os.environ.get("PCCLT_SS_HASH") != "simple-tpu":
            raise RuntimeError(
                "TensorInfo.from_jax_device needs PCCLT_SS_HASH=simple-tpu "
                "(every peer of the group must hash with the TPU-computable "
                "type); set the env var or use from_jax for staged syncs")
        host = np.empty(arr.shape, arr.dtype)   # unmaterialized until needed
        ti = TensorInfo(name, host, _np_dtype_of(host), DeviceType.TPU,
                        allow_content_inequality)
        ti._source = arr
        lazy = True
        if not allow_content_inequality:
            try:
                ti._precomputed_hash = jax_simplehash_device(arr)
            except ValueError:
                # 8-byte dtypes have no device word stream (TPUs run 32-bit
                # ints); fall back to eager staging + the host twin of the
                # SAME hash type, so the group-wide digest still agrees
                from ..ops.hashing import simplehash_tpu

                np.copyto(host, np.asarray(arr))
                ti._precomputed_hash = simplehash_tpu(host)
                lazy = False

        if lazy:
            def _materialize(_ctx):
                # called from a native serving thread (ctypes re-acquires
                # the GIL); one staging D2H, exactly once per sync window
                np.copyto(host, np.asarray(ti._source))

            ti._materialize_cb = _native.MaterializeFn(_materialize)
        return ti

    def jax_value(self):
        """Device array with the current authoritative content: the synced
        host bytes when the sync wrote any (or for staged entries, which
        always hold current content), else the untouched device array."""
        import jax

        if self._materialize_cb is not None and not self._updated:
            # lazy entry the sync never wrote to: the host buffer may be
            # unmaterialized garbage — the device array is authoritative
            return self._source
        if self._source is not None and hasattr(self._source, "sharding"):
            return jax.device_put(self.data, self._source.sharding)
        return jax.device_put(self.data)

    def _as_c(self, keepalive: list) -> _native.TensorInfoC:
        name_b = self.name.encode()
        keepalive.append(name_b)
        has_h = self._precomputed_hash is not None
        if self._materialize_cb is not None:
            keepalive.append(self._materialize_cb)
        return _native.TensorInfoC(
            name=name_b,
            data=self.data.ctypes.data_as(ctypes.c_void_p),
            count=self.data.size,
            dtype=int(self.dtype),
            device=int(self.device),
            allow_content_inequality=1 if self.allow_content_inequality else 0,
            precomputed_hash=self._precomputed_hash if has_h else 0,
            has_precomputed_hash=1 if has_h else 0,
            materialize=self._materialize_cb if self._materialize_cb
            else _native.MaterializeFn(),
            materialize_ctx=None,
            updated=0,
        )


@dataclass
class SharedState:
    """Revisioned named tensor set, synced bit-identically across peers
    (reference: pccl.SharedState, _pccl.py:373-421)."""

    infos: Sequence[TensorInfo]
    revision: int = 0


@dataclass
class SharedStateSyncInfo:
    tx_bytes: int
    rx_bytes: int
    revision: int


@dataclass
class ReduceInfo:
    tx_bytes: int
    rx_bytes: int
    world_size: int


@dataclass
class ReduceDescriptor:
    """Per-op config: wire tag, reduction, optional on-the-wire quantization
    (reference pcclReduceDescriptor_t, pccl.h:140-168)."""

    tag: int = 0
    op: ReduceOp = ReduceOp.SUM
    quantization: QuantizationAlgorithm = QuantizationAlgorithm.NONE
    quantized_dtype: DataType = DataType.UINT8

    def _as_c(self) -> _native.ReduceDescriptor:
        return _native.ReduceDescriptor(
            tag=self.tag, op=int(self.op), quant_algo=int(self.quantization),
            quant_dtype=int(self.quantized_dtype))


class AsyncReduceHandle:
    """Handle for an in-flight all-reduce (reference: _pccl.py:422-459).
    Holds buffer references so the native op never outlives its memory."""

    def __init__(self, comm: "Communicator", tag: int, keepalive: tuple):
        self._comm = comm
        self._tag = tag
        self._keepalive = keepalive
        self._done = False

    def wait(self) -> ReduceInfo:
        if self._done:
            raise PcclError(Result.INVALID_USAGE, "handle already awaited")
        self._done = True
        info = _native.ReduceInfo()
        code = self._comm._lib.pccltAwaitAsyncReduce(
            self._comm._h, self._tag, ctypes.byref(info))
        self._keepalive = ()
        _check(code, f"await reduce tag={self._tag}")
        return ReduceInfo(info.tx_bytes, info.rx_bytes, info.world_size)


# ---------------------------------------------------------------- communicator

class Communicator:
    """One peer of the collective (reference: pccl.Communicator,
    _pccl.py:460-813).

    Usage mirrors the reference loop (README.md:90-130):

        comm = Communicator("10.0.0.1", 48501)
        comm.connect()
        while training:
            comm.update_topology()          # admit joiners / adopt new ring
            comm.optimize_topology()        # optional: bandwidth-aware ring
            try:
                comm.all_reduce(grads, op=ReduceOp.AVG)
            except (ConnectionLostError, OperationAbortedError):
                continue                    # world shrank; retry
    """

    def __init__(self, master_ip: str, master_port: int = 48501, *,
                 peer_group: int = 0, advertised_ip: Optional[str] = None,
                 p2p_port: int = 0, ss_port: int = 0, bench_port: int = 0,
                 p2p_connection_pool_size: int = 1,
                 reconnect_attempts: Optional[int] = None,
                 reconnect_backoff_ms: int = 0,
                 reconnect_backoff_cap_ms: int = 0):
        """``reconnect_*`` tune master-HA session resume: on a lost master
        link the client retries with bounded exponential backoff + jitter
        (keeping p2p connections alive) and re-attaches under its old UUID
        against a journaled master. ``reconnect_attempts`` ``None`` = env
        ``PCCLT_RECONNECT_ATTEMPTS`` (default 8), ``0`` disables; backoff
        ms fields default to env ``PCCLT_RECONNECT_BACKOFF_MS`` (100) /
        ``PCCLT_RECONNECT_MAX_BACKOFF_MS`` (2000). See
        docs/10_high_availability.md."""
        self._lib = _native.load()
        params = _native.CommCreateParams(
            master_ip=master_ip.encode(),
            master_port=master_port,
            peer_group=peer_group,
            advertised_ip=advertised_ip.encode() if advertised_ip else None,
            p2p_port=p2p_port,
            ss_port=ss_port,
            bench_port=bench_port,
            p2p_connection_pool_size=p2p_connection_pool_size,
            reconnect_attempts=(-1 if reconnect_attempts is None
                                else reconnect_attempts),
            reconnect_backoff_ms=reconnect_backoff_ms,
            reconnect_backoff_cap_ms=reconnect_backoff_cap_ms,
        )
        handle = ctypes.c_void_p()
        _check(self._lib.pccltCreateCommunicator(ctypes.byref(params),
                                                 ctypes.byref(handle)))
        self._h = handle
        self._tag_lock = threading.Lock()
        self._next_tag = self._AUTO_TAG_BASE

    # -- lifecycle --

    def connect(self) -> None:
        _check(self._lib.pccltConnect(self._h), "connect")

    def destroy(self) -> None:
        if self._h:
            self._lib.pccltDestroyCommunicator(self._h)
            self._h = None

    def __enter__(self) -> "Communicator":
        self.connect()
        return self

    def __exit__(self, *exc) -> None:
        self.destroy()

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass

    # -- membership / topology --

    def get_attribute(self, attr: Attribute) -> int:
        out = ctypes.c_int64()
        _check(self._lib.pccltGetAttribute(self._h, int(attr), ctypes.byref(out)))
        return out.value

    @property
    def world_size(self) -> int:
        return self.get_attribute(Attribute.PEER_GROUP_WORLD_SIZE)

    @property
    def global_world_size(self) -> int:
        return self.get_attribute(Attribute.GLOBAL_WORLD_SIZE)

    @property
    def num_peer_groups(self) -> int:
        return self.get_attribute(Attribute.NUM_DISTINCT_PEER_GROUPS)

    @property
    def largest_peer_group(self) -> int:
        """Largest group's world size — with num_peer_groups, the grid
        fullness check: global == num_groups * largest (docs 07)."""
        return self.get_attribute(Attribute.LARGEST_PEER_GROUP_WORLD_SIZE)

    @property
    def master_epoch(self) -> int:
        """The master epoch observed at welcome / last session resume. A
        journaled master bumps its epoch on every restart, so a change here
        = 'the master restarted under us and we resumed'."""
        return self.get_attribute(Attribute.MASTER_EPOCH)

    @property
    def reconnect_count(self) -> int:
        """How many times this communicator resumed its master session
        (HA blips absorbed without re-registering)."""
        return self.get_attribute(Attribute.RECONNECT_COUNT)

    @property
    def shared_state_revision(self) -> int:
        """Last shared-state revision known COMPLETE group-wide (from a
        sync Done, or the resume ack after a master restart). If a sync
        raised and this already covers its revision, the round finished
        just before the crash — skip the retry instead of wedging the
        group on a revision disagreement."""
        return self.get_attribute(Attribute.SHARED_STATE_REVISION)

    def update_topology(self) -> None:
        _check(self._lib.pccltUpdateTopology(self._h), "update topology")

    # -- telemetry --

    def stats(self) -> dict:
        """Flight-recorder counter snapshot for THIS communicator:

            {"counters": {collectives_ok, collectives_aborted, ...},
             "edges": {"ip:port": {tx_bytes, rx_bytes, tx_frames,
                                   rx_frames, connects, stall_ms,
                                   tx_zc_frames, tx_zc_reaps}, ...}}

        Edge keys are canonical remote endpoints (the peer's advertised
        p2p listen endpoint — the same key netem's PCCLT_WIRE_*_MAP uses).
        Counters are monotonic since connect and always on; see
        docs/09_observability.md for field semantics."""
        cs = _native.CommStats()
        _check(self._lib.pccltCommGetStats(self._h, ctypes.byref(cs)), "stats")
        counters = {name: int(getattr(cs, name)) for name, _ in cs._fields_}
        n = ctypes.c_uint64()
        _check(self._lib.pccltCommGetEdgeStats(self._h, None, 0,
                                               ctypes.byref(n)), "edge stats")
        edges = {}
        if n.value:
            buf = (_native.EdgeStats * n.value)()
            _check(self._lib.pccltCommGetEdgeStats(self._h, buf, n.value,
                                                   ctypes.byref(n)),
                   "edge stats")
            for i in range(min(n.value, len(buf))):
                e = buf[i]
                edges[e.endpoint.decode()] = {
                    "tx_bytes": int(e.tx_bytes), "rx_bytes": int(e.rx_bytes),
                    "tx_frames": int(e.tx_frames),
                    "rx_frames": int(e.rx_frames),
                    "connects": int(e.connects), "stall_ms": int(e.stall_ms),
                    "tx_zc_frames": int(e.tx_zc_frames),
                    "tx_zc_reaps": int(e.tx_zc_reaps),
                    # edge watchdog + window failover (docs/05)
                    "wd_state": int(e.wd_state),
                    "wd_suspects": int(e.wd_suspects),
                    "wd_confirms": int(e.wd_confirms),
                    "wd_reissues": int(e.wd_reissues),
                    "wd_relays": int(e.wd_relays),
                    "rx_relay_bytes": int(e.rx_relay_bytes),
                    "rx_relay_windows": int(e.rx_relay_windows),
                    "dup_bytes": int(e.dup_bytes),
                    "dup_windows": int(e.dup_windows),
                    # shared-state chunk plane (docs/04)
                    "tx_sync_bytes": int(e.tx_sync_bytes),
                    "rx_sync_bytes": int(e.rx_sync_bytes),
                    # multipath striping (docs/08)
                    "tx_stripe_windows": int(e.tx_stripe_windows),
                    "tx_stripe_bytes": int(e.tx_stripe_bytes),
                }
        return {"counters": counters, "edges": edges}

    def trace_events(self) -> list:
        """Native flight-recorder events as Chrome trace-event dicts. The
        recorder is process-global (one ring per process, every comm and
        the in-process master feed it); exposed here for symmetry with
        stats(). Enable capture with PCCLT_TRACE=path or trace_enable()."""
        return trace_events()

    def are_peers_pending(self) -> bool:
        out = ctypes.c_int()
        _check(self._lib.pccltArePeersPending(self._h, ctypes.byref(out)))
        return out.value != 0

    def optimize_topology(self) -> None:
        _check(self._lib.pccltOptimizeTopology(self._h), "optimize topology")

    # -- collectives --

    # auto tags live in a high band so they can never collide with the small
    # deterministic tags used by blocking all_reduce (0) and
    # all_reduce_multiple_with_retry (0..n-1) or typical user-chosen tags
    _AUTO_TAG_BASE = 1 << 32
    # all_reduce_multiple_with_retry uses deterministic tags in this reserved
    # band (disjoint from the blocking default 0, typical user tags, and the
    # auto band above) so concurrent collectives never collide on tag 0
    _RETRY_TAG_BASE = 1 << 16

    def _auto_tag(self) -> int:
        with self._tag_lock:
            t = self._next_tag
            self._next_tag += 1
            return t

    @staticmethod
    def _buffers(send, recv):
        # the buffer the native core writes into must be the caller's memory —
        # a silent ascontiguousarray copy would discard the result
        if recv is None:
            if not isinstance(send, np.ndarray) or not send.flags["C_CONTIGUOUS"]:
                raise ValueError(
                    "in-place all_reduce requires a C-contiguous ndarray "
                    "(pass a separate contiguous recv buffer otherwise)")
            if not send.flags["WRITEABLE"]:
                raise ValueError("in-place all_reduce requires a writable array")
            return send, send
        if not isinstance(recv, np.ndarray) or not recv.flags["C_CONTIGUOUS"]:
            raise ValueError("recv must be a C-contiguous ndarray")
        if not recv.flags["WRITEABLE"]:
            raise ValueError("recv must be writable")
        send = np.ascontiguousarray(send)  # send is read-only; a copy is fine
        if recv.dtype != send.dtype or recv.size != send.size:
            raise ValueError("recv buffer must match send dtype/size")
        return send, recv

    def all_reduce(self, send, recv=None, *, op: ReduceOp = ReduceOp.SUM,
                   tag: int = 0,
                   quantization: QuantizationAlgorithm = QuantizationAlgorithm.NONE,
                   quantized_dtype: DataType = DataType.UINT8,
                   dtype: Optional[DataType] = None) -> ReduceInfo:
        """Blocking ring all-reduce. recv=None → in place. Raises
        ConnectionLostError / OperationAbortedError on peer churn.

        The tag identifies the op ACROSS peers: every group member must call
        with the same tag for the op to commence (reference descriptor tags).
        The default tag 0 is stable, so late joiners match incumbents; pass
        distinct explicit tags only for concurrent reduces.

        dtype overrides the wire dtype when numpy cannot express it —
        e.g. pass DataType.BFLOAT16 with uint16 arrays holding bf16 bit
        patterns (numpy has no bfloat16)."""
        send, recv = self._buffers(send, recv)
        desc = ReduceDescriptor(tag, op, quantization, quantized_dtype)._as_c()
        info = _native.ReduceInfo()
        wire_dtype = dtype if dtype is not None else _np_dtype_of(send)
        if dtype is not None and \
                _DTYPE_ITEMSIZE[wire_dtype] != send.dtype.itemsize:
            # a mismatched override would silently reinterpret a fraction of
            # the buffer (element COUNT is passed, not bytes)
            raise ValueError(
                f"wire dtype {wire_dtype.name} is "
                f"{_DTYPE_ITEMSIZE[wire_dtype]} bytes/elem but the arrays "
                f"hold {send.dtype.itemsize}-byte elements")
        code = self._lib.pccltAllReduce(
            self._h, send.ctypes.data_as(ctypes.c_void_p),
            recv.ctypes.data_as(ctypes.c_void_p), send.size,
            int(wire_dtype), ctypes.byref(desc), ctypes.byref(info))
        _check(code, "all_reduce")
        return ReduceInfo(info.tx_bytes, info.rx_bytes, info.world_size)

    def all_gather(self, send, recv=None, *, tag: int = 0) -> tuple:
        """Ring all-gather (pcclt extension; the reference lists All-Gather
        as unshipped roadmap work). Every peer contributes `send`; returns
        (recv, ReduceInfo) where segment i belongs to the peer at sorted-
        uuid position i (stable across ring re-orderings; your own index is
        `gather_slot`). recv=None allocates (world_size, *send.shape); a
        caller-provided recv must be a writable C-contiguous array of
        send's dtype with capacity >= world_size * send.size. The native
        side re-checks capacity against the commence-time world, so a
        joiner admitted mid-call aborts the op instead of overflowing."""
        send = np.ascontiguousarray(send)
        world = self.world_size
        if recv is None:
            recv = np.empty((world,) + send.shape, dtype=send.dtype)
        if recv.dtype != send.dtype:
            raise ValueError(f"recv dtype {recv.dtype} != send {send.dtype}")
        if not recv.flags["C_CONTIGUOUS"] or not recv.flags["WRITEABLE"]:
            raise ValueError("recv must be writable and C-contiguous")
        if recv.size < world * send.size:
            raise ValueError(f"recv capacity {recv.size} < world*send "
                             f"{world * send.size}")
        if world <= 1:
            # solo: own segment at slot 0, zero wire traffic — honoring the
            # docstring's unconditional contract instead of surfacing the
            # native layer's group_world<2 rejection
            np.copyto(recv.reshape(-1)[:send.size].reshape(send.shape), send)
            return recv, ReduceInfo(0, 0, 1)
        info = _native.ReduceInfo()
        code = self._lib.pccltAllGather(
            self._h, send.ctypes.data_as(ctypes.c_void_p),
            recv.ctypes.data_as(ctypes.c_void_p), send.size, recv.size,
            int(_np_dtype_of(send)), tag, ctypes.byref(info))
        _check(code, "all_gather")
        return recv, ReduceInfo(info.tx_bytes, info.rx_bytes, info.world_size)

    @property
    def gather_slot(self) -> int:
        """This peer's segment index in all_gather output (position among
        the current ring's sorted peer UUIDs; re-query after churn)."""
        slot = ctypes.c_uint64()
        _check(self._lib.pccltGatherSlot(self._h, ctypes.byref(slot)),
               "gather_slot")
        return int(slot.value)

    def reduce_scatter(self, send, recv=None, *, tag: int = 0,
                       quantization: QuantizationAlgorithm =
                       QuantizationAlgorithm.NONE,
                       quantized_dtype: DataType = DataType.UINT8) -> tuple:
        """Ring reduce-scatter (docs/12): the group SUM of `send` is computed
        and each peer keeps only its own contiguous chunk of the result.
        Returns (chunk, offset, ReduceInfo): `chunk` is a view of recv
        holding this peer's reduced elements and `offset` is its element
        offset within the full count — recv[i] == sum_of_send[offset + i].
        Chunk ownership follows ring rank, so the (offset, count) pair can
        change across churn; always use the returned values. The fold is
        SUM (quantization fields still apply to the wire format). recv=None
        allocates ceil(count/world) elements; a caller-provided recv must
        be writable, C-contiguous, send's dtype, capacity >=
        ceil(count/world) — re-checked natively against the commence-time
        world so mid-call churn aborts instead of overflowing."""
        send = np.ascontiguousarray(send)
        if not hasattr(self._lib, "pccltReduceScatter"):
            raise PcclError(Result.INVALID_USAGE,
                            "this libpcclt.so predates the schedule "
                            "synthesizer (pccltReduceScatter); rebuild")
        world = self.world_size
        if recv is None:
            cap = (send.size + max(world, 1) - 1) // max(world, 1)
            recv = np.empty(max(cap, 1), dtype=send.dtype)
        if recv.dtype != send.dtype:
            raise ValueError(f"recv dtype {recv.dtype} != send {send.dtype}")
        if not recv.flags["C_CONTIGUOUS"] or not recv.flags["WRITEABLE"]:
            raise ValueError("recv must be writable and C-contiguous")
        if world <= 1:
            # solo: the SUM over one peer is the peer's own buffer
            if recv.size < send.size:
                raise ValueError(f"recv capacity {recv.size} < {send.size}")
            np.copyto(recv.reshape(-1)[:send.size],
                      send.reshape(-1))
            return recv.reshape(-1)[:send.size], 0, ReduceInfo(0, 0, 1)
        desc = ReduceDescriptor(tag, ReduceOp.SUM, quantization,
                                quantized_dtype)._as_c()
        info = _native.ReduceInfo()
        off = ctypes.c_uint64()
        cnt = ctypes.c_uint64()
        code = self._lib.pccltReduceScatter(
            self._h, send.ctypes.data_as(ctypes.c_void_p),
            recv.ctypes.data_as(ctypes.c_void_p), send.size, recv.size,
            int(_np_dtype_of(send)), ctypes.byref(desc), ctypes.byref(off),
            ctypes.byref(cnt), ctypes.byref(info))
        _check(code, "reduce_scatter")
        return (recv.reshape(-1)[:int(cnt.value)], int(off.value),
                ReduceInfo(info.tx_bytes, info.rx_bytes, info.world_size))

    def broadcast(self, buf, *, root: int, tag: int = 0,
                  quantization: QuantizationAlgorithm =
                  QuantizationAlgorithm.NONE,
                  quantized_dtype: DataType = DataType.UINT8) -> ReduceInfo:
        """In-place broadcast from the peer at sorted-uuid slot `root` (its
        `gather_slot`; every peer must pass the SAME root — a mismatch is a
        parameter disagreement and gets the minority kicked). On return buf
        holds the root's bytes bit-identically on every peer. The schedule
        synthesizer may run this over a bandwidth-weighted tree instead of
        the ring (docs/12); the result is identical either way."""
        if not isinstance(buf, np.ndarray) or not buf.flags["C_CONTIGUOUS"] \
                or not buf.flags["WRITEABLE"]:
            raise ValueError("broadcast buffer must be a writable "
                             "C-contiguous ndarray (updated in place)")
        if not hasattr(self._lib, "pccltBroadcast"):
            raise PcclError(Result.INVALID_USAGE,
                            "this libpcclt.so predates the schedule "
                            "synthesizer (pccltBroadcast); rebuild")
        if self.world_size <= 1:
            return ReduceInfo(0, 0, 1)
        desc = ReduceDescriptor(tag, ReduceOp.SUM, quantization,
                                quantized_dtype)._as_c()
        info = _native.ReduceInfo()
        code = self._lib.pccltBroadcast(
            self._h, buf.ctypes.data_as(ctypes.c_void_p), buf.size,
            int(root), int(_np_dtype_of(buf)), ctypes.byref(desc),
            ctypes.byref(info))
        _check(code, "broadcast")
        return ReduceInfo(info.tx_bytes, info.rx_bytes, info.world_size)

    def all_to_all(self, send, recv=None, *, tag: int = 0,
                   quantization: QuantizationAlgorithm =
                   QuantizationAlgorithm.NONE,
                   quantized_dtype: DataType = DataType.UINT8) -> tuple:
        """All-to-all personalized exchange (docs/12): `send` is world_size
        equal blocks in sorted-uuid slot order; block j lands as block
        `my_slot` at the peer holding slot j, and recv block i is the block
        peer i addressed to us. send.size must be divisible by world_size.
        recv=None allocates send's shape; a caller-provided recv must be
        writable, C-contiguous, send's dtype, capacity >= send.size
        (re-checked natively against the commence-time world). Returns
        (recv, ReduceInfo)."""
        send = np.ascontiguousarray(send)
        if not hasattr(self._lib, "pccltAllToAll"):
            raise PcclError(Result.INVALID_USAGE,
                            "this libpcclt.so predates the schedule "
                            "synthesizer (pccltAllToAll); rebuild")
        world = self.world_size
        if recv is None:
            recv = np.empty(send.shape, dtype=send.dtype)
        if recv.dtype != send.dtype:
            raise ValueError(f"recv dtype {recv.dtype} != send {send.dtype}")
        if not recv.flags["C_CONTIGUOUS"] or not recv.flags["WRITEABLE"]:
            raise ValueError("recv must be writable and C-contiguous")
        if recv.size < send.size:
            raise ValueError(f"recv capacity {recv.size} < send {send.size}")
        if world <= 1:
            np.copyto(recv.reshape(-1)[:send.size], send.reshape(-1))
            return recv, ReduceInfo(0, 0, 1)
        if send.size % world:
            raise ValueError(f"send size {send.size} not divisible by "
                             f"world {world}")
        desc = ReduceDescriptor(tag, ReduceOp.SUM, quantization,
                                quantized_dtype)._as_c()
        info = _native.ReduceInfo()
        code = self._lib.pccltAllToAll(
            self._h, send.ctypes.data_as(ctypes.c_void_p),
            recv.ctypes.data_as(ctypes.c_void_p), send.size // world,
            recv.size, int(_np_dtype_of(send)), ctypes.byref(desc),
            ctypes.byref(info))
        _check(code, "all_to_all")
        return recv, ReduceInfo(info.tx_bytes, info.rx_bytes, info.world_size)

    def all_reduce_async(self, send, recv=None, *, op: ReduceOp = ReduceOp.SUM,
                         tag: Optional[int] = None,
                         quantization: QuantizationAlgorithm = QuantizationAlgorithm.NONE,
                         quantized_dtype: DataType = DataType.UINT8) -> AsyncReduceHandle:
        """Async variant. tag=None auto-allocates a locally increasing tag —
        fine for a static world, but under dynamic membership every peer must
        pass the SAME explicit tag per op or the group cannot reach consensus
        (see all_reduce)."""
        send, recv = self._buffers(send, recv)
        tag = tag if tag is not None else self._auto_tag()
        desc = ReduceDescriptor(tag, op, quantization, quantized_dtype)._as_c()
        code = self._lib.pccltAllReduceAsync(
            self._h, send.ctypes.data_as(ctypes.c_void_p),
            recv.ctypes.data_as(ctypes.c_void_p), send.size,
            int(_np_dtype_of(send)), ctypes.byref(desc))
        _check(code, "all_reduce_async")
        return AsyncReduceHandle(self, tag, (send, recv))

    def all_reduce_multiple_with_retry(self, tensors: Sequence,
                                       *, op: ReduceOp = ReduceOp.SUM,
                                       quantization: QuantizationAlgorithm =
                                       QuantizationAlgorithm.NONE,
                                       quantized_dtype: DataType = DataType.UINT8,
                                       ) -> list[ReduceInfo]:
        """Launch one reduce per tensor (in place), retrying as the world
        shrinks until all succeed (reference pcclAllReduceMultipleWithRetry)."""
        for t in tensors:
            if not isinstance(t, np.ndarray) or not t.flags["C_CONTIGUOUS"] \
                    or not t.flags["WRITEABLE"]:
                raise ValueError("tensors must be writable C-contiguous ndarrays "
                                 "(reduced in place)")
        arrs = list(tensors)
        if not arrs:
            return []
        dt = _np_dtype_of(arrs[0])
        for a in arrs:
            if _np_dtype_of(a) != dt:
                raise ValueError("all tensors must share a dtype")
        n = len(arrs)
        sendp = (ctypes.c_void_p * n)(*[a.ctypes.data_as(ctypes.c_void_p).value
                                        for a in arrs])
        recvp = (ctypes.c_void_p * n)(*[a.ctypes.data_as(ctypes.c_void_p).value
                                        for a in arrs])
        counts = (ctypes.c_uint64 * n)(*[a.size for a in arrs])
        descs = (_native.ReduceDescriptor * n)()
        for i in range(n):
            # deterministic tags (reserved band + tensor index): peers match
            # ops by tag, and a late joiner's counter must not drift from
            # incumbents'. The band keeps these disjoint from the blocking
            # default tag 0 and from user-chosen small tags, so a foreground
            # all_reduce can run concurrently with a background retry batch.
            d = ReduceDescriptor(self._RETRY_TAG_BASE + i, op, quantization,
                                 quantized_dtype)._as_c()
            descs[i] = d
        infos = (_native.ReduceInfo * n)()
        code = self._lib.pccltAllReduceMultipleWithRetry(
            self._h, sendp, recvp, counts, int(dt), descs, n, infos)
        _check(code, "all_reduce_multiple_with_retry")
        return [ReduceInfo(i.tx_bytes, i.rx_bytes, i.world_size) for i in infos]

    # -- shared state --

    def sync_shared_state(self, state: SharedState,
                          strategy: SharedStateSyncStrategy =
                          SharedStateSyncStrategy.ENFORCE_POPULAR,
                          ) -> SharedStateSyncInfo:
        keepalive: list = []
        infos = (_native.TensorInfoC * len(state.infos))()
        for i, ti in enumerate(state.infos):
            infos[i] = ti._as_c(keepalive)
        st = _native.SharedStateC(revision=state.revision, count=len(state.infos),
                                  infos=infos)
        out = _native.SharedStateSyncInfo()
        code = self._lib.pccltSynchronizeSharedState(
            self._h, ctypes.byref(st), int(strategy), ctypes.byref(out))
        _check(code, "sync_shared_state")
        for i, ti in enumerate(state.infos):
            # per-entry received-content flag (device-hash entries use it
            # to decide between the untouched device array and the synced
            # host bytes in jax_value)
            ti._updated = bool(infos[i].updated)
        return SharedStateSyncInfo(out.tx_bytes, out.rx_bytes, out.revision)
