"""Native-stack loopback all-reduce benchmark (bench.py's preferred path).

Matches BASELINE.md config 1: fp32 ring all-reduce, 2 loopback peers, over
the real native stack (master + 2 communicator processes, PCCP wire
protocol). busbw for a ring all-reduce = 2*(N-1)/N * bytes / time; N=2 →
bytes/time. The reference's equivalent harness is
tests/basic_reduce_test/main.cpp (fp32 loop over loopback peers).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time

import numpy as np


def _peer_main(rank: int, master_port: int, nbytes: int, iters: int, q) -> None:
    from pccl_tpu.comm.api import Communicator, ReduceOp

    comm = Communicator("127.0.0.1", master_port,
                        p2p_port=48700 + rank * 4, ss_port=48740 + rank * 4,
                        bench_port=48780 + rank * 4)
    comm.connect()
    while comm.world_size < 2:
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.02)

    count = nbytes // 4
    x = np.full(count, float(rank + 1), dtype=np.float32)
    y = np.empty_like(x)
    comm.all_reduce(x, y, op=ReduceOp.SUM)  # warmup
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        comm.all_reduce(x, y, op=ReduceOp.SUM)
        times.append(time.perf_counter() - t0)
    assert abs(float(y[0]) - 3.0) < 1e-6, f"allreduce wrong: {y[0]}"
    if q is not None:
        q.put(times)
    comm.destroy()


def run_allreduce_bench(nbytes: int = 64 << 20, iters: int = 10) -> float:
    """Returns busbw in GB/s (median over iters)."""
    from pccl_tpu.comm.api import MasterNode

    master = MasterNode("0.0.0.0", int(os.environ.get("PCCLT_BENCH_MASTER_PORT",
                                                      "48651")))
    master.run()
    try:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p1 = ctx.Process(target=_peer_main,
                         args=(1, master.port, nbytes, iters, None))
        p1.start()
        try:
            _peer_main(0, master.port, nbytes, iters, q)
            times = q.get(timeout=120)
            p1.join(timeout=30)
        finally:
            if p1.is_alive():
                p1.terminate()
                p1.join(timeout=5)
        med = sorted(times)[len(times) // 2]
        return (nbytes / med) / 1e9
    finally:
        master.interrupt()
        master.destroy()
