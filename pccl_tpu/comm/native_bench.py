"""Native-stack loopback benchmarks (bench.py's preferred path).

Covers the BASELINE.md target configs over the real native stack (master +
communicator processes, PCCP wire protocol):

1. ``run_allreduce_bench``            — fp32 ring all-reduce, 2 loopback
   peers; busbw = 2*(N-1)/N * bytes/t; N=2 -> bytes/t. Mirrors the
   reference's tests/basic_reduce_test/main.cpp.
2. ``run_quantized_concurrent_bench`` — int8 zero-point/scale quantized
   concurrent reduces, 4 loopback peers. Mirrors the reference's
   tests/concurrent_reduce_test/main.cpp:48-50 (the
   pcclAllReduceMultipleWithRetry workload).
3. ``run_shared_state_bench``         — per-step SyncSharedState + one
   all-reduce, 4 peers. Mirrors the python examples' training-step shape.
4. ``run_diloco_outer_bench``         — DiLoCo outer-step wall-clock at
   ``params_n`` parameters, 2 peers (device staging + AVG ring + outer SGD).
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Tuple

import numpy as np


@contextmanager
def _wire_env(name: str, value: float):
    """Set a wire-emulation env var for every peer spawned inside the
    block (children inherit the env), restored on exit."""
    old = os.environ.get(name)
    os.environ[name] = str(value)
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = old


def _paced_wire(mbps: float):
    """PCCLT_WIRE_MBPS egress pacing (bandwidth emulation)."""
    return _wire_env("PCCLT_WIRE_MBPS", mbps)


def _rtt_wire(rtt_ms: float):
    """PCCLT_WIRE_RTT_MS round-trip-time emulation (delivery delay line in
    sockets.cpp)."""
    return _wire_env("PCCLT_WIRE_RTT_MS", rtt_ms)


def _edge_value(spec, i: int, j: int):
    """Resolve edge (i -> j) from a scalar, a world x world matrix, or a
    {(i, j): value} dict; None entries mean 'unconstrained'."""
    if spec is None:
        return None
    if isinstance(spec, dict):
        return spec.get((i, j))
    if isinstance(spec, (list, tuple)):
        return spec[i][j]
    return spec  # scalar: every edge


def _endpoint_ports(port_base: int, rank: int):
    """The ports a peer at `rank` is REACHED on (_rank_ports layout): p2p
    (data plane + edge key canonicalized by the P2P hello) and bench (the
    topology optimizer's probe target)."""
    p2p, _ss, bench = _rank_ports(port_base, rank)
    return (p2p, bench)


@contextmanager
def wire_topology(world: int, port_base: int, mbps=None, rtt_ms=None,
                  jitter_ms=None, drop=None, host: str = "127.0.0.1"):
    """Build per-rank PCCLT_WIRE_*_MAP env dicts describing a heterogeneous
    emulated mesh over a loopback world (netem.hpp). Yields a list of env
    dicts, one per rank; each spawned peer applies its own via
    ``os.environ.update(envs[rank])`` BEFORE constructing its Communicator
    (the native layer re-reads the env at every connection establishment).

    Edge (i -> j) constraints live in rank i's env, keyed by rank j's
    endpoints — both the p2p port (data plane; the P2P hello canonicalizes
    accepted conns to it) and the bench port (so ``optimize_topology``'s
    bandwidth probes measure the same emulated edge the ring will ride).

    ``mbps`` / ``rtt_ms`` / ``jitter_ms`` / ``drop`` each accept a scalar
    (uniform), a world x world matrix, or a {(i, j): value} dict; None
    entries leave that edge/dimension unconstrained. The process-global
    PCCLT_WIRE_MBPS / PCCLT_WIRE_RTT_MS vars keep acting as defaults for
    unmapped edges. Nothing in THIS process's environment is touched —
    the context-manager shape only scopes the description; the maps take
    effect in whichever peer applies its env dict."""
    var_specs = (("PCCLT_WIRE_MBPS_MAP", mbps),
                 ("PCCLT_WIRE_RTT_MS_MAP", rtt_ms),
                 ("PCCLT_WIRE_JITTER_MS_MAP", jitter_ms),
                 ("PCCLT_WIRE_DROP_MAP", drop))
    # the native layer's canonical v6 endpoint form is bracketed
    # ("[::1]:5000" — Addr::str()); a bare "::1:5000" key would never match
    key_host = f"[{host}]" if ":" in host and not host.startswith("[") else host
    envs = []
    for i in range(world):
        env: Dict[str, str] = {}
        for var, spec in var_specs:
            entries = []
            for j in range(world):
                if j == i:
                    continue
                v = _edge_value(spec, i, j)
                if v is None:
                    continue
                for port in _endpoint_ports(port_base, j):
                    entries.append(f"{key_host}:{port}={v}")
            if entries:
                env[var] = ",".join(entries)
        envs.append(env)
    yield envs


def _port(env: str, dflt: int) -> int:
    return int(os.environ.get(env, str(dflt)))


def _rank_ports(port_base: int, rank: int) -> Tuple[int, int, int]:
    """The bench harness's port layout for a peer at `rank`: (p2p, ss,
    bench). Single source of truth for _connect, the topology peers, and
    wire_topology's map keys — a stride change that misses one of them
    would silently mis-key the per-edge emulation."""
    return (port_base + rank * 4,
            port_base + 1000 + rank * 4,
            port_base + 2000 + rank * 4)


def _spawn_world(world: int, peer_main: Callable, master_port: int,
                 args: tuple = (), inline_rank0: bool = True,
                 timeout_s: int = 300) -> List[Dict[str, Any]]:
    """Run `peer_main(rank, master_port, q, *args)` in `world` processes
    (rank 0 inline unless `inline_rank0` is False — peers that mutate global
    process state, e.g. jax platform config, must not run in the caller) and
    return each peer's result dict."""
    from pccl_tpu.comm.api import MasterNode

    master = MasterNode("0.0.0.0", master_port)
    master.run()
    try:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = []
        for r in range(0 if not inline_rank0 else 1, world):
            p = ctx.Process(target=peer_main, args=(r, master.port, q) + args)
            p.start()
            procs.append(p)
        try:
            if inline_rank0:
                peer_main(0, master.port, q, *args)
            results = [q.get(timeout=timeout_s) for _ in range(world)]
            for p in procs:
                p.join(timeout=30)
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=5)
        return results
    finally:
        master.interrupt()
        master.destroy()


def _connect(rank: int, master_port: int, world: int, port_base: int):
    """Join and wait until the group reaches `world` peers."""
    from pccl_tpu.comm.api import Communicator

    p2p, ss, bench = _rank_ports(port_base, rank)
    comm = Communicator("127.0.0.1", master_port,
                        p2p_port=p2p, ss_port=ss, bench_port=bench)
    comm.connect()
    while comm.world_size < world:
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.02)
    return comm


# ---------------------------------------------------------------- config 1

def _phase_breakdown(events, iters: int) -> Dict[str, float]:
    """Aggregate the flight recorder's per-op events into a mean per-op
    phase breakdown (seconds): reduce-scatter / all-gather span time plus
    the wire-stall and quantize accumulators (telemetry.hpp)."""
    sums: Dict[str, float] = {}
    for e in events:
        name, args = e.get("name"), e.get("args", {})
        if name in ("reduce_scatter", "all_gather", "allreduce", "allgather") \
                and e.get("ph") == "X":
            sums[name] = sums.get(name, 0.0) + e.get("dur", 0.0) / 1e6
        elif name in ("wire_stall", "quantize") and "ns" in args:
            sums[name] = sums.get(name, 0.0) + args["ns"] / 1e9
    return {f"{k}_s": round(v / max(1, iters), 6) for k, v in sums.items()}


def _peer_allreduce(rank, master_port, q, nbytes, iters, dtype_name, port_base):
    from pccl_tpu.comm.api import (DataType, ReduceOp, shm_ndarray,
                                   trace_clear, trace_enable, trace_events)

    bf16 = dtype_name == "bfloat16"
    dtype = np.uint16 if bf16 else np.dtype(dtype_name)
    comm = _connect(rank, master_port, 2, port_base)
    count = nbytes // np.dtype(dtype).itemsize
    # registered shm buffers: same-host peers map them and reduce zero-copy.
    # bf16 rides as uint16 bit patterns (numpy has no bfloat16): 1.0 is
    # 0x3F80, and 1.0 + 1.0 = 2.0 is 0x4000 — exact, so the check is exact.
    x = shm_ndarray(count, dtype)
    x[:] = 0x3F80 if bf16 else float(rank + 1)
    y = shm_ndarray(count, dtype)
    wire = DataType.BFLOAT16 if bf16 else None
    comm.all_reduce(x, y, op=ReduceOp.SUM, dtype=wire)  # warmup
    # rank 0 runs inline in the bench process: enable the flight recorder
    # for the timed window and pick its events out by timestamp (perf_counter
    # shares the recorder's CLOCK_MONOTONIC timebase), so a user-requested
    # PCCLT_TRACE always-on capture is neither cleared nor disabled
    env_capture = bool(os.environ.get("PCCLT_TRACE"))
    if rank == 0:
        t_mark_us = time.perf_counter() * 1e6
        trace_enable(True)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        comm.all_reduce(x, y, op=ReduceOp.SUM, dtype=wire)
        times.append(time.perf_counter() - t0)
    expect = 0x4000 if bf16 else 3.0
    assert float(y[0]) == expect, f"allreduce wrong: {y[0]} != {expect}"
    res = {"rank": rank, "times": times}
    if rank == 0:
        evs = [e for e in trace_events() if e.get("ts", 0) >= t_mark_us]
        res["phases"] = _phase_breakdown(evs, iters)
        if not env_capture:
            trace_enable(False)
            trace_clear()  # later legs in this process start clean
    q.put(res)
    comm.destroy()


def run_allreduce_bench(nbytes: int = 64 << 20, iters: int = 10,
                        dtype_name: str = "float32", port_env: str =
                        "PCCLT_BENCH_MASTER_PORT", master_port: int = 48651,
                        port_base: int = 48700,
                        return_stats: bool = False):
    """Returns busbw in GB/s (median over iters), or with
    ``return_stats=True`` a {min, med, max} dict — the dispersion that
    makes a headline move attributable (run-to-run spread on this loaded
    1-core host is real; a median alone can't distinguish noise from
    regression)."""
    res = _spawn_world(2, _peer_allreduce, _port(port_env, master_port),
                       (nbytes, iters, dtype_name, port_base))
    r0 = next(r for r in res if r["rank"] == 0)
    gbps = sorted((nbytes / t) / 1e9 for t in r0["times"])
    # (len-1)//2 keeps the same sample the old sorted-times median picked
    # for even iters, so the headline stays comparable across rounds
    stats = {"min": gbps[0], "med": gbps[(len(gbps) - 1) // 2],
             "max": gbps[-1]}
    # flight-recorder phase breakdown (mean per op): reduce_scatter_s /
    # all_gather_s span time + wire_stall_s (+ quantize_s when quantized)
    if "phases" in r0:
        stats["phases"] = r0["phases"]
    return stats if return_stats else stats["med"]


def run_allreduce_bench_bf16(nbytes: int = 64 << 20, iters: int = 10) -> float:
    """bf16 (TPU-native gradient dtype) busbw GB/s, 2 loopback peers."""
    return run_allreduce_bench(nbytes, iters, dtype_name="bfloat16",
                               port_env="PCCLT_BENCH_MASTER_PORT5",
                               master_port=48659, port_base=48770)


# ---------------------------------------------------------------- config 2

def _peer_quant(rank, master_port, q, world, n_tensors, elems, iters,
                quantize=True):
    from pccl_tpu.comm.api import DataType, QuantizationAlgorithm, ReduceOp

    comm = _connect(rank, master_port, world, 48790)
    rng = np.random.default_rng(1234 + rank)
    tensors = [rng.standard_normal(elems).astype(np.float32)
               for _ in range(n_tensors)]
    kw = {}
    if quantize:
        kw = dict(quantization=QuantizationAlgorithm.ZERO_POINT_SCALE,
                  quantized_dtype=DataType.INT8)
    times = []
    for it in range(iters + 1):  # first iter is warmup
        t0 = time.perf_counter()
        comm.all_reduce_multiple_with_retry(tensors, op=ReduceOp.AVG, **kw)
        if it > 0:
            times.append(time.perf_counter() - t0)
    q.put({"rank": rank, "times": times})
    comm.destroy()


def run_quantized_concurrent_bench(world: int = 4, n_tensors: int = 4,
                                   elems: int = 2 << 20, iters: int = 5,
                                   quantize: bool = True) -> float:
    """int8-ZPS quantized concurrent reduces (or the fp32 twin with
    ``quantize=False`` — recorded as concurrent4_fp32_busbw_gbps so BENCH
    is self-describing about the loopback inversion: on a free local wire
    the u8 codec work dominates and fp32 wins; see docs/08_performance.md).
    Returns payload busbw GB/s: 2*(N-1)/N * fp32_bytes / median step."""
    res = _spawn_world(world, _peer_quant, _port("PCCLT_BENCH_MASTER_PORT2", 48653),
                       (world, n_tensors, elems, iters, quantize))
    times = next(r["times"] for r in res if r["rank"] == 0)
    med = sorted(times)[len(times) // 2]
    payload = n_tensors * elems * 4
    return (2 * (world - 1) / world) * payload / med / 1e9


# ---------------------------------------------------------------- config 3

def _peer_shared_state(rank, master_port, q, world, elems, iters):
    from pccl_tpu.comm.api import ReduceOp, SharedState, TensorInfo

    comm = _connect(rank, master_port, world, 48880)
    params = np.zeros(elems, dtype=np.float32)
    grad = np.full(elems, float(rank + 1), dtype=np.float32)
    out = np.empty_like(grad)
    times = []
    for it in range(iters + 1):
        t0 = time.perf_counter()
        state = SharedState(
            infos=[TensorInfo.from_numpy("params", params)], revision=it)
        comm.sync_shared_state(state)
        comm.all_reduce(grad, out, op=ReduceOp.AVG)
        params += 0.01 * out  # all peers apply the same update -> stays in sync
        if it > 0:
            times.append(time.perf_counter() - t0)
    q.put({"rank": rank, "times": times})
    comm.destroy()


def run_shared_state_bench(world: int = 4, elems: int = 4 << 20,
                           iters: int = 5) -> float:
    """SharedState sync + AVG all-reduce per step; returns median step
    seconds."""
    res = _spawn_world(world, _peer_shared_state,
                       _port("PCCLT_BENCH_MASTER_PORT3", 48655),
                       (world, elems, iters))
    times = next(r["times"] for r in res if r["rank"] == 0)
    return sorted(times)[len(times) // 2]


# ---------------------------------------------------------------- config 4

def _peer_diloco(rank, master_port, q, world, params_n, outer_steps, windows=1):
    import dataclasses

    import jax

    jax.config.update("jax_platforms", "cpu")  # peers must not fight over the chip
    import jax.numpy as jnp

    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig

    comm = _connect(rank, master_port, world, 48960)
    params = {"w": jnp.zeros((params_n,), jnp.float32)}
    # shm_staging: bench peers share this host, so the ring is zero-copy.
    # windows=1 by default: concurrent tagged ops lose ~10x on a 1-core
    # host (see docs/08_performance.md) — windowing pays on real WAN pipes
    shm = os.environ.get("PCCLT_BENCH_DILOCO_SHM", "1") != "0"
    diloco = Diloco(comm, params, DilocoConfig(shm_staging=shm,
                                               comm_windows=windows))
    # synthetic inner step: outer params minus a fake gradient update.
    # 2 warmup steps: the first outer steps pay one-time jit compiles of the
    # param-sized codec/apply graphs
    times = []
    cur = diloco.params()
    for it in range(outer_steps + 2):
        inner = jax.tree.map(lambda p: p - 0.01 * (rank + 1), cur)
        jax.block_until_ready(inner)  # keep inner compute out of the timing
        t0 = time.perf_counter()
        cur = diloco.outer_step(inner)
        jax.block_until_ready(cur)
        if it >= 2:
            times.append(time.perf_counter() - t0)
    # one more step with rank 0 profiled for the phase breakdown. Only ONE
    # rank fences: when both do, their lockstep 400 MB allocation bursts
    # trigger a kernel-level pathology on this host (page-fault/THP storms
    # inflate each phase's CPU time ~10x) and the breakdown stops describing
    # production behavior. Rank 1 runs the step unprofiled alongside.
    if rank == 0:
        diloco.cfg = dataclasses.replace(diloco.cfg, profile=True)
    inner = jax.tree.map(lambda p: p - 0.01 * (rank + 1), cur)
    jax.block_until_ready(inner)  # same step shape as the timed loop
    diloco.outer_step(inner)
    q.put({"rank": rank, "times": times, "phases": diloco.last_profile})
    comm.destroy()


def _peer_wan(rank, master_port, q, world, nbytes, iters, quantize, port_base,
              bf16=False):
    from pccl_tpu.comm.api import DataType, QuantizationAlgorithm, ReduceOp

    comm = _connect(rank, master_port, world, port_base)
    rng = np.random.default_rng(7 + rank)
    kw = {}
    if bf16:
        # bf16 bit patterns ride in uint16 arrays (numpy has no bfloat16);
        # truncating f32 -> bf16 is fine for a throughput bench
        f = rng.standard_normal(nbytes // 2).astype(np.float32)
        x = (f.view(np.uint32) >> 16).astype(np.uint16)
        kw["dtype"] = DataType.BFLOAT16
    else:
        x = rng.standard_normal(nbytes // 4).astype(np.float32)
    y = np.empty_like(x)
    if quantize:
        kw.update(quantization=QuantizationAlgorithm.ZERO_POINT_SCALE,
                  quantized_dtype=DataType.UINT8)
    comm.all_reduce(x, y, op=ReduceOp.AVG, **kw)  # warmup
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        comm.all_reduce(x, y, op=ReduceOp.AVG, **kw)
        times.append(time.perf_counter() - t0)
    q.put({"rank": rank, "times": times})
    comm.destroy()


def run_wan_bench(world: int = 4, nbytes: int = 32 << 20, iters: int = 3,
                  mbps: float = 100.0) -> Dict[str, float]:
    """The constrained-wire A/B that justifies quantization's existence
    (reference WAN pitch: docs/md/01_Introduction.md:8). Runs the same
    ``world``-peer AVG ring twice over an emulated ``mbps``-megabit wire
    (PCCLT_WIRE_MBPS egress pacing; CMA/shm force-disabled): once fp32,
    once u8 zero-point/scale. Returns fp32-equivalent busbw GB/s for both
    — 2*(N-1)/N * fp32_bytes / t, i.e. "how fast the logical gradient
    moved" — plus the speedup ratio."""
    out: Dict[str, float] = {}
    with _paced_wire(mbps):
        # bases sit in 45xxx: every derived port (p2p, ss=+1000, bench=+2000)
        # stays below the 48500+ bench masters and the 50000+ fixed test
        # ports, so a bench can run concurrently with the pytest suite
        for name, quant, mport, base in (
                ("wan_fp32_busbw_gbps", False, 48671, 45000),
                ("wan_u8zps_busbw_gbps", True, 48673, 45400)):
            res = _spawn_world(world, _peer_wan,
                               _port("PCCLT_BENCH_MASTER_PORT_WAN", mport),
                               (world, nbytes, iters, quant, base),
                               inline_rank0=False)
            times = next(r["times"] for r in res if r["rank"] == 0)
            med = sorted(times)[len(times) // 2]
            out[name] = (2 * (world - 1) / world) * nbytes / med / 1e9
    out["wan_quant_speedup"] = out["wan_u8zps_busbw_gbps"] / out["wan_fp32_busbw_gbps"]
    return out


def _peer_wan_rtt(rank, master_port, q, world, nbytes, iters, windows,
                  port_base, env=None):
    from pccl_tpu.parallel.ring import avg_all_reduce_windowed

    if env:
        os.environ.update(env)  # data-plane knobs, applied pre-native-load
    comm = _connect(rank, master_port, world, port_base)
    rng = np.random.default_rng(11 + rank)
    x = rng.standard_normal(nbytes // 4).astype(np.float32)
    avg_all_reduce_windowed(comm, x, windows=windows)    # warmup
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        avg_all_reduce_windowed(comm, x, windows=windows)
        times.append(time.perf_counter() - t0)
    q.put({"rank": rank, "times": times})
    comm.destroy()


def run_wan_rtt_windowed_bench(world: int = 4, nbytes: int = 16 << 20,
                               iters: int = 3, mbps: float = 1000.0,
                               rtt_ms: float = 50.0,
                               mports: Tuple[int, int] = (48679, 48681),
                               bases: Tuple[int, int] = (46600, 47000),
                               ) -> Dict[str, float]:
    """The fat-pipe A/B: reduce windowing's reason to exist (reference
    pitch: concurrent reduces saturating the WAN,
    /root/reference/docs/md/01_Introduction.md:8). Same ``world``-peer AVG
    ring over an emulated high-bandwidth-delay pipe — ``mbps`` egress
    pacing (PCCLT_WIRE_MBPS) x ``rtt_ms`` round-trip latency
    (PCCLT_WIRE_RTT_MS delivery delay line) — once as a single flow
    (windows=1), once split into 4 concurrent tagged collectives over the
    connection pool (avg_all_reduce_windowed; 4 is the most the default
    16 MB payload admits under the 1M-element window floor). A single
    flow pays every
    stage-boundary latency stall serially (each ring hop's chunk chain
    fills owd late, and the per-op consensus round trips are exposed);
    concurrent windows overlap one window's stalls with another's drain.
    Returns busbw for both plus wan_rtt_windowed_speedup (>1 = windowing
    pays on fat pipes). Measured sweet spot: the win GROWS as the payload
    shrinks toward the bandwidth-delay product (1.46-1.53x at 16 MB vs
    1.20x at 32 MB on this host) — exactly the latency-dominated regime
    real outer-step shards live in.

    Both legs run with the windowed data-plane pipeline + io_uring backend
    (docs/08) forced OFF: these keys are the classic store-and-forward
    BASELINE, comparable across rounds with the r05 numbers, and the
    windowing A/B only means something on the plane windowing was invented
    for. The new plane's number is run_wan_pipelined_bench — a single
    pipelined flow now matches/beats the 4-window figure, which is exactly
    why the baseline must stay pinned."""
    out: Dict[str, float] = {}
    env = {"PCCLT_PIPELINE": "0", "PCCLT_URING": "0"}
    with _paced_wire(mbps), _rtt_wire(rtt_ms):
        for name, windows, mport, base in (
                ("wan_rtt_single_busbw_gbps", 1, mports[0], bases[0]),
                ("wan_rtt_windowed_busbw_gbps", 4, mports[1], bases[1])):
            res = _spawn_world(world, _peer_wan_rtt,
                               _port("PCCLT_BENCH_MASTER_PORT_RTT", mport),
                               (world, nbytes, iters, windows, base, env),
                               inline_rank0=False)
            times = next(r["times"] for r in res if r["rank"] == 0)
            med = sorted(times)[len(times) // 2]
            out[name] = (2 * (world - 1) / world) * nbytes / med / 1e9
    out["wan_rtt_windowed_speedup"] = (out["wan_rtt_windowed_busbw_gbps"] /
                                       out["wan_rtt_single_busbw_gbps"])
    return out


def run_wan_pipelined_bench(world: int = 4, nbytes: int = 16 << 20,
                            iters: int = 3, mbps: float = 1000.0,
                            rtt_ms: float = 50.0, baselines=None,
                            master_port: int = 48705, base: int = 46600,
                            ) -> Dict[str, float]:
    """The zero-copy pipelined data plane on the exact fat-long-pipe map of
    run_wan_rtt_windowed_bench (same mbps × rtt × payload): ONE flow with
    the windowed quantize→send→recv→dequant pipeline + io_uring batched
    submission forced on (docs/08 "data-plane pipeline"). A single
    pipelined collective pays the per-stage one-way delay once per window
    chain instead of once per stage, recovering MORE than 4-way op
    windowing did (r05: single 0.0603 / windowed 0.0873; the pipelined
    flow must beat both) without splitting the collective or paying 4
    consensus rounds.

    ``baselines`` (optional): a dict holding this run's
    wan_rtt_single_busbw_gbps / wan_rtt_windowed_busbw_gbps, used for the
    speedup keys; bench.py passes the values it just measured so the
    comparison is same-host, same-load."""
    out: Dict[str, float] = {}
    env = {"PCCLT_PIPELINE": "1"}  # io_uring rides its default auto-gate
    with _paced_wire(mbps), _rtt_wire(rtt_ms):
        res = _spawn_world(world, _peer_wan_rtt,
                           _port("PCCLT_BENCH_MASTER_PORT_PIPE", master_port),
                           (world, nbytes, iters, 1, base, env),
                           inline_rank0=False)
        times = next(r["times"] for r in res if r["rank"] == 0)
        med = sorted(times)[len(times) // 2]
        out["wan_pipelined_busbw_gbps"] = \
            (2 * (world - 1) / world) * nbytes / med / 1e9
    for key, name in (("wan_rtt_single_busbw_gbps", "wan_pipelined_speedup"),
                      ("wan_rtt_windowed_busbw_gbps",
                       "wan_pipelined_vs_windowed")):
        ref = (baselines or {}).get(key)
        if ref:
            out[name] = out["wan_pipelined_busbw_gbps"] / ref
    return out


def run_wan_striped_bench(world: int = 4, nbytes: int = 16 << 20,
                          iters: int = 3, mbps: float = 1000.0,
                          rtt_ms: float = 50.0, stripes: int = 4,
                          cwnd_bytes: int = 3 << 19,
                          mports: Tuple[int, int] = (48709, 48711),
                          bases: Tuple[int, int] = (47400, 47800),
                          ) -> Dict[str, float]:
    """Multipath striping A/B on the exact fat-long-pipe map of
    run_wan_pipelined_bench (same mbps × rtt × payload). BOTH legs run the
    full pipelined data plane; the baseline pins every op's window chain
    to ONE pool conn (PCCLT_STRIPE_CONNS=1 — PR 8's behavior and its
    0.0945 busbw), the striped leg round-robins the windows across
    ``stripes`` pool conns that share the one emulated edge bucket (the
    striped per-lane token bucket, docs/08 "multipath striping").

    Why striping wins when the bucket is honest about total bandwidth: a
    single flow is one TX thread serially pacing+writing 256 KiB frames —
    every scheduler oversleep between frames is modeled wire time nothing
    else can reclaim. K stripes keep K reservations queued in the bucket,
    so the wire stays busy across any one sender's scheduling jitter —
    the same reason real WANs run parallel TCP flows on fat-long pipes
    (one cwnd/seriality-limited flow cannot fill the pipe).

    The plain pair keeps the r05-comparable physics (no per-flow window:
    the emulated single flow is only seriality-limited, so the striping
    win there is the scheduler-jitter absorption of the striped bucket).
    The ``_cwnd_`` pair additionally models TCP's per-flow congestion
    window (PCCLT_WIRE_CWND_BYTES = 1.5 MiB over the 50 ms RTT ≈ 30 MB/s
    per flow — the cwnd-limited single flow the ROADMAP describes); BOTH
    its legs run under the same cap, and striping multiplies flows exactly
    the way parallel TCP does on a real fat-long pipe.

    Keys: wan_striped_single_busbw_gbps (same-run pinned baseline),
    wan_striped_busbw_gbps, wan_striped_speedup (striped / single), and
    the wan_striped_cwnd_* triple."""
    out: Dict[str, float] = {}
    legs = [
        ("wan_striped_single_busbw_gbps", 1, mports[0], bases[0], None),
        ("wan_striped_busbw_gbps", stripes, mports[1], bases[1], None),
        ("wan_striped_cwnd_single_busbw_gbps", 1, mports[0] + 4, bases[0],
         str(cwnd_bytes)),
        ("wan_striped_cwnd_busbw_gbps", stripes, mports[1] + 4, bases[1],
         str(cwnd_bytes)),
    ]
    with _paced_wire(mbps), _rtt_wire(rtt_ms):
        for name, sc, mport, base, cwnd in legs:
            env = {"PCCLT_PIPELINE": "1", "PCCLT_STRIPE_CONNS": str(sc),
                   "PCCLT_PIPELINE_WINDOW": "8"}
            if cwnd is not None:
                env["PCCLT_WIRE_CWND_BYTES"] = cwnd
            res = _spawn_world(world, _peer_wan_rtt,
                               _port("PCCLT_BENCH_MASTER_PORT_STRIPE", mport),
                               (world, nbytes, iters, 1, base, env),
                               inline_rank0=False)
            times = next(r["times"] for r in res if r["rank"] == 0)
            med = sorted(times)[len(times) // 2]
            out[name] = (2 * (world - 1) / world) * nbytes / med / 1e9
    out["wan_striped_speedup"] = (out["wan_striped_busbw_gbps"] /
                                  out["wan_striped_single_busbw_gbps"])
    out["wan_striped_cwnd_speedup"] = (
        out["wan_striped_cwnd_busbw_gbps"] /
        out["wan_striped_cwnd_single_busbw_gbps"])
    return out


def _peer_topo(rank, master_port, q, world, nbytes, iters, port_base, envs,
               gate_dir):
    """Peer for the topology-optimizer proof: joins in RANK ORDER (file
    gate) so the naive ring is deterministically [0, 1, ..., world-1] and
    the emulated mesh's pessimal edge provably sits on it."""
    from pccl_tpu.comm.api import Communicator, ReduceOp

    os.environ.update(envs[rank])  # this rank's per-edge wire model
    # ordered join: the master appends newcomers to the ring in join order,
    # so gating each connect on the previous rank's admission pins the
    # naive ring to rank order
    if rank > 0:
        deadline = time.time() + 120
        while not os.path.exists(os.path.join(gate_dir, str(rank - 1))):
            if time.time() > deadline:
                raise TimeoutError(f"rank {rank}: rank {rank-1} never joined")
            time.sleep(0.02)
    p2p, ss, bench = _rank_ports(port_base, rank)
    comm = Communicator("127.0.0.1", master_port,
                        p2p_port=p2p, ss_port=ss, bench_port=bench)
    comm.connect()
    with open(os.path.join(gate_dir, str(rank)), "w"):
        pass
    while comm.world_size < world:
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.02)

    rng = np.random.default_rng(5 + rank)
    x = rng.standard_normal(nbytes // 4).astype(np.float32)
    y = np.empty_like(x)

    def timed():
        comm.all_reduce(x, y, op=ReduceOp.AVG)  # warmup (and ring re-route)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            comm.all_reduce(x, y, op=ReduceOp.AVG)
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    t_naive = timed()
    # every peer votes; blocks until the master's ATSP round adopts a ring
    comm.optimize_topology()
    t_opt = timed()
    # second round: all edges already measured, so this adopts the finished
    # moonshot tour when it beats the quick solve — must improve or hold
    comm.optimize_topology()
    t_opt2 = timed()
    q.put({"rank": rank, "naive": t_naive, "opt": t_opt, "opt2": t_opt2})
    comm.destroy()


def run_topology_opt_bench(world: int = 4, nbytes: int = 4 << 20,
                           iters: int = 3, fast_mbps: float = 200.0,
                           slow_mbps: float = 25.0,
                           master_port: int = 48715,
                           port_base: int = 5000) -> Dict[str, float]:
    """The end-to-end proof that the ATSP topology optimizer wins — the
    reference's headline capability (bandwidth-aware ring optimization,
    PAPER.md), exercised on a deliberately heterogeneous emulated mesh
    (per-edge netem models, PCCLT_WIRE_*_MAP): every directed edge runs at
    ``fast_mbps`` except the pessimal pair 0<->1 at ``slow_mbps`` (+ high
    RTT), and peers join in rank order so the naive ring [0,1,...,n-1]
    provably crosses it. One slow edge gates the whole lockstep ring
    (arxiv 2606.01680's premise), so after ``optimize_topology()`` — whose
    bandwidth probes ride the same emulated edges — the adopted ring
    routes around the degraded link and the step time must drop. A second
    optimize adopts the background moonshot tour and must improve or hold.

    Returns naive/optimized/second-optimized median step seconds plus
    ``topology_opt_speedup`` (naive / optimized)."""
    import tempfile

    mbps = [[None if i == j else fast_mbps for j in range(world)]
            for i in range(world)]
    rtt = [[None if i == j else 8.0 for j in range(world)]
           for i in range(world)]
    mbps[0][1] = mbps[1][0] = slow_mbps   # the degraded link
    rtt[0][1] = rtt[1][0] = 60.0
    old_env = {k: os.environ.get(k) for k in
               ("PCCLT_BENCH_SECONDS", "PCCLT_BENCH_CONNECTIONS",
                "PCCLT_MOONSHOT_MS")}
    # short probe window + small flood pool: the optimize round serializes
    # probes per target, and per-edge pacing makes each one deterministic
    # anyway; moonshot small enough to finish before the second optimize
    os.environ["PCCLT_BENCH_SECONDS"] = "0.4"
    os.environ["PCCLT_BENCH_CONNECTIONS"] = "2"
    os.environ["PCCLT_MOONSHOT_MS"] = "400"
    try:
        with wire_topology(world, port_base, mbps=mbps, rtt_ms=rtt) as envs, \
                tempfile.TemporaryDirectory() as gate_dir:
            res = _spawn_world(world, _peer_topo,
                               _port("PCCLT_BENCH_MASTER_PORT_TOPO",
                                     master_port),
                               (world, nbytes, iters, port_base, envs,
                                gate_dir),
                               inline_rank0=False, timeout_s=600)
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    r0 = next(r for r in res if r["rank"] == 0)
    out = {"topology_naive_step_s": r0["naive"],
           "topology_opt_step_s": r0["opt"],
           "topology_opt2_step_s": r0["opt2"],
           "topology_opt_speedup": r0["naive"] / r0["opt"]}
    return out


def run_wan_bf16_bench(world: int = 4, nbytes: int = 16 << 20, iters: int = 3,
                       mbps: float = 100.0) -> Dict[str, float]:
    """bf16 twin of run_wan_bench: same paced wire, bf16 gradients plain
    (2 B/elem) vs u8-ZPS quantized from bf16 sources (1 B/elem; the typed
    widen-to-f32 SIMD kernels in quantize.cpp). Returns bf16-payload-basis
    busbw for both plus the speedup — the bytes-adjusted proof that
    quantizing the TPU gradient dtype pays on a constrained wire."""
    out: Dict[str, float] = {}
    with _paced_wire(mbps):
        for name, quant, mport, base in (
                # same 45xxx reasoning as run_wan_bench
                ("wan_bf16_busbw_gbps", False, 48675, 45800),
                ("wan_bf16_u8zps_busbw_gbps", True, 48677, 46200)):
            res = _spawn_world(world, _peer_wan,
                               _port("PCCLT_BENCH_MASTER_PORT_WANB", mport),
                               (world, nbytes, iters, quant, base, True),
                               inline_rank0=False)
            times = next(r["times"] for r in res if r["rank"] == 0)
            med = sorted(times)[len(times) // 2]
            out[name] = (2 * (world - 1) / world) * nbytes / med / 1e9
    out["wan_bf16_quant_speedup"] = (out["wan_bf16_u8zps_busbw_gbps"] /
                                     out["wan_bf16_busbw_gbps"])
    return out


def _peer_diloco_churn(rank, master_port, q, world, params_n, n_steps, port_base):
    """DiLoCo peer for the churn bench: runs a FIXED number of outer steps
    (the tag-0 collective keeps live peers in lockstep, so everyone exits
    together — a wall-clock deadline would strand the last peer mid-op in
    slow retries), admitting pending joiners between steps and riding out
    churn via the ring's retry contract. rank 0 streams per-step progress
    so the orchestrator can time the SIGKILL against real steps."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from pccl_tpu.comm.api import (Communicator, MasterUnreachableError,
                                   TooFewPeersError)
    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig

    # connect with retries: on a saturated 1-core host the master thread can
    # miss an accept window while peer processes churn through jax imports
    for attempt in range(10):
        comm = Communicator("127.0.0.1", master_port,
                            p2p_port=port_base + rank * 8,
                            ss_port=port_base + 1000 + rank * 8,
                            bench_port=port_base + 2000 + rank * 8)
        try:
            comm.connect()
            break
        except MasterUnreachableError:
            comm.destroy()
            if attempt == 9:
                raise
            time.sleep(1.0)
    # incumbents wait for the initial world; the rejoiner (rank >= world)
    # joins whoever is alive
    deadline = time.time() + 120
    while rank < world and comm.world_size < world and time.time() < deadline:
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.02)
    params = {"w": jnp.zeros((params_n,), jnp.float32)}
    diloco = Diloco(comm, params, DilocoConfig(shm_staging=True))
    cur = diloco.params()
    steps = []
    solo = False
    for it in range(n_steps):
        if comm.are_peers_pending():
            comm.update_topology()
        inner = jax.tree.map(lambda p: p - 0.01 * (rank + 1), cur)
        jax.block_until_ready(inner)
        t0 = time.perf_counter()
        try:
            cur = diloco.outer_step(inner)
            jax.block_until_ready(cur)
        except TooFewPeersError:
            solo = True  # everyone else finished/died; remaining steps are moot
            break
        steps.append((time.perf_counter() - t0, comm.world_size))
        if rank == 0:
            q.put({"progress": it + 1})
    q.put({"rank": rank, "steps": steps, "solo": solo})
    comm.destroy()


def run_diloco_churn_bench(world: int = 4, params_n: int = 12_500_000,
                           n_steps: int = 8, kill_after: int = 3,
                           master_port: int = 48679,
                           base: int = 41000) -> Dict[str, Any]:
    """BASELINE config 5's churn clause: DiLoCo outer steps at `world`
    peers with one SIGKILL mid-run and a fresh peer rejoining (the
    reference stress recipe, stresstest_orchestrator.py:9-41). The kill
    fires once rank 0 has completed `kill_after` steady steps. Returns
    steady-state median step seconds (full world), the worst churn-window
    step (absorbs abort + retry + re-establish), and the worlds rank 0
    saw."""
    import queue as queue_mod
    import signal

    from pccl_tpu.comm.api import MasterNode

    # default base 41000: derived bands span 41000-43064, clear of the hier
    # bench (38xxx-40xxx) and the wan legs (45xxx-48xxx). Callers that may
    # run concurrently with bench.py (the pytest wedge regression) pass
    # their own master_port and base.
    master = MasterNode("0.0.0.0",
                        _port("PCCLT_BENCH_MASTER_PORT_CHURN", master_port))
    master.run()
    try:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_peer_diloco_churn,
                             args=(r, master.port, q, world, params_n, n_steps,
                                   base))
                 for r in range(world)]
        for p in procs:
            p.start()
        # collect rank 0's progress stream; once the ring has done
        # `kill_after` steady steps, SIGKILL the last rank mid-step and
        # bring a fresh peer into the group
        results = []
        killed = False
        rejoiner = None
        deadline = time.time() + 600
        while len(results) < world and time.time() < deadline:
            try:
                msg = q.get(timeout=10)
            except queue_mod.Empty:
                continue
            if "progress" in msg:
                if not killed and msg["progress"] >= kill_after:
                    os.kill(procs[-1].pid, signal.SIGKILL)
                    killed = True
                    rejoiner = ctx.Process(
                        target=_peer_diloco_churn,
                        args=(world, master.port, q, world, params_n, n_steps,
                              base))
                    rejoiner.start()
            else:
                results.append(msg)
        for p in procs + ([rejoiner] if rejoiner else []):
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
    finally:
        master.interrupt()
        master.destroy()
    r0 = next((r for r in results if r["rank"] == 0), None)
    if r0 is None:
        raise RuntimeError(
            f"churn bench: rank 0 never reported (wedged?); got results from "
            f"ranks {sorted(r['rank'] for r in results)}")
    if not r0["steps"]:
        raise RuntimeError(f"churn bench: rank 0 completed no steps: {r0}")
    times = [t for t, w in r0["steps"]]
    worlds = [w for t, w in r0["steps"]]
    # steady = steps at full world; churn window = the slowest step (the one
    # that ate the abort + retry + rejoin establish)
    steady = sorted(t for t, w in r0["steps"] if w >= world) or sorted(times)
    return {
        "diloco_steady_step_s": steady[len(steady) // 2],
        "diloco_churn_step_s": max(times),
        "worlds_seen": sorted(set(worlds)),
        "steps_completed": len(times),
        "rejoiner_joined": any(r["rank"] == world for r in results),
    }


def _peer_master_recovery(rank, master_port, q, world, n_steps, port_base):
    """Peer for the master-recovery bench: small lockstep reduces, streaming
    per-step wall-clock end times + the comm's resume counter so the parent
    can time SIGKILL -> first post-restart collective."""
    from pccl_tpu.comm.api import (ConnectionLostError, Communicator,
                                   OperationAbortedError)

    p2p, ss, bench = _rank_ports(port_base, rank)
    comm = Communicator("127.0.0.1", master_port, p2p_port=p2p, ss_port=ss,
                        bench_port=bench, reconnect_attempts=20,
                        reconnect_backoff_ms=50, reconnect_backoff_cap_ms=250)
    comm.connect()
    while comm.world_size < world:
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.02)
    x = np.ones(1 << 14, np.float32)
    y = np.empty_like(x)
    steps = []
    step = 0
    while step < n_steps:
        try:
            comm.all_reduce(x, y)
        except (ConnectionLostError, OperationAbortedError):
            try:
                comm.update_topology()
            except Exception:  # noqa: BLE001 — resumed next loop
                time.sleep(0.02)
            continue
        steps.append((time.time(), comm.reconnect_count))
        if rank == 0:
            q.put({"progress": step + 1, "t": time.time(),
                   "resumes": comm.reconnect_count})
        step += 1
        time.sleep(0.05)
    q.put({"rank": rank, "steps": steps})
    comm.destroy()


def run_master_recovery_bench(world: int = 3, n_steps: int = 60,
                              master_port: int = 48694,
                              base: int = 43500) -> Dict[str, Any]:
    """Master HA recovery number (docs/10): SIGKILL the journaled master
    mid-run, restart it on the same port, and measure SIGKILL -> first
    post-restart collective completing (``master_recovery_s``). Peers ride
    the native session resume — the run must finish with zero rejoins."""
    import signal
    import subprocess
    import sys
    import tempfile

    import queue as queue_mod

    port = _port("PCCLT_BENCH_MASTER_PORT_HA", master_port)
    journal = os.path.join(tempfile.mkdtemp(prefix="pcclt_ha_"),
                           "master.journal")

    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    def spawn_master():
        p = subprocess.Popen([sys.executable, "-m", "pccl_tpu.comm.master",
                              "--port", str(port), "--journal", journal],
                             cwd=repo_root, stdout=subprocess.DEVNULL,
                             stderr=subprocess.STDOUT)
        import socket as socket_mod

        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                with socket_mod.create_connection(("127.0.0.1", port),
                                                  timeout=1):
                    return p
            except OSError:
                time.sleep(0.05)
        raise RuntimeError("bench master never started")

    master = spawn_master()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_peer_master_recovery,
                         args=(r, port, q, world, n_steps, base))
             for r in range(world)]
    t_kill = None
    t_first_resumed = None
    try:
        for p in procs:
            p.start()
        results = []
        deadline = time.time() + 300
        while len(results) < world and time.time() < deadline:
            try:
                msg = q.get(timeout=10)
            except queue_mod.Empty:
                continue
            if "progress" in msg:
                if t_kill is None and msg["progress"] >= 5:
                    master.send_signal(signal.SIGKILL)
                    master.wait(timeout=10)
                    t_kill = time.time()
                    time.sleep(0.5)  # outage window
                    master = spawn_master()
                elif (t_kill is not None and t_first_resumed is None
                      and msg["resumes"] >= 1):
                    t_first_resumed = msg["t"]
            else:
                results.append(msg)
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
        if master.poll() is None:
            master.kill()
        master.wait(timeout=10)
    if t_kill is None or t_first_resumed is None:
        raise RuntimeError("master recovery bench: outage never exercised "
                           f"(kill={t_kill}, resumed={t_first_resumed})")
    resumed_ranks = sum(1 for r in results
                        if any(res >= 1 for _, res in r.get("steps", [])))
    return {
        "master_recovery_s": t_first_resumed - t_kill,
        "master_recovery_resumed_peers": resumed_ranks,
    }


def _peer_tele_overhead(rank, master_port, q, nbytes, iters, port_base):
    """One loopback peer of the telemetry-overhead A/B: the observability
    plane's state (digest push cadence + trace capture) is inherited via
    env from the orchestrating leg."""
    from pccl_tpu.comm.api import ReduceOp, trace_clear, trace_enable

    plane_on = os.environ.get("PCCLT_TELEMETRY_PUSH_MS", "0") != "0"
    env_capture = bool(os.environ.get("PCCLT_TRACE"))
    if plane_on:
        trace_enable(True)
    comm = _connect(rank, master_port, 2, port_base)
    count = nbytes // 4
    x = np.full(count, float(rank + 1), dtype=np.float32)
    y = np.empty_like(x)
    comm.all_reduce(x, y, op=ReduceOp.SUM)  # warmup
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        comm.all_reduce(x, y, op=ReduceOp.SUM)
        times.append(time.perf_counter() - t0)
    assert float(y[0]) == 3.0
    q.put({"rank": rank, "times": times})
    comm.destroy()
    if plane_on and not env_capture:
        trace_enable(False)
        trace_clear()  # rank 0 runs inline: later legs start clean


def run_telemetry_overhead_bench(nbytes: int = 8 << 20,
                                 iters: int = 12) -> Dict[str, float]:
    """The observability plane's cost, pinned (docs/09): median loopback
    2-peer all-reduce step time with the full plane ON (100 ms digest
    cadence + flight-recorder capture) vs OFF. Returns the step medians
    and ``telemetry_overhead_pct`` — the acceptance bound is <= 1%, noise
    floor included (counters are always on in BOTH legs; the A/B isolates
    the digest thread + event capture)."""
    def leg(plane_on: bool) -> float:
        # pin the cadence explicitly for BOTH legs (and restore whatever
        # the caller had): an inherited PCCLT_TELEMETRY_PUSH_MS would
        # silently turn the OFF leg on and zero the A/B
        prior = os.environ.get("PCCLT_TELEMETRY_PUSH_MS")
        os.environ["PCCLT_TELEMETRY_PUSH_MS"] = "100" if plane_on else "0"
        try:
            res = _spawn_world(
                2, _peer_tele_overhead,
                _port("PCCLT_BENCH_MASTER_PORT_OBS", 48721),
                (nbytes, iters, 43900))
        finally:
            if prior is None:
                os.environ.pop("PCCLT_TELEMETRY_PUSH_MS", None)
            else:
                os.environ["PCCLT_TELEMETRY_PUSH_MS"] = prior
        r0 = next(r for r in res if r["rank"] == 0)
        ts = sorted(r0["times"])
        return ts[(len(ts) - 1) // 2]
    t_off = leg(False)
    t_on = leg(True)
    return {
        "telemetry_off_step_s": t_off,
        "telemetry_on_step_s": t_on,
        "telemetry_overhead_pct": 100.0 * (t_on - t_off) / t_off,
    }


def _peer_attribution(rank, master_port, q, nbytes, iters, port_base,
                      out_dir):
    """One peer of the attribution bench: flight recorder on, a few paced
    fp32 ring steps, then dump this peer's trace for trace_critic."""
    from pccl_tpu.comm.api import (ReduceOp, trace_clear, trace_dump,
                                   trace_enable, trace_events)

    env_capture = bool(os.environ.get("PCCLT_TRACE"))
    # rank 0 runs inline in the bench process, so the shared ring may hold
    # earlier legs' collectives — and their (epoch, seq) keys collide with
    # this run's, silently merging foreign timelines into the attribution.
    # Pick this leg's events out by timestamp instead (perf_counter shares
    # the recorder's CLOCK_MONOTONIC timebase, same idiom as
    # _peer_allreduce), so a user-requested PCCLT_TRACE capture is neither
    # cleared nor disabled.
    t_mark_us = time.perf_counter() * 1e6
    trace_enable(True)
    comm = _connect(rank, master_port, 2, port_base)
    count = nbytes // 4
    x = np.full(count, float(rank + 1), dtype=np.float32)
    y = np.empty_like(x)
    for _ in range(iters):
        comm.all_reduce(x, y, op=ReduceOp.SUM)
    assert float(y[0]) == 3.0
    path = os.path.join(out_dir, f"attr-peer{rank}.json")
    if rank == 0:
        evs = [e for e in trace_events() if e.get("ts", 0) >= t_mark_us]
        with open(path, "w") as f:
            json.dump({"traceEvents": evs}, f)
    else:
        trace_dump(path)  # fresh subprocess: the whole ring is this leg's
    q.put({"rank": rank, "trace": path})
    comm.destroy()
    if rank == 0 and not env_capture:
        trace_enable(False)
        trace_clear()  # rank 0 runs inline: later legs start clean


def run_attribution_bench(nbytes: int = 4 << 20, iters: int = 4,
                          base: int = 44200) -> Dict[str, Any]:
    """Critical-path attribution keys (docs/09): a netem-paced 2-peer
    world runs with the flight recorder on, each peer dumps its trace, and
    ``tools/trace_critic`` decomposes every collective into (peer, stage,
    edge, phase) segments — so every BENCH run carries WHERE its step time
    went (stall/codec/setup fractions + the dominant verdict), not just
    how long it took."""
    import tempfile

    from tools.trace_critic import analyze_files

    wire_map = ",".join(f"127.0.0.1:{_rank_ports(base, r)[0]}=800"
                        for r in range(2))
    prior = os.environ.get("PCCLT_WIRE_MBPS_MAP")
    os.environ["PCCLT_WIRE_MBPS_MAP"] = wire_map
    # TemporaryDirectory (not mkdtemp): the multi-MB per-peer trace dumps
    # are consumed by analyze_files below and must not pile up in /tmp
    # across bench runs
    with tempfile.TemporaryDirectory(prefix="pcclt-attr-") as tmp:
        try:
            res = _spawn_world(2, _peer_attribution,
                               _port("PCCLT_BENCH_MASTER_PORT_ATTR", 48731),
                               (nbytes, iters, base, tmp))
        finally:
            if prior is None:
                os.environ.pop("PCCLT_WIRE_MBPS_MAP", None)
            else:
                os.environ["PCCLT_WIRE_MBPS_MAP"] = prior
        report = analyze_files(
            [r["trace"] for r in sorted(res, key=lambda r: r["rank"])],
            labels=[f"rank{r['rank']}" for r in
                    sorted(res, key=lambda r: r["rank"])])
    agg = report["aggregate"]
    pt = agg["phase_totals_us"]
    # the denominator is the DISJOINT wall decomposition (cw + setup +
    # stage + stall + drain); codec time runs inside the stage windows, so
    # including it would double-count and bias every fraction low
    tot = sum(v for k, v in pt.items() if k != "codec") or 1.0
    verdicts = agg["verdicts"]
    top = max(verdicts.items(), key=lambda kv: kv[1])[0] if verdicts else ""
    return {
        "attribution_ops": float(agg["ops"]),
        "attribution_coverage": agg["mean_coverage"],
        "attribution_stall_frac": (pt.get("stall", 0.0) +
                                   pt.get("drain", 0.0)) / tot,
        "attribution_codec_frac": pt.get("codec", 0.0) / tot,
        "attribution_setup_frac": (pt.get("commence_wait", 0.0) +
                                   pt.get("op_setup", 0.0)) / tot,
        "attribution_verdict": top,
    }


def _peer_degraded(rank, master_port, q, world, count, steps, fault_at,
                   fault, port_base, mbps_map, watchdog):
    """One peer of the degraded-recovery bench: deterministic fp32 ring
    steps on a uniform emulated mesh; rank 0 injects the chaos fault on its
    outbound ring edge (discovered from stats — no ring-order knowledge
    needed) before step `fault_at`."""
    os.environ["PCCLT_WIRE_MBPS_MAP"] = mbps_map
    os.environ["PCCLT_WATCHDOG"] = watchdog
    import numpy as np

    from pccl_tpu.comm.api import ReduceOp, netem_inject

    comm = _connect(rank, master_port, world, port_base)
    x = np.ones(count, np.float32)
    y = np.empty_like(x)
    times = []
    for step in range(steps):
        if rank == 0 and fault and step == fault_at:
            edges = comm.stats()["edges"]
            ep = max(edges.items(), key=lambda kv: kv[1]["tx_bytes"])[0]
            netem_inject(ep, fault)
        t0 = time.perf_counter()
        comm.all_reduce(x, y, op=ReduceOp.SUM)
        times.append(time.perf_counter() - t0)
    q.put({"rank": rank, "times": times})
    comm.destroy()


def run_degraded_recovery_bench(world: int = 4, count: int = 1 << 20,
                                steps: int = 10, fault_at: int = 4,
                                mbps: float = 300.0,
                                degrade_mbit: float = 10.0,
                                base: int = 33000) -> Dict[str, float]:
    """Straggler-immune data plane, pinned in history (docs/05):

    * ``degraded_recovery_s`` — one ring edge degrades mbps→degrade_mbit
      MID-RUN (pccltNetemInject); measured wall-clock from the fault-step's
      start until the first step back under 2x the healthy baseline. The
      watchdog→failover/relay ladder should land this within seconds — the
      un-protected world stays degraded for the fault's whole duration.
    * ``relay_overhead_pct`` — the chaos/watchdog plane compiled in and
      ARMED but idle (no fault): median step vs the watchdog disabled,
      same map. Acceptance bound <= 1%.
    """
    endpoints = ",".join(
        f"127.0.0.1:{_rank_ports(base, r)[0]}={mbps}" for r in range(world))
    out: Dict[str, float] = {}

    fault = f"degrade@t=0s:{degrade_mbit}mbit/600s"
    res = _spawn_world(world, _peer_degraded,
                       _port("PCCLT_BENCH_MASTER_PORT_CHAOS", 48689),
                       (world, count, steps, fault_at, fault, base,
                        endpoints, "1"), inline_rank0=False)
    times = next(r["times"] for r in res if r["rank"] == 0)
    baseline = sorted(times[1:fault_at])[(fault_at - 2) // 2]
    recovery = 0.0
    for t in times[fault_at:]:
        recovery += t
        if t < 2 * baseline:
            break
    out["degraded_step_baseline_s"] = baseline
    out["degraded_recovery_s"] = recovery
    out["degraded_recovered_step_s"] = times[-1]

    # idle-plane overhead: watchdog ON (armed, never tripping) vs OFF
    def leg(watchdog: str, port_env_dflt: int, leg_base: int) -> float:
        r = _spawn_world(world, _peer_degraded, port_env_dflt,
                         (world, count, steps, -1, "", leg_base,
                          ",".join(f"127.0.0.1:{_rank_ports(leg_base, i)[0]}"
                                   f"={mbps}" for i in range(world)),
                          watchdog), inline_rank0=False)
        ts = sorted(next(x["times"] for x in r if x["rank"] == 0)[1:])
        return ts[(len(ts) - 1) // 2]
    t_on = leg("1", _port("PCCLT_BENCH_MASTER_PORT_CHAOS2", 48691), 33400)
    t_off = leg("0", _port("PCCLT_BENCH_MASTER_PORT_CHAOS3", 48693), 33800)
    out["relay_overhead_pct"] = 100.0 * (t_on - t_off) / t_off
    return out


def _peer_hier(rank, master_port, q, elems, iters, quantize, port_base):
    """One emulated TPU slice (4 virtual CPU devices) of the hierarchical
    all-reduce: ICI staging on the slice mesh, the native ring across
    slices, optional u8-ZPS on the DCN hop (BASELINE config 4 shape)."""
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=4")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.comm.api import DataType, QuantizationAlgorithm
    from pccl_tpu.parallel import mesh as mesh_lib
    from pccl_tpu.parallel.hierarchical import HierarchicalAllReduce

    comm = _connect(rank, master_port, 2, port_base)
    mesh = mesh_lib.make_mesh(jax.devices()[:4], axis_names=("dp",), shape=(4,))
    sharding = NamedSharding(mesh, P("dp"))
    g = jax.device_put(jnp.full((elems,), float(rank + 1), jnp.float32), sharding)
    tree = {"g": g}
    kw = {}
    if quantize:
        kw = dict(quantization=QuantizationAlgorithm.ZERO_POINT_SCALE,
                  quantized_dtype=DataType.UINT8)
    h = HierarchicalAllReduce(comm, tree, shm_staging=not quantize, **kw)
    times = []
    for it in range(iters + 1):  # first is warmup (jit compiles)
        t0 = time.perf_counter()
        out = h.all_reduce(tree)
        jax.block_until_ready(out)
        if it > 0:
            times.append(time.perf_counter() - t0)
    q.put({"rank": rank, "times": times})
    comm.destroy()


def run_hierarchical_bench(elems: int = 8 << 20, iters: int = 3) -> Dict[str, float]:
    """BASELINE config 4 shape: 2 slices x 4 virtual devices, global mean of
    an `elems` fp32 tree — plain DCN hop vs u8-ZPS quantized. Returns median
    step seconds for both."""
    out = {}
    # base 38000: derived bands (p2p/ss +1000/bench +2000) span 38000-40032,
    # clear of the churn bench (41xxx-43xxx), the wan legs (45xxx-48xxx),
    # the 48500+ masters and the 50000+ test ports
    for name, quant, mport, base in (("hier2_step_s", False, 48681, 38000),
                                     ("hier2_q8_step_s", True, 48683, 38400)):
        res = _spawn_world(2, _peer_hier,
                           _port("PCCLT_BENCH_MASTER_PORT_HIER", mport),
                           (elems, iters, quant, base), inline_rank0=False)
        times = next(r["times"] for r in res if r["rank"] == 0)
        out[name] = sorted(times)[len(times) // 2]
    return out


def _peer_soak(rank, master_port, q, world, n_tensors, elems, port_base):
    from pccl_tpu.comm.api import ReduceOp

    comm = _connect(rank, master_port, world, port_base)
    xs = [np.full(elems, float(rank + 1 + i), np.float32)
          for i in range(n_tensors)]
    warm = np.ones(1024, np.float32)
    comm.all_reduce(warm, op=ReduceOp.SUM)  # pay p2p establishment once
    t0 = time.perf_counter()
    comm.all_reduce_multiple_with_retry(xs, op=ReduceOp.SUM)
    dt = time.perf_counter() - t0
    base = world * (world + 1) / 2
    for i, x in enumerate(xs):
        assert float(x[0]) == base + world * i, f"soak value wrong: {x[0]}"
    q.put({"rank": rank, "dt": dt})
    comm.destroy()


def run_soak_bench(world: int = 8, n_tensors: int = 12,
                   elems: int = 8 << 20) -> float:
    """The reference's concurrent_reduce_test workload at scale
    (/root/reference/tests/concurrent_reduce_test/main.cpp:48-50 runs 12
    concurrent 8M-element reduces): one burst of ``n_tensors`` tagged
    collectives at ``world`` peers. Returns rank 0's burst wall-clock —
    surfaced as soak8_step_s in BENCH so large-world scaling regressions
    (RX wakeup herding, master consensus cost) are visible across rounds.
    The nightly guard twin with a per-byte floor lives at
    tests/test_comm_native.py:test_large_world_concurrent_soak."""
    # base 20000: derived bands span 20000-22028 (world 8), clear of every
    # other band (nothing below the guard test's 25xxx)
    res = _spawn_world(world, _peer_soak,
                       _port("PCCLT_BENCH_MASTER_PORT_SOAK", 48703),
                       (world, n_tensors, elems, 20000),
                       inline_rank0=False, timeout_s=600)
    return next(r["dt"] for r in res if r["rank"] == 0)


def run_hierarchical_wan_bench(elems: int = 4 << 20, iters: int = 3,
                               mbps: float = 100.0,
                               mports=(48693, 48695),
                               bases=(31000, 31400)) -> Dict[str, float]:
    """BASELINE config 4 under its actual wire: the same 2-slice global mean
    as run_hierarchical_bench, but with the cross-slice DCN hop paced to
    ``mbps`` megabit/s (PCCLT_WIRE_MBPS; the pacer also force-disables the
    zero-copy same-host transports, so the emulation can't be bypassed).
    This is where the quantized hop earns its keep — on unpaced loopback the
    u8 codec work dominates and the quantized leg *loses* (hier2_q8_step_s >
    hier2_step_s); on a constrained inter-slice wire the 4× byte reduction
    wins. Reference intent: the piquant WAN path
    (/root/reference/ccoip/src/cpp/quantize.cpp:22-57). Returns median step
    seconds for both plus the speedup ratio."""
    out: Dict[str, float] = {}
    with _paced_wire(mbps):
        # bases 31000/31400: derived bands span 31000-33408, clear of the
        # unpaced hier bench (38xxx-40xxx), the diloco-wan bands (28xxx-
        # 30xxx), and the wedge-regression test's 35xxx-37xxx + 48685
        for name, quant, mport, base in (
                ("hier2_wan_step_s", False, mports[0], bases[0]),
                ("hier2_wan_q8_step_s", True, mports[1], bases[1])):
            res = _spawn_world(2, _peer_hier,
                               _port("PCCLT_BENCH_MASTER_PORT_HIERWAN", mport),
                               (elems, iters, quant, base), inline_rank0=False)
            times = next(r["times"] for r in res if r["rank"] == 0)
            out[name] = sorted(times)[len(times) // 2]
    out["hier2_wan_quant_speedup"] = (out["hier2_wan_step_s"] /
                                      out["hier2_wan_q8_step_s"])
    return out


def _peer_diloco_wan(rank, master_port, q, world, params_n, iters, quantize,
                     port_base):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from pccl_tpu.comm.api import DataType, QuantizationAlgorithm
    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig

    comm = _connect(rank, master_port, world, port_base)
    params = {"w": jnp.zeros((params_n,), jnp.float32)}
    cfg = DilocoConfig(shm_staging=False)  # pacer disables zero-copy anyway
    if quantize:
        import dataclasses

        cfg = dataclasses.replace(
            cfg, quantization=QuantizationAlgorithm.ZERO_POINT_SCALE,
            quantized_dtype=DataType.UINT8)
    diloco = Diloco(comm, params, cfg)
    times, _ = _diloco_timed_steps(diloco, rank, iters)
    q.put({"rank": rank, "times": times})
    comm.destroy()


def run_diloco_wan_bench(world: int = 2, params_n: int = 5_000_000,
                         iters: int = 2, mbps: float = 100.0) -> Dict[str, float]:
    """One DiLoCo outer step on a paced wire: fp32 pseudo-gradient ring vs
    u8-ZPS quantized ring at ``params_n`` parameters over an emulated
    ``mbps``-megabit egress. The production DiLoCo shape (BASELINE config 5
    runs over WAN; reference recipe
    /root/reference/python/examples/nanogpt_diloco/sync_diloco.py) — the
    quantized ring must win here or the feature is pointless. Returns median
    outer-step seconds for both plus the speedup."""
    out: Dict[str, float] = {}
    with _paced_wire(mbps):
        # bases 28000/28400: derived bands span 28000-30408, clear of the
        # hier-wan bands (31xxx-33xxx) and everything above
        for name, quant, mport, base in (
                ("diloco_wan_step_s", False, 48689, 28000),
                ("diloco_wan_q8_step_s", True, 48691, 28400)):
            res = _spawn_world(world, _peer_diloco_wan,
                               _port("PCCLT_BENCH_MASTER_PORT_DILWAN", mport),
                               (world, params_n, iters, quant, base),
                               inline_rank0=False, timeout_s=600)
            times = next(r["times"] for r in res if r["rank"] == 0)
            out[name] = sorted(times)[len(times) // 2]
    out["diloco_wan_quant_speedup"] = (out["diloco_wan_step_s"] /
                                       out["diloco_wan_q8_step_s"])
    return out


def _diloco_timed_steps(diloco, rank, iters, donate_inner=False):
    """Shared warmup+timed outer-step loop for the diloco bench peers:
    synthetic inner step, first iteration pays the jit compiles, the rest
    are timed. Returns (times, final params tree)."""
    import jax

    mk = lambda t: jax.tree.map(lambda p: p - 0.01 * (rank + 1), t)  # noqa: E731
    if donate_inner:
        # at multi-GB sizes a fresh output buffer costs ~25x the op
        # (CPU-backend allocation pathology; see codec.build_codec)
        mk = jax.jit(mk, donate_argnums=(0,))
    times = []
    cur = diloco.params()
    for it in range(iters + 1):
        inner = mk(cur)
        jax.block_until_ready(inner)
        t0 = time.perf_counter()
        cur = diloco.outer_step(inner)
        jax.block_until_ready(cur)
        if it >= 1:
            times.append(time.perf_counter() - t0)
    return times, cur


def run_diloco_1b_bench(world: int = 2, params_n: int = 1_000_000_000,
                        iters: int = 3) -> Dict[str, float]:
    """THE driver-configured BASELINE metric: DiLoCo outer-step wall-clock
    at 1B parameters (BASELINE.md: "DiLoCo outer-step 1B params, 4 slices";
    the reference publishes no value for it). Runs ``world`` host peers
    each holding a 4 GB fp32 outer vector — shm-staged zero-copy ring,
    fused apply+unflatten — and returns rank 0's outer-step seconds as
    {median, [min, max]}: a headline this size carries its dispersion
    (VERDICT r4 #8), and README/docs quote the recorded median.
    Needs ~25 GB RAM per peer; callers gate on available memory."""
    # reuse the WAN peer body unpaced: same Diloco loop, shm staging on
    # (zero-copy same-host ring is the right transport at 4 GB)
    res = _spawn_world(world, _peer_diloco_big,
                       _port("PCCLT_BENCH_MASTER_PORT_1B", 48709),
                       (world, params_n, iters, 13000),
                       inline_rank0=False, timeout_s=1800)
    times = sorted(next(r["times"] for r in res if r["rank"] == 0))
    return {"diloco_1b_step_s": times[len(times) // 2],
            "diloco_1b_step_s_minmax": [times[0], times[-1]]}


def _peer_diloco_big(rank, master_port, q, world, params_n, iters, port_base):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig

    comm = _connect(rank, master_port, world, port_base)
    params = {"w": jnp.zeros((params_n,), jnp.float32)}
    diloco = Diloco(comm, params, DilocoConfig(shm_staging=True))
    times, _ = _diloco_timed_steps(diloco, rank, iters, donate_inner=True)
    q.put({"rank": rank, "times": times})
    comm.destroy()


def _peer_diloco_tpu(rank, master_port, q, world, params_n, iters, windows,
                     port_base):
    """DiLoCo peer with rank 0 on the REAL TPU (other ranks pin CPU — the
    chip is exclusive). Rank 0's phase profile is the on-chip breakdown."""
    import dataclasses

    import jax

    if rank != 0:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig

    comm = _connect(rank, master_port, world, port_base)
    params = {"w": jnp.zeros((params_n,), jnp.float32)}
    jax.block_until_ready(params["w"])
    diloco = Diloco(comm, params, DilocoConfig(shm_staging=True,
                                               comm_windows=windows))
    times, cur = _diloco_timed_steps(diloco, rank, iters)
    # one more step, rank 0 profiled — EVERY rank must run it (the ring is
    # a collective; a profiled step without a matching peer step stalls
    # into the abort path and the breakdown records the timeout)
    if rank == 0:
        diloco.cfg = dataclasses.replace(diloco.cfg, profile=True)
    inner = jax.tree.map(lambda p: p - 0.01 * (rank + 1), cur)
    jax.block_until_ready(inner)
    diloco.outer_step(inner)
    q.put({"rank": rank, "times": times, "phases": diloco.last_profile,
           "platform": jax.devices()[0].platform})
    comm.destroy()


def run_diloco_tpu_bench(world: int = 2, params_n: int = 5_000_000,
                         iters: int = 2, mbps: float = 100.0) -> Dict[str, Any]:
    """The on-chip DiLoCo outer step (VERDICT r3 #5): rank 0 holds its outer
    state and delta compute on the real TPU, the pseudo-gradient crosses a
    100 Mbit/s-paced wire — the production WAN shape where the wire, not
    the device staging, must dominate. Two legs:

    * windows=1 — phases separable: on-chip delta, D2H, ring, H2D+apply.
    * windows=4 — `_reduce_pipelined`: the D2H of window k+1 overlaps the
      ring of window k, so staging hides under the paced wire.

    Caveat recorded in docs/08_performance.md: this host reaches the chip
    through a development tunnel whose D2H sustains ~0.03 GB/s (production
    PCIe: 8-16 GB/s), so the D2H phase here is a pessimistic bound — if
    staging hides under the wire HERE, it vanishes on production hosts.
    Returns medians + rank-0 phase breakdowns for both legs."""
    out: Dict[str, Any] = {}
    with _paced_wire(mbps):
        # bases 15000/15400 -> derived bands 15000-17408, clear of the soak
        # band (whose p2p ports start at 20000 — a base of 18000 would put
        # this leg's bench band exactly there) and everything above
        for name, windows, mport, base in (
                ("diloco_tpu", 1, 48705, 15000),
                ("diloco_tpu_pipelined", 4, 48707, 15400)):
            res = _spawn_world(world, _peer_diloco_tpu,
                               _port("PCCLT_BENCH_MASTER_PORT_DILTPU", mport),
                               (world, params_n, iters, windows, base),
                               inline_rank0=False, timeout_s=600)
            r0 = next(r for r in res if r["rank"] == 0)
            if r0.get("platform") != "tpu":
                raise RuntimeError(
                    f"rank 0 ran on {r0.get('platform')}, not tpu")
            out[f"{name}_step_s"] = sorted(r0["times"])[len(r0["times"]) // 2]
            out[f"{name}_phases_s"] = {k: round(v, 3)
                                       for k, v in (r0["phases"] or {}).items()}
    return out


def _peer_diloco_async_tpu(rank, master_port, q, world, params_n, iters,
                           inner_s, sync, port_base):
    """Async-vs-sync DiLoCo peer with rank 0 on the REAL TPU. The inner
    phase is a calibrated on-device matmul burn of ~``inner_s`` wall
    seconds (per backend — CPU ranks calibrate themselves, so the ring
    isn't skew-limited), making 'does the paced ring hide behind inner
    compute' directly readable off the step time."""
    import jax

    if rank != 0:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax

    from pccl_tpu.parallel.diloco import AsyncDiloco, Diloco, DilocoConfig

    comm = _connect(rank, master_port, world, port_base)
    params = {"w": jnp.zeros((params_n,), jnp.float32)}
    jax.block_until_ready(params["w"])
    dl = (Diloco if sync else AsyncDiloco)(
        comm, params, DilocoConfig(shm_staging=True))

    # calibrated burn: chained normalized matmuls with a DYNAMIC trip count
    # (one jit cache entry for every n — a static n would make each timed
    # calibration call pay a fresh trace+compile and inflate the estimate).
    # The final readback fences it (docs 08: on this host only a host
    # readback is a trustworthy fence).
    m = jnp.full((1024, 1024), 1.0 / 1024.0, jnp.bfloat16)

    @jax.jit
    def burn(x, n):
        return lax.fori_loop(
            0, n, lambda i, y: (y @ m).astype(jnp.bfloat16), x)[0, 0]

    float(burn(m, jnp.int32(8)))  # the one compile
    # calibrate on a sample long enough (≥1 s) that the tunnel's ~100 ms
    # readback stalls are ~10 % noise — a small-difference scheme (t64−t8)
    # can go negative under one noisy readback and blow n_burn up by
    # orders of magnitude; a fat single sample cannot. Residual per-leg
    # calibration skew is cancelled out of hidden_s by reporting each
    # leg's measured burn and differencing per-leg overheads.
    n = 64
    while True:
        t0 = time.perf_counter()
        float(burn(m, jnp.int32(n)))
        dt = time.perf_counter() - t0
        if dt >= 1.0 or n >= 1 << 22:
            break
        n = min(max(n * 2, int(n * 1.2 / max(dt, 1e-4))), 1 << 22)
    per = dt / n
    n_burn = jnp.int32(min(max(8, int(inner_s / per)), 1 << 24))
    t0 = time.perf_counter()
    float(burn(m, n_burn))  # the burn the timed laps actually run, measured
    measured_inner = time.perf_counter() - t0

    step_fn = dl.outer_step if sync else dl.outer_step_async
    times = []
    cur = dl.params()
    for it in range(iters + 1):
        t0 = time.perf_counter()
        float(burn(m, n_burn))  # the inner phase (ring should hide under it)
        inner = jax.tree.map(lambda p: p - 0.01 * (rank + 1), cur)
        jax.block_until_ready(inner)
        cur = step_fn(inner)
        jax.block_until_ready(cur)
        if it >= 1:  # first lap pays jit compiles + async pipeline fill
            times.append(time.perf_counter() - t0)
    if not sync:
        dl.finish()
    q.put({"rank": rank, "times": times, "inner_s": measured_inner,
           "platform": jax.devices()[0].platform})
    comm.destroy()


def run_async_diloco_tpu_bench(world: int = 2, params_n: int = 5_000_000,
                               iters: int = 3, mbps: float = 100.0,
                               inner_s: float = 2.5) -> Dict[str, Any]:
    """Async DiLoCo's overlap claim, measured ON CHIP (VERDICT r4 #5): the
    one-step-delayed reduce (reference async_diloco.py,
    docs/md/07-.../03-AsyncDiloco.md) should make the steady-state step
    ≈ the inner-compute time, with the 100 Mbit/s-paced ring hidden behind
    it — vs the sync twin's compute + wire sum. Identical peers, identical
    calibrated ~``inner_s`` inner burn, same paced wire; only the driver
    class differs. Returns medians for both legs, the measured inner burn,
    and the wall-clock the overlap hides per step (sync − async)."""
    out: Dict[str, Any] = {}
    with _paced_wire(mbps):
        # bases 9000/9400: derived bands 9000-11408, below the 1B band
        # (13000+) and clear of the 25000/25400 bands test_comm_native.py
        # reserved for running concurrently with bench.py; the two legs
        # here run sequentially so their own overlap is moot
        for name, sync, mport, base in (
                ("async_diloco_tpu", False, 48711, 9000),
                ("async_diloco_tpu_sync_twin", True, 48713, 9400)):
            res = _spawn_world(world, _peer_diloco_async_tpu,
                               _port("PCCLT_BENCH_MASTER_PORT_ADILTPU", mport),
                               (world, params_n, iters, inner_s, sync, base),
                               inline_rank0=False, timeout_s=600)
            r0 = next(r for r in res if r["rank"] == 0)
            if r0.get("platform") != "tpu":
                raise RuntimeError(
                    f"rank 0 ran on {r0.get('platform')}, not tpu")
            out[f"{name}_step_s"] = sorted(r0["times"])[len(r0["times"]) // 2]
            # both legs' measured burns land in the artifact so a reader
            # can see the calibrations agreed
            out[f"{name}_inner_s"] = r0["inner_s"]
    # hidden wall per step = sync overhead (step − its own burn) minus
    # async overhead (ditto): the per-leg burn subtraction cancels the
    # small independent-calibration skew, leaving ≈ the paced ring time
    # that the async pipeline removed from the critical path
    out["async_diloco_tpu_hidden_s"] = (
        (out["async_diloco_tpu_sync_twin_step_s"]
         - out["async_diloco_tpu_sync_twin_inner_s"])
        - (out["async_diloco_tpu_step_s"]
           - out["async_diloco_tpu_inner_s"]))
    return out


def run_diloco_outer_bench(world: int = 2, params_n: int = 100_000_000,
                           outer_steps: int = 5,
                           windows: int = 1) -> "Tuple[float, Dict]":
    """DiLoCo outer-step wall-clock (device staging + AVG ring + outer SGD)
    at `params_n` parameters; returns (median outer-step seconds, per-phase
    breakdown of one fenced step — delta compute, D2H, stage copy, ring,
    H2D+apply, unflatten)."""
    res = _spawn_world(world, _peer_diloco,
                       _port("PCCLT_BENCH_MASTER_PORT4", 48657),
                       (world, params_n, outer_steps, windows),
                       inline_rank0=False, timeout_s=600)
    r0 = next(r for r in res if r["rank"] == 0)
    med = sorted(r0["times"])[len(r0["times"]) // 2]
    phases = {k: round(v, 3) for k, v in (r0.get("phases") or {}).items()}
    return med, phases


# ------------------------------------------------- shared-state chunk plane

def _peer_sync_swarm(rank, master_port, q, world, seeders, keys, elems,
                     chunk_bytes, mbps, port_base):
    # env BEFORE any native object exists: the chunk size is read per sync,
    # the wildcard pacing map per connection construction. The wildcard ip
    # edge gives each PROCESS one egress bucket (a per-NIC stand-in), so a
    # single distributor is a genuine bottleneck and N seeders genuinely
    # multiply bandwidth — what the chunk plane exists to exploit.
    os.environ["PCCLT_SS_CHUNK_BYTES"] = str(chunk_bytes)
    os.environ["PCCLT_WIRE_MBPS_MAP"] = f"127.0.0.1={mbps}"
    comm = _connect(rank, master_port, world, port_base)
    rng = np.random.default_rng(424242)
    role_seeder = rank < seeders
    if role_seeder:
        arrays = {f"k{i}": rng.standard_normal(elems).astype(np.float32)
                  for i in range(keys)}
        rev = 1
    else:
        arrays = {f"k{i}": np.zeros(elems, dtype=np.float32)
                  for i in range(keys)}
        rev = 0
    from pccl_tpu.comm.api import SharedState, TensorInfo
    st = SharedState([TensorInfo.from_numpy(k, v) for k, v in arrays.items()],
                     revision=rev)
    t0 = time.perf_counter()
    info = comm.sync_shared_state(st)
    wall = time.perf_counter() - t0
    digest = float(sum(v.sum() for v in arrays.values()))
    q.put({"rank": rank, "wall": wall, "rx": info.rx_bytes,
           "digest": digest, "counters": comm.stats()["counters"]})
    comm.destroy()


def run_sync_swarm_bench(world: int = 8, seeders: int = 4, keys: int = 8,
                         elems: int = 262144, chunk_bytes: int = 262144,
                         mbps: float = 250.0,
                         base: int = 34200) -> Dict[str, float]:
    """Shared-state swarm scaling (ISSUE-13 acceptance, docs/04):
    ``world - seeders`` simultaneous cold joiners adopt an
    ``keys * elems * 4``-byte state, once over the content-addressed chunk
    plane (multi-source fetch + mid-round seeder promotion) and once on
    the forced single-seeder baseline (PCCLT_SS_CHUNK_BYTES=0). Keys:

    * ``sync_swarm_chunked_s`` / ``sync_swarm_legacy_s`` — slowest
      joiner's sync wall per leg;
    * ``sync_swarm_speedup`` — legacy / chunked (gate: >= 2x);
    * ``sync_swarm_resourced_chunks`` / ``_dup_chunks`` — failover noise.

    Per-chunk conservation is asserted byte-exact on every joiner:
    fetched + re-sourced - dup == unique state bytes.
    """
    nbytes = keys * elems * 4
    out: Dict[str, float] = {}

    def leg(chunk: int, port_env: str, dflt: int, leg_base: int):
        res = _spawn_world(world, _peer_sync_swarm, _port(port_env, dflt),
                           (world, seeders, keys, elems, chunk, mbps,
                            leg_base),
                           inline_rank0=False, timeout_s=420)
        joiners = [r for r in res if r["rank"] >= seeders]
        ref = next(r for r in res if r["rank"] == 0)["digest"]
        for r in joiners:
            assert r["digest"] == ref, "joiner diverged from popular content"
            assert r["rx"] == nbytes, (r["rx"], nbytes)
            c = r["counters"]
            if chunk:
                got = (c["ss_chunk_bytes_fetched"]
                       + c["ss_chunk_bytes_resourced"]
                       - c["ss_chunk_bytes_dup"])
                assert got == nbytes, f"conservation broken: {got} != {nbytes}"
        return (max(r["wall"] for r in joiners),
                sum(r["counters"]["ss_chunks_resourced"] for r in joiners),
                sum(r["counters"]["ss_chunks_dup"] for r in joiners))

    chunked, resourced, dup = leg(chunk_bytes, "PCCLT_BENCH_MASTER_PORT_SS",
                                  48691, base)
    legacy, _, _ = leg(0, "PCCLT_BENCH_MASTER_PORT_SS2", 48693, base + 600)
    out["sync_swarm_chunked_s"] = chunked
    out["sync_swarm_legacy_s"] = legacy
    out["sync_swarm_speedup"] = legacy / chunked if chunked > 0 else 0.0
    out["sync_swarm_resourced_chunks"] = float(resourced)
    out["sync_swarm_dup_chunks"] = float(dup)
    return out


# ------------------------------------------------- fleet-scale master plane

def _scrape_http(port: int, path: str = "/metrics",
                 timeout: float = 30.0) -> str:
    import socket as socket_mod
    with socket_mod.create_connection(("127.0.0.1", port),
                                      timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(f"GET {path} HTTP/1.0\r\nHost: x\r\n\r\n".encode())
        buf = b""
        while True:
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            buf += chunk
    return buf.split(b"\r\n\r\n", 1)[1].decode("utf-8", "replace")


def _prom_value(text: str, name: str):
    """First sample value of an unlabelled series, or None."""
    for line in text.split("\n"):
        if line.startswith(name + " "):
            return float(line.rsplit(None, 1)[-1])
    return None


def run_master_scale_bench(peers: int = 1000, edges: int = 8,
                           hz: float = 12.0, seconds: float = 4.0,
                           threads: int = 8,
                           master_port: int = 48715) -> Dict[str, Any]:
    """The N=1000 observability gate (docs/09): one master, ``peers``
    observer sessions (the PCCP/2 hello tail byte — they push digests but
    never join the world) each pushing an ``edges``-edge digest at ``hz``,
    all from ``pccltDigestFlood`` (native threads; ctypes releases the
    GIL). Measures the whole ISSUE-17 surface in one run:

    * ``master_scale_ingest_rate`` — digests/s actually accepted (the
      flood is paced, so this ~= peers*hz when the master keeps up) with
      ``master_scale_digest_drops`` the bounded-queue drop count;
    * ``master_scale_fold_p99_s`` — off-dispatcher fold latency p99, from
      the master's own ``pcclt_master_digest_fold_seconds`` histogram;
    * ``master_scale_scrape_s`` / ``_bytes`` / ``_series`` — one timed
      /metrics render at the default edge top-K, promlint-validated
      (``master_scale_promlint_violations`` must be 0);
    * ``master_scale_admission_quiet_s`` vs ``_flood_s`` — the paired A/B
      on DISPATCHER round latency (observer hello -> welcome round trips
      via ``pccltAdmissionProbe``) with the digest flood off vs on: the
      enqueue-only ingest path must leave admission latency unchanged;
    * ``master_scale_health_quiet_s`` vs ``_flood_s`` — /health cost with
      the plane idle vs mid-flood (the dispatcher must stay responsive);
    * ``master_scale_replay_s`` — journal replay wall for ``peers``
      client records (cold-restart cost at fleet scale).

    CI gates (ci.yml fleet-scale lane): ingest >= 10k/s, scrape < 1 s,
    drops == 0, promlint clean."""
    import ctypes as c
    import subprocess
    import sys
    import tempfile

    from pccl_tpu.comm import _native, promlint

    lib = _native.load()
    if not hasattr(lib, "pccltDigestFlood"):
        raise RuntimeError("libpcclt.so too old: no pccltDigestFlood")

    port = _port("PCCLT_BENCH_MASTER_PORT_SCALE", master_port)
    mport = port + 1
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    # fresh renders: the render cache would make the timed scrape measure
    # a memcpy; the gate is about the real top-K render at N=1000
    env["PCCLT_METRICS_MAX_AGE_MS"] = "0"
    env.pop("PCCLT_METRICS_EDGE_TOPK", None)   # default top-K = the gate
    master = subprocess.Popen(
        [sys.executable, "-m", "pccl_tpu.comm.master", "--port", str(port),
         "--metrics-port", str(mport)],
        cwd=repo_root, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT)
    out: Dict[str, Any] = {"master_scale_peers": float(peers),
                           "master_scale_edges_per_peer": float(edges)}
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                _scrape_http(mport, "/health", timeout=2)
                break
            except OSError:
                time.sleep(0.05)
        else:
            raise RuntimeError("scale-bench master never served /health")

        t0 = time.perf_counter()
        _scrape_http(mport, "/health")
        out["master_scale_health_quiet_s"] = time.perf_counter() - t0

        def admission(rounds: int = 50):
            mean = c.c_double(0.0)
            p99 = c.c_double(0.0)
            rc = lib.pccltAdmissionProbe(b"127.0.0.1", port, rounds,
                                         c.byref(mean), c.byref(p99))
            if rc != 0:
                raise RuntimeError(f"pccltAdmissionProbe rc={rc}")
            return mean.value, p99.value

        (out["master_scale_admission_quiet_s"],
         out["master_scale_admission_quiet_p99_s"]) = admission()

        sent = c.c_uint64(0)
        wall = c.c_double(0.0)
        flood_err: List[int] = []

        def flood():
            flood_err.append(lib.pccltDigestFlood(
                b"127.0.0.1", port, peers, edges, hz, seconds, threads,
                c.byref(sent), c.byref(wall)))

        import threading
        th = threading.Thread(target=flood)
        th.start()
        # mid-flood control-plane responsiveness: /health while ~peers*hz
        # digests/s are landing
        time.sleep(max(0.5, seconds * 0.4))
        t0 = time.perf_counter()
        _scrape_http(mport, "/health")
        out["master_scale_health_flood_s"] = time.perf_counter() - t0
        # the A/B's flood leg: admission round trips WHILE ~peers*hz
        # digests/s are hitting the same dispatcher
        (out["master_scale_admission_flood_s"],
         out["master_scale_admission_flood_p99_s"]) = admission()
        th.join(timeout=seconds * 20 + 120)
        if th.is_alive():
            raise RuntimeError("digest flood wedged")
        if flood_err and flood_err[0] != 0:
            raise RuntimeError(f"pccltDigestFlood rc={flood_err[0]}")
        out["master_scale_digests_sent"] = float(sent.value)
        out["master_scale_flood_wall_s"] = wall.value
        out["master_scale_ingest_rate"] = (
            sent.value / wall.value if wall.value > 0 else 0.0)

        # fold drain: every accepted digest must land in health state
        deadline = time.time() + 60
        folded = drops = 0.0
        while time.time() < deadline:
            text = _scrape_http(mport)
            folded = _prom_value(
                text, "pcclt_master_telemetry_digests_total") or 0.0
            drops = _prom_value(
                text, "pcclt_master_digest_queue_dropped_total") or 0.0
            if folded + drops >= sent.value:
                break
            time.sleep(0.2)
        out["master_scale_digests_folded"] = folded
        out["master_scale_digest_drops"] = drops
        out["master_scale_fold_p99_s"] = _prom_value(
            text, "pcclt_master_digest_fold_p99_seconds") or 0.0

        # THE scrape gate: one timed render of the steady-state surface
        t0 = time.perf_counter()
        text = _scrape_http(mport)
        out["master_scale_scrape_s"] = time.perf_counter() - t0
        out["master_scale_scrape_bytes"] = float(len(text))
        out["master_scale_scrape_series"] = float(sum(
            1 for ln in text.split("\n") if ln and not ln.startswith("#")))
        out["master_scale_promlint_violations"] = float(
            len(promlint.lint(text)))

        t0 = time.perf_counter()
        _scrape_http(mport, "/health?history=1")
        out["master_scale_health_history_s"] = time.perf_counter() - t0
    finally:
        if master.poll() is None:
            master.kill()
        master.wait(timeout=10)

    # cold-restart cost: journal write + replay of `peers` client records,
    # entirely native (pccltMasterReplayBench)
    if hasattr(lib, "pccltMasterReplayBench"):
        jpath = os.path.join(tempfile.mkdtemp(prefix="pcclt_scale_"),
                             "replay.journal")
        w_s = c.c_double(0.0)
        r_s = c.c_double(0.0)
        rc = lib.pccltMasterReplayBench(jpath.encode(), peers,
                                        c.byref(w_s), c.byref(r_s))
        if rc != 0:
            raise RuntimeError(f"pccltMasterReplayBench rc={rc}")
        out["master_scale_replay_write_s"] = w_s.value
        out["master_scale_replay_s"] = r_s.value
    return out


# ------------------------------------------------- schedule synthesizer

def _peer_sched_bcast(rank, master_port, q, world, nbytes, iters, port_base,
                      envs, gate_dir):
    """Broadcast peer for the schedule bench: rank 0 publishes its
    sorted-uuid gather slot through a file gate so every peer names the
    SAME root (slot order is join-order-racy; a root mismatch is a
    parameter disagreement and gets the minority kicked)."""
    os.environ.update(envs[rank])  # this rank's per-edge wire model
    comm = _connect(rank, master_port, world, port_base)
    # measure the emulated edges so the synthesizer's tree hangs off the
    # hub (the forced algo fixes the KIND; the shape comes from the matrix)
    comm.optimize_topology()
    root_path = os.path.join(gate_dir, "root_slot")
    if rank == 0:
        with open(root_path + ".tmp", "w") as f:
            f.write(str(comm.gather_slot))
        os.replace(root_path + ".tmp", root_path)
    deadline = time.time() + 120
    while not os.path.exists(root_path):
        if time.time() > deadline:
            raise TimeoutError(f"rank {rank}: root slot never published")
        time.sleep(0.02)
    with open(root_path) as f:
        root = int(f.read())

    count = nbytes // 4
    ref = (np.arange(count, dtype=np.float32) % 509.0) + 1.0
    buf = ref.copy() if comm.gather_slot == root \
        else np.full(count, -7.0, dtype=np.float32)
    comm.broadcast(buf, root=root, tag=31)  # warmup (+ correctness)
    if not np.array_equal(buf, ref):
        raise AssertionError(f"rank {rank}: broadcast payload mismatch")
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        comm.broadcast(buf, root=root, tag=31)
        times.append(time.perf_counter() - t0)
    q.put({"rank": rank, "t": sorted(times)[len(times) // 2]})
    comm.destroy()


def _peer_sched_a2a(rank, master_port, q, world, nbytes, iters, port_base,
                    envs):
    """All-to-all peer for the schedule bench: slot-seeded blocks so one
    verification pass proves delivery, then a timed loop."""
    os.environ.update(envs[rank])
    comm = _connect(rank, master_port, world, port_base)
    comm.optimize_topology()  # measured matrix -> site-aware schedules
    slot = comm.gather_slot
    per = nbytes // 4 // world
    send = np.concatenate(
        [np.full(per, slot * 100.0 + j + 0.25, dtype=np.float32)
         for j in range(world)])
    recv, _ = comm.all_to_all(send, tag=32)  # warmup (+ correctness)
    for i in range(world):
        if not np.array_equal(recv[i * per:(i + 1) * per],
                              np.full(per, i * 100.0 + slot + 0.25,
                                      dtype=np.float32)):
            raise AssertionError(f"rank {rank}: a2a block {i} mismatch")
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        comm.all_to_all(send, recv, tag=32)
        times.append(time.perf_counter() - t0)
    q.put({"rank": rank, "t": sorted(times)[len(times) // 2]})
    comm.destroy()


def run_schedule_bench(world: int = 4, nbytes: int = 4 << 20, iters: int = 3,
                       hub_mbps: float = 200.0, spoke_mbps: float = 20.0,
                       intra_mbps: float = 400.0,
                       inter_mbps: float = 40.0) -> Dict[str, float]:
    """End-to-end proof that the collective schedule synthesizer (docs/12)
    beats the one-ring-for-everything baseline on the two wire shapes it
    was built for, with same-run ring baselines:

    - hub-and-spoke: every spoke<->spoke edge at ``spoke_mbps``, hub edges
      at ``hub_mbps``. Any Hamiltonian ring crosses slow spoke edges, so a
      ring broadcast is gated at ``spoke_mbps``; the bandwidth-weighted
      tree fans out from the hub root on fast edges
      (``sched_hub_speedup`` = ring / tree median step time).
    - two-datacenter: ranks split into two sites, ``intra_mbps`` inside,
      ``inter_mbps`` across. The ring all-to-all's rotation makes the
      block at distance r ride r sequential hops (multiply crossing the
      cut); the mesh sends every block once, directly
      (``sched_2dc_speedup`` = ring / mesh, plus the mesh's algorithmic
      ``alltoall_busbw_gbps`` = (N-1)/N * bytes / t).

    PCCLT_SCHEDULE_FORCE pins each leg's algorithm (master-side; the
    master lives in this process), so the deltas isolate the schedule —
    same wire, same peers, same payload."""
    import tempfile

    hub = [[None if i == j else (hub_mbps if 0 in (i, j) else spoke_mbps)
            for j in range(world)] for i in range(world)]
    half = world // 2
    twodc = [[None if i == j else
              (intra_mbps if (i < half) == (j < half) else inter_mbps)
              for j in range(world)] for i in range(world)]

    old_env = {k: os.environ.get(k) for k in
               ("PCCLT_SCHEDULE", "PCCLT_SCHEDULE_FORCE",
                "PCCLT_BENCH_SECONDS", "PCCLT_BENCH_CONNECTIONS")}
    os.environ["PCCLT_SCHEDULE"] = "1"
    os.environ["PCCLT_BENCH_SECONDS"] = "0.4"
    os.environ["PCCLT_BENCH_CONNECTIONS"] = "2"

    def bcast_leg(force, mport_env, mport, base):
        os.environ["PCCLT_SCHEDULE_FORCE"] = force
        with wire_topology(world, base, mbps=hub) as envs, \
                tempfile.TemporaryDirectory() as gate_dir:
            res = _spawn_world(world, _peer_sched_bcast,
                               _port(mport_env, mport),
                               (world, nbytes, iters, base, envs, gate_dir),
                               inline_rank0=False, timeout_s=600)
        return max(r["t"] for r in res)  # collective ends with slowest rank

    def a2a_leg(force, mport_env, mport, base):
        os.environ["PCCLT_SCHEDULE_FORCE"] = force
        with wire_topology(world, base, mbps=twodc) as envs:
            res = _spawn_world(world, _peer_sched_a2a,
                               _port(mport_env, mport),
                               (world, nbytes, iters, base, envs),
                               inline_rank0=False, timeout_s=600)
        return max(r["t"] for r in res)

    try:
        t_tree = bcast_leg("tree", "PCCLT_BENCH_MASTER_PORT_SCHED", 48741,
                           34200)
        t_bring = bcast_leg("ring", "PCCLT_BENCH_MASTER_PORT_SCHED2", 48743,
                            34600)
        t_mesh = a2a_leg("mesh", "PCCLT_BENCH_MASTER_PORT_SCHED3", 48745,
                         35000)
        t_aring = a2a_leg("ring", "PCCLT_BENCH_MASTER_PORT_SCHED4", 48747,
                          35400)
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {"sched_hub_tree_step_s": t_tree,
            "sched_hub_ring_step_s": t_bring,
            "sched_hub_speedup": t_bring / t_tree,
            "sched_2dc_mesh_step_s": t_mesh,
            "sched_2dc_ring_step_s": t_aring,
            "sched_2dc_speedup": t_aring / t_mesh,
            "alltoall_busbw_gbps":
                (world - 1) / world * nbytes / t_mesh / 1e9}
