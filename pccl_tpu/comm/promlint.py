"""Strict Prometheus text-format (0.0.4) validator.

The master's ``/metrics`` surface is consumed by real scrapers, which
reject the WHOLE scrape on a single malformed line — a regression there
is an observability outage, not a cosmetic bug. This module is the
reusable gate both the unit tests and the fleet-scale bench lane apply to
every scrape they take:

* every line parses (comment, blank, or ``name{labels} value``),
* ``# TYPE`` precedes its family's samples and appears at most once,
* a family's samples are contiguous (no interleaving — scrapers group by
  family and many reject re-opened families),
* no duplicate series (same name + identical label set),
* label values are well-formed (quotes closed, only ``\\``, ``\\"`` and
  ``\\n`` escapes),
* histograms are coherent: ``le`` parses as a float, bucket counts are
  monotone non-decreasing in ``le`` order, the ``+Inf`` bucket exists and
  equals ``_count``, and ``_sum``/``_count`` accompany the buckets.

``lint(text)`` returns a list of violation strings (empty = clean);
``assert_valid(text)`` raises ``AssertionError`` with the first few.
"""
from __future__ import annotations

import math
import re
from typing import Dict, List, Tuple

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*")

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_of(name: str, hist_families: set) -> str:
    """Collapse histogram sample names onto their declared family."""
    for suf in _HIST_SUFFIXES:
        if name.endswith(suf) and name[: -len(suf)] in hist_families:
            return name[: -len(suf)]
    return name


def _parse_labels(raw: str, lineno: int, errors: List[str]):
    """Parse the inside of ``{...}`` into an ordered (name, value) tuple.

    Returns None (and records the violation) on any malformed construct.
    """
    labels: List[Tuple[str, str]] = []
    i, n = 0, len(raw)
    while i < n:
        m = _LABEL_NAME_RE.match(raw, i)
        if not m:
            errors.append(f"line {lineno}: bad label name at {raw[i:i+20]!r}")
            return None
        lname = m.group(0)
        i = m.end()
        if i >= n or raw[i] != "=":
            errors.append(f"line {lineno}: expected '=' after label {lname!r}")
            return None
        i += 1
        if i >= n or raw[i] != '"':
            errors.append(f"line {lineno}: unquoted value for label {lname!r}")
            return None
        i += 1
        val = []
        closed = False
        while i < n:
            ch = raw[i]
            if ch == "\\":
                if i + 1 >= n or raw[i + 1] not in ('"', "\\", "n"):
                    errors.append(
                        f"line {lineno}: invalid escape in label {lname!r}")
                    return None
                val.append({"n": "\n"}.get(raw[i + 1], raw[i + 1]))
                i += 2
                continue
            if ch == '"':
                closed = True
                i += 1
                break
            if ch == "\n":
                break
            val.append(ch)
            i += 1
        if not closed:
            errors.append(f"line {lineno}: unterminated value for {lname!r}")
            return None
        labels.append((lname, "".join(val)))
        if i < n and raw[i] == ",":
            i += 1
        elif i < n:
            errors.append(f"line {lineno}: expected ',' between labels")
            return None
    return tuple(labels)


def lint(text: str) -> List[str]:
    errors: List[str] = []
    types: Dict[str, str] = {}          # family -> declared type
    helped: set = set()
    hist_families: set = set()
    closed_families: set = set()        # families whose sample block ended
    seen_series: set = set()
    # histogram accounting: (family, labels-without-le) -> buckets/sum/count
    buckets: Dict[Tuple, List[Tuple[float, float, int]]] = {}
    counts: Dict[Tuple, float] = {}
    sums: Dict[Tuple, bool] = {}
    current_family = None

    for lineno, line in enumerate(text.split("\n"), 1):
        if line == "":
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                fam = parts[2]
                if not _NAME_RE.fullmatch(fam):
                    errors.append(f"line {lineno}: bad family name {fam!r}")
                    continue
                if parts[1] == "TYPE":
                    if len(parts) < 4 or parts[3].split()[0] not in (
                            "counter", "gauge", "histogram", "summary",
                            "untyped"):
                        errors.append(f"line {lineno}: bad TYPE for {fam}")
                        continue
                    if fam in types:
                        errors.append(f"line {lineno}: duplicate TYPE {fam}")
                    t = parts[3].split()[0]
                    types[fam] = t
                    if t == "histogram":
                        hist_families.add(fam)
                else:
                    if fam in helped:
                        errors.append(f"line {lineno}: duplicate HELP {fam}")
                    helped.add(fam)
            # other comments are legal and ignored
            continue
        m = _NAME_RE.match(line)
        if not m:
            errors.append(f"line {lineno}: unparsable line {line[:40]!r}")
            continue
        name = m.group(0)
        rest = line[m.end():]
        labels: Tuple = ()
        if rest.startswith("{"):
            close = rest.rfind("}")
            if close < 0:
                errors.append(f"line {lineno}: unclosed label set")
                continue
            parsed = _parse_labels(rest[1:close], lineno, errors)
            if parsed is None:
                continue
            labels = parsed
            rest = rest[close + 1:]
        fields = rest.split()
        if len(fields) not in (1, 2):  # value [timestamp]
            errors.append(f"line {lineno}: expected value after series")
            continue
        try:
            value = float(fields[0])
        except ValueError:
            errors.append(f"line {lineno}: bad value {fields[0]!r}")
            continue

        fam = _family_of(name, hist_families)
        if fam in types and fam not in helped and fam not in closed_families \
                and fam != current_family:
            pass  # TYPE-only families are fine
        if fam != current_family:
            if fam in closed_families:
                errors.append(
                    f"line {lineno}: family {fam} reopened (samples must be "
                    "contiguous)")
            if current_family is not None:
                closed_families.add(current_family)
            current_family = fam
        series_key = (name, labels)
        if series_key in seen_series:
            errors.append(f"line {lineno}: duplicate series {name}{labels!r}")
        seen_series.add(series_key)

        if fam in hist_families:
            base = tuple(l for l in labels if l[0] != "le")
            key = (fam, base)
            if name == fam + "_bucket":
                le = dict(labels).get("le")
                if le is None:
                    errors.append(f"line {lineno}: bucket without le label")
                    continue
                try:
                    le_f = math.inf if le == "+Inf" else float(le)
                except ValueError:
                    errors.append(f"line {lineno}: bad le {le!r}")
                    continue
                buckets.setdefault(key, []).append((le_f, value, lineno))
            elif name == fam + "_count":
                counts[key] = value
            elif name == fam + "_sum":
                sums[key] = True
            elif name != fam:
                errors.append(
                    f"line {lineno}: stray sample {name} in histogram {fam}")

    for (fam, base), bs in buckets.items():
        bs_sorted = sorted(bs, key=lambda b: b[0])
        prev = -1.0
        for le_f, v, lineno in bs_sorted:
            if v < prev:
                errors.append(
                    f"line {lineno}: {fam}{dict(base)!r} bucket le={le_f} "
                    f"count {v} < previous {prev} (non-monotone)")
            prev = v
        if not bs_sorted or bs_sorted[-1][0] != math.inf:
            errors.append(f"{fam}{dict(base)!r}: missing +Inf bucket")
        else:
            inf_v = bs_sorted[-1][1]
            if (fam, base) not in counts:
                errors.append(f"{fam}{dict(base)!r}: buckets without _count")
            elif counts[(fam, base)] != inf_v:
                errors.append(
                    f"{fam}{dict(base)!r}: +Inf bucket {inf_v} != _count "
                    f"{counts[(fam, base)]}")
        if (fam, base) not in sums:
            errors.append(f"{fam}{dict(base)!r}: buckets without _sum")
    return errors


def assert_valid(text: str, context: str = "scrape") -> None:
    errs = lint(text)
    if errs:
        shown = "\n  ".join(errs[:12])
        more = f"\n  ... and {len(errs) - 12} more" if len(errs) > 12 else ""
        raise AssertionError(
            f"{context}: {len(errs)} prometheus-text violation(s):\n"
            f"  {shown}{more}")
