"""pccl_tpu.comm — fault-tolerant collectives over TCP (native core).

Public surface (reference parity: python/framework/pccl/__init__.py):
Communicator, MasterNode, SharedState, TensorInfo, ReduceOp, DataType,
QuantizationAlgorithm, SharedStateSyncStrategy, Attribute, AsyncReduceHandle,
ReduceDescriptor, plus the PcclError exception family.

The native library loads lazily on first Communicator/MasterNode use, so
importing this package never requires the C++ build (bench.py and pure-JAX
users fall back cleanly).
"""

from .api import (  # noqa: F401
    AsyncReduceHandle,
    Attribute,
    Communicator,
    ConnectionLostError,
    DataType,
    DeviceType,
    KickedError,
    MasterNode,
    MasterUnreachableError,
    OperationAbortedError,
    PcclError,
    QuantizationAlgorithm,
    ReduceDescriptor,
    ReduceInfo,
    ReduceOp,
    Result,
    SharedState,
    SharedStateSyncInfo,
    SharedStateSyncStrategy,
    TooFewPeersError,
    TensorInfo,
    shm_ndarray,
    netem_inject,
    trace_clear,
    trace_dump,
    trace_enable,
    trace_events,
)
