"""Pure-Python 2-peer loopback ring all-reduce benchmark (fallback path).

bench.py prefers the native (C++) stack once built; this module keeps the
benchmark meaningful before/without it: two OS processes, one TCP connection,
full-duplex reduce-scatter + all-gather on fp32 — i.e. an actual on-the-wire
all-reduce measurement, matching BASELINE.md config 1
("basic_reduce_test: fp32 allreduce, 2 loopback peers").

busbw for ring all-reduce = 2*(N-1)/N * bytes / time  (N=2 → bytes/time).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import socket
import threading
import time

import numpy as np

_PORT = int(os.environ.get("PCCLT_BENCH_PORT", "47911"))


def _send_all(sock: socket.socket, buf: memoryview) -> None:
    sock.sendall(buf)


def _recv_all(sock: socket.socket, buf: memoryview) -> None:
    got = 0
    n = len(buf)
    while got < n:
        r = sock.recv_into(buf[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r


def _allreduce_2peer(sock: socket.socket, rank: int, x: np.ndarray) -> np.ndarray:
    """In-place sum all-reduce between 2 peers; returns reduced array."""
    n = x.size
    half = n // 2
    mine = slice(0, half) if rank == 0 else slice(half, n)       # chunk I own/reduce
    theirs = slice(half, n) if rank == 0 else slice(0, half)     # chunk peer owns
    rxbuf = np.empty(max(half, n - half), dtype=x.dtype)

    # reduce-scatter: send peer's chunk, receive mine, accumulate (full duplex)
    tx = threading.Thread(target=_send_all, args=(sock, memoryview(x[theirs]).cast("B")))
    tx.start()
    rxv = rxbuf[: (mine.stop - mine.start)]
    _recv_all(sock, memoryview(rxv).cast("B"))
    tx.join()
    x[mine] += rxv

    # all-gather: exchange reduced chunks
    tx = threading.Thread(target=_send_all, args=(sock, memoryview(x[mine]).cast("B")))
    tx.start()
    rxv = rxbuf[: (theirs.stop - theirs.start)]
    _recv_all(sock, memoryview(rxv).cast("B"))
    tx.join()
    x[theirs] = rxv
    return x


def _peer_main(rank: int, nbytes: int, iters: int, port: int, q) -> None:
    if rank == 0:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        srv.settimeout(30)
        sock, _ = srv.accept()
        srv.close()
    else:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        for attempt in range(100):
            try:
                sock.connect(("127.0.0.1", port))
                break
            except OSError:
                if attempt == 99:
                    raise
                time.sleep(0.05)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    count = nbytes // 4
    x = np.full(count, float(rank + 1), dtype=np.float32)
    _allreduce_2peer(sock, rank, x.copy())  # warmup
    times = []
    for _ in range(iters):
        y = x.copy()
        t0 = time.perf_counter()
        y = _allreduce_2peer(sock, rank, y)
        times.append(time.perf_counter() - t0)
    assert abs(float(y[0]) - 3.0) < 1e-6, "allreduce result wrong"
    sock.close()
    if q is not None:
        q.put(times)


def run_allreduce_bench(nbytes: int = 64 << 20, iters: int = 10) -> float:
    """Returns busbw in GB/s (median over iters)."""
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = _PORT
    p1 = ctx.Process(target=_peer_main, args=(1, nbytes, iters, port, None))
    p1.start()
    try:
        _peer_main(0, nbytes, iters, port, q)
        times = q.get(timeout=60)
        p1.join(timeout=30)
    finally:
        if p1.is_alive():
            p1.terminate()
            p1.join(timeout=5)
    med = sorted(times)[len(times) // 2]
    return (nbytes / med) / 1e9
