"""ctypes loader for the native core (libpcclt.so).

Reference parity: python/framework/pccl/_loader.py + _cdecls.py of the
reference (cffi ABI mode over libpccl). Here: plain ctypes over the pcclt
C API (pccl_tpu/native/include/pcclt.h) — no codegen step, the surface is
declared once below.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path

_LIB = None


def _candidate_paths():
    env = os.environ.get("PCCLT_LIB")
    if env:
        yield Path(env)
    pkg = Path(__file__).resolve().parent.parent
    # packaged install (pip): setup.py's CMake build drops the core here
    yield pkg / "_lib" / "libpcclt.so"
    # source tree: the documented cmake -B build layout
    yield pkg / "native" / "build" / "libpcclt.so"
    yield pkg / "native" / "libpcclt.so"


def load():
    """Load libpcclt.so and declare signatures. Raises OSError if missing."""
    global _LIB
    if _LIB is not None:
        return _LIB
    path = None
    for p in _candidate_paths():
        if p.exists():
            path = p
            break
    if path is None:
        raise OSError(
            "libpcclt.so not found; build it with "
            "`cmake -S pccl_tpu/native -B pccl_tpu/native/build -G Ninja && "
            "ninja -C pccl_tpu/native/build` or set PCCLT_LIB")
    lib = ctypes.CDLL(str(path))
    _declare(lib)
    _LIB = lib
    return lib


class CommCreateParams(ctypes.Structure):
    _fields_ = [
        ("master_ip", ctypes.c_char_p),
        ("master_port", ctypes.c_uint16),
        ("peer_group", ctypes.c_uint32),
        ("advertised_ip", ctypes.c_char_p),
        ("p2p_port", ctypes.c_uint16),
        ("ss_port", ctypes.c_uint16),
        ("bench_port", ctypes.c_uint16),
        ("p2p_connection_pool_size", ctypes.c_uint32),
        # master HA reconnect: -1 = env default (PCCLT_RECONNECT_ATTEMPTS,
        # 8), 0 = disabled; backoff fields in ms, 0 = env defaults
        ("reconnect_attempts", ctypes.c_int32),
        ("reconnect_backoff_ms", ctypes.c_uint32),
        ("reconnect_backoff_cap_ms", ctypes.c_uint32),
    ]


class ReduceDescriptor(ctypes.Structure):
    _fields_ = [
        ("tag", ctypes.c_uint64),
        ("op", ctypes.c_int),
        ("quant_algo", ctypes.c_int),
        ("quant_dtype", ctypes.c_int),
    ]


class ReduceInfo(ctypes.Structure):
    _fields_ = [
        ("tx_bytes", ctypes.c_uint64),
        ("rx_bytes", ctypes.c_uint64),
        ("world_size", ctypes.c_uint32),
    ]


MaterializeFn = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class TensorInfoC(ctypes.Structure):
    _fields_ = [
        ("name", ctypes.c_char_p),
        ("data", ctypes.c_void_p),
        ("count", ctypes.c_uint64),
        ("dtype", ctypes.c_int),
        ("device", ctypes.c_int),
        ("allow_content_inequality", ctypes.c_int),
        # accelerator-resident entries (pcclt.h round 5): on-device hash +
        # lazy host staging + received-content flag
        ("precomputed_hash", ctypes.c_uint64),
        ("has_precomputed_hash", ctypes.c_int),
        ("materialize", MaterializeFn),
        ("materialize_ctx", ctypes.c_void_p),
        ("updated", ctypes.c_int),
    ]


class SharedStateC(ctypes.Structure):
    _fields_ = [
        ("revision", ctypes.c_uint64),
        ("count", ctypes.c_uint64),
        ("infos", ctypes.POINTER(TensorInfoC)),
    ]


class SharedStateSyncInfo(ctypes.Structure):
    _fields_ = [
        ("tx_bytes", ctypes.c_uint64),
        ("rx_bytes", ctypes.c_uint64),
        ("revision", ctypes.c_uint64),
    ]


class CommStats(ctypes.Structure):
    _fields_ = [
        ("collectives_ok", ctypes.c_uint64),
        ("collectives_aborted", ctypes.c_uint64),
        ("collectives_connection_lost", ctypes.c_uint64),
        ("topology_updates", ctypes.c_uint64),
        ("topology_optimizes", ctypes.c_uint64),
        ("syncs_ok", ctypes.c_uint64),
        ("syncs_failed", ctypes.c_uint64),
        ("sync_hash_mismatches", ctypes.c_uint64),
        ("kicked", ctypes.c_uint64),
        ("peers_joined", ctypes.c_uint64),
        ("peers_left", ctypes.c_uint64),
        ("master_reconnects", ctypes.c_uint64),
        ("p2p_conns_reused", ctypes.c_uint64),
        # observability plane: digests pushed to the master, and
        # flight-recorder ring drop accounting (process-global)
        ("telemetry_digests", ctypes.c_uint64),
        ("trace_ring_dropped", ctypes.c_uint64),
        # straggler-immune data plane (docs/05): windows forwarded as the
        # relay hop, and process-global netem chaos fault accounting
        ("relay_forwarded", ctypes.c_uint64),
        ("chaos_faults_armed", ctypes.c_uint64),
        ("chaos_faults_activated", ctypes.c_uint64),
        # appended (not inserted mid-struct, matching pcclt.h): ring
        # saturation gauges — dropped > 0 means traces hold only the
        # newest trace_ring_capacity events
        ("trace_ring_pushed", ctypes.c_uint64),
        ("trace_ring_capacity", ctypes.c_uint64),
        # shared-state chunk plane (docs/04); conservation identity:
        # ss_chunk_bytes_fetched + ss_chunk_bytes_resourced -
        # ss_chunk_bytes_dup == unique chunk bytes delivered
        ("ss_chunks_fetched", ctypes.c_uint64),
        ("ss_chunks_resourced", ctypes.c_uint64),
        ("ss_chunks_dup", ctypes.c_uint64),
        ("ss_chunk_bytes_fetched", ctypes.c_uint64),
        ("ss_chunk_bytes_resourced", ctypes.c_uint64),
        ("ss_chunk_bytes_dup", ctypes.c_uint64),
        ("ss_seeder_chunks_served", ctypes.c_uint64),
        ("ss_seeder_promotions", ctypes.c_uint64),
        ("ss_seeders_lost", ctypes.c_uint64),
        ("ss_legacy_syncs", ctypes.c_uint64),
        # straggler-failover relay acks (docs/05): end-to-end delivery
        # acks received back at the origin, and zombie sends retired early
        ("relay_acks", ctypes.c_uint64),
        ("relay_retired_early", ctypes.c_uint64),
        # collective schedule synthesizer (docs/12): ops per stamped
        # algorithm, program steps run, and PLANNED kRelayRing relay bytes
        # (kept apart from the watchdog's emergency wd_relays)
        ("sched_ops_ring", ctypes.c_uint64),
        ("sched_ops_tree", ctypes.c_uint64),
        ("sched_ops_butterfly", ctypes.c_uint64),
        ("sched_ops_mesh", ctypes.c_uint64),
        ("sched_ops_relay", ctypes.c_uint64),
        ("sched_steps", ctypes.c_uint64),
        ("sched_relay_planned_bytes", ctypes.c_uint64),
        # sparse revision delta (docs/04): chunks never fetched because the
        # request-time local leaf already matched the expected leaf
        ("ss_chunks_delta_skipped", ctypes.c_uint64),
        ("ss_chunk_bytes_delta_skipped", ctypes.c_uint64),
    ]


class EdgeStats(ctypes.Structure):
    _fields_ = [
        ("endpoint", ctypes.c_char * 64),
        ("tx_bytes", ctypes.c_uint64),
        ("rx_bytes", ctypes.c_uint64),
        ("tx_frames", ctypes.c_uint64),
        ("rx_frames", ctypes.c_uint64),
        ("connects", ctypes.c_uint64),
        ("stall_ms", ctypes.c_uint64),
        ("tx_zc_frames", ctypes.c_uint64),
        ("tx_zc_reaps", ctypes.c_uint64),
        # edge watchdog + window failover (docs/05); quiescent invariant:
        # rx_bytes + rx_relay_bytes - dup_bytes == unique payload delivered
        ("wd_state", ctypes.c_uint64),
        ("wd_suspects", ctypes.c_uint64),
        ("wd_confirms", ctypes.c_uint64),
        ("wd_reissues", ctypes.c_uint64),
        ("wd_relays", ctypes.c_uint64),
        ("rx_relay_bytes", ctypes.c_uint64),
        ("rx_relay_windows", ctypes.c_uint64),
        ("dup_bytes", ctypes.c_uint64),
        ("dup_windows", ctypes.c_uint64),
        # shared-state chunk plane (docs/04): sync payload on this edge
        ("tx_sync_bytes", ctypes.c_uint64),
        ("rx_sync_bytes", ctypes.c_uint64),
        # multipath striping (docs/08): windows/bytes the striped window
        # scheduler round-robined across the conn pool
        ("tx_stripe_windows", ctypes.c_uint64),
        ("tx_stripe_bytes", ctypes.c_uint64),
    ]


def _declare(lib):
    c = ctypes
    P = c.POINTER

    lib.pccltInit.restype = c.c_int
    lib.pccltGetBuildInfo.restype = c.c_char_p

    lib.pccltCreateMaster.restype = c.c_int
    lib.pccltCreateMaster.argtypes = [c.c_char_p, c.c_uint16, P(c.c_void_p)]
    for fn in ("pccltRunMaster", "pccltInterruptMaster",
               "pccltMasterAwaitTermination", "pccltDestroyMaster"):
        f = getattr(lib, fn)
        f.restype = c.c_int
        f.argtypes = [c.c_void_p]
    lib.pccltMasterPort.restype = c.c_uint16
    lib.pccltMasterPort.argtypes = [c.c_void_p]
    # master HA (journal + epoch); tolerate older builds via PCCLT_LIB
    try:
        lib.pccltCreateMasterEx.restype = c.c_int
        lib.pccltCreateMasterEx.argtypes = [c.c_char_p, c.c_uint16, c.c_char_p,
                                            P(c.c_void_p)]
        lib.pccltMasterEpoch.restype = c.c_uint64
        lib.pccltMasterEpoch.argtypes = [c.c_void_p]
    except AttributeError:
        pass

    # observability plane: metrics/health endpoint mirror (same older-build
    # tolerance as the HA surface above)
    try:
        lib.pccltMasterMetricsPort.restype = c.c_uint16
        lib.pccltMasterMetricsPort.argtypes = [c.c_void_p]
        lib.pccltMasterGetHealth.restype = c.c_int
        lib.pccltMasterGetHealth.argtypes = [c.c_void_p, c.c_char_p,
                                             c.c_uint64, P(c.c_uint64)]
    except AttributeError:
        pass

    # fleet-scale bench hooks: observer-session digest flood + journal
    # replay bench (docs/09; same older-build tolerance)
    try:
        lib.pccltDigestFlood.restype = c.c_int
        lib.pccltDigestFlood.argtypes = [c.c_char_p, c.c_uint16, c.c_uint32,
                                         c.c_uint32, c.c_double, c.c_double,
                                         c.c_uint32, P(c.c_uint64),
                                         P(c.c_double)]
        lib.pccltAdmissionProbe.restype = c.c_int
        lib.pccltAdmissionProbe.argtypes = [c.c_char_p, c.c_uint16,
                                            c.c_uint32, P(c.c_double),
                                            P(c.c_double)]
        lib.pccltMasterReplayBench.restype = c.c_int
        lib.pccltMasterReplayBench.argtypes = [c.c_char_p, c.c_uint32,
                                               P(c.c_double), P(c.c_double)]
    except AttributeError:
        pass

    lib.pccltCreateCommunicator.restype = c.c_int
    lib.pccltCreateCommunicator.argtypes = [P(CommCreateParams), P(c.c_void_p)]
    for fn in ("pccltDestroyCommunicator", "pccltConnect", "pccltUpdateTopology",
               "pccltOptimizeTopology"):
        f = getattr(lib, fn)
        f.restype = c.c_int
        f.argtypes = [c.c_void_p]
    lib.pccltGetAttribute.restype = c.c_int
    lib.pccltGetAttribute.argtypes = [c.c_void_p, c.c_int, P(c.c_int64)]
    lib.pccltArePeersPending.restype = c.c_int
    lib.pccltArePeersPending.argtypes = [c.c_void_p, P(c.c_int)]

    lib.pccltAllReduce.restype = c.c_int
    lib.pccltAllReduce.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_uint64,
                                   c.c_int, P(ReduceDescriptor), P(ReduceInfo)]
    lib.pccltAllReduceAsync.restype = c.c_int
    lib.pccltAllReduceAsync.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                        c.c_uint64, c.c_int, P(ReduceDescriptor)]
    lib.pccltAwaitAsyncReduce.restype = c.c_int
    lib.pccltAwaitAsyncReduce.argtypes = [c.c_void_p, c.c_uint64, P(ReduceInfo)]
    lib.pccltAllReduceMultipleWithRetry.restype = c.c_int
    lib.pccltAllReduceMultipleWithRetry.argtypes = [
        c.c_void_p, P(c.c_void_p), P(c.c_void_p), P(c.c_uint64), c.c_int,
        P(ReduceDescriptor), c.c_uint64, P(ReduceInfo)]

    lib.pccltSynchronizeSharedState.restype = c.c_int
    lib.pccltSynchronizeSharedState.argtypes = [c.c_void_p, P(SharedStateC), c.c_int,
                                                P(SharedStateSyncInfo)]

    lib.pccltHashBuffer.restype = c.c_uint64
    lib.pccltHashBuffer.argtypes = [c.c_int, c.c_void_p, c.c_uint64]

    lib.pccltAllGather.restype = c.c_int
    lib.pccltAllGather.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                   c.c_uint64, c.c_uint64, c.c_int,
                                   c.c_uint64, P(ReduceInfo)]
    lib.pccltGatherSlot.restype = c.c_int
    lib.pccltGatherSlot.argtypes = [c.c_void_p, P(c.c_uint64)]

    # widened collective vocabulary (docs/12); tolerate older builds so
    # PCCLT_LIB can still point at a pre-schedule library
    try:
        lib.pccltReduceScatter.restype = c.c_int
        lib.pccltReduceScatter.argtypes = [
            c.c_void_p, c.c_void_p, c.c_void_p, c.c_uint64, c.c_uint64,
            c.c_int, P(ReduceDescriptor), P(c.c_uint64), P(c.c_uint64),
            P(ReduceInfo)]
        lib.pccltBroadcast.restype = c.c_int
        lib.pccltBroadcast.argtypes = [c.c_void_p, c.c_void_p, c.c_uint64,
                                       c.c_uint64, c.c_int,
                                       P(ReduceDescriptor), P(ReduceInfo)]
        lib.pccltAllToAll.restype = c.c_int
        lib.pccltAllToAll.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                      c.c_uint64, c.c_uint64, c.c_int,
                                      P(ReduceDescriptor), P(ReduceInfo)]
    except AttributeError:
        pass

    lib.pccltShmAlloc.restype = c.c_int
    lib.pccltShmAlloc.argtypes = [c.c_uint64, P(c.c_void_p)]
    lib.pccltShmFree.restype = c.c_int
    lib.pccltShmFree.argtypes = [c.c_void_p]

    # per-edge wire-emulation introspection: resolve what a conn to ip:port
    # would emulate with under the current PCCLT_WIRE_* env (netem.hpp).
    # Tolerate its absence so PCCLT_LIB can still point at an older build.
    try:
        lib.pccltWireModelQuery.restype = c.c_int
        lib.pccltWireModelQuery.argtypes = [c.c_char_p, c.c_uint16,
                                            P(c.c_double), P(c.c_double),
                                            P(c.c_double), P(c.c_double)]
    except AttributeError:
        pass

    # runtime chaos injection (docs/05; same older-build tolerance)
    try:
        lib.pccltNetemInject.restype = c.c_int
        lib.pccltNetemInject.argtypes = [c.c_char_p, c.c_char_p]
    except AttributeError:
        pass

    # flight-recorder telemetry (same older-build tolerance)
    try:
        lib.pccltCommGetStats.restype = c.c_int
        lib.pccltCommGetStats.argtypes = [c.c_void_p, P(CommStats)]
        lib.pccltCommGetEdgeStats.restype = c.c_int
        lib.pccltCommGetEdgeStats.argtypes = [c.c_void_p, P(EdgeStats),
                                              c.c_uint64, P(c.c_uint64)]
        lib.pccltTraceEnable.restype = c.c_int
        lib.pccltTraceEnable.argtypes = [c.c_int]
        lib.pccltTraceClear.restype = c.c_int
        lib.pccltTraceClear.argtypes = []
        lib.pccltTraceDump.restype = c.c_int
        lib.pccltTraceDump.argtypes = [c.c_char_p]
    except AttributeError:
        pass
