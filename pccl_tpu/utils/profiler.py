"""Lightweight section profiler for training loops.

Reference parity: python/examples/nanogpt_diloco/profiler.py of the
reference (a session wrapper timing named spans around the DiLoCo loop,
used at sync_diloco.py:396-497) — promoted here from example code to a
library utility, with aggregation and an optional Chrome-trace export the
reference lacks.

Usage::

    from pccl_tpu.utils.profiler import Profiler

    prof = Profiler()
    for step in range(steps):
        with prof.section("inner"):
            params, loss = train_step(params, batch)
        with prof.section("outer/allreduce"):
            params = diloco.outer_step(params)
    print(prof.summary())
    prof.export_chrome_trace("trace.json")   # chrome://tracing / perfetto

Sections nest; each section records its full INCLUSIVE duration (a parent's
total contains its children's time — summary() rows are not additive across
nesting levels; the chrome trace shows the nesting explicitly).
Zero dependencies, threadsafe for disjoint section names.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class _Stat:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.min_s = min(self.min_s, dt)
        self.max_s = max(self.max_s, dt)


@dataclass
class Profiler:
    enabled: bool = True
    _stats: Dict[str, _Stat] = field(default_factory=dict)
    _events: List[dict] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _t0: float = field(default_factory=time.perf_counter)
    max_events: int = 100_000  # chrome-trace ring guard

    @contextmanager
    def section(self, name: str):
        if not self.enabled:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            with self._lock:
                self._stats.setdefault(name, _Stat()).add(end - start)
                if len(self._events) < self.max_events:
                    self._events.append({
                        "name": name, "ph": "X", "pid": 0,
                        "tid": threading.get_ident() & 0xFFFF,
                        "ts": (start - self._t0) * 1e6,
                        "dur": (end - start) * 1e6,
                    })

    def stats(self) -> Dict[str, _Stat]:
        with self._lock:
            return dict(self._stats)

    def summary(self) -> str:
        """Aligned per-section table: count, total, mean, min, max."""
        with self._lock:
            if not self._stats:
                return "(no sections recorded)"
            rows = [("section", "count", "total_s", "mean_ms", "min_ms", "max_ms")]
            for name in sorted(self._stats, key=lambda n: -self._stats[n].total_s):
                s = self._stats[name]
                if s.count == 0:
                    # a never-entered section (pre-registered stat, or a
                    # reset mid-flight) must not render "min=inf" / divide
                    # by zero
                    rows.append((name, "0", "0.000", "-", "-", "-"))
                    continue
                rows.append((name, str(s.count), f"{s.total_s:.3f}",
                             f"{1e3 * s.total_s / s.count:.2f}",
                             f"{1e3 * s.min_s:.2f}", f"{1e3 * s.max_s:.2f}"))
            widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
            return "\n".join(
                "  ".join(c.ljust(w) for c, w in zip(r, widths)) for r in rows)

    def export_chrome_trace(self, path: str, *, native_events=None,
                            overwrite: bool = True) -> None:
        """Write accumulated spans as a Chrome trace-event JSON file
        (load in chrome://tracing or ui.perfetto.dev).

        ``native_events`` merges the native flight recorder's events
        (``pccl_tpu.comm.trace_events()``) onto the same timeline:
        Python sections stay on pid 0 ("python"), native events keep
        their own pid (the recorder labels it "pcclt native (pid N)"),
        so perfetto renders them as separate process tracks. Alignment
        is exact on Linux: native timestamps are CLOCK_MONOTONIC µs and
        ``time.perf_counter`` is CLOCK_MONOTONIC too, so the profiler's
        t0 anchors both clocks; events that predate this profiler's
        construction are clamped to ts=0.

        ``overwrite=False`` refuses to clobber an existing file
        (FileExistsError) — by default the export silently overwrites,
        matching the save-per-run workflow of the examples."""
        with self._lock:
            events = list(self._events)
            t0_us = self._t0 * 1e6
        out = [{"ph": "M", "name": "process_name", "pid": 0,
                "args": {"name": "python"}}] + events
        for ev in native_events or []:
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = max(0.0, ev["ts"] - t0_us)
            out.append(ev)
        mode = "w" if overwrite else "x"
        with open(path, mode) as f:
            json.dump({"traceEvents": out}, f)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._events.clear()
            self._t0 = time.perf_counter()
