"""Token data pipeline: sharded sampling + background device prefetch.

The reference's training loops sample random crops from a memmapped token
array on every step (nanoGPT get_batch in
/root/reference/python/examples/nanogptddp/train_pccl.py) and block on the
host->device copy inside the step. TPU-first, the input pipeline is its own
overlap axis: `prefetch_to_device` stages the next batches onto the device
from a background thread so H2D rides under the previous step's compute —
the standard TPU input recipe — and `TokenDataset` gives each peer a
disjoint random stream so data-parallel peers don't train on identical
batches.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Tuple

import numpy as np


class TokenDataset:
    """Random-crop next-token batches over a 1-D token array (in-memory or
    np.memmap — nothing is copied until a crop is sampled).

    Each (seed, worker_index) pair is an independent deterministic stream;
    peers pass their rank so a data-parallel group samples disjointly, the
    same contract as the reference's DDP split.
    """

    def __init__(self, tokens: np.ndarray, block_size: int, batch_size: int,
                 *, seed: int = 0, worker_index: int = 0):
        if tokens.ndim != 1:
            raise ValueError(f"tokens must be 1-D, got shape {tokens.shape}")
        if len(tokens) < block_size + 2:
            raise ValueError(
                f"need > block_size+1={block_size + 1} tokens, got {len(tokens)}")
        self.tokens = tokens
        self.block_size = block_size
        self.batch_size = batch_size
        self._rng = np.random.default_rng((seed << 20) ^ (worker_index + 1))

    def sample(self) -> Tuple[np.ndarray, np.ndarray]:
        """(tokens, targets) int32 [B, T] — one random-crop batch."""
        B, T = self.batch_size, self.block_size
        starts = self._rng.integers(0, len(self.tokens) - T - 1, size=B)
        x = np.stack([self.tokens[s:s + T] for s in starts])
        y = np.stack([self.tokens[s + 1:s + T + 1] for s in starts])
        return x.astype(np.int32), y.astype(np.int32)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        while True:
            yield self.sample()


def prefetch_to_device(it: Iterable, size: int = 2,
                       sharding: Any = None) -> Iterator:
    """Stage upcoming items on device from a background thread.

    Yields `jax.device_put(item, sharding)` for each item of `it`, keeping
    up to `size` future items already transferred — the H2D copy of batch
    k+1 overlaps the device compute of batch k. Pytrees pass through
    device_put leaf-wise. The feeder thread is a daemon and also stops at
    generator close; iteration ends when `it` does.
    """
    import jax

    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = threading.Event()
    _END = object()

    def put_respecting_stop(x):
        while not stop.is_set():
            try:
                q.put(x, timeout=0.2)
                return
            except queue.Full:
                continue

    def feed():
        try:
            for item in it:
                if stop.is_set():
                    return
                put_respecting_stop(jax.device_put(item, sharding))
            put_respecting_stop(_END)
        except BaseException as e:  # noqa: BLE001 — surfaced on next()
            put_respecting_stop(e)

    t = threading.Thread(target=feed, daemon=True)
    t.start()
    try:
        while True:
            got = q.get()
            if got is _END:
                return
            if isinstance(got, BaseException):
                raise got
            yield got
    finally:
        stop.set()
