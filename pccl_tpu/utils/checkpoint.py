"""Checkpoint/resume for fault-tolerant training (orbax-backed).

The reference keeps checkpointing an application contract: apps
periodically dump state, and a restarted master accepts whatever revision
the cohort offers (reference ccoip_master_state.cpp:1083-1086 — revision-0
bootstrap; docs/md/04-API Overview/01_PCCL_API_Overview.md:341-347). This
module implements that contract as a library:

- ``Checkpointer`` saves/restores a pytree (params, opt state, ...) plus a
  step counter, with retention, using orbax (the TPU-ecosystem
  checkpointing library — async-friendly, sharding-aware).
- ``DilocoCheckpoint`` snapshots a Diloco driver (outer params, outer
  momentum, step) so a fully-restarted cohort resumes at the exact outer
  revision: every peer restores the same snapshot, offers the same
  revision to the fresh master, and the one-increment rule carries on.

The shared-state path (pccl_tpu.comm.SharedState) remains the LIVE-cohort
catch-up mechanism (late joiners fetch from incumbents over TCP);
checkpoints cover the cold-start case where no incumbent survives.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


class Checkpointer:
    """Save/restore a pytree + step under a directory, keeping the last
    ``keep`` checkpoints. Thin, deliberate wrapper over
    ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, directory: str, *, keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir, options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True))

    def save(self, step: int, tree: Any, *, wait: bool = True) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(tree))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of ``template``. step=None
        restores the latest; raises FileNotFoundError when none exist."""
        import orbax.checkpoint as ocp

        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype,
                                           sharding=getattr(x, "sharding", None)),
            template)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(shapes))

    def close(self) -> None:
        self._mgr.close()


class DilocoCheckpoint:
    """Snapshot/restore a Diloco driver's outer state.

    Usage::

        ck = DilocoCheckpoint("ckpt/", keep=2)
        dl = Diloco(comm, params, cfg)
        start = ck.maybe_restore(dl)           # cold start resumes here
        for outer in range(start, total):
            ...inner steps...
            params = dl.outer_step(params)
            if outer % 10 == 9:
                ck.save(dl)

    After a full-cohort restart, every peer restores the same outer
    revision; the first sync_shared_state against the fresh master
    re-seeds revision tracking (revision-0 bootstrap)."""

    def __init__(self, directory: str, *, keep: int = 3):
        self._ck = Checkpointer(directory, keep=keep)

    def save(self, diloco, *, wait: bool = True) -> None:
        state = {
            "outer_params": diloco.outer_params,
            "momentum": diloco._momentum_vec,
            "step": np.int64(diloco.step),
        }
        self._ck.save(diloco.step, state, wait=wait)

    def maybe_restore(self, diloco) -> int:
        """Restore the newest snapshot into ``diloco`` if one exists.
        Returns the outer step to resume from (0 on a fresh start)."""
        if self._ck.latest_step() is None:
            return 0
        template = {
            "outer_params": diloco.outer_params,
            "momentum": diloco._momentum_vec,
            "step": np.int64(0),
        }
        state = self._ck.restore(template)
        diloco.outer_params = diloco._restore_shardings(state["outer_params"])
        # the live momentum buffer is UNcommitted (jit places it freely)
        # but orbax restores arrays committed to one device — re-place it
        # with the outer vector's sharding or the fused apply sees two
        # incompatible device sets on a multi-device mesh
        mom = state["momentum"]
        if hasattr(diloco._outer_vec, "sharding"):
            mom = jax.device_put(mom, diloco._outer_vec.sharding)
        diloco._momentum_vec = mom
        diloco.step = int(state["step"])
        return diloco.step

    def close(self) -> None:
        self._ck.close()
