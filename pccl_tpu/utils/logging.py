"""Leveled stream logger for the Python layer.

Reference parity: the reference has a C++ stream logger with env-selected
level (/root/reference/log/src/pccl_log.cpp:28-56, levels TRACE..FATAL).
The native library has its own C++ logger (pccl_tpu/native/src/log.cpp)
honouring the same env var; this module mirrors it Python-side so both
halves of the framework log uniformly.

Env: PCCLT_LOG_LEVEL in {TRACE, DEBUG, INFO, WARN, ERROR, FATAL}; default INFO.
"""

from __future__ import annotations

import os
import sys
import threading
import time

_LEVELS = {"TRACE": 0, "DEBUG": 1, "INFO": 2, "WARN": 3, "ERROR": 4, "FATAL": 5}
_level_name = os.environ.get("PCCLT_LOG_LEVEL", "INFO").upper()
_threshold = _LEVELS.get(_level_name, 2)
_lock = threading.Lock()


def set_level(name: str) -> None:
    global _threshold
    _threshold = _LEVELS.get(name.upper(), _threshold)


def _log(level: str, msg: str) -> None:
    if _LEVELS[level] < _threshold:
        return
    ts = time.strftime("%H:%M:%S", time.localtime())
    tid = threading.get_ident() % 100000
    with _lock:
        print(f"[{ts}][{level:>5}][py:{tid}] {msg}", file=sys.stderr, flush=True)


def trace(msg: str) -> None:
    _log("TRACE", msg)


def debug(msg: str) -> None:
    _log("DEBUG", msg)


def info(msg: str) -> None:
    _log("INFO", msg)


def warn(msg: str) -> None:
    _log("WARN", msg)


def error(msg: str) -> None:
    _log("ERROR", msg)


def fatal(msg: str) -> None:
    _log("FATAL", msg)
    raise SystemExit(1)
