from . import logging  # noqa: F401
