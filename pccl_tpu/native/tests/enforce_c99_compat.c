/* Compile-time check that pcclt.h is valid C99 — the public API must stay
 * consumable from plain C (reference: tests/c99_compat/enforce_c99_compat.c).
 * Compiled with -std=c99 -Werror by the build; never executed beyond a
 * trivial smoke of the function-pointer surface. */
#include <pcclt.h>

#include <stddef.h>

int main(void) {
    /* touch every exported symbol so missing declarations fail the build */
    pccltResult_t (*fns[])(void) = {pccltInit};
    const char *(*info)(void) = pccltGetBuildInfo;
    pccltResult_t (*cm)(const char *, uint16_t, pccltMaster_t **) = pccltCreateMaster;
    pccltResult_t (*rm)(pccltMaster_t *) = pccltRunMaster;
    pccltResult_t (*im)(pccltMaster_t *) = pccltInterruptMaster;
    pccltResult_t (*am)(pccltMaster_t *) = pccltMasterAwaitTermination;
    pccltResult_t (*dm)(pccltMaster_t *) = pccltDestroyMaster;
    uint16_t (*mp)(pccltMaster_t *) = pccltMasterPort;
    pccltResult_t (*cc)(const pccltCommCreateParams_t *, pccltComm_t **) =
        pccltCreateCommunicator;
    pccltResult_t (*dc)(pccltComm_t *) = pccltDestroyCommunicator;
    pccltResult_t (*cn)(pccltComm_t *) = pccltConnect;
    pccltResult_t (*ga)(pccltComm_t *, pccltAttribute_t, int64_t *) = pccltGetAttribute;
    pccltResult_t (*ut)(pccltComm_t *) = pccltUpdateTopology;
    pccltResult_t (*pp)(pccltComm_t *, int *) = pccltArePeersPending;
    pccltResult_t (*ot)(pccltComm_t *) = pccltOptimizeTopology;
    pccltResult_t (*ar)(pccltComm_t *, const void *, void *, uint64_t,
                        pccltDataType_t, const pccltReduceDescriptor_t *,
                        pccltReduceInfo_t *) = pccltAllReduce;
    pccltResult_t (*ara)(pccltComm_t *, const void *, void *, uint64_t,
                         pccltDataType_t, const pccltReduceDescriptor_t *) =
        pccltAllReduceAsync;
    pccltResult_t (*aw)(pccltComm_t *, uint64_t, pccltReduceInfo_t *) =
        pccltAwaitAsyncReduce;
    pccltResult_t (*mr)(pccltComm_t *, const void *const *, void *const *,
                        const uint64_t *, pccltDataType_t,
                        const pccltReduceDescriptor_t *, uint64_t,
                        pccltReduceInfo_t *) = pccltAllReduceMultipleWithRetry;
    pccltResult_t (*ss)(pccltComm_t *, pccltSharedState_t *, pccltSyncStrategy_t,
                        pccltSharedStateSyncInfo_t *) = pccltSynchronizeSharedState;
    uint64_t (*hb)(int, const void *, uint64_t) = pccltHashBuffer;

    (void)fns; (void)info; (void)cm; (void)rm; (void)im; (void)am; (void)dm;
    (void)mp; (void)cc; (void)dc; (void)cn; (void)ga; (void)ut; (void)pp;
    (void)ot; (void)ar; (void)ara; (void)aw; (void)mr; (void)ss; (void)hb;
    return 0;
}
