/* pcclt — public C99 API of the pccl_tpu native core.
 *
 * Reference parity: include/pccl.h of the reference (19 exported functions,
 * /root/reference/include/pccl.h) — same capability surface with a TPU
 * device type. Bulk data pointers are host memory; TPU (HBM) arrays are
 * staged by the Python layer (pccl_tpu.comm) which owns the JAX side.
 */
#ifndef PCCLT_H
#define PCCLT_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define PCCLT_EXPORT __attribute__((visibility("default")))

typedef enum pccltResult_t {
    pccltSuccess = 0,
    pccltInvalidArgument = 1,
    pccltNotConnected = 2,
    pccltConnectionLost = 3,
    pccltOperationAborted = 4,
    pccltTooFewPeers = 5,
    pccltDuplicateTag = 6,
    pccltKicked = 7,
    pccltMasterUnreachable = 8,
    pccltInternalError = 9,
    pccltContentMismatch = 10,
    pccltPendingAsyncOps = 11,
    pccltInvalidUsage = 12,
} pccltResult_t;

typedef enum pccltDataType_t {
    pccltUint8 = 0,
    pccltInt8 = 1,
    pccltUint16 = 2,
    pccltInt16 = 3,
    pccltUint32 = 4,
    pccltInt32 = 5,
    pccltUint64 = 6,
    pccltInt64 = 7,
    pccltFloat16 = 8,
    pccltBFloat16 = 9,
    pccltFloat32 = 10,
    pccltFloat64 = 11,
} pccltDataType_t;

typedef enum pccltDeviceType_t {
    pccltDeviceHost = 0,
    pccltDeviceTpu = 1, /* HBM-resident JAX array staged to host by bindings */
} pccltDeviceType_t;

typedef enum pccltRedOp_t {
    pccltSum = 0,
    pccltAvg = 1,
    pccltProd = 2,
    pccltMax = 3,
    pccltMin = 4,
} pccltRedOp_t;

typedef enum pccltQuantAlgo_t {
    pccltQuantNone = 0,
    pccltQuantMinMax = 1,
    pccltQuantZeroPointScale = 2,
} pccltQuantAlgo_t;

typedef enum pccltSyncStrategy_t {
    pccltSyncEnforcePopular = 0,
    pccltSyncReceiveOnly = 1,
    pccltSyncSendOnly = 2,
} pccltSyncStrategy_t;

typedef enum pccltAttribute_t {
    PCCLT_ATTR_GLOBAL_WORLD_SIZE = 0,
    PCCLT_ATTR_PEER_GROUP_WORLD_SIZE = 1,
    PCCLT_ATTR_NUM_DISTINCT_PEER_GROUPS = 2,
    PCCLT_ATTR_LARGEST_PEER_GROUP_WORLD_SIZE = 3,
    /* master HA (docs/10_high_availability.md): the master epoch observed at
     * welcome / session resume (bumped on every journaled master restart),
     * and how many times THIS communicator resumed its session */
    PCCLT_ATTR_MASTER_EPOCH = 4,
    PCCLT_ATTR_RECONNECT_COUNT = 5,
    /* last shared-state revision known complete (sync Done or resume ack):
     * after a resumed master restart, an app whose sync failed mid-crash
     * checks this to skip re-syncing a revision that completed group-wide */
    PCCLT_ATTR_SHARED_STATE_REVISION = 6,
} pccltAttribute_t;

typedef struct pccltComm pccltComm_t;
typedef struct pccltMaster pccltMaster_t;

typedef struct pccltCommCreateParams_t {
    const char *master_ip;   /* dotted quad */
    uint16_t master_port;
    uint32_t peer_group;
    const char *advertised_ip; /* NULL = let master observe source address */
    uint16_t p2p_port;       /* 0 = default base; bump-allocated upward */
    uint16_t ss_port;
    uint16_t bench_port;
    uint32_t p2p_connection_pool_size; /* 0 = 1 */
    /* Master HA reconnect (session resume after a master restart). On
     * kMasterUnreachable mid-session the client retries with bounded
     * exponential backoff + jitter while keeping its p2p connections
     * alive; a journaled master re-binds the session under the old UUID.
     * reconnect_attempts: -1 = env PCCLT_RECONNECT_ATTEMPTS (default 8),
     * 0 = disabled, >0 = attempt budget. The backoff fields are ms; 0 =
     * env PCCLT_RECONNECT_BACKOFF_MS (100) / _MAX_BACKOFF_MS (2000). */
    int32_t reconnect_attempts;
    uint32_t reconnect_backoff_ms;
    uint32_t reconnect_backoff_cap_ms;
} pccltCommCreateParams_t;

typedef struct pccltReduceDescriptor_t {
    uint64_t tag;
    pccltRedOp_t op;
    pccltQuantAlgo_t quant_algo;
    pccltDataType_t quant_dtype;
} pccltReduceDescriptor_t;

typedef struct pccltReduceInfo_t {
    uint64_t tx_bytes;
    uint64_t rx_bytes;
    uint32_t world_size;
} pccltReduceInfo_t;

typedef struct pccltTensorInfo_t {
    const char *name;
    void *data;
    uint64_t count;
    pccltDataType_t dtype;
    pccltDeviceType_t device;
    int allow_content_inequality;
    /* Accelerator-resident entries (optional; zero-init for host state):
     * with has_precomputed_hash set, `precomputed_hash` (computed on the
     * device, type matching PCCLT_SS_HASH — use hash_type 2 on TPUs) is
     * used at request time and `data` may be unmaterialized; `materialize`
     * (with `materialize_ctx`) is then called at most once, from a native
     * thread, before this entry's bytes are first served to an outdated
     * peer. `updated` is written back nonzero iff the sync overwrote
     * `data` (push it back to the device). */
    uint64_t precomputed_hash;
    int has_precomputed_hash;
    void (*materialize)(void *ctx);
    void *materialize_ctx;
    int updated;
} pccltTensorInfo_t;

typedef struct pccltSharedState_t {
    uint64_t revision;
    uint64_t count;
    pccltTensorInfo_t *infos;
} pccltSharedState_t;

typedef struct pccltSharedStateSyncInfo_t {
    uint64_t tx_bytes;
    uint64_t rx_bytes;
    uint64_t revision;
} pccltSharedStateSyncInfo_t;

/* --- the 19-function surface --- */

PCCLT_EXPORT pccltResult_t pccltInit(void);
PCCLT_EXPORT const char *pccltGetBuildInfo(void);

/* Creates a master. When the PCCLT_MASTER_JOURNAL env var is set, master
 * HA is enabled: authoritative state is write-ahead-logged to that path
 * and rehydrated on the next pccltRunMaster at the same path, so a
 * restarted master resumes the same world view under a bumped epoch
 * (docs/10_high_availability.md). */
PCCLT_EXPORT pccltResult_t pccltCreateMaster(const char *listen_ip, uint16_t port,
                                             pccltMaster_t **out);
/* Same, with an explicit journal path: NULL = fall back to the env var,
 * empty string = force-disable journaling. */
PCCLT_EXPORT pccltResult_t pccltCreateMasterEx(const char *listen_ip, uint16_t port,
                                               const char *journal_path,
                                               pccltMaster_t **out);
/* This master incarnation's epoch (1 fresh / journal-less; +1 per journaled
 * restart). Valid after pccltRunMaster. */
PCCLT_EXPORT uint64_t pccltMasterEpoch(pccltMaster_t *m);
PCCLT_EXPORT pccltResult_t pccltRunMaster(pccltMaster_t *m);
PCCLT_EXPORT pccltResult_t pccltInterruptMaster(pccltMaster_t *m);
PCCLT_EXPORT pccltResult_t pccltMasterAwaitTermination(pccltMaster_t *m);
PCCLT_EXPORT pccltResult_t pccltDestroyMaster(pccltMaster_t *m);
PCCLT_EXPORT uint16_t pccltMasterPort(pccltMaster_t *m); /* bound port */

/* Observability plane (docs/09_observability.md). When the
 * PCCLT_MASTER_METRICS_PORT env var is set, pccltRunMaster also serves
 * plain HTTP on that port ("0" = kernel-assigned, query it here):
 * GET /metrics -> Prometheus text format, GET /health -> fleet health
 * JSON. Returns 0 while disabled or before pccltRunMaster. */
PCCLT_EXPORT uint16_t pccltMasterMetricsPort(pccltMaster_t *m);

/* Copy the master's current fleet-health JSON (the /health payload) into
 * buf (NUL-terminated, at most cap bytes) and store the full length
 * (excluding the NUL) into *need — call with cap=0 to size the buffer.
 * Valid after pccltRunMaster; works with the HTTP endpoint disabled. */
PCCLT_EXPORT pccltResult_t pccltMasterGetHealth(pccltMaster_t *m, char *buf,
                                                uint64_t cap, uint64_t *need);

/* --- fleet-scale bench hooks (pcclt extension, docs/09) ---
 *
 * pccltDigestFlood: simulated-fleet telemetry load generator. Opens one
 * OBSERVER control session per simulated peer against the master at
 * ip:port (observer sessions push digests but never join the world, so a
 * flood cannot wedge real admission rounds), then pushes one pre-encoded
 * telemetry digest of `edges_per_peer` unique edges per peer per 1/hz
 * tick for `seconds`, spread over `threads` sender threads (0 = default).
 * Blocking; returns the digest count actually written and the wall time.
 * pccltMasterUnreachable if any session failed to connect or send.
 *
 * pccltAdmissionProbe: dispatcher round-latency probe. Each round is one
 * fresh observer hello -> welcome round trip, timed after TCP connect —
 * the hello is parsed, admitted and answered on the dispatcher thread, so
 * the samples measure exactly the queueing an admission/topology frame
 * sees, without perturbing the world. Reports mean and p99 seconds.
 *
 * pccltMasterReplayBench: journal write + cold-restart replay timing.
 * Appends `clients` session records to a fresh journal at journal_path,
 * then replays it (compacted snapshot rewrite + master-state rehydrate)
 * and reports both phases' wall seconds. The path should be a scratch
 * file; its contents are overwritten. */
PCCLT_EXPORT pccltResult_t pccltDigestFlood(const char *ip, uint16_t port,
                                            uint32_t peers,
                                            uint32_t edges_per_peer, double hz,
                                            double seconds, uint32_t threads,
                                            uint64_t *digests_sent,
                                            double *wall_seconds);
PCCLT_EXPORT pccltResult_t pccltAdmissionProbe(const char *ip, uint16_t port,
                                               uint32_t rounds,
                                               double *mean_seconds,
                                               double *p99_seconds);
PCCLT_EXPORT pccltResult_t pccltMasterReplayBench(const char *journal_path,
                                                  uint32_t clients,
                                                  double *write_seconds,
                                                  double *replay_seconds);

PCCLT_EXPORT pccltResult_t pccltCreateCommunicator(const pccltCommCreateParams_t *params,
                                                   pccltComm_t **out);
PCCLT_EXPORT pccltResult_t pccltDestroyCommunicator(pccltComm_t *c);
PCCLT_EXPORT pccltResult_t pccltConnect(pccltComm_t *c);
PCCLT_EXPORT pccltResult_t pccltGetAttribute(pccltComm_t *c, pccltAttribute_t attr,
                                             int64_t *out);
PCCLT_EXPORT pccltResult_t pccltUpdateTopology(pccltComm_t *c);
PCCLT_EXPORT pccltResult_t pccltArePeersPending(pccltComm_t *c, int *pending);
PCCLT_EXPORT pccltResult_t pccltOptimizeTopology(pccltComm_t *c);

PCCLT_EXPORT pccltResult_t pccltAllReduce(pccltComm_t *c, const void *sendbuf,
                                          void *recvbuf, uint64_t count,
                                          pccltDataType_t dtype,
                                          const pccltReduceDescriptor_t *desc,
                                          pccltReduceInfo_t *info);
PCCLT_EXPORT pccltResult_t pccltAllReduceAsync(pccltComm_t *c, const void *sendbuf,
                                               void *recvbuf, uint64_t count,
                                               pccltDataType_t dtype,
                                               const pccltReduceDescriptor_t *desc);
PCCLT_EXPORT pccltResult_t pccltAwaitAsyncReduce(pccltComm_t *c, uint64_t tag,
                                                 pccltReduceInfo_t *info);
/* Launch all descriptors, await all; on failure retry completed world until
 * all succeed or world < 2 (reference pcclAllReduceMultipleWithRetry). */
PCCLT_EXPORT pccltResult_t pccltAllReduceMultipleWithRetry(
    pccltComm_t *c, const void *const *sendbufs, void *const *recvbufs,
    const uint64_t *counts, pccltDataType_t dtype,
    const pccltReduceDescriptor_t *descs, uint64_t n_ops, pccltReduceInfo_t *infos);

/* Ring all-gather (pcclt extension; the reference lists All-Gather as
 * unshipped roadmap work). Each peer contributes send_count elements;
 * recvbuf (capacity >= world * send_count) receives every peer's segment,
 * ordered by SORTED peer UUID — stable across ring re-orderings. tag
 * semantics match pccltAllReduce; quantization is not applicable. */
PCCLT_EXPORT pccltResult_t pccltAllGather(pccltComm_t *c, const void *sendbuf,
                                          void *recvbuf, uint64_t send_count,
                                          uint64_t recv_capacity,
                                          pccltDataType_t dtype, uint64_t tag,
                                          pccltReduceInfo_t *info);

/* This peer's segment index in pccltAllGather output (its position among
 * the current ring's SORTED peer UUIDs). Valid for the current topology;
 * re-query after churn. */
PCCLT_EXPORT pccltResult_t pccltGatherSlot(pccltComm_t *c, uint64_t *slot);

/* --- widened collective vocabulary (docs/12) ---
 * All three share pccltAllReduce's consensus/tag/abort/quantization
 * semantics and ride the synthesized schedule the master stamps on the
 * commence (PCCLT_SCHEDULE / PCCLT_SCHEDULE_FORCE, docs/03). */

/* Reduce-scatter: the reduce-scatter half of the ring without the
 * all-gather. recvbuf (capacity recv_capacity elements, >= ceil(count /
 * world)) receives this rank's fully-reduced chunk of the count-element
 * global vector; *recv_offset / *recv_count (elements, optional NULL)
 * report which chunk. Chunk ownership follows ring position, which the
 * topology optimizer reshuffles — outputs, not inputs. The fold is SUM
 * (desc->op selects quantization fields only; see docs/12). */
PCCLT_EXPORT pccltResult_t pccltReduceScatter(pccltComm_t *c, const void *sendbuf,
                                              void *recvbuf, uint64_t count,
                                              uint64_t recv_capacity,
                                              pccltDataType_t dtype,
                                              const pccltReduceDescriptor_t *desc,
                                              uint64_t *recv_offset,
                                              uint64_t *recv_count,
                                              pccltReduceInfo_t *info);

/* Broadcast: `buf` (count elements) is broadcast IN PLACE from the peer
 * whose gather slot (sorted-uuid order, pccltGatherSlot) equals
 * root_slot. Every member must pass the same root_slot (matched-
 * parameters contract; mismatches kick). Quantized broadcasts end
 * bit-identical on every rank INCLUDING the root. */
PCCLT_EXPORT pccltResult_t pccltBroadcast(pccltComm_t *c, void *buf,
                                          uint64_t count, uint64_t root_slot,
                                          pccltDataType_t dtype,
                                          const pccltReduceDescriptor_t *desc,
                                          pccltReduceInfo_t *info);

/* All-to-all: block j of sendbuf (count_per_peer elements, gather-slot
 * order) lands at the sender's slot-indexed block of peer j's recvbuf
 * (capacity recv_capacity >= world * count_per_peer elements). */
PCCLT_EXPORT pccltResult_t pccltAllToAll(pccltComm_t *c, const void *sendbuf,
                                         void *recvbuf, uint64_t count_per_peer,
                                         uint64_t recv_capacity,
                                         pccltDataType_t dtype,
                                         const pccltReduceDescriptor_t *desc,
                                         pccltReduceInfo_t *info);

PCCLT_EXPORT pccltResult_t pccltSynchronizeSharedState(pccltComm_t *c,
                                                       pccltSharedState_t *state,
                                                       pccltSyncStrategy_t strategy,
                                                       pccltSharedStateSyncInfo_t *info);

/* Content hash used for shared-state drift detection (reference
 * ccoip_hash_type_t). hash_type: 0 = simplehash (default), 1 = CRC32,
 * 2 = simplehash-tpu (u32-only lane/fold hash an accelerator can compute
 * over device-resident bytes; see pcclt::hash::simplehash_tpu).
 * Exposed so bindings/tools can verify bit parity with the Python twin. */
PCCLT_EXPORT uint64_t pccltHashBuffer(int hash_type, const void *data,
                                      uint64_t nbytes);

/* Registered shared-memory buffers (pcclt extension; no reference
 * counterpart — the reference always streams over TCP). Collective payloads
 * living in a registered buffer take the same-host ZERO-copy path: peers on
 * this host map the region and read it directly instead of pulling through
 * the kernel. Allocate communication-heavy tensors (DiLoCo staging, bench
 * buffers) here for maximum same-host bandwidth; any pointer works with the
 * collectives either way. Free only when no collective is using the buffer. */
PCCLT_EXPORT pccltResult_t pccltShmAlloc(uint64_t nbytes, void **out);
PCCLT_EXPORT pccltResult_t pccltShmFree(void *ptr);

/* Per-edge wire-emulation introspection (pcclt extension). Re-reads the
 * PCCLT_WIRE_MBPS / PCCLT_WIRE_RTT_MS globals and the per-endpoint
 * PCCLT_WIRE_MBPS_MAP / PCCLT_WIRE_RTT_MS_MAP / PCCLT_WIRE_JITTER_MS_MAP /
 * PCCLT_WIRE_DROP_MAP env maps ("ip:port=value,ip=value,..."), then
 * resolves the parameters a connection to ip:port would emulate with
 * (exact entry, else bare-ip wildcard, else the globals; 0 = that
 * dimension off). Output pointers may be NULL. Mirrors exactly what the
 * data plane resolves at connection establishment, so tests and tools can
 * verify a topology description without opening sockets. */
PCCLT_EXPORT pccltResult_t pccltWireModelQuery(const char *ip, uint16_t port,
                                               double *mbps, double *rtt_ms,
                                               double *jitter_ms, double *drop);

/* Runtime chaos injection (pcclt extension, docs/05). Arm a time-scripted
 * fault schedule on the wire-emulation edge toward endpoint "ip:port",
 * with fault offsets relative to NOW:
 *   "degrade@t=0s:40mbit/8s;flap@t=10s:200msx5"   (';'-separated faults;
 *   kinds: degrade@t=T:<R>mbit/<D>, flap@t=T:<D>x<N>, blackhole@t=T:<D>)
 * Replaces any schedule already armed on the edge; an empty spec disarms.
 * Live connections are affected immediately when they resolved to a
 * per-endpoint edge (the endpoint appears in a PCCLT_WIRE_*_MAP /
 * PCCLT_WIRE_CHAOS_MAP); otherwise the schedule applies to connections
 * created after this call. Returns InvalidArgument on an unparsable
 * endpoint or spec. */
PCCLT_EXPORT pccltResult_t pccltNetemInject(const char *endpoint,
                                            const char *spec);

/* --- flight-recorder telemetry (pcclt extension) ---
 *
 * Monotonic counters are always on (relaxed atomic adds at frame
 * granularity). The event recorder is off unless PCCLT_TRACE=path is set
 * in the environment (Chrome-trace JSON dumped to `path` at process exit;
 * "%p" in the path expands to the pid) or pccltTraceEnable(1) is called. */

typedef struct pccltCommStats_t {
    /* collectives by final outcome */
    uint64_t collectives_ok;
    uint64_t collectives_aborted;
    uint64_t collectives_connection_lost;
    /* control-plane rounds */
    uint64_t topology_updates;
    uint64_t topology_optimizes;
    /* shared-state sync outcomes */
    uint64_t syncs_ok;
    uint64_t syncs_failed;
    uint64_t sync_hash_mismatches;
    /* membership */
    uint64_t kicked;       /* times THIS peer was kicked */
    uint64_t peers_joined; /* ring additions observed (self excluded) */
    uint64_t peers_left;   /* ring departures observed */
    /* master HA */
    uint64_t master_reconnects; /* control sessions resumed after a restart */
    uint64_t p2p_conns_reused;  /* p2p conns kept alive across topology rounds */
    /* observability plane (docs/09) */
    uint64_t telemetry_digests;   /* digests pushed to the master (off unless
                                   * PCCLT_TELEMETRY_PUSH_MS sets a cadence) */
    uint64_t trace_ring_dropped;  /* flight-recorder events lost to ring wrap
                                   * since the last clear (process-global): a
                                   * nonzero value means PCCLT_TRACE dumps are
                                   * silently truncated to the newest 64k */
    /* straggler-immune data plane (docs/05) */
    uint64_t relay_forwarded;     /* windows this peer forwarded as the RELAY
                                   * hop of another peer's failover detour */
    uint64_t chaos_faults_armed;      /* netem chaos faults armed (process) */
    uint64_t chaos_faults_activated;  /* fault windows observed active */
    /* appended (not inserted mid-struct): consumers compiled against an
     * older layout keep valid offsets for everything above */
    uint64_t trace_ring_pushed;   /* events pushed into the ring since the
                                   * last clear (process-global) */
    uint64_t trace_ring_capacity; /* ring capacity: dropped > 0 means traces
                                   * hold only the newest this-many events */
    /* shared-state chunk plane (docs/04). Conservation identity at sync
     * completion: ss_chunk_bytes_fetched + ss_chunk_bytes_resourced -
     * ss_chunk_bytes_dup == unique chunk bytes delivered. */
    uint64_t ss_chunks_fetched;        /* first-assignment chunk arrivals */
    uint64_t ss_chunks_resourced;      /* arrivals from re-sourced fetches */
    uint64_t ss_chunks_dup;            /* arrivals for already-done chunks */
    uint64_t ss_chunk_bytes_fetched;
    uint64_t ss_chunk_bytes_resourced;
    uint64_t ss_chunk_bytes_dup;
    uint64_t ss_seeder_chunks_served;  /* chunks this peer served as seeder */
    uint64_t ss_seeder_promotions;     /* keys this peer completed + seeded */
    uint64_t ss_seeders_lost;          /* sources lost mid-fetch (survived) */
    uint64_t ss_legacy_syncs;          /* syncs on the 1-seeder fallback */
    /* straggler-failover relay acks (docs/05): end-to-end delivery acks
     * received back at the ORIGIN (kRelayAck), and CONFIRMED-stalled
     * zombie sends retired early because an ack covered their span */
    uint64_t relay_acks;
    uint64_t relay_retired_early;
    /* collective schedule synthesizer (docs/12): ops executed per stamped
     * algorithm, synthesized-program steps run, and PLANNED relay bytes —
     * scheduled kRelayRing detours, kept apart from the watchdog's
     * emergency wd_relays accounting */
    uint64_t sched_ops_ring;
    uint64_t sched_ops_tree;
    uint64_t sched_ops_butterfly;
    uint64_t sched_ops_mesh;
    uint64_t sched_ops_relay;
    uint64_t sched_steps;
    uint64_t sched_relay_planned_bytes;
    /* sparse revision delta (docs/04): chunks skipped because the
     * request-time local leaf hash already matched the expected leaf.
     * Extends the conservation identity: unique delivered bytes +
     * ss_chunk_bytes_delta_skipped == total dirty-key bytes. */
    uint64_t ss_chunks_delta_skipped;
    uint64_t ss_chunk_bytes_delta_skipped;
} pccltCommStats_t;

typedef struct pccltEdgeStats_t {
    char endpoint[64];  /* canonical remote endpoint "ip:port" (netem key) */
    uint64_t tx_bytes;  /* data payload bytes sent (TCP streamed or CMA) */
    uint64_t rx_bytes;  /* data payload bytes received */
    uint64_t tx_frames; /* data sends (frames / same-host descriptors) */
    uint64_t rx_frames;
    uint64_t connects;  /* connections established on this edge */
    uint64_t stall_ms;  /* receiver wire-stall charged to this edge */
    uint64_t tx_zc_frames; /* frames sent via io_uring MSG_ZEROCOPY */
    uint64_t tx_zc_reaps;  /* zerocopy completion notifications reaped */
    /* edge watchdog + window failover (docs/05). Conservation invariant at
     * quiescence per inbound edge:
     *   rx_bytes + rx_relay_bytes - dup_bytes == unique payload delivered */
    uint64_t wd_state;         /* 0 ok, 1 suspect, 2 confirmed (relaying) */
    uint64_t wd_suspects;      /* SUSPECT verdicts raised on this edge */
    uint64_t wd_confirms;      /* SUSPECT -> CONFIRMED escalations */
    uint64_t wd_reissues;      /* windows re-issued on a fresh pool conn */
    uint64_t wd_relays;        /* windows detoured via a healthy neighbor */
    uint64_t rx_relay_bytes;   /* relayed payload delivered (origin-charged) */
    uint64_t rx_relay_windows;
    uint64_t dup_bytes;        /* duplicate arrivals dropped by the dedupe */
    uint64_t dup_windows;
    /* shared-state chunk plane (docs/04): sync payload served to (tx) /
     * fetched from (rx) this edge, kept apart from the collective
     * data-plane byte counters and their conservation invariant */
    uint64_t tx_sync_bytes;
    uint64_t rx_sync_bytes;
    /* multipath striping (docs/08): windows (and their payload bytes)
     * the striped scheduler round-robined across the conn pool — a
     * subset of tx_bytes/tx_frames, zero when PCCLT_STRIPE_CONNS <= 1 */
    uint64_t tx_stripe_windows;
    uint64_t tx_stripe_bytes;
} pccltEdgeStats_t;

/* Snapshot this communicator's counters. */
PCCLT_EXPORT pccltResult_t pccltCommGetStats(pccltComm_t *c,
                                             pccltCommStats_t *out);

/* Snapshot per-edge counters. Writes up to `cap` entries into `out` and
 * always stores the TOTAL edge count into *count (call with cap=0 to size
 * the buffer). */
PCCLT_EXPORT pccltResult_t pccltCommGetEdgeStats(pccltComm_t *c,
                                                 pccltEdgeStats_t *out,
                                                 uint64_t cap, uint64_t *count);

/* Toggle the process-global event recorder at runtime. */
PCCLT_EXPORT pccltResult_t pccltTraceEnable(int on);

/* Drop every captured event (isolates multi-phase runs in one process). */
PCCLT_EXPORT pccltResult_t pccltTraceClear(void);

/* Write the recorder's current event ring as Chrome trace-event JSON
 * (chrome://tracing, ui.perfetto.dev). path NULL falls back to the
 * PCCLT_TRACE env value; with neither set, returns InvalidArgument.
 * Timestamps are CLOCK_MONOTONIC microseconds. */
PCCLT_EXPORT pccltResult_t pccltTraceDump(const char *path);

#ifdef __cplusplus
}
#endif

#endif /* PCCLT_H */
