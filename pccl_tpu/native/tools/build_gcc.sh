#!/bin/bash
# Direct g++ build mirroring CMakeLists.txt, for hosts without cmake/ninja.
# Usage: bash pccl_tpu/native/tools/build_gcc.sh   (artifacts land in native/build/)
set -e
cd "$(dirname "$0")/.."  # pccl_tpu/native
SRC=src
OUT=build
CXX=${CXX:-g++}
FLAGS="-std=c++20 -O3 -g -fPIC -Wall -Wextra -Wno-unused-parameter -fopenmp-simd -Iinclude -pthread"
EXTRA_FLAGS="${PCCLT_BUILD_FLAGS:-}"
mkdir -p $OUT/obj
# coarse header dependency tracking: a changed header (e.g. a class layout
# edit in sockets.hpp) must rebuild EVERY object, or stale objects keep the
# old ABI and the linked library silently misbehaves
NEWEST_HDR=$(ls -t $SRC/*.hpp include/*.h 2>/dev/null | head -1)
objs=""
for f in log telemetry guarded_alloc wire shm sockets uring netem protocol journal hash hash_clmul ss_chunk kernels kernels_avx2 quantize bandwidth atsp schedule benchmark master_state master client reduce api; do
  [ -f $SRC/$f.cpp ] || continue
  arch=""
  [ "$f" = kernels_avx2 ] && arch="-mavx2"
  [ "$f" = hash_clmul ] && arch="-mpclmul -msse4.1"
  if [ $SRC/$f.cpp -nt $OUT/obj/$f.o ] || [ -n "$NEWEST_HDR" -a "$NEWEST_HDR" -nt $OUT/obj/$f.o ] || [ -n "$FORCE" ]; then
    echo "CXX $f.cpp"
    $CXX $FLAGS $EXTRA_FLAGS $arch -c $SRC/$f.cpp -o $OUT/obj/$f.o &
  fi
  objs="$objs $OUT/obj/$f.o"
done
wait
$CXX -shared $FLAGS $EXTRA_FLAGS -o $OUT/libpcclt.so $objs
$CXX $FLAGS $EXTRA_FLAGS -Isrc -o $OUT/pcclt_selftest $SRC/selftest.cpp -L$OUT -lpcclt -Wl,-rpath,'$ORIGIN'
$CXX $FLAGS $EXTRA_FLAGS -Isrc -o $OUT/pcclt_socktest $SRC/socktest.cpp -L$OUT -lpcclt -Wl,-rpath,'$ORIGIN'
$CXX $FLAGS $EXTRA_FLAGS -Isrc -o $OUT/pcclt_fuzz $SRC/fuzz_decode.cpp -L$OUT -lpcclt -Wl,-rpath,'$ORIGIN'
echo "build ok"
