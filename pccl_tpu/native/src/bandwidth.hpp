// Asymmetric peer-to-peer bandwidth store.
// Reference parity: /root/reference/ccoip/internal/bandwidth_store.hpp —
// map<from, map<to, mbps>> with missing-edge enumeration for the
// benchmark scheduler.
#pragma once

#include <map>
#include <optional>
#include <utility>
#include <vector>

#include "protocol.hpp"

namespace pcclt::master {

class BandwidthStore {
public:
    void store(const proto::Uuid &from, const proto::Uuid &to, double mbps);
    std::optional<double> get(const proto::Uuid &from, const proto::Uuid &to) const;
    // directed (from,to) pairs among `peers` with no measurement yet
    std::vector<std::pair<proto::Uuid, proto::Uuid>>
    missing_edges(const std::vector<proto::Uuid> &peers) const;
    void forget(const proto::Uuid &peer);
    bool fully_connected(const std::vector<proto::Uuid> &peers) const {
        return missing_edges(peers).empty();
    }

private:
    std::map<proto::Uuid, std::map<proto::Uuid, double>> mbps_;
};

} // namespace pcclt::master
