// Runtime-dispatched AVX2 bf16 kernels (see kernels_avx2.cpp). Call
// available() once and cache; the add functions are only valid when it
// returned true.
#pragma once

#include <cstddef>
#include <cstdint>

namespace pcclt::kernels::avx2 {

bool available();
// dst[i] = bf16(f32(a[i]) + f32(b[i])), round-to-nearest-even — bit-equal
// to the scalar helpers in kernels.hpp
void bf16_add3(uint16_t *dst, const uint16_t *a, const uint16_t *b, size_t n);
void bf16_add2(uint16_t *dst, const uint16_t *src, size_t n);

} // namespace pcclt::kernels::avx2
