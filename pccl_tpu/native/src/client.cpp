#include "client.hpp"

#include <sys/socket.h>
#include <sys/stat.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <set>

#include "benchmark.hpp"
#include "hash.hpp"
#include "log.hpp"
#include "netem.hpp"
#include "reduce.hpp"

namespace pcclt::client {

using proto::PacketType;

namespace {

size_t max_concurrent_ops() {
    if (const char *e = std::getenv("PCCLT_MAX_CONCURRENT_COLLECTIVE_OPS")) {
        int v = atoi(e);
        if (v > 0) return static_cast<size_t>(v);
    }
    return 16;
}

int env_int(const char *name, int dflt) {
    if (const char *e = std::getenv(name)) return atoi(e);
    return dflt;
}

double env_double(const char *name, double dflt) {
    if (const char *e = std::getenv(name)) {
        double v = atof(e);
        if (v > 0) return v;
    }
    return dflt;
}

// shared-state chunk size (docs/04). 0 disables the chunk plane (legacy
// single-distributor transport + whole-entry hashes). Must agree
// group-wide, like PCCLT_SS_HASH: the chunk-tree root of identical
// content depends on it.
uint64_t ss_chunk_bytes_env() {
    if (const char *e = std::getenv("PCCLT_SS_CHUNK_BYTES")) {
        long long v = atoll(e);
        if (v <= 0) return 0;
        return static_cast<uint64_t>(
            std::clamp<long long>(v, 4096, 64ll << 20));
    }
    return 1ull << 20;
}

} // namespace

Client::~Client() { disconnect(); }

// ---------------- service thread registry ----------------

void Client::spawn_service(
    net::Socket sock,
    std::function<void(net::Socket &, const std::shared_ptr<std::atomic<int>> &)> body) {
    auto fd = std::make_shared<std::atomic<int>>(sock.fd());
    auto done = std::make_shared<std::atomic<bool>>(false);
    // reap finished threads so the vector stays bounded under churn; the
    // joins happen OUTSIDE svc_mu_ — a done-flagged thread exits promptly,
    // but "promptly" on a loaded host is still a stall every accept would
    // serialize behind (blocking-under-lock lint, tools/pcclt_verify)
    std::vector<std::thread> reap;
    {
        MutexLock lk(svc_mu_);
        if (!svc_accepting_) return; // disconnecting: drop the connection
        for (auto it = svc_threads_.begin(); it != svc_threads_.end();) {
            if (it->done->load()) {
                reap.push_back(std::move(it->th));
                it = svc_threads_.erase(it);
            } else {
                ++it;
            }
        }
        SvcThread st;
        st.fd = fd;
        st.done = done;
        st.th = std::thread(
            [sock = std::move(sock), body = std::move(body), fd, done]() mutable {
                body(sock, fd);
                fd->store(-1);
                done->store(true);
            });
        svc_threads_.push_back(std::move(st));
    }
    for (auto &t : reap)
        if (t.joinable()) t.join();
}

// ---------------- accept handlers ----------------

void Client::on_p2p_accept(net::Socket sock) {
    // handshake: peer sends P2PHello{uuid, pool index, p2p listen port};
    // we ack with our uuid
    spawn_service(std::move(sock), [this](net::Socket &sock,
                                          const std::shared_ptr<std::atomic<int>> &fd) {
        auto hello = net::recv_frame(sock, 15'000);
        if (!hello || hello->type != PacketType::kP2PHello) return;
        proto::Uuid peer;
        uint32_t idx = 0;
        uint16_t peer_p2p_port = 0;
        try {
            wire::Reader r(hello->payload);
            peer = proto::get_uuid(r);
            idx = r.u32();
            // the peer's advertised p2p listen port: the accepted socket's
            // source port is ephemeral, so this is the only way to key the
            // conn's wire-emulation edge by the peer's canonical endpoint.
            // Optional (absent = 0) so a bare uuid+idx hello still connects.
            try {
                peer_p2p_port = r.u16();
            } catch (...) {}
        } catch (...) { return; }
        wire::Writer w;
        proto::put_uuid(w, uuid_);
        Mutex mu;
        if (!net::send_frame(sock, mu, PacketType::kP2PHelloAck, w.data())) return;
        sock.set_keepalive();
        sock.set_bufsizes(8 << 20);

        // all inbound conns from one peer share a sink table so striped
        // transfers land in one place
        std::shared_ptr<net::SinkTable> table;
        {
            MutexLock lk(state_mu_);
            auto &pc = peers_[peer];
            if (!pc.rx_table) pc.rx_table = std::make_shared<net::SinkTable>();
            table = pc.rx_table;
        }
        auto conn = std::make_shared<net::MultiplexConn>(std::move(sock), table,
                                                         tele_);
        fd->store(-1); // handed off: the conn owns the fd now
        // relay windows (kRelayFwd/kRelayDeliver) can arrive on ANY conn —
        // accepted ones included — so every conn gets the router
        install_relay_handlers(conn);
        if (peer_p2p_port != 0) {
            // canonical peer endpoint = observed source ip + advertised p2p
            // port: per-edge wire emulation resolves against it (before
            // run(), so the zero-copy gate sees the final emulation state)
            net::Addr pa = conn->socket().peer_addr();
            pa.port = peer_p2p_port;
            conn->set_wire_peer(pa);
        }
        conn->run();
        std::shared_ptr<net::MultiplexConn> replaced;
        {
            MutexLock lk(state_mu_);
            auto &pc = peers_[peer];
            if (pc.rx.size() <= idx) pc.rx.resize(idx + 1);
            replaced = std::move(pc.rx[idx]);
            pc.rx[idx] = conn;
        }
        state_cv_.notify_all();
        // close a replaced conn (peer reconnect) outside state_mu_: close
        // joins its RX/TX threads, which can take a while mid-transfer
        if (replaced) replaced->close();
    });
}

void Client::on_ss_accept(net::Socket sock) {
    spawn_service(std::move(sock), [this](net::Socket &sock,
                                          const std::shared_ptr<std::atomic<int>> &) {
        auto req = net::recv_frame(sock, 15'000);
        if (!req) return;
        if (req->type == PacketType::kC2SStateRequest) {
            ss_serve_legacy(sock, *req);
            return;
        }
        // chunk plane (docs/04): persistent serve loop — one fetch worker
        // issues many range requests over this socket; the conn dies on
        // refusal, socket error, or 30 s idle
        while (req && req->type == PacketType::kC2SChunkRequest) {
            if (!ss_serve_chunk(sock, *req)) return;
            req = net::recv_frame(sock, 30'000);
        }
    });
}

// resolve the netem edge + telemetry counters for a shared-state peer,
// keyed by its CANONICAL endpoint (advertised ip + p2p port — the same
// key the collective data plane, PCCLT_WIRE_*_MAP and PCCLT_WIRE_CHAOS_MAP
// use; port 0 falls back to the shared-state port so un-upgraded peers
// still resolve to something stable)
static std::shared_ptr<net::netem::Edge> ss_edge_for(
    const net::Addr &ip, uint16_t p2p_port, uint16_t fallback_port,
    telemetry::Domain &dom, telemetry::EdgeCounters **ec,
    std::string *key_out = nullptr) {
    net::Addr canon = ip;
    canon.port = p2p_port ? p2p_port : fallback_port;
    std::string key = canon.str();
    *ec = &dom.edge(key);
    if (key_out) *key_out = key;
    return net::netem::Registry::inst().resolve(canon);
}

bool Client::ss_serve_enter(uint64_t revision, const std::string &key) {
    MutexLock lk(dist_mu_);
    if (!dist_open_ || revision != dist_revision_ ||
        !dist_servable_.count(key))
        return false;
    ++dist_serving_;
    return true;
}

void Client::ss_serve_exit() {
    MutexLock lk(dist_mu_);
    if (--dist_serving_ == 0) dist_cv_.notify_all();
}

void Client::ss_close_window() {
    MutexLock lk(dist_mu_);
    dist_open_ = false;
    // wait out in-flight serve slices: their SharedStateEntry copies
    // point into the sync caller's buffers, which the app may free the
    // moment sync_shared_state returns. Slices re-check the window, so
    // this drains within one paced slice.
    while (dist_serving_ > 0) dist_cv_.wait(dist_mu_);
    dist_entries_.clear();
    dist_servable_.clear();
}

void Client::ss_serve_legacy(net::Socket &sock, const net::Frame &req) {
    uint64_t revision;
    std::vector<std::string> keys;
    uint16_t req_p2p = 0;
    try {
        wire::Reader r(req.payload);
        revision = r.u64();
        uint32_t n = r.u32();
        for (uint32_t i = 0; i < n; ++i) keys.push_back(r.str());
        // trailing: requester's advertised p2p port (its canonical
        // data-plane endpoint) so wire emulation + telemetry key this
        // serve by the same edge the collectives use
        try {
            req_p2p = r.u16();
        } catch (...) {}
    } catch (...) { return; }

    std::vector<SharedStateEntry> entries;
    bool ok;
    {
        MutexLock lk(dist_mu_);
        ok = dist_open_ && revision == dist_revision_;
        if (ok)
            for (const auto &k : keys) {
                auto it = dist_entries_.find(k);
                if (it == dist_entries_.end() || !dist_servable_.count(k)) {
                    ok = false;
                    break;
                }
                entries.push_back(it->second);
            }
    }
    wire::Writer w;
    w.u8(ok ? 1 : 0);
    w.u32(ok ? static_cast<uint32_t>(entries.size()) : 0);
    for (const auto &e : entries) {
        w.str(e.name);
        w.u8(static_cast<uint8_t>(e.dtype));
        w.u64(e.count);
    }
    Mutex mu;
    if (!net::send_frame(sock, mu, PacketType::kS2CStateHeader, w.data())) return;
    if (!ok) return;
    telemetry::EdgeCounters *ec = nullptr;
    auto edge = ss_edge_for(sock.peer_addr(), req_p2p,
                            sock.peer_addr().port, *tele_, &ec);
    for (const auto &e : entries) {
        // lazily-staged accelerator entries materialize exactly once
        // per window, before their first byte is served; concurrent
        // fetchers block on the once-flag until the bytes are real.
        // Materialize writes the app's buffer — serving-guarded too.
        if (e.materialize && e.mat_once) {
            if (!ss_serve_enter(revision, e.name)) return;
            std::call_once(*e.mat_once, e.materialize, e.materialize_ctx);
            ss_serve_exit();
        }
        size_t nbytes = e.count * proto::dtype_size(e.dtype);
        // count BEFORE sending: the requester can complete its fetch and
        // the whole dist-done handshake the instant the last byte lands,
        // and the distributor reads this counter right after Done — a
        // post-send increment could still be pending on this thread
        dist_tx_bytes_.fetch_add(nbytes);
        ec->tx_sync_bytes.fetch_add(nbytes, std::memory_order_relaxed);
        // pace in bounded slices so a chaos window (degrade/blackhole)
        // lands mid-transfer instead of being charged up front — and so
        // a window close (sync returning, app reclaiming its buffers)
        // stops the serve at a slice boundary instead of racing it
        const uint8_t *p = static_cast<const uint8_t *>(e.data);
        size_t off = 0;
        while (off < nbytes) {
            size_t n = std::min<size_t>(nbytes - off, 1 << 20);
            if (!ss_serve_enter(revision, e.name)) return;
            if (edge && edge->pace_enabled()) edge->pace(n);
            bool ok = sock.send_all(p + off, n);
            ss_serve_exit();
            if (!ok) return;
            off += n;
        }
    }
}

bool Client::ss_serve_chunk(net::Socket &sock, const net::Frame &req) {
    auto spec = ssc::ChunkReqSpec::decode(req.payload);
    if (!spec) return false;
    uint64_t revision = spec->revision, cb = spec->chunk_bytes;
    std::string key = spec->key;
    uint32_t first = spec->first, count = spec->count;
    uint16_t req_p2p = spec->req_p2p;

    // status: 0 = ok (payload follows), 1 = retry later (window/key not
    // ready — the fetcher backs off without blacklisting us), 2 = refuse
    // (unknown key / bad range — the fetcher re-sources elsewhere)
    SharedStateEntry e;
    int status = 0;
    {
        MutexLock lk(dist_mu_);
        if (!dist_open_ || revision != dist_revision_) {
            status = 1;
        } else {
            auto it = dist_entries_.find(key);
            if (it == dist_entries_.end()) status = 2;
            else if (!dist_servable_.count(key)) status = 1;
            else e = it->second;
        }
    }
    uint64_t nbytes = status == 0 ? e.count * proto::dtype_size(e.dtype) : 0;
    if (status == 0) {
        uint32_t nchunks = ssc::chunk_count(nbytes, cb);
        if (cb == 0 || cb > (64ull << 20) || count == 0 || first >= nchunks ||
            count > nchunks - first)
            status = 2;
    }
    uint64_t payload = 0;
    for (uint32_t i = 0; status == 0 && i < count; ++i)
        payload += ssc::chunk_len(nbytes, cb, first + i);
    wire::Writer w;
    w.u8(static_cast<uint8_t>(status));
    w.u64(payload);
    Mutex mu;
    if (!net::send_frame(sock, mu, PacketType::kS2CChunkHeader, w.data()))
        return false;
    if (status != 0) return status == 1;  // retry keeps the conn alive
    if (e.materialize && e.mat_once) {
        // materialize writes the app's buffer — serving-guarded
        if (!ss_serve_enter(revision, key)) return false;
        std::call_once(*e.mat_once, e.materialize, e.materialize_ctx);
        ss_serve_exit();
    }
    telemetry::EdgeCounters *ec = nullptr;
    auto edge = ss_edge_for(sock.peer_addr(), req_p2p,
                            sock.peer_addr().port, *tele_, &ec);
    const auto *base = static_cast<const uint8_t *>(e.data);
    for (uint32_t i = 0; i < count; ++i) {
        uint64_t len = ssc::chunk_len(nbytes, cb, first + i);
        // per-chunk serving guard: the entry bytes belong to the sync
        // caller; once the window closes (sync returning) this serve
        // must stop touching them — ss_close_window waits us out
        if (!ss_serve_enter(revision, key)) return false;
        if (edge && edge->pace_enabled()) edge->pace(len);
        dist_tx_bytes_.fetch_add(len);
        ec->tx_sync_bytes.fetch_add(len, std::memory_order_relaxed);
        tele_->comm.ss_seeder_chunks_served.fetch_add(
            1, std::memory_order_relaxed);
        bool ok =
            sock.send_all(base + static_cast<uint64_t>(first + i) * cb, len);
        ss_serve_exit();
        if (!ok) return false;
    }
    return true;
}

// ---------------- pooled chunk serve (docs/04 unified transport) ----------

// RX threads land kChunkReq here; they must never do window/materialize/
// striped-send work inline (that would head-of-line-block every tag
// multiplexed on the same conn), so requests queue to a small serve pool.
void Client::chunk_req_enqueue(const uint8_t *requester_uuid, uint64_t tag,
                               std::vector<uint8_t> spec) {
    ChunkServeReq req;
    memcpy(req.requester.data(), requester_uuid, 16);
    req.tag = tag;
    req.spec = std::move(spec);
    MutexLock lk(chunk_mu_);
    if (chunk_stop_) return;  // tearing down: the fetcher re-sources
    if (chunk_threads_.empty()) {
        int n = std::max(1, env_int("PCCLT_SS_SERVE_THREADS", 4));
        for (int i = 0; i < n; ++i)
            chunk_threads_.emplace_back([this] { chunk_serve_loop(); });
    }
    chunk_queue_.push_back(std::move(req));
    chunk_cv_.notify_one();
}

void Client::chunk_serve_loop() {
    while (true) {
        ChunkServeReq req;
        {
            MutexLock lk(chunk_mu_);
            // opportunistic zombie reaping: a parked serve's buffer is
            // freed the moment its last handle drains (or its conn dies);
            // a relay delivery ack retires the stalled direct copy early
            // at the next frame boundary (same idiom as drain_zombies)
            for (auto zit = chunk_zombies_.begin();
                 zit != chunk_zombies_.end();) {
                bool all_done = true;
                for (auto &h : zit->hs) {
                    if (!h) continue;
                    if (!h->done()) {
                        all_done = false;
                        if (!h->cancel.load(std::memory_order_relaxed) &&
                            relay_ack_covered(h->tag, h->off,
                                              h->span.size())) {
                            h->cancel.store(true, std::memory_order_relaxed);
                            tele_->comm.relay_retired_early.fetch_add(
                                1, std::memory_order_relaxed);
                        }
                    }
                }
                if (all_done) zit = chunk_zombies_.erase(zit);
                else ++zit;
            }
            while (chunk_queue_.empty() && !chunk_stop_)
                chunk_cv_.wait_for(chunk_mu_, std::chrono::milliseconds(250));
            if (chunk_stop_) return;
            if (chunk_queue_.empty()) continue;
            req = std::move(chunk_queue_.front());
            chunk_queue_.pop_front();
        }
        chunk_serve_pooled(req.requester, req.tag, req.spec);
    }
}

// Serve one chunk range over the pooled data plane: header via kChunkHdr,
// payload as striped kData windows into the requester's registered sink —
// the exact transport the collectives ride, so the bytes inherit per-lane
// wire emulation, the per-flow cwnd model, zerocopy TX, and (below) the
// same three-stage watchdog failover ladder.
void Client::chunk_serve_pooled(const proto::Uuid &requester, uint64_t tag,
                                const std::vector<uint8_t> &spec) {
    uint64_t revision = 0, cb = 0;
    std::string key;
    uint32_t first = 0, count = 0;
    int status = 0;
    if (auto rs = ssc::ChunkReqSpec::decode(spec)) {
        revision = rs->revision;
        key = rs->key;
        cb = rs->chunk_bytes;
        first = rs->first;
        count = rs->count;
    } else {
        status = 2;
    }

    // the reverse route: header + payload ride OUR tx pool toward the
    // requester, landing in the rx table where its fetch worker registered
    // the sink. Edge accounting keys by the requester's canonical
    // data-plane endpoint — the same edge the collectives and the chaos
    // map use (the sync-byte attribution fix rides on this convergence).
    net::Link txl = tx_link(requester);
    std::shared_ptr<net::MultiplexConn> hdr_conn;
    std::string canon_key;
    {
        MutexLock lk(state_mu_);
        auto it = peers_.find(requester);
        if (it != peers_.end()) {
            net::Addr canon = it->second.ep.ip;
            canon.port = it->second.ep.p2p_port;
            canon_key = canon.str();
            for (const auto &c : it->second.tx)
                if (c && c->alive()) { hdr_conn = c; break; }
        }
    }
    // no route back: drop silently — the fetcher's chunk budget expires
    // and it re-sources from another seeder (normal churn behavior)
    if (!hdr_conn || !txl.valid()) return;

    SharedStateEntry e;
    if (status == 0) {
        MutexLock lk(dist_mu_);
        if (!dist_open_ || revision != dist_revision_) {
            status = 1;
        } else {
            auto it = dist_entries_.find(key);
            if (it == dist_entries_.end()) status = 2;
            else if (!dist_servable_.count(key)) status = 1;
            else e = it->second;
        }
    }
    uint64_t nbytes = status == 0 ? e.count * proto::dtype_size(e.dtype) : 0;
    if (status == 0) {
        uint32_t nchunks = ssc::chunk_count(nbytes, cb);
        if (cb == 0 || cb > (64ull << 20) || count == 0 || first >= nchunks ||
            count > nchunks - first)
            status = 2;
    }
    uint64_t payload = 0;
    for (uint32_t i = 0; status == 0 && i < count; ++i)
        payload += ssc::chunk_len(nbytes, cb, first + i);

    wire::Writer hw;
    hw.u8(static_cast<uint8_t>(status));
    hw.u64(payload);
    hdr_conn->send_owned(net::MultiplexConn::kChunkHdr, tag, 0, hw.take());
    if (status != 0) return;

    if (e.materialize && e.mat_once) {
        // materialize writes the app's buffer — serving-guarded
        if (!ss_serve_enter(revision, key)) return;
        std::call_once(*e.mat_once, e.materialize, e.materialize_ctx);
        ss_serve_exit();
    }

    // Copy the range into OWNED scratch under serving-guard slices: the
    // striped async sends (and any copy parked behind a relay detour)
    // must never read app memory after ss_close_window returns — the
    // guard only covers this copy, not the send lifetime.
    auto buf = std::make_shared<std::vector<uint8_t>>(payload);
    const auto *base = static_cast<const uint8_t *>(e.data);
    const uint64_t src0 = static_cast<uint64_t>(first) * cb;
    for (uint64_t off = 0; off < payload;) {
        uint64_t n = std::min<uint64_t>(payload - off, 1u << 20);
        // window closed mid-copy: the header promised bytes we can no
        // longer read — stop; the fetcher's budget expires + re-sources
        if (!ss_serve_enter(revision, key)) return;
        memcpy(buf->data() + off, base + src0 + off, n);
        ss_serve_exit();
        off += n;
    }

    // count BEFORE the sends complete: the requester can finish its round
    // the instant the last byte lands, and the distributor reads
    // dist_tx_bytes_ right after Done — a post-send increment could still
    // be pending on this thread (same rationale as the legacy serve)
    auto *ec = &tele_->edge(canon_key);
    dist_tx_bytes_.fetch_add(payload);
    ec->tx_sync_bytes.fetch_add(payload, std::memory_order_relaxed);
    tele_->comm.ss_seeder_chunks_served.fetch_add(count,
                                                  std::memory_order_relaxed);

    // striped launch: the range is one window sub-striped across the pool
    // (the collective grid: PCCLT_STRIPE_CONNS clamped to pool, 64 KiB
    // sub floor) — conn TX paces per-lane on the netem edge, so a chaos
    // degrade/blackhole lands mid-transfer exactly like a collective's
    size_t stripes = 4;
    if (const char *se = std::getenv("PCCLT_STRIPE_CONNS")) {
        int v = atoi(se);
        if (v > 0) stripes = static_cast<size_t>(v);
    }
    stripes = std::max<size_t>(1, std::min(stripes, txl.size()));
    const size_t rot0 = static_cast<size_t>(
        chunk_tag_seq_.fetch_add(1, std::memory_order_relaxed));
    constexpr size_t kSubMin = 64u << 10;
    std::vector<net::SendHandle> hs;
    if (stripes <= 1 || payload < 2 * kSubMin) {
        hs.push_back(txl.send_at(tag, 0,
                                 {buf->data(), static_cast<size_t>(payload)},
                                 rot0));
    } else {
        size_t sub = (static_cast<size_t>(payload) + stripes - 1) / stripes;
        if (sub < kSubMin) sub = kSubMin;
        for (size_t off = 0, j = 0; off < payload; off += sub, ++j)
            hs.push_back(txl.send_at(
                tag, off,
                {buf->data() + off,
                 std::min(sub, static_cast<size_t>(payload) - off)},
                rot0 + j % stripes));
        ec->tx_stripe_windows.fetch_add(1, std::memory_order_relaxed);
        ec->tx_stripe_bytes.fetch_add(payload, std::memory_order_relaxed);
    }

    // ---- watchdog ladder join (docs/05, serve side) ----
    // Same opt-in + envelope as the collectives: deadline = factor x the
    // EWMA-predicted transfer time, floored. SUSPECT re-issues the
    // pending backlog on a fresh conn (races the originals — receiver
    // dedupe makes the copy free); CONFIRMED detours the backlog via a
    // third peer in 1 MiB relay windows and stops waiting on the direct
    // copies. A capped join bounds the serve thread; whatever is still
    // pending parks as a zombie holding the buffer alive.
    const bool wd_on = [] {
        const char *wde = std::getenv("PCCLT_WATCHDOG");
        return wde && wde[0] && wde[0] != '0';
    }();
    const double wd_factor = env_double("PCCLT_WATCHDOG_FACTOR", 4.0);
    const uint64_t wd_min_ns =
        static_cast<uint64_t>(env_int("PCCLT_WATCHDOG_MIN_MS", 300)) *
        1'000'000ull;
    auto deadline_ns = [&](uint64_t bytes) {
        uint64_t rate = ec->wd_rate_bps.load(std::memory_order_relaxed);
        uint64_t base_t = rate > 0
                              ? static_cast<uint64_t>(bytes * 1e9 / rate)
                              : 500'000'000ull;
        return std::max(static_cast<uint64_t>(base_t * wd_factor), wd_min_ns);
    };
    auto mark = [&](telemetry::EdgeHealth v) {
        auto nv = static_cast<uint32_t>(v);
        uint32_t cur = ec->wd_health.load(std::memory_order_relaxed);
        while (cur < nv && !ec->wd_health.compare_exchange_weak(
                               cur, nv, std::memory_order_relaxed)) {
        }
        if (v == telemetry::EdgeHealth::kSuspect)
            ec->wd_suspects.fetch_add(1, std::memory_order_relaxed);
        if (v == telemetry::EdgeHealth::kConfirmed) {
            ec->wd_confirms.fetch_add(1, std::memory_order_relaxed);
            ec->wd_confirmed_at_ns.store(telemetry::now_ns(),
                                         std::memory_order_relaxed);
        }
    };
    const uint64_t t_launch = telemetry::now_ns();
    uint64_t t_rung = t_launch;  // re-armed at each escalation
    bool reissued = false, confirmed = false;
    net::Link fresh;
    std::vector<net::SendHandle> extra;  // reissue copies (kept for zombies)
    std::set<const net::SendState *> satisfied;  // detoured or copy-covered
    std::set<const net::SendState *> measured;   // fed the EWMA already
    // give-up cap: bounds a serve thread even when every rung fails
    // (requester gone, no third peer) — the fetcher re-sources regardless
    const uint64_t cap_ns =
        std::max<uint64_t>(3 * deadline_ns(payload), 30'000'000'000ull);
    std::map<const net::SendState *, net::SendHandle> reissue_of;
    while (true) {
        uint64_t backlog = 0;
        net::SendHandle oldest;
        for (auto &h : hs) {
            if (satisfied.count(h.get())) continue;
            if (h->done()) {
                if (!measured.count(h.get())) {
                    measured.insert(h.get());
                    if (h->wait(0) &&
                        ec->wd_health.load(std::memory_order_relaxed) == 0) {
                        // healthy completion feeds the EWMA — with the
                        // anti-poisoning clamp: a sample an order of
                        // magnitude under the envelope IS the degradation
                        uint64_t dur = telemetry::now_ns() - t_launch;
                        uint64_t rate =
                            ec->wd_rate_bps.load(std::memory_order_relaxed);
                        bool degraded =
                            rate > 0 && dur > 0 &&
                            h->span.size() * 1e9 / dur < rate / 8.0;
                        if (!degraded && dur >= 1'000'000 &&
                            h->span.size() >= kSubMin) {
                            auto r2 = static_cast<uint64_t>(h->span.size() *
                                                            1e9 / dur);
                            ec->wd_rate_bps.store(
                                rate ? static_cast<uint64_t>(0.7 * rate +
                                                             0.3 * r2)
                                     : r2,
                                std::memory_order_relaxed);
                        }
                    }
                }
                continue;
            }
            // a landed reissue copy satisfies its stalled original: the
            // bytes are delivered (receiver-side dedupe), the original
            // drains as a zombie
            auto rit = reissue_of.find(h.get());
            if (rit != reissue_of.end() && rit->second->done() &&
                rit->second->wait(0)) {
                satisfied.insert(h.get());
                continue;
            }
            backlog += h->span.size();
            if (!oldest) oldest = h;
        }
        if (!oldest) break;  // everything delivered / detoured / satisfied
        const uint64_t now = telemetry::now_ns();
        if (now - t_launch > cap_ns) break;  // give up: park as zombie
        if (wd_on && now - t_rung > deadline_ns(backlog)) {
            if (!reissued) {
                // rung 1, SUSPECT: one fresh conn, re-issue the backlog —
                // first copy to land wins, the loser drains as a zombie
                reissued = true;
                t_rung = telemetry::now_ns();
                mark(telemetry::EdgeHealth::kSuspect);
                fresh = fresh_pool_conn(requester);
                if (fresh.valid()) {
                    for (auto &h : hs) {
                        if (h->done() || satisfied.count(h.get())) continue;
                        auto h2 = fresh.send_at(h->tag, h->off, h->span, 0);
                        reissue_of[h.get()] = h2;
                        extra.push_back(std::move(h2));
                        ec->wd_reissues.fetch_add(1,
                                                  std::memory_order_relaxed);
                    }
                }
                continue;
            }
            if (!confirmed) {
                // rung 2, CONFIRMED: detour the backlog via a third peer
                // in relay windows; detoured spans stop gating the join
                confirmed = true;
                t_rung = telemetry::now_ns();
                bool any = false;
                constexpr size_t kRelayWin = 1u << 20;
                for (auto &h : hs) {
                    if (h->done() || satisfied.count(h.get())) continue;
                    bool ok = true;
                    const uint8_t *p = h->span.data();
                    for (size_t off = 0; ok && off < h->span.size();
                         off += kRelayWin) {
                        size_t n = std::min(kRelayWin, h->span.size() - off);
                        ok = relay_window_via(requester, tag, h->off + off,
                                              {p + off, n});
                        if (ok)
                            ec->wd_relays.fetch_add(
                                1, std::memory_order_relaxed);
                    }
                    if (ok) {
                        satisfied.insert(h.get());
                        any = true;
                    }
                }
                if (any) mark(telemetry::EdgeHealth::kConfirmed);
                continue;
            }
            // both rungs burned: wait out the cap, then zombie
        }
        oldest->wait(50);
    }
    // park whatever is still pending (stalled originals behind a detour,
    // loser reissue copies): the zombie holds the scratch alive until the
    // handles drain or their conns die; the sweep in chunk_serve_loop
    // cancels acked spans early and frees the buffer
    ChunkTxZombie z;
    for (auto &h : hs)
        if (h && !h->done()) z.hs.push_back(h);
    for (auto &h : extra)
        if (h && !h->done()) z.hs.push_back(h);
    if (!z.hs.empty()) {
        z.buf = std::move(buf);
        MutexLock lk(chunk_mu_);
        chunk_zombies_.push_back(std::move(z));
    }
}

void Client::chunk_serve_stop_join() {
    std::vector<std::thread> threads;
    {
        MutexLock lk(chunk_mu_);
        chunk_stop_ = true;
        chunk_queue_.clear();
        threads.swap(chunk_threads_);
        chunk_cv_.notify_all();
    }
    for (auto &t : threads) t.join();
    // called after every pool conn closed: close() failed all pending
    // handles, so the parked buffers are safe to drop
    MutexLock lk(chunk_mu_);
    chunk_zombies_.clear();
}

void Client::on_bench_accept(net::Socket sock) {
    static bench::ServeState state;
    spawn_service(std::move(sock), [](net::Socket &sock,
                                      const std::shared_ptr<std::atomic<int>> &) {
        bench::serve_connection(std::move(sock), state);
    });
}

// ---------------- connect / disconnect ----------------

Status Client::connect() {
    if (connected_.load()) return Status::kInvalid;
    {
        MutexLock lk(svc_mu_);
        svc_accepting_ = true;
    }
    {
        // re-arm the pooled chunk-serve plane after a prior disconnect
        MutexLock lk(chunk_mu_);
        chunk_stop_ = false;
    }
    if (!p2p_listener_.listen(cfg_.p2p_port, 64)) return Status::kInternal;
    if (!ss_listener_.listen(cfg_.ss_port, 64)) return Status::kInternal;
    if (!bench_listener_.listen(cfg_.bench_port, 64)) return Status::kInternal;
    p2p_listener_.run_async([this](net::Socket s) { on_p2p_accept(std::move(s)); });
    ss_listener_.run_async([this](net::Socket s) { on_ss_accept(std::move(s)); });
    bench_listener_.run_async([this](net::Socket s) { on_bench_accept(std::move(s)); });

    if (!master_.connect(cfg_.master)) return Status::kMasterUnreachable;
    // incident black box (docs/09): consume the fire-and-forget capture
    // order on the reader — no recv_match ever waits for it, and the map
    // must be populated before the first run() (it survives resumes)
    master_.set_notify(
        static_cast<uint16_t>(PacketType::kM2CIncidentDump),
        [this](net::Frame &&f) { on_incident_dump(std::move(f)); });
    // schedule plane (docs/12): fire-and-forget table broadcasts after an
    // optimize round. Adopted for introspection/telemetry only — the
    // per-op algorithm binding is the commence stamp, so a late or lost
    // update can never split the group.
    master_.set_notify(
        static_cast<uint16_t>(PacketType::kM2CScheduleUpdate),
        [this](net::Frame &&f) {
            if (auto su = proto::ScheduleUpdateM2C::decode(f.payload)) {
                if (auto t = sched::Table::decode(su->table)) {
                    MutexLock lk(state_mu_);
                    if (t->version >= sched_table_.version)
                        sched_table_ = std::move(*t);
                }
            }
        });
    master_.run();

    proto::HelloC2M h;
    h.peer_group = cfg_.peer_group;
    h.p2p_port = p2p_listener_.port();
    h.ss_port = ss_listener_.port();
    h.bench_port = bench_listener_.port();
    h.adv_ip = cfg_.adv_ip;
    if (!master_.send(PacketType::kC2MHello, h.encode())) return Status::kMasterUnreachable;
    auto welcome = master_.recv_match(PacketType::kM2CWelcome, nullptr, 30'000);
    if (!welcome) return Status::kMasterUnreachable;
    try {
        wire::Reader r(welcome->payload);
        if (r.u8() != 1) {
            std::string reason;
            try {
                reason = r.str();
            } catch (...) {}
            PLOG(kError) << "master rejected join"
                         << (reason.empty() ? "" : ": " + reason);
            return Status::kMasterUnreachable;
        }
        uuid_ = proto::get_uuid(r);
        // master epoch (HA) trails the welcome string; tolerate its absence
        // so an older master still welcomes us
        try {
            r.str();
            uint64_t ep = r.u64();
            master_epoch_.store(ep, std::memory_order_relaxed);
            telemetry::Recorder::inst().set_epoch(ep);
        } catch (...) {}
    } catch (...) { return Status::kInternal; }
    connected_ = true;

    // blocks until the first topology round admits us
    Status st = establish_loop();
    if (st != Status::kOk) {
        connected_ = false;
        return st;
    }
    // fleet observability plane (docs/09): periodic digest pushes to the
    // master. Off unless PCCLT_TELEMETRY_PUSH_MS gives a cadence.
    int push_ms = env_int("PCCLT_TELEMETRY_PUSH_MS", 0);
    if (push_ms > 0) {
        tele_stop_ = false;
        tele_thread_ = std::thread([this, push_ms] {
            telemetry_push_loop(push_ms);
        });
    }
    PLOG(kInfo) << "connected as " << proto::uuid_str(uuid_) << ", group world "
                << group_world();
    return Status::kOk;
}

void Client::telemetry_push_loop(int push_ms) {
    telemetry::DigestSnapshotter snap(tele_);
    // sleep in short slices so disconnect() joins promptly even on a
    // multi-second cadence
    const auto slice = std::chrono::milliseconds(20);
    auto next = std::chrono::steady_clock::now() +
                std::chrono::milliseconds(push_ms);
    while (!tele_stop_.load(std::memory_order_relaxed)) {
        if (std::chrono::steady_clock::now() < next) {
            std::this_thread::sleep_for(slice);
            continue;
        }
        next += std::chrono::milliseconds(push_ms);
        auto d = snap.snapshot();
        proto::TelemetryDigestC2M pkt;
        pkt.epoch = master_epoch_.load(std::memory_order_relaxed);
        pkt.last_seq = d.last_seq;
        pkt.interval_ms = d.interval_ns / 1'000'000;
        pkt.ring_dropped = d.ring_dropped;
        pkt.collectives_ok = d.collectives_ok;
        for (auto &e : d.edges) {
            proto::TelemetryDigestC2M::Edge pe;
            pe.endpoint = e.endpoint;
            pe.tx_mbps = e.tx_mbps;
            pe.rx_mbps = e.rx_mbps;
            pe.stall_ratio = e.stall_ratio;
            pe.tx_bytes = e.tx_bytes;
            pe.rx_bytes = e.rx_bytes;
            pe.wd_state = static_cast<uint8_t>(e.wd_state);
            pe.stage_wire_hist = {e.stage_wire_hist.sum_ns,
                                  telemetry::hist_sparse(e.stage_wire_hist)};
            pe.stall_hist = {e.stall_hist.sum_ns,
                             telemetry::hist_sparse(e.stall_hist)};
            pkt.edges.push_back(std::move(pe));
        }
        for (auto &o : d.ops) pkt.ops.push_back({o.seq, o.dur_ns, o.stall_ns});
        // trailing attribution section: ring accounting + the comm-level
        // phase latency histograms (empty phases stay off the wire)
        pkt.ring_pushed = d.ring_pushed;
        pkt.ring_cap = d.ring_cap;
        for (size_t p = 0; p < telemetry::kPhaseCount; ++p)
            if (!d.phases[p].empty())
                pkt.phase_hists.emplace_back(
                    static_cast<uint8_t>(p),
                    proto::WireHist{d.phases[p].sum_ns,
                                    telemetry::hist_sparse(d.phases[p])});
        // fire and forget: a down master link is the resume path's problem,
        // not ours — the next digest after a resume carries fresh rates
        if (master_.send(PacketType::kC2MTelemetryDigest, pkt.encode()))
            tele_->comm.telemetry_digests.fetch_add(1,
                                                    std::memory_order_relaxed);
    }
}

// ---------------- incident black box (docs/09) ----------------

void Client::on_incident_dump(net::Frame &&f) {
    auto d = proto::IncidentDumpM2C::decode(f.payload);
    if (!d) return;
    if (const char *e = std::getenv("PCCLT_INCIDENT_DIR"); !e || !e[0])
        return; // peer opted out of the black box
    std::thread prev;
    {
        MutexLock lk(incident_mu_);
        if (d->incident_id == last_incident_id_) return; // duplicate order
        if (incident_busy_ && incident_busy_->load(std::memory_order_acquire)) {
            // previous bundle still writing (rate limiter off or a slow
            // disk): skip rather than stall the control reader — abort /
            // commence packets must keep flowing during an incident storm
            PLOG(kWarn) << "incident " << d->incident_id
                        << " skipped: previous bundle still writing";
            return;
        }
        last_incident_id_ = d->incident_id;
        prev = std::move(incident_thread_);
        auto busy = std::make_shared<std::atomic<bool>>(true);
        incident_busy_ = busy;
        incident_thread_ = std::thread([this, dump = *d, busy] {
            write_incident_bundle(dump);
            busy->store(false, std::memory_order_release);
        });
    }
    // the previous writer already cleared busy, so this join is instant
    if (prev.joinable()) prev.join();
}

void Client::write_incident_bundle(const proto::IncidentDumpM2C &d) {
    const char *env = std::getenv("PCCLT_INCIDENT_DIR");
    if (!env || !env[0]) return;
    std::string dir(env);
    ::mkdir(dir.c_str(), 0755);
    dir += "/" + d.incident_id; // id is charset-validated at decode
    ::mkdir(dir.c_str(), 0755);
    const std::string me = proto::uuid_str(uuid_).substr(0, 8);
    PLOG(kWarn) << "incident " << d.incident_id << " (" << d.trigger
                << "): writing black-box bundle under " << dir;
    // 1. the flight-recorder ring as-is (the pcclt_trace_meta header
    //    documents capture state even when the recorder was off)
    telemetry::Recorder::inst().dump_json(dir + "/peer-" + me +
                                          ".trace.json");
    // 2. counters + per-edge stats snapshot, with the trigger context
    FILE *f = fopen((dir + "/peer-" + me + ".stats.json").c_str(), "w");
    if (!f) return;
    auto esc = [](const std::string &s) { return telemetry::json_escape(s); };
    const auto &cm = tele_->comm;
    auto ld = [](const std::atomic<uint64_t> &a) {
        return a.load(std::memory_order_relaxed);
    };
    fprintf(f,
            "{\"incident_id\":\"%s\",\"trigger\":\"%s\",\"epoch\":%llu,"
            "\"uuid\":\"%s\",\"counters\":{"
            "\"collectives_ok\":%llu,\"collectives_aborted\":%llu,"
            "\"collectives_lost\":%llu,\"kicked\":%llu,"
            "\"master_reconnects\":%llu,\"relay_forwarded\":%llu,"
            "\"trace_ring_pushed\":%llu,\"trace_ring_dropped\":%llu},"
            "\"edges\":{",
            esc(d.incident_id).c_str(), esc(d.trigger).c_str(),
            (unsigned long long)d.epoch, proto::uuid_str(uuid_).c_str(),
            (unsigned long long)ld(cm.collectives_ok),
            (unsigned long long)ld(cm.collectives_aborted),
            (unsigned long long)ld(cm.collectives_lost),
            (unsigned long long)ld(cm.kicked),
            (unsigned long long)ld(cm.master_reconnects),
            (unsigned long long)ld(cm.relay_forwarded),
            (unsigned long long)telemetry::Recorder::inst().pushed(),
            (unsigned long long)telemetry::Recorder::inst().dropped());
    bool first = true;
    for (const auto &e : tele_->snapshot_edges()) {
        fprintf(f,
                "%s\"%s\":{\"tx_bytes\":%llu,\"rx_bytes\":%llu,"
                "\"stall_ms\":%llu,\"wd_state\":%u,\"wd_suspects\":%llu,"
                "\"wd_confirms\":%llu,\"wd_reissues\":%llu,"
                "\"wd_relays\":%llu,\"rx_relay_bytes\":%llu,"
                "\"dup_bytes\":%llu,"
                "\"stage_p99_ms\":%.3f,\"stall_p99_ms\":%.3f}",
                first ? "" : ",", esc(e.endpoint).c_str(),
                (unsigned long long)e.tx_bytes, (unsigned long long)e.rx_bytes,
                (unsigned long long)(e.stall_ns / 1000000),
                e.wd_health, (unsigned long long)e.wd_suspects,
                (unsigned long long)e.wd_confirms,
                (unsigned long long)e.wd_reissues,
                (unsigned long long)e.wd_relays,
                (unsigned long long)e.rx_relay_bytes,
                (unsigned long long)e.dup_bytes,
                e.stage_wire_hist.quantile_ns(0.99) / 1e6,
                e.stall_hist.quantile_ns(0.99) / 1e6);
        first = false;
    }
    fputs("}}\n", f);
    fclose(f);
}

void Client::disconnect() {
    connected_ = false; // unparks an in-flight resume loop promptly
    tele_stop_ = true;  // telemetry push thread drains within a sleep slice
    std::unique_ptr<util::WorkerPool> pool;
    {
        MutexLock lk(ops_mu_);
        for (auto &[_, op] : ops_) {
            op->abort = true;
            op->result.wait();
        }
        ops_.clear();
        pool = std::move(op_pool_); // taken under the admission lock
    }
    pool.reset(); // joins the pooled worker threads (they never take ops_mu_)
    // Join the push thread BEFORE master_.close() tears the socket down
    // (a send racing the fd teardown is UB) but AFTER shutting the wire:
    // a digest send stuck in a blocking ::send against a master that
    // stopped reading (wedged process, black-holed link) would otherwise
    // hold the join for the kernel TCP timeout. Ops are already drained,
    // so nothing else needs the control conn.
    master_.shutdown_wire();
    if (tele_thread_.joinable()) tele_thread_.join();
    {
        // serialize against resume_master_session's reconnect of master_
        MutexLock lk(resume_mu_);
        master_.close();
    }
    // incident writer: join AFTER master_.close() — the control reader is
    // the only spawner of incident_thread_ (set_notify dispatch), and
    // close() joins it, so a kM2CIncidentDump read during teardown cannot
    // respawn the writer behind this join. Join outside incident_mu_
    // (blocking-under-lock).
    std::thread inc;
    {
        MutexLock lk(incident_mu_);
        inc = std::move(incident_thread_);
    }
    if (inc.joinable()) inc.join();
    p2p_listener_.stop();
    ss_listener_.stop();
    bench_listener_.stop();
    // interrupt + join all service threads before tearing down state they touch
    std::vector<SvcThread> svcs;
    {
        MutexLock lk(svc_mu_);
        svc_accepting_ = false;
        for (auto &s : svc_threads_) {
            int fd = s.fd->load();
            if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
        }
        svcs = std::move(svc_threads_);
        svc_threads_.clear();
    }
    for (auto &s : svcs)
        if (s.th.joinable()) s.th.join();
    // detach the peer map under state_mu_, close OUTSIDE it: close() joins
    // each conn's rx/tx threads, and holding the client's state lock across
    // those joins stalls every concurrent state reader for the whole
    // teardown (blocking-under-lock lint, tools/pcclt_verify). Nothing can
    // repopulate peers_ here — the listeners and service threads are
    // already down.
    std::map<proto::Uuid, PeerConns> peers;
    {
        MutexLock lk(state_mu_);
        peers.swap(peers_);
        ring_.clear();
    }
    for (auto &[_, pc] : peers) {
        for (auto &c : pc.tx)
            if (c) c->close();
        for (auto &c : pc.rx)
            if (c) c->close();
    }
    // LAST: the conn closes above failed every pending send handle, so the
    // serve pool's parked zombie buffers are droppable and the workers
    // (which only touch peers_ via the state lock) have nothing to serve
    chunk_serve_stop_join();
}

Status Client::check_kicked() {
    auto kicked = master_.recv_match(PacketType::kM2CKicked, nullptr, 0, true);
    if (kicked) {
        std::string reason;
        try {
            wire::Reader r(kicked->payload);
            reason = r.str();
        } catch (...) {}
        PLOG(kError) << "kicked by master: " << reason;
        tele_->comm.kicked.fetch_add(1, std::memory_order_relaxed);
        if (telemetry::Recorder::inst().on())
            telemetry::Recorder::inst().instant(
                "membership", "kicked", nullptr, 0, nullptr, 0,
                telemetry::intern(reason));
        connected_ = false;
        return Status::kKicked;
    }
    // link down is no longer session death: the session may still resume
    // (classify_master_loss) — connected_ only drops when resume gives up
    if (!master_.connected()) return Status::kConnectionLost;
    return Status::kOk;
}

// ---------------- master HA: session resume ----------------

Status Client::resume_master_session() {
    MutexLock lk(resume_mu_);
    if (master_.connected()) return Status::kOk; // another caller already resumed
    if (!connected_.load()) return Status::kNotConnected;
    const int attempts = cfg_.reconnect_attempts >= 0
                             ? cfg_.reconnect_attempts
                             : env_int("PCCLT_RECONNECT_ATTEMPTS", 8);
    if (attempts <= 0) return Status::kMasterUnreachable;
    const int backoff_ms = cfg_.reconnect_backoff_ms > 0
                               ? cfg_.reconnect_backoff_ms
                               : env_int("PCCLT_RECONNECT_BACKOFF_MS", 100);
    const int cap_ms = cfg_.reconnect_backoff_cap_ms > 0
                           ? cfg_.reconnect_backoff_cap_ms
                           : env_int("PCCLT_RECONNECT_MAX_BACKOFF_MS", 2000);
    auto t0 = telemetry::now_ns();
    telemetry::Recorder::inst().instant("membership", "master_limbo", "epoch",
                                        master_epoch_.load());
    std::mt19937_64 rng{std::random_device{}() ^
                        static_cast<uint64_t>(reinterpret_cast<uintptr_t>(this))};
    for (int a = 0; a < attempts; ++a) {
        if (a > 0) {
            // exponential backoff with jitter: desynchronizes a whole world
            // of clients hammering the restarting master in lockstep.
            // Slept in slices so a concurrent disconnect() (which waits on
            // resume_mu_) is released within ~100 ms, not a full backoff.
            double d = std::min<double>(cap_ms, backoff_ms * double(1ull << (a - 1)));
            d *= 0.5 + std::uniform_real_distribution<>{}(rng);
            for (double slept = 0; slept < d && connected_.load(); slept += 100)
                std::this_thread::sleep_for(std::chrono::duration<double, std::milli>(
                    std::min(100.0, d - slept)));
        }
        if (!connected_.load()) return Status::kNotConnected; // disconnect() raced
        if (!master_.reconnect(cfg_.master)) continue; // master still down
        master_.run();
        proto::SessionResumeC2M req;
        req.uuid = uuid_;
        req.last_revision = last_sync_revision_.load();
        req.p2p_port = p2p_listener_.port();
        req.ss_port = ss_listener_.port();
        req.bench_port = bench_listener_.port();
        req.adv_ip = cfg_.adv_ip;
        if (!master_.send(PacketType::kC2MSessionResume, req.encode())) continue;
        auto fr = master_.recv_match(PacketType::kM2CSessionResumeAck, nullptr,
                                     10'000);
        if (!fr) continue; // died again mid-handshake: next backoff slot
        auto ack = proto::SessionResumeAck::decode(fr->payload);
        if (!ack) continue;
        if (!ack->ok) {
            // the master is up but holds no journaled state for us (no
            // journal, limbo expired, or uuid re-bound): resuming is
            // impossible — the caller must re-register from scratch
            PLOG(kWarn) << "session resume rejected: " << ack->reason;
            telemetry::Recorder::inst().instant(
                "membership", "master_resume_rejected", "epoch", ack->epoch,
                nullptr, 0, telemetry::intern(ack->reason));
            master_.close();
            return Status::kMasterUnreachable;
        }
        master_epoch_.store(ack->epoch, std::memory_order_relaxed);
        telemetry::Recorder::inst().set_epoch(ack->epoch);
        // the master's journaled group revision may be AHEAD of what we saw
        // complete (its Done to us was lost in the crash); adopt the max so
        // the app can skip re-syncing an already-completed revision
        uint64_t lr = last_sync_revision_.load(std::memory_order_relaxed);
        while (ack->last_revision > lr &&
               !last_sync_revision_.compare_exchange_weak(lr, ack->last_revision)) {}
        reconnects_.fetch_add(1, std::memory_order_relaxed);
        session_gen_.fetch_add(1, std::memory_order_release);
        tele_->comm.master_reconnects.fetch_add(1, std::memory_order_relaxed);
        PLOG(kInfo) << "master session resumed as " << proto::uuid_str(uuid_)
                    << " (epoch " << ack->epoch << ", attempt " << a + 1 << ")";
        telemetry::Recorder::inst().span("membership", "master_resume", t0,
                                         telemetry::now_ns(), "epoch",
                                         ack->epoch, "attempts",
                                         static_cast<uint64_t>(a + 1));
        return Status::kOk;
    }
    PLOG(kError) << "master unreachable after " << attempts << " reconnect attempts";
    return Status::kMasterUnreachable;
}

Status Client::classify_master_loss() {
    // a queued kick is authoritative — we were thrown out, the master lives
    auto kicked = master_.recv_match(PacketType::kM2CKicked, nullptr, 0, true);
    if (kicked) {
        std::string reason;
        try {
            wire::Reader r(kicked->payload);
            reason = r.str();
        } catch (...) {}
        PLOG(kError) << "kicked by master: " << reason;
        tele_->comm.kicked.fetch_add(1, std::memory_order_relaxed);
        if (telemetry::Recorder::inst().on())
            telemetry::Recorder::inst().instant("membership", "kicked", nullptr,
                                                0, nullptr, 0,
                                                telemetry::intern(reason));
        connected_ = false;
        return Status::kKicked;
    }
    if (master_.connected()) return Status::kConnectionLost; // not a link loss
    Status st = resume_master_session();
    if (st == Status::kOk)
        return Status::kConnectionLost; // session re-bound; caller retries the op
    connected_ = false;
    return st;
}

// ---------------- topology / establishment ----------------

size_t Client::pool_width() const {
    // configured width, grown to what the striped data plane wants
    // (PCCLT_STRIPE_CONNS, docs/08) so setting the env alone provisions
    // enough parallel paths for the window scheduler; capped at 8.
    size_t n = cfg_.pool_size ? cfg_.pool_size : 1;
    if (const char *e = std::getenv("PCCLT_STRIPE_CONNS")) {
        long v = atol(e);
        if (v > 1) n = std::max(n, static_cast<size_t>(std::min<long>(v, 8)));
    }
    return n;
}

Status Client::establish_from_info(const proto::P2PConnInfo &info,
                                   std::vector<proto::Uuid> &failed) {
    const size_t width = pool_width();
    for (const auto &ep : info.peers) {
        // take the old pool + shared table under the lock, then do all the
        // blocking connect/handshake work OUTSIDE state_mu_ so attribute
        // reads and the p2p accept path never stall behind a reconnect
        std::vector<std::shared_ptr<net::MultiplexConn>> old_pool;
        std::shared_ptr<net::SinkTable> table;
        {
            MutexLock lk(state_mu_);
            auto &pc = peers_[ep.uuid];
            // Blip-not-rebuild: when the peer's endpoint is unchanged and
            // every pooled conn is still alive, keep the pool — a topology
            // round after a master restart (or a plain re-vote) then moves
            // ZERO data-plane bytes. A peer that died and rejoined always
            // reconnects: it comes back under a fresh UUID (or, post-resume,
            // with its old conns dead).
            bool reusable = pc.ep.ip == ep.ip && pc.ep.p2p_port == ep.p2p_port &&
                            pc.tx.size() == width && !pc.tx.empty();
            if (reusable)
                for (const auto &c : pc.tx)
                    if (!c || !c->alive()) reusable = false;
            if (reusable) {
                pc.ep = ep;
                tele_->comm.p2p_conns_reused.fetch_add(
                    pc.tx.size(), std::memory_order_relaxed);
                continue;
            }
            pc.ep = ep;
            old_pool = std::move(pc.tx);
            pc.tx.clear();
            if (!pc.tx_table) pc.tx_table = std::make_shared<net::SinkTable>();
            table = pc.tx_table;
        }
        // reconnect from scratch each round: robust under churn
        for (auto &c : old_pool)
            if (c) c->close();
        old_pool.clear();

        std::vector<std::shared_ptr<net::MultiplexConn>> pool;
        bool ok = true;
        for (size_t i = 0; i < width; ++i) {
            // dial_p2p retries transient connect/handshake failures on a
            // bounded backoff (p2p reconnect hardening) and installs the
            // straggler-relay routing before the conn runs
            auto conn = dial_p2p(ep, static_cast<uint32_t>(i), table);
            if (!conn) {
                ok = false;
                break;
            }
            pool.push_back(std::move(conn));
        }
        if (!ok) {
            failed.push_back(ep.uuid);
            for (auto &c : pool)
                if (c) c->close();
        } else {
            MutexLock lk(state_mu_);
            peers_[ep.uuid].tx = std::move(pool);
        }
    }
    // drop peers no longer in the world (close outside the lock: close joins
    // the conns' RX/TX threads)
    std::vector<std::shared_ptr<net::MultiplexConn>> to_close;
    {
        MutexLock lk(state_mu_);
        std::set<proto::Uuid> alive;
        for (const auto &ep : info.peers) alive.insert(ep.uuid);
        for (auto it = peers_.begin(); it != peers_.end();) {
            if (!alive.count(it->first)) {
                for (auto &c : it->second.tx)
                    if (c) to_close.push_back(c);
                for (auto &c : it->second.rx)
                    if (c) to_close.push_back(c);
                it = peers_.erase(it);
            } else {
                ++it;
            }
        }
    }
    for (auto &c : to_close) c->close();
    return failed.empty() ? Status::kOk : Status::kInternal;
}

void Client::adopt(const proto::P2PConnInfo &info, const std::vector<proto::Uuid> &ring) {
    size_t joined = 0, left = 0;
    {
        MutexLock lk(state_mu_);
        // membership churn counters: ring delta vs the previous adoption
        // (self excluded — it is not a peer)
        for (const auto &u : ring)
            if (u != uuid_ &&
                std::find(ring_.begin(), ring_.end(), u) == ring_.end())
                ++joined;
        for (const auto &u : ring_)
            if (u != uuid_ &&
                std::find(ring.begin(), ring.end(), u) == ring.end())
                ++left;
        ring_ = ring;
        topo_revision_ = info.revision;
        // trailing schedule table rides the conn info (docs/12): a
        // rejoining peer adopts ring order and schedule in one step
        if (!info.sched.empty())
            if (auto t = sched::Table::decode(info.sched))
                if (t->version >= sched_table_.version)
                    sched_table_ = std::move(*t);
        // Sweep stale watchdog verdicts (docs/05): the in-op re-probe only
        // runs while an edge is the CURRENT ring successor, so a verdict on
        // an edge the re-opt routed AWAY from would otherwise latch forever
        // — its digests would keep the master's straggler flag up and the
        // substituted matrix rate in place long after the link recovered.
        // A verdict older than the CONFIRMED hold has served its purpose;
        // dropping it lets the edge prove itself if it re-enters the ring.
        const uint64_t hold_ns = static_cast<uint64_t>(
            env_int("PCCLT_WATCHDOG_HOLD_MS", 5000)) * 1'000'000ull;
        const uint64_t now = telemetry::now_ns();
        for (auto &[uuid, pc] : peers_) {
            net::Addr pa = pc.ep.ip;
            pa.port = pc.ep.p2p_port;
            auto &e = tele_->edge(pa.str());
            uint32_t h = e.wd_health.load(std::memory_order_relaxed);
            if (h == 0) continue;
            uint64_t since = e.wd_confirmed_at_ns.load(std::memory_order_relaxed);
            const bool succ = !ring.empty() &&
                              uuid == ring[(static_cast<size_t>(
                                                std::find(ring.begin(), ring.end(),
                                                          uuid_) -
                                            ring.begin()) + 1) % ring.size()];
            if (!succ && (h == 1 || now - since > hold_ns))
                e.wd_health.compare_exchange_strong(h, 0,
                                                    std::memory_order_relaxed);
        }
    }
    tele_->comm.peers_joined.fetch_add(joined, std::memory_order_relaxed);
    tele_->comm.peers_left.fetch_add(left, std::memory_order_relaxed);
    if (telemetry::Recorder::inst().on())
        telemetry::Recorder::inst().instant("membership", "topology_adopt",
                                            "world", ring.size(), "revision",
                                            info.revision);
}

Status Client::establish_loop(bool vote_deferrable) {
    while (true) {
        if (auto st = check_kicked(); st != Status::kOk) {
            // master link down mid-round: classify (and maybe resume); any
            // vote we held died with the old session — the caller re-votes
            return st == Status::kConnectionLost ? classify_master_loss() : st;
        }
        std::optional<net::Frame> fr;
        if (vote_deferrable) {
            // the master declines the vote (kM2CTopologyDeferred) when our
            // group is mid-collective/sync commence: a parked voter would
            // cross-wait with the round forever. Deferred = no-op success;
            // the caller's admit-pending loop re-votes after its next op.
            fr = master_.recv_match_any(
                {static_cast<uint16_t>(PacketType::kM2CP2PConnInfo),
                 static_cast<uint16_t>(PacketType::kM2CTopologyDeferred)},
                nullptr, 120'000);
            if (fr && fr->type == static_cast<uint16_t>(PacketType::kM2CTopologyDeferred))
                return Status::kOk;
            vote_deferrable = false; // only the first wait can be deferred
        } else {
            fr = master_.recv_match(PacketType::kM2CP2PConnInfo, nullptr, 120'000);
        }
        if (!fr) {
            if (master_.connected()) {
                // round stalled with the link up: old surface (kick-aware)
                auto st = check_kicked();
                return st == Status::kOk ? Status::kMasterUnreachable : st;
            }
            return classify_master_loss();
        }
        // stale rounds may have queued older conn infos; use the newest
        while (auto newer = master_.recv_match(PacketType::kM2CP2PConnInfo, nullptr, 0, true))
            fr = std::move(newer);
        auto info = proto::P2PConnInfo::decode(fr->payload);
        if (!info) return Status::kInternal;

        std::vector<proto::Uuid> failed;
        establish_from_info(*info, failed);

        wire::Writer w;
        w.u64(info->revision);
        w.u8(failed.empty() ? 1 : 0);
        w.u32(static_cast<uint32_t>(failed.size()));
        for (const auto &f : failed) proto::put_uuid(w, f);
        if (!master_.send(PacketType::kC2MP2PEstablished, w.data()))
            return classify_master_loss();

        // match only this round's response (stale-round responses are dropped
        // by revision, mirroring the reference's connection-revision guard)
        auto rev_pred = [rev = info->revision](const std::vector<uint8_t> &p) {
            try {
                wire::Reader r(p);
                return r.u64() == rev;
            } catch (...) { return false; }
        };
        auto resp =
            master_.recv_match(PacketType::kM2CP2PEstablishedResp, rev_pred, 120'000);
        if (!resp) {
            if (master_.connected()) {
                auto st = check_kicked();
                return st == Status::kOk ? Status::kMasterUnreachable : st;
            }
            return classify_master_loss();
        }
        try {
            wire::Reader r(resp->payload);
            r.u64(); // revision (matched by predicate)
            bool ok = r.u8() != 0;
            uint32_t n = r.u32();
            std::vector<proto::Uuid> ring;
            for (uint32_t i = 0; i < n; ++i) ring.push_back(proto::get_uuid(r));
            if (ok) {
                adopt(*info, ring);
                return Status::kOk;
            }
        } catch (...) { return Status::kInternal; }
        // retry: wait for the next round's conn info
    }
}

Status Client::update_topology() {
    if (!connected_.load()) return Status::kNotConnected;
    auto t0 = telemetry::now_ns();
    Status st = Status::kConnectionLost;
    // a master blip mid-round is absorbed here: resume the session and
    // re-vote (the old master's vote died with it) instead of surfacing a
    // loss the app would treat as a world reset. Bounded so a flapping
    // master still fails out.
    for (int round = 0; round < 4; ++round) {
        if (!connected_.load()) return Status::kNotConnected;
        if (!master_.connected()) {
            Status rst = resume_master_session();
            if (rst != Status::kOk) {
                connected_ = false;
                return rst;
            }
        }
        if (!master_.send(PacketType::kC2MTopologyUpdate, {})) {
            st = Status::kConnectionLost;
            continue; // next round resumes the session first
        }
        st = establish_loop(/*vote_deferrable=*/true);
        if (st != Status::kConnectionLost) break; // done, or a non-link failure
    }
    if (st == Status::kOk) {
        tele_->comm.topology_updates.fetch_add(1, std::memory_order_relaxed);
        telemetry::Recorder::inst().span("membership", "update_topology", t0,
                                         telemetry::now_ns(), "world",
                                         group_world());
    }
    return st;
}

Status Client::are_peers_pending(bool &pending) {
    if (!connected_.load()) return Status::kNotConnected;
    for (int attempt = 0; attempt < 2; ++attempt) {
        if (!master_.send(PacketType::kC2MPeersPendingQuery, {})) {
            auto st = classify_master_loss();
            if (st != Status::kConnectionLost) return st;
            continue; // session resumed underneath: retry the query
        }
        auto fr = master_.recv_match(PacketType::kM2CPeersPendingReply, nullptr, 30'000);
        if (fr) {
            pending = !fr->payload.empty() && fr->payload[0] != 0;
            return Status::kOk;
        }
        auto st = classify_master_loss();
        if (st != Status::kConnectionLost) return st;
    }
    return Status::kConnectionLost;
}

Status Client::optimize_topology() {
    if (!connected_.load()) return Status::kNotConnected;
    if (!master_.send(PacketType::kC2MOptimizeTopology, {}))
        return classify_master_loss();
    // the whole-group optimize round serializes probes per target, so a fast
    // peer may wait roughly (world * window * retry-budget) for the slowest
    // prober; the wait must scale accordingly or healthy large clusters time out
    const int optimize_wait_ms = std::max(
        300'000, static_cast<int>(std::min(3'600'000.0,
                     2000.0 * std::max<uint32_t>(2, global_world()) *
                         std::max(1.0, bench::probe_seconds()))));
    while (true) {
        auto fr = master_.recv_match_any(
            {PacketType::kM2COptimizeResponse, PacketType::kM2COptimizeComplete}, nullptr,
            optimize_wait_ms);
        if (!fr) {
            if (master_.connected()) {
                auto st = check_kicked();
                return st == Status::kOk ? Status::kMasterUnreachable : st;
            }
            // the optimize round died with the master; resume (if possible)
            // and let the caller re-enter a fresh round
            return classify_master_loss();
        }
        if (fr->type == PacketType::kM2COptimizeComplete) {
            try {
                wire::Reader r(fr->payload);
                bool ok = r.u8() != 0;
                uint32_t n = r.u32();
                std::vector<proto::Uuid> ring;
                for (uint32_t i = 0; i < n; ++i) ring.push_back(proto::get_uuid(r));
                if (ok) {
                    MutexLock lk(state_mu_);
                    ring_ = ring;
                }
                if (ok) {
                    tele_->comm.topology_optimizes.fetch_add(
                        1, std::memory_order_relaxed);
                    telemetry::Recorder::inst().instant(
                        "membership", "optimize_topology", "world",
                        group_world());
                }
                return ok ? Status::kOk : Status::kInternal;
            } catch (...) { return Status::kInternal; }
        }
        auto resp = proto::OptimizeResponse::decode(fr->payload);
        if (!resp) return Status::kInternal;
        for (const auto &req : resp->requests) {
            // busy-retry budget must outlast the worst-case queue: the target
            // admits one prober at a time for probe_seconds() each, and with
            // W peers up to W-1 probers can be queued ahead of us, so the
            // deadline scales with the world size
            const double window = bench::probe_seconds();
            const uint32_t world = std::max<uint32_t>(2, global_world());
            const auto busy_deadline =
                std::chrono::steady_clock::now() +
                std::chrono::duration<double>(world * window + 3.0);
            std::mt19937_64 jitter_rng{
                std::random_device{}() ^
                static_cast<uint64_t>(reinterpret_cast<uintptr_t>(&req))};
            double mbps = -1.0;
            int hard_failures = 0;
            while (mbps < 0) {
                net::Addr ba = req.ip;
                ba.port = req.bench_port;
                mbps = bench::run_probe(ba);
                if (mbps == -2.0) { // busy; jittered nap, retry until deadline
                    mbps = -1.0;
                    // jitter desynchronizes probers that got rejected at the
                    // same instant so they don't re-collide in lockstep
                    const double nap = std::max(0.2, window / 5.0) *
                                       (0.5 + std::uniform_real_distribution<>{}(jitter_rng));
                    if (std::chrono::steady_clock::now() +
                            std::chrono::duration<double>(nap) < busy_deadline) {
                        std::this_thread::sleep_for(std::chrono::duration<double>(nap));
                        continue;
                    }
                    break;
                }
                // hard failures get 5 tries of their own, independent of how
                // many busy rejections came before
                if (mbps < 0 && ++hard_failures >= 5) break;
            }
            if (mbps < 0) mbps = 0.001; // unreachable: report epsilon
            wire::Writer w;
            proto::put_uuid(w, req.to);
            w.f64(mbps);
            if (!master_.send(PacketType::kC2MBandwidthReport, w.data()))
                return classify_master_loss();
        }
        if (!master_.send(PacketType::kC2MOptimizeWorkDone, {}))
            return classify_master_loss();
    }
}

// ---------------- conn lookup ----------------

Status Client::gather_slot(uint64_t *slot) {
    if (!connected_.load()) return Status::kNotConnected;
    MutexLock lk(state_mu_);
    if (ring_.empty()) return Status::kInvalid;
    std::vector<proto::Uuid> sorted = ring_;
    std::sort(sorted.begin(), sorted.end());
    auto it = std::find(sorted.begin(), sorted.end(), uuid_);
    if (it == sorted.end()) return Status::kInternal;
    *slot = static_cast<uint64_t>(it - sorted.begin());
    return Status::kOk;
}

// ---------------- straggler-immune data plane (docs/05) ----------------

void Client::install_relay_handlers(
    const std::shared_ptr<net::MultiplexConn> &conn) {
    conn->set_relay_handlers(
        // RELAY hop: re-emit the window toward its final destination over
        // our own healthy link. Runs on the conn's RX thread holding no
        // lock; the send is enqueue-only (send_owned never writes inline).
        [this](const uint8_t *dst, const uint8_t *origin, uint64_t tag,
               uint64_t off, std::vector<uint8_t> bytes) {
            proto::Uuid d;
            memcpy(d.data(), dst, 16);
            std::shared_ptr<net::MultiplexConn> out;
            {
                MutexLock lk(state_mu_);
                auto it = peers_.find(d);
                if (it != peers_.end())
                    for (const auto &c : it->second.tx)
                        if (c && c->alive()) {
                            out = c;
                            break;
                        }
            }
            if (!out) {
                PLOG(kDebug) << "relay: no live link toward final dst; "
                                "dropping window tag=" << tag;
                return;
            }
            std::vector<uint8_t> payload(16 + bytes.size());
            memcpy(payload.data(), origin, 16);
            if (!bytes.empty())
                memcpy(payload.data() + 16, bytes.data(), bytes.size());
            out->send_owned(net::MultiplexConn::kRelayDeliver, tag, off,
                            std::move(payload));
            tele_->comm.relay_forwarded.fetch_add(1,
                                                  std::memory_order_relaxed);
        },
        // FINAL destination: the window belongs to the ORIGIN peer's
        // inbound link — place it into that link's sink table (dedupe +
        // conservation accounting charge the origin's edge), then ack
        // delivery END-TO-END so the origin can retire its stalled direct
        // copy early (kRelayAck rides our own reverse link to the origin,
        // which is a different direction from the degraded hop)
        [this](const uint8_t *origin, uint64_t tag, uint64_t off,
               std::vector<uint8_t> bytes) {
            proto::Uuid o;
            memcpy(o.data(), origin, 16);
            std::shared_ptr<net::SinkTable> table;
            telemetry::EdgeCounters *edge = nullptr;
            std::shared_ptr<net::MultiplexConn> ack_out;
            {
                MutexLock lk(state_mu_);
                auto it = peers_.find(o);
                if (it != peers_.end() && it->second.rx_table) {
                    table = it->second.rx_table;
                    net::Addr pa = it->second.ep.ip;
                    pa.port = it->second.ep.p2p_port;
                    edge = &tele_->edge(pa.str());
                    for (const auto &c : it->second.tx)
                        if (c && c->alive()) {
                            ack_out = c;
                            break;
                        }
                }
            }
            if (!table) {
                PLOG(kDebug) << "relay-deliver for unknown origin; dropping "
                                "window tag=" << tag;
                return;
            }
            const uint64_t len = bytes.size();
            bool settled = table->deliver_window(tag, off, std::move(bytes),
                                                 edge);
            if (ack_out && settled) {
                // fire-and-forget (enqueue-only: we are on an RX thread);
                // the ack covers the RANGE — whether this copy or an
                // earlier one placed the bytes, [off, off+len) is durably
                // accounted for. deliver_window withholds `settled` when
                // any byte was skipped against a mid-write CLAIM: the
                // claim-holder can still die and tear those bytes, and an
                // ack would let the origin cancel the last remaining copy
                // on lying coverage (model-checker finding,
                // relay_vs_direct_deaths)
                wire::Writer w;
                w.u64(len);
                ack_out->send_owned(net::MultiplexConn::kRelayAck, tag, off,
                                    w.take());
            }
        },
        // ORIGIN side: merge the acked range so drain_zombies can query it
        [this](uint64_t tag, uint64_t off, uint64_t len) {
            note_relay_ack(tag, off, len);
        });
    // chunk plane on the pool (docs/04 unified transport): a kChunkReq can
    // arrive on any inbound conn; the RX thread only enqueues — the serve
    // pool does the window/materialize/striped-send work
    conn->set_chunk_req_handler(
        [this](const uint8_t *req_uuid, uint64_t tag,
               std::vector<uint8_t> spec) {
            chunk_req_enqueue(req_uuid, tag, std::move(spec));
        });
}

void Client::note_relay_ack(uint64_t tag, uint64_t off, uint64_t len) {
    if (len == 0) return;
    MutexLock lk(relay_mu_);
    // bounded: tags are op-scoped and monotone, so evicting the lowest tag
    // range when full can only drop stale ops' acks
    if (relay_acks_.size() > 64 && !relay_acks_.count(tag))
        relay_acks_.erase(relay_acks_.begin());
    auto &m = relay_acks_[tag];
    uint64_t lo = off, hi = off + len;
    auto it = m.upper_bound(lo);
    if (it != m.begin()) {
        auto p = std::prev(it);
        if (p->second >= lo) {
            lo = p->first;
            hi = std::max(hi, p->second);
            it = m.erase(p);
        }
    }
    while (it != m.end() && it->first <= hi) {
        hi = std::max(hi, it->second);
        it = m.erase(it);
    }
    m[lo] = hi;
    tele_->comm.relay_acks.fetch_add(1, std::memory_order_relaxed);
}

bool Client::relay_ack_covered(uint64_t tag, uint64_t off, size_t len) {
    MutexLock lk(relay_mu_);
    auto t = relay_acks_.find(tag);
    if (t == relay_acks_.end()) return false;
    auto it = t->second.upper_bound(off);
    if (it == t->second.begin()) return false;
    return std::prev(it)->second >= off + len;
}

void Client::purge_relay_acks(uint64_t lo, uint64_t hi) {
    MutexLock lk(relay_mu_);
    for (auto it = relay_acks_.lower_bound(lo);
         it != relay_acks_.end() && it->first < hi;)
        it = relay_acks_.erase(it);
}

std::shared_ptr<net::MultiplexConn> Client::dial_p2p(
    const proto::PeerEndpoint &ep, uint32_t idx,
    const std::shared_ptr<net::SinkTable> &table, int attempts_override) {
    // p2p connect/reconnect hardening: a peer mid-restart refuses or
    // resets the first dial — retry on a bounded exponential backoff with
    // jitter (the PR-3 reconnect_* family) instead of failing the round.
    // The default p2p budget is intentionally smaller than the master's:
    // a genuinely dead peer must still fail the round promptly so the
    // master can kick it.
    int attempts = attempts_override > 0
                       ? attempts_override
                       : std::min(2, std::max(1, cfg_.reconnect_attempts > 0
                                                     ? cfg_.reconnect_attempts
                                                     : env_int("PCCLT_RECONNECT_ATTEMPTS", 8)));
    const int backoff_ms = cfg_.reconnect_backoff_ms > 0
                               ? cfg_.reconnect_backoff_ms
                               : env_int("PCCLT_RECONNECT_BACKOFF_MS", 100);
    const int cap_ms = cfg_.reconnect_backoff_cap_ms > 0
                           ? cfg_.reconnect_backoff_cap_ms
                           : env_int("PCCLT_RECONNECT_MAX_BACKOFF_MS", 2000);
    std::mt19937_64 rng{std::random_device{}() ^
                        static_cast<uint64_t>(reinterpret_cast<uintptr_t>(this)) ^
                        idx};
    for (int a = 0; a < attempts; ++a) {
        if (a > 0) {
            double d = std::min<double>(cap_ms,
                                        backoff_ms * double(1ull << (a - 1)));
            d *= 0.5 + std::uniform_real_distribution<>{}(rng);
            std::this_thread::sleep_for(
                std::chrono::duration<double, std::milli>(d));
        }
        net::Socket s;
        net::Addr pa = ep.ip;
        pa.port = ep.p2p_port;
        if (!s.connect(pa, 5000)) continue;
        s.set_keepalive();
        s.set_bufsizes(8 << 20);
        wire::Writer w;
        proto::put_uuid(w, uuid_);
        w.u32(idx);
        // our p2p listen port: lets the acceptor key its side of this
        // conn by our canonical endpoint (per-edge wire emulation)
        w.u16(p2p_listener_.port());
        Mutex mu;
        if (!net::send_frame(s, mu, PacketType::kP2PHello, w.data())) continue;
        auto ack = net::recv_frame(s, 15'000);
        if (!ack || ack->type != PacketType::kP2PHelloAck) continue;
        auto conn = std::make_shared<net::MultiplexConn>(std::move(s), table,
                                                         tele_);
        conn->set_wire_peer(pa); // canonical endpoint (= the addr dialed)
        install_relay_handlers(conn);
        conn->run();
        return conn;
    }
    return nullptr;
}

net::Link Client::fresh_pool_conn(const proto::Uuid &peer) {
    proto::PeerEndpoint ep;
    std::shared_ptr<net::SinkTable> table;
    uint32_t idx = 0;
    {
        MutexLock lk(state_mu_);
        auto it = peers_.find(peer);
        if (it == peers_.end() || !it->second.tx_table) return {};
        ep = it->second.ep;
        table = it->second.tx_table;
        idx = static_cast<uint32_t>(it->second.tx.size());
    }
    // exactly one dial: the watchdog already burned a deadline getting
    // here — a second stall escalates to the relay rung instead
    auto conn = dial_p2p(ep, idx, table, /*attempts_override=*/1);
    if (!conn) return {};
    bool adopted = false;
    {
        MutexLock lk(state_mu_);
        auto it = peers_.find(peer);
        if (it != peers_.end()) {
            it->second.tx.push_back(conn); // heals the pool for later ops
            adopted = true;
        }
    }
    if (!adopted) {
        conn->close();
        return {};
    }
    return net::Link({conn}, table);
}

bool Client::relay_window_via(const proto::Uuid &dst, uint64_t tag,
                              uint64_t off, std::span<const uint8_t> payload) {
    // relay-path load balancing (docs/05): collect EVERY healthy third
    // peer and rotate successive windows across them — one funnel neighbor
    // caps detour throughput at a single relay's egress, striping detours
    // multiplies it. PCCLT_RELAY_FANOUT caps the candidate set (in ring
    // order): 1 = the PR-10 single-neighbor behavior.
    std::vector<std::shared_ptr<net::MultiplexConn>> candidates;
    {
        MutexLock lk(state_mu_);
        for (const auto &u : ring_) {
            if (u == uuid_ || u == dst) continue;
            auto it = peers_.find(u);
            if (it == peers_.end()) continue;
            for (const auto &c : it->second.tx)
                if (c && c->alive()) {
                    candidates.push_back(c);
                    break;
                }
        }
    }
    if (candidates.empty()) return false;
    size_t fan = static_cast<size_t>(env_int("PCCLT_RELAY_FANOUT", 0));
    if (fan == 0 || fan > candidates.size()) fan = candidates.size();
    auto via = candidates[relay_rr_.fetch_add(1, std::memory_order_relaxed) %
                          fan];
    std::vector<uint8_t> buf(32 + payload.size());
    memcpy(buf.data(), dst.data(), 16);
    memcpy(buf.data() + 16, uuid_.data(), 16);
    if (!payload.empty())
        memcpy(buf.data() + 32, payload.data(), payload.size());
    auto h = via->send_owned(net::MultiplexConn::kRelayFwd, tag, off,
                             std::move(buf));
    // wait out the first (local, healthy) hop: a failure here lets the
    // caller fall back to the direct path; the relay->dst hop is covered
    // by receiver-side dedupe + the degraded direct copy still in flight
    return h->wait(-1);
}

net::Link Client::tx_link(const proto::Uuid &peer) {
    MutexLock lk(state_mu_);
    auto it = peers_.find(peer);
    if (it == peers_.end() || it->second.tx.empty()) return {};
    return net::Link(it->second.tx, it->second.tx_table);
}

net::Link Client::rx_link(const proto::Uuid &peer, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
    MutexLock lk(state_mu_);
    while (true) {
        auto it = peers_.find(peer);
        if (it != peers_.end()) {
            for (const auto &c : it->second.rx) {
                if (c && c->alive())
                    return net::Link(it->second.rx, it->second.rx_table);
            }
        }
        if (state_cv_.wait_until(state_mu_, deadline) == std::cv_status::timeout)
            return {};
    }
}

// ---------------- collectives ----------------

Status Client::all_reduce_async(const void *send, void *recv, uint64_t count,
                                proto::DType dtype, const ReduceDesc &desc) {
    if (!connected_.load()) return Status::kNotConnected;
    if (!send || !recv || count == 0) return Status::kInvalid;
    // gather forwards verbatim: quantization has no meaning on this op
    if (desc.op == proto::RedOp::kGather && desc.quant != proto::QuantAlgo::kNone)
        return Status::kInvalid;
    if (group_world() < 2) return Status::kTooFewPeers;
    {
        MutexLock lk(ops_mu_);
        // re-check under the lock: a concurrent disconnect() clears ops_ and
        // tears the pool down under this same mutex, so an op admitted here
        // can never race the pool's destruction
        if (!connected_.load()) return Status::kNotConnected;
        if (ops_.count(desc.tag)) return Status::kDuplicateTag;
        if (ops_.size() >= max_concurrent_ops()) return Status::kPendingAsyncOps;
        // pool sized to the concurrency cap, created on first use: every
        // admitted op gets a thread immediately (reference: the client
        // state's pithreadpool, ccoip_client_state.hpp:98)
        if (!op_pool_)
            op_pool_ = std::make_unique<util::WorkerPool>(max_concurrent_ops());
        auto op = std::make_unique<AsyncOp>();
        auto promise = std::make_shared<std::promise<Status>>();
        op->result = promise->get_future();
        AsyncOp *op_ptr = op.get();
        op_pool_->submit([this, send, recv, count, dtype, desc, op_ptr, promise] {
            Status st = run_reduce_worker(send, recv, count, dtype, desc, op_ptr);
            promise->set_value(st);
        });
        ops_[desc.tag] = std::move(op);
    }
    return Status::kOk;
}

Status Client::run_reduce_worker(const void *send, void *recv, uint64_t count,
                                 proto::DType dtype, ReduceDesc desc, AsyncOp *op) {
    bool is_retry;
    uint64_t retry_seq = 0;
    {
        MutexLock lk(retry_mu_);
        auto it = retry_tags_.find(desc.tag);
        is_retry = it != retry_tags_.end();
        if (is_retry) retry_seq = it->second;
    }
    uint64_t observed_seq = 0;
    Status st = run_reduce_worker_impl(send, recv, count, dtype, desc, op,
                                       is_retry, retry_seq, &observed_seq);
    // a session-loss outcome marks the NEXT init of this tag as a retry of
    // the attempt that observed `observed_seq` at commence; any concluded
    // outcome (ok/aborted/fatal) clears the mark. A RETRY that itself died
    // pre-commence keeps the ORIGINAL incarnation seq — overwriting with 0
    // would unkey the journaled verdict forever (code-review catch)
    MutexLock lk(retry_mu_);
    if (st == Status::kConnectionLost)
        retry_tags_[desc.tag] =
            (is_retry && observed_seq == 0) ? retry_seq : observed_seq;
    else
        retry_tags_.erase(desc.tag);
    return st;
}

Status Client::run_reduce_worker_impl(const void *send, void *recv, uint64_t count,
                                      proto::DType dtype, const ReduceDesc &desc,
                                      AsyncOp *op, bool is_retry,
                                      uint64_t retry_seq, uint64_t *observed_seq) {
    // session generation at op start: if a concurrent thread resumes the
    // master session mid-op, replies to THIS op's packets can never arrive
    // on the new session — bail with a retryable status instead of waiting
    // out the full commence/verdict timeouts
    const uint64_t gen0 = session_gen_.load(std::memory_order_acquire);
    auto session_flipped = [&] {
        return session_gen_.load(std::memory_order_acquire) != gen0;
    };
    // 1. initiate with master, await commence (predicate-matched by tag)
    proto::CollectiveInit ci;
    ci.tag = desc.tag;
    ci.count = count;
    ci.dtype = dtype;
    ci.op = desc.op;
    ci.quant = desc.quant;
    ci.quant_dtype = desc.quant_dtype;
    ci.retry = is_retry ? 1 : 0;
    ci.retry_seq = retry_seq;
    ci.aux = desc.aux;
    if (!master_.send(PacketType::kC2MCollectiveInit, ci.encode()))
        return classify_master_loss();

    auto tag_pred = [tag = desc.tag](const std::vector<uint8_t> &p) {
        try {
            wire::Reader r(p);
            return r.u64() == tag;
        } catch (...) { return false; }
    };
    if (session_flipped()) return Status::kConnectionLost;
    // ---- pre-arm (docs/08): local op setup overlapped with the master
    // consensus round trip. The in-place snapshot memcpy (the largest
    // fixed local cost after PR 8's buffer pooling) and the optimistic
    // ring/link resolution run WHILE the commence wait is in flight, so
    // op_setup afterwards is a re-validation, not work. The snapshot does
    // not depend on the commence at all (the caller owns the buffer for
    // the op's whole lifetime); the links are re-checked against the
    // post-commence ring and redone on the rare mid-wait reshuffle.
    const size_t nbytes = count * proto::dtype_size(dtype);
    std::vector<uint8_t> snapshot;
    if (send == recv) {
        snapshot = take_scratch();
        if (snapshot.capacity() < nbytes) snapshot = std::vector<uint8_t>();
        snapshot.resize(nbytes);
        memcpy(snapshot.data(), recv, nbytes);
    }
    std::vector<proto::Uuid> ring0;
    {
        MutexLock lk(state_mu_);
        ring0 = ring_;
    }
    net::Link pre_tx, pre_rx;
    auto self0 = std::find(ring0.begin(), ring0.end(), uuid_);
    if (self0 != ring0.end() && ring0.size() >= 2) {
        uint32_t r0 = static_cast<uint32_t>(self0 - ring0.begin());
        uint32_t w0 = static_cast<uint32_t>(ring0.size());
        pre_tx = tx_link(ring0[(r0 + 1) % w0]);
        pre_rx = rx_link(ring0[(r0 + w0 - 1) % w0], 0);  // no wait: optimistic
    }
    // Wait for commence OR an abort verdict. An abort BEFORE any commence
    // is a restarted master replaying the outcome of an op that completed
    // under its previous incarnation (our Done was lost in the crash, the
    // peers moved on, and no commence will ever come — journal OpDoneRec,
    // found by the pcclt-verify model checker). In the normal flow the
    // commence always precedes any abort on this ordered connection.
    auto frame_tag_pred = [tag = desc.tag](const net::Frame &f) {
        try {
            wire::Reader r(f.payload);
            return r.u64() == tag;
        } catch (...) { return false; }
    };
    uint64_t commence_t0 = telemetry::now_ns();
    auto commence = master_.recv_match_any(
        {static_cast<uint16_t>(PacketType::kM2CCollectiveCommence),
         static_cast<uint16_t>(PacketType::kM2CCollectiveAbort)},
        frame_tag_pred, 600'000);
    const uint64_t commence_t1 = telemetry::now_ns();
    // the span is emitted on EVERY exit from this wait (seq 0 when none
    // was issued): an op that dies here is exactly the one an incident
    // bundle needs consensus-wait evidence for
    auto commence_span = [&](uint64_t seq_v) {
        if (telemetry::Recorder::inst().on())
            telemetry::Recorder::inst().span("collective", "commence_wait",
                                             commence_t0, commence_t1, "tag",
                                             desc.tag, "seq", seq_v);
    };
    // pre-armed snapshot on paths that never reach the ring: back to the
    // pool (warm pages), not the allocator
    auto drop_prearm = [&] { give_scratch(std::move(snapshot)); };
    if (!commence) {
        // master loss / 600 s timeout: NOT a consensus-wait sample — one
        // overflow-bucket entry would pin the cumulative commence_wait
        // p99 gauge to ~137 s for the rest of the process lifetime
        commence_span(0);
        drop_prearm();
        return classify_master_loss();
    }
    // attribution histogram: the consensus wait is a first-class phase —
    // the residual ~40 ms/op the ROADMAP multipath item hunts lives here.
    // Recorded only when the master actually answered (commence or a
    // replayed verdict), so the distribution measures consensus latency,
    // not failure timeouts.
    tele_->record_phase(telemetry::Phase::kCommenceWait,
                        commence_t1 - commence_t0);
    if (commence->type == static_cast<uint16_t>(PacketType::kM2CCollectiveAbort)) {
        commence_span(0);
        bool replay_aborted = true;
        uint32_t replay_world = 0;
        try {
            wire::Reader r(commence->payload);
            r.u64();
            replay_aborted = r.u8() != 0;
            replay_world = r.u32(); // replayed verdicts carry the op world
        } catch (...) {}
        auto done =
            master_.recv_match(PacketType::kM2CCollectiveDone, tag_pred, 600'000);
        drop_prearm();
        if (!done) return classify_master_loss();
        // kOk: our ring ran to completion back then — the retry's recv
        // buffer (same args per the retry contract, and uniquely for this
        // path the SAME buffer) already holds the result. kAborted: the
        // group aborted it; retry from the inputs.
        op->info.world = replay_world;
        return replay_aborted ? Status::kAborted : Status::kOk;
    }
    if (session_flipped()) {
        commence_span(0);
        drop_prearm();
        return Status::kConnectionLost;
    }
    uint64_t seq;
    // commence stamp (docs/12): the master binds ONE algorithm + root per
    // op. Trailing fields — a pre-schedule master's commence simply stops
    // after seq and the op runs ring (the executors' shared default).
    sched::Algo sched_algo = sched::Algo::kRing;
    uint32_t sched_root = 0;
    try {
        wire::Reader r(commence->payload);
        r.u64();
        seq = r.u64();
        if (r.remaining() >= 13) {
            sched_algo = static_cast<sched::Algo>(r.u8());
            sched_root = r.u32();
            r.u64();  // table version the stamp was drawn from (telemetry)
        }
    } catch (...) {
        commence_span(0);
        drop_prearm();
        return Status::kInternal;
    }
    *observed_seq = seq; // the incarnation a session-loss retry refers to
    // emitted here, not at the recv: the span carries the master-issued
    // seq (known only now) so trace_critic can pin it to its collective
    commence_span(seq);

    // 2. snapshot ring + neighbor connections
    std::vector<proto::Uuid> ring;
    {
        MutexLock lk(state_mu_);
        ring = ring_;
    }
    uint32_t world = static_cast<uint32_t>(ring.size());
    auto self_it = std::find(ring.begin(), ring.end(), uuid_);
    if (self_it == ring.end() || world < 2) {
        // The op COMMENCED group-wide but this member cannot run a ring (a
        // singleton group, or our ring snapshot raced churn). Returning a
        // bare error here used to leave the master's CollectiveOp waiting
        // for a completion that never comes — wedging this tag for every
        // future group member until we happened to disconnect (found by
        // the pcclt-verify model checker). Fail the op through the NORMAL
        // completion handshake instead: complete(aborted=1), consume the
        // exactly-one abort verdict, await done.
        wire::Writer w;
        w.u64(desc.tag);
        w.u8(1);
        drop_prearm();
        if (!master_.send(PacketType::kC2MCollectiveComplete, w.data()))
            return classify_master_loss();
        auto verdict =
            master_.recv_match(PacketType::kM2CCollectiveAbort, tag_pred, 600'000);
        auto done =
            master_.recv_match(PacketType::kM2CCollectiveDone, tag_pred, 600'000);
        if (!verdict || !done) return classify_master_loss();
        return Status::kInternal;
    }
    uint32_t rank = static_cast<uint32_t>(self_it - ring.begin());
    const proto::Uuid &next = ring[(rank + 1) % world];
    const proto::Uuid &prev = ring[(rank + world - 1) % world];

    bool consumed_abort = false;
    bool verdict_aborted = false;
    auto consume_abort = [&](bool no_wait) -> bool {
        auto fr = master_.recv_match(PacketType::kM2CCollectiveAbort, tag_pred,
                                     no_wait ? 0 : 600'000, no_wait);
        if (!fr) return false;
        consumed_abort = true;
        try {
            wire::Reader r(fr->payload);
            r.u64();
            verdict_aborted = r.u8() != 0;
        } catch (...) {}
        return true;
    };

    static const bool dbg_phases = std::getenv("PCCLT_DEBUG_PHASES") != nullptr;
    if (dbg_phases)
        fprintf(stderr, "[op %llu] commenced seq=%llu\n",
                (unsigned long long)desc.tag, (unsigned long long)seq);
    Status st = Status::kOk;
    // The in-place snapshot (abort restore source: all ranks must retry a
    // failed collective from identical inputs) and the optimistic links
    // were PRE-ARMED before the commence wait — op_setup here only
    // re-validates them against the post-commence ring, so the memcpy and
    // the pool lookups are off the critical path entirely.
    uint64_t links_t0 = telemetry::now_ns();
    net::Link tx, rx;
    if (ring == ring0) {
        // a pool rebuild mid-wait leaves a pre-armed link pointing at
        // closed conns — fall through to a fresh lookup in that case
        if (pre_tx.valid() && pre_tx.alive()) tx = pre_tx;
        if (pre_rx.valid() && pre_rx.alive()) rx = pre_rx;
    }
    if (!tx.valid()) tx = tx_link(next);
    // wait for the inbound link in short slices so an abort that already
    // landed (our prev died before establishing) fails the op immediately
    // instead of sitting out the whole mesh-formation timeout
    if (!rx.valid())
        for (auto rx_deadline = std::chrono::steady_clock::now() +
                                std::chrono::seconds(10);;) {
            rx = rx_link(prev, 250);
            if (rx.valid() ||
                std::chrono::steady_clock::now() >= rx_deadline)
                break;
            if (op->abort.load() || consume_abort(true)) break;
        }
    const uint64_t links_t1 = telemetry::now_ns();
    tele_->record_phase(telemetry::Phase::kOpSetup, links_t1 - links_t0);
    if (telemetry::Recorder::inst().on())
        telemetry::Recorder::inst().span("collective", "op_setup", links_t0,
                                         links_t1, "seq", seq);
    if (dbg_phases)
        fprintf(stderr, "[op %llu] links tx=%d rx=%d abort=%d seq=%llu\n",
                (unsigned long long)desc.tag, tx.valid(), rx.valid(),
                int(consumed_abort), (unsigned long long)seq);
    if (!tx.valid() || !rx.valid() || !tx.alive() ||
        (consumed_abort && verdict_aborted) || op->abort.load()) {
        st = (consumed_abort && verdict_aborted) || op->abort.load()
                 ? Status::kAborted
                 : Status::kConnectionLost;
        // Bailing WITHOUT running the ring, but the op commenced group-wide:
        // a peer that made it into the ring may already have raced data for
        // this seq into our tables — same-host CMA descriptors wait for our
        // ack, and its stage-end join blocks until they complete. Retire the
        // op's tag range so those sends get ack-dropped. Without this, an
        // abort delivered to some members before ring entry wedges the
        // member that entered (churn repro: SIGKILL a 4th peer right after
        // the survivors' retry op commences).
        const uint64_t base_tag = seq << 16;
        if (rx.valid()) rx.table().purge_range(base_tag, base_tag + 0x10000);
        if (tx.valid()) tx.table().purge_range(base_tag, base_tag + 0x10000);
    } else {
        reduce::RingCtx ctx;
        ctx.tx = tx;
        ctx.rx = rx;
        ctx.rank = rank;
        ctx.world = world;
        ctx.op_seq = seq;
        ctx.dtype = dtype;
        ctx.op = desc.op;
        ctx.quant = desc.quant;
        ctx.q_dtype = desc.quant_dtype;
        ctx.backup = snapshot.empty() ? nullptr : snapshot.data();
        ctx.tele = tele_.get();
        {
            // receiver wire-stall is charged to the inbound edge: the ring
            // predecessor's canonical endpoint (the netem/telemetry key);
            // the watchdog additionally tracks the OUTBOUND edge (successor)
            MutexLock lk(state_mu_);
            auto it = peers_.find(prev);
            if (it != peers_.end()) {
                net::Addr pa = it->second.ep.ip;
                pa.port = it->second.ep.p2p_port;
                ctx.rx_edge = &tele_->edge(pa.str());
                ctx.rx_endpoint = telemetry::intern(pa.str());
            }
            auto nt = peers_.find(next);
            if (nt != peers_.end()) {
                net::Addr pa = nt->second.ep.ip;
                pa.port = nt->second.ep.p2p_port;
                ctx.tx_edge = &tele_->edge(pa.str());
                ctx.tx_endpoint = telemetry::intern(pa.str());
            }
        }
        // edge watchdog + live failover (docs/05): opt-in via PCCLT_WATCHDOG
        // =1; env re-read per op so tests can flip it at runtime
        if (const char *wde = std::getenv("PCCLT_WATCHDOG");
            wde && wde[0] == '1' && ctx.tx_edge) {
            ctx.wd_factor = env_double("PCCLT_WATCHDOG_FACTOR", 4.0);
            ctx.wd_min_ns = static_cast<uint64_t>(
                env_int("PCCLT_WATCHDOG_MIN_MS", 300)) * 1'000'000ull;
            ctx.wd_hold_ns = static_cast<uint64_t>(
                env_int("PCCLT_WATCHDOG_HOLD_MS", 5000)) * 1'000'000ull;
            proto::Uuid succ = next;
            ctx.fresh_tx_conn = [this, succ] { return fresh_pool_conn(succ); };
            if (world >= 3) {
                ctx.relay_window = [this, succ](uint64_t tag, uint64_t off,
                                                std::span<const uint8_t> p) {
                    return relay_window_via(succ, tag, off, p);
                };
                // end-to-end delivery acks let drain_zombies retire
                // CONFIRMED-stalled direct copies early (docs/05)
                ctx.relay_acked = [this](uint64_t tag, uint64_t off,
                                         size_t len) {
                    return relay_ack_covered(tag, off, len);
                };
            }
        }
        // ---- synthesized schedule bindings (docs/12) ----
        ctx.sched_algo = sched_algo;
        ctx.sched_root = sched_root;
        {
            // per-ring-index link/counter resolvers: tree/butterfly/mesh
            // step programs address peers that are not ring neighbors
            auto ring_sp = std::make_shared<std::vector<proto::Uuid>>(ring);
            ctx.link_to = [this, ring_sp](uint32_t r) -> net::Link {
                return r < ring_sp->size() ? tx_link((*ring_sp)[r])
                                           : net::Link{};
            };
            ctx.link_from = [this, ring_sp](uint32_t r,
                                            int timeout_ms) -> net::Link {
                return r < ring_sp->size() ? rx_link((*ring_sp)[r], timeout_ms)
                                           : net::Link{};
            };
            ctx.edge_of = [this,
                           ring_sp](uint32_t r) -> telemetry::EdgeCounters * {
                if (r >= ring_sp->size()) return nullptr;
                MutexLock lk(state_mu_);
                auto it = peers_.find((*ring_sp)[r]);
                if (it == peers_.end()) return nullptr;
                net::Addr pa = it->second.ep.ip;
                pa.port = it->second.ep.p2p_port;
                return &tele_->edge(pa.str());
            };
        }
        if (sched_algo == sched::Algo::kRelayRing && rank == sched_root &&
            world >= 3) {
            // planned relay: the stamp routes THIS rank's outbound hop
            // through the relay plane for the whole op. Bind the relay
            // lambdas even with the watchdog env off — planned and
            // emergency detours share the machinery, only the accounting
            // differs (sched_relay_planned_bytes vs wd_relays).
            ctx.planned_relay = true;
            if (!ctx.relay_window) {
                proto::Uuid succ = next;
                ctx.relay_window = [this, succ](uint64_t tag, uint64_t off,
                                                std::span<const uint8_t> p) {
                    return relay_window_via(succ, tag, off, p);
                };
                ctx.relay_acked = [this](uint64_t tag, uint64_t off,
                                         size_t len) {
                    return relay_ack_covered(tag, off, len);
                };
            }
        }
        // per-schedule-kind op counters (stats() / /metrics satellite)
        switch (sched_algo) {
        case sched::Algo::kTree:
            tele_->comm.sched_ops_tree.fetch_add(1, std::memory_order_relaxed);
            break;
        case sched::Algo::kButterfly:
            tele_->comm.sched_ops_butterfly.fetch_add(1,
                                                      std::memory_order_relaxed);
            break;
        case sched::Algo::kMesh:
            tele_->comm.sched_ops_mesh.fetch_add(1, std::memory_order_relaxed);
            break;
        case sched::Algo::kRelayRing:
            tele_->comm.sched_ops_relay.fetch_add(1, std::memory_order_relaxed);
            break;
        case sched::Algo::kRing:
        default:
            tele_->comm.sched_ops_ring.fetch_add(1, std::memory_order_relaxed);
            break;
        }
        auto scratch = take_scratch();
        ctx.scratch = &scratch;
        ctx.should_abort = [&]() -> bool {
            if (op->abort.load()) return true;
            if (consume_abort(true) && verdict_aborted) return true;
            return false;
        };
        reduce::Result res;
        // segment order for slotted collectives is by SORTED peer uuid
        // (ring positions reshuffle across topology rounds and would leak
        // that instability into the user-visible layout)
        auto fill_slots = [&] {
            std::vector<proto::Uuid> sorted = ring;
            std::sort(sorted.begin(), sorted.end());
            ctx.slots.resize(world);
            for (uint32_t i = 0; i < world; ++i)
                ctx.slots[i] = static_cast<uint32_t>(
                    std::find(sorted.begin(), sorted.end(), ring[i]) -
                    sorted.begin());
        };
        // recv elements the op will actually write: gather and all-to-all
        // scale with the commence-time world, reduce-scatter with the
        // chunk partition ceiling
        uint64_t recv_need = 0;
        if (desc.op == proto::RedOp::kGather ||
            desc.op == proto::RedOp::kAllToAll)
            recv_need = static_cast<uint64_t>(world) * count;
        else if (desc.op == proto::RedOp::kReduceScatter)
            recv_need = (count + world - 1) / world;
        if (recv_need > desc.recv_capacity) {
            // membership grew between the caller sizing recv and commence:
            // fail OUR leg through the normal complete/abort protocol (a
            // silent overflow or a unilateral bail would wedge the group).
            // Retire the op's tag range so peers' in-flight sends to us get
            // ack-dropped instead of waiting out the conn teardown.
            const uint64_t base_tag = seq << 16;
            rx.table().purge_range(base_tag, base_tag + 0x10000);
            res = reduce::Result::kAborted;
        } else if (desc.op == proto::RedOp::kGather) {
            fill_slots();
            res = reduce::ring_allgather(ctx, send, recv, count);
        } else if (desc.op == proto::RedOp::kReduceScatter) {
            res = reduce::ring_reduce_scatter(ctx, send, recv, count,
                                              &op->info.rs_offset,
                                              &op->info.rs_count);
        } else if (desc.op == proto::RedOp::kBroadcast) {
            // in place in recv; ctx.sched_root is the ring-index root the
            // master converted from the slot-space aux stamp
            res = reduce::run_broadcast(ctx, recv, count);
        } else if (desc.op == proto::RedOp::kAllToAll) {
            fill_slots();
            res = reduce::run_all_to_all(ctx, send, recv, count);
        } else if (sched_algo == sched::Algo::kButterfly) {
            // stamped small-payload schedule; falls back to the ring
            // internally when the commence world is not a power of two
            res = reduce::butterfly_allreduce(ctx, send, recv, count);
        } else {
            res = reduce::ring_allreduce(ctx, send, recv, count);
        }
        give_scratch(std::move(scratch));
        // relay delivery acks are op-scoped (tag ranges are never reused)
        purge_relay_acks(seq << 16, (seq << 16) + 0x10000);
        op->info.tx_bytes = ctx.tx_bytes;
        op->info.rx_bytes = ctx.rx_bytes;
        op->info.world = world;
        if (res == reduce::Result::kAborted) st = Status::kAborted;
        else if (res == reduce::Result::kConnectionLost) st = Status::kConnectionLost;
    }

    // 3. report completion; consume the exactly-one abort verdict; await done
    if (dbg_phases)
        fprintf(stderr, "[op %llu] ring done st=%d seq=%llu\n",
                (unsigned long long)desc.tag, int(st), (unsigned long long)seq);
    bool local_failure = st != Status::kOk;
    if (session_flipped()) return Status::kConnectionLost;
    wire::Writer w;
    w.u64(desc.tag);
    w.u8(local_failure ? 1 : 0);
    if (!master_.send(PacketType::kC2MCollectiveComplete, w.data()))
        return classify_master_loss();
    if (!consumed_abort) {
        if (session_flipped()) return Status::kConnectionLost;
        if (!consume_abort(false)) return classify_master_loss();
    }
    if (dbg_phases)
        fprintf(stderr, "[op %llu] verdict=%d seq=%llu\n",
                (unsigned long long)desc.tag, int(verdict_aborted),
                (unsigned long long)seq);
    if (session_flipped()) return Status::kConnectionLost;
    auto done = master_.recv_match(PacketType::kM2CCollectiveDone, tag_pred, 600'000);
    if (!done) return classify_master_loss();
    if (dbg_phases)
        fprintf(stderr, "[op %llu] done seq=%llu\n", (unsigned long long)desc.tag,
                (unsigned long long)seq);

    if (st == Status::kOk && verdict_aborted) {
        // we finished the ring, but the op was aborted group-wide: restore
        // the input so every rank retries from identical buffers. Gather,
        // reduce-scatter and all-to-all never reduce into a full-vector
        // recv (their recv is segment-sized or freshly rewritten per
        // retry), so only full-vector ops restore — a blanket memcpy of
        // nbytes would overrun a chunk-sized reduce-scatter recv.
        if (desc.op != proto::RedOp::kGather &&
            desc.op != proto::RedOp::kReduceScatter &&
            desc.op != proto::RedOp::kAllToAll)
            memcpy(recv, snapshot.empty() ? send : snapshot.data(), nbytes);
        st = Status::kAborted;
    }
    give_scratch(std::move(snapshot)); // retain the warm pages for the next op
    return st;
}

std::vector<uint8_t> Client::take_scratch() {
    MutexLock lk(scratch_mu_);
    if (scratch_pool_.empty()) return {};
    auto v = std::move(scratch_pool_.back());
    scratch_pool_.pop_back();
    return v;
}

void Client::give_scratch(std::vector<uint8_t> v) {
    if (v.empty()) return;
    // v.size() is what THIS op actually needed; capacity is the historical
    // high-water mark. Retire far-oversized buffers so one giant reduce
    // doesn't pin 8x its chunk size in the pool forever (contents are
    // scratch, so the shrink realloc copies nothing worth keeping)
    if (v.capacity() > 2 * v.size() + (1u << 20))
        v.shrink_to_fit();
    MutexLock lk(scratch_mu_);
    if (scratch_pool_.size() < 8) scratch_pool_.push_back(std::move(v));
}

Status Client::await_reduce(uint64_t tag, ReduceInfo *info) {
    std::unique_ptr<AsyncOp> op;
    {
        MutexLock lk(ops_mu_);
        auto it = ops_.find(tag);
        if (it == ops_.end()) return Status::kInvalid;
        op = std::move(it->second);
        ops_.erase(it);
    }
    Status st = op->result.get();
    if (info) *info = op->info;
    // single accounting point: every collective's final status funnels
    // through here (blocking all_reduce included)
    auto &c = tele_->comm;
    if (st == Status::kOk) c.collectives_ok.fetch_add(1, std::memory_order_relaxed);
    else if (st == Status::kAborted)
        c.collectives_aborted.fetch_add(1, std::memory_order_relaxed);
    else c.collectives_lost.fetch_add(1, std::memory_order_relaxed);
    return st;
}

Status Client::all_reduce(const void *send, void *recv, uint64_t count,
                          proto::DType dtype, const ReduceDesc &desc, ReduceInfo *info) {
    Status st = all_reduce_async(send, recv, count, dtype, desc);
    if (st != Status::kOk) return st;
    return await_reduce(desc.tag, info);
}

// ---------------- shared state ----------------

Status Client::sync_shared_state(uint64_t revision, proto::SyncStrategy strategy,
                                 const std::vector<SharedStateEntry> &entries,
                                 SyncInfo *info) {
    // telemetry wrapper: one accounting + trace point for every exit path
    auto t0 = telemetry::now_ns();
    Status st = sync_shared_state_impl(revision, strategy, entries, info);
    auto &c = tele_->comm;
    if (st == Status::kOk) c.syncs_ok.fetch_add(1, std::memory_order_relaxed);
    else c.syncs_failed.fetch_add(1, std::memory_order_relaxed);
    telemetry::Recorder::inst().span("membership", "shared_state_sync", t0,
                                     telemetry::now_ns(), "revision", revision,
                                     "status", static_cast<uint64_t>(st));
    return st;
}

Status Client::sync_shared_state_impl(uint64_t revision, proto::SyncStrategy strategy,
                                      const std::vector<SharedStateEntry> &entries,
                                      SyncInfo *info) {
    if (!connected_.load()) return Status::kNotConnected;
    // session generation at sync start: a concurrent thread resuming the
    // master session mid-sync orphans this round (sync rounds are not
    // journaled) — bail retryable instead of waiting out the 300 s recvs
    const uint64_t gen0 = session_gen_.load(std::memory_order_acquire);
    auto session_flipped = [&] {
        return session_gen_.load(std::memory_order_acquire) != gen0;
    };

    // open the distribution window (we may be elected distributor; in
    // chunk mode every peer with popular content is a seeder)
    {
        MutexLock lk(dist_mu_);
        dist_open_ = true;
        dist_revision_ = revision;
        dist_entries_.clear();
        dist_servable_.clear();
        for (const auto &e : entries) {
            auto &d = dist_entries_[e.name] = e;
            dist_servable_.insert(e.name);
            if (d.materialize)   // fresh once-flag per sync window
                d.mat_once = std::make_shared<std::once_flag>();
        }
        dist_tx_bytes_ = 0;
    }
    // closing waits out in-flight serve slices: the entries borrow the
    // caller's buffers, which may be freed the moment we return
    auto close_window = [this] { ss_close_window(); };
    // leftover seeder-promotion broadcasts from an earlier round would
    // otherwise rot in the control queue forever (fire-and-forget, no
    // recv_match ever waits for them outside a fetch)
    while (master_.recv_match(PacketType::kM2CSeederUpdate, nullptr, 0, true)) {}

    // hoisted: one env read per sync, so request-time and verify-time hashes
    // always use the same algorithm even if the env changes mid-sync
    const hash::Type hash_type = hash::type_from_env();
    const uint64_t chunk_bytes = ss_chunk_bytes_env();
    proto::SharedStateSyncC2M req;
    req.revision = revision;
    req.strategy = strategy;
    req.chunk_bytes = chunk_bytes;
    for (const auto &e : entries) {
        proto::SharedStateEntryMeta m;
        m.name = e.name;
        m.dtype = e.dtype;
        m.count = e.count;
        m.allow_content_inequality = e.allow_content_inequality ? 1 : 0;
        // precomputed (on-device) hashes take precedence: the caller's
        // accelerator digested its resident bytes and shipped 8 bytes to
        // host, so a clean sync never stages the array (the type must
        // match PCCLT_SS_HASH group-wide — kSimpleTpu is the one a TPU
        // can compute, ops/hashing.py:jax_simplehash_device). Such
        // entries carry no chunk leaves; if dirty they ride the legacy
        // transport. Host entries under the chunk plane offer the chunk
        // hash tree: per-chunk leaves + the root as the entry hash (the
        // leaves subsume the old whole-entry digest, docs/04).
        if (e.allow_content_inequality) {
            m.hash = 0;
        } else if (e.has_precomputed_hash) {
            m.hash = e.precomputed_hash;
        } else if (chunk_bytes) {
            m.chunk_leaves = ssc::leaf_hashes(
                hash_type, e.data, e.count * proto::dtype_size(e.dtype),
                chunk_bytes);
            m.hash = ssc::root_hash(hash_type, m.chunk_leaves);
        } else {
            m.hash = hash::content_hash(hash_type, e.data,
                                        e.count * proto::dtype_size(e.dtype));
        }
        req.entries.push_back(std::move(m));
    }
    if (!master_.send(PacketType::kC2MSharedStateSync, req.encode()) ||
        session_flipped()) {
        close_window();
        return master_.connected() && session_flipped()
                   ? Status::kConnectionLost // resumed mid-sync: round is gone
                   : classify_master_loss();
    }
    auto fr = master_.recv_match(PacketType::kM2CSharedStateSyncResp, nullptr, 300'000);
    if (!fr) {
        close_window();
        return classify_master_loss();
    }
    if (session_flipped()) {
        // a concurrent resume replaced the session while the response was in
        // flight: the round (and any distributor assignment) died with the
        // old master — retry the whole sync on the live session
        close_window();
        return Status::kConnectionLost;
    }
    auto resp = proto::SharedStateSyncResp::decode(fr->payload);
    if (!resp) {
        close_window();
        return Status::kInternal;
    }
    if (resp->failed) {
        // the master could not elect a distributor at the expected revision
        // (e.g. the only advancing peer was kicked, or no peer incremented);
        // the round is over — no dist-done handshake follows. Surface the
        // expected revision so the application can see how far ahead the
        // master believes the group should be.
        close_window();
        if (info) {
            info->tx_bytes = 0;
            info->rx_bytes = 0;
            info->revision = resp->revision;
        }
        return Status::kAborted;
    }

    uint64_t rx_bytes = 0;
    Status st = Status::kOk;
    // ---- transport choice (docs/04): content-addressed multi-source
    // chunk fetch when the master brokered a chunk map and it pays off;
    // the legacy single-distributor stream for tiny states, world=2,
    // leafless (device-hash) keys, or an un-upgraded master ----
    const bool have_map = resp->has_chunk_map && resp->chunk_bytes > 0;
    bool any_leaves = false;
    uint64_t total_dirty = 0;
    if (resp->outdated) {
        for (size_t k = 0; k < resp->outdated_keys.size(); ++k) {
            for (const auto &e : entries)
                if (e.name == resp->outdated_keys[k])
                    total_dirty += e.count * proto::dtype_size(e.dtype);
            if (have_map && k < resp->key_leaves.size() &&
                !resp->key_leaves[k].empty())
                any_leaves = true;
        }
    }
    const bool use_chunks = resp->outdated && have_map && any_leaves &&
                            group_world() > 2 &&
                            total_dirty > resp->chunk_bytes;
    {
        // from here the window serves the CANONICAL revision: clean keys
        // hold popular bytes regardless of the revision we offered
        // (drag-along seeding). Dirty keys leave the servable set until
        // their last chunk verifies; the legacy path still closes the
        // window wholesale (old single-seeder semantics).
        MutexLock lk(dist_mu_);
        if (dist_open_) {
            dist_revision_ = resp->revision;
            if (resp->outdated && !use_chunks) {
                dist_open_ = false;
            } else {
                for (const auto &k : resp->outdated_keys)
                    dist_servable_.erase(k);
            }
        }
    }
    if (resp->outdated) {
        if (use_chunks) {
            std::vector<std::string> legacy_keys;
            for (size_t k = 0; k < resp->outdated_keys.size(); ++k)
                if (k >= resp->key_leaves.size() || resp->key_leaves[k].empty())
                    legacy_keys.push_back(resp->outdated_keys[k]);
            st = ss_fetch_chunked(*resp, req, entries, hash_type, gen0,
                                  &rx_bytes);
            if (st == Status::kOk && !legacy_keys.empty())
                st = ss_fetch_legacy(*resp, legacy_keys, entries, hash_type,
                                     &rx_bytes);
        } else {
            tele_->comm.ss_legacy_syncs.fetch_add(1, std::memory_order_relaxed);
            st = ss_fetch_legacy(*resp, resp->outdated_keys, entries,
                                 hash_type, &rx_bytes);
        }
    }

    if (session_flipped()) {
        close_window();
        return Status::kConnectionLost;
    }
    if (!master_.send(PacketType::kC2MSharedStateDistDone, {})) {
        close_window();
        return classify_master_loss();
    }
    auto done = master_.recv_match(PacketType::kM2CSharedStateDone, nullptr, 300'000);
    close_window();
    if (!done) return classify_master_loss();

    uint64_t done_rev = 0;
    try {
        wire::Reader r(done->payload);
        done_rev = r.u64();
    } catch (...) {}
    // remember the last revision we saw COMPLETE: re-presented on session
    // resume so a restarted master whose journal missed the final append
    // still restores the one-increment invariant (monotonic max — a
    // malformed Done payload must not wipe the counter back to 0)
    uint64_t prev = last_sync_revision_.load(std::memory_order_relaxed);
    while (done_rev > prev &&
           !last_sync_revision_.compare_exchange_weak(prev, done_rev)) {}
    if (info) {
        info->rx_bytes = rx_bytes;
        info->tx_bytes = dist_tx_bytes_.load();
        info->revision = done_rev;
    }
    return st;
}

Status Client::ss_fetch_legacy(const proto::SharedStateSyncResp &resp,
                               const std::vector<std::string> &keys,
                               const std::vector<SharedStateEntry> &entries,
                               hash::Type ht, uint64_t *rx_bytes) {
    if (keys.empty()) return Status::kOk;
    // expected hash (+ chunk leaves when the mask hashed with the chunk
    // tree — the verify must recompute with the SAME scheme) by key name
    std::map<std::string, std::pair<uint64_t, const std::vector<uint64_t> *>>
        expect;
    for (size_t k = 0; k < resp.outdated_keys.size(); ++k) {
        const std::vector<uint64_t> *lv = nullptr;
        if (resp.has_chunk_map && resp.chunk_bytes && k < resp.key_leaves.size() &&
            !resp.key_leaves[k].empty())
            lv = &resp.key_leaves[k];
        if (k < resp.expected_hashes.size())
            expect[resp.outdated_keys[k]] = {resp.expected_hashes[k], lv};
    }
    net::Socket sock;
    net::Addr da = resp.dist_ip;
    da.port = resp.dist_port;
    if (!sock.connect(da, 10'000)) return Status::kConnectionLost;
    wire::Writer w;
    w.u64(resp.revision);
    w.u32(static_cast<uint32_t>(keys.size()));
    for (const auto &k : keys) w.str(k);
    // trailing: our canonical data-plane port, so the distributor's wire
    // emulation + telemetry key this transfer by the same edge as the
    // collectives (netem satellite, docs/04)
    w.u16(p2p_listener_.port());
    Mutex mu;
    if (!net::send_frame(sock, mu, PacketType::kC2SStateRequest, w.data()))
        return Status::kConnectionLost;
    auto hdr = net::recv_frame(sock, 30'000);
    if (!hdr || hdr->type != PacketType::kS2CStateHeader)
        return Status::kConnectionLost;
    telemetry::EdgeCounters *ec = nullptr;
    auto edge = ss_edge_for(resp.dist_ip, resp.dist_p2p_port, resp.dist_port,
                            *tele_, &ec);
    Status st = Status::kOk;
    try {
        wire::Reader r(hdr->payload);
        bool ok = r.u8() != 0;
        uint32_t n = r.u32();
        if (!ok) return Status::kAborted;
        for (uint32_t i = 0; i < n && st == Status::kOk; ++i) {
            std::string name = r.str();
            auto dt = static_cast<proto::DType>(r.u8());
            uint64_t cnt = r.u64();
            const SharedStateEntry *target = nullptr;
            for (const auto &e : entries)
                if (e.name == name) target = &e;
            if (!target || target->dtype != dt || target->count != cnt) {
                st = Status::kContentMismatch;
                break;
            }
            size_t nbytes = cnt * proto::dtype_size(dt);
            // netem ingress on the distributor's canonical edge: delivery
            // delay incl. any scripted chaos outage
            if (edge && edge->delay_enabled())
                std::this_thread::sleep_for(
                    std::chrono::nanoseconds(edge->delivery_delay_ns()));
            // the bulk read is bounded now (the data-phase twin of the
            // 30 s header deadline): a blackholed distributor fails the
            // round with kConnectionLost instead of wedging it until the
            // kernel TCP timeout. Sliced so a slow-but-moving paced wire
            // never trips it — only true no-progress windows do.
            auto t0 = telemetry::now_ns();
            auto *p = static_cast<uint8_t *>(target->data);
            size_t off = 0;
            bool lost = false;
            while (off < nbytes) {
                size_t slice = std::min<size_t>(nbytes - off, 1 << 20);
                if (!sock.recv_all_deadline(p + off, slice, 30'000)) {
                    lost = true;
                    break;
                }
                off += slice;
            }
            if (lost) {
                st = Status::kConnectionLost;
                break;
            }
            tele_->record_phase(telemetry::Phase::kSyncFetch,
                                telemetry::now_ns() - t0);
            *rx_bytes += nbytes;
            ec->rx_sync_bytes.fetch_add(nbytes, std::memory_order_relaxed);
            // the host buffer now holds authoritative content; the caller
            // must push it back to the device (TPU entries)
            if (target->updated) *target->updated = 1;
            // verify against the mask's expected hash, with the mask's
            // hashing scheme: the brokered leaves' chunk grid when
            // present; otherwise reconstruct it — with the chunk plane
            // on, HOST entries were offered as chunk-tree roots even if
            // the response carried no map (un-upgraded master, torn
            // tail), so a plain whole-entry digest would hard-fail every
            // adoption. Group-wide env agreement makes our own
            // chunk_bytes the mask's. Device-hash entries (precomputed,
            // leafless) verify with the whole-entry digest as before.
            auto it = expect.find(name);
            if (it != expect.end()) {
                auto v0 = telemetry::now_ns();
                const uint64_t own_cb = ss_chunk_bytes_env();
                uint64_t h;
                if (it->second.second)
                    h = ssc::root_hash(ht, ssc::leaf_hashes(
                                               ht, target->data, nbytes,
                                               resp.chunk_bytes));
                else if (own_cb && !target->has_precomputed_hash)
                    h = ssc::root_hash(ht, ssc::leaf_hashes(
                                               ht, target->data, nbytes,
                                               own_cb));
                else
                    h = hash::content_hash(ht, target->data, nbytes);
                tele_->record_phase(telemetry::Phase::kSyncVerify,
                                    telemetry::now_ns() - v0);
                if (h != it->second.first) {
                    st = Status::kContentMismatch;
                    tele_->comm.sync_hash_mismatches.fetch_add(
                        1, std::memory_order_relaxed);
                    telemetry::Recorder::inst().instant(
                        "membership", "sync_hash_mismatch", "revision",
                        resp.revision, nullptr, 0, telemetry::intern(name));
                }
            }
        }
    } catch (...) { return Status::kInternal; }
    return st;
}

Status Client::ss_fetch_chunked(const proto::SharedStateSyncResp &resp,
                                const proto::SharedStateSyncC2M &req,
                                const std::vector<SharedStateEntry> &entries,
                                hash::Type ht, uint64_t gen0,
                                uint64_t *rx_bytes) {
    auto t_fetch0 = telemetry::now_ns();
    std::vector<ssc::KeySpec> specs;
    std::vector<size_t> resp_idx;  // spec index -> outdated_keys index
    std::vector<const SharedStateEntry *> targets;
    for (size_t k = 0; k < resp.outdated_keys.size(); ++k) {
        if (k >= resp.key_leaves.size() || resp.key_leaves[k].empty()) continue;
        const auto &name = resp.outdated_keys[k];
        const SharedStateEntry *t = nullptr;
        for (const auto &e : entries)
            if (e.name == name) t = &e;
        if (!t) return Status::kContentMismatch;
        uint64_t nbytes = t->count * proto::dtype_size(t->dtype);
        const auto &lv = resp.key_leaves[k];
        // the brokered map must cohere: leaf count matches the entry's
        // chunk grid and the leaves fold to the expected root — otherwise
        // a torn map would verify chunk-by-chunk into a whole-entry
        // mismatch at the end of an expensive fetch
        if (lv.size() != ssc::chunk_count(nbytes, resp.chunk_bytes) ||
            (k < resp.expected_hashes.size() &&
             ssc::root_hash(ht, lv) != resp.expected_hashes[k])) {
            tele_->comm.sync_hash_mismatches.fetch_add(
                1, std::memory_order_relaxed);
            return Status::kContentMismatch;
        }
        ssc::KeySpec ks;
        ks.name = name;
        ks.nbytes = nbytes;
        ks.dst = static_cast<uint8_t *>(t->data);
        ks.leaves = lv;
        // sparse revision delta (docs/04): the request-time leaves we sent
        // the master describe the bytes ALREADY in this buffer — chunks
        // whose local leaf matches the expected one are born done and
        // never travel (the plan counts them as delta-skipped)
        for (const auto &m : req.entries)
            if (m.name == name) {
                if (m.chunk_leaves.size() == lv.size())
                    ks.local_leaves = m.chunk_leaves;
                break;
            }
        specs.push_back(std::move(ks));
        resp_idx.push_back(k);
        targets.push_back(t);
    }
    if (specs.empty()) return Status::kOk;

    uint64_t rot = 0;
    for (uint8_t b : uuid_) rot = rot * 131 + b;
    auto plan = std::make_shared<ssc::FetchPlan>(
        std::move(specs), resp.chunk_bytes,
        env_double("PCCLT_SS_FETCH_FACTOR", 4.0),
        static_cast<uint64_t>(std::max(1, env_int("PCCLT_SS_FETCH_MIN_MS", 500))) *
            1'000'000ull,
        static_cast<uint32_t>(std::max(1, env_int("PCCLT_SS_FETCH_RANGE", 8))),
        rot);

    std::vector<std::thread> workers;
    // one worker per seeder PEER (uuid-keyed): the transport is the pooled
    // mesh conns, so there are no per-worker sockets to manage — a worker
    // parked mid-range waits in bounded slices and re-checks finished(),
    // so the dispatcher never needs an fd sweep to unblock it
    std::map<std::string, uint32_t> started;  // uuid -> seeder index
    auto spawn_for = [&](const proto::SeederRec &rec) -> int {
        if (rec.uuid == uuid_) return -1;  // self-seeding is a no-op
        {
            // not in our mesh: unusable as a pooled source (the master's
            // directory and our membership can skew for a beat mid-churn)
            MutexLock lk(state_mu_);
            if (!peers_.count(rec.uuid)) return -1;
        }
        net::Addr canon = rec.ip;
        canon.port = rec.p2p_port ? rec.p2p_port : rec.ss_port;
        std::string ukey = proto::uuid_str(rec.uuid);
        uint32_t sidx = plan->add_seeder(canon.str());
        if (!started.count(ukey)) {
            started[ukey] = sidx;
            workers.emplace_back(
                [this, plan, sidx, rec, rev = resp.revision, ht] {
                    ss_fetch_worker(plan, sidx, rec, rev, ht);
                });
        }
        return static_cast<int>(sidx);
    };
    for (uint32_t ki = 0; ki < plan->key_count(); ++ki) {
        size_t k = resp_idx[ki];
        if (k >= resp.key_seeders.size()) continue;
        for (uint32_t si : resp.key_seeders[k]) {
            int sidx = spawn_for(resp.seeders[si]);
            if (sidx >= 0) plan->add_key_seeder(ki, static_cast<uint32_t>(sidx));
        }
    }
    plan->check_liveness();  // a key with no viable source fails out now

    auto key_index_of = [&](const std::string &name) -> int {
        for (uint32_t ki = 0; ki < plan->key_count(); ++ki)
            if (plan->key_spec(ki).name == name) return static_cast<int>(ki);
        return -1;
    };
    auto session_flipped = [&] {
        return session_gen_.load(std::memory_order_acquire) != gen0;
    };
    auto drain_completions = [&] {
        for (uint32_t ki : plan->take_completed_keys()) {
            const auto &name = plan->key_spec(ki).name;
            {
                // mid-round seeder promotion: our bytes for this key are
                // canonical now — serve them for the rest of the round
                MutexLock lk(dist_mu_);
                if (dist_open_) dist_servable_.insert(name);
            }
            if (targets[ki]->updated) *targets[ki]->updated = 1;
            proto::SyncKeyDoneC2M kd;
            kd.revision = resp.revision;
            kd.key = name;
            // best-effort fire-and-forget: a dead master fails the sync
            // at the dist-done handshake, not mid-fetch
            master_.send(PacketType::kC2MSyncKeyDone, kd.encode());
            tele_->comm.ss_seeder_promotions.fetch_add(
                1, std::memory_order_relaxed);
            telemetry::Recorder::inst().instant(
                "membership", "sync_key_seeding", "revision", resp.revision,
                nullptr, 0, telemetry::intern(name));
        }
    };

    while (!plan->finished()) {
        plan->wait_event(50);
        plan->expire_overdue(telemetry::now_ns());
        plan->check_liveness();
        drain_completions();
        // fold other peers' mid-round promotions into the source set
        while (auto fr = master_.recv_match(PacketType::kM2CSeederUpdate,
                                            nullptr, 0, true)) {
            auto up = proto::SeederUpdateM2C::decode(fr->payload);
            if (!up || up->revision != resp.revision) continue;
            int ki = key_index_of(up->key);
            if (ki < 0) continue;
            int sidx = spawn_for(up->seeder);
            if (sidx >= 0)
                plan->add_key_seeder(static_cast<uint32_t>(ki),
                                     static_cast<uint32_t>(sidx));
        }
        if (session_flipped()) plan->abort();
    }
    for (auto &t : workers)
        if (t.joinable()) t.join();
    drain_completions();

    auto ps = plan->stats();
    auto &c = tele_->comm;
    auto add = [](std::atomic<uint64_t> &a, uint64_t v) {
        if (v) a.fetch_add(v, std::memory_order_relaxed);
    };
    add(c.ss_chunks_fetched, ps.chunks_fetched);
    add(c.ss_chunks_resourced, ps.chunks_resourced);
    add(c.ss_chunks_dup, ps.chunks_dup);
    add(c.ss_chunk_bytes_fetched, ps.bytes_fetched);
    add(c.ss_chunk_bytes_resourced, ps.bytes_resourced);
    add(c.ss_chunk_bytes_dup, ps.bytes_dup);
    add(c.ss_chunks_delta_skipped, ps.chunks_delta_skipped);
    add(c.ss_chunk_bytes_delta_skipped, ps.bytes_delta_skipped);
    *rx_bytes += ps.unique_bytes;
    telemetry::Recorder::inst().span(
        "membership", "sync_fetch", t_fetch0, telemetry::now_ns(), "bytes",
        ps.unique_bytes, "resourced", ps.chunks_resourced);
    if (plan->complete_ok()) return Status::kOk;
    if (session_flipped()) return Status::kConnectionLost;
    return plan->saw_hash_mismatch() ? Status::kContentMismatch
                                     : Status::kConnectionLost;
}

// Pooled fetch worker (docs/04 unified transport): ranges are requested
// over the mesh conns as kChunkReq frames and the payload arrives as
// striped kData windows in this peer's rx SinkTable — the same sink a
// relay detour (kRelayDeliver, origin = the seeder) feeds, so a seeder
// whose direct edge degrades mid-range still lands its bytes here via a
// third peer, deduped and charged to the canonical edge. The worker never
// owns a socket: waits are bounded slices that re-check finished(), so
// the dispatcher join needs no fd sweep.
void Client::ss_fetch_worker(const std::shared_ptr<ssc::FetchPlan> &plan,
                             uint32_t sidx, proto::SeederRec rec,
                             uint64_t revision, hash::Type ht) {
    telemetry::EdgeCounters *ec = nullptr;
    std::string canon_key;
    // resolved at FETCH time, so a chaos schedule injected after the mesh
    // dialed (pccltNetemInject creates a per-endpoint edge that conns
    // holding the process default never see) is still honored below
    auto edge =
        ss_edge_for(rec.ip, rec.p2p_port, rec.ss_port, *tele_, &ec, &canon_key);
    int fails = 0;     // consecutive transport failures against this seeder
    int refusals = 0;  // consecutive status-1 "window not ready" answers
    std::vector<uint8_t> scratch;
    auto retire = [&] {
        plan->seeder_gone(sidx);
        tele_->comm.ss_seeders_lost.fetch_add(1, std::memory_order_relaxed);
        telemetry::Recorder::inst().instant(
            "membership", "sync_seeder_lost", "revision", revision, nullptr, 0,
            telemetry::intern(canon_key));
    };
    // the seeder's inbound sink table: payload kData frames land here, and
    // so do relay detours (kRelayDeliver resolves origin = the seeder)
    std::shared_ptr<net::SinkTable> rx_table;
    {
        MutexLock lk(state_mu_);
        auto it = peers_.find(rec.uuid);
        if (it != peers_.end()) {
            if (!it->second.rx_table)
                it->second.rx_table = std::make_shared<net::SinkTable>();
            rx_table = it->second.rx_table;
        }
    }
    if (!rx_table) {
        retire();
        return;
    }
    // dead-peer detection (the pooled analogue of a refused dial / broken
    // recv): a SIGKILLed seeder's conns RST and go !alive() within a beat,
    // while a blackholed edge keeps its conns — so this trips on real
    // death, not chaos, and the wait loops below bail promptly instead of
    // parking a whole budget against a corpse
    auto peer_alive = [&] {
        MutexLock lk(state_mu_);
        auto it = peers_.find(rec.uuid);
        if (it == peers_.end()) return false;
        for (const auto &c : it->second.tx)
            if (c && c->alive()) return true;
        for (const auto &c : it->second.rx)
            if (c && c->alive()) return true;
        return false;
    };
    while (!plan->finished() && plan->seeder_alive(sidx)) {
        auto take = plan->take(sidx, telemetry::now_ns());
        if (!take) {
            plan->wait_event(25);
            continue;
        }
        const auto &ks = plan->key_spec(take->key);
        const uint64_t cb = plan->chunk_bytes();
        auto fail_range = [&](uint32_t from, bool hash_bad = false) {
            for (uint32_t i = from; i < take->count; ++i)
                plan->failed(take->key, take->first + i, sidx,
                             hash_bad && i == from);
        };
        // scripted outage on the canonical sync edge: park HERE in bounded
        // slices (range held — the dispatcher's deadline re-sources the
        // chunks from another seeder, the per-chunk failover of docs/04)
        // instead of racing requests into a blackhole. The park ends at
        // the outage's ABSOLUTE end, so when the conns model the same
        // armed edge nothing is double-charged.
        if (edge) {
            while (!plan->finished() && plan->seeder_alive(sidx) &&
                   edge->chaos_at().outage)
                std::this_thread::sleep_for(std::chrono::milliseconds(50));
            if (plan->finished() || !plan->seeder_alive(sidx)) break;
        }
        uint64_t payload = 0;
        for (uint32_t i = 0; i < take->count; ++i)
            payload += ssc::chunk_len(ks.nbytes, cb, take->first + i);
        // register the sink BEFORE the request leaves: a fast seeder's
        // first kData frame must find the sink, not the queued-frame path
        const uint64_t tag =
            (1ull << 63) |
            chunk_tag_seq_.fetch_add(1, std::memory_order_relaxed);
        scratch.resize(payload);
        rx_table->register_sink(tag, scratch.data(), payload);
        auto drop_sink = [&] {
            rx_table->unregister_sink(tag);
            // retire the tag: stripes/detours straggling in after a failed
            // or finished range are dropped instead of queueing forever
            rx_table->purge_range(tag, tag + 1);
        };
        // request rides OUR tx pool toward the seeder: [16B own uuid][spec]
        std::shared_ptr<net::MultiplexConn> out;
        {
            MutexLock lk(state_mu_);
            auto it = peers_.find(rec.uuid);
            if (it != peers_.end())
                for (const auto &c : it->second.tx)
                    if (c && c->alive()) { out = c; break; }
        }
        if (!out) {
            drop_sink();
            fail_range(0);
            retire();
            break;
        }
        ssc::ChunkReqSpec rq;
        rq.revision = revision;
        rq.key = ks.name;
        rq.chunk_bytes = cb;
        rq.first = take->first;
        rq.count = take->count;
        auto spec = rq.encode(/*with_p2p=*/false);
        std::vector<uint8_t> pl(16 + spec.size());
        memcpy(pl.data(), uuid_.data(), 16);
        memcpy(pl.data() + 16, spec.data(), spec.size());
        out->send_owned(net::MultiplexConn::kChunkReq, tag, 0, std::move(pl));
        // header: [u8 status][BE u64 payload len] on the queued-frame path
        // (same tag, kChunkHdr) — bounded slices so a finished plan
        // reclaims this worker promptly even mid-outage
        const uint64_t hdr_budget_ns = std::min<uint64_t>(
            plan->chunk_budget_ns() + 1'000'000'000ull, 60'000'000'000ull);
        const uint64_t t_hdr = telemetry::now_ns();
        std::optional<std::vector<uint8_t>> hdr;
        while (true) {
            hdr = rx_table->recv_queued(tag, 50);
            if (hdr || plan->finished() || !peer_alive() ||
                telemetry::now_ns() - t_hdr > hdr_budget_ns)
                break;
        }
        uint8_t status = 255;
        if (hdr) {
            try {
                wire::Reader r(*hdr);
                status = r.u8();
                (void)r.u64();  // payload length (implied by the chunk grid)
            } catch (...) { status = 255; }
        }
        if (plan->finished()) {
            drop_sink();
            break;
        }
        if (status == 255) {  // no (or garbled) header inside the budget
            drop_sink();
            fail_range(0);
            if (!peer_alive() || ++fails >= 2) {
                retire();
                break;
            }
            continue;
        }
        if (status == 1) {
            // serve window not ready (peer still processing its response
            // / key not yet complete there): back off, don't blacklist —
            // but BOUNDED. A window that closed for good (the seeder's
            // own sync errored out while its process lives) would
            // otherwise requeue/backoff forever with nothing ever
            // marking the seeder tried, and the plan could neither fail
            // out nor finish. ~20 refusals ≈ 2 s of backoff is far past
            // any response-processing race; after that the refusal is a
            // real failure and the normal retire ladder applies.
            drop_sink();
            if (++refusals >= 20) {
                fail_range(0);
                retire();
                break;
            }
            for (uint32_t i = 0; i < take->count; ++i)
                plan->requeue(take->key, take->first + i, sidx);
            plan->seeder_backoff(sidx, telemetry::now_ns() + 100'000'000ull);
            continue;
        }
        if (status != 0) {
            drop_sink();
            fail_range(0);
            if (++fails >= 2) {
                retire();
                break;
            }
            continue;
        }
        // payload: striped kData windows (direct, re-issued, or relay-
        // detoured — the sink dedupes) filling [0, payload). Verify chunk
        // by chunk as the contiguous prefix grows; a blackholed sync edge
        // parks HERE in bounded waits while the dispatcher's deadline
        // re-sources the chunks from a different seeder (docs/04) and the
        // SEEDER's watchdog climbs its ladder to route around the edge.
        uint64_t need = 0;
        bool range_ok = true, hash_bad = false;
        uint32_t failed_at = 0;
        for (uint32_t i = 0; i < take->count; ++i) {
            uint32_t idx = take->first + i;
            uint64_t len = ssc::chunk_len(ks.nbytes, cb, idx);
            const uint64_t budget_ns = std::min<uint64_t>(
                plan->chunk_budget_ns() + 100'000'000ull, 60'000'000'000ull);
            const uint64_t t0 = telemetry::now_ns();
            size_t have = 0;
            while (true) {
                have = rx_table->wait_filled(tag, need + len, 50);
                if (have >= need + len || plan->finished() ||
                    !peer_alive() || telemetry::now_ns() - t0 > budget_ns)
                    break;
            }
            if (have < need + len) {
                range_ok = false;
                failed_at = i;
                break;
            }
            uint64_t t1 = telemetry::now_ns();
            tele_->record_phase(telemetry::Phase::kSyncFetch, t1 - t0);
            uint64_t h = hash::content_hash(ht, scratch.data() + need, len);
            tele_->record_phase(telemetry::Phase::kSyncVerify,
                                telemetry::now_ns() - t1);
            if (h != ks.leaves[idx]) {
                // content-addressing is the defense: a corrupt source
                // costs one re-source, never a poisoned buffer
                tele_->comm.sync_hash_mismatches.fetch_add(
                    1, std::memory_order_relaxed);
                telemetry::Recorder::inst().instant(
                    "membership", "sync_chunk_mismatch", "revision", revision,
                    "chunk", idx, telemetry::intern(ks.name));
                range_ok = false;
                hash_bad = true;
                failed_at = i;
                break;
            }
            ec->rx_sync_bytes.fetch_add(len, std::memory_order_relaxed);
            if (uint8_t *dst = plan->claim(take->key, idx)) {
                memcpy(dst, scratch.data() + need, len);
                plan->published(take->key, idx, sidx, take->gens[i],
                                telemetry::now_ns());
            } else {
                plan->duplicate(take->key, idx, sidx, take->gens[i]);
            }
            fails = 0;
            refusals = 0;
            need += len;
        }
        drop_sink();
        if (!range_ok) {
            fail_range(failed_at, hash_bad);
            if (!hash_bad && (!peer_alive() || ++fails >= 2)) {
                retire();
                break;
            }
        }
    }
}

// ---------------- attributes ----------------

uint32_t Client::global_world() const {
    MutexLock lk(state_mu_);
    return static_cast<uint32_t>(peers_.size() + 1);
}

uint32_t Client::group_world() const {
    MutexLock lk(state_mu_);
    return static_cast<uint32_t>(ring_.size());
}

uint32_t Client::num_groups() const {
    MutexLock lk(state_mu_);
    std::set<uint32_t> g{cfg_.peer_group};
    for (const auto &[_, pc] : peers_) g.insert(pc.ep.peer_group);
    return static_cast<uint32_t>(g.size());
}

uint32_t Client::largest_group() const {
    MutexLock lk(state_mu_);
    std::map<uint32_t, uint32_t> counts;
    ++counts[cfg_.peer_group];
    for (const auto &[_, pc] : peers_) ++counts[pc.ep.peer_group];
    uint32_t best = 0;
    for (auto &[_, c] : counts) best = std::max(best, c);
    return best;
}

} // namespace pcclt::client
