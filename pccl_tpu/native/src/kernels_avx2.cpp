// AVX2 bf16 reduction kernels, compiled as their own TU with -mavx2 and
// gated at runtime by __builtin_cpu_supports — the rest of the library
// stays baseline x86-64. bf16 is the TPU-native gradient dtype, so the
// DCN all-reduce hot path for TPU training is bf16 sums: the generic path
// converts element-by-element through scalar helpers (kernels.cpp loop16),
// which is an order of magnitude below memory bandwidth.
//
// Reference parity note: the reference keeps arch-specific kernels as
// separate static libs selected at configure time (its CRC32 SSE4.2/PCLMUL
// variants); pcclt uses one TU + runtime dispatch instead, which also
// covers heterogeneous fleets with a single binary.
//
// Conversion scheme (matches the scalar helpers bit-for-bit):
//   bf16 -> f32: u32(b) << 16, reinterpret as float
//   f32 -> bf16: round-to-nearest-even on bit 16: (u + 0x7FFF + ((u>>16)&1)) >> 16
#include <cstddef>
#include <cstdint>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#define PCCLT_X86 1
#endif

namespace pcclt::kernels::avx2 {

bool available() {
#if defined(PCCLT_X86) && defined(__GNUC__)
    return __builtin_cpu_supports("avx2");
#else
    return false;
#endif
}

#if defined(PCCLT_X86)

namespace {

// widen the low 8 bf16 lanes of `v` to 8 f32
inline __m256 bf16lo_to_f32(__m128i v) {
    __m256i w = _mm256_cvtepu16_epi32(v);
    return _mm256_castsi256_ps(_mm256_slli_epi32(w, 16));
}

// round-to-nearest-even f32 -> bf16 for 8 lanes; result in the low 8 u16
// of the return (packed, lane-crossing fixed up)
inline __m128i f32_to_bf16_8(__m256 f) {
    __m256i u = _mm256_castps_si256(f);
    __m256i lsb = _mm256_and_si256(_mm256_srli_epi32(u, 16), _mm256_set1_epi32(1));
    __m256i bias = _mm256_add_epi32(_mm256_set1_epi32(0x7FFF), lsb);
    __m256i r = _mm256_srli_epi32(_mm256_add_epi32(u, bias), 16);
    // pack 8x u32 (values fit u16) -> 8x u16; packus works per 128-bit lane,
    // so permute the two halves back into order afterwards
    __m256i packed = _mm256_packus_epi32(r, _mm256_setzero_si256());
    packed = _mm256_permute4x64_epi64(packed, _MM_SHUFFLE(3, 1, 2, 0));
    return _mm256_castsi256_si128(packed);
}

} // namespace

void bf16_add3(uint16_t *dst, const uint16_t *a, const uint16_t *b, size_t n) {
    size_t i = 0;
    for (; i + 8 <= n; i += 8) {
        __m128i va = _mm_loadu_si128(reinterpret_cast<const __m128i *>(a + i));
        __m128i vb = _mm_loadu_si128(reinterpret_cast<const __m128i *>(b + i));
        __m256 s = _mm256_add_ps(bf16lo_to_f32(va), bf16lo_to_f32(vb));
        _mm_storeu_si128(reinterpret_cast<__m128i *>(dst + i), f32_to_bf16_8(s));
    }
    for (; i < n; ++i) {
        // scalar tail, identical rounding
        uint32_t ua = static_cast<uint32_t>(a[i]) << 16;
        uint32_t ub = static_cast<uint32_t>(b[i]) << 16;
        float fa, fb;
        __builtin_memcpy(&fa, &ua, 4);
        __builtin_memcpy(&fb, &ub, 4);
        float fr = fa + fb;
        uint32_t ur;
        __builtin_memcpy(&ur, &fr, 4);
        dst[i] = static_cast<uint16_t>((ur + 0x7FFF + ((ur >> 16) & 1)) >> 16);
    }
}

void bf16_add2(uint16_t *dst, const uint16_t *src, size_t n) {
    bf16_add3(dst, dst, src, n);
}

#else

void bf16_add3(uint16_t *, const uint16_t *, const uint16_t *, size_t) {}
void bf16_add2(uint16_t *, const uint16_t *, size_t) {}

#endif

} // namespace pcclt::kernels::avx2
