#include "annotations.hpp"
#include "log.hpp"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>
#include <thread>

namespace pcclt::log {

namespace {

Level parse_env() {
    const char *e = std::getenv("PCCLT_LOG_LEVEL");
    if (!e) return Level::kInfo;
    if (!strcasecmp(e, "TRACE")) return Level::kTrace;
    if (!strcasecmp(e, "DEBUG")) return Level::kDebug;
    if (!strcasecmp(e, "INFO")) return Level::kInfo;
    if (!strcasecmp(e, "WARN")) return Level::kWarn;
    if (!strcasecmp(e, "ERROR")) return Level::kError;
    if (!strcasecmp(e, "FATAL")) return Level::kFatal;
    return Level::kInfo;
}

Level g_threshold = parse_env();
Mutex g_mu; // lock-rank: io (serializes stderr)

const char *name(Level lv) {
    switch (lv) {
    case Level::kTrace: return "TRACE";
    case Level::kDebug: return "DEBUG";
    case Level::kInfo: return "INFO";
    case Level::kWarn: return "WARN";
    case Level::kError: return "ERROR";
    case Level::kFatal: return "FATAL";
    }
    return "?";
}

} // namespace

Level threshold() { return g_threshold; }
void set_threshold(Level lv) { g_threshold = lv; }

void write(Level lv, const std::string &msg) {
    if (lv < g_threshold) return;
    time_t t = time(nullptr);
    struct tm tmv;
    localtime_r(&t, &tmv);
    char ts[16];
    strftime(ts, sizeof ts, "%H:%M:%S", &tmv);
    auto tid = std::hash<std::thread::id>{}(std::this_thread::get_id()) % 100000;
    MutexLock lk(g_mu);
    fprintf(stderr, "[%s][%5s][cc:%zu] %s\n", ts, name(lv), tid, msg.c_str());
    if (lv == Level::kFatal) {
        fflush(stderr);
        abort();
    }
}

} // namespace pcclt::log
