// Futex-based thread parking.
//
// Reference parity: third_party threadpark (tpark_handle_t create/beginPark/
// wait/wake, used by the reference's multiplexed socket TX thread and
// send-completion handshakes, /root/reference/tinysockets/src/
// multiplexed_socket.cpp:377-384,555-598). Redesigned as a single 32-bit
// futex word: waiters snapshot the word and sleep until it changes; wakers
// bump it and wake. No condition variable, no mutex — one atomic op per
// wake on the hot path.
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <ctime>

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace pcclt::park {

// A 32-bit event counter threads can sleep on. Typical use:
//   uint32_t v = ev.epoch();
//   ... re-check predicate ...
//   ev.wait(v, timeout_ms);   // sleeps only if nothing signalled since
// and on the producer side: ev.signal() after publishing.
class Event {
public:
    uint32_t epoch() const { return word_.load(std::memory_order_acquire); }

    // Wake all waiters (and bump the epoch so racing waiters don't sleep).
    // The wake syscall is skipped when no thread is parked: the epoch bump
    // is sequenced before the waiter-count load, and a waiter registers
    // BEFORE its kernel-side word re-check, so a racing waiter either sees
    // the bumped epoch (and never sleeps) or is counted (and gets woken).
    // This makes multi-event signalling (sharded tables) ~one atomic each.
    void signal() {
        word_.fetch_add(1, std::memory_order_seq_cst);
        if (waiters_.load(std::memory_order_seq_cst) != 0)
            syscall(SYS_futex, reinterpret_cast<uint32_t *>(&word_),
                    FUTEX_WAKE_PRIVATE, INT32_MAX, nullptr, nullptr, 0);
    }

    // Sleep until the epoch moves past `seen` or timeout_ms elapses
    // (timeout_ms < 0 = no timeout). Returns false on timeout.
    bool wait(uint32_t seen, int timeout_ms = -1) const {
        if (word_.load(std::memory_order_acquire) != seen) return true;
        struct timespec ts, *tsp = nullptr;
        if (timeout_ms >= 0) {
            ts.tv_sec = timeout_ms / 1000;
            ts.tv_nsec = static_cast<long>(timeout_ms % 1000) * 1'000'000L;
            tsp = &ts;
        }
        waiters_.fetch_add(1, std::memory_order_seq_cst);
        long rc = syscall(SYS_futex,
                          reinterpret_cast<uint32_t *>(
                              const_cast<std::atomic<uint32_t> *>(&word_)),
                          FUTEX_WAIT_PRIVATE, seen, tsp, nullptr, 0);
        waiters_.fetch_sub(1, std::memory_order_seq_cst);
        (void)rc; // EAGAIN (word moved) and EINTR both mean "re-check"
        return word_.load(std::memory_order_acquire) != seen;
    }

private:
    std::atomic<uint32_t> word_{0};
    mutable std::atomic<uint32_t> waiters_{0};
};

// Wait until `pred()` holds or `timeout_ms` elapses (timeout_ms < 0 = no
// timeout). The epoch is snapshotted BEFORE each predicate check so a signal
// between check and sleep is never lost. `pred` is responsible for its own
// locking. Returns the final predicate value.
template <typename Pred>
bool wait_event(const Event &ev, int timeout_ms, Pred &&pred) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    while (true) {
        uint32_t e = ev.epoch();
        if (pred()) return true;
        int slice = 1000;
        if (timeout_ms >= 0) {
            auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                            deadline - std::chrono::steady_clock::now())
                            .count();
            if (left <= 0) return pred();
            slice = static_cast<int>(left < 1000 ? left : 1000);
        }
        ev.wait(e, slice);
    }
}

} // namespace pcclt::park
