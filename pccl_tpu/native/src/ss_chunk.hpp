// Content-addressed shared-state chunk plane (docs/04).
//
// SyncSharedState used to fan the whole state out from ONE elected
// distributor over ONE raw TCP connection — a single preempted VM mid-sync
// failed the round for everyone. This module is the churn-proof core that
// replaces it: entries are split into fixed-size chunks under a per-entry
// hash tree (leaf = content hash of one chunk, root = content hash over
// the leaf array — the root subsumes the old whole-entry drift hash), and
// outdated peers fetch chunks from MANY seeders in parallel, verifying
// each chunk on arrival and re-sourcing slow/dead fetches from a
// different seeder (the PR-10 watchdog ladder, applied to the state
// plane: EWMA deadline -> re-issue -> alternate source).
//
// Two deliberately separable pieces:
//   * the hash tree (chunk_count / leaf_hashes / root_hash) — pure
//     functions over buffers;
//   * FetchPlan — the multi-source assignment/verify/retry state machine,
//     time passed in explicitly so the selftest can drive deadlines
//     deterministically. client.cpp owns the sockets and threads; the
//     plan owns WHICH chunk goes to WHICH seeder and the conservation
//     accounting (fetched + re-sourced - dup == unique chunk bytes,
//     asserted byte-exact by the swarm bench).
#pragma once

#include <cstdint>
#include <map>
#include <optional>
#include <set>
#include <string>
#include <vector>

#include "annotations.hpp"
#include "hash.hpp"

namespace pcclt::ssc {

// chunks covering nbytes (last chunk may be short); 0 for an empty entry
uint32_t chunk_count(uint64_t nbytes, uint64_t chunk_bytes);

// byte length of chunk `idx` of an nbytes-long entry
uint64_t chunk_len(uint64_t nbytes, uint64_t chunk_bytes, uint32_t idx);

// one content hash per chunk, in chunk order
std::vector<uint64_t> leaf_hashes(hash::Type t, const void *data,
                                  uint64_t nbytes, uint64_t chunk_bytes);

// tree root: content hash over the big-endian leaf array. This IS the
// entry hash offered to the master when the chunk plane is on — drift
// detection was already hash-based, so the leaves subsume the old
// whole-entry digest (device-precomputed hashes keep their own digest and
// simply carry no leaves; their dirty keys take the legacy path).
uint64_t root_hash(hash::Type t, const std::vector<uint64_t> &leaves);

// ----------------------------------------------------------- request wire

// One chunk-range request as it crosses the wire (legacy kSSChunkReq
// socket payload and the pooled kChunkReq spec share this grammar):
//   revision u64, key str, chunk_bytes u64, first u32, count u32,
//   then optionally the requester's p2p port (u16) — only the legacy
//   socket path sends it (the pooled path already knows the route back).
// Both serve paths in client.cpp decode through here, and pcclt_fuzz
// drives decode() directly with adversarial bytes.
struct ChunkReqSpec {
    uint64_t revision = 0;
    std::string key;
    uint64_t chunk_bytes = 0;
    uint32_t first = 0, count = 0;
    uint16_t req_p2p = 0;              // 0 = absent (pooled requests)

    std::vector<uint8_t> encode(bool with_p2p) const;
    static std::optional<ChunkReqSpec> decode(
        const std::vector<uint8_t> &b);
};

// ------------------------------------------------------------- fetch plan

// One outdated key the plan must fill.
struct KeySpec {
    std::string name;
    uint64_t nbytes = 0;
    uint8_t *dst = nullptr;            // receive buffer (entry host memory)
    std::vector<uint64_t> leaves;      // expected per-chunk hashes
    // sparse revision delta (docs/04): the fetcher's CURRENT per-chunk
    // hashes over dst, computed at request time. Where local == expected
    // the chunk's bytes are already canonical — the plan marks it done at
    // construction (delta-skipped) and no seeder is ever asked for it. A
    // drag-along peer one revision behind thus fetches only what changed.
    // Empty = no local baseline (cold joiner / size change): fetch all.
    std::vector<uint64_t> local_leaves;
};

// Cumulative plan counters (chunk granularity + bytes). Every verified
// arrival lands in exactly one of fetched/resourced (by assignment
// generation: first assignment vs a re-sourced one); arrivals for an
// already-delivered chunk ALSO land in dup. Chunks proven locally
// canonical at construction (sparse delta) are counted in delta_skipped
// and never assigned. Hence the conservation identities at completion:
//   fetched_bytes + resourced_bytes - dup_bytes == unique_bytes
//   unique_bytes + bytes_delta_skipped == sum(chunk bytes)
struct PlanStats {
    uint64_t chunks_fetched = 0, chunks_resourced = 0, chunks_dup = 0;
    uint64_t bytes_fetched = 0, bytes_resourced = 0, bytes_dup = 0;
    uint64_t hash_mismatches = 0;
    uint64_t seeders_lost = 0;
    uint64_t unique_bytes = 0;         // delivered into buffers (verified)
    // sparse revision delta: chunks whose local bytes already matched the
    // expected leaf at plan construction (never fetched)
    uint64_t chunks_delta_skipped = 0, bytes_delta_skipped = 0;
};

// Multi-source fetch state machine. Thread-safe: workers (one per seeder
// connection) call take/claim/published/duplicate/failed; the dispatcher
// calls expire_overdue/take_completed_keys/add_seeder. All waits go
// through wait_event so a worker never spins.
class FetchPlan {
public:
    // factor/min_ns parameterize the per-chunk deadline envelope:
    //   budget = max(min_ns, factor * EWMA(chunk fetch ns))
    // max_range caps chunks per assignment (one request serves a
    // contiguous run); rot_seed staggers the key order per peer so a
    // swarm of cold joiners completes DIFFERENT keys first and the
    // mid-round promotions multiply the seeder set.
    FetchPlan(std::vector<KeySpec> keys, uint64_t chunk_bytes, double factor,
              uint64_t min_ns, uint32_t max_range, uint64_t rot_seed);

    // Register a seeder (keyed by its canonical endpoint string). Returns
    // its index; re-adding an endpoint returns the existing index (a
    // retired seeder is NOT revived — a dead endpoint stays dead).
    uint32_t add_seeder(const std::string &endpoint);
    // Mark seeder eligible to serve `key` (per-key seeder sets from the
    // master's chunk map / a mid-round promotion).
    void add_key_seeder(uint32_t key, uint32_t seeder);
    // Seeder died (dial/socket failure): its inflight chunks return to
    // pending for other seeders.
    void seeder_gone(uint32_t seeder);
    // Transient refusal (serve window not ready yet): back the seeder off
    // without retiring it or marking chunks tried.
    void seeder_backoff(uint32_t seeder, uint64_t until_ns);
    bool seeder_alive(uint32_t seeder) const;
    std::string seeder_endpoint(uint32_t seeder) const;
    size_t seeder_count() const;

    struct Take {
        uint32_t key = 0;
        uint32_t first = 0;                // chunk index within the key
        uint32_t count = 0;
        std::vector<uint32_t> gens;        // per-chunk assignment ordinal
    };
    // Next contiguous run of pending chunks this seeder may serve; nullopt
    // when nothing is currently assignable to it. Chunks are stamped
    // inflight with staggered deadlines (chunk i of the run gets
    // (i+1) * budget).
    std::optional<Take> take(uint32_t seeder, uint64_t now_ns);

    // Verified-arrival protocol (tsan-safe two-phase write):
    //   dst = claim(key, idx); if dst: memcpy; published(...);
    //   else duplicate(...)  [chunk already delivered or being written]
    // A claim the caller cannot complete (socket died mid-copy cannot
    // happen — bytes are already local — but keep abandon for symmetry).
    uint8_t *claim(uint32_t key, uint32_t idx);
    void abandon(uint32_t key, uint32_t idx);
    void published(uint32_t key, uint32_t idx, uint32_t seeder, uint32_t gen,
                   uint64_t now_ns);
    void duplicate(uint32_t key, uint32_t idx, uint32_t seeder, uint32_t gen);
    // Fetch failed (timeout / socket error / hash mismatch): chunk back to
    // pending, seeder remembered in its tried set. hash_bad additionally
    // counts a verify failure (a corrupt seeder must not fail the round
    // while an honest one remains).
    void failed(uint32_t key, uint32_t idx, uint32_t seeder,
                bool hash_bad = false);
    // Transient refusal (seeder's serve window not ready): chunk back to
    // pending WITHOUT marking the seeder tried — pair with seeder_backoff.
    void requeue(uint32_t key, uint32_t idx, uint32_t seeder);

    // Force the plan to a failed terminal state (caller abandoning the
    // sync, e.g. a master-session flip mid-fetch): workers drain out.
    void abort();
    // Re-evaluate fail-out (a key whose seeder set is empty can never
    // complete); dispatchers call this each poll so a plan with no viable
    // source terminates instead of spinning.
    void check_liveness();

    // Dispatcher: re-source inflight chunks whose deadline passed (they
    // become assignable to OTHER seeders; the stuck worker's eventual
    // arrival dedupes). Returns how many expired.
    size_t expire_overdue(uint64_t now_ns);

    // Keys that newly completed (all chunks verified), each reported once
    // — the caller marks them servable and sends the promotion packet.
    std::vector<uint32_t> take_completed_keys();

    // Plan lifecycle: finished = every chunk delivered OR the plan failed
    // out (no alive seeder can serve some pending chunk and the retry
    // waves are exhausted).
    bool finished() const;
    bool complete_ok() const;
    bool failed_out() const;
    bool saw_hash_mismatch() const;

    // Current per-chunk deadline budget (workers bound their recv with it).
    uint64_t chunk_budget_ns() const;

    PlanStats stats() const;
    uint64_t chunk_bytes() const { return chunk_bytes_; }
    // key metadata is immutable after construction and keys_ is never
    // resized, so the returned reference stays valid without the lock —
    // the accessors still lock to keep the annotation contract honest
    const KeySpec &key_spec(uint32_t key) const;
    size_t key_count() const;
    uint32_t key_chunks(uint32_t key) const;
    uint64_t total_bytes() const { return total_bytes_; }

    // Park until something changed (arrival, expiry, promotion) or
    // timeout; spurious wakeups are fine — callers re-poll.
    void wait_event(int timeout_ms);

private:
    enum class CState : uint8_t { kPending, kInflight, kWriting, kDone };
    struct Chunk {
        CState state = CState::kPending;
        uint32_t attempts = 0;           // assignment generations handed out
        uint32_t inflight = 0;           // outstanding assignments
        uint64_t deadline_ns = 0;        // newest assignment's deadline
        uint64_t taken_ns = 0;           // newest assignment time (EWMA)
        std::set<uint32_t> tried;        // seeders that failed/expired it
        // seeders with an OUTSTANDING assignment for this chunk, so a
        // seeder death invalidates exactly ITS fetches — not every
        // healthy inflight transfer in the plan (one entry per
        // outstanding assignment; a seeder can legitimately appear twice
        // after an expire/re-take cycle)
        std::multiset<uint32_t> owners;
    };
    struct Key {
        KeySpec spec;
        uint32_t nchunks = 0;
        uint32_t done = 0;
        bool reported = false;
        std::set<uint32_t> seeders;      // eligible seeder indices
        std::vector<Chunk> chunks;
    };
    struct Seeder {
        std::string endpoint;
        bool alive = true;
        uint64_t backoff_until_ns = 0;
    };

    bool assignable(const Key &k, const Chunk &c, uint32_t seeder) const
        PCCLT_REQUIRES(mu_);
    void fail_locked(uint32_t key, uint32_t idx, uint32_t seeder,
                     bool hash_bad) PCCLT_REQUIRES(mu_);
    void maybe_fail_out() PCCLT_REQUIRES(mu_);
    uint64_t budget_locked() const PCCLT_REQUIRES(mu_);

    const uint64_t chunk_bytes_;
    const double factor_;
    const uint64_t min_ns_;
    const uint32_t max_range_;
    const uint64_t rot_seed_;
    uint64_t total_bytes_ = 0;
    uint64_t total_chunks_ = 0;

    mutable Mutex mu_; // lock-rank: 25
    CondVar cv_;
    std::vector<Key> keys_ PCCLT_GUARDED_BY(mu_);
    std::vector<Seeder> seeders_ PCCLT_GUARDED_BY(mu_);
    std::map<std::string, uint32_t> seeder_idx_ PCCLT_GUARDED_BY(mu_);
    std::vector<uint32_t> completed_keys_ PCCLT_GUARDED_BY(mu_);
    uint64_t done_chunks_ PCCLT_GUARDED_BY(mu_) = 0;
    // retry waves: when every pending chunk has been tried against every
    // alive eligible seeder, tried sets clear and a wave is consumed; the
    // plan fails out after kMaxWaves fruitless sweeps (bounded retry, the
    // chunk-plane analogue of the legacy path's single hard failure)
    uint32_t waves_ PCCLT_GUARDED_BY(mu_) = 0;
    bool failed_out_ PCCLT_GUARDED_BY(mu_) = false;
    double ewma_ns_ PCCLT_GUARDED_BY(mu_) = 0;
    PlanStats stats_ PCCLT_GUARDED_BY(mu_);

    static constexpr uint32_t kMaxWaves = 4;
};

}  // namespace pcclt::ssc
