#include "benchmark.hpp"

#include <chrono>
#include <cstdlib>
#include <random>
#include <vector>

#include "log.hpp"
#include "protocol.hpp"
#include "wire.hpp"

namespace pcclt::bench {

using Clock = std::chrono::steady_clock;

double probe_seconds() {
    if (const char *e = std::getenv("PCCLT_BENCH_SECONDS")) {
        double v = atof(e);
        if (v > 0) return v;
    }
    return 1.0;
}

double run_probe(const net::Addr &target) {
    net::Socket sock;
    if (!sock.connect(target)) return -1.0;
    std::mutex mu;
    if (!net::send_frame(sock, mu, proto::kBenchHello, {})) return -1.0;
    auto ack = net::recv_frame(sock);
    if (!ack || ack->type != proto::kBenchAck || ack->payload.empty() ||
        ack->payload[0] == 0)
        return -2.0; // busy

    std::vector<uint8_t> buf(8 << 20);
    std::mt19937_64 rng{0x9E3779B97F4A7C15ull};
    for (size_t i = 0; i + 8 <= buf.size(); i += 8) {
        uint64_t v = rng();
        memcpy(buf.data() + i, &v, 8);
    }
    double secs = probe_seconds();
    auto deadline = Clock::now() + std::chrono::duration<double>(secs);
    uint64_t sent = 0;
    auto t0 = Clock::now();
    while (Clock::now() < deadline) {
        if (!sock.send_all(buf.data(), buf.size())) break;
        sent += buf.size();
    }
    double elapsed = std::chrono::duration<double>(Clock::now() - t0).count();
    sock.shutdown();
    sock.close();
    if (elapsed <= 0 || sent == 0) return -1.0;
    return static_cast<double>(sent) * 8.0 / 1e6 / elapsed;
}

void serve_connection(net::Socket sock, std::atomic<int> &active, int max_active) {
    auto hello = net::recv_frame(sock);
    if (!hello || hello->type != proto::kBenchHello) return;
    int cur = active.load();
    bool accept = false;
    while (cur < max_active) {
        if (active.compare_exchange_weak(cur, cur + 1)) {
            accept = true;
            break;
        }
    }
    std::mutex mu;
    uint8_t flag = accept ? 1 : 0;
    net::send_frame(sock, mu, proto::kBenchAck, {&flag, 1});
    if (!accept) return;

    std::vector<uint8_t> buf(1 << 20);
    while (true) {
        ssize_t r = sock.recv_some(buf.data(), buf.size(), 2000);
        if (r == 0 || r == -1) break; // closed or error; -2 timeout keeps waiting
    }
    active.fetch_sub(1);
}

} // namespace pcclt::bench
