#include "benchmark.hpp"

#include <sys/socket.h>

#include <algorithm>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

#include "log.hpp"
#include "netem.hpp"
#include "protocol.hpp"
#include "uring.hpp"
#include "wire.hpp"

namespace pcclt::bench {

using Clock = std::chrono::steady_clock;

double probe_seconds() {
    if (const char *e = std::getenv("PCCLT_BENCH_SECONDS")) {
        double v = atof(e);
        if (v > 0) return v;
    }
    return 10.0; // reference default: BENCHMARK_LENGTH_SECONDS = 10
}

int probe_connections() {
    if (const char *e = std::getenv("PCCLT_BENCH_CONNECTIONS")) {
        int v = atoi(e);
        if (v > 0 && v <= kMaxProbeConnections) return v;
    }
    return 4;
}

double run_probe(const net::Addr &target) {
    const int ncon = probe_connections();

    // per-edge wire emulation must shape the probe too — the whole point
    // of the topology optimizer is to measure the edge the collective will
    // actually ride, and on an emulated mesh that edge is the netem model.
    // The flood below paces through the target's Edge bucket (shared with
    // the data plane), so the measured rate ≈ the emulated rate.
    net::netem::Registry::inst().refresh();
    auto edge = net::netem::Registry::inst().resolve(target);

    // one random token per probe: the server admits connections per-PROBER
    // (all-or-nothing), so two concurrent probers can never split the
    // server's capacity and both walk away busy-rejected
    std::array<uint8_t, 16> token;
    {
        std::random_device rd;
        for (auto &b : token) b = static_cast<uint8_t>(rd());
    }

    // establish ALL connections before flooding (all-or-nothing, like the
    // reference's launchBenchmark loop): a partial flood would understate
    // the link and a busy rejection mid-run would waste the window
    std::vector<net::Socket> socks(ncon);
    for (int i = 0; i < ncon; ++i) {
        if (!socks[i].connect(target)) return -1.0;
        Mutex mu;
        if (!net::send_frame(socks[i], mu, proto::kBenchHello, token)) return -1.0;
        auto ack = net::recv_frame(socks[i]);
        if (!ack || ack->type != proto::kBenchAck || ack->payload.empty())
            return -1.0;
        if (ack->payload[0] == 0) return -2.0; // busy: another prober holds it
    }

    // one shared random 8 MB buffer (reference: DEFAULT_SEND_BUFFER_SIZE).
    // On a paced edge, flood in chunks the emulated wire drains in ~25 ms
    // so the deadline stays meaningful (one 8 MB send at 25 Mbit/s would
    // blow a sub-second probe window by seconds on its own).
    std::vector<uint8_t> buf(8 << 20);
    std::mt19937_64 rng{0x9E3779B97F4A7C15ull};
    for (size_t i = 0; i + 8 <= buf.size(); i += 8) {
        uint64_t v = rng();
        memcpy(buf.data() + i, &v, 8);
    }
    size_t chunk = buf.size();
    if (edge->pace_enabled()) {
        double mbps_cap = edge->params().mbps;
        chunk = std::min(chunk, std::max<size_t>(
            64 << 10, static_cast<size_t>(mbps_cap * 1e6 / 8 * 0.025)));
    }

    const double secs = probe_seconds();
    std::vector<double> mbps(ncon, 0.0);
    std::vector<std::thread> threads;
    threads.reserve(ncon);
    for (int i = 0; i < ncon; ++i) {
        threads.emplace_back([&, i, chunk] {
            auto deadline = Clock::now() + std::chrono::duration<double>(secs);
            uint64_t sent = 0;
            auto t0 = Clock::now();
            // the probe floods through the same data-plane backend the
            // collective will ride: batched io_uring sends when available
            // (4 chunks per submission, each still paced through the
            // target's netem edge bucket), the plain send loop otherwise
            net::uring::Ring ring;
            bool use_uring = net::uring::enabled() && ring.init(8);
            while (Clock::now() < deadline) {
                if (use_uring) {
                    constexpr unsigned kProbeBatch = 4;
                    unsigned nb = 0;
                    for (; nb < kProbeBatch; ++nb) {
                        if (Clock::now() >= deadline && nb) break;
                        edge->pace(chunk);  // no-op on unemulated edges
                        auto *sqe = ring.get_sqe();
                        if (!sqe) break;
                        sqe->opcode = net::uring::kOpSend;
                        sqe->fd = socks[i].fd();
                        sqe->addr = reinterpret_cast<uint64_t>(buf.data());
                        sqe->len = static_cast<uint32_t>(chunk);
                        sqe->msg_flags = MSG_NOSIGNAL | MSG_WAITALL;
                        sqe->user_data = nb;
                    }
                    // link all but the last, preserving stream order within
                    // one submission (flags are settable until submit())
                    for (unsigned k = 0; k + 1 < nb; ++k)
                        ring.sqe_at_tail(nb - k)->flags |=
                            net::uring::kSqeIoLink;
                    int rc = nb == 0 ? -1 : ring.submit();
                    if (rc < 0) {
                        use_uring = false;
                        continue;
                    }
                    // reap exactly what was consumed — a short submission
                    // (async-context allocation failure) must not leave the
                    // loop waiting for CQEs that will never arrive
                    const unsigned submitted = static_cast<unsigned>(rc);
                    bool dead = false;
                    for (unsigned r = 0; r < submitted; ++r) {
                        net::uring::Ring::Cqe c;
                        if (!ring.next_cqe(c) || c.res < 0 ||
                            static_cast<size_t>(c.res) < chunk)
                            dead = true;
                        else
                            sent += chunk;
                    }
                    if (dead) break;
                    if (submitted < nb) use_uring = false; // ring is sick
                    continue;
                }
                edge->pace(chunk);  // no-op on unemulated edges
                if (!socks[i].send_all(buf.data(), chunk)) break;
                sent += chunk;
            }
            double elapsed =
                std::chrono::duration<double>(Clock::now() - t0).count();
            socks[i].shutdown();
            socks[i].close();
            if (elapsed > 0) mbps[i] = static_cast<double>(sent) * 8.0 / 1e6 / elapsed;
        });
    }
    for (auto &t : threads) t.join();

    double total = 0;
    for (double m : mbps) {
        if (m <= 0) return -1.0; // a dead connection invalidates the probe
        total += m;
    }
    return total;
}

void serve_connection(net::Socket sock, ServeState &state) {
    auto hello = net::recv_frame(sock);
    if (!hello || hello->type != proto::kBenchHello ||
        hello->payload.size() != 16)
        return;

    bool accept = false;
    {
        MutexLock lk(state.mu);
        if (state.refcount == 0) {
            memcpy(state.token.data(), hello->payload.data(), 16);
            state.refcount = 1;
            accept = true;
        } else if (memcmp(state.token.data(), hello->payload.data(), 16) == 0 &&
                   state.refcount < kMaxProbeConnections) {
            // same prober adding another flood connection
            state.refcount++;
            accept = true;
        }
    }
    Mutex mu;
    uint8_t flag = accept ? 1 : 0;
    net::send_frame(sock, mu, proto::kBenchAck, {&flag, 1});
    if (!accept) return;

    std::vector<uint8_t> buf(1 << 20);
    while (true) {
        ssize_t r = sock.recv_some(buf.data(), buf.size(), 2000);
        if (r == 0 || r == -1) break; // closed or error; -2 timeout keeps waiting
    }
    {
        MutexLock lk(state.mu);
        state.refcount--; // reaching 0 releases the token for the next prober
    }
}

} // namespace pcclt::bench
