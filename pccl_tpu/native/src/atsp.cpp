#include "atsp.hpp"

#include <algorithm>
#include <chrono>
#include <functional>
#include <limits>
#include <random>

namespace pcclt::atsp {

namespace {

using Clock = std::chrono::steady_clock;

double edge(const std::vector<double> &c, size_t n, int i, int j) {
    return c[static_cast<size_t>(i) * n + static_cast<size_t>(j)];
}

std::vector<int> held_karp(const std::vector<double> &cost, size_t n) {
    // exact DP over subsets; fix node 0 as start. O(2^n * n^2).
    const size_t full = size_t{1} << n;
    const double inf = std::numeric_limits<double>::infinity();
    std::vector<double> dp(full * n, inf);
    std::vector<int> parent(full * n, -1);
    dp[(1u << 0) * n + 0] = 0.0;
    for (size_t mask = 1; mask < full; ++mask) {
        if (!(mask & 1)) continue;
        for (size_t last = 0; last < n; ++last) {
            if (!(mask & (size_t{1} << last))) continue;
            double cur = dp[mask * n + last];
            if (cur == inf) continue;
            for (size_t nxt = 0; nxt < n; ++nxt) {
                if (mask & (size_t{1} << nxt)) continue;
                size_t nmask = mask | (size_t{1} << nxt);
                double cand = cur + edge(cost, n, static_cast<int>(last), static_cast<int>(nxt));
                if (cand < dp[nmask * n + nxt]) {
                    dp[nmask * n + nxt] = cand;
                    parent[nmask * n + nxt] = static_cast<int>(last);
                }
            }
        }
    }
    double best = inf;
    int best_last = 0;
    for (size_t last = 1; last < n; ++last) {
        double cand = dp[(full - 1) * n + last] + edge(cost, n, static_cast<int>(last), 0);
        if (cand < best) {
            best = cand;
            best_last = static_cast<int>(last);
        }
    }
    std::vector<int> tour(n);
    size_t mask = full - 1;
    int cur = best_last;
    for (size_t i = n; i-- > 0;) {
        tour[i] = cur;
        int p = parent[mask * n + cur];
        mask &= ~(size_t{1} << cur);
        cur = p;
    }
    return tour;
}

std::vector<int> nearest_neighbor(const std::vector<double> &cost, size_t n, int start) {
    std::vector<bool> used(n, false);
    std::vector<int> tour;
    tour.reserve(n);
    int cur = start;
    used[cur] = true;
    tour.push_back(cur);
    for (size_t step = 1; step < n; ++step) {
        int best = -1;
        double bc = std::numeric_limits<double>::infinity();
        for (size_t j = 0; j < n; ++j) {
            if (used[j]) continue;
            double c = edge(cost, n, cur, static_cast<int>(j));
            if (c < bc) {
                bc = c;
                best = static_cast<int>(j);
            }
        }
        used[best] = true;
        tour.push_back(best);
        cur = best;
    }
    return tour;
}

// directed 2-opt: reverse segment (costs recomputed fully — asymmetric) + Or-opt
bool local_search_pass(const std::vector<double> &cost, size_t n, std::vector<int> &tour,
                       double &cur_cost) {
    bool improved = false;
    // Or-opt: move a segment of length 1..3 elsewhere
    for (size_t seg = 1; seg <= 3 && seg < n; ++seg) {
        for (size_t i = 0; i + seg <= n; ++i) {
            for (size_t j = 0; j <= n - seg; ++j) {
                if (j >= i && j <= i + seg) continue;
                std::vector<int> cand;
                cand.reserve(n);
                for (size_t k = 0; k < n; ++k)
                    if (k < i || k >= i + seg) cand.push_back(tour[k]);
                size_t insert_at = j > i ? j - seg : j;
                cand.insert(cand.begin() + insert_at, tour.begin() + i,
                            tour.begin() + i + seg);
                double c = tour_cost(cost, n, cand);
                if (c + 1e-12 < cur_cost) {
                    tour = cand;
                    cur_cost = c;
                    improved = true;
                }
            }
        }
    }
    return improved;
}

} // namespace

double tour_cost(const std::vector<double> &cost, size_t n, const std::vector<int> &tour) {
    double c = 0;
    for (size_t i = 0; i < n; ++i) c += edge(cost, n, tour[i], tour[(i + 1) % n]);
    return c;
}

double improve(const std::vector<double> &cost, size_t n, std::vector<int> &tour,
               int budget_ms, const std::atomic<bool> *stop) {
    auto deadline = Clock::now() + std::chrono::milliseconds(budget_ms);
    double cur = tour_cost(cost, n, tour);
    while (Clock::now() < deadline && !(stop && stop->load())) {
        if (!local_search_pass(cost, n, tour, cur)) break;
    }
    return cur;
}

std::vector<int> hamiltonian(const std::vector<double> &cost, size_t n, double limit,
                             int budget_ms) {
    if (n == 0) return {};
    if (n == 1) return {0};
    auto deadline = Clock::now() + std::chrono::milliseconds(budget_ms);

    // adjacency: usable out-neighbors per node, cheapest first
    std::vector<std::vector<int>> adj(n);
    for (size_t i = 0; i < n; ++i) {
        for (size_t j = 0; j < n; ++j)
            if (i != j && edge(cost, n, static_cast<int>(i), static_cast<int>(j)) < limit)
                adj[i].push_back(static_cast<int>(j));
        std::sort(adj[i].begin(), adj[i].end(), [&](int a, int b) {
            return edge(cost, n, static_cast<int>(i), a) <
                   edge(cost, n, static_cast<int>(i), b);
        });
        if (adj[i].empty()) return {}; // a node with no usable out-edge
    }

    std::vector<int> tour{0};
    std::vector<bool> used(n, false);
    used[0] = true;
    bool timed_out = false;

    std::function<bool()> dfs = [&]() -> bool {
        if (Clock::now() >= deadline) {
            timed_out = true;
            return false;
        }
        if (tour.size() == n)
            return edge(cost, n, tour.back(), 0) < limit; // close the cycle
        for (int nxt : adj[tour.back()]) {
            if (used[nxt]) continue;
            used[nxt] = true;
            tour.push_back(nxt);
            if (dfs()) return true;
            if (timed_out) return false;
            tour.pop_back();
            used[nxt] = false;
        }
        return false;
    };
    if (dfs()) return tour;
    return {};
}

std::vector<int> solve(const std::vector<double> &cost, size_t n, int budget_ms) {
    if (n == 0) return {};
    if (n == 1) return {0};
    if (n == 2) return {0, 1};
    if (n <= 12) return held_karp(cost, n);

    auto deadline = Clock::now() + std::chrono::milliseconds(budget_ms);
    std::vector<int> best_tour;
    double best = std::numeric_limits<double>::infinity();
    for (size_t s = 0; s < n && Clock::now() < deadline; ++s) {
        auto t = nearest_neighbor(cost, n, static_cast<int>(s));
        double c = tour_cost(cost, n, t);
        while (Clock::now() < deadline && local_search_pass(cost, n, t, c)) {}
        if (c < best) {
            best = c;
            best_tour = t;
        }
    }
    // random restarts with the remaining budget
    std::mt19937 rng(12345);
    while (Clock::now() < deadline) {
        std::vector<int> t(n);
        for (size_t i = 0; i < n; ++i) t[i] = static_cast<int>(i);
        std::shuffle(t.begin(), t.end(), rng);
        double c = tour_cost(cost, n, t);
        while (Clock::now() < deadline && local_search_pass(cost, n, t, c)) {}
        if (c < best) {
            best = c;
            best_tour = t;
        }
    }
    return best_tour;
}

} // namespace pcclt::atsp
