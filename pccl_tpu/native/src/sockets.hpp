// TCP socket layer for the PCCP protocol.
//
// Reference parity: tinysockets (/root/reference/tinysockets/include/
// tinysockets.hpp) provides ServerSocket (libuv), BlockingIOSocket,
// QueuedSocket, BlockingIOServerSocket, MultiplexedIOSocket. This layer
// covers the same roles with a leaner, thread-per-connection design:
//
//   Socket        — RAII fd + sendall/recvall            (BlockingIOSocket)
//   Listener      — accept loop on own thread            (BlockingIOServerSocket
//                                                         + libuv ServerSocket roles)
//   ControlClient — reader thread + type/predicate-matched
//                   receive queue                        (QueuedSocket)
//   MultiplexConn — tag-demuxed full-duplex data plane
//                   with registered zero-copy sinks      (MultiplexedIOSocket)
//
// Framing:
//   control: [u32 len][u16 type][payload]         len = 2 + payload_size
//   data:    [u32 len][u64 tag][u64 seq][payload] len = 16 + payload_size
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <span>
#include <string>
#include <thread>
#include <vector>

namespace pcclt::net {

struct Addr {
    uint32_t ip = 0; // host byte order
    uint16_t port = 0;
    std::string str() const;
    static std::optional<Addr> parse(const std::string &ip_str, uint16_t port);
    bool operator==(const Addr &o) const { return ip == o.ip && port == o.port; }
};

class Socket {
public:
    Socket() = default;
    explicit Socket(int fd) : fd_(fd) {}
    ~Socket() { close(); }
    Socket(const Socket &) = delete;
    Socket &operator=(const Socket &) = delete;
    Socket(Socket &&o) noexcept : fd_(o.fd_.exchange(-1)) {}
    Socket &operator=(Socket &&o) noexcept {
        if (this != &o) {
            close();
            fd_ = o.fd_.exchange(-1);
        }
        return *this;
    }

    bool connect(const Addr &addr, int timeout_ms = 5000);
    bool send_all(const void *data, size_t n);
    bool recv_all(void *data, size_t n);
    // recv with timeout; returns bytes read (0 on orderly close), -1 error, -2 timeout
    ssize_t recv_some(void *data, size_t n, int timeout_ms);
    // SO_SNDBUF/SO_RCVBUF — large buffers keep the p2p data plane streaming
    // with fewer scheduler round-trips (matters on low-core-count hosts)
    void set_bufsizes(int bytes);
    void shutdown(); // wake up blocked recv
    void close();
    bool valid() const { return fd_ >= 0; }
    int fd() const { return fd_; }
    void set_nodelay();
    void set_keepalive(int idle_s = 30);
    Addr peer_addr() const;

private:
    std::atomic<int> fd_{-1};
};

// --- control framing over a Socket ---
struct Frame {
    uint16_t type = 0;
    std::vector<uint8_t> payload;
};

bool send_frame(Socket &s, std::mutex &write_mu, uint16_t type,
                std::span<const uint8_t> payload);
// blocking; returns nullopt on disconnect/error
std::optional<Frame> recv_frame(Socket &s);
// bounded: returns nullopt on disconnect/error/deadline (for handshake
// threads that must not block forever on a silent connection)
std::optional<Frame> recv_frame(Socket &s, int timeout_ms);

// --- Listener: accept loop on its own thread ---
class Listener {
public:
    ~Listener() { stop(); }
    // binds 127.0.0.1/0.0.0.0:port, bump-allocating upward up to +tries if taken
    bool listen(uint16_t port, int tries = 16, bool loopback_only = false);
    uint16_t port() const { return port_; }
    // on_accept runs on the accept thread; it must hand off quickly
    void run_async(std::function<void(Socket)> on_accept);
    void stop();

private:
    int fd_ = -1;
    uint16_t port_ = 0;
    std::thread thread_;
    std::atomic<bool> running_{false};
};

// --- ControlClient: one socket, reader thread, matched receive ---
class ControlClient {
public:
    ~ControlClient() { close(); }
    bool connect(const Addr &addr);
    // spawn reader thread; on_disconnect fires once when the socket dies
    void run(std::function<void()> on_disconnect = nullptr);
    bool send(uint16_t type, std::span<const uint8_t> payload);

    using Pred = std::function<bool(const std::vector<uint8_t> &)>;
    // Wait for a frame of `type` matching pred (nullptr = any). timeout_ms<0 →
    // wait forever; no_wait → poll. Returns nullopt on timeout or disconnect.
    std::optional<Frame> recv_match(uint16_t type, const Pred &pred,
                                    int timeout_ms = -1, bool no_wait = false);
    // Same, but matches any of `types`; pred sees the whole frame.
    using FramePred = std::function<bool(const Frame &)>;
    std::optional<Frame> recv_match_any(const std::vector<uint16_t> &types,
                                        const FramePred &pred, int timeout_ms = -1,
                                        bool no_wait = false);
    bool connected() const { return connected_.load(); }
    void close();

private:
    Socket sock_;
    std::mutex write_mu_;
    std::thread reader_;
    std::atomic<bool> connected_{false};
    std::mutex mu_;
    std::condition_variable cv_;
    std::deque<Frame> queue_;
    std::function<void()> on_disconnect_;
};

// --- MultiplexConn: tag-demuxed bulk data plane ---
class MultiplexConn {
public:
    explicit MultiplexConn(Socket sock) : sock_(std::move(sock)) {}
    ~MultiplexConn() { close(); }

    void run(); // spawn RX thread

    // TX: splits into sub-frames of `chunk` bytes; blocking; thread-safe.
    bool send_bytes(uint64_t tag, uint64_t seq, std::span<const uint8_t> data,
                    size_t chunk = 4 << 20);

    // Zero-copy RX: register a sink; RX thread appends payloads for `tag`
    // in arrival order starting at base. wait_filled blocks until >= min
    // bytes landed or timeout_ms elapsed (timeout_ms < 0 = forever); returns
    // the current fill level so callers can poll abort conditions between
    // bounded waits. unregister_sink blocks while the RX thread is mid-write
    // into the sink buffer (busy flag) so the buffer can be freed safely.
    void register_sink(uint64_t tag, uint8_t *base, size_t cap);
    size_t wait_filled(uint64_t tag, size_t min_bytes, int timeout_ms = -1);
    void unregister_sink(uint64_t tag);

    // Queued RX for small per-tag messages (quantization metadata):
    // frames for tags with no sink land in a per-tag queue.
    std::optional<std::vector<uint8_t>> recv_queued(uint64_t tag, int timeout_ms = -1,
                                                    const std::atomic<bool> *abort = nullptr);

    // Drop all sinks and queued frames with lo <= tag < hi (end-of-op cleanup).
    void purge_range(uint64_t lo, uint64_t hi);

    bool alive() const { return alive_.load(); }
    void close();
    Socket &socket() { return sock_; }

private:
    void rx_loop();

    struct Sink {
        uint8_t *base = nullptr;
        size_t cap = 0;
        size_t filled = 0;
        bool busy = false;   // RX thread is writing into base outside the lock
        bool cancel = false; // unregister requested: stop writing, drain+drop
    };

    Socket sock_;
    std::mutex write_mu_;
    std::thread rx_thread_;
    std::atomic<bool> alive_{false};
    std::mutex mu_;
    std::condition_variable cv_;
    std::map<uint64_t, Sink> sinks_;
    std::map<uint64_t, std::deque<std::vector<uint8_t>>> queues_;
};

} // namespace pcclt::net
