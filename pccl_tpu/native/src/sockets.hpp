// TCP socket layer for the PCCP protocol.
//
// Reference parity: tinysockets (/root/reference/tinysockets/include/
// tinysockets.hpp) provides ServerSocket (libuv), BlockingIOSocket,
// QueuedSocket, BlockingIOServerSocket, MultiplexedIOSocket. This layer
// covers the same roles with a leaner design:
//
//   Socket        — RAII fd + sendall/recvall/writev      (BlockingIOSocket)
//   Listener      — accept loop on own thread             (BlockingIOServerSocket
//                                                          + libuv ServerSocket roles)
//   ControlClient — reader thread + type/predicate-matched
//                   receive queue                         (QueuedSocket)
//   MultiplexConn — tag-demuxed full-duplex data plane:
//                   dedicated TX thread fed by a lock-free
//                   MPSC queue (mpsc.hpp) with futex
//                   parking (park.hpp), RX demux into a
//                   shared SinkTable                      (MultiplexedIOSocket)
//   SinkTable     — per-peer-link registered zero-copy RX sinks, shared by a
//                   connection pool so large transfers can stripe across it
//   Link          — striped send/recv view over a pool of MultiplexConns
//
// Same-host fast path: when a MultiplexConn's peer is on the same host
// (loopback), bulk payloads skip the TCP stream entirely — the sender ships
// a tiny CMA descriptor {pid, addr, len} and the RECEIVER pulls the bytes
// straight from the sender's buffer via process_vm_readv into the registered
// sink (one copy total, no kernel socket buffers). The receiver acks so the
// sender knows when its buffer is reusable; any CMA failure falls back to
// TCP streaming transparently. This is the same-host transport strategy of
// NCCL/MPI intra-node paths, applied to the reference's WAN-oriented design
// (the reference has no same-host fast path; multiplexed_socket.cpp always
// streams).
//
// Framing:
//   control: [u32 len][u16 type][payload]              len = 2 + payload_size
//   data:    [u32 len][u8 kind][u64 tag][u64 off][payload]
//            len = 17 + payload_size; kind: 0=data @off, 1=CMA descriptor,
//            2=CMA ack, 3=CMA nack
#pragma once

#include <array>
#include <atomic>
#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <optional>
#include <span>
#include <string>
#include <thread>
#include <vector>

#include "annotations.hpp"
#include "mpsc.hpp"
#include "net_addr.hpp"
#include "park.hpp"

namespace pcclt::telemetry {
class Domain;         // per-comm counter registry (telemetry.hpp)
struct EdgeCounters;  // per-edge byte/frame/stall counters
}

namespace pcclt::net {

namespace netem {
class Edge;  // per-remote-endpoint wire emulation model (netem.hpp)
}

namespace uring {
class Ring;  // io_uring submission/completion ring (uring.hpp)
}

class Socket {
public:
    Socket() = default;
    explicit Socket(int fd) : fd_(fd) {}
    ~Socket() { close(); }
    Socket(const Socket &) = delete;
    Socket &operator=(const Socket &) = delete;
    Socket(Socket &&o) noexcept : fd_(o.fd_.exchange(-1)) {}
    Socket &operator=(Socket &&o) noexcept {
        if (this != &o) {
            close();
            fd_ = o.fd_.exchange(-1);
        }
        return *this;
    }

    bool connect(const Addr &addr, int timeout_ms = 5000);
    bool send_all(const void *data, size_t n);
    // gathered write: header + payload in one syscall (no staging copy)
    bool send_all2(const void *a, size_t na, const void *b, size_t nb);
    bool recv_all(void *data, size_t n);
    // recv_all with a wall deadline: false on error, close, or deadline.
    // The shared-state plane's bulk reads go through this — an unbounded
    // recv_all let one blackholed seeder wedge a sync round until the
    // kernel TCP timeout (docs/04).
    bool recv_all_deadline(void *data, size_t n, int timeout_ms);
    // recv with timeout; returns bytes read (0 on orderly close), -1 error, -2 timeout
    ssize_t recv_some(void *data, size_t n, int timeout_ms);
    // SO_SNDBUF/SO_RCVBUF — large buffers keep the p2p data plane streaming
    // with fewer scheduler round-trips (matters on low-core-count hosts)
    void set_bufsizes(int bytes);
    void shutdown(); // wake up blocked recv
    void close();
    bool valid() const { return fd_ >= 0; }
    int fd() const { return fd_; }
    void set_nodelay();
    void set_quickack();
    void set_keepalive(int idle_s = 30);
    Addr peer_addr() const;
    bool peer_is_loopback() const;

private:
    std::atomic<int> fd_{-1};
};

// --- control framing over a Socket ---
struct Frame {
    uint16_t type = 0;
    std::vector<uint8_t> payload;
};

bool send_frame(Socket &s, Mutex &write_mu, uint16_t type,
                std::span<const uint8_t> payload) PCCLT_EXCLUDES(write_mu);
// blocking; returns nullopt on disconnect/error
std::optional<Frame> recv_frame(Socket &s);

// --- data-plane frame preamble (MultiplexConn wire format) ---
// Every multiplexed frame leads with the fixed 21-byte header
// [4B be len][1B kind][8B be tag][8B be off]; `len` counts kind + tag +
// off + payload, so a well-formed frame has len in [17, kMaxLen]. The
// parse is factored out of rx_loop so the wire-decode fuzzer can drive
// it byte-for-byte (tools: pcclt_fuzz).
struct FrameHeader {
    static constexpr size_t kWire = 21;
    static constexpr uint32_t kMaxLen = 272u << 20;
    uint8_t kind = 0;
    uint64_t tag = 0;
    uint64_t off = 0;
    size_t payload = 0;  // len - 17 bytes follow the preamble
    // nullopt on a short buffer or a length outside [17, kMaxLen]
    static std::optional<FrameHeader> parse(const uint8_t *hdr, size_t n);
};
// bounded: returns nullopt on disconnect/error/deadline (for handshake
// threads that must not block forever on a silent connection)
std::optional<Frame> recv_frame(Socket &s, int timeout_ms);

// --- Listener: accept loop on its own thread ---
class Listener {
public:
    ~Listener() { stop(); }
    // binds 127.0.0.1/0.0.0.0:port, bump-allocating upward up to +tries if taken
    bool listen(uint16_t port, int tries = 16, bool loopback_only = false);
    uint16_t port() const { return port_; }
    // on_accept runs on the accept thread; it must hand off quickly
    void run_async(std::function<void(Socket)> on_accept);
    void stop();

private:
    int fd_ = -1;
    uint16_t port_ = 0;
    std::thread thread_;
    std::atomic<bool> running_{false};
};

// --- ControlClient: one socket, reader thread, matched receive ---
class ControlClient {
public:
    ~ControlClient() { close(); }
    bool connect(const Addr &addr);
    // Tear down the current socket/reader/queue and dial `addr` afresh on
    // the SAME object (master HA session resume). Pending recv_match
    // waiters wake with nullopt when the old socket dies; the caller must
    // re-issue any request that was in flight. Call run() again after a
    // successful reconnect.
    bool reconnect(const Addr &addr);
    // spawn reader thread; on_disconnect fires once when the socket dies
    void run(std::function<void()> on_disconnect = nullptr);
    // Fire-and-forget notification handler for `type`: the reader thread
    // dispatches matching frames to `fn` INSTEAD of queueing them — the
    // consumption path for M2C packets no recv_match will ever wait for
    // (kM2CIncidentDump). Set BEFORE the first run() and never again: the
    // map is read lock-free by the reader; handlers survive reconnect().
    // Keep handlers brief (they run on the reader thread) — hand heavy
    // work to another thread.
    void set_notify(uint16_t type, std::function<void(Frame &&)> fn) {
        notify_[type] = std::move(fn);
    }
    bool send(uint16_t type, std::span<const uint8_t> payload);

    using Pred = std::function<bool(const std::vector<uint8_t> &)>;
    // Wait for a frame of `type` matching pred (nullptr = any). timeout_ms<0 →
    // wait forever; no_wait → poll. Returns nullopt on timeout or disconnect.
    std::optional<Frame> recv_match(uint16_t type, const Pred &pred,
                                    int timeout_ms = -1, bool no_wait = false);
    // Same, but matches any of `types`; pred sees the whole frame.
    using FramePred = std::function<bool(const Frame &)>;
    std::optional<Frame> recv_match_any(const std::vector<uint16_t> &types,
                                        const FramePred &pred, int timeout_ms = -1,
                                        bool no_wait = false);
    bool connected() const { return connected_.load(); }
    // Half-teardown: shutdown(2) the wire WITHOUT closing the fd or
    // joining the reader — unblocks any thread stuck in a blocking send
    // (e.g. the telemetry push thread against a master that stopped
    // reading) so its owner can join it BEFORE close() tears the socket
    // down. Safe concurrently with send/recv: the fd stays valid.
    void shutdown_wire() { sock_.shutdown(); }
    void close();

private:
    // match-scan over the receive queue; factored out of recv_match_any so
    // the lock contract is explicit (a scan lambda would not inherit the
    // caller's lock set under -Wthread-safety). recv_match is an adapter.
    std::optional<Frame> scan_queue_any(const std::vector<uint16_t> &types,
                                        const FramePred &pred)
        PCCLT_REQUIRES(mu_);

    Socket sock_;
    Mutex write_mu_; // lock-rank: io (serializes this socket's writes)
    std::thread reader_;
    std::atomic<bool> connected_{false};
    // written before the first run(), read lock-free by reader threads
    std::map<uint16_t, std::function<void(Frame &&)>> notify_;
    Mutex mu_; // lock-rank: 56
    CondVar cv_;
    std::deque<Frame> queue_ PCCLT_GUARDED_BY(mu_);
    // assigned in run() before the reader thread exists; read only by the
    // reader at exit — reconnect() joins the old reader before re-running
    std::function<void()> on_disconnect_;
};

class MultiplexConn;

// --- data-plane send completion handle ---
struct SendState {
    std::atomic<int> status{0}; // 0 pending, 1 ok, 2 failed
    park::Event ev;
    // borrowed payload + routing, kept so a CMA nack can fall back to
    // streaming the same bytes over TCP
    uint64_t tag = 0, off = 0;
    std::span<const uint8_t> span;
    // early-retire request (docs/05): once a relay delivery ack covers
    // this span, the remaining DIRECT frames are pure dead weight — the
    // TX path checks this at frame boundaries and fails the handle
    // without touching the span again, so the zombie drain ends in at
    // most one in-flight frame instead of the whole span at the degraded
    // rate. The conn itself stays alive (it may be the op's only pool
    // conn, still carrying metas and later re-probes).
    std::atomic<bool> cancel{false};

    // true once the send completed successfully; false on failure or timeout
    bool wait(int timeout_ms = -1) const;
    void complete(bool ok) {
        status.store(ok ? 1 : 2, std::memory_order_release);
        ev.signal();
    }
    bool done() const { return status.load(std::memory_order_acquire) != 0; }
};
using SendHandle = std::shared_ptr<SendState>;

// --- SinkTable: registered RX destinations, shared across a conn pool ---
class SinkTable {
public:
    // Zero-copy RX: register a sink; RX threads place payload bytes for
    // `tag` at their frame offsets starting at base. wait_filled blocks
    // until the CONTIGUOUS prefix reaches min bytes or timeout_ms elapsed
    // (timeout_ms < 0 = forever); returns the current prefix so callers can
    // poll abort conditions between bounded waits.
    // consumer_pull: same-host CMA descriptors for this tag are NOT filled
    // by the RX thread; they stay pending for the consumer to claim_cma()
    // and pull fused with its reduction (TCP frames still fill normally).
    void register_sink(uint64_t tag, uint8_t *base, size_t cap,
                       bool consumer_pull = false);
    // cma_pending (optional): also return, setting *cma_pending, as soon as
    // a same-host descriptor is pending for `tag` — the caller claims it via
    // consume_cma instead of waiting out the slice
    size_t wait_filled(uint64_t tag, size_t min_bytes, int timeout_ms = -1,
                       bool *cma_pending = nullptr);
    // Blocks while any RX thread is mid-write into the sink buffer so the
    // buffer can be freed safely.
    void unregister_sink(uint64_t tag);

    // Queued RX for small per-tag messages (quantization metadata):
    // frames for tags with no sink land in a per-tag queue.
    std::optional<std::vector<uint8_t>> recv_queued(uint64_t tag, int timeout_ms = -1,
                                                    const std::atomic<bool> *abort = nullptr);
    // Same, but also returns the frame's wire OFFSET. The per-window
    // quantization-meta protocol (docs/08) keys meta frames by offset
    // (0 = legacy whole-chunk meta, w+1 = window w's meta) and frames
    // arrive in any order across striped conns — the caller sorts them
    // by the returned offset.
    std::optional<std::pair<uint64_t, std::vector<uint8_t>>> recv_queued_any(
        uint64_t tag, int timeout_ms = -1,
        const std::atomic<bool> *abort = nullptr);

    // Fused same-host consume: if a CMA descriptor covering exactly [0, len)
    // is pending for `tag` (registered consumer_pull), pull it on the CALLING
    // thread in cache-sized element-aligned slices, feeding each slice to
    // consume(src, lo, n) while it is still cache-hot — the peer's bytes go
    // straight through the reduction without a scratch round-trip to DRAM.
    //   kNone      — nothing pending: caller should wait_filled (TCP path)
    //   kDone      — fully pulled + consumed; sender acked
    //   kCancelled — consume returned false (op abort); sender acked-dropped
    //   kFailed    — identity/read failure; sender falls back to TCP
    // fill_if_unmapped: when the descriptor does NOT resolve to a mapped
    // registered region, pull it into the sink on the CALLING thread (one
    // copy, no RX-thread handoff) instead of bouncing it through the
    // consumer — callers whose consume is a plain copy into the sink use
    // this to avoid a double copy on the process_vm_readv path.
    enum class CmaClaim { kNone, kDone, kCancelled, kFailed };
    CmaClaim consume_cma(uint64_t tag, size_t len, size_t slice_align,
                         const std::function<bool(const uint8_t *, size_t, size_t)> &consume,
                         bool fill_if_unmapped = false);

    // Route any pending descriptors for `tag` through the ordinary sink fill
    // (rx-thread style, on the calling thread). Used when fused consumption
    // is no longer possible — e.g. TCP stripes already started streaming for
    // this tag — so a late CMA stripe can never strand un-acked.
    void fill_pending(uint64_t tag);

    // --- straggler failover delivery (docs/05) ---
    // Place one re-issued/relayed window [off, off+len) for `tag`, deduping
    // against everything already delivered or in flight: regions covered by
    // the sink's prefix/extents or CLAIMED by an RX thread mid-write are
    // skipped (first arrival wins), only the uncovered gaps are copied and
    // published. Dropped/duplicate bytes are charged to `origin` (the edge
    // of the peer whose hop the relay routed around); delivered bytes are
    // charged to origin->rx_relay_*. With no sink yet (window raced ahead
    // of the stage's registration) the window parks in relay_pending_ and
    // register_sink drains it with the same dedupe + accounting.
    //
    // Returns whether [off, off+len) is DURABLY accounted for after the
    // call (published, parked, or belongs to a finished/cancelled op) —
    // the gate for the end-to-end kRelayAck. Bytes skipped because an RX
    // thread holds a CLAIM over them are NOT durable: the claim-holder
    // can still die mid-write and tear them, so acking such a range lets
    // the origin cancel the only remaining copy of those bytes
    // (model-checker finding, relay_vs_direct_deaths).
    bool deliver_window(uint64_t tag, uint64_t off,
                        std::vector<uint8_t> bytes,
                        telemetry::EdgeCounters *origin);

    // Drop all sinks, queued frames, and pending CMA descriptors with
    // lo <= tag < hi (end-of-op cleanup).
    void purge_range(uint64_t lo, uint64_t hi);

    // conns sharing this table call these
    void attach(const std::shared_ptr<MultiplexConn> &conn);
    void on_conn_dead(); // wake all waiters so they re-check liveness

private:
    friend class MultiplexConn;

    struct Sink {
        uint8_t *base = nullptr;
        size_t cap = 0;
        size_t prefix = 0;               // contiguous bytes from offset 0
        std::map<size_t, size_t> extents; // out-of-order [off,end) past prefix
        // [off,end) ranges an RX thread is writing into OUTSIDE the lock.
        // The failover dedupe treats a claim as covered (the claimant was
        // first) but never publishes an extent over it — the claimant does
        // when its write completes. Claims are removed on completion AND
        // on failure (a failed claim's conn is dying; the op fails with it).
        std::map<size_t, size_t> claims;
        int busy = 0;    // RX/CMA writers currently writing outside the lock
        bool cancel = false; // unregister requested: stop writing, drop rest
        bool consumer_pull = false; // CMA descs held for consume_cma()
        void add_extent(size_t off, size_t end);
        // covered-by prefix/extents/claims test for the dedupe
        bool fully_covered(size_t off, size_t end) const;
        // bytes of [off, end) already published (prefix/extents only —
        // NOT claims: an in-flight claimant counts its own overlap when
        // its write publishes). Feeds dup_bytes at direct-commit time.
        size_t published_overlap(size_t off, size_t end) const;
    };
    struct PendingDesc { // CMA descriptor that arrived before its sink
        std::weak_ptr<MultiplexConn> ack_conn; // conn to pull through and ack on
        uint32_t pid = 0;
        uint64_t addr = 0, len = 0, off = 0, tag = 0;
    };

    // waits for !busy on every sink with lo <= tag < hi; on a 5 s stall
    // kills the attached conns (peer made no progress at all: last resort).
    // Drops and reacquires mu_ while parked.
    void wait_not_busy_range(uint64_t lo, uint64_t hi) PCCLT_REQUIRES(mu_);

    bool is_retired(uint64_t tag) const PCCLT_REQUIRES(mu_);

    Mutex mu_; // lock-rank: 44
    // Sharded wakeups: per-tag waiters (wait_filled, recv_queued, the
    // consume_cma poll) park on their tag's shard so a fill for one tag
    // does not thundering-herd every concurrent op's consumer (the
    // reference reaches the same goal with per-tag lock-free inboxes).
    // Shards are array members — no lifetime hazard when a purge erases a
    // sink under a parked waiter, unlike true per-sink events. The global
    // ev_ is kept for whole-table waiters (wait_not_busy, conn death);
    // tag-signals bump both, which is ~free now that park::Event skips the
    // wake syscall without waiters.
    static constexpr size_t kEvShards = 16;
    park::Event ev_;
    park::Event shard_evs_[kEvShards];
    park::Event &shard_ev(uint64_t tag) {
        return shard_evs_[(tag ^ (tag >> 16) ^ (tag >> 32)) & (kEvShards - 1)];
    }
    void signal_tag(uint64_t tag) {
        shard_ev(tag).signal();
        ev_.signal();
    }
    void signal_all() {
        for (auto &e : shard_evs_) e.signal();
        ev_.signal();
    }
    // REQUIRES(mu_): place `bytes` at [off, off+len) of `s`, copying only
    // the gaps not already covered/claimed; publishes extents per gap.
    // Returns delivered byte count (len - delivered = duplicate bytes).
    size_t place_deduped(Sink &s, uint64_t tag, uint64_t off,
                         const uint8_t *bytes, size_t len) PCCLT_REQUIRES(mu_);

    std::map<uint64_t, Sink> sinks_ PCCLT_GUARDED_BY(mu_);
    std::map<uint64_t, std::deque<std::vector<uint8_t>>> queues_
        PCCLT_GUARDED_BY(mu_);
    struct PendingRelay {  // failover window that raced sink registration
        uint64_t off = 0;
        std::vector<uint8_t> bytes;
        telemetry::EdgeCounters *origin = nullptr;
    };
    std::multimap<uint64_t, PendingRelay> relay_pending_ PCCLT_GUARDED_BY(mu_);
    std::multimap<uint64_t, PendingDesc> pending_descs_ PCCLT_GUARDED_BY(mu_);
    std::vector<std::weak_ptr<MultiplexConn>> members_ PCCLT_GUARDED_BY(mu_);
    // recently purged tag ranges: data/descriptors that straggle in AFTER an
    // op's end-of-life purge are dropped (and CMA descs ack-dropped) instead
    // of queueing forever — otherwise the sender's handle never completes.
    // Tag ranges are op-seq scoped and never reused, so a bounded memory of
    // past purges is safe.
    std::deque<std::pair<uint64_t, uint64_t>> retired_ PCCLT_GUARDED_BY(mu_);
};

// --- MultiplexConn: tag-demuxed bulk data plane over one socket ---
class MultiplexConn : public std::enable_shared_from_this<MultiplexConn> {
public:
    // A fresh SinkTable is created when `table` is null (standalone conn).
    // `dom` is the telemetry domain whose per-edge counters this conn
    // feeds (the owning comm's); null falls back to the process default.
    explicit MultiplexConn(Socket sock, std::shared_ptr<SinkTable> table = nullptr,
                           std::shared_ptr<telemetry::Domain> dom = nullptr);
    ~MultiplexConn();

    void run(); // spawn RX + TX threads

    // Re-resolve the conn's wire-emulation edge against the peer's
    // CANONICAL endpoint (its advertised ip + p2p listen port) once the
    // handshake reveals it — accepted conns only see an ephemeral source
    // port, which can never match a per-endpoint map entry. Call before
    // run() so the CMA/zero-copy gate sees the final emulation state.
    void set_wire_peer(const Addr &peer);

    // Async TX. The payload span must stay valid and unmodified until the
    // returned handle completes. allow_cma lets same-host transfers go
    // through the CMA descriptor path.
    SendHandle send_async(uint64_t tag, uint64_t off, std::span<const uint8_t> payload,
                          bool allow_cma = true);
    // Owned small frame (metadata): copied into the queue, completes when
    // written to the kernel.
    SendHandle send_copy(uint64_t tag, std::vector<uint8_t> payload);
    // Owned frame of an explicit kind at an explicit offset (relay path).
    // Always queued to the TX thread — relay senders run on RX threads and
    // must never block on this socket's write mutex.
    SendHandle send_owned(uint8_t kind, uint64_t tag, uint64_t off,
                          std::vector<uint8_t> payload);
    // Blocking convenience (tests, small transfers).
    bool send_bytes(uint64_t tag, std::span<const uint8_t> data, bool allow_cma = true);

    // Relay routing hooks (straggler failover). Set by the owning client
    // BEFORE run() — the RX thread reads them lock-free. on_fwd: this conn
    // received a kRelayFwd and should re-emit toward dst; on_deliver: a
    // kRelayDeliver window for one of this client's inbound links arrived.
    // Both run on the RX thread holding no lock; implementations must not
    // block (enqueue-only sends).
    using RelayFwdFn = std::function<void(const uint8_t *dst_uuid,
                                          const uint8_t *origin_uuid,
                                          uint64_t tag, uint64_t off,
                                          std::vector<uint8_t> bytes)>;
    using RelayDeliverFn = std::function<void(const uint8_t *origin_uuid,
                                              uint64_t tag, uint64_t off,
                                              std::vector<uint8_t> bytes)>;
    // End-to-end relay delivery ack (kRelayAck): the final receiver tells
    // the ORIGIN that [off, off+len) of `tag` was delivered, so the origin
    // can retire the stalled direct copy (zombie) early. Runs on the RX
    // thread holding no lock; must not block.
    using RelayAckFn = std::function<void(uint64_t tag, uint64_t off,
                                          uint64_t len)>;
    void set_relay_handlers(RelayFwdFn fwd, RelayDeliverFn deliver,
                            RelayAckFn ack = nullptr) {
        relay_fwd_ = std::move(fwd);
        relay_deliver_ = std::move(deliver);
        relay_ack_ = std::move(ack);
    }

    // Chunk-plane request hook (docs/04 unified transport). Set by the
    // owning client BEFORE run(): a kChunkReq arrived on this conn —
    // payload is [16B requester uuid][range spec]; the handler receives the
    // uuid pointer plus the spec bytes after it. Runs on the RX thread
    // holding no lock; must not block (enqueue-only — the serve pool does
    // the materialize/send work).
    using ChunkReqFn = std::function<void(const uint8_t *requester_uuid,
                                          uint64_t tag,
                                          std::vector<uint8_t> spec)>;
    void set_chunk_req_handler(ChunkReqFn fn) { chunk_req_ = std::move(fn); }

    SinkTable &table() { return *table_; }
    const std::shared_ptr<SinkTable> &table_ptr() { return table_; }

    bool alive() const { return alive_.load(); }
    void close();
    void kill_socket() { sock_.shutdown(); } // unblock stalled RX (stall handler)
    Socket &socket() { return sock_; }
    bool cma_eligible() const { return cma_ok_.load(); }

    // public: the client's relay router names kRelayFwd/kRelayDeliver when
    // re-emitting windows via send_owned
    enum Kind : uint8_t {
        kData = 0,
        kCmaDesc = 1,
        kCmaAck = 2,
        kCmaNack = 3,
        kCmaHello = 4, // {pid, token_addr, 16-byte token}: CMA identity proof
        // registered shm regions (shm.hpp): zero-copy same-host transport.
        // Announce {pid, fd, base, len} lets the peer map the region via
        // /proc/<pid>/fd/<fd>; afterwards CMA descriptors inside [base,len)
        // resolve to direct local pointers. Retire {base} unmaps peer-side.
        kShmAnnounce = 5,
        kShmRetire = 6,
        // ack-DROP: completes the sender's handle like kCmaAck, but the
        // payload was discarded (op aborted/purged receiver-side), so the
        // sender must not account it as delivered on the edge counters
        kCmaAckDrop = 7,
        // straggler failover relay (docs/05): a window detouring around a
        // degraded edge. kRelayFwd rides sender -> relay peer, payload
        // [16B final-dst uuid][16B origin uuid][window bytes]; the relay
        // re-emits it to the final destination as kRelayDeliver, payload
        // [16B origin uuid][window bytes]. tag/off in the header are the
        // ORIGINAL window coordinates. Delivery dedupes via
        // SinkTable::deliver_window; neither kind counts into the direct
        // tx/rx byte counters (relayed payload is accounted separately).
        kRelayFwd = 8,
        kRelayDeliver = 9,
        // end-to-end relay delivery ack (docs/05): final receiver ->
        // origin, over the receiver's own (reverse-direction) link to the
        // origin. tag/off are the ORIGINAL window coordinates; payload is
        // the delivered length as a BE u64. Fire-and-forget; lets the
        // origin retire CONFIRMED-stalled zombies before op end.
        kRelayAck = 10,
        // shared-state chunk plane on the pool (docs/04 unified transport):
        // a chunk-range REQUEST rides fetcher -> seeder, payload
        // [16B requester uuid][protocol-framed range spec]; tag is the
        // fetcher-chosen response tag, off is 0. The seeder answers with a
        // kChunkHdr on the SAME tag ([u8 status][BE u64 payload len]) and,
        // on status 0, the payload itself as plain kData frames at
        // range-relative offsets — so chunk bytes reassemble through the
        // fetcher's registered sink exactly like collective windows and
        // inherit striping, pacing, zerocopy, relay dedupe, and per-edge
        // telemetry from the one transport.
        kChunkReq = 11,
        kChunkHdr = 12,
    };

private:
    friend class SinkTable;

    struct SendReq : mpsc::Node {
        Kind kind = kData;
        uint64_t tag = 0, off = 0;
        std::span<const uint8_t> span;  // borrowed (payload)
        std::vector<uint8_t> owned;     // or owned (meta/acks)
        bool allow_cma = false;
        SendHandle state;               // null for fire-and-forget acks
    };

    void rx_loop();
    void tx_loop();
    void enqueue(SendReq *req);
    // All frame writes serialize on wr_mu_ so small control frames (CMA
    // descriptors, acks, shm announces) can be written INLINE from the
    // calling thread — on the same-host path the TX thread never enters the
    // critical path at all (no wakeup/context-switch per stage).
    bool write_frame(Kind kind, uint64_t tag, uint64_t off,
                     std::span<const uint8_t> payload);
    bool stream_payload(const SendReq &req); // TCP frames of ≤ chunk bytes
    // io_uring TX: the payload's frames are built (and netem-paced) outside
    // wr_mu_, then submitted as a chain of LINKED vectored SQEs — header +
    // payload always leave in one submission, frames ≥ zc_min_ go
    // SENDMSG_ZC with completion-notification reaping. Falls back to the
    // plain gathered-write path on any ring setup failure (fallback ladder,
    // docs/08). Counters/pacing are identical to write_frame's.
    bool stream_payload_uring(const SendReq &req);
    // io_uring RX: batched linked MSG_WAITALL RECV slices straight into the
    // registered sink at `dst`. Returns false on socket death (like
    // recv_all); *cancelled is set when the sink cancels mid-frame (the
    // remaining bytes are still drained into dst — the busy refcount keeps
    // the buffer alive — but must not be marked delivered).
    bool uring_recv_sink(uint8_t *dst, size_t n, uint64_t tag, bool *cancelled);
    // receiver side: pull `d` into the registered sink via process_vm_readv,
    // update the fill level, and ack/nack on this conn
    void do_cma_fill(uint64_t tag, const SinkTable::PendingDesc &d);
    // identity probe: the announced pid must still resolve to the announcing
    // process in OUR pid namespace (token read-back)
    bool cma_verify_peer(const SinkTable::PendingDesc &d);
    // consumer-thread fused pull for consume_cma(); bounce-buffer slices
    SinkTable::CmaClaim consumer_cma_pull(
        uint64_t tag, const SinkTable::PendingDesc &d, size_t slice_align,
        const std::function<bool(const uint8_t *, size_t, size_t)> &consume);
    void send_ctl(Kind kind, uint64_t tag, uint64_t off); // ack/nack via TX queue
    void fail_all_pending();
    // Emit pending kShmRetire frames, then announce the region containing
    // `span` if it is registered and not yet announced on this conn.
    // Thread-safe (shm_tx_mu_). Returns false on socket failure.
    bool shm_sync_tx(std::span<const uint8_t> span);
    // inline same-host descriptor post (no TX-thread hop); see sockets.cpp
    bool cma_post_desc(uint64_t tag, uint64_t off, std::span<const uint8_t> span,
                       const SendHandle &st);
    // Resolve a peer address range against mapped announce records (null if
    // not covered). Safe from any thread.
    const uint8_t *shm_resolve(uint64_t addr, uint64_t len);

    Socket sock_;
    std::shared_ptr<SinkTable> table_;
    // wire-emulation edge for this conn's remote endpoint; shared by every
    // conn to the same endpoint (one bucket per edge). Never null.
    std::shared_ptr<netem::Edge> wire_;
    // telemetry: owning domain + this conn's edge counters (keyed by the
    // same canonical endpoint as wire_). Atomics because set_wire_peer may
    // rekey a LIVE conn (socktest's netem rekey) while the RX/TX threads
    // bump counters; the pointee lives in dom_'s never-erased map and the
    // label is interned (both immortal), so a stale read is merely a
    // frame attributed to the pre-rekey edge. Never null after ctor.
    std::shared_ptr<telemetry::Domain> dom_;
    std::atomic<telemetry::EdgeCounters *> edge_{nullptr};
    std::atomic<const char *> edge_label_{""};
    // acquire pairs with set_wire_peer's release store, so a rekeyed-in
    // EdgeCounters is fully constructed before any counter add through it
    telemetry::EdgeCounters &edge() const {
        return *edge_.load(std::memory_order_acquire);
    }
    std::thread rx_thread_, tx_thread_;
    std::atomic<bool> alive_{false};
    std::atomic<bool> closing_{false};
    // lock-rank: 40 blocking-ok — close() joins the rx/tx threads under
    // this lock BY DESIGN: concurrent join on one std::thread is UB, so
    // the losing closer must block until the winner finished tearing
    // down. Only closers/destructors ever take it.
    Mutex close_mu_; // serializes close(); guards closed_
    bool closed_ PCCLT_GUARDED_BY(close_mu_) = false;

    mpsc::Queue txq_;
    park::Event tx_ev_;
    // lock-rank: io (serializes this socket's frame writes)
    Mutex wr_mu_; // serializes write_frame across tx thread + inline writers

    std::atomic<bool> cma_ok_{false}; // same-host CMA negotiated & not failed
    Mutex cma_mu_; // lock-rank: 50
    // (tag,off)
    std::map<std::pair<uint64_t, uint64_t>, SendHandle> pending_cma_
        PCCLT_GUARDED_BY(cma_mu_);
    // Sender side: a random token at a stable address; the receiver
    // probe-reads it via process_vm_readv before every pull and compares
    // with the copy received over TCP — proving the pid resolves to THIS
    // process in the receiver's pid namespace (guards against pid reuse and
    // cross-pidns pid collisions; raw pids are not namespace-safe).
    std::unique_ptr<std::array<uint8_t, 16>> cma_token_;
    // Receiver side: the peer's announced identity
    bool cma_peer_valid_ PCCLT_GUARDED_BY(cma_mu_) = false;
    uint32_t cma_peer_pid_ PCCLT_GUARDED_BY(cma_mu_) = 0;
    uint64_t cma_peer_token_addr_ PCCLT_GUARDED_BY(cma_mu_) = 0;
    std::array<uint8_t, 16> cma_peer_token_ PCCLT_GUARDED_BY(cma_mu_){};

    // registered-shm transport state (shm.hpp).
    // TX side (guarded by shm_tx_mu_): regions already announced on this
    // conn and the retire-feed cursor.
    // lock-rank: 46 blocking-ok — held across the announce/retire frame
    // writes BY DESIGN: a racing writer must not observe "announced" and
    // ship a descriptor before the announce actually hit the wire (see
    // shm_sync_tx). Writers block on each other here at most one frame.
    Mutex shm_tx_mu_;
    // base -> len
    std::map<uint64_t, uint64_t> shm_announced_ PCCLT_GUARDED_BY(shm_tx_mu_);
    uint64_t shm_retire_cursor_ PCCLT_GUARDED_BY(shm_tx_mu_) = 0;
    // RX side (guarded by shm_mu_): peer base addr -> {len, local mapping}.
    // Mappings are NEVER munmapped while the conn is alive — shm_resolve
    // hands out raw pointers that op threads read lock-free, so a retire or
    // close only moves the entry to shm_zombies_; the actual munmap happens
    // in the destructor, when no thread can still hold a shared_ptr to us
    // mid-read. (A straggling reader on a retired region reads stale bytes
    // from pages the memfd keeps alive — never a SIGSEGV.)
    struct ShmMap {
        uint64_t len = 0;
        uint8_t *local = nullptr;
    };
    Mutex shm_mu_; // lock-rank: 52
    std::map<uint64_t, ShmMap> shm_maps_ PCCLT_GUARDED_BY(shm_mu_);
    std::vector<ShmMap> shm_zombies_ PCCLT_GUARDED_BY(shm_mu_);

    size_t tx_chunk_;       // active wire chunk (capped on emulated edges)
    size_t tx_chunk_base_;  // env-configured chunk, pre-cap
    size_t cma_min_;

    // relay routing (set before run(), RX-thread-read only)
    RelayFwdFn relay_fwd_;
    RelayDeliverFn relay_deliver_;
    RelayAckFn relay_ack_;
    // chunk-plane request hook (set before run(), RX-thread-read only)
    ChunkReqFn chunk_req_;

    // striped-bucket pacing lane on wire_ (docs/08 multipath striping):
    // allocated at construction / set_wire_peer rekey, released on close,
    // so every pool conn paces in its own fair-share sub-schedule of the
    // shared per-edge bucket instead of head-of-line-blocking the others.
    // Atomic for the same reason as edge_: socktest rekeys a live conn.
    std::atomic<uint32_t> lane_{0};

    // io_uring data plane (uring.hpp): sampled once at construction (env
    // gate × kernel probe), so a test flipping PCCLT_URING affects the
    // NEXT connection, mirroring the netem refresh contract. The TX ring
    // is created and used only under wr_mu_ (an io-rank leaf — blocking
    // submit/reap under it is the same contract as the blocking sendmsg
    // it replaces); the RX ring is owned and used by the RX thread alone.
    // *_down_ flags latch a ring failure so the conn stops retrying and
    // stays on the poll-loop fallback.
    bool uring_on_ = false;
    size_t zc_min_ = 0;  // MSG_ZEROCOPY threshold; 0 = zerocopy off
    std::unique_ptr<uring::Ring> tx_ring_ PCCLT_GUARDED_BY(wr_mu_);
    bool tx_uring_down_ PCCLT_GUARDED_BY(wr_mu_) = false;
    // MSG_ZEROCOPY notifs submitted but not yet reaped (lazy reaping,
    // docs/08): later submits, the idle TX loop, and close() scoop them
    // opportunistically instead of each stream draining synchronously —
    // tx_zc_frames == tx_zc_reaps still holds at quiescence. The atomic
    // mirror lets the TX loop check for pending notifs without wr_mu_.
    unsigned zc_unreaped_ PCCLT_GUARDED_BY(wr_mu_) = 0;
    std::atomic<unsigned> zc_unreaped_hint_{0};
    // reap posted CQEs without blocking; block==true additionally waits
    // for every outstanding notif (close-time quiescence)
    void reap_zc(bool block) PCCLT_REQUIRES(wr_mu_);
    // drain-then-free the TX ring (fallback/teardown paths): keeps the
    // reap accounting exact across every rung of the fallback ladder
    void drop_tx_ring() PCCLT_REQUIRES(wr_mu_);
    std::unique_ptr<uring::Ring> rx_ring_;  // RX-thread-only
    bool rx_uring_down_ = false;
};

// --- Link: striped send view over a pool of conns sharing one SinkTable ---
class Link {
public:
    Link() = default;
    Link(std::vector<std::shared_ptr<MultiplexConn>> conns,
         std::shared_ptr<SinkTable> table)
        : conns_(std::move(conns)), table_(std::move(table)) {}

    bool valid() const { return !conns_.empty() && table_; }
    bool alive() const;
    SinkTable &table() { return *table_; }
    // pool width: the upper bound on how many ways a window chain can
    // stripe (reduce.cpp clamps PCCLT_STRIPE_CONNS against this)
    size_t size() const { return conns_.size(); }

    // Send payload for `tag`, striping across the pool when it pays off
    // (TCP path, large payloads). Same-host CMA sends go as a single
    // descriptor — there is no wire bottleneck to stripe around. `rot`
    // rotates the starting conn so concurrent ops spread over the pool.
    std::vector<SendHandle> send_async(uint64_t tag, std::span<const uint8_t> payload,
                                       size_t rot = 0, bool allow_cma = true);
    // Window send for the pipelined data plane (reduce.cpp): one stream of
    // `payload` landing at byte offset `off` of tag's sink, on the
    // rot-selected pool conn — successive windows rotate across the pool,
    // which stripes a stage's windows over parallel TCP streams. CMA is
    // off by design: a window is a partial-buffer span the fused same-host
    // descriptor claim cannot cover.
    SendHandle send_at(uint64_t tag, uint64_t off, std::span<const uint8_t> payload,
                       size_t rot = 0);
    SendHandle send_meta(uint64_t tag, std::vector<uint8_t> payload);
    // Owned small frame at an explicit wire offset, queued to the TX
    // thread (per-window quantization metas: offset 0 is the legacy
    // whole-chunk meta, w+1 is window w's — recv_queued_any reads it back)
    SendHandle send_meta_at(uint64_t tag, uint64_t off,
                            std::vector<uint8_t> payload);
    // any live pool conn negotiated the same-host CMA transport (the
    // pipelined window path steps aside for the fused zero-copy claim)
    bool cma_eligible() const;
    static bool wait_all(const std::vector<SendHandle> &hs, int timeout_ms = -1);

private:
    std::vector<std::shared_ptr<MultiplexConn>> conns_;
    std::shared_ptr<SinkTable> table_;
};

} // namespace pcclt::net
