// Guarded allocator for memory-corruption debugging.
//
// Reference parity: the reference's optional mprotect-guarded malloc +
// global new/delete hook (/root/reference/ccoip/src/cpp/alloc.cpp:1-16,
// guarded_alloc.cpp:13-95), off by default. Allocations are placed so the
// buffer ends flush against a PROT_NONE guard page: any overrun faults
// immediately at the overrunning instruction instead of corrupting
// neighboring state.
//
// Enable the global operator new/delete hook with -DPCCLT_GUARDED_ALLOC=ON
// (debug builds only — every allocation costs >= 2 pages).
#pragma once

#include <cstddef>

namespace pcclt::galloc {

// Allocate n bytes with a PROT_NONE page immediately after the buffer.
// Returns nullptr on failure. Alignment: 16 bytes.
void *guarded_malloc(size_t n);
void guarded_free(void *p);

// Introspection for tests.
size_t live_count();

} // namespace pcclt::galloc
