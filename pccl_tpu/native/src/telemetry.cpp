#include "telemetry.hpp"

#include <sys/syscall.h>
#include <unistd.h>

#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <algorithm>
#include <set>

#include "log.hpp"

namespace pcclt::telemetry {

uint64_t now_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
           static_cast<uint64_t>(ts.tv_nsec);
}

const char *intern(const std::string &s) {
    static Mutex mu; // lock-rank: 68
    static std::set<std::string> *table = new std::set<std::string>;  // leaked
    MutexLock lk(mu);
    return table->insert(s).first->c_str();
}

namespace {

uint32_t tid_now() {
    static thread_local uint32_t tid =
        static_cast<uint32_t>(::syscall(SYS_gettid));
    return tid;
}

}  // namespace

// ---------------------------------------------------------------- Domain

EdgeCounters &Domain::edge(const std::string &endpoint) {
    MutexLock lk(mu_);
    auto &p = edges_[endpoint];
    if (!p) p = std::make_unique<EdgeCounters>();
    return *p;
}

std::vector<EdgeSnapshot> Domain::snapshot_edges() const {
    MutexLock lk(mu_);
    std::vector<EdgeSnapshot> out;
    out.reserve(edges_.size());
    for (const auto &[key, e] : edges_) {
        EdgeSnapshot s;
        s.endpoint = key;
        s.conns = e->conns.load(std::memory_order_relaxed);
        if (s.conns == 0) continue;  // pre-rekey ephemeral-port stub: no
                                     // conn ever ran keyed here — noise
        s.tx_bytes = e->tx_bytes.load(std::memory_order_relaxed);
        s.rx_bytes = e->rx_bytes.load(std::memory_order_relaxed);
        s.tx_frames = e->tx_frames.load(std::memory_order_relaxed);
        s.rx_frames = e->rx_frames.load(std::memory_order_relaxed);
        s.stall_ns = e->stall_ns.load(std::memory_order_relaxed);
        s.tx_zc_frames = e->tx_zc_frames.load(std::memory_order_relaxed);
        s.tx_zc_reaps = e->tx_zc_reaps.load(std::memory_order_relaxed);
        out.push_back(std::move(s));
    }
    return out;
}

const std::shared_ptr<Domain> &default_domain() {
    static const std::shared_ptr<Domain> *d =
        new std::shared_ptr<Domain>(std::make_shared<Domain>());  // leaked
    return *d;
}

// ---------------------------------------------------------------- Recorder

Recorder &Recorder::inst() {
    // leaked: conns/op threads may record during static destruction
    static Recorder *r = new Recorder;
    return *r;
}

std::string Recorder::env_trace_path() {
    const char *e = std::getenv("PCCLT_TRACE");
    if (!e || !e[0]) return {};
    std::string path(e);
    auto pos = path.find("%p");
    if (pos != std::string::npos)
        path.replace(pos, 2, std::to_string(getpid()));
    return path;
}

Recorder::Recorder() : ring_(new Slot[kCap]) {
    if (!env_trace_path().empty()) {
        on_.store(true, std::memory_order_relaxed);
        // always-on capture: dump whatever the ring holds at process exit
        std::atexit([] {
            auto path = env_trace_path();
            if (!path.empty()) Recorder::inst().dump_json(path);
        });
    }
}

void Recorder::push(const Event &ev) {
    uint64_t buf[kEvWords] = {0};
    memcpy(buf, &ev, sizeof(Event));
    uint64_t idx = head_.fetch_add(1, std::memory_order_relaxed);
    Slot &s = ring_[idx % kCap];
    uint64_t gen = (idx / kCap + 1) * 2;  // even, strictly increasing per slot
    s.seq.store(gen - 1, std::memory_order_relaxed);  // odd: write in progress
    std::atomic_thread_fence(std::memory_order_release);  // odd BEFORE words
    for (size_t i = 0; i < kEvWords; ++i)
        s.w[i].store(buf[i], std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_release);  // words BEFORE even
    s.seq.store(gen, std::memory_order_relaxed);
}

void Recorder::span(const char *cat, const char *name, uint64_t t0_ns,
                    uint64_t t1_ns, const char *arg0, uint64_t v0,
                    const char *arg1, uint64_t v1, const char *detail) {
    if (!on()) return;
    Event ev;
    ev.ts_ns = t0_ns;
    ev.dur_ns = t1_ns > t0_ns ? t1_ns - t0_ns : 0;
    ev.cat = cat;
    ev.name = name;
    ev.arg0 = arg0;
    ev.arg1 = arg1;
    ev.v0 = v0;
    ev.v1 = v1;
    ev.detail = detail;
    ev.tid = tid_now();
    push(ev);
}

void Recorder::instant(const char *cat, const char *name, const char *arg0,
                       uint64_t v0, const char *arg1, uint64_t v1,
                       const char *detail) {
    if (!on()) return;
    Event ev;
    ev.ts_ns = now_ns();
    ev.cat = cat;
    ev.name = name;
    ev.arg0 = arg0;
    ev.arg1 = arg1;
    ev.v0 = v0;
    ev.v1 = v1;
    ev.detail = detail;
    ev.tid = tid_now();
    push(ev);
}

std::vector<Event> Recorder::snapshot() const {
    std::vector<Event> out;
    out.reserve(kCap);
    for (size_t i = 0; i < kCap; ++i) {
        const Slot &s = ring_[i];
        // seqlock read: retry a torn slot a few times, then skip it — a
        // frozen snapshot matters less than never blocking a writer
        for (int attempt = 0; attempt < 4; ++attempt) {
            uint64_t a = s.seq.load(std::memory_order_acquire);
            if (a == 0) break;           // never written
            if (a & 1) continue;         // mid-write; retry
            uint64_t buf[kEvWords];
            for (size_t k = 0; k < kEvWords; ++k)
                buf[k] = s.w[k].load(std::memory_order_relaxed);
            std::atomic_thread_fence(std::memory_order_acquire);
            if (s.seq.load(std::memory_order_relaxed) == a) {
                Event ev;
                memcpy(&ev, buf, sizeof(Event));
                out.push_back(ev);
                break;
            }
        }
    }
    std::sort(out.begin(), out.end(),
              [](const Event &a, const Event &b) { return a.ts_ns < b.ts_ns; });
    return out;
}

void Recorder::clear() {
    for (size_t i = 0; i < kCap; ++i)
        ring_[i].seq.store(0, std::memory_order_relaxed);
    // head_ keeps counting: generations stay strictly increasing
}

namespace {

void json_escaped(FILE *f, const char *s) {
    for (; *s; ++s) {
        unsigned char c = *s;
        if (c == '"' || c == '\\') fprintf(f, "\\%c", c);
        else if (c < 0x20) fprintf(f, "\\u%04x", c);
        else fputc(c, f);
    }
}

}  // namespace

bool Recorder::dump_json(const std::string &path) const {
    auto events = snapshot();
    FILE *f = fopen(path.c_str(), "w");
    if (!f) {
        PLOG(kWarn) << "telemetry: cannot write trace to " << path;
        return false;
    }
    const int pid = getpid();
    fputs("{\"traceEvents\":[", f);
    fprintf(f,
            "{\"ph\":\"M\",\"name\":\"process_name\",\"pid\":%d,"
            "\"args\":{\"name\":\"pcclt native (pid %d)\"}}",
            pid, pid);
    for (const auto &ev : events) {
        fputs(",\n", f);
        fprintf(f, "{\"name\":\"");
        json_escaped(f, ev.name);
        fprintf(f, "\",\"cat\":\"");
        json_escaped(f, ev.cat);
        // ts/dur in µs on the raw monotonic timebase (doubles carry the
        // magnitude exactly enough: boot-relative µs stay < 2^53)
        fprintf(f, "\",\"ph\":\"%s\",\"pid\":%d,\"tid\":%u,\"ts\":%.3f",
                ev.dur_ns ? "X" : "i", pid, ev.tid, ev.ts_ns / 1e3);
        if (ev.dur_ns) fprintf(f, ",\"dur\":%.3f", ev.dur_ns / 1e3);
        else fputs(",\"s\":\"t\"", f);  // instant scope: thread
        fputs(",\"args\":{", f);
        bool first = true;
        auto arg_u64 = [&](const char *k, uint64_t v) {
            if (!k) return;
            fprintf(f, "%s\"", first ? "" : ",");
            json_escaped(f, k);
            fprintf(f, "\":%" PRIu64, v);
            first = false;
        };
        arg_u64(ev.arg0, ev.v0);
        arg_u64(ev.arg1, ev.v1);
        if (ev.detail) {
            fprintf(f, "%s\"detail\":\"", first ? "" : ",");
            json_escaped(f, ev.detail);
            fputs("\"", f);
        }
        fputs("}}", f);
    }
    fputs("]}\n", f);
    bool ok = fclose(f) == 0;
    if (ok)
        PLOG(kDebug) << "telemetry: wrote " << events.size() << " events to "
                     << path;
    return ok;
}

}  // namespace pcclt::telemetry
