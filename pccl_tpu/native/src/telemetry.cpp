#include "telemetry.hpp"

#include <sys/syscall.h>
#include <unistd.h>

#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <algorithm>
#include <set>

#include "log.hpp"

namespace pcclt::telemetry {

uint64_t now_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
           static_cast<uint64_t>(ts.tv_nsec);
}

const char *intern(const std::string &s) {
    static Mutex mu; // lock-rank: 68
    static std::set<std::string> *table = new std::set<std::string>;  // leaked
    MutexLock lk(mu);
    return table->insert(s).first->c_str();
}

bool win_trace_enabled() {
    static const bool on = [] {
        const char *e = std::getenv("PCCLT_TRACE_WINDOWS");
        return e && e[0] == '1';
    }();
    return on;
}

const char *phase_name(Phase p) {
    switch (p) {
    case Phase::kOp: return "op";
    case Phase::kCommenceWait: return "commence_wait";
    case Phase::kOpSetup: return "op_setup";
    case Phase::kQuantize: return "quantize";
    case Phase::kDequantize: return "dequantize";
    case Phase::kStageWire: return "stage_wire";
    case Phase::kStall: return "stall";
    case Phase::kSyncFetch: return "sync_fetch";
    case Phase::kSyncVerify: return "sync_verify";
    case Phase::kCount: break;
    }
    return "?";
}

uint64_t HistSnapshot::quantile_ns(double q) const {
    const uint64_t total = count();
    if (total == 0) return 0;
    if (q < 0) q = 0;
    if (q > 1) q = 1;
    // rank of the q-th sample, 1-based; walk the buckets to it
    auto rank = static_cast<uint64_t>(q * (total - 1)) + 1;
    uint64_t seen = 0;
    for (size_t i = 0; i < kHistBuckets; ++i) {
        seen += buckets[i];
        // overflow bucket: report its (finite) lower edge, not +Inf
        if (seen >= rank)
            return i + 1 >= kHistBuckets ? (1ull << (12 + kHistBuckets - 1))
                                         : hist_upper_ns(i);
    }
    return 1ull << (12 + kHistBuckets - 1);
}

std::vector<std::pair<uint8_t, uint64_t>> hist_sparse(const HistSnapshot &h) {
    std::vector<std::pair<uint8_t, uint64_t>> out;
    for (size_t i = 0; i < kHistBuckets; ++i)
        if (h.buckets[i])
            out.emplace_back(static_cast<uint8_t>(i), h.buckets[i]);
    return out;
}

HistSnapshot hist_dense(uint64_t sum_ns,
                        const std::vector<std::pair<uint8_t, uint64_t>> &b) {
    HistSnapshot h;
    h.sum_ns = sum_ns;
    for (const auto &[idx, count] : b)
        if (idx < kHistBuckets) h.buckets[idx] += count;
    return h;
}

namespace {

uint32_t tid_now() {
    static thread_local uint32_t tid =
        static_cast<uint32_t>(::syscall(SYS_gettid));
    return tid;
}

}  // namespace

// ---------------------------------------------------------------- Domain

EdgeCounters &Domain::edge(const std::string &endpoint) {
    MutexLock lk(mu_);
    auto &p = edges_[endpoint];
    if (!p) p = std::make_unique<EdgeCounters>();
    return *p;
}

std::vector<EdgeSnapshot> Domain::snapshot_edges() const {
    MutexLock lk(mu_);
    std::vector<EdgeSnapshot> out;
    out.reserve(edges_.size());
    for (const auto &[key, e] : edges_) {
        EdgeSnapshot s;
        s.endpoint = key;
        s.conns = e->conns.load(std::memory_order_relaxed);
        s.tx_sync_bytes = e->tx_sync_bytes.load(std::memory_order_relaxed);
        s.rx_sync_bytes = e->rx_sync_bytes.load(std::memory_order_relaxed);
        if (s.conns == 0 && s.tx_sync_bytes == 0 && s.rx_sync_bytes == 0)
            continue;  // pre-rekey ephemeral-port stub: no conn ever ran
                       // keyed here — noise (sync-only edges stay visible)
        s.tx_bytes = e->tx_bytes.load(std::memory_order_relaxed);
        s.rx_bytes = e->rx_bytes.load(std::memory_order_relaxed);
        s.tx_frames = e->tx_frames.load(std::memory_order_relaxed);
        s.rx_frames = e->rx_frames.load(std::memory_order_relaxed);
        s.stall_ns = e->stall_ns.load(std::memory_order_relaxed);
        s.tx_zc_frames = e->tx_zc_frames.load(std::memory_order_relaxed);
        s.tx_zc_reaps = e->tx_zc_reaps.load(std::memory_order_relaxed);
        s.wd_health = e->wd_health.load(std::memory_order_relaxed);
        s.wd_suspects = e->wd_suspects.load(std::memory_order_relaxed);
        s.wd_confirms = e->wd_confirms.load(std::memory_order_relaxed);
        s.wd_reissues = e->wd_reissues.load(std::memory_order_relaxed);
        s.wd_relays = e->wd_relays.load(std::memory_order_relaxed);
        s.rx_relay_bytes = e->rx_relay_bytes.load(std::memory_order_relaxed);
        s.rx_relay_windows =
            e->rx_relay_windows.load(std::memory_order_relaxed);
        s.dup_bytes = e->dup_bytes.load(std::memory_order_relaxed);
        s.dup_windows = e->dup_windows.load(std::memory_order_relaxed);
        s.tx_stripe_windows =
            e->tx_stripe_windows.load(std::memory_order_relaxed);
        s.tx_stripe_bytes = e->tx_stripe_bytes.load(std::memory_order_relaxed);
        s.stage_wire_hist = e->stage_wire_hist.snapshot();
        s.stall_hist = e->stall_hist.snapshot();
        out.push_back(std::move(s));
    }
    return out;
}

void Domain::record_op(uint64_t seq, uint64_t dur_ns, uint64_t stall_ns) {
    // keep the max: concurrent ops can complete out of seq order
    uint64_t prev = last_seq_.load(std::memory_order_relaxed);
    while (seq > prev &&
           !last_seq_.compare_exchange_weak(prev, seq,
                                            std::memory_order_relaxed)) {
    }
    MutexLock lk(op_mu_);
    ops_[op_head_ % kOpRing] = {seq, dur_ns, stall_ns};
    ++op_head_;
}

std::vector<OpSample> Domain::recent_ops() const {
    MutexLock lk(op_mu_);
    std::vector<OpSample> out;
    const uint64_t n = op_head_ < kOpRing ? op_head_ : kOpRing;
    out.reserve(n);
    for (uint64_t i = op_head_ - n; i < op_head_; ++i)
        out.push_back(ops_[i % kOpRing]);
    return out;
}

// ------------------------------------------------------- DigestSnapshotter

Digest DigestSnapshotter::snapshot() {
    Digest d;
    const uint64_t now = now_ns();
    const uint64_t dt = now > prev_t_ ? now - prev_t_ : 1;
    prev_t_ = now;
    d.interval_ns = dt;
    d.last_seq = d_->last_seq();
    d.ring_dropped = Recorder::inst().dropped();
    d.ring_pushed = Recorder::inst().pushed();
    d.ring_cap = Recorder::ring_capacity();
    d.collectives_ok =
        d_->comm.collectives_ok.load(std::memory_order_relaxed);
    d.ops = d_->recent_ops();
    for (size_t p = 0; p < kPhaseCount; ++p)
        d.phases[p] = d_->phase_snapshot(static_cast<Phase>(p));
    const double dt_s = dt / 1e9;
    for (const auto &e : d_->snapshot_edges()) {
        auto &p = prev_[e.endpoint];
        auto rate_mbps = [&](uint64_t cur, uint64_t prev_bytes) {
            uint64_t db = cur > prev_bytes ? cur - prev_bytes : 0;
            return db * 8.0 / (dt_s * 1e6);
        };
        double tx = rate_mbps(e.tx_bytes, p.tx_bytes);
        double rx = rate_mbps(e.rx_bytes, p.rx_bytes);
        double stall =
            (e.stall_ns > p.stall_ns ? e.stall_ns - p.stall_ns : 0) /
            static_cast<double>(dt);
        if (!p.seeded) {
            p.tx_mbps = tx;
            p.rx_mbps = rx;
            p.stall_ratio = stall;
            p.seeded = true;
        } else {
            p.tx_mbps = alpha_ * tx + (1 - alpha_) * p.tx_mbps;
            p.rx_mbps = alpha_ * rx + (1 - alpha_) * p.rx_mbps;
            p.stall_ratio = alpha_ * stall + (1 - alpha_) * p.stall_ratio;
        }
        p.tx_bytes = e.tx_bytes;
        p.rx_bytes = e.rx_bytes;
        p.stall_ns = e.stall_ns;
        EdgeDigest ed;
        ed.endpoint = e.endpoint;
        ed.tx_mbps = p.tx_mbps;
        ed.rx_mbps = p.rx_mbps;
        ed.stall_ratio = p.stall_ratio;
        ed.tx_bytes = e.tx_bytes;
        ed.rx_bytes = e.rx_bytes;
        ed.wd_state = e.wd_health;
        ed.stage_wire_hist = e.stage_wire_hist;
        ed.stall_hist = e.stall_hist;
        d.edges.push_back(std::move(ed));
    }
    return d;
}

const std::shared_ptr<Domain> &default_domain() {
    static const std::shared_ptr<Domain> *d =
        new std::shared_ptr<Domain>(std::make_shared<Domain>());  // leaked
    return *d;
}

// ---------------------------------------------------------------- Recorder

Recorder &Recorder::inst() {
    // leaked: conns/op threads may record during static destruction
    static Recorder *r = new Recorder;
    return *r;
}

std::string Recorder::env_trace_path() {
    const char *e = std::getenv("PCCLT_TRACE");
    if (!e || !e[0]) return {};
    std::string path(e);
    auto pos = path.find("%p");
    if (pos != std::string::npos)
        path.replace(pos, 2, std::to_string(getpid()));
    return path;
}

Recorder::Recorder() : ring_(new Slot[kCap]) {
    if (!env_trace_path().empty()) {
        on_.store(true, std::memory_order_relaxed);
        // always-on capture: dump whatever the ring holds at process exit
        std::atexit([] {
            auto path = env_trace_path();
            if (!path.empty()) Recorder::inst().dump_json(path);
        });
    }
}

void Recorder::push(const Event &ev) {
    uint64_t buf[kEvWords] = {0};
    Event stamped = ev;
    stamped.epoch = epoch_.load(std::memory_order_relaxed);
    memcpy(buf, &stamped, sizeof(Event));
    uint64_t idx = head_.fetch_add(1, std::memory_order_relaxed);
    Slot &s = ring_[idx % kCap];
    uint64_t gen = (idx / kCap + 1) * 2;  // even, strictly increasing per slot
    s.seq.store(gen - 1, std::memory_order_relaxed);  // odd: write in progress
    std::atomic_thread_fence(std::memory_order_release);  // odd BEFORE words
    for (size_t i = 0; i < kEvWords; ++i)
        s.w[i].store(buf[i], std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_release);  // words BEFORE even
    s.seq.store(gen, std::memory_order_relaxed);
}

void Recorder::span(const char *cat, const char *name, uint64_t t0_ns,
                    uint64_t t1_ns, const char *arg0, uint64_t v0,
                    const char *arg1, uint64_t v1, const char *detail,
                    const char *arg2, uint64_t v2) {
    if (!on()) return;
    Event ev;
    ev.ts_ns = t0_ns;
    ev.dur_ns = t1_ns > t0_ns ? t1_ns - t0_ns : 0;
    ev.cat = cat;
    ev.name = name;
    ev.arg0 = arg0;
    ev.arg1 = arg1;
    ev.arg2 = arg2;
    ev.v0 = v0;
    ev.v1 = v1;
    ev.v2 = v2;
    ev.detail = detail;
    ev.tid = tid_now();
    push(ev);
}

void Recorder::instant(const char *cat, const char *name, const char *arg0,
                       uint64_t v0, const char *arg1, uint64_t v1,
                       const char *detail, const char *arg2, uint64_t v2) {
    if (!on()) return;
    Event ev;
    ev.ts_ns = now_ns();
    ev.cat = cat;
    ev.name = name;
    ev.arg0 = arg0;
    ev.arg1 = arg1;
    ev.arg2 = arg2;
    ev.v0 = v0;
    ev.v1 = v1;
    ev.v2 = v2;
    ev.detail = detail;
    ev.tid = tid_now();
    push(ev);
}

std::vector<Event> Recorder::snapshot() const {
    std::vector<Event> out;
    out.reserve(kCap);
    for (size_t i = 0; i < kCap; ++i) {
        const Slot &s = ring_[i];
        // seqlock read: retry a torn slot a few times, then skip it — a
        // frozen snapshot matters less than never blocking a writer
        for (int attempt = 0; attempt < 4; ++attempt) {
            uint64_t a = s.seq.load(std::memory_order_acquire);
            if (a == 0) break;           // never written
            if (a & 1) continue;         // mid-write; retry
            uint64_t buf[kEvWords];
            for (size_t k = 0; k < kEvWords; ++k)
                buf[k] = s.w[k].load(std::memory_order_relaxed);
            std::atomic_thread_fence(std::memory_order_acquire);
            if (s.seq.load(std::memory_order_relaxed) == a) {
                Event ev;
                memcpy(&ev, buf, sizeof(Event));
                out.push_back(ev);
                break;
            }
        }
    }
    std::sort(out.begin(), out.end(),
              [](const Event &a, const Event &b) { return a.ts_ns < b.ts_ns; });
    return out;
}

void Recorder::clear() {
    for (size_t i = 0; i < kCap; ++i)
        ring_[i].seq.store(0, std::memory_order_relaxed);
    // head_ keeps counting: generations stay strictly increasing. base_
    // re-anchors so pushed()/dropped() count this capture window only.
    base_.store(head_.load(std::memory_order_relaxed),
                std::memory_order_relaxed);
}

std::string json_escape(const std::string &s) {
    std::string o;
    o.reserve(s.size());
    for (unsigned char c : s) {
        if (c == '"' || c == '\\') {
            o += '\\';
            o += static_cast<char>(c);
        } else if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof buf, "\\u%04x", c);
            o += buf;
        } else {
            o += static_cast<char>(c);
        }
    }
    return o;
}

namespace {

void json_escaped(FILE *f, const char *s) {
    fputs(json_escape(s).c_str(), f);
}

}  // namespace

bool Recorder::dump_json(const std::string &path) const {
    auto events = snapshot();
    FILE *f = fopen(path.c_str(), "w");
    if (!f) {
        PLOG(kWarn) << "telemetry: cannot write trace to " << path;
        return false;
    }
    const int pid = getpid();
    fputs("{\"traceEvents\":[", f);
    fprintf(f,
            "{\"ph\":\"M\",\"name\":\"process_name\",\"pid\":%d,"
            "\"args\":{\"name\":\"pcclt native (pid %d)\"}}",
            pid, pid);
    // dump header: ring accounting so a saturated capture is VISIBLE in
    // the artifact itself (dropped > 0 = the ring wrapped and this trace
    // is the newest kCap events, not the whole run), plus the master
    // epoch for cross-peer correlation (tools/trace_merge).
    fprintf(f,
            ",\n{\"ph\":\"M\",\"name\":\"pcclt_trace_meta\",\"pid\":%d,"
            "\"args\":{\"captured\":%zu,\"pushed\":%" PRIu64
            ",\"dropped\":%" PRIu64 ",\"ring_cap\":%zu,\"epoch\":%" PRIu64
            "}}",
            pid, events.size(), pushed(), dropped(), kCap, epoch());
    for (const auto &ev : events) {
        fputs(",\n", f);
        fprintf(f, "{\"name\":\"");
        json_escaped(f, ev.name);
        fprintf(f, "\",\"cat\":\"");
        json_escaped(f, ev.cat);
        // ts/dur in µs on the raw monotonic timebase (doubles carry the
        // magnitude exactly enough: boot-relative µs stay < 2^53)
        fprintf(f, "\",\"ph\":\"%s\",\"pid\":%d,\"tid\":%u,\"ts\":%.3f",
                ev.dur_ns ? "X" : "i", pid, ev.tid, ev.ts_ns / 1e3);
        if (ev.dur_ns) fprintf(f, ",\"dur\":%.3f", ev.dur_ns / 1e3);
        else fputs(",\"s\":\"t\"", f);  // instant scope: thread
        fputs(",\"args\":{", f);
        bool first = true;
        auto arg_u64 = [&](const char *k, uint64_t v) {
            if (!k) return;
            fprintf(f, "%s\"", first ? "" : ",");
            json_escaped(f, k);
            fprintf(f, "\":%" PRIu64, v);
            first = false;
        };
        arg_u64(ev.arg0, ev.v0);
        arg_u64(ev.arg1, ev.v1);
        arg_u64(ev.arg2, ev.v2);
        if (ev.epoch) arg_u64("epoch", ev.epoch);
        if (ev.detail) {
            fprintf(f, "%s\"detail\":\"", first ? "" : ",");
            json_escaped(f, ev.detail);
            fputs("\"", f);
        }
        fputs("}}", f);
    }
    fputs("]}\n", f);
    bool ok = fclose(f) == 0;
    if (ok)
        PLOG(kDebug) << "telemetry: wrote " << events.size() << " events to "
                     << path;
    return ok;
}

}  // namespace pcclt::telemetry
