// PCLMULQDQ-folded CRC-32 (IEEE 802.3 reflected, poly 0xEDB88320) — the
// hardware variant of hash.cpp's slice-by-8 table CRC. Compiled as its own
// TU with -mpclmul -msse4.1 and gated at runtime by __builtin_cpu_supports
// (same pattern as kernels_avx2.cpp), replacing the reference's
// configure-time arch-specific CRC static libs (reference
// ccoip/src/cpp/crc32/crc32_amd64_sse42*.cpp, selected in
// ccoip/CMakeLists.txt:17-29) with one binary + dispatch.
//
// Method: the classic carry-less-multiply folding scheme for reflected
// CRCs — fold 64-byte blocks with x^(512+k) constants, reduce 4 lanes to
// one with the 128-bit fold constants, then 128→64 reduction and a final
// Barrett reduction back to 32 bits. The folding constants are the
// published values for this polynomial (x^t mod P for the relevant t),
// bit-reflected. Bit parity with the table implementation is enforced by
// selftest across sizes, alignments, and seeds.
#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#include <wmmintrin.h>
#define PCCLT_X86 1
#endif

namespace pcclt::hash::clmul {

bool available() {
#if defined(PCCLT_X86) && defined(__GNUC__)
    return __builtin_cpu_supports("pclmul") && __builtin_cpu_supports("sse4.1");
#else
    return false;
#endif
}

#if defined(PCCLT_X86)

namespace {

// x^(512+64), x^512 mod P (reflected) — 64-byte distance folds
const uint64_t kFold512[2] = {0x0154442bd4, 0x01c6e41596};
// x^(128+64), x^128 mod P (reflected) — 16-byte distance folds
const uint64_t kFold128[2] = {0x01751997d0, 0x00ccaa009e};
// x^96, x^64 shifts for the 128->64 reduction
const uint64_t kShift[2] = {0x00ccaa009e, 0x0163cd6124};
// Barrett: mu = floor(x^64 / P)', P' (both with the implicit top bit)
const uint64_t kBarrett[2] = {0x01f7011641, 0x01db710641};

inline __m128i fold(__m128i acc, __m128i data, __m128i k) {
    // reflected fold: acc = (lo(acc)*k_lo) ^ (hi(acc)*k_hi) ^ data
    return _mm_xor_si128(
        _mm_xor_si128(_mm_clmulepi64_si128(acc, k, 0x00),
                      _mm_clmulepi64_si128(acc, k, 0x11)),
        data);
}

} // namespace

uint32_t crc32(const void *data, size_t nbytes, uint32_t crc) {
    const auto *p = static_cast<const uint8_t *>(data);
    crc = ~crc;
    // the vector path needs at least one full 64-byte block
    if (nbytes >= 64) {
        const __m128i k512 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(kFold512));
        __m128i x0 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(p));
        __m128i x1 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(p + 16));
        __m128i x2 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(p + 32));
        __m128i x3 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(p + 48));
        x0 = _mm_xor_si128(x0, _mm_cvtsi32_si128(static_cast<int>(crc)));
        p += 64;
        nbytes -= 64;
        while (nbytes >= 64) {
            x0 = fold(x0, _mm_loadu_si128(reinterpret_cast<const __m128i *>(p)), k512);
            x1 = fold(x1, _mm_loadu_si128(reinterpret_cast<const __m128i *>(p + 16)), k512);
            x2 = fold(x2, _mm_loadu_si128(reinterpret_cast<const __m128i *>(p + 32)), k512);
            x3 = fold(x3, _mm_loadu_si128(reinterpret_cast<const __m128i *>(p + 48)), k512);
            p += 64;
            nbytes -= 64;
        }
        // 4 lanes -> 1 with the 128-bit-distance constants
        const __m128i k128 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(kFold128));
        x1 = fold(x0, x1, k128);
        x2 = fold(x1, x2, k128);
        x0 = fold(x2, x3, k128);
        // remaining whole 16-byte blocks
        while (nbytes >= 16) {
            x0 = fold(x0, _mm_loadu_si128(reinterpret_cast<const __m128i *>(p)), k128);
            p += 16;
            nbytes -= 16;
        }
        // 128 -> 64: fold the low qword across, then the 96/64 shifts
        const __m128i ks = _mm_loadu_si128(reinterpret_cast<const __m128i *>(kShift));
        __m128i t = _mm_clmulepi64_si128(x0, ks, 0x00);       // lo * x^128-ish
        x0 = _mm_xor_si128(_mm_srli_si128(x0, 8), t);
        t = _mm_clmulepi64_si128(_mm_and_si128(x0, _mm_set_epi32(0, 0, 0, ~0)),
                                 ks, 0x10);                   // low dword * x^64
        x0 = _mm_xor_si128(_mm_srli_si128(x0, 4), t);
        // Barrett reduction 64 -> 32
        const __m128i kb = _mm_loadu_si128(reinterpret_cast<const __m128i *>(kBarrett));
        __m128i lo = _mm_and_si128(x0, _mm_set_epi32(0, 0, 0, ~0));
        t = _mm_clmulepi64_si128(lo, kb, 0x00);               // * mu
        t = _mm_and_si128(t, _mm_set_epi32(0, 0, 0, ~0));
        t = _mm_clmulepi64_si128(t, kb, 0x10);                // * P'
        x0 = _mm_xor_si128(x0, t);
        crc = static_cast<uint32_t>(_mm_extract_epi32(x0, 1));
    }
    // scalar tail (and short inputs): byte-at-a-time with the CRC32 step
    while (nbytes--) {
        crc ^= *p++;
        for (int i = 0; i < 8; ++i)
            crc = (crc >> 1) ^ (0xEDB88320u & (0u - (crc & 1)));
    }
    return ~crc;
}

#else

uint32_t crc32(const void *, size_t, uint32_t) { return 0; }

#endif

} // namespace pcclt::hash::clmul
