#include "kernels.hpp"

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <type_traits>

#if defined(__SSE2__)
#include <emmintrin.h>
#include <xmmintrin.h>
#endif

#include "kernels_avx2.hpp"

namespace pcclt::kernels {

namespace {

template <typename T, typename Op> void loop(T *dst, const T *src, size_t n, Op op) {
#pragma omp simd
    for (size_t i = 0; i < n; ++i) dst[i] = op(dst[i], src[i]);
}

template <typename T, typename Op>
void loop3(T *dst, const T *a, const T *b, size_t n, Op op) {
#pragma omp simd
    for (size_t i = 0; i < n; ++i) dst[i] = op(a[i], b[i]);
}

#if defined(__SSE2__)
// f32 sum is the gradient hot path (DDP/DiLoCo). dst is written exactly once
// and not re-read by this pass, so non-temporal stores skip the
// read-for-ownership traffic on the destination — on a memory-bound host the
// 3-stream kernel becomes a 2-read-1-write stream at full bus speed.
void loop3_f32_add_stream(float *dst, const float *a, const float *b, size_t n) {
    size_t i = 0;
    // scalar prologue until dst is 16-byte aligned
    while (i < n && (reinterpret_cast<uintptr_t>(dst + i) & 15u)) {
        dst[i] = a[i] + b[i];
        ++i;
    }
    for (; i + 4 <= n; i += 4) {
        __m128 va = _mm_loadu_ps(a + i);
        __m128 vb = _mm_loadu_ps(b + i);
        _mm_stream_ps(dst + i, _mm_add_ps(va, vb));
    }
    _mm_sfence();
    for (; i < n; ++i) dst[i] = a[i] + b[i];
}
#endif

template <typename Op>
void loop16(bool bf16, uint16_t *dst, const uint16_t *src, size_t n, Op op) {
    for (size_t i = 0; i < n; ++i) {
        float a = bf16 ? bf16_to_f32(dst[i]) : f16_to_f32(dst[i]);
        float b = bf16 ? bf16_to_f32(src[i]) : f16_to_f32(src[i]);
        float r = op(a, b);
        dst[i] = bf16 ? f32_to_bf16(r) : f32_to_f16(r);
    }
}

struct Add {
    template <typename T> T operator()(T a, T b) const { return a + b; }
};
struct Mul {
    template <typename T> T operator()(T a, T b) const { return a * b; }
};
struct Max {
    template <typename T> T operator()(T a, T b) const { return std::max(a, b); }
};
struct Min {
    template <typename T> T operator()(T a, T b) const { return std::min(a, b); }
};

template <typename T>
void dispatch_op(proto::RedOp op, T *dst, const T *src, size_t n) {
    switch (op) {
    case proto::RedOp::kSum:
    case proto::RedOp::kAvg: loop(dst, src, n, Add{}); break;
    case proto::RedOp::kProd: loop(dst, src, n, Mul{}); break;
    case proto::RedOp::kMax: loop(dst, src, n, Max{}); break;
    case proto::RedOp::kMin: loop(dst, src, n, Min{}); break;
    case proto::RedOp::kGather:
    case proto::RedOp::kReduceScatter:
    case proto::RedOp::kBroadcast:
    case proto::RedOp::kAllToAll:
        break; // collective-kind markers, not arithmetic ops; client.cpp /
               // api.cpp route them around these kernels (docs/12)
    }
}

bool avx2_ok() {
    static const bool ok = avx2::available();
    return ok;
}

void dispatch_op16(bool bf16, proto::RedOp op, uint16_t *dst, const uint16_t *src,
                   size_t n) {
    switch (op) {
    case proto::RedOp::kSum:
    case proto::RedOp::kAvg:
        if (bf16 && avx2_ok()) {
            avx2::bf16_add2(dst, src, n);
            break;
        }
        loop16(bf16, dst, src, n, Add{});
        break;
    case proto::RedOp::kProd: loop16(bf16, dst, src, n, Mul{}); break;
    case proto::RedOp::kMax: loop16(bf16, dst, src, n, Max{}); break;
    case proto::RedOp::kMin: loop16(bf16, dst, src, n, Min{}); break;
    case proto::RedOp::kGather:
    case proto::RedOp::kReduceScatter:
    case proto::RedOp::kBroadcast:
    case proto::RedOp::kAllToAll:
        break; // collective-kind markers, not arithmetic ops; client.cpp /
               // api.cpp route them around these kernels (docs/12)
    }
}

template <typename T>
void dispatch_op3(proto::RedOp op, T *dst, const T *a, const T *b, size_t n) {
    switch (op) {
    case proto::RedOp::kSum:
    case proto::RedOp::kAvg:
#if defined(__SSE2__)
        if constexpr (std::is_same_v<T, float>) {
            if (n >= (1u << 16)) { // NT pays off only on cache-exceeding runs
                loop3_f32_add_stream(dst, a, b, n);
                break;
            }
        }
#endif
        loop3(dst, a, b, n, Add{});
        break;
    case proto::RedOp::kProd: loop3(dst, a, b, n, Mul{}); break;
    case proto::RedOp::kMax: loop3(dst, a, b, n, Max{}); break;
    case proto::RedOp::kMin: loop3(dst, a, b, n, Min{}); break;
    case proto::RedOp::kGather:
    case proto::RedOp::kReduceScatter:
    case proto::RedOp::kBroadcast:
    case proto::RedOp::kAllToAll:
        break; // collective-kind markers, not arithmetic ops; client.cpp /
               // api.cpp route them around these kernels (docs/12)
    }
}

void dispatch_op16_3(bool bf16, proto::RedOp op, uint16_t *dst, const uint16_t *a,
                     const uint16_t *b, size_t n) {
    auto cvt = [bf16](uint16_t x) { return bf16 ? bf16_to_f32(x) : f16_to_f32(x); };
    auto enc = [bf16](float f) { return bf16 ? f32_to_bf16(f) : f32_to_f16(f); };
    auto go = [&](auto op_fn) {
        for (size_t i = 0; i < n; ++i) dst[i] = enc(op_fn(cvt(a[i]), cvt(b[i])));
    };
    switch (op) {
    case proto::RedOp::kSum:
    case proto::RedOp::kAvg:
        if (bf16 && avx2_ok()) {
            avx2::bf16_add3(dst, a, b, n);
            break;
        }
        go(Add{});
        break;
    case proto::RedOp::kProd: go(Mul{}); break;
    case proto::RedOp::kMax: go(Max{}); break;
    case proto::RedOp::kMin: go(Min{}); break;
    case proto::RedOp::kGather:
    case proto::RedOp::kReduceScatter:
    case proto::RedOp::kBroadcast:
    case proto::RedOp::kAllToAll:
        break; // collective-kind markers, not arithmetic ops; client.cpp /
               // api.cpp route them around these kernels (docs/12)
    }
}

} // namespace

void accumulate(proto::DType dt, proto::RedOp op, void *dst, const void *src,
                size_t count) {
    using proto::DType;
    switch (dt) {
    case DType::kU8: dispatch_op(op, static_cast<uint8_t *>(dst), static_cast<const uint8_t *>(src), count); break;
    case DType::kI8: dispatch_op(op, static_cast<int8_t *>(dst), static_cast<const int8_t *>(src), count); break;
    case DType::kU16: dispatch_op(op, static_cast<uint16_t *>(dst), static_cast<const uint16_t *>(src), count); break;
    case DType::kI16: dispatch_op(op, static_cast<int16_t *>(dst), static_cast<const int16_t *>(src), count); break;
    case DType::kU32: dispatch_op(op, static_cast<uint32_t *>(dst), static_cast<const uint32_t *>(src), count); break;
    case DType::kI32: dispatch_op(op, static_cast<int32_t *>(dst), static_cast<const int32_t *>(src), count); break;
    case DType::kU64: dispatch_op(op, static_cast<uint64_t *>(dst), static_cast<const uint64_t *>(src), count); break;
    case DType::kI64: dispatch_op(op, static_cast<int64_t *>(dst), static_cast<const int64_t *>(src), count); break;
    case DType::kF16: dispatch_op16(false, op, static_cast<uint16_t *>(dst), static_cast<const uint16_t *>(src), count); break;
    case DType::kBF16: dispatch_op16(true, op, static_cast<uint16_t *>(dst), static_cast<const uint16_t *>(src), count); break;
    case DType::kF32: dispatch_op(op, static_cast<float *>(dst), static_cast<const float *>(src), count); break;
    case DType::kF64: dispatch_op(op, static_cast<double *>(dst), static_cast<const double *>(src), count); break;
    }
}

void accumulate3(proto::DType dt, proto::RedOp op, void *dst, const void *a,
                 const void *b, size_t count) {
    using proto::DType;
    switch (dt) {
    case DType::kU8: dispatch_op3(op, static_cast<uint8_t *>(dst), static_cast<const uint8_t *>(a), static_cast<const uint8_t *>(b), count); break;
    case DType::kI8: dispatch_op3(op, static_cast<int8_t *>(dst), static_cast<const int8_t *>(a), static_cast<const int8_t *>(b), count); break;
    case DType::kU16: dispatch_op3(op, static_cast<uint16_t *>(dst), static_cast<const uint16_t *>(a), static_cast<const uint16_t *>(b), count); break;
    case DType::kI16: dispatch_op3(op, static_cast<int16_t *>(dst), static_cast<const int16_t *>(a), static_cast<const int16_t *>(b), count); break;
    case DType::kU32: dispatch_op3(op, static_cast<uint32_t *>(dst), static_cast<const uint32_t *>(a), static_cast<const uint32_t *>(b), count); break;
    case DType::kI32: dispatch_op3(op, static_cast<int32_t *>(dst), static_cast<const int32_t *>(a), static_cast<const int32_t *>(b), count); break;
    case DType::kU64: dispatch_op3(op, static_cast<uint64_t *>(dst), static_cast<const uint64_t *>(a), static_cast<const uint64_t *>(b), count); break;
    case DType::kI64: dispatch_op3(op, static_cast<int64_t *>(dst), static_cast<const int64_t *>(a), static_cast<const int64_t *>(b), count); break;
    case DType::kF16: dispatch_op16_3(false, op, static_cast<uint16_t *>(dst), static_cast<const uint16_t *>(a), static_cast<const uint16_t *>(b), count); break;
    case DType::kBF16: dispatch_op16_3(true, op, static_cast<uint16_t *>(dst), static_cast<const uint16_t *>(a), static_cast<const uint16_t *>(b), count); break;
    case DType::kF32: dispatch_op3(op, static_cast<float *>(dst), static_cast<const float *>(a), static_cast<const float *>(b), count); break;
    case DType::kF64: dispatch_op3(op, static_cast<double *>(dst), static_cast<const double *>(a), static_cast<const double *>(b), count); break;
    }
}

void assign(proto::DType dt, void *dst, const void *src, size_t count) {
    memcpy(dst, src, count * proto::dtype_size(dt));
}

void copy_stream(void *dst, const void *src, size_t n) {
#if defined(__SSE2__)
    // NT stores skip the destination read-for-ownership: a cache-exceeding
    // copy becomes 1-read-1-write instead of 2-read-1-write. Only worth it
    // when the destination won't be re-read from cache (all-gather results,
    // mapped-region fills).
    if (n >= (256u << 10)) {
        auto *d = static_cast<uint8_t *>(dst);
        auto *s = static_cast<const uint8_t *>(src);
        size_t head = (16 - (reinterpret_cast<uintptr_t>(d) & 15u)) & 15u;
        if (head) {
            memcpy(d, s, head);
            d += head;
            s += head;
            n -= head;
        }
        size_t i = 0;
        for (; i + 64 <= n; i += 64) {
            __m128i a = _mm_loadu_si128(reinterpret_cast<const __m128i *>(s + i));
            __m128i b = _mm_loadu_si128(reinterpret_cast<const __m128i *>(s + i + 16));
            __m128i c = _mm_loadu_si128(reinterpret_cast<const __m128i *>(s + i + 32));
            __m128i e = _mm_loadu_si128(reinterpret_cast<const __m128i *>(s + i + 48));
            _mm_stream_si128(reinterpret_cast<__m128i *>(d + i), a);
            _mm_stream_si128(reinterpret_cast<__m128i *>(d + i + 16), b);
            _mm_stream_si128(reinterpret_cast<__m128i *>(d + i + 32), c);
            _mm_stream_si128(reinterpret_cast<__m128i *>(d + i + 48), e);
        }
        _mm_sfence();
        if (i < n) memcpy(d + i, s + i, n - i);
        return;
    }
#endif
    memcpy(dst, src, n);
}

namespace {

template <typename T> void div_loop(T *dst, size_t n, uint64_t world) {
#pragma omp simd
    for (size_t i = 0; i < n; ++i) dst[i] = static_cast<T>(dst[i] / static_cast<T>(world));
}

} // namespace

void finalize_avg(proto::DType dt, void *dst, size_t count, uint64_t world) {
    using proto::DType;
    switch (dt) {
    case DType::kU8: div_loop(static_cast<uint8_t *>(dst), count, world); break;
    case DType::kI8: div_loop(static_cast<int8_t *>(dst), count, world); break;
    case DType::kU16: div_loop(static_cast<uint16_t *>(dst), count, world); break;
    case DType::kI16: div_loop(static_cast<int16_t *>(dst), count, world); break;
    case DType::kU32: div_loop(static_cast<uint32_t *>(dst), count, world); break;
    case DType::kI32: div_loop(static_cast<int32_t *>(dst), count, world); break;
    case DType::kU64: div_loop(static_cast<uint64_t *>(dst), count, world); break;
    case DType::kI64: div_loop(static_cast<int64_t *>(dst), count, world); break;
    case DType::kF16: {
        auto *d = static_cast<uint16_t *>(dst);
        for (size_t i = 0; i < count; ++i)
            d[i] = f32_to_f16(f16_to_f32(d[i]) / static_cast<float>(world));
        break;
    }
    case DType::kBF16: {
        auto *d = static_cast<uint16_t *>(dst);
        for (size_t i = 0; i < count; ++i)
            d[i] = f32_to_bf16(bf16_to_f32(d[i]) / static_cast<float>(world));
        break;
    }
    case DType::kF32: div_loop(static_cast<float *>(dst), count, world); break;
    case DType::kF64: div_loop(static_cast<double *>(dst), count, world); break;
    }
}

} // namespace pcclt::kernels
