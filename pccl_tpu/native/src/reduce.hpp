// Pipelined ring all-reduce over multiplex connections.
// Reference parity: reduce::pipelineRingReduce (/root/reference/ccoip/src/
// cpp/reduce.cpp:528) — reduce-scatter + all-gather with on-the-wire
// quantization, streaming sub-chunk accumulation, abort polling and
// src-buffer restore. Wire tags: (op_seq << 16) | stage, meta bit 0x8000.
#pragma once

#include <atomic>
#include <functional>
#include <memory>

#include "protocol.hpp"
#include "schedule.hpp"
#include "sockets.hpp"

namespace pcclt::telemetry {
struct EdgeCounters;  // per-edge flight-recorder counters (telemetry.hpp)
class Domain;         // per-comm counter registry (telemetry.hpp)
}

namespace pcclt::reduce {

enum class Result : int { kOk = 0, kAborted, kConnectionLost };

struct RingCtx {
    net::Link tx; // to ring successor (striped over the p2p pool)
    net::Link rx; // from ring predecessor
    uint32_t rank = 0, world = 0;
    uint64_t op_seq = 0;
    proto::DType dtype = proto::DType::kF32;
    proto::RedOp op = proto::RedOp::kSum;
    proto::QuantAlgo quant = proto::QuantAlgo::kNone;
    proto::DType q_dtype = proto::DType::kU8;
    // polled between sub-chunks; true → abort (master abort or conn loss)
    std::function<bool()> should_abort;
    // caller-owned copy of the input (same byte size). When set, the ring
    // restores from it on abort instead of making its own backup — the caller
    // can then also restore after a post-hoc abort verdict from the master.
    const uint8_t *backup = nullptr;
    // optional caller-pooled receive scratch: a fresh per-op vector would be
    // page-zeroed by the kernel on every reduce (~ms per 32 MiB), so the
    // client keeps a reuse pool and lends a buffer for the op's lifetime
    std::vector<uint8_t> *scratch = nullptr;
    uint64_t tx_bytes = 0, rx_bytes = 0;
    // telemetry: the inbound edge's counters (keyed by the ring
    // predecessor's canonical endpoint) — receiver wire-stall time is
    // charged here at op end. Optional; null skips attribution.
    telemetry::EdgeCounters *rx_edge = nullptr;
    // interned canonical endpoints of the inbound/outbound hops, stamped
    // into per-stage trace events so tools/trace_critic can attribute a
    // binding segment to a concrete EDGE, not just a peer. Optional.
    const char *rx_endpoint = nullptr;
    const char *tx_endpoint = nullptr;
    // ---- straggler-immune data plane (docs/05 three-stage ladder) ----
    // Edge watchdog config, resolved by the client per op from
    // PCCLT_WATCHDOG / PCCLT_WATCHDOG_FACTOR / PCCLT_WATCHDOG_MIN_MS.
    // wd_factor == 0 disables the watchdog entirely (the default).
    double wd_factor = 0;     // deadline = factor x EWMA window drain time
    uint64_t wd_min_ns = 0;   // deadline floor (absorbs scheduler noise)
    uint64_t wd_hold_ns = 0;  // how long a CONFIRMED verdict keeps the op
                              // in relay mode before re-probing direct
    // outbound edge's counters (ring successor) — watchdog verdicts,
    // EWMA baseline and failover accounting live here
    telemetry::EdgeCounters *tx_edge = nullptr;
    // failover rung 1: dial ONE extra pool connection to the ring
    // successor (flap recovery) and return a Link holding only it;
    // an invalid Link means the dial failed
    std::function<net::Link()> fresh_tx_conn;
    // failover rung 2: detour a window around the outbound edge through a
    // healthy neighbor (kRelayFwd). The implementation copies the bytes
    // (fire-and-forget toward the relay); false = no relay path exists
    // (world < 3 or no live link to any third peer). The client stripes
    // successive detours across several healthy neighbors (docs/05).
    std::function<bool(uint64_t tag, uint64_t off,
                       std::span<const uint8_t> payload)> relay_window;
    // end-to-end relay delivery acks (kRelayAck): true when the final
    // receiver has confirmed delivery of the whole [off, off+len) span of
    // `tag` — lets drain_zombies cancel a CONFIRMED-stalled direct copy's
    // remaining frames early instead of parking it to op end. Optional.
    std::function<bool(uint64_t tag, uint64_t off, size_t len)> relay_acked;
    // the comm's counter domain: completed ops deposit an OpSample
    // (seq/duration/stall) for the telemetry digest. Optional.
    telemetry::Domain *tele = nullptr;
    // all-gather only: destination slot per ring position (stable ordering
    // by sorted peer uuid — ring positions reshuffle across topology
    // rounds, so they cannot define the user-visible segment order)
    std::vector<uint32_t> slots;
    // ---- synthesized schedules (docs/12) ----
    // The commence-stamped algorithm + root (ring index: broadcast origin
    // or relay bottleneck sender). The interpreter executes exactly what
    // the master stamped — never a local choice, so the group can't split.
    sched::Algo sched_algo = sched::Algo::kRing;
    uint32_t sched_root = 0;
    // kRelayRing and this rank is the bottleneck sender: route the whole
    // op through the acked relay plane as a PLANNED detour (counted in
    // sched_relay_planned_bytes, not the watchdog's emergency counters)
    bool planned_relay = false;
    // per-ring-index link/counter resolvers for non-neighbor transfers
    // (tree/butterfly/mesh schedules). Absent → ring-neighbor-only algos.
    std::function<net::Link(uint32_t)> link_to;
    std::function<net::Link(uint32_t, int)> link_from;
    std::function<telemetry::EdgeCounters *(uint32_t)> edge_of;
};

Result ring_allreduce(RingCtx &ctx, const void *send, void *recv, size_t count);

// Ring all-gather: each peer contributes `count` elements; `recv`
// (capacity world*count) ends with every peer's segment at
// slots[ring_rank]. Forward-only (no reduction, no quantization); the
// reference lists All-Gather as unshipped roadmap work
// (docs/md/04-API Overview/01_PCCL_API_Overview.md:176-177), so this is a
// pcclt extension built on the same consensus + tag machinery.
Result ring_allgather(RingCtx &ctx, const void *send, void *recv, size_t count);

// ---- widened collective vocabulary (docs/12) ----

// Reduce-scatter (SUM): the reduce-scatter half of the ring. `recv`
// (capacity >= ceil(count/world) elements) gets this rank's fully-reduced
// chunk; out_offset/out_count (elements, in the global vector) report
// which chunk that is — chunk ownership follows ring position, which the
// topology optimizer reshuffles, so the range is an output, not an input.
Result ring_reduce_scatter(RingCtx &ctx, const void *send, void *recv,
                           size_t count, uint64_t *out_offset,
                           uint64_t *out_count);

// Broadcast from ctx.sched_root (ring index), in place in `buf`.
// ctx.sched_algo picks the chain (kRing: pipelined store-and-forward
// along ring order) or the star (kTree: root sends to every rank
// directly). Quantized: the root quantizes ONCE and every rank —
// including the root, via requantize_self — ends bit-identical.
Result run_broadcast(RingCtx &ctx, void *buf, size_t count);

// All-to-all: block j of `send` (count_per_peer elements, slots in
// sorted-uuid order like the all-gather) lands at block `rank-slot` of
// every peer's `recv`. kMesh sends every block directly over the full
// p2p mesh; kRing is the rotation baseline (block at ring distance r
// rides r store-and-forward hops).
Result run_all_to_all(RingCtx &ctx, const void *send, void *recv,
                      size_t count_per_peer);

// Recursive-doubling all-reduce (power-of-two worlds, small payloads):
// log2(world) full-payload exchanges with the round-k partner rank^2^k.
// Commutative fold order makes results bit-identical across ranks.
Result butterfly_allreduce(RingCtx &ctx, const void *send, void *recv,
                           size_t count);

} // namespace pcclt::reduce
