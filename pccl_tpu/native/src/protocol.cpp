#include "protocol.hpp"

#include <cctype>
#include <cmath>
#include <random>
#include <stdexcept>

#include "telemetry.hpp"

namespace pcclt::proto {

std::string uuid_str(const Uuid &u) {
    static const char *hex = "0123456789abcdef";
    std::string s;
    s.reserve(36);
    for (int i = 0; i < 16; ++i) {
        if (i == 4 || i == 6 || i == 8 || i == 10) s.push_back('-');
        s.push_back(hex[u[i] >> 4]);
        s.push_back(hex[u[i] & 0xf]);
    }
    return s;
}

Uuid uuid_random() {
    static thread_local std::mt19937_64 rng{std::random_device{}()};
    Uuid u;
    for (int i = 0; i < 16; i += 8) {
        uint64_t v = rng();
        memcpy(u.data() + i, &v, 8);
    }
    u[6] = (u[6] & 0x0f) | 0x40; // version 4
    u[8] = (u[8] & 0x3f) | 0x80;
    return u;
}

size_t dtype_size(DType d) {
    switch (d) {
    case DType::kU8: case DType::kI8: return 1;
    case DType::kU16: case DType::kI16: case DType::kF16: case DType::kBF16: return 2;
    case DType::kU32: case DType::kI32: case DType::kF32: return 4;
    case DType::kU64: case DType::kI64: case DType::kF64: return 8;
    }
    return 0;
}

// --- HelloC2M ---

std::vector<uint8_t> HelloC2M::encode() const {
    wire::Writer w;
    w.u8(wire_rev);
    w.u32(peer_group);
    w.u16(p2p_port);
    w.u16(ss_port);
    w.u16(bench_port);
    w.str(adv_ip);
    w.u8(observer); // optional tail: old decoders ignore trailing bytes
    return w.take();
}

std::optional<HelloC2M> HelloC2M::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        HelloC2M h;
        h.wire_rev = r.u8();
        h.peer_group = r.u32();
        h.p2p_port = r.u16();
        h.ss_port = r.u16();
        h.bench_port = r.u16();
        h.adv_ip = r.str();
        if (!r.done()) h.observer = r.u8(); // tail-tolerant observer flag
        return h;
    } catch (...) { return std::nullopt; }
}

// --- SessionResumeC2M / SessionResumeAck (master HA) ---

std::vector<uint8_t> SessionResumeC2M::encode() const {
    wire::Writer w;
    put_uuid(w, uuid);
    w.u64(last_revision);
    w.u16(p2p_port);
    w.u16(ss_port);
    w.u16(bench_port);
    w.str(adv_ip);
    return w.take();
}

std::optional<SessionResumeC2M> SessionResumeC2M::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        SessionResumeC2M s;
        s.uuid = get_uuid(r);
        s.last_revision = r.u64();
        s.p2p_port = r.u16();
        s.ss_port = r.u16();
        s.bench_port = r.u16();
        s.adv_ip = r.str();
        return s;
    } catch (...) { return std::nullopt; }
}

std::vector<uint8_t> SessionResumeAck::encode() const {
    wire::Writer w;
    w.u8(ok);
    w.u64(epoch);
    w.u64(last_revision);
    w.str(reason);
    return w.take();
}

std::optional<SessionResumeAck> SessionResumeAck::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        SessionResumeAck a;
        a.ok = r.u8();
        a.epoch = r.u64();
        a.last_revision = r.u64();
        a.reason = r.str();
        return a;
    } catch (...) { return std::nullopt; }
}

namespace {

// Family-tagged wire addresses (PCCP/2): a u8 family then 4 bytes (v4,
// host-order u32) or 16 bytes (v6, network order). Both families ROUTE
// end-to-end since round 4 (net::Addr carries either; connect/listen/
// peer_addr speak both). Reference parity: ccoip_inet.h:15-29 carries
// both in its inet types, IPv4-first in its plumbing.
void put_addr(wire::Writer &w, const net::Addr &a) {
    if (a.family == 6) {
        w.u8(6);
        w.raw(a.ip6.data(), 16);
    } else {
        w.u8(4);
        w.u32(a.ip);
    }
}

net::Addr get_addr(wire::Reader &r) {
    uint8_t family = r.u8();
    if (family == 4) return net::Addr{r.u32(), 0};
    if (family == 6) {
        net::Addr a{0, 0, 6};
        for (auto &b : a.ip6) b = r.u8();
        return a;
    }
    throw std::runtime_error("bad wire address family");
}

} // namespace

// --- P2PConnInfo ---

std::vector<uint8_t> P2PConnInfo::encode() const {
    wire::Writer w;
    w.u64(revision);
    w.u32(static_cast<uint32_t>(peers.size()));
    for (const auto &p : peers) {
        put_uuid(w, p.uuid);
        put_addr(w, p.ip);
        w.u16(p.p2p_port);
        w.u16(p.bench_port);
        w.u32(p.peer_group);
    }
    w.u32(static_cast<uint32_t>(ring.size()));
    for (const auto &u : ring) put_uuid(w, u);
    // trailing schedule table (docs/12); older clients stop reading above
    w.bytes(sched);
    return w.take();
}

std::optional<P2PConnInfo> P2PConnInfo::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        P2PConnInfo p;
        p.revision = r.u64();
        uint32_t n = r.u32();
        for (uint32_t i = 0; i < n; ++i) {
            PeerEndpoint e;
            e.uuid = get_uuid(r);
            e.ip = get_addr(r);
            e.p2p_port = r.u16();
            e.bench_port = r.u16();
            e.peer_group = r.u32();
            p.peers.push_back(e);
        }
        uint32_t m = r.u32();
        for (uint32_t i = 0; i < m; ++i) p.ring.push_back(get_uuid(r));
        try {
            p.sched = r.bytes(); // trailing; absent from older masters
        } catch (...) {}
        return p;
    } catch (...) { return std::nullopt; }
}

// --- CollectiveInit ---

std::vector<uint8_t> CollectiveInit::encode() const {
    wire::Writer w;
    w.u64(tag);
    w.u64(count);
    w.u8(static_cast<uint8_t>(dtype));
    w.u8(static_cast<uint8_t>(op));
    w.u8(static_cast<uint8_t>(quant));
    w.u8(static_cast<uint8_t>(quant_dtype));
    w.u8(retry);
    w.u64(retry_seq);
    w.u64(aux);
    return w.take();
}

std::optional<CollectiveInit> CollectiveInit::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        CollectiveInit c;
        c.tag = r.u64();
        c.count = r.u64();
        c.dtype = static_cast<DType>(r.u8());
        c.op = static_cast<RedOp>(r.u8());
        c.quant = static_cast<QuantAlgo>(r.u8());
        c.quant_dtype = static_cast<DType>(r.u8());
        try {
            c.retry = r.u8(); // trailing; absent from older clients
            c.retry_seq = r.u64();
            c.aux = r.u64(); // trailing (docs/12); absent decodes 0
        } catch (...) {}
        return c;
    } catch (...) { return std::nullopt; }
}

// --- SharedStateSyncC2M ---

std::vector<uint8_t> SharedStateSyncC2M::encode() const {
    wire::Writer w;
    w.u64(revision);
    w.u8(static_cast<uint8_t>(strategy));
    w.u32(static_cast<uint32_t>(entries.size()));
    for (const auto &e : entries) {
        w.str(e.name);
        w.u8(static_cast<uint8_t>(e.dtype));
        w.u64(e.count);
        w.u8(e.allow_content_inequality);
        w.u64(e.hash);
    }
    // trailing chunk-plane section (older peers stop reading above):
    // chunk size + one leaf list per entry, same order
    if (chunk_bytes) {
        w.u64(chunk_bytes);
        for (const auto &e : entries) {
            w.u32(static_cast<uint32_t>(e.chunk_leaves.size()));
            for (uint64_t h : e.chunk_leaves) w.u64(h);
        }
    }
    return w.take();
}

std::optional<SharedStateSyncC2M> SharedStateSyncC2M::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        SharedStateSyncC2M s;
        s.revision = r.u64();
        s.strategy = static_cast<SyncStrategy>(r.u8());
        uint32_t n = r.u32();
        for (uint32_t i = 0; i < n; ++i) {
            SharedStateEntryMeta e;
            e.name = r.str();
            e.dtype = static_cast<DType>(r.u8());
            e.count = r.u64();
            e.allow_content_inequality = r.u8();
            e.hash = r.u64();
            s.entries.push_back(std::move(e));
        }
        if (!r.done()) {
            // chunk-plane tail: all-or-nothing — a torn tail degrades to
            // the legacy (unchunked) interpretation instead of failing
            // the whole request
            try {
                uint64_t cb = r.u64();
                std::vector<std::vector<uint64_t>> leaves(s.entries.size());
                for (uint32_t i = 0; i < n; ++i) {
                    uint32_t nl = r.u32();
                    if (nl > (64u << 20) / 8) throw std::runtime_error("leaves");
                    leaves[i].reserve(nl);
                    for (uint32_t j = 0; j < nl; ++j) leaves[i].push_back(r.u64());
                }
                s.chunk_bytes = cb;
                for (uint32_t i = 0; i < n; ++i)
                    s.entries[i].chunk_leaves = std::move(leaves[i]);
            } catch (...) {
                s.chunk_bytes = 0;
                for (auto &e : s.entries) e.chunk_leaves.clear();
            }
        }
        return s;
    } catch (...) { return std::nullopt; }
}

// --- SharedStateSyncResp ---

std::vector<uint8_t> SharedStateSyncResp::encode() const {
    wire::Writer w;
    w.u8(outdated);
    w.u8(failed);
    put_addr(w, dist_ip);
    w.u16(dist_port);
    w.u64(revision);
    w.u32(static_cast<uint32_t>(outdated_keys.size()));
    for (const auto &k : outdated_keys) w.str(k);
    w.u32(static_cast<uint32_t>(expected_hashes.size()));
    for (auto h : expected_hashes) w.u64(h);
    // trailing chunk map (docs/04): seeder directory + per-outdated-key
    // leaf hashes and seeder indices. Older clients stop reading above
    // and use the legacy single-distributor fields.
    if (has_chunk_map) {
        w.u8(1);
        w.u64(chunk_bytes);
        w.u16(dist_p2p_port);
        w.u32(static_cast<uint32_t>(seeders.size()));
        for (const auto &sd : seeders) {
            put_uuid(w, sd.uuid);
            put_addr(w, sd.ip);
            w.u16(sd.ss_port);
            w.u16(sd.p2p_port);
        }
        for (size_t i = 0; i < outdated_keys.size(); ++i) {
            const auto &lv = i < key_leaves.size() ? key_leaves[i]
                                                   : std::vector<uint64_t>{};
            const auto &ks = i < key_seeders.size() ? key_seeders[i]
                                                    : std::vector<uint32_t>{};
            w.u32(static_cast<uint32_t>(lv.size()));
            for (uint64_t h : lv) w.u64(h);
            w.u32(static_cast<uint32_t>(ks.size()));
            for (uint32_t s : ks) w.u32(s);
        }
    }
    return w.take();
}

std::optional<SharedStateSyncResp> SharedStateSyncResp::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        SharedStateSyncResp s;
        s.outdated = r.u8();
        s.failed = r.u8();
        s.dist_ip = get_addr(r);
        s.dist_port = r.u16();
        s.revision = r.u64();
        uint32_t n = r.u32();
        for (uint32_t i = 0; i < n; ++i) s.outdated_keys.push_back(r.str());
        uint32_t m = r.u32();
        for (uint32_t i = 0; i < m; ++i) s.expected_hashes.push_back(r.u64());
        if (!r.done()) {
            // chunk-map tail, all-or-nothing like the C2M tail
            try {
                SharedStateSyncResp t = s;
                t.has_chunk_map = r.u8();
                t.chunk_bytes = r.u64();
                t.dist_p2p_port = r.u16();
                uint32_t ns = r.u32();
                if (ns > 65536) throw std::runtime_error("seeders");
                for (uint32_t i = 0; i < ns; ++i) {
                    SeederRec sd;
                    sd.uuid = get_uuid(r);
                    sd.ip = get_addr(r);
                    sd.ss_port = r.u16();
                    sd.p2p_port = r.u16();
                    t.seeders.push_back(sd);
                }
                for (uint32_t i = 0; i < n; ++i) {
                    uint32_t nl = r.u32();
                    if (nl > (64u << 20) / 8) throw std::runtime_error("leaves");
                    std::vector<uint64_t> lv;
                    lv.reserve(nl);
                    for (uint32_t j = 0; j < nl; ++j) lv.push_back(r.u64());
                    uint32_t nk = r.u32();
                    if (nk > ns) throw std::runtime_error("key seeders");
                    std::vector<uint32_t> ks;
                    ks.reserve(nk);
                    for (uint32_t j = 0; j < nk; ++j) {
                        uint32_t idx = r.u32();
                        // index-bounds-validated: a bad index must not
                        // become an out-of-range seeder dereference
                        if (idx >= ns) throw std::runtime_error("seeder idx");
                        ks.push_back(idx);
                    }
                    t.key_leaves.push_back(std::move(lv));
                    t.key_seeders.push_back(std::move(ks));
                }
                if (t.has_chunk_map) s = std::move(t);
            } catch (...) {
                s.has_chunk_map = 0;
                s.seeders.clear();
                s.key_leaves.clear();
                s.key_seeders.clear();
            }
        }
        return s;
    } catch (...) { return std::nullopt; }
}

// --- SyncKeyDoneC2M / SeederUpdateM2C (chunk plane, docs/04) ---

std::vector<uint8_t> SyncKeyDoneC2M::encode() const {
    wire::Writer w;
    w.u64(revision);
    w.str(key);
    return w.take();
}

std::optional<SyncKeyDoneC2M> SyncKeyDoneC2M::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        SyncKeyDoneC2M s;
        s.revision = r.u64();
        s.key = r.str();
        return s;
    } catch (...) { return std::nullopt; }
}

std::vector<uint8_t> SeederUpdateM2C::encode() const {
    wire::Writer w;
    w.u64(revision);
    w.str(key);
    put_uuid(w, seeder.uuid);
    put_addr(w, seeder.ip);
    w.u16(seeder.ss_port);
    w.u16(seeder.p2p_port);
    return w.take();
}

std::optional<SeederUpdateM2C> SeederUpdateM2C::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        SeederUpdateM2C s;
        s.revision = r.u64();
        s.key = r.str();
        s.seeder.uuid = get_uuid(r);
        s.seeder.ip = get_addr(r);
        s.seeder.ss_port = r.u16();
        s.seeder.p2p_port = r.u16();
        return s;
    } catch (...) { return std::nullopt; }
}

// --- ScheduleUpdateM2C (schedule plane, docs/12) ---

std::vector<uint8_t> ScheduleUpdateM2C::encode() const {
    wire::Writer w;
    w.u32(group);
    w.bytes(table);
    return w.take();
}

std::optional<ScheduleUpdateM2C> ScheduleUpdateM2C::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        ScheduleUpdateM2C s;
        s.group = r.u32();
        s.table = r.bytes();
        return s;
    } catch (...) { return std::nullopt; }
}

// --- OptimizeResponse ---

std::vector<uint8_t> OptimizeResponse::encode() const {
    wire::Writer w;
    w.u8(complete);
    w.u32(static_cast<uint32_t>(requests.size()));
    for (const auto &q : requests) {
        put_uuid(w, q.to);
        put_addr(w, q.ip);
        w.u16(q.bench_port);
    }
    return w.take();
}

std::optional<OptimizeResponse> OptimizeResponse::decode(const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        OptimizeResponse o;
        o.complete = r.u8();
        uint32_t n = r.u32();
        for (uint32_t i = 0; i < n; ++i) {
            BenchRequest q;
            q.to = get_uuid(r);
            q.ip = get_addr(r);
            q.bench_port = r.u16();
            o.requests.push_back(q);
        }
        return o;
    } catch (...) { return std::nullopt; }
}

// --- TelemetryDigestC2M ---

namespace {

// sparse histogram blob: u64 sum, u8 n, n x (u8 idx, u64 count).
// n and every idx are bounded by the fixed log2 grid.
constexpr uint8_t kWireHistBuckets = 26;
// growing the telemetry grid without widening the wire bound would make
// get_hist reject every digest carrying the new buckets — the fleet view
// would silently go stale with no diagnostic
static_assert(kWireHistBuckets == telemetry::kHistBuckets,
              "wire histogram grid must match telemetry::kHistBuckets");
// the decode bound below accepts phase ids <= 16 (looser than kPhaseCount
// on purpose: a newer peer's extra phases are dropped at the fold, not
// rejected) — but if the Phase enum itself outgrows the wire bound, every
// digest from a new peer is rejected wholesale and the fleet view goes
// silently stale
static_assert(telemetry::kPhaseCount <= 17,
              "Phase outgrew the digest decode bound (phase > 16): widen "
              "the wire bound in TelemetryDigestC2M::decode in lockstep");

void put_hist(wire::Writer &w, const WireHist &h) {
    w.u64(h.sum_ns);
    w.u8(static_cast<uint8_t>(h.buckets.size()));
    for (const auto &[idx, count] : h.buckets) {
        w.u8(idx);
        w.u64(count);
    }
}

// throws on structural damage (via Reader); returns nullopt on a blob
// that parses but violates the grid bounds
std::optional<WireHist> get_hist(wire::Reader &r) {
    WireHist h;
    h.sum_ns = r.u64();
    uint8_t n = r.u8();
    if (n > kWireHistBuckets) return std::nullopt;
    for (uint8_t i = 0; i < n; ++i) {
        uint8_t idx = r.u8();
        uint64_t count = r.u64();
        if (idx >= kWireHistBuckets) return std::nullopt;
        h.buckets.emplace_back(idx, count);
    }
    return h;
}

} // namespace

std::vector<uint8_t> TelemetryDigestC2M::encode() const {
    wire::Writer w;
    w.u64(epoch);
    w.u64(last_seq);
    w.u64(interval_ms);
    w.u64(ring_dropped);
    w.u64(collectives_ok);
    w.u32(static_cast<uint32_t>(edges.size()));
    for (const auto &e : edges) {
        w.str(e.endpoint);
        w.f64(e.tx_mbps);
        w.f64(e.rx_mbps);
        w.f64(e.stall_ratio);
        w.u64(e.tx_bytes);
        w.u64(e.rx_bytes);
        w.u8(e.wd_state);
    }
    w.u32(static_cast<uint32_t>(ops.size()));
    for (const auto &o : ops) {
        w.u64(o.seq);
        w.u64(o.dur_ns);
        w.u64(o.stall_ns);
    }
    // trailing attribution section (decoders without it stop above)
    w.u64(ring_pushed);
    w.u64(ring_cap);
    w.u8(static_cast<uint8_t>(phase_hists.size()));
    for (const auto &[phase, h] : phase_hists) {
        w.u8(phase);
        put_hist(w, h);
    }
    // per-edge hists, parallel to `edges` by index (same count, in order)
    for (const auto &e : edges) {
        put_hist(w, e.stage_wire_hist);
        put_hist(w, e.stall_hist);
    }
    return w.take();
}

namespace {

// digest floats feed the master's /metrics text and /health JSON, and the
// endpoint string becomes a Prometheus label: reject anything a renderer
// could choke on (NaN/Inf are invalid JSON; quotes/newlines/backslashes
// corrupt the label set). Endpoints are Addr::str() output — ip:port.
bool valid_rate(double v) { return std::isfinite(v) && v >= 0; }

bool valid_endpoint(const std::string &s) {
    if (s.empty() || s.size() > 63) return false;
    for (char c : s)
        if (!isalnum(static_cast<unsigned char>(c)) && c != '.' && c != ':' &&
            c != '[' && c != ']' && c != '%' && c != '-')
            return false;
    return true;
}

} // namespace

std::optional<TelemetryDigestC2M> TelemetryDigestC2M::decode(
    const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        TelemetryDigestC2M d;
        d.epoch = r.u64();
        d.last_seq = r.u64();
        d.interval_ms = r.u64();
        d.ring_dropped = r.u64();
        d.collectives_ok = r.u64();
        uint32_t ne = r.u32();
        // sanity bounds: a digest describes one peer's live edges and a
        // tiny op ring — a count beyond these is a corrupt/hostile frame,
        // not a bigger fleet
        if (ne > 4096) return std::nullopt;
        for (uint32_t i = 0; i < ne; ++i) {
            Edge e;
            e.endpoint = r.str();
            e.tx_mbps = r.f64();
            e.rx_mbps = r.f64();
            e.stall_ratio = r.f64();
            e.tx_bytes = r.u64();
            e.rx_bytes = r.u64();
            e.wd_state = r.u8();
            if (!valid_endpoint(e.endpoint) || !valid_rate(e.tx_mbps) ||
                !valid_rate(e.rx_mbps) || !valid_rate(e.stall_ratio) ||
                e.wd_state > 2)
                return std::nullopt;
            d.edges.push_back(std::move(e));
        }
        uint32_t no = r.u32();
        if (no > 256) return std::nullopt;
        for (uint32_t i = 0; i < no; ++i) {
            Op o;
            o.seq = r.u64();
            o.dur_ns = r.u64();
            o.stall_ns = r.u64();
            d.ops.push_back(o);
        }
        // trailing attribution section: absent on older peers (clean EOF
        // right here), malformed content still rejects the frame
        bool has_tail = true;
        try {
            d.ring_pushed = r.u64();
        } catch (...) { has_tail = false; }
        if (has_tail) {
            d.ring_cap = r.u64();
            uint8_t np = r.u8();
            if (np > 16) return std::nullopt; // telemetry::kPhaseCount is 7
            for (uint8_t i = 0; i < np; ++i) {
                uint8_t phase = r.u8();
                auto h = get_hist(r);
                if (!h || phase > 16) return std::nullopt;
                d.phase_hists.emplace_back(phase, std::move(*h));
            }
            for (auto &e : d.edges) {
                auto hw = get_hist(r);
                auto hs = get_hist(r);
                if (!hw || !hs) return std::nullopt;
                e.stage_wire_hist = std::move(*hw);
                e.stall_hist = std::move(*hs);
            }
        }
        return d;
    } catch (...) { return std::nullopt; }
}

// --- IncidentDumpM2C ---

std::vector<uint8_t> IncidentDumpM2C::encode() const {
    wire::Writer w;
    w.str(incident_id);
    w.str(trigger);
    w.u64(epoch);
    return w.take();
}

std::optional<IncidentDumpM2C> IncidentDumpM2C::decode(
    const std::vector<uint8_t> &b) {
    try {
        wire::Reader r(b);
        IncidentDumpM2C d;
        d.incident_id = r.str();
        d.trigger = r.str();
        d.epoch = r.u64();
        // the id becomes a directory name on every peer: refuse anything
        // that could traverse or hide ("" / separators / dotfiles)
        if (d.incident_id.empty() || d.incident_id.size() > 128 ||
            d.incident_id[0] == '.')
            return std::nullopt;
        for (char c : d.incident_id)
            if (!isalnum(static_cast<unsigned char>(c)) && c != '-' &&
                c != '_')
                return std::nullopt;
        return d;
    } catch (...) { return std::nullopt; }
}

} // namespace pcclt::proto
