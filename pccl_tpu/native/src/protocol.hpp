// PCCP wire protocol: packet ids + typed payload serializers.
//
// Reference parity: the 7 packet families of CCoIP
// (/root/reference/ccoip/internal/ccoip_packets.hpp) — C2M/M2C for
// control, P2P handshake, C2S/S2C shared-state distribution, benchmark
// handshake. Re-designed: ids are grouped by direction nibble, payloads are
// written with the big-endian wire::Writer rather than per-packet classes.
#pragma once

#include <array>
#include <cstdint>
#include <optional>
#include <string>
#include <vector>

#include "net_addr.hpp" // PCCP/2 wire addresses carry the family
#include "wire.hpp"

namespace pcclt::proto {

using Uuid = std::array<uint8_t, 16>;

std::string uuid_str(const Uuid &u);
Uuid uuid_random();

inline void put_uuid(wire::Writer &w, const Uuid &u) { w.raw(u.data(), 16); }
inline Uuid get_uuid(wire::Reader &r) {
    Uuid u;
    for (auto &b : u) b = r.u8();
    return u;
}

enum PacketType : uint16_t {
    // client -> master
    kC2MHello = 0x1001,
    kC2MTopologyUpdate = 0x1002,
    kC2MPeersPendingQuery = 0x1003,
    kC2MP2PEstablished = 0x1004,
    kC2MCollectiveInit = 0x1005,
    kC2MCollectiveComplete = 0x1006,
    kC2MSharedStateSync = 0x1007,
    kC2MSharedStateDistDone = 0x1008,
    kC2MOptimizeTopology = 0x1009,
    kC2MBandwidthReport = 0x100A,
    kC2MOptimizeWorkDone = 0x100B,
    // re-attach under an existing UUID after a master restart (HA resume;
    // only honored when the restarted master rehydrated this session from
    // its journal — see journal.hpp and docs/10_high_availability.md)
    kC2MSessionResume = 0x100C,
    // fire-and-forget telemetry digest (fleet observability plane, docs/09):
    // per-edge EWMA throughput/stall + last-N op timings pushed on the
    // PCCLT_TELEMETRY_PUSH_MS cadence; the master folds these into its
    // fleet health model (/metrics, /health, straggler detection). Never
    // answered — a slow master must not back-pressure the data plane.
    kC2MTelemetryDigest = 0x100D,
    // chunk plane (docs/04): an outdated peer finished verifying every
    // chunk of one key mid-round — the master promotes it to a seeder for
    // that key and broadcasts kM2CSeederUpdate, so late fetchers scale
    // ~O(1/peers) instead of hammering the original seeders. Never
    // answered (the fetch engine must not block on the control plane).
    kC2MSyncKeyDone = 0x100E,

    // master -> client
    kM2CWelcome = 0x2001,
    kM2CPeersPendingReply = 0x2002,
    kM2CP2PConnInfo = 0x2003,
    kM2CP2PEstablishedResp = 0x2004,
    kM2CCollectiveCommence = 0x2005,
    kM2CCollectiveAbort = 0x2006,
    kM2CCollectiveDone = 0x2007,
    kM2CSharedStateSyncResp = 0x2008,
    kM2CSharedStateDone = 0x2009,
    kM2COptimizeResponse = 0x200A,
    kM2COptimizeComplete = 0x200B,
    kM2CKicked = 0x200C,
    // topology vote declined because the voter's group is mid-collective /
    // mid-sync commence: a parked voter can never join that round, and the
    // round can never complete while the vote holds members back — the
    // voter's update_topology returns no-op and the app's admit-pending
    // loop retries after its next collective (deadlock tie-break; see
    // MasterState::defer_topology_voters)
    kM2CTopologyDeferred = 0x200D,
    kM2CSessionResumeAck = 0x200E,
    // fire-and-forget black-box capture order (incident plane, docs/09):
    // broadcast by the master when an incident trigger fires (collective
    // abort, kick, watchdog CONFIRM, limbo expiry) and PCCLT_INCIDENT_DIR
    // is set. Each peer writes its trace ring + stats snapshot under the
    // shared incident id; never answered and rate-limited master-side so
    // a flapping edge cannot spam disk.
    kM2CIncidentDump = 0x200F,
    // chunk plane: a peer was promoted to seeder for (revision, key)
    // mid-round. Fire-and-forget broadcast to the syncing group; fetch
    // engines fold the new source in, idle receivers drain and drop it.
    kM2CSeederUpdate = 0x2010,
    // schedule plane (docs/12): the group's synthesized collective
    // schedule table changed (new version after optimize-topology).
    // Fire-and-forget broadcast; the per-op binding truth stays the
    // commence stamp, so a late or lost update can never split the group.
    kM2CScheduleUpdate = 0x2011,

    // p2p handshake
    kP2PHello = 0x3001,
    kP2PHelloAck = 0x3002,

    // shared-state distribution
    kC2SStateRequest = 0x4001,
    kS2CStateHeader = 0x4002,
    // chunk plane (docs/04): request a contiguous chunk range of one key
    // at one revision from a seeder's serve window; the seeder answers
    // kS2CChunkHeader{status, payload_bytes} followed by the raw chunk
    // bytes. Connections are persistent — a fetch worker issues many
    // requests over one socket.
    kC2SChunkRequest = 0x4003,
    kS2CChunkHeader = 0x4004,

    // bandwidth benchmark handshake
    kBenchHello = 0x5001,
    kBenchAck = 0x5002, // {accepted u8} — busy-rejection
};

// dtypes shared across API / wire / kernels
enum class DType : uint8_t {
    kU8 = 0, kI8, kU16, kI16, kU32, kI32, kU64, kI64, kF16, kBF16, kF32, kF64
};
size_t dtype_size(DType d);

// kGather: not a reduction — the all-gather collective rides the same
// consensus/abort machinery with this op id (pcclt extension; the
// reference lists All-Gather as unshipped roadmap work).
// kReduceScatter/kBroadcast/kAllToAll (docs/12): the widened collective
// vocabulary; reduce-scatter reduces with SUM, broadcast/all-to-all move
// bytes unreduced. They share the init/commence consensus, tags and abort
// machinery with the all-reduce.
enum class RedOp : uint8_t {
    kSum = 0, kAvg, kProd, kMax, kMin, kGather,
    kReduceScatter = 6, kBroadcast = 7, kAllToAll = 8
};
enum class QuantAlgo : uint8_t { kNone = 0, kMinMax, kZeroPointScale };
enum class SyncStrategy : uint8_t { kEnforcePopular = 0, kRxOnly, kTxOnly };

// --- typed payloads for the structured packets ---

// PCCP wire revision. Rev 2: family-tagged addresses in every
// address-carrying packet, and this byte LEADING the hello so the master
// can kick a mismatched client with a clear error instead of misparsing
// its packets (a rev-1 client's first hello byte lands here as 0).
inline constexpr uint8_t kWireRev = 2;

struct HelloC2M {
    uint8_t wire_rev = kWireRev;
    uint32_t peer_group = 0;
    uint16_t p2p_port = 0, ss_port = 0, bench_port = 0;
    std::string adv_ip; // empty = use source address of the connection
    // optional trailing byte (tail-tolerant, PCCP/2-compatible both ways):
    // 1 = telemetry-only observer session — may push digests, never joins
    // the world (digest bots, external monitors). Old masters ignore the
    // extra byte; old clients simply never send it (decodes as 0).
    uint8_t observer = 0;
    std::vector<uint8_t> encode() const;
    static std::optional<HelloC2M> decode(const std::vector<uint8_t> &);
};

// Session resume after a master restart (HA). The client re-presents its
// UUID plus the last shared-state revision it saw complete; a journaled
// master that rehydrated this session re-binds it (same UUID, same
// membership, ring preserved) instead of forcing a fresh registration.
struct SessionResumeC2M {
    Uuid uuid{};
    uint64_t last_revision = 0;
    uint16_t p2p_port = 0, ss_port = 0, bench_port = 0; // re-advertised
    std::string adv_ip;
    std::vector<uint8_t> encode() const;
    static std::optional<SessionResumeC2M> decode(const std::vector<uint8_t> &);
};

struct SessionResumeAck {
    uint8_t ok = 0;           // 0 = unknown session (client must re-register)
    uint64_t epoch = 0;       // master epoch (bumped on every restart)
    uint64_t last_revision = 0; // master's view of the group revision
    std::string reason;       // diagnostic on rejection
    std::vector<uint8_t> encode() const;
    static std::optional<SessionResumeAck> decode(const std::vector<uint8_t> &);
};

struct PeerEndpoint {
    Uuid uuid{};
    net::Addr ip{}; // family-tagged; port field unused (ports below)
    uint16_t p2p_port = 0;
    uint16_t bench_port = 0;
    uint32_t peer_group = 0;
};

struct P2PConnInfo {
    uint64_t revision = 0;
    std::vector<PeerEndpoint> peers; // everyone else in my group's world
    std::vector<Uuid> ring;          // group ring order (includes self)
    // trailing (tail-tolerant): the group's current synthesized schedule
    // table, sched::Table::encode() bytes — empty = none yet / old master.
    // Rides the same packet as the ring order so a rejoining peer adopts
    // both in one epoch-safe step.
    std::vector<uint8_t> sched;
    std::vector<uint8_t> encode() const;
    static std::optional<P2PConnInfo> decode(const std::vector<uint8_t> &);
};

struct CollectiveInit {
    uint64_t tag = 0;
    uint64_t count = 0;
    DType dtype = DType::kF32;
    RedOp op = RedOp::kSum;
    QuantAlgo quant = QuantAlgo::kNone;
    DType quant_dtype = DType::kU8;
    // RETRY of an op whose previous attempt died with the master session
    // (set by the client library, not the app), plus the seq that attempt
    // observed at commence (0 = it never saw a commence). Only a
    // retry-flagged init whose retry_seq MATCHES the journaled completed
    // op may be answered by a verdict REPLAY after a master restart: tags
    // are app-reused across steps, so neither the tag nor the bare retry
    // flag identifies the op incarnation — a genuine lost-Done retrier
    // always knows the seq (completion implies its commence was
    // delivered). Trailing on the wire; absent (older client) decodes 0.
    uint8_t retry = 0;
    uint64_t retry_seq = 0;
    // collective-specific argument, trailing (absent decodes 0): the
    // broadcast root SLOT (sorted-uuid order). Part of the group's
    // matched-parameters contract — a mismatch kicks like count/dtype/op.
    uint64_t aux = 0;
    std::vector<uint8_t> encode() const;
    static std::optional<CollectiveInit> decode(const std::vector<uint8_t> &);
};

struct SharedStateEntryMeta {
    std::string name;
    DType dtype = DType::kF32;
    uint64_t count = 0;
    uint8_t allow_content_inequality = 0;
    // chunk plane ON (request carries chunk_bytes > 0): the root of the
    // entry's chunk hash tree (ssc::root_hash over chunk_leaves) — the
    // leaves subsume the old whole-entry digest. Device-precomputed
    // entries keep their on-device whole-array digest and ship no leaves
    // (their dirty keys take the legacy transport).
    uint64_t hash = 0;
    // per-chunk content hashes; empty = unchunked (trailing on the wire,
    // absent from older clients)
    std::vector<uint64_t> chunk_leaves;
};

struct SharedStateSyncC2M {
    uint64_t revision = 0;
    SyncStrategy strategy = SyncStrategy::kEnforcePopular;
    std::vector<SharedStateEntryMeta> entries;
    // chunk size the leaves were computed with; 0 = chunk plane off.
    // Must agree group-wide (like PCCLT_SS_HASH): the root hash of
    // identical content depends on it. Trailing on the wire.
    uint64_t chunk_bytes = 0;
    std::vector<uint8_t> encode() const;
    static std::optional<SharedStateSyncC2M> decode(const std::vector<uint8_t> &);
};

// One peer that already holds the popular revision of some key: where to
// fetch chunks from (ss_port) and the canonical data-plane endpoint the
// wire emulation / telemetry key the edge by (ip + p2p_port).
struct SeederRec {
    Uuid uuid{};
    net::Addr ip{};
    uint16_t ss_port = 0;
    uint16_t p2p_port = 0;
};

struct SharedStateSyncResp {
    uint8_t outdated = 0;
    uint8_t failed = 0; // round could not elect a distributor at the expected revision
    net::Addr dist_ip{}; // family-tagged; port carried in dist_port
    uint16_t dist_port = 0;
    uint64_t revision = 0;
    std::vector<std::string> outdated_keys;
    std::vector<uint64_t> expected_hashes; // parallel to outdated_keys
    // ---- chunk map (trailing; absent from an older master = legacy) ----
    // has_chunk_map gates the whole section. key_leaves / key_seeders are
    // parallel to outdated_keys; key_seeders holds indices into seeders.
    // A key with no leaves (device-hash entry) falls back to the legacy
    // single-distributor transport; its expected hash still verifies.
    uint8_t has_chunk_map = 0;
    uint64_t chunk_bytes = 0;
    uint16_t dist_p2p_port = 0; // legacy path's netem/telemetry edge key
    std::vector<SeederRec> seeders;
    std::vector<std::vector<uint64_t>> key_leaves;
    std::vector<std::vector<uint32_t>> key_seeders;
    std::vector<uint8_t> encode() const;
    static std::optional<SharedStateSyncResp> decode(const std::vector<uint8_t> &);
};

// kC2MSyncKeyDone: fetcher completed (verified) every chunk of `key` at
// `revision` and can serve it for the rest of the round.
struct SyncKeyDoneC2M {
    uint64_t revision = 0;
    std::string key;
    std::vector<uint8_t> encode() const;
    static std::optional<SyncKeyDoneC2M> decode(const std::vector<uint8_t> &);
};

// kM2CSeederUpdate: mid-round seeder promotion broadcast.
struct SeederUpdateM2C {
    uint64_t revision = 0;
    std::string key;
    SeederRec seeder;
    std::vector<uint8_t> encode() const;
    static std::optional<SeederUpdateM2C> decode(const std::vector<uint8_t> &);
};

// kM2CScheduleUpdate: fire-and-forget broadcast of a group's new
// synthesized schedule table (docs/12). `table` is sched::Table::encode()
// bytes; the receiver adopts it for introspection/telemetry only — the
// per-op algorithm binding is the commence stamp.
struct ScheduleUpdateM2C {
    uint32_t group = 0;
    std::vector<uint8_t> table;
    std::vector<uint8_t> encode() const;
    static std::optional<ScheduleUpdateM2C> decode(const std::vector<uint8_t> &);
};

// Telemetry digest (fleet observability plane). Compact by construction:
// one fixed-size record per live edge (edge count = ring degree, not
// world size) plus at most kOpRing op samples — a digest stays well under
// a KiB even on wide worlds, so the default cadence costs nothing
// next to a single data frame.
// Latency histogram on the wire (critical-path attribution, docs/09):
// sparse (index, count) pairs over the fixed log2 bucket grid — a hist
// with k nonzero buckets costs 9k+9 bytes, bounded by the grid size, so
// the digest stays compact even with every phase populated.
struct WireHist {
    uint64_t sum_ns = 0;
    std::vector<std::pair<uint8_t, uint64_t>> buckets; // (bucket idx, count)
    bool empty() const { return buckets.empty(); }
};

struct TelemetryDigestC2M {
    uint64_t epoch = 0;         // master epoch the client observes
    uint64_t last_seq = 0;      // newest collective seq completed
    uint64_t interval_ms = 0;   // wall time this digest folds
    uint64_t ring_dropped = 0;  // flight-recorder events lost to wrap
    uint64_t collectives_ok = 0;
    struct Edge {
        std::string endpoint;   // canonical "ip:port" (netem/telemetry key)
        double tx_mbps = 0, rx_mbps = 0, stall_ratio = 0;
        uint64_t tx_bytes = 0, rx_bytes = 0;
        // data-plane watchdog verdict (telemetry::EdgeHealth): 0 ok /
        // 1 suspect / 2 confirmed. A CONFIRMED report short-circuits the
        // master's rate-based straggler detector — the peer is already
        // relaying around the edge, so the background re-opt fires now.
        uint8_t wd_state = 0;
        // cumulative per-edge latency distributions (stage wall / stall)
        WireHist stage_wire_hist, stall_hist;
    };
    std::vector<Edge> edges;
    struct Op {
        uint64_t seq = 0, dur_ns = 0, stall_ns = 0;
    };
    std::vector<Op> ops;
    // trailing attribution section (older peers simply omit it):
    // flight-recorder ring accounting + comm-level phase histograms
    // keyed by telemetry::Phase values (u8 on the wire)
    uint64_t ring_pushed = 0;
    uint64_t ring_cap = 0;
    std::vector<std::pair<uint8_t, WireHist>> phase_hists;
    std::vector<uint8_t> encode() const;
    static std::optional<TelemetryDigestC2M> decode(const std::vector<uint8_t> &);
};

// Black-box capture order (kM2CIncidentDump, docs/09 incident plane).
struct IncidentDumpM2C {
    std::string incident_id; // shared bundle key ("inc-e<epoch>-<n>")
    std::string trigger;     // what fired: collective_abort / kick / ...
    uint64_t epoch = 0;      // master epoch at the trigger
    std::vector<uint8_t> encode() const;
    static std::optional<IncidentDumpM2C> decode(const std::vector<uint8_t> &);
};

struct BenchRequest {
    Uuid to{};
    net::Addr ip{}; // family-tagged; port carried in bench_port
    uint16_t bench_port = 0;
};

struct OptimizeResponse {
    uint8_t complete = 0;
    std::vector<BenchRequest> requests;
    std::vector<uint8_t> encode() const;
    static std::optional<OptimizeResponse> decode(const std::vector<uint8_t> &);
};

} // namespace pcclt::proto
