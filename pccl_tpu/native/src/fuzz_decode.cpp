// pcclt_fuzz — structure-aware wire-decode fuzzing (docs/11, layer 5).
//
// Every byte sequence a peer can hand us must decode-or-reject: no crash,
// no UB, no out-of-bounds read, and every successful decode must be a
// fixed point of the encode<->decode pair (decode(encode(v)) re-encodes
// to identical bytes). This binary drives EVERY wire decoder in the tree
// against adversarial input:
//
//   * the 13 proto::* control-plane payload decoders (protocol.hpp);
//   * net::FrameHeader::parse — the 21-byte data-plane frame preamble
//     rx_loop trusts before reading a payload;
//   * sched::Table::decode — the journaled schedule table (docs/12);
//   * ssc::ChunkReqSpec::decode — the chunk-range request grammar both
//     serve paths (legacy socket + pooled kChunkReq) share;
//   * the netem env grammars: parse_chaos / parse_map / parse_chaos_map /
//     parse_dur_ns (PCCLT_WIRE_*_MAP, PCCLT_WIRE_CHAOS_MAP).
//
// One binary, two drivers:
//   * libFuzzer (clang, -DPCCLT_LIBFUZZER with -fsanitize=fuzzer):
//     coverage-guided over LLVMFuzzerTestOneInput. The first input byte
//     selects the target decoder, the rest is its payload — one corpus
//     explores the whole decode surface.
//   * standalone (default — gcc ships no libFuzzer): replays any corpus
//     files passed as argv, then runs a deterministic structure-aware
//     sweep: for every wire struct, encode representative instances and
//     (a) check the round-trip fixed point, (b) decode EVERY prefix of
//     the encoding (torn tail: each must decode-or-reject), (c) decode
//     every single-byte corruption, (d) a seeded xorshift garbage pass.
//     Build with PCCLT_BUILD_FLAGS="-fsanitize=address,undefined" to get
//     the memory/UB oracle the sweep is designed for.
//
// `--emit-corpus DIR` writes the sweep's seed encodings as corpus files
// (target byte + payload) for the CI fuzz lane to start from.

#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

#include "log.hpp"
#include "netem.hpp"
#include "protocol.hpp"
#include "schedule.hpp"
#include "sockets.hpp"
#include "ss_chunk.hpp"
#include "wire.hpp"

using namespace pcclt;

namespace {

[[noreturn]] void die(const char *target, const char *what) {
    fprintf(stderr, "pcclt_fuzz: %s: %s\n", target, what);
    abort();  // crash: libFuzzer/ASan harvest the input as a finding
}

// decode(bytes) -> if accepted, encode/decode must reach a fixed point:
// e1 = v.encode(); v2 = decode(e1) must ACCEPT and re-encode to e1.
// (decode(bytes) need not re-encode to `bytes`: trailing optional
// sections are tail-tolerant by design, so garbage tails are dropped.)
template <typename T>
void round_trip(const char *target, const std::vector<uint8_t> &bytes) {
    auto v = T::decode(bytes);
    if (!v) return;
    auto e1 = v->encode();
    auto v2 = T::decode(e1);
    if (!v2) die(target, "re-decode of own encoding rejected");
    if (v2->encode() != e1) die(target, "encode<->decode not a fixed point");
}

void chunk_req_target(const std::vector<uint8_t> &bytes) {
    auto v = ssc::ChunkReqSpec::decode(bytes);
    if (!v) return;
    // the optional p2p tail makes the plain round-trip lossy (a present
    // zero port re-encodes as absent); fix the tail choice and iterate
    auto e1 = v->encode(v->req_p2p != 0);
    auto v2 = ssc::ChunkReqSpec::decode(e1);
    if (!v2) die("chunk_req", "re-decode of own encoding rejected");
    if (v2->encode(v2->req_p2p != 0) != e1)
        die("chunk_req", "encode<->decode not a fixed point");
}

void frame_header_target(const uint8_t *data, size_t size) {
    auto fh = net::FrameHeader::parse(data, size);
    if (!fh) return;
    if (size < net::FrameHeader::kWire)
        die("frame_header", "accepted a short preamble");
    if (fh->payload > net::FrameHeader::kMaxLen - 17)
        die("frame_header", "payload length above the frame cap");
}

void table_target(const std::vector<uint8_t> &bytes) {
    auto t = sched::Table::decode(bytes);
    if (!t) return;
    auto e1 = t->encode();
    auto t2 = sched::Table::decode(e1);
    if (!t2) die("sched_table", "re-decode of own encoding rejected");
    if (t2->encode() != e1) die("sched_table", "encode<->decode not a fixed point");
}

constexpr int kNumTargets = 20;

void one_input(const uint8_t *data, size_t size) {
    if (size == 0) return;
    const int target = data[0] % kNumTargets;
    const uint8_t *p = data + 1;
    const size_t n = size - 1;
    const std::vector<uint8_t> b(p, p + n);
    const std::string s(reinterpret_cast<const char *>(p), n);
    switch (target) {
    case 0: round_trip<proto::HelloC2M>("hello", b); break;
    case 1: round_trip<proto::SessionResumeC2M>("session_resume", b); break;
    case 2: round_trip<proto::SessionResumeAck>("session_resume_ack", b); break;
    case 3: round_trip<proto::P2PConnInfo>("p2p_conn_info", b); break;
    case 4: round_trip<proto::CollectiveInit>("collective_init", b); break;
    case 5: round_trip<proto::SharedStateSyncC2M>("ss_sync", b); break;
    case 6: round_trip<proto::SharedStateSyncResp>("ss_sync_resp", b); break;
    case 7: round_trip<proto::SyncKeyDoneC2M>("sync_key_done", b); break;
    case 8: round_trip<proto::SeederUpdateM2C>("seeder_update", b); break;
    case 9: round_trip<proto::ScheduleUpdateM2C>("schedule_update", b); break;
    case 10: round_trip<proto::TelemetryDigestC2M>("telemetry_digest", b); break;
    case 11: round_trip<proto::IncidentDumpM2C>("incident_dump", b); break;
    case 12: round_trip<proto::OptimizeResponse>("optimize_resp", b); break;
    case 13: frame_header_target(p, n); break;
    case 14: table_target(b); break;
    case 15: chunk_req_target(b); break;
    case 16: net::netem::parse_chaos(s, "fuzz"); break;
    case 17: net::netem::parse_map(s.c_str(), "fuzz"); break;
    case 18: net::netem::parse_chaos_map(s.c_str()); break;
    case 19: net::netem::parse_dur_ns(s); break;
    }
}

}  // namespace

extern "C" int LLVMFuzzerTestOneInput(const uint8_t *data, size_t size) {
    one_input(data, size);
    return 0;
}

#ifndef PCCLT_LIBFUZZER

namespace {

// ------------------------------------------------ structure-aware seeds

struct Seed {
    const char *name;
    uint8_t target;
    std::vector<uint8_t> payload;
};

std::vector<uint8_t> str_bytes(const char *s) {
    return {reinterpret_cast<const uint8_t *>(s),
            reinterpret_cast<const uint8_t *>(s) + strlen(s)};
}

std::vector<Seed> make_seeds() {
    std::vector<Seed> out;
    auto add = [&](const char *name, uint8_t target,
                   std::vector<uint8_t> payload) {
        out.push_back({name, target, std::move(payload)});
    };
    proto::Uuid ua{}, ub{};
    for (int i = 0; i < 16; ++i) { ua[i] = uint8_t(i + 1); ub[i] = uint8_t(0xF0 + i); }
    net::Addr a4 = *net::Addr::parse("10.1.2.3", 0);
    net::Addr a6 = *net::Addr::parse("::1", 0);

    {   // empty-default + populated instance of every proto struct
        proto::HelloC2M v;
        add("hello_default", 0, v.encode());
        v.peer_group = 7; v.p2p_port = 4001; v.ss_port = 4002;
        v.bench_port = 4003; v.adv_ip = "10.1.2.3"; v.observer = 1;
        add("hello", 0, v.encode());
    }
    {
        proto::SessionResumeC2M v;
        v.uuid = ua; v.last_revision = 42; v.p2p_port = 4001;
        v.adv_ip = "10.1.2.3";
        add("session_resume", 1, v.encode());
    }
    {
        proto::SessionResumeAck v;
        v.ok = 1; v.epoch = 3; v.last_revision = 42; v.reason = "rehydrated";
        add("session_resume_ack", 2, v.encode());
    }
    {
        proto::P2PConnInfo v;
        add("p2p_conn_info_empty", 3, v.encode());
        v.revision = 9;
        v.peers.push_back({ua, a4, 4001, 4003, 7});
        v.peers.push_back({ub, a6, 5001, 5003, 7});
        v.ring = {ua, ub};
        sched::Table t;
        t.version = 2;
        t.entries.push_back({0, 2, 0, 0});
        t.entries.push_back({3, 1, 3, 1});
        v.sched = t.encode();
        add("p2p_conn_info", 3, v.encode());
        add("sched_table", 14, t.encode());
    }
    {
        proto::CollectiveInit v;
        v.tag = 77; v.count = 1 << 20; v.retry = 1; v.retry_seq = 5; v.aux = 2;
        add("collective_init", 4, v.encode());
    }
    {
        proto::SharedStateSyncC2M v;
        v.revision = 12;
        proto::SharedStateEntryMeta m;
        m.name = "weights"; m.count = 4096; m.hash = 0xDEADBEEF;
        m.chunk_leaves = {1, 2, 3};
        v.entries.push_back(m);
        v.chunk_bytes = 1 << 20;
        add("ss_sync", 5, v.encode());
    }
    {
        proto::SharedStateSyncResp v;
        add("ss_sync_resp_empty", 6, v.encode());
        v.outdated = 1; v.dist_ip = a4; v.dist_port = 4002; v.revision = 12;
        v.outdated_keys = {"weights", "opt"};
        v.expected_hashes = {0xAA, 0xBB};
        v.has_chunk_map = 1; v.chunk_bytes = 1 << 20; v.dist_p2p_port = 4001;
        v.seeders = {{ua, a4, 4002, 4001}, {ub, a6, 5002, 5001}};
        v.key_leaves = {{1, 2, 3}, {}};
        v.key_seeders = {{0, 1}, {1}};
        add("ss_sync_resp", 6, v.encode());
    }
    {
        proto::SyncKeyDoneC2M v;
        v.revision = 12; v.key = "weights";
        add("sync_key_done", 7, v.encode());
    }
    {
        proto::SeederUpdateM2C v;
        v.revision = 12; v.key = "weights"; v.seeder = {ua, a4, 4002, 4001};
        add("seeder_update", 8, v.encode());
    }
    {
        proto::ScheduleUpdateM2C v;
        v.group = 7;
        sched::Table t;
        t.version = 4;
        t.entries.push_back({1, 0, 1, 3});
        v.table = t.encode();
        add("schedule_update", 9, v.encode());
    }
    {
        proto::TelemetryDigestC2M v;
        add("telemetry_digest_empty", 10, v.encode());
        v.epoch = 3; v.last_seq = 100; v.interval_ms = 500;
        v.collectives_ok = 99;
        proto::TelemetryDigestC2M::Edge e;
        e.endpoint = "10.1.2.3:4001"; e.tx_mbps = 940.5; e.wd_state = 2;
        e.stage_wire_hist.sum_ns = 1234;
        e.stage_wire_hist.buckets = {{3, 10}, {7, 2}};
        v.edges.push_back(e);
        v.ops.push_back({100, 5'000'000, 1'000'000});
        v.ring_pushed = 7; v.ring_cap = 1024;
        proto::WireHist ph;
        ph.sum_ns = 99; ph.buckets = {{1, 1}};
        v.phase_hists = {{2, ph}};
        add("telemetry_digest", 10, v.encode());
    }
    {
        proto::IncidentDumpM2C v;
        v.incident_id = "inc-e3-1"; v.trigger = "collective_abort"; v.epoch = 3;
        add("incident_dump", 11, v.encode());
    }
    {
        proto::OptimizeResponse v;
        v.complete = 0;
        v.requests.push_back({ua, a4, 4003});
        add("optimize_resp", 12, v.encode());
    }
    {   // valid data-plane frame preamble: len = 17 + 8 payload bytes
        wire::Writer w;
        w.u32(17 + 8);
        w.u8(net::MultiplexConn::kRelayFwd);
        w.u64(0x1122334455667788ull);
        w.u64(4096);
        add("frame_header", 13, w.take());
    }
    {
        ssc::ChunkReqSpec v;
        v.revision = 12; v.key = "weights"; v.chunk_bytes = 1 << 20;
        v.first = 3; v.count = 4;
        add("chunk_req", 15, v.encode(false));
        v.req_p2p = 4001;
        add("chunk_req_p2p", 15, v.encode(true));
    }
    add("chaos", 16,
        str_bytes("flap@t=3s:500msx3;degrade@t=10s:100mbit/5s;blackhole:2s"));
    add("map", 17, str_bytes("10.1.2.3:4001=940,10.1.2.4:4001=12.5"));
    add("chaos_map", 18,
        str_bytes("10.1.2.3:4001=flap@t=3s:1sx2,10.1.2.4:4001=degrade:50mbit/2s"));
    add("dur", 19, str_bytes("200ms"));
    return out;
}

// --------------------------------------------------- deterministic sweep

uint64_t g_cases = 0;

void run(const std::vector<uint8_t> &input) {
    one_input(input.data(), input.size());
    ++g_cases;
}

// a known-valid encoding MUST be accepted — prove it, don't just not-crash
// (a decoder that rejects everything passes every robustness test)
void assert_accepts(const Seed &seed) {
    const auto &b = seed.payload;
    bool ok = true;
    switch (seed.target) {
    case 0: ok = proto::HelloC2M::decode(b).has_value(); break;
    case 1: ok = proto::SessionResumeC2M::decode(b).has_value(); break;
    case 2: ok = proto::SessionResumeAck::decode(b).has_value(); break;
    case 3: ok = proto::P2PConnInfo::decode(b).has_value(); break;
    case 4: ok = proto::CollectiveInit::decode(b).has_value(); break;
    case 5: ok = proto::SharedStateSyncC2M::decode(b).has_value(); break;
    case 6: ok = proto::SharedStateSyncResp::decode(b).has_value(); break;
    case 7: ok = proto::SyncKeyDoneC2M::decode(b).has_value(); break;
    case 8: ok = proto::SeederUpdateM2C::decode(b).has_value(); break;
    case 9: ok = proto::ScheduleUpdateM2C::decode(b).has_value(); break;
    case 10: ok = proto::TelemetryDigestC2M::decode(b).has_value(); break;
    case 11: ok = proto::IncidentDumpM2C::decode(b).has_value(); break;
    case 12: ok = proto::OptimizeResponse::decode(b).has_value(); break;
    case 13:
        ok = net::FrameHeader::parse(b.data(), b.size()).has_value();
        break;
    case 14: ok = sched::Table::decode(b).has_value(); break;
    case 15: ok = ssc::ChunkReqSpec::decode(b).has_value(); break;
    default: {  // grammar targets: the valid seed must parse non-empty
        const std::string s(b.begin(), b.end());
        if (seed.target == 16)
            ok = !net::netem::parse_chaos(s, "seed").empty();
        else if (seed.target == 17)
            ok = !net::netem::parse_map(s.c_str(), "seed").empty();
        else if (seed.target == 18)
            ok = !net::netem::parse_chaos_map(s.c_str()).empty();
        else if (seed.target == 19)
            ok = net::netem::parse_dur_ns(s).has_value();
        break;
    }
    }
    if (!ok) die(seed.name, "rejected a known-valid encoding");
}

void sweep() {
    for (const auto &seed : make_seeds()) {
        assert_accepts(seed);
        std::vector<uint8_t> input;
        input.push_back(seed.target);
        input.insert(input.end(), seed.payload.begin(), seed.payload.end());
        // torn tail: every prefix decodes-or-rejects (n == size -> the
        // full input, which also exercises the round-trip fixed point)
        for (size_t n = 0; n <= input.size(); ++n)
            run({input.begin(), input.begin() + n});
        // single-byte corruption at every position
        for (size_t i = 1; i < input.size(); ++i) {
            auto m = input;
            m[i] ^= 0xFF;
            run(m);
        }
        // length-field inflation: smash each u32-aligned window to huge
        for (size_t i = 1; i + 4 <= input.size(); i += 4) {
            auto m = input;
            m[i] = 0xFF; m[i + 1] = 0xFF; m[i + 2] = 0xFF; m[i + 3] = 0xFE;
            run(m);
        }
    }
    // seeded xorshift garbage across all targets (deterministic)
    uint64_t x = 0x9E3779B97F4A7C15ull;
    auto next = [&x] {
        x ^= x << 13; x ^= x >> 7; x ^= x << 17;
        return x;
    };
    for (int t = 0; t < kNumTargets; ++t) {
        for (int rep = 0; rep < 64; ++rep) {
            std::vector<uint8_t> input;
            input.push_back(uint8_t(t));
            size_t len = next() % 96;
            for (size_t i = 0; i < len; ++i) input.push_back(uint8_t(next()));
            run(input);
        }
    }
}

// the seeds double as the CI fuzz lane's starting corpus
int emit_corpus(const char *dir) {
    int wrote = 0;
    for (const auto &seed : make_seeds()) {
        std::string path = std::string(dir) + "/" + seed.name + ".bin";
        std::ofstream f(path, std::ios::binary | std::ios::trunc);
        if (!f) {
            fprintf(stderr, "pcclt_fuzz: cannot write %s\n", path.c_str());
            return 1;
        }
        f.put(char(seed.target));
        f.write(reinterpret_cast<const char *>(seed.payload.data()),
                std::streamsize(seed.payload.size()));
        ++wrote;
    }
    printf("pcclt_fuzz: wrote %d corpus seeds to %s\n", wrote, dir);
    return 0;
}

}  // namespace

int main(int argc, char **argv) {
    // the netem grammars warn on every malformed entry — gag them below
    // ERROR or a sweep emits tens of thousands of expected-reject lines
    // (the env threshold is latched by a static initializer, so set the
    // threshold directly rather than via setenv)
    log::set_threshold(log::Level::kError);
    if (argc == 3 && strcmp(argv[1], "--emit-corpus") == 0)
        return emit_corpus(argv[2]);
    int replayed = 0;
    for (int i = 1; i < argc; ++i) {
        std::ifstream f(argv[i], std::ios::binary);
        if (!f) {
            fprintf(stderr, "pcclt_fuzz: cannot read %s\n", argv[i]);
            return 1;
        }
        std::vector<uint8_t> bytes{std::istreambuf_iterator<char>(f),
                                   std::istreambuf_iterator<char>()};
        run(bytes);
        ++replayed;
    }
    sweep();
    printf("pcclt_fuzz: sweep ok (%" PRIu64 " cases, %d corpus files replayed)\n",
           g_cases, replayed);
    return 0;
}

#endif  // !PCCLT_LIBFUZZER
