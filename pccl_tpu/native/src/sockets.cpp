#include "sockets.hpp"

#include <arpa/inet.h>
#include <cerrno>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <random>

#include "log.hpp"
#include "kernels.hpp"
#include "netem.hpp"
#include "shm.hpp"
#include "telemetry.hpp"
#include "uring.hpp"
#include "wire.hpp"

namespace pcclt::net {

// ---------- Addr ----------

std::string Addr::str() const {
    if (family == 6) {
        char buf[INET6_ADDRSTRLEN];
        inet_ntop(AF_INET6, ip6.data(), buf, sizeof buf);
        return "[" + std::string(buf) + "]:" + std::to_string(port);
    }
    struct in_addr a;
    a.s_addr = htonl(ip);
    char buf[INET_ADDRSTRLEN];
    inet_ntop(AF_INET, &a, buf, sizeof buf);
    return std::string(buf) + ":" + std::to_string(port);
}

std::optional<Addr> Addr::parse(const std::string &ip_str, uint16_t port) {
    struct in_addr a;
    if (inet_pton(AF_INET, ip_str.c_str(), &a) == 1)
        return Addr{ntohl(a.s_addr), port};
    // v6, with or without URL-style brackets
    std::string s = ip_str;
    if (s.size() >= 2 && s.front() == '[' && s.back() == ']')
        s = s.substr(1, s.size() - 2);
    struct in6_addr a6;
    if (inet_pton(AF_INET6, s.c_str(), &a6) == 1) {
        Addr out{0, port, 6};
        memcpy(out.ip6.data(), &a6, 16);
        return out;
    }
    return std::nullopt;
}

// ---------- Socket ----------

bool Socket::connect(const Addr &addr, int timeout_ms) {
    close();
    const bool v6 = addr.family == 6;
    int fd = ::socket(v6 ? AF_INET6 : AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return false;
    struct sockaddr_storage ss{};
    socklen_t salen;
    if (v6) {
        auto *sa6 = reinterpret_cast<sockaddr_in6 *>(&ss);
        sa6->sin6_family = AF_INET6;
        sa6->sin6_port = htons(addr.port);
        memcpy(&sa6->sin6_addr, addr.ip6.data(), 16);
        salen = sizeof(sockaddr_in6);
    } else {
        auto *sa4 = reinterpret_cast<sockaddr_in *>(&ss);
        sa4->sin_family = AF_INET;
        sa4->sin_port = htons(addr.port);
        sa4->sin_addr.s_addr = htonl(addr.ip);
        salen = sizeof(sockaddr_in);
    }

    // non-blocking connect with timeout, then back to blocking
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = ::connect(fd, reinterpret_cast<sockaddr *>(&ss), salen);
    if (rc != 0 && errno != EINPROGRESS) {
        ::close(fd);
        return false;
    }
    if (rc != 0) {
        struct pollfd pfd{fd, POLLOUT, 0};
        rc = ::poll(&pfd, 1, timeout_ms);
        if (rc <= 0) {
            ::close(fd);
            return false;
        }
        int err = 0;
        socklen_t len = sizeof err;
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
        if (err != 0) {
            ::close(fd);
            return false;
        }
    }
    fcntl(fd, F_SETFL, flags);
    fd_ = fd;
    set_nodelay();
    return true;
}

bool Socket::send_all(const void *data, size_t n) {
    auto *p = static_cast<const uint8_t *>(data);
    while (n > 0) {
        int fd = fd_.load();
        if (fd < 0) return false;
        ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
        if (w < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        p += w;
        n -= static_cast<size_t>(w);
    }
    return true;
}

bool Socket::send_all2(const void *a, size_t na, const void *b, size_t nb) {
    // gathered write: header + payload leave in one syscall, no staging copy
    struct iovec iov[2];
    iov[0] = {const_cast<void *>(a), na};
    iov[1] = {const_cast<void *>(b), nb};
    struct msghdr msg{};
    msg.msg_iov = iov;
    msg.msg_iovlen = 2;
    size_t sent = 0, total = na + nb;
    while (sent < total) {
        int fd = fd_.load();
        if (fd < 0) return false;
        ssize_t w = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
        if (w < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        sent += static_cast<size_t>(w);
        // advance the iovec past what was written
        size_t skip = static_cast<size_t>(w);
        while (skip > 0 && msg.msg_iovlen > 0) {
            if (skip >= msg.msg_iov[0].iov_len) {
                skip -= msg.msg_iov[0].iov_len;
                ++msg.msg_iov;
                --msg.msg_iovlen;
            } else {
                msg.msg_iov[0].iov_base =
                    static_cast<uint8_t *>(msg.msg_iov[0].iov_base) + skip;
                msg.msg_iov[0].iov_len -= skip;
                skip = 0;
            }
        }
    }
    return true;
}

bool Socket::recv_all(void *data, size_t n) {
    auto *p = static_cast<uint8_t *>(data);
    while (n > 0) {
        int fd = fd_.load();
        if (fd < 0) return false;
        ssize_t r = ::recv(fd, p, n, 0);
        if (r < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        if (r == 0) return false;
        p += r;
        n -= static_cast<size_t>(r);
    }
    return true;
}

bool Socket::recv_all_deadline(void *data, size_t n, int timeout_ms) {
    auto *p = static_cast<uint8_t *>(data);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    size_t off = 0;
    while (off < n) {
        auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                        deadline - std::chrono::steady_clock::now())
                        .count();
        if (left <= 0) return false;
        ssize_t r = recv_some(p + off, n - off,
                              static_cast<int>(std::min<long long>(left, 200)));
        if (r == -2) continue;  // poll slice elapsed; re-check deadline
        if (r <= 0) return false;
        off += static_cast<size_t>(r);
    }
    return true;
}

ssize_t Socket::recv_some(void *data, size_t n, int timeout_ms) {
    int fd = fd_.load();
    if (fd < 0) return -1;
    struct pollfd pfd{fd, POLLIN, 0};
    int rc = ::poll(&pfd, 1, timeout_ms);
    if (rc == 0) return -2;
    if (rc < 0) return -1;
    ssize_t r = ::recv(fd, data, n, 0);
    return r < 0 ? -1 : r;
}

void Socket::shutdown() {
    int fd = fd_.load();
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
}

void Socket::close() {
    int fd = fd_.exchange(-1);
    if (fd >= 0) ::close(fd);
}

void Socket::set_nodelay() {
    int one = 1;
    setsockopt(fd_.load(), IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
}

void Socket::set_quickack() {
    int fd = fd_.load();
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_QUICKACK, &one, sizeof one);
}

void Socket::set_bufsizes(int bytes) {
    int fd = fd_.load();
    if (fd < 0) return;
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bytes, sizeof bytes);
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bytes, sizeof bytes);
}

void Socket::set_keepalive(int idle_s) {
    int fd = fd_.load();
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof one);
    setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle_s, sizeof idle_s);
    int intvl = 5, cnt = 3;
    setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof intvl);
    setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof cnt);
}

Addr Socket::peer_addr() const {
    struct sockaddr_storage ss{};
    socklen_t len = sizeof ss;
    if (getpeername(fd_.load(), reinterpret_cast<sockaddr *>(&ss), &len) != 0) return {};
    if (ss.ss_family == AF_INET6) {
        auto *sa6 = reinterpret_cast<const sockaddr_in6 *>(&ss);
        const uint8_t *b = sa6->sin6_addr.s6_addr;
        // a v4 client hitting the dual-stack listener arrives v4-mapped
        // (::ffff:a.b.c.d) — report it as the v4 address it is, so master
        // bookkeeping and endpoint distribution stay family-consistent
        static const uint8_t mapped[12] = {0, 0, 0, 0, 0, 0, 0, 0,
                                           0, 0, 0xff, 0xff};
        if (memcmp(b, mapped, 12) == 0) {
            uint32_t v4 = (uint32_t(b[12]) << 24) | (uint32_t(b[13]) << 16) |
                          (uint32_t(b[14]) << 8) | b[15];
            return Addr{v4, ntohs(sa6->sin6_port)};
        }
        Addr out{0, ntohs(sa6->sin6_port), 6};
        memcpy(out.ip6.data(), b, 16);
        return out;
    }
    auto *sa = reinterpret_cast<const sockaddr_in *>(&ss);
    return Addr{ntohl(sa->sin_addr.s_addr), ntohs(sa->sin_port)};
}

bool Socket::peer_is_loopback() const {
    // 127.0.0.0/8 or ::1. Two hosts can never reach each other via
    // loopback, and a loopback connection can never cross a network
    // namespace boundary, so this is a sound same-host test for the CMA
    // fast path. (v4-mapped loopback is already folded to v4 above.)
    Addr a = peer_addr();
    if (a.family == 6) {
        static const uint8_t l6[16] = {0, 0, 0, 0, 0, 0, 0, 0,
                                       0, 0, 0, 0, 0, 0, 0, 1};
        return memcmp(a.ip6.data(), l6, 16) == 0;
    }
    return (a.ip >> 24) == 127;
}

// ---------- control framing ----------

bool send_frame(Socket &s, Mutex &write_mu, uint16_t type,
                std::span<const uint8_t> payload) {
    uint32_t len = static_cast<uint32_t>(2 + payload.size());
    uint8_t hdr[6];
    uint32_t be_len = wire::to_be(len);
    uint16_t be_type = wire::to_be(type);
    memcpy(hdr, &be_len, 4);
    memcpy(hdr + 4, &be_type, 2);
    MutexLock lk(write_mu);
    // gathered write: header + payload in one segment, so control packets
    // don't interact badly with Nagle/delayed-ACK, without a staging copy
    return s.send_all2(hdr, 6, payload.data(), payload.size());
}

// single implementation: timeout_ms < 0 blocks forever (plain recv_all),
// otherwise the whole frame must arrive before the deadline
static std::optional<Frame> recv_frame_impl(Socket &s, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    auto recv_n = [&](uint8_t *dst, size_t n) -> bool {
        if (timeout_ms < 0) return s.recv_all(dst, n);
        size_t off = 0;
        while (off < n) {
            auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                            deadline - std::chrono::steady_clock::now())
                            .count();
            if (left <= 0) return false;
            ssize_t r = s.recv_some(dst + off, n - off,
                                    static_cast<int>(std::min<long long>(left, 200)));
            if (r == -2) continue; // poll slice elapsed; re-check deadline
            if (r <= 0) return false;
            off += static_cast<size_t>(r);
        }
        return true;
    };
    uint8_t hdr[6];
    if (!recv_n(hdr, 6)) return std::nullopt;
    uint32_t be_len;
    uint16_t be_type;
    memcpy(&be_len, hdr, 4);
    memcpy(&be_type, hdr + 4, 2);
    uint32_t len = wire::from_be(be_len);
    if (len < 2 || len > wire::kMaxControlPacket) {
        PLOG(kError) << "recv_frame: bad length " << len;
        return std::nullopt;
    }
    Frame f;
    f.type = wire::from_be(be_type);
    f.payload.resize(len - 2);
    if (!f.payload.empty() && !recv_n(f.payload.data(), f.payload.size()))
        return std::nullopt;
    return f;
}

std::optional<Frame> recv_frame(Socket &s) { return recv_frame_impl(s, -1); }

std::optional<Frame> recv_frame(Socket &s, int timeout_ms) {
    return recv_frame_impl(s, timeout_ms);
}

// ---------- Listener ----------

bool Listener::listen(uint16_t port, int tries, bool loopback_only) {
    for (int i = 0; i < tries; ++i) {
        uint16_t p = static_cast<uint16_t>(port + i);
        int fd = -1;
        // Production listeners are dual-stack: one AF_INET6 socket with
        // V6ONLY off accepts both families (v4 clients appear v4-mapped,
        // folded back to v4 in peer_addr). Falls back to v4-only when the
        // kernel has no v6. loopback_only (a socktest-only knob) stays
        // v4 127.0.0.1 — its callers connect there explicitly.
        if (!loopback_only) {
            fd = ::socket(AF_INET6, SOCK_STREAM, 0);
            if (fd >= 0) {
                int one = 1, zero = 0;
                setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
                // V6ONLY must verifiably turn OFF: a v6-only listener would
                // silently refuse every v4 client (net.ipv6.bindv6only=1
                // hosts), so on failure fall back to the v4 socket instead
                if (setsockopt(fd, IPPROTO_IPV6, IPV6_V6ONLY, &zero,
                               sizeof zero) != 0) {
                    PLOG(kWarn) << "listener: IPV6_V6ONLY=0 refused; "
                                   "using v4-only listener";
                    ::close(fd);
                    fd = -1;
                } else {
                    struct sockaddr_in6 sa6{};
                    sa6.sin6_family = AF_INET6;
                    sa6.sin6_port = htons(p);
                    sa6.sin6_addr = in6addr_any;
                    if (bind(fd, reinterpret_cast<sockaddr *>(&sa6),
                             sizeof sa6) != 0 || ::listen(fd, 64) != 0) {
                        // trace, not warn: callers port-scan (tries up to
                        // 64), so a busy port here is expected noise — the
                        // v4 attempt below fails the same way and the scan
                        // moves to the next port
                        PLOG(kTrace) << "listener: dual-stack bind on port "
                                     << p << " failed (" << strerror(errno)
                                     << ")";
                        ::close(fd);
                        fd = -1;
                    } else {
                        goto bound;
                    }
                }
            }
        }
        fd = ::socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0) return false;
        {
            int one = 1;
            setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
            struct sockaddr_in sa{};
            sa.sin_family = AF_INET;
            sa.sin_port = htons(p);
            sa.sin_addr.s_addr = htonl(loopback_only ? INADDR_LOOPBACK : INADDR_ANY);
            if (bind(fd, reinterpret_cast<sockaddr *>(&sa), sizeof sa) != 0 ||
                ::listen(fd, 64) != 0) {
                ::close(fd);
                continue;
            }
        }
    bound:
        fd_ = fd;
        port_ = p;
        if (port_ == 0) {
            // port 0 = kernel-assigned ephemeral; report the real port so
            // callers can advertise it (family-agnostic: port sits at the
            // same offset in sockaddr_in and sockaddr_in6)
            struct sockaddr_storage bound{};
            socklen_t slen = sizeof bound;
            if (getsockname(fd, reinterpret_cast<sockaddr *>(&bound), &slen) == 0)
                port_ = ntohs(reinterpret_cast<sockaddr_in *>(&bound)->sin_port);
        }
        return true;
    }
    return false;
}

void Listener::run_async(std::function<void(Socket)> on_accept) {
    running_ = true;
    thread_ = std::thread([this, on_accept = std::move(on_accept)] {
        while (running_.load()) {
            struct pollfd pfd{fd_, POLLIN, 0};
            int rc = ::poll(&pfd, 1, 200);
            if (rc < 0 && errno != EINTR) break;
            if (rc <= 0) continue;
            int cfd = ::accept(fd_, nullptr, nullptr);
            if (cfd < 0) continue;
            Socket s(cfd);
            // accepted sockets carry small control replies (commence/abort/
            // done); without NODELAY those hit Nagle+delayed-ACK stalls
            s.set_nodelay();
            on_accept(std::move(s));
        }
    });
}

void Listener::stop() {
    running_ = false;
    if (thread_.joinable()) thread_.join();
    if (fd_ >= 0) {
        ::close(fd_);
        fd_ = -1;
    }
}

// ---------- ControlClient ----------

bool ControlClient::connect(const Addr &addr) {
    if (!sock_.connect(addr)) return false;
    sock_.set_keepalive();
    connected_ = true;
    return true;
}

bool ControlClient::reconnect(const Addr &addr) {
    close(); // joins the old reader; wakes matched-receive waiters
    {
        // drop frames of the dead session: a stale queued packet must never
        // satisfy a post-resume recv_match
        MutexLock lk(mu_);
        queue_.clear();
    }
    // exclude in-flight writers before swapping the socket: a sender that
    // entered send_frame before connected_ flipped could otherwise write the
    // TAIL of its stale frame into the fresh connection, corrupting the
    // resumed session's framing (close() already failed its socket, so the
    // writer exits promptly and we take the lock)
    MutexLock wl(write_mu_);
    sock_ = Socket();
    return connect(addr);
}

void ControlClient::run(std::function<void()> on_disconnect) {
    on_disconnect_ = std::move(on_disconnect);
    reader_ = std::thread([this] {
        while (connected_.load()) {
            auto f = recv_frame(sock_);
            if (!f) break;
            // fire-and-forget notifications never enter the queue: no
            // recv_match will ever consume them, and a leaked frame per
            // push would grow the queue for the session's lifetime
            if (auto it = notify_.find(f->type); it != notify_.end()) {
                it->second(std::move(*f));
                continue;
            }
            {
                MutexLock lk(mu_);
                queue_.push_back(std::move(*f));
            }
            cv_.notify_all();
        }
        bool was = connected_.exchange(false);
        cv_.notify_all();
        if (was && on_disconnect_) on_disconnect_();
    });
}

bool ControlClient::send(uint16_t type, std::span<const uint8_t> payload) {
    if (!connected_.load()) return false;
    return send_frame(sock_, write_mu_, type, payload);
}

std::optional<Frame> ControlClient::recv_match(uint16_t type, const Pred &pred,
                                               int timeout_ms, bool no_wait) {
    // thin adapter over the any-of variant: one wait loop to maintain
    FramePred fp;
    if (pred) fp = [&pred](const Frame &f) { return pred(f.payload); };
    return recv_match_any({type}, fp, timeout_ms, no_wait);
}

std::optional<Frame> ControlClient::scan_queue_any(
    const std::vector<uint16_t> &types, const FramePred &pred) {
    for (auto it = queue_.begin(); it != queue_.end(); ++it) {
        bool type_ok = false;
        for (auto t : types)
            if (it->type == t) type_ok = true;
        if (type_ok && (!pred || pred(*it))) {
            Frame f = std::move(*it);
            queue_.erase(it);
            return f;
        }
    }
    return std::nullopt;
}

std::optional<Frame> ControlClient::recv_match_any(const std::vector<uint16_t> &types,
                                                   const FramePred &pred, int timeout_ms,
                                                   bool no_wait) {
    MutexLock lk(mu_);
    if (auto f = scan_queue_any(types, pred)) return f;
    if (no_wait) return std::nullopt;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    while (connected_.load()) {
        if (timeout_ms < 0) {
            cv_.wait_for(mu_, std::chrono::seconds(1));
        } else if (cv_.wait_until(mu_, deadline) == std::cv_status::timeout) {
            return scan_queue_any(types, pred);
        }
        if (auto f = scan_queue_any(types, pred)) return f;
        if (timeout_ms >= 0 && std::chrono::steady_clock::now() >= deadline)
            return std::nullopt;
    }
    return scan_queue_any(types, pred);
}

void ControlClient::close() {
    connected_ = false;
    sock_.shutdown();
    if (reader_.joinable()) reader_.join();
    sock_.close();
    cv_.notify_all();
}

// ---------- SendState ----------

bool SendState::wait(int timeout_ms) const {
    park::wait_event(ev, timeout_ms,
                     [&] { return status.load(std::memory_order_acquire) != 0; });
    return status.load(std::memory_order_acquire) == 1;
}

// ---------- SinkTable ----------

bool SinkTable::Sink::fully_covered(size_t off, size_t end) const {
    // walk [off, end) against prefix + extents + claims; any gap = false
    size_t at = off;
    while (at < end) {
        if (at < prefix) {
            at = prefix;
            continue;
        }
        size_t next = end;  // nearest covered interval starting at/before at
        bool advanced = false;
        for (const auto *m : {&extents, &claims}) {
            auto it = m->upper_bound(at);
            if (it != m->begin()) {
                auto p = std::prev(it);
                if (p->second > at) {
                    at = p->second;
                    advanced = true;
                    break;
                }
            }
            if (it != m->end()) next = std::min(next, it->first);
        }
        if (!advanced) {
            if (at < next) return false;  // a genuine gap
        }
    }
    return true;
}

size_t SinkTable::Sink::published_overlap(size_t off, size_t end) const {
    // count bytes of [off, end) already covered by prefix + extents.
    // Claims are deliberately excluded: a claim's owner runs this same
    // accounting when its own write publishes, so each byte's FIRST
    // publisher counts zero and every later overlapping publisher counts
    // exactly its overlap — no byte is double-charged.
    size_t overlap = 0;
    size_t at = off;
    while (at < end) {
        size_t covered_to = 0;
        if (at < prefix) covered_to = prefix;
        auto it = extents.upper_bound(at);
        if (it != extents.begin()) {
            auto p = std::prev(it);
            if (p->second > at) covered_to = std::max(covered_to, p->second);
        }
        if (covered_to > at) {
            size_t to = std::min(covered_to, end);
            overlap += to - at;
            at = to;
            continue;
        }
        at = it != extents.end() ? std::min(end, it->first) : end;
    }
    return overlap;
}

size_t SinkTable::place_deduped(Sink &s, uint64_t tag, uint64_t off,
                                const uint8_t *bytes, size_t len) {
    // copy only the gaps the coverage map leaves open. Claimed ranges are
    // skipped WITHOUT publishing an extent over them — the claiming RX
    // thread publishes when its write completes (publishing early would
    // let a consumer read bytes still being written).
    size_t delivered = 0;
    size_t at = off;
    const size_t end = off + len;
    while (at < end) {
        // find the covered interval (prefix/extent/claim) containing `at`
        size_t covered_to = 0;
        if (at < s.prefix) covered_to = s.prefix;
        for (const auto *m : {&s.extents, &s.claims}) {
            auto it = m->upper_bound(at);
            if (it != m->begin()) {
                auto p = std::prev(it);
                if (p->second > at) covered_to = std::max(covered_to, p->second);
            }
        }
        if (covered_to > at) {
            at = std::min(covered_to, end);
            continue;
        }
        // gap starts at `at`: runs to the nearest covered interval start
        size_t gap_end = end;
        for (const auto *m : {&s.extents, &s.claims}) {
            auto it = m->upper_bound(at);
            if (it != m->end()) gap_end = std::min(gap_end, it->first);
        }
        memcpy(s.base + at, bytes + (at - off), gap_end - at);
        s.add_extent(at, gap_end);
        delivered += gap_end - at;
        at = gap_end;
    }
    (void)tag;
    return delivered;
}

bool SinkTable::deliver_window(uint64_t tag, uint64_t off,
                               std::vector<uint8_t> bytes,
                               telemetry::EdgeCounters *origin) {
    const size_t n = bytes.size();
    size_t delivered = 0;
    bool handled = false;
    bool ack_ok = false;
    {
        MutexLock lk(mu_);
        if (is_retired(tag)) {
            handled = true;  // straggler for a finished op: drop + count dup
            ack_ok = true;   // the op is done — its bytes are settled
        } else {
            auto it = sinks_.find(tag);
            if (it != sinks_.end() && !it->second.cancel &&
                off + n <= it->second.cap) {
                delivered = place_deduped(it->second, tag, off, bytes.data(), n);
                handled = true;
                // model-checker finding (relay_vs_direct_deaths): ack only
                // a range that is fully PUBLISHED. Bytes this window
                // skipped because an RX thread holds a mid-write claim are
                // not durable — the claim-holder can die and tear them,
                // and an ack here would let the origin cancel the last
                // copy of those bytes on lying coverage.
                ack_ok = it->second.published_overlap(off, off + n) == n;
            } else if (it == sinks_.end()) {
                // raced ahead of the stage's registration: park it;
                // register_sink drains with the same dedupe + accounting
                relay_pending_.emplace(tag,
                                       PendingRelay{off, std::move(bytes),
                                                    origin});
                ack_ok = true;  // held verbatim until the sink appears
            } else {
                handled = true;  // cancelled/overflow: unwanted, count dup
                // a cancelled sink means the consumer is tossing the op —
                // acking cannot lose bytes anyone still wants; an
                // overflowing window is malformed and must NOT be acked
                ack_ok = it->second.cancel;
            }
        }
    }
    signal_tag(tag);
    if (!handled || !origin) return ack_ok;
    // symmetric with the direct path's rx_bytes: EVERY handled relay byte
    // counts as received, and the not-delivered remainder as duplicate —
    // so rx_bytes + rx_relay_bytes - dup_bytes == unique payload, exactly
    origin->rx_relay_bytes.fetch_add(n, std::memory_order_relaxed);
    origin->rx_relay_windows.fetch_add(1, std::memory_order_relaxed);
    if (delivered < n) {
        origin->dup_bytes.fetch_add(n - delivered, std::memory_order_relaxed);
        if (delivered == 0)
            origin->dup_windows.fetch_add(1, std::memory_order_relaxed);
    }
    return ack_ok;
}

void SinkTable::Sink::add_extent(size_t off, size_t end) {
    if (off <= prefix) {
        prefix = std::max(prefix, end);
        // absorb any queued extents the new prefix reaches
        auto it = extents.begin();
        while (it != extents.end() && it->first <= prefix) {
            prefix = std::max(prefix, it->second);
            it = extents.erase(it);
        }
    } else {
        auto [it, inserted] = extents.try_emplace(off, end);
        if (!inserted) it->second = std::max(it->second, end);
    }
}

void SinkTable::attach(const std::shared_ptr<MultiplexConn> &conn) {
    MutexLock lk(mu_);
    // drop expired members while we're here (conn churn under retries)
    members_.erase(std::remove_if(members_.begin(), members_.end(),
                                  [](const auto &w) { return w.expired(); }),
                   members_.end());
    members_.push_back(conn);
}

void SinkTable::on_conn_dead() { signal_all(); }

void SinkTable::register_sink(uint64_t tag, uint8_t *base, size_t cap,
                              bool consumer_pull) {
    std::vector<PendingDesc> descs;
    {
        MutexLock lk(mu_);
        // un-retire a completed-tag marker (single-tag entries from
        // unregister_sink): re-registration means the tag is live again
        for (auto it = retired_.begin(); it != retired_.end();)
            it = (it->first == tag && it->second == tag + 1)
                     ? retired_.erase(it)
                     : std::next(it);
        Sink s;
        s.base = base;
        s.cap = cap;
        s.consumer_pull = consumer_pull;
        // frames that raced ahead of registration were queued with their
        // offsets; place them now
        auto qit = queues_.find(tag);
        if (qit != queues_.end()) {
            for (auto &qf : qit->second) {
                // queued frames store their offset in the first 8 bytes
                if (qf.size() < 8) continue;
                uint64_t off;
                memcpy(&off, qf.data(), 8);
                size_t n = qf.size() - 8;
                if (off + n <= cap) {
                    memcpy(base + off, qf.data() + 8, n);
                    s.add_extent(off, off + n);
                }
            }
            queues_.erase(qit);
        }
        auto &sink = sinks_[tag] = std::move(s);
        // failover windows that raced this registration: place them now,
        // with the same dedupe + origin accounting as a live delivery
        auto rrange = relay_pending_.equal_range(tag);
        for (auto it = rrange.first; it != rrange.second; ++it) {
            PendingRelay &pr = it->second;
            const size_t n = pr.bytes.size();
            size_t delivered = 0;
            if (!sink.cancel && pr.off + n <= sink.cap)
                delivered =
                    place_deduped(sink, tag, pr.off, pr.bytes.data(), n);
            if (pr.origin) {
                // same received/duplicate split as a live delivery
                pr.origin->rx_relay_bytes.fetch_add(
                    n, std::memory_order_relaxed);
                pr.origin->rx_relay_windows.fetch_add(
                    1, std::memory_order_relaxed);
                if (delivered < n) {
                    pr.origin->dup_bytes.fetch_add(
                        n - delivered, std::memory_order_relaxed);
                    if (delivered == 0)
                        pr.origin->dup_windows.fetch_add(
                            1, std::memory_order_relaxed);
                }
            }
        }
        relay_pending_.erase(rrange.first, rrange.second);
        if (!consumer_pull) {
            auto range = pending_descs_.equal_range(tag);
            for (auto it = range.first; it != range.second; ++it)
                descs.push_back(it->second);
            pending_descs_.erase(range.first, range.second);
        }
        // consumer_pull: pendings stay queued for consume_cma()
    }
    signal_tag(tag);
    // resolve CMA descriptors that arrived before the sink: pull the bytes
    // now, on the registering thread (it is about to wait for them anyway)
    for (auto &d : descs)
        if (auto c = d.ack_conn.lock()) c->do_cma_fill(tag, d);
}

size_t SinkTable::wait_filled(uint64_t tag, size_t min_bytes, int timeout_ms,
                              bool *cma_pending) {
    size_t cur = 0;
    park::wait_event(shard_ev(tag), timeout_ms, [&] {
        MutexLock lk(mu_);
        if (cma_pending && pending_descs_.count(tag)) {
            *cma_pending = true; // a claimable same-host descriptor arrived
            auto it = sinks_.find(tag);
            cur = it == sinks_.end() ? 0 : it->second.prefix;
            return true;
        }
        auto it = sinks_.find(tag);
        if (it == sinks_.end()) {
            cur = 0;
            return true;
        }
        cur = it->second.prefix;
        if (cur >= min_bytes) return true;
        // all member conns dead: the prefix can never grow again — return
        // the short count now instead of sleeping out the full timeout
        // (callers distinguish via Link::alive())
        bool dead = !members_.empty();
        for (auto &w : members_) {
            auto c = w.lock();
            if (c && c->alive()) {
                dead = false;
                break;
            }
        }
        return dead;
    });
    return cur;
}

void SinkTable::wait_not_busy_range(uint64_t lo, uint64_t hi) {
    auto start = std::chrono::steady_clock::now();
    bool killed = false;
    while (true) {
        uint32_t e = ev_.epoch();
        bool busy = false;
        for (auto it = sinks_.lower_bound(lo);
             it != sinks_.end() && it->first < hi; ++it)
            if (it->second.busy > 0) {
                busy = true;
                break;
            }
        if (!busy) return;
        if (!killed &&
            std::chrono::steady_clock::now() - start > std::chrono::seconds(5)) {
            // the writer made no progress at all (genuinely stalled peer):
            // kill the attached sockets so the blocked recv fails promptly
            auto members = members_;
            mu_.unlock();
            for (auto &w : members)
                if (auto c = w.lock()) c->kill_socket();
            mu_.lock();
            killed = true;
        }
        mu_.unlock();
        ev_.wait(e, 100);
        mu_.lock();
    }
}

void SinkTable::unregister_sink(uint64_t tag) {
    MutexLock lk(mu_);
    auto it = sinks_.find(tag);
    if (it == sinks_.end()) return;
    it->second.cancel = true;
    // a FULLY streamed sink retires its tag: any copy arriving later (a
    // zombie direct send whose window the failover already delivered via
    // re-issue/relay) is by definition a duplicate — it must be dropped
    // AND counted, not parked in a queue nobody will ever read (that
    // silently broke the delivered-unique conservation invariant).
    // register_sink un-retires on reuse, so non-op tag reuse stays legal.
    const bool complete =
        it->second.cap > 0 && it->second.prefix >= it->second.cap;
    wait_not_busy_range(tag, tag + 1);
    sinks_.erase(tag);
    if (complete) {
        retired_.emplace_back(tag, tag + 1);
        if (retired_.size() > 512) retired_.pop_front();
    }
}

std::optional<std::vector<uint8_t>> SinkTable::recv_queued(
    uint64_t tag, int timeout_ms, const std::atomic<bool> *abort) {
    auto got = recv_queued_any(tag, timeout_ms, abort);
    if (!got) return std::nullopt;
    return std::move(got->second);
}

std::optional<std::pair<uint64_t, std::vector<uint8_t>>>
SinkTable::recv_queued_any(uint64_t tag, int timeout_ms,
                           const std::atomic<bool> *abort) {
    std::optional<std::pair<uint64_t, std::vector<uint8_t>>> out;
    park::wait_event(shard_ev(tag), timeout_ms, [&] {
        bool dead;
        {
            MutexLock lk(mu_);
            auto it = queues_.find(tag);
            if (it != queues_.end() && !it->second.empty()) {
                auto v = std::move(it->second.front());
                it->second.pop_front();
                // queued frames carry their wire offset in the first 8
                // bytes (host order, written by the RX thread)
                uint64_t off = 0;
                if (v.size() >= 8) {
                    memcpy(&off, v.data(), 8);
                    v.erase(v.begin(), v.begin() + 8);
                }
                out = {off, std::move(v)};
                return true;
            }
            dead = !members_.empty();
            for (auto &w : members_) {
                auto c = w.lock();
                if (c && c->alive()) {
                    dead = false;
                    break;
                }
            }
        }
        if (dead) return true;                  // no frame will ever arrive
        return abort && abort->load();          // caller-requested abort
    });
    return out;
}

void SinkTable::purge_range(uint64_t lo, uint64_t hi) {
    std::vector<PendingDesc> dropped;
    {
        MutexLock lk(mu_);
        for (auto &[tag, s] : sinks_)
            if (tag >= lo && tag < hi) s.cancel = true;
        wait_not_busy_range(lo, hi);
        for (auto it = sinks_.begin(); it != sinks_.end();)
            it = (it->first >= lo && it->first < hi) ? sinks_.erase(it) : std::next(it);
        for (auto it = queues_.begin(); it != queues_.end();)
            it = (it->first >= lo && it->first < hi) ? queues_.erase(it) : std::next(it);
        for (auto it = relay_pending_.begin(); it != relay_pending_.end();)
            it = (it->first >= lo && it->first < hi) ? relay_pending_.erase(it)
                                                     : std::next(it);
        for (auto it = pending_descs_.begin(); it != pending_descs_.end();) {
            if (it->first >= lo && it->first < hi) {
                dropped.push_back(it->second);
                it = pending_descs_.erase(it);
            } else {
                ++it;
            }
        }
        // remember the purge: stragglers for these tags arriving from now on
        // are dropped on receipt (tag ranges are never reused)
        retired_.emplace_back(lo, hi);
        if (retired_.size() > 512) retired_.pop_front();
    }
    // wake every waiter: a consumer parked on a purged tag must notice the
    // missing sink now, not at its next poll slice
    signal_all();
    // ack dropped descriptors so the sender's pending handle completes —
    // the data is unwanted (op aborted), not undeliverable
    for (auto &d : dropped)
        if (auto c = d.ack_conn.lock())
            c->send_ctl(MultiplexConn::kCmaAckDrop, d.tag, d.off);
}

bool SinkTable::is_retired(uint64_t tag) const {
    for (const auto &[lo, hi] : retired_)
        if (tag >= lo && tag < hi) return true;
    return false;
}

// ---------- MultiplexConn ----------

namespace {

size_t env_size(const char *name, size_t dflt) {
    if (const char *e = std::getenv(name)) {
        long long v = atoll(e);
        if (v > 0) return static_cast<size_t>(v);
    }
    return dflt;
}

bool cma_enabled_env() {
    const char *e = std::getenv("PCCLT_CMA");
    return !(e && e[0] == '0');
}

// Wire emulation lives in netem.hpp/.cpp: per-remote-endpoint Edge models
// (egress leaky bucket, RTT/jitter/drop delivery delay) resolved from the
// PCCLT_WIRE_*_MAP env maps with the process-global PCCLT_WIRE_MBPS /
// PCCLT_WIRE_RTT_MS vars as defaults. Every conn resolves its edge at
// construction (re-resolved by set_wire_peer once the peer's canonical
// endpoint is known from the P2P hello) and:
//  * paces every frame write through the edge's bucket — shared by the
//    whole conn pool to that endpoint, so striping cannot manufacture
//    bandwidth, and in a ring each peer's per-edge egress IS its link
//  * delays RX visibility (extent marking / queue delivery + wakeup) by
//    the edge's per-frame delay via the shared netem::DelayLine; the RX
//    thread never blocks, preserving bandwidth spacing like a real pipe
//  * force-disables the same-host zero-copy transports (CMA, registered
//    shm) on emulated edges: an emulated WAN cannot be bypassed

constexpr size_t kRxSlice = 256 << 10;  // TCP sink write slice (cancel latency)

// process_vm_readv slice. Measured on the target host class, the kernel's
// pin-and-copy path peaks at small-to-mid slices (64K–512K ≈ 4.4 GB/s) and
// collapses on multi-MB iovecs without huge pages (8M ≈ 0.8 GB/s), so a
// mid-size default wins on both THP and non-THP buffers. Also bounds cancel
// latency and gives streaming consumers their overlap granularity.
size_t cma_slice() {
    static const size_t v = env_size("PCCLT_CMA_SLICE_BYTES", 512 << 10);
    return v;
}

} // namespace

std::optional<FrameHeader> FrameHeader::parse(const uint8_t *hdr, size_t n) {
    if (n < kWire) return std::nullopt;
    uint32_t be_len;
    uint64_t be_tag, be_off;
    memcpy(&be_len, hdr, 4);
    memcpy(&be_tag, hdr + 5, 8);
    memcpy(&be_off, hdr + 13, 8);
    uint32_t len = wire::from_be(be_len);
    if (len < 17 || len > kMaxLen) return std::nullopt;
    FrameHeader fh;
    fh.kind = hdr[4];
    fh.tag = wire::from_be(be_tag);
    fh.off = wire::from_be(be_off);
    fh.payload = len - 17;
    return fh;
}

MultiplexConn::MultiplexConn(Socket sock, std::shared_ptr<SinkTable> table,
                             std::shared_ptr<telemetry::Domain> dom)
    : sock_(std::move(sock)),
      table_(table ? std::move(table) : std::make_shared<SinkTable>()),
      dom_(dom ? std::move(dom) : telemetry::default_domain()) {
    tx_chunk_base_ = env_size("PCCLT_MULTIPLEX_CHUNK_SIZE", 8 << 20);
    cma_min_ = env_size("PCCLT_CMA_MIN_BYTES", 64 << 10);
    // io_uring backend gate, sampled per conn like the netem refresh below
    uring_on_ = uring::enabled();
    zc_min_ = uring_on_ ? uring::zc_min_bytes() : 0;
    // per-conn env re-read (old WirePacer::refresh semantics): a process
    // that flips the wire env between connections gets the new model
    netem::Registry::inst().refresh();
    // initial resolution by observed peer address: exact for outgoing conns
    // (we dialed the canonical endpoint); accepted conns see an ephemeral
    // source port and land on the ip-wildcard/default until set_wire_peer
    // re-resolves with the hello's advertised endpoint
    set_wire_peer(sock_.peer_addr());
}

void MultiplexConn::set_wire_peer(const Addr &peer) {
    auto resolved = netem::Registry::inst().resolve(peer);
    if (resolved != wire_) {
        // striped-bucket lane: one fair-share pacing lane per conn on its
        // edge, moved (released + re-allocated) when a rekey lands the
        // conn on a different Edge object
        if (wire_) wire_->release_lane(lane_.load(std::memory_order_relaxed));
        lane_.store(resolved->alloc_lane(), std::memory_order_relaxed);
    }
    wire_ = std::move(resolved);
    // per-edge telemetry keys by the same canonical endpoint as the wire
    // model; an accepted conn lands on the ephemeral source port until the
    // P2P hello rekeys it (bytes moved before that are handshake-free —
    // run() has not started). Interned label + release stores: a live
    // rekey must not race the RX/TX threads' counter reads, and a freshly
    // constructed EdgeCounters must be fully visible before its pointer is
    // (edge() pairs with an acquire load).
    const std::string key = peer.str();
    edge_.store(&dom_->edge(key), std::memory_order_release);
    edge_label_.store(telemetry::intern(key), std::memory_order_release);
    // under wire emulation, cap the wire chunk: a streamed receiver
    // consumes as frames land, and at WAN rates an 8 MB frame is ~60 ms of
    // pipeline stall before the first byte of a ring slice can be reduced.
    // Recomputed from the base on every resolution, so a rekey from an
    // emulated wildcard to an exempt canonical endpoint restores the full
    // chunk instead of keeping the cap for the conn's lifetime.
    tx_chunk_ = wire_->emulated() ? std::min(tx_chunk_base_, size_t{256} << 10)
                                  : tx_chunk_base_;
}

MultiplexConn::~MultiplexConn() {
    close();
    // safe now: no thread can hold a shared_ptr to us (we are being
    // destroyed), so no shm_resolve pointer can still be in use
    MutexLock lk(shm_mu_);
    for (auto &[base, m] : shm_maps_)
        if (m.local) munmap(m.local, m.len);
    shm_maps_.clear();
    for (auto &m : shm_zombies_)
        if (m.local) munmap(m.local, m.len);
    shm_zombies_.clear();
}

void MultiplexConn::run() {
    alive_ = true;
    edge().conns.fetch_add(1, std::memory_order_relaxed);
    if (telemetry::Recorder::inst().on())
        telemetry::Recorder::inst().instant(
            "edge", "conn_up", nullptr, 0, nullptr, 0,
            edge_label_.load(std::memory_order_relaxed));
    cma_ok_ = cma_enabled_env() && !wire_->emulated() &&
              sock_.peer_is_loopback();
    sock_.set_quickack();
    table_->attach(shared_from_this());
    if (cma_ok_.load()) {
        // announce CMA identity: pid + address of a random in-process token.
        // The receiver probe-reads the token before every pull, proving the
        // pid resolves to this process in ITS pid namespace (raw pids are
        // not namespace-safe and can be reused across restarts). Written
        // synchronously BEFORE any other traffic can start: descriptors are
        // posted inline by op threads, and the identity gate on the peer
        // drops announces that precede the hello.
        cma_token_ = std::make_unique<std::array<uint8_t, 16>>();
        std::random_device rd;
        for (auto &b : *cma_token_) b = static_cast<uint8_t>(rd());
        wire::Writer w;
        w.u32(static_cast<uint32_t>(getpid()));
        w.u64(reinterpret_cast<uint64_t>(cma_token_->data()));
        w.raw(cma_token_->data(), 16);
        write_frame(kCmaHello, 0, 0, w.data());
    }
    rx_thread_ = std::thread([this] { rx_loop(); });
    tx_thread_ = std::thread([this] { tx_loop(); });
}

void MultiplexConn::enqueue(SendReq *req) {
    {
        MutexLock lk(cma_mu_); // doubles as the enqueue/close gate
        if (!closing_.load() && alive_.load()) {
            txq_.push(req);
            tx_ev_.signal();
            return;
        }
    }
    if (req->state) req->state->complete(false);
    delete req;
}

SendHandle MultiplexConn::send_async(uint64_t tag, uint64_t off,
                                     std::span<const uint8_t> payload, bool allow_cma) {
    auto st = std::make_shared<SendState>();
    st->tag = tag;
    st->off = off;
    st->span = payload;
    if (allow_cma && cma_ok_.load() && payload.size() >= cma_min_ && alive_.load()) {
        // same-host: post the descriptor inline on THIS thread — the TX
        // thread (and its wakeup latency) never enters the data path
        cma_post_desc(tag, off, payload, st);
        return st;
    }
    auto *req = new SendReq;
    req->kind = kData;
    req->tag = tag;
    req->off = off;
    req->span = payload;
    req->allow_cma = allow_cma;
    req->state = st;
    enqueue(req);
    return st;
}

SendHandle MultiplexConn::send_copy(uint64_t tag, std::vector<uint8_t> payload) {
    auto st = std::make_shared<SendState>();
    st->tag = tag;
    if (payload.size() <= (64u << 10) && alive_.load()) {
        // small owned frame (quant metadata, control blobs): write inline —
        // cheaper than a TX-thread wakeup, and the write completes the send
        st->complete(write_frame(kData, tag, 0, payload));
        return st;
    }
    auto *req = new SendReq;
    req->kind = kData;
    req->tag = tag;
    req->owned = std::move(payload);
    req->span = req->owned;
    req->allow_cma = false;
    req->state = st;
    enqueue(req);
    return st;
}

bool MultiplexConn::send_bytes(uint64_t tag, std::span<const uint8_t> data,
                               bool allow_cma) {
    return send_async(tag, 0, data, allow_cma)->wait(-1);
}

SendHandle MultiplexConn::send_owned(uint8_t kind, uint64_t tag, uint64_t off,
                                     std::vector<uint8_t> payload) {
    auto st = std::make_shared<SendState>();
    st->tag = tag;
    st->off = off;
    // always via the TX thread: relay senders run on RX threads and must
    // not block on wr_mu_ (or pace) inline
    auto *req = new SendReq;
    req->kind = static_cast<Kind>(kind);
    req->tag = tag;
    req->off = off;
    req->owned = std::move(payload);
    req->span = req->owned;
    req->allow_cma = false;
    req->state = st;
    enqueue(req);
    return st;
}

void MultiplexConn::send_ctl(Kind kind, uint64_t tag, uint64_t off) {
    // inline fire-and-forget: a 21-byte frame under wr_mu_ — cheaper than a
    // TX-thread wakeup, and ack latency is the peer's stage-join latency.
    // Failure is ignored: the conn is dying and rx/close fail the pendings.
    write_frame(kind, tag, off, {});
}

bool MultiplexConn::write_frame(Kind kind, uint64_t tag, uint64_t off,
                                std::span<const uint8_t> payload) {
    uint8_t hdr[21];
    uint32_t be_len = wire::to_be(static_cast<uint32_t>(17 + payload.size()));
    uint64_t be_tag = wire::to_be(tag);
    uint64_t be_off = wire::to_be(off);
    memcpy(hdr, &be_len, 4);
    hdr[4] = static_cast<uint8_t>(kind);
    memcpy(hdr + 5, &be_tag, 8);
    memcpy(hdr + 13, &be_off, 8);
    // pace BEFORE taking wr_mu_: the sleep must only delay this writer, not
    // head-of-line-block other frames on the conn. Reordering is safe —
    // within a tag only one thread streams (offsets carried per frame), and
    // the order-sensitive shm announce path is disabled under pacing.
    wire_->pace(21 + payload.size(), lane_.load(std::memory_order_relaxed));
    if (kind == kData) {
        // per-edge data-plane accounting: payload bytes only (headers and
        // control frames excluded), so a ring op's per-edge tx total equals
        // its logical 2*(n-1)/n payload movement exactly
        edge().tx_frames.fetch_add(1, std::memory_order_relaxed);
        edge().tx_bytes.fetch_add(payload.size(), std::memory_order_relaxed);
    }
    MutexLock lk(wr_mu_);
    return sock_.send_all2(hdr, 21, payload.data(), payload.size());
}

// Post a CMA descriptor for `span` inline on the calling thread: register
// the pending ack, sync shm announce frames, write the descriptor. The TX
// thread is not involved — on the same-host path this removes two thread
// wakeups per ring stage. Completes `st` with failure on socket error.
bool MultiplexConn::cma_post_desc(uint64_t tag, uint64_t off,
                                  std::span<const uint8_t> span, const SendHandle &st) {
    {
        MutexLock lk(cma_mu_);
        pending_cma_[{tag, off}] = st;
    }
    wire::Writer w;
    w.u32(static_cast<uint32_t>(getpid()));
    w.u64(reinterpret_cast<uint64_t>(span.data()));
    w.u64(span.size());
    PLOG(kTrace) << "tx cma-desc tag=" << tag << " off=" << off
                 << " len=" << span.size();
    bool ok = shm_sync_tx(span) && write_frame(kCmaDesc, tag, off, w.data());
    if (!ok) {
        bool mine;
        {
            MutexLock lk(cma_mu_);
            mine = pending_cma_.erase({tag, off}) > 0;
        }
        if (mine) st->complete(false); // else rx/close already failed it
    }
    return ok;
}

// Lazy MSG_ZEROCOPY notif reaping (docs/08). Non-blocking: scoop whatever
// notifs have already posted — the per-submit drop-in point. Blocking:
// wait out every outstanding notif (quiescence: close(), ring teardown).
// Either way the deferred backlog is bounded (kZcLazyCap, under the CQ
// capacity) so deferred notifs can never overflow the completion ring.
void MultiplexConn::reap_zc(bool block) {
    if (!tx_ring_) {
        // the ring (and its fd) is gone: teardown released every pinned
        // page, so stragglers are charged here to keep the documented
        // tx_zc_reaps == tx_zc_frames quiescence invariant exact
        if (zc_unreaped_) {
            edge().tx_zc_reaps.fetch_add(zc_unreaped_,
                                         std::memory_order_relaxed);
            zc_unreaped_ = 0;
        }
        zc_unreaped_hint_.store(0, std::memory_order_relaxed);
        return;
    }
    constexpr unsigned kZcLazyCap = 24;  // CQ holds 2*2*kBatch = 64
    uring::Ring::Cqe c;
    while (zc_unreaped_ > 0) {
        bool got = (block || zc_unreaped_ > kZcLazyCap)
                       ? tx_ring_->next_cqe(c)
                       : tx_ring_->peek_cqe(c);
        if (!got) break;  // nothing posted yet (or ring failure while
                          // blocking: teardown will charge the remainder)
        if (c.flags & uring::kCqeFNotif) {
            edge().tx_zc_reaps.fetch_add(1, std::memory_order_relaxed);
            --zc_unreaped_;
        }
        // non-notif CQEs cannot appear: every batch drains its own send
        // completions before returning — dropping one here is still safe
        // (the stream that owned it has already failed its conn)
    }
    zc_unreaped_hint_.store(zc_unreaped_, std::memory_order_relaxed);
}

void MultiplexConn::drop_tx_ring() {
    if (tx_ring_) reap_zc(/*block=*/true);  // drain what the ring still owes
    tx_ring_.reset();
    reap_zc(/*block=*/true);  // ring gone: charge any stragglers
}

bool MultiplexConn::stream_payload(const SendReq &req) {
    // io_uring path when the payload spans several frames (batched
    // submission pays) or a single frame is zerocopy-eligible; everything
    // else — including the fallback ladder's bottom — uses the classic
    // per-frame gathered write below.
    if (uring_on_ && !req.span.empty() &&
        (req.span.size() > tx_chunk_ ||
         (zc_min_ && req.span.size() >= zc_min_)))
        // handles its own fallback internally — a false here is a dead
        // socket, never "please retry" (a retry would duplicate frames)
        return stream_payload_uring(req);
    size_t off = 0;
    do {
        // early-retire poll (frame boundary): a cancelled stream stops
        // here with the socket healthy; the caller fails the handle
        if (req.state &&
            req.state->cancel.load(std::memory_order_relaxed))
            return true;
        size_t n = std::min(tx_chunk_, req.span.size() - off);
        if (!write_frame(kData, req.tag, req.off + off, req.span.subspan(off, n)))
            return false;
        off += n;
    } while (off < req.span.size());
    return true;
}

// Batched io_uring TX. Per batch: frames are paced and their headers built
// OUTSIDE wr_mu_ (the netem sleep must only delay this writer), then the
// whole batch is submitted under one lock hold as IOSQE_IO_LINK-chained
// vectored SENDMSG SQEs — one submission per frame carrying header +
// payload together (never two sendmsg calls), links preserving TCP stream
// order, MSG_WAITALL making every completion all-or-error. Frames at or
// above zc_min_ go as SENDMSG_ZC: the kernel pins the payload pages
// instead of copying. Completion NOTIFs are reaped LAZILY (docs/08): a
// batch blocks only for its SEND completions and scoops whatever notifs
// have already posted; the remainder are swept by later submits, the idle
// TX loop, and close() — so a stream never stalls waiting for the peer's
// ACK clock, and tx_zc_frames == tx_zc_reaps still holds at quiescence.
// CAVEAT, documented deliberately: a notif outstanding past handle
// completion means the kernel may still reference the pinned pages for a
// TCP retransmit, so rewriting the span before the notif lands could put
// the NEW bytes on the wire. This plane runs over loopback (the emulated
// WANs pace loopback sockets), where segments are never lost and
// retransmits do not occur; on a real lossy wire the lazy window
// (bounded at kZcLazyCap) would have to shrink to zero — synchronous
// reaping — or sends would need owned buffers.
bool MultiplexConn::stream_payload_uring(const SendReq &req) {
    constexpr size_t kBatch = 16;
    struct Slot {
        uint8_t hdr[21];
        struct iovec iov[2];
        struct msghdr msg;
        uint32_t bytes = 0;   // 21 + payload
        uint32_t sent = 0;    // completed bytes (recovery path)
        bool zc = false;
        bool ok = false;
    };
    Slot slots[kBatch];
    const size_t total = req.span.size();
    // On a paced (netem) edge, pace() blocks until each frame has fully
    // drained through the emulated wire — batching N frames would sleep out
    // N frame-times BEFORE the first byte is submitted, adding a whole
    // batch of first-byte latency per stage. Cap the batch at 2 there (one
    // frame paced ahead of the wire); the full batch depth is for real
    // links, where pace() is a no-op and the win is one syscall per batch.
    const size_t batch_cap = wire_->pace_enabled() ? 2 : kBatch;
    size_t off = 0;
    while (off < total) {
        // early-retire poll (batch boundary), mirroring stream_payload's
        if (req.state &&
            req.state->cancel.load(std::memory_order_relaxed))
            return true;
        size_t nb = 0;
        while (nb < batch_cap && off < total) {
            size_t n = std::min(tx_chunk_, total - off);
            Slot &sl = slots[nb];
            uint32_t be_len = wire::to_be(static_cast<uint32_t>(17 + n));
            uint64_t be_tag = wire::to_be(req.tag);
            uint64_t be_off = wire::to_be(req.off + off);
            memcpy(sl.hdr, &be_len, 4);
            sl.hdr[4] = static_cast<uint8_t>(kData);
            memcpy(sl.hdr + 5, &be_tag, 8);
            memcpy(sl.hdr + 13, &be_off, 8);
            sl.iov[0] = {sl.hdr, 21};
            sl.iov[1] = {const_cast<uint8_t *>(req.span.data() + off), n};
            memset(&sl.msg, 0, sizeof sl.msg);
            sl.msg.msg_iov = sl.iov;
            sl.msg.msg_iovlen = 2;
            sl.bytes = static_cast<uint32_t>(21 + n);
            sl.sent = 0;
            sl.zc = zc_min_ && n >= zc_min_;
            sl.ok = false;
            // identical pacing + accounting to write_frame's. tx_zc_frames
            // is NOT charged here: a frame only counts as zerocopy once the
            // kernel confirms it pinned the pages (the F_MORE completion in
            // the reap loop) — a fallback-to-plain or failed ZC send must
            // not leave the tx_zc_reaps == tx_zc_frames invariant broken.
            wire_->pace(21 + n, lane_.load(std::memory_order_relaxed));
            edge().tx_frames.fetch_add(1, std::memory_order_relaxed);
            edge().tx_bytes.fetch_add(n, std::memory_order_relaxed);
            off += n;
            ++nb;
        }
        MutexLock lk(wr_mu_);
        int fd = sock_.fd();
        if (fd < 0) return false;
        if (!tx_ring_ && !tx_uring_down_) {
            tx_ring_ = std::make_unique<uring::Ring>();
            if (!tx_ring_->init(2 * kBatch)) {
                tx_ring_.reset();
                tx_uring_down_ = true;
                PLOG(kWarn) << "io_uring TX ring setup failed; "
                               "falling back to the poll loop";
            }
        }
        auto plain_frame = [&](Slot &sl) {
            // counters/pacing already charged above — write the raw bytes
            const auto *pay = static_cast<const uint8_t *>(sl.iov[1].iov_base);
            size_t pn = sl.iov[1].iov_len;
            if (sl.sent < 21)
                return sock_.send_all(sl.hdr + sl.sent, 21 - sl.sent) &&
                       sock_.send_all(pay, pn);
            return sock_.send_all(pay + (sl.sent - 21), pn - (sl.sent - 21));
        };
        if (tx_uring_down_) {
            drop_tx_ring();  // dead ring: free the fd + mmaps
            for (size_t i = 0; i < nb; ++i)
                if (!plain_frame(slots[i])) return false;
            continue;
        }
        // drop-in reap point: notifs for EARLIER batches that have posted
        // by now cost one ring peek each here, zero waiting
        reap_zc(/*block=*/false);
        unsigned expect = 0;  // SEND completions only; notifs reap lazily
        for (size_t i = 0; i < nb; ++i) {
            uring::Sqe *sqe = tx_ring_->get_sqe();
            if (!sqe) {  // cannot happen at 2*kBatch entries; stay safe
                tx_uring_down_ = true;
                break;
            }
            sqe->opcode = slots[i].zc ? uring::kOpSendmsgZc : uring::kOpSendmsg;
            sqe->fd = fd;
            sqe->addr = reinterpret_cast<uint64_t>(&slots[i].msg);
            sqe->len = 1;
            sqe->msg_flags = MSG_NOSIGNAL | MSG_WAITALL;
            sqe->user_data = i;
            if (i + 1 < nb) sqe->flags |= uring::kSqeIoLink;
            ++expect;
        }
        if (tx_uring_down_) {
            drop_tx_ring();  // nothing submitted: safe to free now
            for (size_t i = 0; i < nb; ++i)
                if (!plain_frame(slots[i])) return false;
            continue;
        }
        int rc = tx_ring_->submit();
        if (rc < 0) {
            // enter() errors without consuming: nothing is in flight
            tx_uring_down_ = true;
            drop_tx_ring();
            PLOG(kWarn) << "io_uring submit failed (" << strerror(-rc)
                        << "); falling back to the poll loop";
            for (size_t i = 0; i < nb; ++i)
                if (!plain_frame(slots[i])) return false;
            continue;
        }
        if (static_cast<unsigned>(rc) < nb) {
            // short submission (async-context allocation failed mid-batch):
            // only the consumed prefix is in flight — reap exactly those
            // CQEs, then the recovery loop below streams the rest plainly,
            // in order, and the ring is abandoned (a reap loop sized to the
            // full batch would wait forever for CQEs that never come)
            tx_uring_down_ = true;
            expect = static_cast<unsigned>(rc);
        }
        bool hard_fail = false;
        unsigned sends_seen = 0;
        while (sends_seen < expect) {
            uring::Ring::Cqe c;
            if (!tx_ring_->next_cqe(c)) return false;
            if (c.flags & uring::kCqeFNotif) {
                // zerocopy pages released by the kernel — this batch's or a
                // lazily-deferred notif from an earlier one, same counter
                edge().tx_zc_reaps.fetch_add(1, std::memory_order_relaxed);
                if (zc_unreaped_) --zc_unreaped_;
                continue;
            }
            ++sends_seen;
            Slot &sl = slots[c.user_data];
            if (sl.zc && (c.flags & uring::kCqeFMore)) {
                // pages pinned, notif guaranteed to follow: THIS is a
                // zerocopy frame (reap-side charge keeps the documented
                // reaps == frames invariant exact on every fallback path).
                // The notif itself reaps lazily — count it outstanding.
                edge().tx_zc_frames.fetch_add(1, std::memory_order_relaxed);
                ++zc_unreaped_;
            }
            if (c.res == -ECANCELED) {
                // link chain broken by an earlier failure; recovered below
            } else if (c.res < 0) {
                if (c.res != -EINTR && c.res != -EAGAIN) hard_fail = true;
            } else if (static_cast<uint32_t>(c.res) >= sl.bytes) {
                sl.ok = true;
            } else {
                sl.sent = static_cast<uint32_t>(c.res);  // short: finish below
            }
        }
        // scoop already-posted notifs (and cap the deferred backlog so it
        // can never overflow the CQ ring) without waiting for the rest
        reap_zc(/*block=*/false);
        zc_unreaped_hint_.store(zc_unreaped_, std::memory_order_relaxed);
        if (hard_fail) return false;  // real socket error: the conn is dying
        // a short submission latched tx_uring_down_ above; its in-flight
        // CQEs are now drained, so the dead ring can be freed like RX does
        if (tx_uring_down_) drop_tx_ring();
        // rare recovery (signal-shortened send / canceled chain tail):
        // complete the stream in order on the plain path
        for (size_t i = 0; i < nb; ++i)
            if (!slots[i].ok && !plain_frame(slots[i])) return false;
    }
    return true;
}

void MultiplexConn::tx_loop() {
    while (true) {
        mpsc::Node *n = txq_.pop();
        if (!n) {
            if (closing_.load() || !alive_.load()) break;
            // idle sweep for lazily-deferred zerocopy notifs: with no
            // further submits coming, this is what converges
            // tx_zc_reaps == tx_zc_frames at quiescence without a close
            if (zc_unreaped_hint_.load(std::memory_order_relaxed) > 0) {
                MutexLock lk(wr_mu_);
                if (tx_ring_) reap_zc(/*block=*/false);
            }
            uint32_t e = tx_ev_.epoch();
            if ((n = txq_.pop()) == nullptr) {
                tx_ev_.wait(e, 100);
                continue;
            }
        }
        auto *req = static_cast<SendReq *>(n);
        bool sock_ok = true;
        switch (req->kind) {
        case kData:
            if (req->state &&
                req->state->cancel.load(std::memory_order_relaxed)) {
                // early-retired (relay ack covered the span): fail the
                // handle without touching the span — the conn lives on
                req->state->complete(false);
            } else if (req->allow_cma && cma_ok_.load() &&
                       req->span.size() >= cma_min_) {
                // same-host fast path (queued variant; the common route is
                // the inline post in send_async). Completion is deferred to
                // the receiver's ack (rx_loop).
                sock_ok = cma_post_desc(req->tag, req->off, req->span, req->state);
            } else {
                sock_ok = stream_payload(*req);
                if (req->state)
                    req->state->complete(
                        sock_ok && !req->state->cancel.load(
                                       std::memory_order_relaxed));
            }
            break;
        case kCmaAck:
        case kCmaAckDrop:
        case kCmaNack:
            sock_ok = write_frame(req->kind, req->tag, req->off, {});
            break;
        case kRelayFwd:
        case kRelayDeliver:
        case kRelayAck:
        case kChunkReq:
        case kChunkHdr:
            // one frame per window (windows are pipeline-granular, well
            // under the frame cap); tag/off are the ORIGINAL coordinates
            sock_ok = write_frame(req->kind, req->tag, req->off, req->span);
            if (req->state) req->state->complete(sock_ok);
            break;
        case kCmaHello:
            sock_ok = write_frame(kCmaHello, 0, 0, req->span);
            break;
        case kCmaDesc:
        case kShmAnnounce:
        case kShmRetire:
            break; // never enqueued directly (shm frames go via shm_sync_tx)
        }
        delete req;
        if (!sock_ok) break;
    }
    // fail whatever is still queued. alive_ goes false under the enqueue
    // gate so no producer can slip a request past this drain (a racer either
    // pushed before we took the gate — its node is visible to pop() — or it
    // sees alive_ false and fails its request itself).
    {
        MutexLock lk(cma_mu_);
        alive_ = false;
    }
    mpsc::Node *n;
    while ((n = txq_.pop()) != nullptr) {
        auto *req = static_cast<SendReq *>(n);
        if (req->state) req->state->complete(false);
        delete req;
    }
    fail_all_pending();
    table_->on_conn_dead();
}

bool MultiplexConn::shm_sync_tx(std::span<const uint8_t> span) {
    // serializes announce bookkeeping across inline writers + the TX thread;
    // held across the frame writes so a racing writer cannot see "announced"
    // and ship a descriptor before the announce actually hit the wire
    // (lock order: shm_tx_mu_ -> wr_mu_, nowhere reversed)
    MutexLock lk(shm_tx_mu_);
    // retires first: they must reach the peer before the address range can
    // be re-announced (alloc never reuses a retired range, but the peer's
    // resolution map must not keep stale entries alive indefinitely)
    auto feed = shm::drain_retires(&shm_retire_cursor_);
    if (feed.reset) {
        // the registry compacted past our cursor: retire everything we have
        // announced (live regions re-announce on next use)
        for (const auto &[base, len] : shm_announced_)
            if (!write_frame(kShmRetire, 0, base, {})) return false;
        shm_announced_.clear();
    }
    for (uint64_t base : feed.bases) {
        shm_announced_.erase(base);
        if (!write_frame(kShmRetire, 0, base, {})) return false;
    }
    auto r = shm::find(span.data(), span.size());
    if (!r) return true;
    auto base = reinterpret_cast<uint64_t>(r->base);
    auto it = shm_announced_.find(base);
    if (it != shm_announced_.end() && it->second == r->len) return true;
    wire::Writer w;
    w.u32(static_cast<uint32_t>(getpid()));
    w.u32(static_cast<uint32_t>(r->fd));
    w.u64(base);
    w.u64(r->len);
    if (!write_frame(kShmAnnounce, 0, 0, w.data())) return false;
    PLOG(kTrace) << "tx shm-announce base=" << std::hex << base << std::dec
                 << " len=" << r->len;
    shm_announced_[base] = r->len;
    return true;
}

const uint8_t *MultiplexConn::shm_resolve(uint64_t addr, uint64_t len) {
    MutexLock lk(shm_mu_);
    auto it = shm_maps_.upper_bound(addr);
    if (it == shm_maps_.begin()) return nullptr;
    --it;
    if (addr >= it->first && addr + len <= it->first + it->second.len)
        return it->second.local + (addr - it->first);
    return nullptr;
}

void MultiplexConn::do_cma_fill(uint64_t tag, const SinkTable::PendingDesc &d) {
    uint8_t *dst = nullptr;
    bool drop = false;
    {
        MutexLock lk(table_->mu_);
        auto it = table_->sinks_.find(tag);
        if (it == table_->sinks_.end()) {
            // a purge may have landed between the caller's check and here:
            // retired data is unwanted (ack-drop) — a NACK would trigger a
            // pointless full streaming retransmit the receiver then discards
            drop = table_->is_retired(tag);
        } else if (it->second.cancel) {
            drop = true; // op aborted locally: data unwanted, ack-drop
        } else if (d.off + d.len <= it->second.cap) {
            dst = it->second.base + d.off;
            ++it->second.busy;
        }
    }
    if (!dst) {
        send_ctl(drop ? kCmaAckDrop : kCmaNack, tag, d.off);
        return;
    }
    if (const uint8_t *mapped = shm_resolve(d.addr, d.len)) {
        // registered-region fast path: the peer's bytes are already mapped
        // here — fill is a plain memcpy (identity was gated at announce)
        bool cancelled = false;
        size_t off = 0;
        while (off < d.len && !cancelled) {
            size_t want = std::min<size_t>(2u << 20, d.len - off);
            kernels::copy_stream(dst + off, mapped + off, want);
            MutexLock lk(table_->mu_);
            auto it = table_->sinks_.find(tag);
            if (it == table_->sinks_.end() || it->second.cancel) {
                cancelled = true;
            } else {
                it->second.add_extent(d.off + off, d.off + off + want);
                off += want;
            }
            table_->signal_tag(tag);
        }
        {
            MutexLock lk(table_->mu_);
            auto it = table_->sinks_.find(tag);
            if (it != table_->sinks_.end()) --it->second.busy;
        }
        table_->signal_tag(tag);
        if (!cancelled) {
            edge().rx_frames.fetch_add(1, std::memory_order_relaxed);
            edge().rx_bytes.fetch_add(d.len, std::memory_order_relaxed);
        }
        send_ctl(cancelled ? kCmaAckDrop : kCmaAck, tag, d.off);
        return;
    }
    if (!cma_verify_peer(d)) {
        {
            MutexLock lk(table_->mu_);
            auto it = table_->sinks_.find(tag);
            if (it != table_->sinks_.end()) --it->second.busy;
        }
        table_->signal_tag(tag);
        send_ctl(kCmaNack, tag, d.off);
        PLOG(kWarn) << "CMA identity probe failed for pid " << d.pid
                    << "; falling back to streaming";
        return;
    }
    bool ok = true, cancelled = false;
    size_t off = 0;
    while (off < d.len && ok && !cancelled) {
        size_t want = std::min(cma_slice(), d.len - off);
        size_t got = 0;
        while (got < want) {
            struct iovec liov{dst + off + got, want - got};
            struct iovec riov{reinterpret_cast<void *>(d.addr + off + got), want - got};
            ssize_t r = process_vm_readv(static_cast<pid_t>(d.pid), &liov, 1, &riov, 1, 0);
            if (r <= 0) {
                ok = false;
                break;
            }
            got += static_cast<size_t>(r);
        }
        if (ok) {
            // publish every slice (not just the whole payload) so a streaming
            // consumer overlaps its reduction with the remainder of the pull
            MutexLock lk(table_->mu_);
            auto it = table_->sinks_.find(tag);
            if (it == table_->sinks_.end() || it->second.cancel) {
                cancelled = true;
            } else {
                it->second.add_extent(d.off + off, d.off + off + want);
            }
        }
        off += want;
        if (ok && !cancelled) table_->signal_tag(tag);
    }
    {
        MutexLock lk(table_->mu_);
        auto it = table_->sinks_.find(tag);
        if (it != table_->sinks_.end()) --it->second.busy;
    }
    table_->signal_tag(tag);
    if (ok && !cancelled) {
        edge().rx_frames.fetch_add(1, std::memory_order_relaxed);
        edge().rx_bytes.fetch_add(d.len, std::memory_order_relaxed);
    }
    send_ctl(ok && !cancelled ? kCmaAck
             : cancelled      ? kCmaAckDrop
                              : kCmaNack,
             tag, d.off);
    if (!ok && !cancelled)
        PLOG(kWarn) << "CMA read from pid " << d.pid << " failed (errno " << errno
                    << "); peer will fall back to streaming";
}

bool MultiplexConn::cma_verify_peer(const SinkTable::PendingDesc &d) {
    // identity probe: read the announced token from the announced pid and
    // compare with the copy that came over TCP. A pid from another pid
    // namespace, or reused after a restart, fails here and the sender falls
    // back to streaming — never a silent read of the wrong process.
    uint32_t pid = 0;
    uint64_t taddr = 0;
    std::array<uint8_t, 16> expect{};
    {
        MutexLock lk(cma_mu_);
        if (cma_peer_valid_) {
            pid = cma_peer_pid_;
            taddr = cma_peer_token_addr_;
            expect = cma_peer_token_;
        }
    }
    std::array<uint8_t, 16> got{};
    struct iovec liov{got.data(), 16};
    struct iovec riov{reinterpret_cast<void *>(taddr), 16};
    return pid != 0 && pid == d.pid &&
           process_vm_readv(static_cast<pid_t>(pid), &liov, 1, &riov, 1, 0) == 16 &&
           got == expect;
}

SinkTable::CmaClaim MultiplexConn::consumer_cma_pull(
    uint64_t tag, const SinkTable::PendingDesc &d, size_t slice_align,
    const std::function<bool(const uint8_t *, size_t, size_t)> &consume) {
    if (const uint8_t *mapped = shm_resolve(d.addr, d.len)) {
        // registered-region fast path: feed the consumer straight out of the
        // sender's mapped buffer — no bounce, no kernel copy. The reduction
        // IS the only pass over the bytes. Identity was gated at announce.
        static const size_t dslice = env_size("PCCLT_SHM_SLICE_BYTES", 2u << 20);
        size_t slice = dslice;
        if (slice_align > 1) slice -= slice % slice_align;
        if (slice == 0) slice = slice_align;
        size_t off = 0;
        while (off < d.len) {
            size_t want = std::min(slice, d.len - off);
            if (!consume(mapped + off, d.off + off, want)) {
                send_ctl(kCmaAckDrop, tag, d.off); // op aborted locally
                return SinkTable::CmaClaim::kCancelled;
            }
            off += want;
        }
        edge().rx_frames.fetch_add(1, std::memory_order_relaxed);
        edge().rx_bytes.fetch_add(d.len, std::memory_order_relaxed);
        send_ctl(kCmaAck, tag, d.off);
        return SinkTable::CmaClaim::kDone;
    }
    if (!cma_verify_peer(d)) {
        send_ctl(kCmaNack, tag, d.off);
        PLOG(kWarn) << "CMA identity probe failed for pid " << d.pid
                    << "; falling back to streaming";
        return SinkTable::CmaClaim::kFailed;
    }
    // cache-sized bounce: each slice is pulled and immediately fed to the
    // reduction while still cache-hot — no scratch round-trip through DRAM
    // 128K: measured sweet spot for the kernel's pin-and-copy path on 4K
    // pages, and comfortably L2-resident for the fused consumer
    static const size_t bounce_bytes = env_size("PCCLT_CMA_BOUNCE_BYTES", 128u << 10);
    size_t slice = bounce_bytes;
    if (slice_align > 1) slice -= slice % slice_align;
    if (slice == 0) slice = slice_align;
    thread_local std::vector<uint8_t> bounce;
    if (bounce.size() < slice) bounce.resize(slice);

    size_t off = 0;
    while (off < d.len) {
        size_t want = std::min(slice, d.len - off);
        size_t got = 0;
        while (got < want) {
            struct iovec liov{bounce.data() + got, want - got};
            struct iovec riov{reinterpret_cast<void *>(d.addr + off + got), want - got};
            ssize_t r = process_vm_readv(static_cast<pid_t>(d.pid), &liov, 1, &riov, 1, 0);
            if (r <= 0) {
                send_ctl(kCmaNack, tag, d.off);
                PLOG(kWarn) << "CMA read from pid " << d.pid << " failed (errno "
                            << errno << "); peer will fall back to streaming";
                return SinkTable::CmaClaim::kFailed;
            }
            got += static_cast<size_t>(r);
        }
        if (!consume(bounce.data(), d.off + off, want)) {
            // consumer aborted: ack-drop so the sender's handle completes
            send_ctl(kCmaAckDrop, tag, d.off);
            return SinkTable::CmaClaim::kCancelled;
        }
        off += want;
    }
    edge().rx_frames.fetch_add(1, std::memory_order_relaxed);
    edge().rx_bytes.fetch_add(d.len, std::memory_order_relaxed);
    send_ctl(kCmaAck, tag, d.off);
    return SinkTable::CmaClaim::kDone;
}

void SinkTable::fill_pending(uint64_t tag) {
    std::vector<PendingDesc> descs;
    {
        MutexLock lk(mu_);
        auto range = pending_descs_.equal_range(tag);
        for (auto it = range.first; it != range.second; ++it)
            descs.push_back(it->second);
        pending_descs_.erase(range.first, range.second);
    }
    for (auto &d : descs)
        if (auto c = d.ack_conn.lock()) c->do_cma_fill(tag, d);
}

SinkTable::CmaClaim SinkTable::consume_cma(
    uint64_t tag, size_t len, size_t slice_align,
    const std::function<bool(const uint8_t *, size_t, size_t)> &consume,
    bool fill_if_unmapped) {
    PendingDesc d;
    std::shared_ptr<MultiplexConn> conn;
    bool mismatch = false;
    {
        MutexLock lk(mu_);
        auto it = pending_descs_.find(tag);
        if (it == pending_descs_.end()) return CmaClaim::kNone;
        d = it->second;
        conn = d.ack_conn.lock();
        pending_descs_.erase(it);
        mismatch = d.off != 0 || d.len != len;
    }
    if (!conn) return CmaClaim::kNone; // conn died; nothing to ack
    if (mismatch || (fill_if_unmapped && !conn->shm_resolve(d.addr, d.len))) {
        // unexpected shape (striped/partial), or a copy-consumer whose
        // descriptor is not zero-copy reachable: fill the registered sink
        // the ordinary way — this one and any other stripes queued behind
        // it — and let the caller's wait_filled path consume them
        conn->do_cma_fill(tag, d);
        fill_pending(tag);
        return CmaClaim::kNone;
    }
    return conn->consumer_cma_pull(tag, d, slice_align, consume);
}

// Batched io_uring RX for one large data frame: up to 8 kRxSlice slices are
// posted as IOSQE_IO_LINK-chained MSG_WAITALL RECVs into the registered sink
// and submitted in ONE io_uring_enter. Writing into dst is always safe —
// the caller holds the sink's busy refcount, so unregister/purge wait for
// us — a cancel only downgrades the frame to "drained, not delivered"
// (*cancelled), exactly like the poll loop's scratch drain. On a mid-frame
// submit failure the frame is finished with plain recv_all, so the TCP
// stream position never desynchronizes.
bool MultiplexConn::uring_recv_sink(uint8_t *dst, size_t n, uint64_t tag,
                                    bool *cancelled) {
    constexpr unsigned kRxBatch = 8;
    int fd = sock_.fd();
    if (fd < 0) return false;
    size_t done = 0;
    while (done < n) {
        struct {
            size_t len = 0;
        } segs[kRxBatch];
        unsigned nb = 0;
        size_t posted = 0;
        while (nb < kRxBatch && done + posted < n) {
            uring::Sqe *sqe = rx_ring_->get_sqe();
            if (!sqe) break;
            size_t want = std::min(kRxSlice, n - done - posted);
            sqe->opcode = uring::kOpRecv;
            sqe->fd = fd;
            sqe->addr = reinterpret_cast<uint64_t>(dst + done + posted);
            sqe->len = static_cast<uint32_t>(want);
            sqe->msg_flags = MSG_WAITALL;
            sqe->user_data = nb;
            segs[nb].len = want;
            posted += want;
            ++nb;
        }
        if (nb == 0) {  // SQ unexpectedly full: never spin — poll loop
            rx_uring_down_ = true;
            while (done < n) {
                size_t want = std::min(kRxSlice, n - done);
                if (!sock_.recv_all(dst + done, want)) return false;
                done += want;
            }
            return true;
        }
        // link all but the last: chained RECVs run strictly in order
        // (we can set flags after the fact — nothing is published until
        // submit()), and MSG_WAITALL makes each one all-or-error
        for (unsigned i = 0; i + 1 < nb; ++i)
            rx_ring_->sqe_at_tail(nb - i)->flags |= uring::kSqeIoLink;
        int rc = rx_ring_->submit();
        if (rc < 0) {
            // enter() errored without consuming: nothing of this batch hit
            // the wire-read position — finish the frame on the poll loop.
            // rx_loop frees the ring once this frame is done;
            // rx_uring_down_ keeps every later frame on the poll loop.
            rx_uring_down_ = true;
            PLOG(kWarn) << "io_uring RX submit failed (" << strerror(-rc)
                        << "); falling back to the poll loop";
            while (done < n) {
                size_t want = std::min(kRxSlice, n - done);
                if (!sock_.recv_all(dst + done, want)) return false;
                done += want;
            }
            return true;
        }
        const unsigned submitted = static_cast<unsigned>(rc);
        if (submitted < nb)
            rx_uring_down_ = true;  // short submission: abandon the ring
        bool dead = false;
        size_t got = 0;
        for (unsigned reaped = 0; reaped < submitted; ++reaped) {
            uring::Ring::Cqe c;
            if (!rx_ring_->next_cqe(c)) return false;
            // a short read (EOF/reset) or error fails the conn, matching
            // recv_all; later chained slices surface as -ECANCELED
            if (c.res < 0 || static_cast<size_t>(c.res) < segs[c.user_data].len)
                dead = true;
            else
                got += segs[c.user_data].len;
        }
        if (dead) return false;
        done += got;
        if (submitted < nb) {
            // slices are posted in stream order, so the unsubmitted tail
            // starts exactly at `done` — drain it (and the frame) plainly
            while (done < n) {
                size_t want = std::min(kRxSlice, n - done);
                if (!sock_.recv_all(dst + done, want)) return false;
                done += want;
            }
            return true;
        }
        if (!*cancelled && done < n) {
            MutexLock lk(table_->mu_);
            auto it = table_->sinks_.find(tag);
            *cancelled = it == table_->sinks_.end() || it->second.cancel;
        }
    }
    return true;
}

void MultiplexConn::rx_loop() {
    std::vector<uint8_t> scratch;
    while (alive_.load()) {
        uint8_t hdr[FrameHeader::kWire];
        if (!sock_.recv_all(hdr, sizeof hdr)) break;
        auto fh = FrameHeader::parse(hdr, sizeof hdr);
        if (!fh) {
            PLOG(kError) << "multiplex rx: bad frame header";
            break;
        }
        uint8_t kind = fh->kind;
        uint64_t tag = fh->tag;
        uint64_t off = fh->off;
        size_t n = fh->payload;

        if (kind == kCmaAck || kind == kCmaAckDrop || kind == kCmaNack) {
            SendHandle st;
            {
                MutexLock lk(cma_mu_);
                auto it = pending_cma_.find({tag, off});
                if (it != pending_cma_.end()) {
                    st = it->second;
                    pending_cma_.erase(it);
                }
            }
            if (st) {
                if (kind == kCmaAck || kind == kCmaAckDrop) {
                    if (kind == kCmaAck) {
                        // same-host delivery confirmed: account the payload
                        // as sent on this edge (one descriptor = one logical
                        // send). Ack-DROPPED payloads (op aborted/purged on
                        // the receiver) complete the handle but were never
                        // delivered — counting them would break the per-edge
                        // tx==rx conservation invariant.
                        edge().tx_frames.fetch_add(1, std::memory_order_relaxed);
                        edge().tx_bytes.fetch_add(st->span.size(),
                                                  std::memory_order_relaxed);
                    }
                    st->complete(true);
                } else {
                    // receiver could not pull: fall back to TCP streaming of
                    // the same bytes, and stop offering CMA on this conn
                    cma_ok_ = false;
                    auto *req = new SendReq;
                    req->kind = kData;
                    req->tag = st->tag;
                    req->off = st->off;
                    req->span = st->span;
                    req->allow_cma = false;
                    req->state = st;
                    enqueue(req);
                }
            }
            continue;
        }

        if (kind == kCmaHello) {
            if (n != 28) {
                PLOG(kError) << "multiplex rx: bad CMA hello";
                break;
            }
            uint8_t buf[28];
            if (!sock_.recv_all(buf, 28)) break;
            uint32_t be_pid;
            uint64_t be_addr;
            memcpy(&be_pid, buf, 4);
            memcpy(&be_addr, buf + 4, 8);
            MutexLock lk(cma_mu_);
            cma_peer_pid_ = wire::from_be(be_pid);
            cma_peer_token_addr_ = wire::from_be(be_addr);
            memcpy(cma_peer_token_.data(), buf + 12, 16);
            cma_peer_valid_ = true;
            continue;
        }

        if (kind == kShmAnnounce) {
            if (n != 24) {
                PLOG(kError) << "multiplex rx: bad shm announce";
                break;
            }
            uint8_t buf[24];
            if (!sock_.recv_all(buf, 24)) break;
            uint32_t be_pid, be_fd;
            uint64_t be_base, be_rlen;
            memcpy(&be_pid, buf, 4);
            memcpy(&be_fd, buf + 4, 4);
            memcpy(&be_base, buf + 8, 8);
            memcpy(&be_rlen, buf + 16, 8);
            uint32_t pid = wire::from_be(be_pid);
            uint64_t base = wire::from_be(be_base);
            uint64_t rlen = wire::from_be(be_rlen);
            // identity gate: only map regions of the verified hello peer
            // (same trust model as every process_vm_readv pull)
            bool pid_ok;
            {
                MutexLock lk(cma_mu_);
                pid_ok = cma_peer_valid_ && cma_peer_pid_ == pid;
            }
            if (pid_ok && rlen > 0 && rlen <= (64ull << 30)) {
                char path[64];
                snprintf(path, sizeof path, "/proc/%u/fd/%u", pid,
                         wire::from_be(be_fd));
                int fd = open(path, O_RDONLY);
                if (fd >= 0) {
                    void *m = mmap(nullptr, rlen, PROT_READ, MAP_SHARED, fd, 0);
                    ::close(fd);
                    if (m != MAP_FAILED) {
                        MutexLock lk(shm_mu_);
                        auto [it, fresh] = shm_maps_.try_emplace(base);
                        if (!fresh && it->second.local)
                            shm_zombies_.push_back(it->second); // reader-safe
                        it->second = {rlen, static_cast<uint8_t *>(m)};
                    }
                }
                // open/mmap failure is soft: descriptors in the region fall
                // back to the process_vm_readv pull path
            }
            continue;
        }

        if (kind == kShmRetire) {
            MutexLock lk(shm_mu_);
            auto it = shm_maps_.find(off); // retire carries base in `off`
            if (it != shm_maps_.end()) {
                // no munmap here: an op thread may hold a shm_resolve
                // pointer mid-copy — zombie until the destructor
                shm_zombies_.push_back(it->second);
                shm_maps_.erase(it);
            }
            continue;
        }

        if (kind == kCmaDesc) {
            if (n != 20) {
                PLOG(kError) << "multiplex rx: bad CMA descriptor";
                break;
            }
            uint8_t buf[20];
            if (!sock_.recv_all(buf, 20)) break;
            SinkTable::PendingDesc d;
            d.ack_conn = weak_from_this();
            d.tag = tag;
            uint32_t be_pid;
            uint64_t be_addr, be_dlen;
            memcpy(&be_pid, buf, 4);
            memcpy(&be_addr, buf + 4, 8);
            memcpy(&be_dlen, buf + 12, 8);
            d.pid = wire::from_be(be_pid);
            d.addr = wire::from_be(be_addr);
            d.len = wire::from_be(be_dlen);
            d.off = off;
            bool fill_now;
            bool retired;
            {
                MutexLock lk(table_->mu_);
                retired = table_->is_retired(tag);
                auto it = table_->sinks_.find(tag);
                // consumer_pull sinks (and absent sinks) keep the descriptor
                // pending: the consumer claims it via consume_cma and pulls
                // fused with its reduction on its own thread
                fill_now = !retired && it != table_->sinks_.end() &&
                           !it->second.consumer_pull;
                if (!fill_now && !retired) table_->pending_descs_.emplace(tag, d);
            }
            if (retired) {
                // straggler for a purged op: ack-drop NOW so the sender's
                // handle completes — nobody is left to claim it later
                send_ctl(kCmaAckDrop, tag, d.off);
            } else if (fill_now) {
                do_cma_fill(tag, d);
            } else {
                table_->signal_tag(tag); // wake a consumer polling for the claim
            }
            continue;
        }

        if (kind == kRelayFwd || kind == kRelayDeliver) {
            // straggler failover detour (docs/05). Read the whole frame
            // owned — these are single-window frames on a HEALTHY edge of
            // a degraded op; they never ride the registered-sink path here
            // (the final placement dedupes into the origin link's table).
            const size_t hdr_uuids = kind == kRelayFwd ? 32u : 16u;
            if (n < hdr_uuids) {
                PLOG(kError) << "multiplex rx: short relay frame";
                break;
            }
            std::vector<uint8_t> buf(n);
            if (n > 0 && !sock_.recv_all(buf.data(), n)) break;
            std::vector<uint8_t> bytes(buf.begin() + hdr_uuids, buf.end());
            if (kind == kRelayFwd) {
                if (relay_fwd_)
                    relay_fwd_(buf.data(), buf.data() + 16, tag, off,
                               std::move(bytes));
                else
                    PLOG(kWarn) << "relay-forward frame with no router; "
                                   "dropping (tag=" << tag << ")";
            } else {
                if (relay_deliver_)
                    relay_deliver_(buf.data(), tag, off, std::move(bytes));
                else
                    // standalone conns (socktest): deliver into OUR table,
                    // charging this conn's edge — lets the transport be
                    // exercised without a client-side router
                    table_->deliver_window(tag, off, std::move(bytes),
                                           &edge());
            }
            continue;
        }

        if (kind == kRelayAck) {
            // end-to-end relay delivery ack (docs/05): the final receiver
            // confirms [off, off+len) of `tag` landed, letting the origin
            // retire the stalled direct copy early. Fire-and-forget; an
            // unrouted ack (standalone conn) is dropped harmlessly.
            std::vector<uint8_t> buf(n);
            if (n > 0 && !sock_.recv_all(buf.data(), n)) break;
            if (relay_ack_ && n >= 8) {
                uint64_t len;
                memcpy(&len, buf.data(), 8);
                relay_ack_(tag, off, wire::from_be(len));
            }
            continue;
        }

        if (kind == kChunkReq) {
            // shared-state chunk-range request (docs/04): [16B requester
            // uuid][range spec]. Hand off to the client's serve pool —
            // materialize/copy/send happens off the RX thread.
            if (n < 16) {
                PLOG(kError) << "multiplex rx: short chunk-req frame";
                break;
            }
            std::vector<uint8_t> buf(n);
            if (!sock_.recv_all(buf.data(), n)) break;
            if (chunk_req_) {
                std::vector<uint8_t> spec(buf.begin() + 16, buf.end());
                chunk_req_(buf.data(), tag, std::move(spec));
            } else {
                PLOG(kWarn) << "chunk-req frame with no server; dropping "
                               "(tag=" << tag << ")";
            }
            continue;
        }

        if (kind == kChunkHdr) {
            // chunk-range response header ([u8 status][BE u64 payload
            // len]): queued for the fetch worker exactly like a sink-less
            // kData frame — [8B host-order off][payload] — so recv_queued
            // on the response tag picks it up with no new plumbing.
            std::vector<uint8_t> buf(n);
            if (n > 0 && !sock_.recv_all(buf.data(), n)) break;
            {
                MutexLock lk(table_->mu_);
                if (!table_->is_retired(tag)) {
                    std::vector<uint8_t> qf(8 + n);
                    memcpy(qf.data(), &off, 8);
                    if (n > 0) memcpy(qf.data() + 8, buf.data(), n);
                    table_->queues_[tag].push_back(std::move(qf));
                }
            }
            table_->signal_tag(tag);
            continue;
        }

        // kData — sink fast path: read straight into the registered
        // destination at the frame's offset. busy guards the buffer against
        // unregister/purge while we write outside the lock; the frame is
        // read in bounded slices so a cancel request (op abort) is honoured
        // promptly without killing the connection.
        PLOG(kTrace) << "rx data tag=" << tag << " off=" << off << " len=" << n;
        edge().rx_frames.fetch_add(1, std::memory_order_relaxed);
        edge().rx_bytes.fetch_add(n, std::memory_order_relaxed);
        // per-window attribution tier (docs/09): frame arrival on the RX
        // thread, the wire-side counterpart of reduce.cpp's rx_slice
        if (telemetry::win_trace_enabled() &&
            telemetry::Recorder::inst().on())
            telemetry::Recorder::inst().instant("window", "rx_frame", "off",
                                                off, "bytes", n, nullptr,
                                                "tag", tag);
        uint8_t *dst = nullptr;
        bool already_covered = false;
        bool tag_retired = false;
        {
            MutexLock lk(table_->mu_);
            auto it = table_->sinks_.find(tag);
            if (it != table_->sinks_.end() && !it->second.cancel &&
                off + n <= it->second.cap) {
                if (it->second.fully_covered(off, off + n)) {
                    // (op, stage, window) dedupe — first arrival won (a
                    // relayed/re-issued copy, or a writer mid-claim): drain
                    // this copy off the stream and count it, never rewrite
                    // published bytes under a consumer
                    already_covered = true;
                } else {
                    dst = it->second.base + off;
                    ++it->second.busy;
                    // claim before writing: a concurrent failover delivery
                    // must skip (not republish) the range we're filling
                    it->second.claims[off] =
                        std::max(it->second.claims[off], off + n);
                }
            } else {
                // no live sink claimed: only now is the retired scan worth
                // paying (a live sink implies not-retired — register_sink
                // un-retires — so the fast path skips the deque walk)
                tag_retired = table_->is_retired(tag);
            }
        }
        const bool drop_dup = already_covered || (tag_retired && !dst);
        if (drop_dup) {
            // duplicate (or post-purge straggler): rx_bytes already counted
            // this copy — the dup counter keeps delivered-unique accounting
            // exact: unique == rx_bytes + rx_relay_bytes - dup_bytes
            edge().dup_bytes.fetch_add(n, std::memory_order_relaxed);
            if (already_covered)
                edge().dup_windows.fetch_add(1, std::memory_order_relaxed);
        }
        if (dst) {
            bool ok = true, cancelled = false;
            if (uring_on_ && !rx_uring_down_ && n > kRxSlice && !rx_ring_) {
                rx_ring_ = std::make_unique<uring::Ring>();
                if (!rx_ring_->init(16)) {
                    rx_ring_.reset();
                    rx_uring_down_ = true;
                    PLOG(kWarn) << "io_uring RX ring setup failed; "
                                   "falling back to the poll loop";
                }
            }
            if (rx_ring_ && !rx_uring_down_ && n > kRxSlice) {
                // batched linked RECV slices straight into the sink
                ok = uring_recv_sink(dst, n, tag, &cancelled);
                // a mid-frame ring failure latched rx_uring_down_ and
                // drained its in-flight completions — free the dead ring
                // (fd + mmaps) instead of carrying it for the conn's life
                if (rx_uring_down_) rx_ring_.reset();
            } else {
                size_t done = 0;
                while (done < n && ok) {
                    size_t want = std::min(kRxSlice, n - done);
                    if (!cancelled) {
                        ok = sock_.recv_all(dst + done, want);
                    } else {
                        scratch.resize(want); // drain + drop rest of the frame
                        ok = sock_.recv_all(scratch.data(), want);
                    }
                    done += want;
                    if (ok && !cancelled && done < n) {
                        MutexLock lk(table_->mu_);
                        auto it = table_->sinks_.find(tag);
                        cancelled =
                            it == table_->sinks_.end() || it->second.cancel;
                    }
                }
            }
            bool delivered = ok && !cancelled;
            // per-edge delivery delay: rtt/2 + jitter + drop penalty for
            // THIS frame on THIS conn's emulated edge (0 = deliver now)
            uint64_t delay_ns =
                wire_->delay_enabled() ? wire_->delivery_delay_ns() : 0;
            {
                MutexLock lk(table_->mu_);
                auto it = table_->sinks_.find(tag);
                if (it != table_->sinks_.end()) {
                    --it->second.busy;   // buffer write done: release NOW
                    // the claim holds until the extent publishes (the
                    // delayed path keeps it so a failover copy arriving
                    // inside the visibility delay still reads as covered)
                    if (!(delivered && delay_ns > 0))
                        it->second.claims.erase(off);
                    if (delivered && delay_ns == 0) {
                        // model-checker finding: a committed direct write
                        // whose range partially overlaps already-published
                        // bytes grew coverage by the fresh remainder only —
                        // the overlap is a duplicate and must be counted,
                        // or rx + relay - dup drifts from unique on every
                        // relay-vs-direct race with misaligned windows
                        size_t ovl =
                            it->second.published_overlap(off, off + n);
                        if (ovl)
                            edge().dup_bytes.fetch_add(
                                ovl, std::memory_order_relaxed);
                        it->second.add_extent(off, off + n);
                    }
                }
            }
            if (delivered && delay_ns > 0) {
                // bytes already landed zero-copy in the sink; only their
                // VISIBILITY (extent + wakeup) rides the delay line
                netem::DelayLine::inst().deliver(
                    delay_ns,
                    [tbl = table_, tag, off, n, dom = dom_, ec = &edge()] {
                        {
                            MutexLock lk(tbl->mu_);
                            auto it = tbl->sinks_.find(tag);
                            if (it != tbl->sinks_.end()) {
                                it->second.claims.erase(off);
                                if (!it->second.cancel &&
                                    off + n <= it->second.cap) {
                                    // model-checker finding: same overlap
                                    // accounting as the undelayed commit —
                                    // a failover copy published inside the
                                    // visibility delay makes our overlap a
                                    // duplicate
                                    size_t ovl = it->second.published_overlap(
                                        off, off + n);
                                    if (ovl)
                                        ec->dup_bytes.fetch_add(
                                            ovl, std::memory_order_relaxed);
                                    it->second.add_extent(off, off + n);
                                }
                            }
                        }
                        (void)dom;  // keeps the counter domain alive
                        tbl->signal_tag(tag);
                    });
            } else {
                table_->signal_tag(tag);
            }
            if (!ok) break;
        } else {
            scratch.resize(n);
            if (n > 0 && !sock_.recv_all(scratch.data(), n)) break;
            if (drop_dup) {
                // dedupe/post-purge drop: bytes drained off the stream and
                // discarded; accounting happened at the verdict above
                table_->signal_tag(tag);
                continue;
            }
            uint64_t delay_ns =
                wire_->delay_enabled() ? wire_->delivery_delay_ns() : 0;
            if (delay_ns > 0) {
                // move the payload onto the delay line (scratch is resized
                // fresh next iteration); the closure re-runs the
                // sink-or-queue logic at visibility time. Placement goes
                // through the dedupe (a failover copy may have covered the
                // range during the delay); short-delivered bytes are
                // charged as duplicates to this conn's edge.
                std::vector<uint8_t> bytes(std::move(scratch));
                netem::DelayLine::inst().deliver(
                    delay_ns,
                    [tbl = table_, tag, off, bytes = std::move(bytes),
                     dom = dom_, ec = &edge()] {
                        size_t delivered = 0;
                        bool placed = false;
                        {
                            MutexLock lk(tbl->mu_);
                            auto it = tbl->sinks_.find(tag);
                            size_t n = bytes.size();
                            if (it != tbl->sinks_.end() &&
                                !it->second.cancel &&
                                off + n <= it->second.cap) {
                                delivered = tbl->place_deduped(
                                    it->second, tag, off, bytes.data(), n);
                                placed = true;
                            } else if (!tbl->is_retired(tag)) {
                                // model-checker finding: same exact-duplicate
                                // queue dedupe as the undelayed path — a
                                // dropped copy stays placed=false and is
                                // charged as a dup below
                                auto &q = tbl->queues_[tag];
                                bool dup_q = false;
                                for (const auto &f : q)
                                    if (f.size() == 8 + n &&
                                        memcmp(f.data(), &off, 8) == 0) {
                                        dup_q = true;
                                        break;
                                    }
                                if (!dup_q) {
                                    std::vector<uint8_t> qf(8 + n);
                                    memcpy(qf.data(), &off, 8);
                                    if (n > 0)
                                        memcpy(qf.data() + 8, bytes.data(),
                                               n);
                                    q.push_back(std::move(qf));
                                    delivered = n;
                                    placed = true;
                                }
                            }
                        }
                        if (!placed || delivered < bytes.size())
                            ec->dup_bytes.fetch_add(
                                bytes.size() - (placed ? delivered : 0),
                                std::memory_order_relaxed);
                        (void)dom;  // keeps the counter domain alive
                        tbl->signal_tag(tag);
                    });
                continue;
            }
            size_t delivered = n;
            bool placed = true;
            {
                // re-check: a sink may have been registered while we were in
                // recv_all above — queueing now would strand the bytes where
                // wait_filled never looks (this was a real deadlock)
                MutexLock lk(table_->mu_);
                auto it = table_->sinks_.find(tag);
                if (it != table_->sinks_.end() && !it->second.cancel &&
                    off + n <= it->second.cap) {
                    delivered = table_->place_deduped(it->second, tag, off,
                                                      scratch.data(), n);
                } else if (!table_->is_retired(tag)) {
                    // queued frames carry their offset in the first 8 bytes.
                    // model-checker finding: a re-issued window racing sink
                    // registration must not queue twice — register_sink's
                    // drain publishes extents with no dup accounting, so an
                    // exact (off, len) duplicate would double-publish
                    // uncounted. Drop it here and charge it as a dup.
                    auto &q = table_->queues_[tag];
                    bool dup_q = false;
                    for (const auto &f : q)
                        if (f.size() == 8 + n &&
                            memcmp(f.data(), &off, 8) == 0) {
                            dup_q = true;
                            break;
                        }
                    if (dup_q) {
                        placed = false;
                    } else {
                        std::vector<uint8_t> qf(8 + n);
                        memcpy(qf.data(), &off, 8);
                        if (n > 0) memcpy(qf.data() + 8, scratch.data(), n);
                        q.push_back(std::move(qf));
                    }
                } else {
                    // retired tag: straggler from a purged op — drop
                    placed = false;
                }
            }
            if (!placed || delivered < n)
                edge().dup_bytes.fetch_add(n - (placed ? delivered : 0),
                                           std::memory_order_relaxed);
            table_->signal_tag(tag);
        }
    }
    alive_ = false;
    if (telemetry::Recorder::inst().on())
        telemetry::Recorder::inst().instant(
            "edge", "conn_down", nullptr, 0, nullptr, 0,
            edge_label_.load(std::memory_order_relaxed));
    tx_ev_.signal(); // wake the TX thread so it notices and drains
    fail_all_pending();
    table_->on_conn_dead();
}

void MultiplexConn::fail_all_pending() {
    std::map<std::pair<uint64_t, uint64_t>, SendHandle> pending;
    {
        MutexLock lk(cma_mu_);
        pending.swap(pending_cma_);
    }
    for (auto &[_, st] : pending) st->complete(false);
}

void MultiplexConn::close() {
    // serialize concurrent closers: the loser blocks until the winner has
    // fully torn down, then returns (concurrent join on one std::thread is
    // UB, so exactly one thread may run the sequence below)
    MutexLock close_lk(close_mu_);
    if (closed_) return;
    {
        MutexLock lk(cma_mu_); // enqueue gate: no pushes after this
        closing_ = true;
        alive_ = false;
    }
    tx_ev_.signal();
    sock_.shutdown();
    if (tx_thread_.joinable()) tx_thread_.join();
    if (rx_thread_.joinable()) rx_thread_.join();
    {
        // lazily-deferred MSG_ZEROCOPY notifs: the shutdown above freed the
        // socket's skbs, so every outstanding notif is posted (or posts
        // promptly) — drain them so tx_zc_reaps == tx_zc_frames holds at
        // quiescence, then drop the ring
        MutexLock wlk(wr_mu_);
        drop_tx_ring();
    }
    if (wire_) wire_->release_lane(lane_.load(std::memory_order_relaxed));
    // drain stragglers that were pushed before the gate closed
    mpsc::Node *n;
    while ((n = txq_.pop()) != nullptr) {
        auto *req = static_cast<SendReq *>(n);
        if (req->state) req->state->complete(false);
        delete req;
    }
    fail_all_pending();
    sock_.close();
    table_->on_conn_dead();
    // mappings stay alive (see ShmMap comment): an op thread that resolved
    // a pointer before close() may still be mid-copy. ~MultiplexConn —
    // which cannot run until every such thread drops its shared_ptr —
    // does the actual munmaps.
    closed_ = true;
}

// ---------- Link ----------

bool Link::alive() const {
    for (const auto &c : conns_)
        if (c && c->alive()) return true;
    return false;
}

std::vector<SendHandle> Link::send_async(uint64_t tag, std::span<const uint8_t> payload,
                                         size_t rot, bool allow_cma) {
    std::vector<std::shared_ptr<MultiplexConn>> live;
    for (const auto &c : conns_)
        if (c && c->alive()) live.push_back(c);
    if (live.empty()) {
        auto st = std::make_shared<SendState>();
        st->complete(false);
        return {st};
    }
    auto &first = live[rot % live.size()];
    // CMA sends have no wire bottleneck to stripe around; small payloads
    // aren't worth the extra frames
    constexpr size_t kStripeMin = 4 << 20;
    if (live.size() == 1 || payload.size() < kStripeMin ||
        (allow_cma && first->cma_eligible()))
        return {first->send_async(tag, 0, payload, allow_cma)};
    std::vector<SendHandle> hs;
    size_t k = live.size();
    size_t seg = (payload.size() + k - 1) / k;
    seg = (seg + 4095) & ~size_t(4095); // page-align stripe boundaries
    for (size_t i = 0, off = 0; i < k && off < payload.size(); ++i, off += seg) {
        size_t n = std::min(seg, payload.size() - off);
        hs.push_back(live[(rot + i) % k]->send_async(tag, off, payload.subspan(off, n),
                                                     allow_cma));
    }
    return hs;
}

SendHandle Link::send_at(uint64_t tag, uint64_t off,
                         std::span<const uint8_t> payload, size_t rot) {
    std::vector<std::shared_ptr<MultiplexConn>> live;
    for (const auto &c : conns_)
        if (c && c->alive()) live.push_back(c);
    if (live.empty()) {
        auto st = std::make_shared<SendState>();
        st->complete(false);
        return st;
    }
    // one stream per window; rotating windows across the pool stripes a
    // stage over parallel TCP streams. allow_cma=false: a window is a
    // partial span the fused same-host claim cannot cover.
    return live[rot % live.size()]->send_async(tag, off, payload,
                                               /*allow_cma=*/false);
}

bool Link::cma_eligible() const {
    for (const auto &c : conns_)
        if (c && c->alive() && c->cma_eligible()) return true;
    return false;
}

SendHandle Link::send_meta(uint64_t tag, std::vector<uint8_t> payload) {
    for (const auto &c : conns_)
        if (c && c->alive()) return c->send_copy(tag, std::move(payload));
    auto st = std::make_shared<SendState>();
    st->complete(false);
    return st;
}

SendHandle Link::send_meta_at(uint64_t tag, uint64_t off,
                              std::vector<uint8_t> payload) {
    // per-window quantization metas (docs/08): offset-keyed small owned
    // frames; tag has no sink, so the receiver reads them back through
    // recv_queued_any. Rotating conns would gain nothing (metas are ~100 B)
    // — any live conn serves.
    for (const auto &c : conns_)
        if (c && c->alive())
            return c->send_owned(MultiplexConn::kData, tag, off,
                                 std::move(payload));
    auto st = std::make_shared<SendState>();
    st->complete(false);
    return st;
}

bool Link::wait_all(const std::vector<SendHandle> &hs, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    bool ok = true;
    for (const auto &h : hs) {
        int left = -1;
        if (timeout_ms >= 0) {
            auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                          deadline - std::chrono::steady_clock::now())
                          .count();
            left = static_cast<int>(ms < 0 ? 0 : ms);
        }
        if (!h->wait(left)) ok = false;
    }
    return ok;
}

} // namespace pcclt::net
