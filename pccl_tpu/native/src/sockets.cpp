#include "sockets.hpp"

#include <arpa/inet.h>
#include <cerrno>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include "log.hpp"
#include "wire.hpp"

namespace pcclt::net {

// ---------- Addr ----------

std::string Addr::str() const {
    struct in_addr a;
    a.s_addr = htonl(ip);
    char buf[INET_ADDRSTRLEN];
    inet_ntop(AF_INET, &a, buf, sizeof buf);
    return std::string(buf) + ":" + std::to_string(port);
}

std::optional<Addr> Addr::parse(const std::string &ip_str, uint16_t port) {
    struct in_addr a;
    if (inet_pton(AF_INET, ip_str.c_str(), &a) != 1) return std::nullopt;
    return Addr{ntohl(a.s_addr), port};
}

// ---------- Socket ----------

bool Socket::connect(const Addr &addr, int timeout_ms) {
    close();
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return false;
    struct sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons(addr.port);
    sa.sin_addr.s_addr = htonl(addr.ip);

    // non-blocking connect with timeout, then back to blocking
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = ::connect(fd, reinterpret_cast<sockaddr *>(&sa), sizeof sa);
    if (rc != 0 && errno != EINPROGRESS) {
        ::close(fd);
        return false;
    }
    if (rc != 0) {
        struct pollfd pfd{fd, POLLOUT, 0};
        rc = ::poll(&pfd, 1, timeout_ms);
        if (rc <= 0) {
            ::close(fd);
            return false;
        }
        int err = 0;
        socklen_t len = sizeof err;
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
        if (err != 0) {
            ::close(fd);
            return false;
        }
    }
    fcntl(fd, F_SETFL, flags);
    fd_ = fd;
    set_nodelay();
    return true;
}

bool Socket::send_all(const void *data, size_t n) {
    auto *p = static_cast<const uint8_t *>(data);
    while (n > 0) {
        int fd = fd_.load();
        if (fd < 0) return false;
        ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
        if (w < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        p += w;
        n -= static_cast<size_t>(w);
    }
    return true;
}

bool Socket::recv_all(void *data, size_t n) {
    auto *p = static_cast<uint8_t *>(data);
    while (n > 0) {
        int fd = fd_.load();
        if (fd < 0) return false;
        ssize_t r = ::recv(fd, p, n, 0);
        if (r < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        if (r == 0) return false;
        p += r;
        n -= static_cast<size_t>(r);
    }
    return true;
}

ssize_t Socket::recv_some(void *data, size_t n, int timeout_ms) {
    int fd = fd_.load();
    if (fd < 0) return -1;
    struct pollfd pfd{fd, POLLIN, 0};
    int rc = ::poll(&pfd, 1, timeout_ms);
    if (rc == 0) return -2;
    if (rc < 0) return -1;
    ssize_t r = ::recv(fd, data, n, 0);
    return r < 0 ? -1 : r;
}

void Socket::shutdown() {
    int fd = fd_.load();
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
}

void Socket::close() {
    int fd = fd_.exchange(-1);
    if (fd >= 0) ::close(fd);
}

void Socket::set_nodelay() {
    int one = 1;
    setsockopt(fd_.load(), IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
}

void Socket::set_bufsizes(int bytes) {
    int fd = fd_.load();
    if (fd < 0) return;
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bytes, sizeof bytes);
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bytes, sizeof bytes);
}

void Socket::set_keepalive(int idle_s) {
    int fd = fd_.load();
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof one);
    setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle_s, sizeof idle_s);
    int intvl = 5, cnt = 3;
    setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof intvl);
    setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof cnt);
}

Addr Socket::peer_addr() const {
    struct sockaddr_in sa{};
    socklen_t len = sizeof sa;
    if (getpeername(fd_.load(), reinterpret_cast<sockaddr *>(&sa), &len) != 0) return {};
    return Addr{ntohl(sa.sin_addr.s_addr), ntohs(sa.sin_port)};
}

// ---------- control framing ----------

bool send_frame(Socket &s, std::mutex &write_mu, uint16_t type,
                std::span<const uint8_t> payload) {
    uint32_t len = static_cast<uint32_t>(2 + payload.size());
    uint8_t hdr[6];
    uint32_t be_len = wire::to_be(len);
    uint16_t be_type = wire::to_be(type);
    memcpy(hdr, &be_len, 4);
    memcpy(hdr + 4, &be_type, 2);
    std::lock_guard lk(write_mu);
    // small frames go out in one send: two back-to-back small writes would
    // otherwise interact badly with Nagle/delayed-ACK on control sockets
    if (payload.size() <= 64 << 10) {
        uint8_t buf[6 + (64 << 10)];
        memcpy(buf, hdr, 6);
        if (!payload.empty()) memcpy(buf + 6, payload.data(), payload.size());
        return s.send_all(buf, 6 + payload.size());
    }
    if (!s.send_all(hdr, 6)) return false;
    return s.send_all(payload.data(), payload.size());
}

// single implementation: timeout_ms < 0 blocks forever (plain recv_all),
// otherwise the whole frame must arrive before the deadline
static std::optional<Frame> recv_frame_impl(Socket &s, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    auto recv_n = [&](uint8_t *dst, size_t n) -> bool {
        if (timeout_ms < 0) return s.recv_all(dst, n);
        size_t off = 0;
        while (off < n) {
            auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                            deadline - std::chrono::steady_clock::now())
                            .count();
            if (left <= 0) return false;
            ssize_t r = s.recv_some(dst + off, n - off,
                                    static_cast<int>(std::min<long long>(left, 200)));
            if (r == -2) continue; // poll slice elapsed; re-check deadline
            if (r <= 0) return false;
            off += static_cast<size_t>(r);
        }
        return true;
    };
    uint8_t hdr[6];
    if (!recv_n(hdr, 6)) return std::nullopt;
    uint32_t be_len;
    uint16_t be_type;
    memcpy(&be_len, hdr, 4);
    memcpy(&be_type, hdr + 4, 2);
    uint32_t len = wire::from_be(be_len);
    if (len < 2 || len > wire::kMaxControlPacket) {
        PLOG(kError) << "recv_frame: bad length " << len;
        return std::nullopt;
    }
    Frame f;
    f.type = wire::from_be(be_type);
    f.payload.resize(len - 2);
    if (!f.payload.empty() && !recv_n(f.payload.data(), f.payload.size()))
        return std::nullopt;
    return f;
}

std::optional<Frame> recv_frame(Socket &s) { return recv_frame_impl(s, -1); }

std::optional<Frame> recv_frame(Socket &s, int timeout_ms) {
    return recv_frame_impl(s, timeout_ms);
}

// ---------- Listener ----------

bool Listener::listen(uint16_t port, int tries, bool loopback_only) {
    for (int i = 0; i < tries; ++i) {
        int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0) return false;
        int one = 1;
        setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
        struct sockaddr_in sa{};
        sa.sin_family = AF_INET;
        sa.sin_port = htons(static_cast<uint16_t>(port + i));
        sa.sin_addr.s_addr = htonl(loopback_only ? INADDR_LOOPBACK : INADDR_ANY);
        if (bind(fd, reinterpret_cast<sockaddr *>(&sa), sizeof sa) == 0 &&
            ::listen(fd, 64) == 0) {
            fd_ = fd;
            port_ = static_cast<uint16_t>(port + i);
            return true;
        }
        ::close(fd);
    }
    return false;
}

void Listener::run_async(std::function<void(Socket)> on_accept) {
    running_ = true;
    thread_ = std::thread([this, on_accept = std::move(on_accept)] {
        while (running_.load()) {
            struct pollfd pfd{fd_, POLLIN, 0};
            int rc = ::poll(&pfd, 1, 200);
            if (rc < 0 && errno != EINTR) break;
            if (rc <= 0) continue;
            int cfd = ::accept(fd_, nullptr, nullptr);
            if (cfd < 0) continue;
            Socket s(cfd);
            // accepted sockets carry small control replies (commence/abort/
            // done); without NODELAY those hit Nagle+delayed-ACK stalls
            s.set_nodelay();
            on_accept(std::move(s));
        }
    });
}

void Listener::stop() {
    running_ = false;
    if (thread_.joinable()) thread_.join();
    if (fd_ >= 0) {
        ::close(fd_);
        fd_ = -1;
    }
}

// ---------- ControlClient ----------

bool ControlClient::connect(const Addr &addr) {
    if (!sock_.connect(addr)) return false;
    sock_.set_keepalive();
    connected_ = true;
    return true;
}

void ControlClient::run(std::function<void()> on_disconnect) {
    on_disconnect_ = std::move(on_disconnect);
    reader_ = std::thread([this] {
        while (connected_.load()) {
            auto f = recv_frame(sock_);
            if (!f) break;
            {
                std::lock_guard lk(mu_);
                queue_.push_back(std::move(*f));
            }
            cv_.notify_all();
        }
        bool was = connected_.exchange(false);
        cv_.notify_all();
        if (was && on_disconnect_) on_disconnect_();
    });
}

bool ControlClient::send(uint16_t type, std::span<const uint8_t> payload) {
    if (!connected_.load()) return false;
    return send_frame(sock_, write_mu_, type, payload);
}

std::optional<Frame> ControlClient::recv_match(uint16_t type, const Pred &pred,
                                               int timeout_ms, bool no_wait) {
    std::unique_lock lk(mu_);
    auto scan = [&]() -> std::optional<Frame> {
        for (auto it = queue_.begin(); it != queue_.end(); ++it) {
            if (it->type == type && (!pred || pred(it->payload))) {
                Frame f = std::move(*it);
                queue_.erase(it);
                return f;
            }
        }
        return std::nullopt;
    };
    if (auto f = scan()) return f;
    if (no_wait) return std::nullopt;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    while (connected_.load()) {
        if (timeout_ms < 0) {
            cv_.wait_for(lk, std::chrono::seconds(1)); // forever, re-armed
        } else if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
            return scan(); // last chance
        }
        if (auto f = scan()) return f;
        if (timeout_ms >= 0 && std::chrono::steady_clock::now() >= deadline)
            return std::nullopt;
    }
    return scan();
}

std::optional<Frame> ControlClient::recv_match_any(const std::vector<uint16_t> &types,
                                                   const FramePred &pred, int timeout_ms,
                                                   bool no_wait) {
    std::unique_lock lk(mu_);
    auto scan = [&]() -> std::optional<Frame> {
        for (auto it = queue_.begin(); it != queue_.end(); ++it) {
            bool type_ok = false;
            for (auto t : types)
                if (it->type == t) type_ok = true;
            if (type_ok && (!pred || pred(*it))) {
                Frame f = std::move(*it);
                queue_.erase(it);
                return f;
            }
        }
        return std::nullopt;
    };
    if (auto f = scan()) return f;
    if (no_wait) return std::nullopt;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    while (connected_.load()) {
        if (timeout_ms < 0) {
            cv_.wait_for(lk, std::chrono::seconds(1));
        } else if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
            return scan();
        }
        if (auto f = scan()) return f;
        if (timeout_ms >= 0 && std::chrono::steady_clock::now() >= deadline)
            return std::nullopt;
    }
    return scan();
}

void ControlClient::close() {
    connected_ = false;
    sock_.shutdown();
    if (reader_.joinable()) reader_.join();
    sock_.close();
    cv_.notify_all();
}

// ---------- MultiplexConn ----------

void MultiplexConn::run() {
    alive_ = true;
    rx_thread_ = std::thread([this] { rx_loop(); });
}

bool MultiplexConn::send_bytes(uint64_t tag, uint64_t seq,
                               std::span<const uint8_t> data, size_t chunk) {
    size_t off = 0;
    do {
        size_t n = std::min(chunk, data.size() - off);
        uint8_t hdr[20];
        uint32_t be_len = wire::to_be(static_cast<uint32_t>(16 + n));
        uint64_t be_tag = wire::to_be(tag);
        uint64_t be_seq = wire::to_be(seq);
        memcpy(hdr, &be_len, 4);
        memcpy(hdr + 4, &be_tag, 8);
        memcpy(hdr + 12, &be_seq, 8);
        std::lock_guard lk(write_mu_);
        if (!sock_.send_all(hdr, 20)) return false;
        if (n > 0 && !sock_.send_all(data.data() + off, n)) return false;
        off += n;
    } while (off < data.size());
    return true;
}

void MultiplexConn::register_sink(uint64_t tag, uint8_t *base, size_t cap) {
    std::lock_guard lk(mu_);
    Sink s{base, cap, 0};
    // frames that raced ahead of registration are queued; drain them in order
    auto it = queues_.find(tag);
    if (it != queues_.end()) {
        for (auto &buf : it->second) {
            size_t n = std::min(buf.size(), s.cap - s.filled);
            memcpy(s.base + s.filled, buf.data(), n);
            s.filled += n;
        }
        queues_.erase(it);
    }
    sinks_[tag] = s;
    cv_.notify_all();
}

size_t MultiplexConn::wait_filled(uint64_t tag, size_t min_bytes, int timeout_ms) {
    std::unique_lock lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    while (true) {
        auto it = sinks_.find(tag);
        if (it == sinks_.end()) return 0;
        if (it->second.filled >= min_bytes) return it->second.filled;
        if (!alive_.load()) return it->second.filled;
        if (timeout_ms < 0) {
            cv_.wait_for(lk, std::chrono::seconds(1)); // forever, re-armed
        } else if (cv_.wait_until(lk, deadline) == std::cv_status::timeout ||
                   std::chrono::steady_clock::now() >= deadline) {
            auto it2 = sinks_.find(tag);
            return it2 == sinks_.end() ? 0 : it2->second.filled;
        }
    }
}

void MultiplexConn::unregister_sink(uint64_t tag) {
    std::unique_lock lk(mu_);
    // The RX thread may be mid-recv into the sink buffer outside the lock.
    // Mark the sink cancelled: the RX thread checks between bounded slices,
    // redirects the rest of the frame to scratch, and clears busy — the
    // connection stays healthy. Only if the wire makes NO progress for 5 s
    // (genuinely stalled peer) do we shutdown to free the caller's buffer.
    auto it0 = sinks_.find(tag);
    if (it0 != sinks_.end()) it0->second.cancel = true;
    auto busy = [&] {
        auto it = sinks_.find(tag);
        return it != sinks_.end() && it->second.busy;
    };
    if (busy()) {
        if (!cv_.wait_for(lk, std::chrono::seconds(5), [&] { return !busy(); })) {
            sock_.shutdown();
            cv_.wait(lk, [&] { return !busy(); }); // recv now fails promptly
        }
    }
    sinks_.erase(tag);
}

std::optional<std::vector<uint8_t>> MultiplexConn::recv_queued(
    uint64_t tag, int timeout_ms, const std::atomic<bool> *abort) {
    std::unique_lock lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    while (true) {
        auto it = queues_.find(tag);
        if (it != queues_.end() && !it->second.empty()) {
            auto v = std::move(it->second.front());
            it->second.pop_front();
            return v;
        }
        if (!alive_.load()) return std::nullopt;
        if (abort && abort->load()) return std::nullopt;
        cv_.wait_for(lk, std::chrono::milliseconds(50));
        if (timeout_ms >= 0 && std::chrono::steady_clock::now() >= deadline)
            return std::nullopt;
    }
}

void MultiplexConn::purge_range(uint64_t lo, uint64_t hi) {
    std::unique_lock lk(mu_);
    for (auto &[tag, s] : sinks_)
        if (tag >= lo && tag < hi) s.cancel = true;
    auto any_busy = [&] {
        for (auto &[tag, s] : sinks_)
            if (tag >= lo && tag < hi && s.busy) return true;
        return false;
    };
    if (any_busy()) {
        if (!cv_.wait_for(lk, std::chrono::seconds(5), [&] { return !any_busy(); })) {
            sock_.shutdown(); // peer made no progress at all: last resort
            cv_.wait(lk, [&] { return !any_busy(); });
        }
    }
    for (auto it = sinks_.begin(); it != sinks_.end();)
        it = (it->first >= lo && it->first < hi) ? sinks_.erase(it) : std::next(it);
    for (auto it = queues_.begin(); it != queues_.end();)
        it = (it->first >= lo && it->first < hi) ? queues_.erase(it) : std::next(it);
}

void MultiplexConn::rx_loop() {
    std::vector<uint8_t> scratch;
    while (alive_.load()) {
        uint8_t hdr[20];
        if (!sock_.recv_all(hdr, 20)) break;
        uint32_t be_len;
        uint64_t be_tag, be_seq;
        memcpy(&be_len, hdr, 4);
        memcpy(&be_tag, hdr + 4, 8);
        memcpy(&be_seq, hdr + 12, 8);
        uint32_t len = wire::from_be(be_len);
        uint64_t tag = wire::from_be(be_tag);
        if (len < 16 || len > (272u << 20)) {
            PLOG(kError) << "multiplex rx: bad frame length " << len;
            break;
        }
        size_t n = len - 16;

        // sink fast path: read straight into the registered destination.
        // busy marks the sink so unregister/purge cannot free the buffer
        // while we write outside the lock; the frame is read in bounded
        // slices so a cancel request (op abort) is honoured promptly without
        // killing the connection.
        constexpr size_t kSlice = 256 << 10;
        uint8_t *dst = nullptr;
        {
            std::lock_guard lk(mu_);
            auto it = sinks_.find(tag);
            if (it != sinks_.end() && !it->second.cancel &&
                it->second.filled + n <= it->second.cap) {
                dst = it->second.base + it->second.filled;
                it->second.busy = true;
            }
        }
        if (dst) {
            bool ok = true, cancelled = false;
            size_t off = 0;
            while (off < n && ok) {
                size_t want = std::min(kSlice, n - off);
                if (!cancelled) {
                    ok = sock_.recv_all(dst + off, want);
                } else {
                    scratch.resize(want); // drain + drop the rest of the frame
                    ok = sock_.recv_all(scratch.data(), want);
                }
                off += want;
                if (ok && !cancelled && off < n) {
                    std::lock_guard lk(mu_);
                    auto it = sinks_.find(tag);
                    cancelled = it == sinks_.end() || it->second.cancel;
                }
            }
            {
                std::lock_guard lk(mu_);
                auto it = sinks_.find(tag);
                if (it != sinks_.end()) {
                    it->second.busy = false;
                    if (ok && !cancelled) it->second.filled += n;
                }
            }
            cv_.notify_all();
            if (!ok) break;
        } else {
            scratch.resize(n);
            if (n > 0 && !sock_.recv_all(scratch.data(), n)) break;
            {
                // re-check: a sink may have been registered while we were in
                // recv_all above — queueing now would strand the bytes where
                // wait_filled never looks (this was a real deadlock)
                std::lock_guard lk(mu_);
                auto it = sinks_.find(tag);
                if (it != sinks_.end() && !it->second.cancel &&
                    it->second.filled + n <= it->second.cap) {
                    memcpy(it->second.base + it->second.filled, scratch.data(), n);
                    it->second.filled += n;
                } else {
                    queues_[tag].push_back(scratch);
                }
            }
            cv_.notify_all();
        }
    }
    alive_ = false;
    cv_.notify_all();
}

void MultiplexConn::close() {
    alive_ = false;
    sock_.shutdown();
    if (rx_thread_.joinable()) rx_thread_.join();
    sock_.close();
    cv_.notify_all();
}

} // namespace pcclt::net
