#include "netem.hpp"

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <optional>
#include <thread>

#include "log.hpp"

namespace pcclt::net::netem {

namespace {

uint64_t mono_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
           static_cast<uint64_t>(ts.tv_nsec);
}

uint64_t splitmix64(uint64_t &s) {
    uint64_t z = (s += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
}

// strip leading/trailing spaces (map values often come from shell strings)
std::string trim(const std::string &s) {
    size_t a = s.find_first_not_of(" \t");
    if (a == std::string::npos) return "";
    size_t b = s.find_last_not_of(" \t");
    return s.substr(a, b - a + 1);
}

}  // namespace

// ---------- chaos schedules ----------

namespace {

// process-wide chaos accounting (CHAOS SUMMARY)
std::atomic<uint64_t> g_chaos_armed{0};
std::atomic<uint64_t> g_chaos_activated{0};

}  // namespace

// "5s" / "200ms" / bare seconds -> ns; nullopt on garbage
std::optional<uint64_t> parse_dur_ns(const std::string &s) {
    char *endp = nullptr;
    double v = strtod(s.c_str(), &endp);
    if (endp == s.c_str() || !(v >= 0) || !std::isfinite(v)) return std::nullopt;
    std::string unit = trim(endp);
    double scale;
    if (unit.empty() || unit == "s") scale = 1e9;
    else if (unit == "ms") scale = 1e6;
    else return std::nullopt;
    return static_cast<uint64_t>(v * scale);
}

std::vector<ChaosFault> parse_chaos(const std::string &spec, const char *what) {
    std::vector<ChaosFault> out;
    size_t pos = 0;
    while (pos <= spec.size()) {
        size_t semi = spec.find(';', pos);
        std::string f = trim(spec.substr(
            pos, semi == std::string::npos ? std::string::npos : semi - pos));
        pos = semi == std::string::npos ? spec.size() + 1 : semi + 1;
        if (f.empty()) continue;
        auto bad = [&](const char *why) {
            PLOG(kWarn) << what << ": skipping malformed fault '" << f << "' ("
                        << why << ")";
        };
        // <kind>@t=<T>:<args>   (@t=... optional: omitted = fire on arming)
        size_t at = f.find('@');
        std::string kind = trim(at == std::string::npos ? f.substr(0, f.find(':'))
                                                        : f.substr(0, at));
        ChaosFault cf;
        std::string args;
        if (at != std::string::npos) {
            size_t colon = f.find(':', at);
            if (colon == std::string::npos) {
                bad("want kind@t=T:args");
                continue;
            }
            std::string t = trim(f.substr(at + 1, colon - at - 1));
            if (t.rfind("t=", 0) != 0) {
                bad("want t=<time> after '@'");
                continue;
            }
            auto tn = parse_dur_ns(t.substr(2));
            if (!tn) {
                bad("bad start time");
                continue;
            }
            cf.start_ns = *tn;
            args = trim(f.substr(colon + 1));
        } else {
            size_t colon = f.find(':');
            args = colon == std::string::npos ? "" : trim(f.substr(colon + 1));
        }
        if (kind == "degrade") {
            // <R>mbit/<D>
            size_t slash = args.find('/');
            if (slash == std::string::npos) {
                bad("want <rate>mbit/<duration>");
                continue;
            }
            std::string rate = trim(args.substr(0, slash));
            if (rate.size() > 4 && rate.substr(rate.size() - 4) == "mbit")
                rate = trim(rate.substr(0, rate.size() - 4));
            char *endp = nullptr;
            double r = strtod(rate.c_str(), &endp);
            auto d = parse_dur_ns(trim(args.substr(slash + 1)));
            if (endp == rate.c_str() || *trim(endp).c_str() != '\0' ||
                !(r > 0) || !std::isfinite(r) || !d || *d == 0) {
                bad("bad rate or duration");
                continue;
            }
            cf.kind = ChaosFault::kDegrade;
            cf.mbps = r;
            cf.dur_ns = *d;
        } else if (kind == "flap") {
            // <D>x<N>  ('x' or the Unicode '×')
            size_t x = args.find('x');
            size_t cut = x, skip = 1;
            if (x == std::string::npos) {
                cut = args.find("\xc3\x97");  // UTF-8 '×'
                skip = 2;
            }
            if (cut == std::string::npos) {
                bad("want <duration>x<count>");
                continue;
            }
            auto d = parse_dur_ns(trim(args.substr(0, cut)));
            long n = atol(trim(args.substr(cut + skip)).c_str());
            if (!d || *d == 0 || n <= 0 || n > 100000) {
                bad("bad duration or count");
                continue;
            }
            cf.kind = ChaosFault::kFlap;
            cf.dur_ns = *d;
            cf.repeat = static_cast<uint32_t>(n);
        } else if (kind == "blackhole") {
            auto d = parse_dur_ns(args);
            if (!d || *d == 0) {
                bad("bad duration");
                continue;
            }
            cf.kind = ChaosFault::kBlackhole;
            cf.dur_ns = *d;
        } else {
            bad("unknown fault kind");
            continue;
        }
        out.push_back(cf);
    }
    return out;
}

ChaosStats chaos_stats() {
    return {g_chaos_armed.load(std::memory_order_relaxed),
            g_chaos_activated.load(std::memory_order_relaxed)};
}

// ---------- Edge ----------

void Edge::arm_chaos(std::vector<ChaosFault> faults) {
    MutexLock lk(mu_);
    chaos_ = std::move(faults);
    chaos_t0_ = mono_ns();
    fired_outages_.assign(chaos_.size(), 0);
    chaos_armed_.store(!chaos_.empty(), std::memory_order_relaxed);
    if (!chaos_.empty())
        g_chaos_armed.fetch_add(chaos_.size(), std::memory_order_relaxed);
}

ChaosVerdict Edge::chaos_at(uint64_t now_ns) {
    if (!chaos_armed_.load(std::memory_order_relaxed)) return {};
    if (now_ns == 0) now_ns = mono_ns();
    MutexLock lk(mu_);
    return chaos_eval(now_ns);
}

// Shared by pace()/delivery_delay_ns() (which already hold mu_) and
// chaos_at. Scans the (tiny) fault list; counts newly-observed fault
// windows into the process activation counter.
ChaosVerdict Edge::chaos_eval(uint64_t now_ns) {
    ChaosVerdict v;
    for (size_t i = 0; i < chaos_.size(); ++i) {
        const ChaosFault &f = chaos_[i];
        uint64_t t0 = chaos_t0_ + f.start_ns;
        if (now_ns < t0) continue;
        uint64_t rel = now_ns - t0;
        if (f.kind == ChaosFault::kDegrade) {
            if (rel < f.dur_ns) {
                v.mbps_override = f.mbps;  // last active degrade wins
                if (fired_outages_[i] == 0) {
                    fired_outages_[i] = 1;
                    g_chaos_activated.fetch_add(1, std::memory_order_relaxed);
                }
            }
        } else {
            // flap: outage windows of dur_ns at period 2*dur_ns, repeat
            // times; blackhole: one outage window
            uint64_t period = f.kind == ChaosFault::kFlap ? 2 * f.dur_ns
                                                          : f.dur_ns;
            uint32_t reps = f.kind == ChaosFault::kFlap ? f.repeat : 1;
            uint64_t k = rel / period;
            if (k < reps && rel - k * period < f.dur_ns) {
                v.outage = true;
                v.outage_end_ns =
                    std::max(v.outage_end_ns, t0 + k * period + f.dur_ns);
                if (fired_outages_[i] < k + 1) {
                    g_chaos_activated.fetch_add(k + 1 - fired_outages_[i],
                                                std::memory_order_relaxed);
                    fired_outages_[i] = static_cast<uint32_t>(k + 1);
                }
            }
        }
    }
    return v;
}

void Edge::configure(const EdgeParams &p) {
    ns_per_byte_.store(p.mbps > 0 ? 8000.0 / p.mbps : 0.0,
                       std::memory_order_relaxed);
    // per-flow cwnd cap: a lane drains at most cwnd/rtt bytes/s (needs a
    // modeled rtt — on a zero-latency wire TCP's window never binds)
    cwnd_npb_.store(p.cwnd_bytes > 0 && p.rtt_ms > 0
                        ? (p.rtt_ms * 1e6) / p.cwnd_bytes
                        : 0.0,
                    std::memory_order_relaxed);
    owd_ns_.store(p.rtt_ms > 0 ? static_cast<uint64_t>(p.rtt_ms * 0.5e6) : 0,
                  std::memory_order_relaxed);
    jitter_ns_.store(
        p.jitter_ms > 0 ? static_cast<uint64_t>(p.jitter_ms * 1e6) : 0,
        std::memory_order_relaxed);
    drop_.store(p.drop > 0 ? std::min(p.drop, 1.0) : 0.0,
                std::memory_order_relaxed);
}

EdgeParams Edge::params() const {
    EdgeParams p;
    double npb = ns_per_byte_.load(std::memory_order_relaxed);
    p.mbps = npb > 0 ? 8000.0 / npb : 0.0;
    p.rtt_ms = static_cast<double>(owd_ns_.load(std::memory_order_relaxed)) /
               0.5e6;
    double cn = cwnd_npb_.load(std::memory_order_relaxed);
    p.cwnd_bytes = cn > 0 && p.rtt_ms > 0 ? (p.rtt_ms * 1e6) / cn : 0.0;
    p.jitter_ms =
        static_cast<double>(jitter_ns_.load(std::memory_order_relaxed)) / 1e6;
    p.drop = drop_.load(std::memory_order_relaxed);
    return p;
}

uint32_t Edge::alloc_lane() {
    MutexLock lk(mu_);
    for (size_t l = 1; l < lane_used_.size(); ++l)
        if (!lane_used_[l]) {
            lane_used_[l] = 1;
            lane_next_[l] = 0;
            return static_cast<uint32_t>(l);
        }
    lane_used_.push_back(1);
    lane_next_.push_back(0);
    return static_cast<uint32_t>(lane_used_.size() - 1);
}

void Edge::release_lane(uint32_t lane) {
    MutexLock lk(mu_);
    if (lane > 0 && lane < lane_used_.size()) lane_used_[lane] = 0;
}

void Edge::pace(size_t bytes, uint32_t lane) {
    double npb = ns_per_byte_.load(std::memory_order_relaxed);
    const double cwnd_npb = cwnd_npb_.load(std::memory_order_relaxed);
    const bool armed = chaos_armed_.load(std::memory_order_relaxed);
    if (npb <= 0 && cwnd_npb <= 0 && !armed) return;
    uint64_t end;
    {
        MutexLock lk(mu_);
        uint64_t now = mono_ns();
        if (lane >= lane_next_.size() || !lane_used_[lane]) lane = 0;
        // reserve the transmission slot [start, end) in THIS lane's
        // sub-schedule and sleep until the frame has fully drained — a
        // sender cannot complete a send faster than the wire carries it
        // (no burst credit: a lane's next never lags now)
        uint64_t start = std::max(lane_next_[lane], now);
        if (armed) {
            // chaos verdict at reservation time: an outage pushes the slot
            // past the outage window; a degrade caps the drain rate
            ChaosVerdict cv = chaos_eval(now);
            if (cv.outage) start = std::max(start, cv.outage_end_ns);
            if (cv.mbps_override > 0) npb = 8000.0 / cv.mbps_override;
        }
        // fair share: lanes still draining a prior reservation at `now`
        // split the modeled rate evenly with this one. Idle lanes count
        // zero — the work-conserving reclaim — so a single backlogged
        // lane drains at the full modeled rate (the exact pre-striping
        // behavior), K backlogged lanes sum to it.
        uint32_t active = 1;
        for (size_t l = 0; l < lane_next_.size(); ++l)
            if (l != lane && lane_used_[l] && lane_next_[l] > now) ++active;
        // per-flow cwnd cap (fat-long-pipe physics): one lane never drains
        // faster than cwnd/rtt even with the whole edge to itself — the
        // reason parallel flows (stripes) exist on real high-BDP links
        double lane_npb = std::max(npb * active, cwnd_npb);
        end = start +
              static_cast<uint64_t>(static_cast<double>(bytes) * lane_npb);
        lane_next_[lane] = end;
    }
    // small frames (ctl, quant metadata) charge the bucket but may run a
    // bounded window ahead of the wire: a real qdisc interleaves a sub-MTU
    // packet ~one chunk behind the current queue, not the full depth. The
    // bound matters — traffic composed ENTIRELY of small frames must still
    // be throttled, so beyond the window small frames pace like the rest.
    if (bytes <= 4096) {
        constexpr uint64_t kAheadNs = 40'000'000;  // ~2 chunk-times @ 100 Mbit
        if (end <= mono_ns() + kAheadNs) return;
        end -= kAheadNs;
    }
    for (uint64_t now = mono_ns(); now < end; now = mono_ns()) {
        uint64_t gap = end - now;
        struct timespec ts{static_cast<time_t>(gap / 1000000000ull),
                           static_cast<long>(gap % 1000000000ull)};
        nanosleep(&ts, nullptr);
    }
}

uint64_t Edge::delivery_delay_ns() {
    uint64_t d = owd_ns_.load(std::memory_order_relaxed);
    uint64_t jit = jitter_ns_.load(std::memory_order_relaxed);
    double drop = drop_.load(std::memory_order_relaxed);
    const bool armed = chaos_armed_.load(std::memory_order_relaxed);
    if (jit == 0 && drop <= 0 && !armed) return d;
    MutexLock lk(mu_);
    if (armed) {
        // a frame already off the (emulated) wire during an outage window
        // becomes visible only once the outage lifts
        uint64_t now = mono_ns();
        ChaosVerdict cv = chaos_eval(now);
        if (cv.outage && cv.outage_end_ns > now) d += cv.outage_end_ns - now;
    }
    if (jit > 0) d += splitmix64(rng_) % jit;
    if (drop > 0 &&
        static_cast<double>(splitmix64(rng_) >> 11) * 0x1.0p-53 < drop) {
        // TCP never loses a frame; a "dropped" one arrives an RTO late
        uint64_t rto = std::max<uint64_t>(
            2 * owd_ns_.load(std::memory_order_relaxed), 200'000'000ull);
        d += rto;
    }
    return d;
}

// ---------- DelayLine ----------

DelayLine &DelayLine::inst() {
    // intentionally leaked: the detached timer thread blocks on mu_/cv_
    // forever, so a static-destruction teardown would be UB at exit
    static DelayLine *d = new DelayLine;
    return *d;
}

void DelayLine::deliver(uint64_t delay_ns, std::function<void()> fn) {
    uint64_t at = mono_ns() + delay_ns;
    {
        MutexLock lk(mu_);
        q_.emplace(at, std::move(fn));
        if (!running_) {
            running_ = true;
            std::thread([this] { timer_loop(); }).detach();
        }
    }
    cv_.notify_one();
}

void DelayLine::timer_loop() {
    while (true) {
        std::function<void()> fn;
        {
            MutexLock lk(mu_);
            if (q_.empty()) {
                cv_.wait_for(mu_, std::chrono::seconds(1));
                continue;
            }
            uint64_t at = q_.begin()->first;
            uint64_t now = mono_ns();
            if (now < at) {
                cv_.wait_for(mu_, std::chrono::nanoseconds(at - now));
                continue;
            }
            fn = std::move(q_.begin()->second);
            q_.erase(q_.begin());
        }
        fn();
    }
}

// ---------- map parsing ----------

std::map<std::string, double> parse_map(const char *spec, const char *name) {
    std::map<std::string, double> out;
    if (!spec) return out;
    std::string s(spec);
    size_t pos = 0;
    while (pos <= s.size()) {
        size_t comma = s.find(',', pos);
        std::string entry =
            trim(s.substr(pos, comma == std::string::npos ? std::string::npos
                                                          : comma - pos));
        pos = comma == std::string::npos ? s.size() + 1 : comma + 1;
        if (entry.empty()) continue;
        // split on the LAST '=': v6 keys like [::1]:7000 contain no '=',
        // but being defensive costs nothing
        size_t eq = entry.rfind('=');
        if (eq == std::string::npos || eq == 0 || eq + 1 >= entry.size()) {
            PLOG(kWarn) << name << ": skipping malformed entry '" << entry
                        << "' (want key=value)";
            continue;
        }
        std::string key = trim(entry.substr(0, eq));
        std::string val = trim(entry.substr(eq + 1));
        char *endp = nullptr;
        double v = strtod(val.c_str(), &endp);
        if (key.empty() || !endp || *endp != '\0' || !(v >= 0) ||
            !std::isfinite(v)) {
            PLOG(kWarn) << name << ": skipping malformed entry '" << entry
                        << "' (bad key or value)";
            continue;
        }
        out[key] = v;
    }
    return out;
}

// ---------- Registry ----------

Registry &Registry::inst() {
    static Registry *r = new Registry;  // leaked: edges outlive any conn
    return *r;
}

namespace {
double env_f(const char *name) {
    if (const char *e = std::getenv(name)) {
        double v = atof(e);
        if (v > 0) return v;
    }
    return 0;
}
}  // namespace

// chaos-map split: values contain '=' (t=5s) and faults are ';'-joined,
// so the generic parse_map (last-'=' split, numeric values) cannot serve —
// split entries on ',' and the key at the FIRST '='
std::map<std::string, std::string> parse_chaos_map(const char *spec) {
    std::map<std::string, std::string> out;
    if (!spec) return out;
    std::string s(spec);
    size_t pos = 0;
    while (pos <= s.size()) {
        size_t comma = s.find(',', pos);
        std::string entry = trim(s.substr(
            pos, comma == std::string::npos ? std::string::npos : comma - pos));
        pos = comma == std::string::npos ? s.size() + 1 : comma + 1;
        if (entry.empty()) continue;
        size_t eq = entry.find('=');
        if (eq == std::string::npos || eq == 0 || eq + 1 >= entry.size()) {
            PLOG(kWarn) << "PCCLT_WIRE_CHAOS_MAP: skipping malformed entry '"
                        << entry << "' (want key=schedule)";
            continue;
        }
        out[trim(entry.substr(0, eq))] = trim(entry.substr(eq + 1));
    }
    return out;
}

void Registry::refresh() {
    MutexLock lk(mu_);
    mbps_ = parse_map(std::getenv("PCCLT_WIRE_MBPS_MAP"),
                      "PCCLT_WIRE_MBPS_MAP");
    rtt_ = parse_map(std::getenv("PCCLT_WIRE_RTT_MS_MAP"),
                     "PCCLT_WIRE_RTT_MS_MAP");
    jitter_ = parse_map(std::getenv("PCCLT_WIRE_JITTER_MS_MAP"),
                        "PCCLT_WIRE_JITTER_MS_MAP");
    drop_ = parse_map(std::getenv("PCCLT_WIRE_DROP_MAP"),
                      "PCCLT_WIRE_DROP_MAP");
    cwnd_ = parse_map(std::getenv("PCCLT_WIRE_CWND_MAP"),
                      "PCCLT_WIRE_CWND_MAP");
    chaos_specs_ = parse_chaos_map(std::getenv("PCCLT_WIRE_CHAOS_MAP"));
    global_.mbps = env_f("PCCLT_WIRE_MBPS");
    global_.rtt_ms = env_f("PCCLT_WIRE_RTT_MS");
    global_.jitter_ms = 0;
    global_.drop = 0;
    global_.cwnd_bytes = env_f("PCCLT_WIRE_CWND_BYTES");
    if (!default_) default_ = std::make_shared<Edge>();
    default_->configure(global_);
    // retune live edges in place: conns keep their shared_ptr (and their
    // shared bucket) across refreshes; keys that dropped out of the maps
    // fall back to the current global defaults field by field. Chaos
    // schedules arm ONCE per EDGE (an armed script keeps its t0 across
    // refreshes — a mid-run env re-read must not restart the timeline; an
    // ip-keyed schedule arms EVERY edge on that host, each on its own
    // timeline, so the armed marker is per edge key, not per spec key).
    for (auto &[key, e] : edges_) {
        e.edge->configure(params_for(e.exact_key, e.ip_key));
        auto cs = chaos_specs_.find(e.exact_key);
        if (cs == chaos_specs_.end()) cs = chaos_specs_.find(e.ip_key);
        if (cs != chaos_specs_.end() && !chaos_armed_keys_[key]) {
            chaos_armed_keys_[key] = true;
            e.edge->arm_chaos(parse_chaos(cs->second,
                                          "PCCLT_WIRE_CHAOS_MAP"));
        }
    }
}

EdgeParams Registry::params_for(const std::string &exact_key,
                                const std::string &ip_key) const {
    auto field = [&](const std::map<std::string, double> &m,
                     double global) -> double {
        auto it = m.find(exact_key);
        if (it != m.end()) return it->second;
        it = m.find(ip_key);
        if (it != m.end()) return it->second;
        return global;
    };
    EdgeParams p;
    p.mbps = field(mbps_, global_.mbps);
    p.rtt_ms = field(rtt_, global_.rtt_ms);
    p.jitter_ms = field(jitter_, global_.jitter_ms);
    p.drop = field(drop_, global_.drop);
    p.cwnd_bytes = field(cwnd_, global_.cwnd_bytes);
    return p;
}

std::shared_ptr<Edge> Registry::resolve(const Addr &peer) {
    std::string exact = peer.str();
    // bare-ip wildcard key: Addr::str() is "a.b.c.d:port" / "[v6]:port"
    std::string ip = exact.substr(0, exact.rfind(':'));
    MutexLock lk(mu_);
    // written out per key (not a helper lambda): a lambda body does not
    // inherit the caller's lock set under -Wthread-safety
    std::string match;
    if (mbps_.count(exact) || rtt_.count(exact) || jitter_.count(exact) ||
        drop_.count(exact) || cwnd_.count(exact) ||
        chaos_specs_.count(exact)) {
        match = exact;  // per-endpoint bucket
    } else if (edges_.count(exact)) {
        // injected per-endpoint edge (pccltNetemInject): exact beats the
        // ip wildcard below, same as an exact MAP entry would — an
        // injection is deliberate and endpoint-specific, so a host-wide
        // wildcard must not shadow it for post-injection resolvers (the
        // fetch workers re-resolve per range; docs/04)
        match = exact;
    } else if (mbps_.count(ip) || rtt_.count(ip) || jitter_.count(ip) ||
               drop_.count(ip) || cwnd_.count(ip) || chaos_specs_.count(ip)) {
        match = ip;  // per-host bucket, shared by every port on that ip
    } else if (edges_.count(ip)) {
        match = ip;
    } else {
        return default_;  // globals: the one process-wide bucket (legacy)
    }
    auto it = edges_.find(match);
    if (it == edges_.end()) {
        Entry e;
        // wildcard-matched edges key their refresh lookups by the ip too:
        // the bucket is shared host-wide, so one endpoint's later exact
        // entry must not retune it
        e.exact_key = match == ip ? ip : exact;
        e.ip_key = ip;
        e.edge = std::make_shared<Edge>(params_for(e.exact_key, ip));
        it = edges_.emplace(match, std::move(e)).first;
        // a chaos schedule covering this edge (exact entry, or the
        // host-wide ip wildcard) arms the moment the edge exists
        auto cs = chaos_specs_.find(it->second.exact_key);
        if (cs == chaos_specs_.end())
            cs = chaos_specs_.find(it->second.ip_key);
        if (cs != chaos_specs_.end() && !chaos_armed_keys_[match]) {
            chaos_armed_keys_[match] = true;
            it->second.edge->arm_chaos(
                parse_chaos(cs->second, "PCCLT_WIRE_CHAOS_MAP"));
        }
    }
    return it->second.edge;
}

bool inject(const std::string &endpoint, const std::string &spec) {
    size_t colon = endpoint.rfind(':');
    if (colon == std::string::npos) return false;
    long port = atol(endpoint.substr(colon + 1).c_str());
    auto addr = Addr::parse(endpoint.substr(0, colon),
                            static_cast<uint16_t>(port));
    if (!addr || port <= 0 || port > 65535) return false;
    auto faults = parse_chaos(spec, "pccltNetemInject");
    // an empty schedule is a valid DISARM request, but a spec that parses
    // to nothing while non-empty is an error the caller should hear about
    if (faults.empty() && !trim(spec).empty()) return false;
    auto &reg = Registry::inst();
    // force a per-endpoint edge: live conns to this endpoint hold the edge
    // resolve() returns, so arming it mid-run affects them immediately.
    // (Conns that resolved to the shared DEFAULT edge — no map entry for
    // the endpoint at connect time — keep the default model; arm before
    // connecting, or list the endpoint in a PCCLT_WIRE_*_MAP. docs/05.)
    {
        MutexLock lk(reg.mu_);
        std::string exact = addr->str();
        auto it = reg.edges_.find(exact);
        if (it == reg.edges_.end()) {
            std::string ip = exact.substr(0, exact.rfind(':'));
            Registry::Entry e;
            e.exact_key = exact;
            e.ip_key = ip;
            e.edge = std::make_shared<Edge>(reg.params_for(exact, ip));
            it = reg.edges_.emplace(exact, std::move(e)).first;
        }
        it->second.edge->arm_chaos(std::move(faults));
    }
    return true;
}

std::shared_ptr<Edge> Registry::default_edge() {
    MutexLock lk(mu_);
    return default_;
}

}  // namespace pcclt::net::netem
